"""Telemetry layer (mine_tpu/telemetry): the contracts everything else now
leans on, each asserted here:

  * histogram quantiles track numpy percentiles within the documented
    bucket-width bound, clamped to the observed range;
  * counter/gauge/registry snapshot semantics (types, prefixes, conflicts);
  * the JSONL sink degrades to a warn-once no-op on an unwritable path —
    instrumentation must never kill the run it observes;
  * every emitted line round-trips through the mtpu-ev1 validator;
  * span timers nest into dotted paths and unwind on exceptions;
  * the frozen st1 step-time line: format -> parse round-trip, legacy-form
    parity, unknown-tail tolerance (the append-only evolution rule);
  * tools/step_breakdown.py really reads through the ONE shared parser;
  * the instrumented serve render path is BITWISE-unchanged by telemetry
    being on or off (host-side-only is a testable property, not a comment).
"""

import json
import os
import sys

import numpy as np
import pytest

from mine_tpu import telemetry
from mine_tpu.telemetry import events as tevents
from mine_tpu.telemetry import stepline
from mine_tpu.telemetry.registry import Histogram, MetricsRegistry
from mine_tpu.telemetry.spans import current_span_path, span


@pytest.fixture
def clean_sink(monkeypatch):
    """Isolate the process-wide sink: no env funnel, nothing configured;
    re-arm the env-var check afterwards so an outer harness's
    MINE_TPU_TELEMETRY_EVENTS keeps working for later tests."""
    monkeypatch.delenv(tevents.ENV_VAR, raising=False)
    tevents.reset()
    yield
    tevents.reset()


# ---------------- histogram math ----------------

def test_histogram_quantiles_match_numpy():
    """Default latency buckets grow 1.3x, so an interpolated quantile lies
    within its containing bucket: relative error vs the exact numpy
    percentile is bounded by the growth factor."""
    rng = np.random.RandomState(7)
    samples = np.exp(rng.normal(2.0, 1.5, size=5000))  # 0.05..120k-ish ms
    h = Histogram("t")
    for v in samples:
        h.record(float(v))
    for q in (0.5, 0.9, 0.99):
        exact = np.percentile(samples, 100 * q)
        approx = h.quantile(q)
        assert abs(approx - exact) <= 0.35 * exact + 1e-9, (q, approx, exact)
    assert h.count == len(samples)
    np.testing.assert_allclose(h.sum, samples.sum(), rtol=1e-9)
    np.testing.assert_allclose(h.mean, samples.mean(), rtol=1e-9)


def test_histogram_quantile_clamped_to_observed_range():
    h = Histogram("t", edges=(1.0, 10.0, 100.0))
    h.record(3.0)
    h.record(4.0)
    # interpolation within the (1, 10] bucket would report up to 10;
    # the clamp keeps every quantile inside [min, max] actually seen
    assert h.quantile(0.0) == 3.0
    assert 3.0 <= h.quantile(0.5) <= 4.0
    assert h.quantile(1.0) == 4.0


def test_histogram_overflow_bucket_and_nan():
    h = Histogram("t", edges=(1.0, 2.0))
    h.record(float("nan"))  # dropped, not poisoning sum/mean
    assert h.count == 0
    h.record(1000.0)  # overflow bucket: p99 reports the observed max
    assert h.count == 1 and h.quantile(0.99) == 1000.0


def test_histogram_empty_is_nan():
    h = Histogram("t", edges=(1.0,))
    assert np.isnan(h.quantile(0.5))
    assert h.snapshot() == {"count": 0}


def test_histogram_rejects_bad_edges_and_q():
    with pytest.raises(ValueError):
        Histogram("t", edges=(2.0, 1.0))
    h = Histogram("t", edges=(1.0,))
    with pytest.raises(ValueError):
        h.quantile(1.5)


# ---------------- registry semantics ----------------

def test_registry_counter_gauge_snapshot():
    reg = MetricsRegistry()
    reg.counter("a.hits").inc()
    reg.counter("a.hits").inc(3)  # get-or-create: same counter
    reg.gauge("a.bytes").set(12.5)
    reg.histogram("b.ms").record(2.0)
    snap = reg.snapshot()
    assert snap["a.hits"] == 4 and isinstance(snap["a.hits"], int)
    assert snap["a.bytes"] == 12.5
    assert snap["b.ms"]["count"] == 1
    # prefix filter + JSON-safety (what the metrics.snapshot event carries)
    assert set(reg.snapshot("a.")) == {"a.hits", "a.bytes"}
    json.dumps(reg.snapshot())
    reg.reset()
    assert reg.snapshot() == {}


def test_registry_type_conflicts_raise():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")
    reg.histogram("h", edges=(1.0, 2.0))
    with pytest.raises(ValueError):
        reg.histogram("h", edges=(5.0,))
    with pytest.raises(ValueError):
        reg.counter("x").inc(-1)


# ---------------- event sink ----------------

def test_sink_roundtrip_and_validation(tmp_path, clean_sink):
    path = str(tmp_path / "ev.jsonl")
    tevents.configure(path)
    assert telemetry.emit("unit.test", n=3, nested={"a": [1, 2]},
                          arr=np.float32(1.5))
    tevents.current_sink().close()
    assert tevents.validate_file(path) == []
    (ev,) = tevents.read_events(path)
    assert ev["schema"] == tevents.SCHEMA and ev["kind"] == "unit.test"
    assert ev["n"] == 3 and ev["nested"] == {"a": [1, 2]}
    assert ev["arr"] == 1.5  # numpy degraded to a JSON scalar, not killed
    assert isinstance(ev["ts"], float)


def test_validate_line_rejects_bad_shapes():
    ok = json.dumps({"schema": tevents.SCHEMA, "ts": 1.0, "kind": "k"})
    assert tevents.validate_line(ok) is None
    assert tevents.validate_line("") is None  # blank lines tolerated
    assert tevents.validate_line("not json") is not None
    assert tevents.validate_line("[1,2]") is not None
    assert tevents.validate_line(json.dumps({"ts": 1.0, "kind": "k"})) \
        is not None
    assert tevents.validate_line(json.dumps(
        {"schema": "mtpu-ev999", "ts": 1.0, "kind": "k"})) is not None
    assert tevents.validate_line(json.dumps(
        {"schema": tevents.SCHEMA, "ts": "late", "kind": "k"})) is not None
    assert tevents.validate_line(json.dumps(
        {"schema": tevents.SCHEMA, "ts": 1.0, "kind": ""})) is not None


def test_sink_unwritable_degrades_with_one_warning(tmp_path, clean_sink,
                                                  caplog):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file where a directory is needed")
    sink = tevents.configure(str(blocker / "events.jsonl"))
    with caplog.at_level("WARNING", logger=tevents.__name__):
        assert telemetry.emit("a") is False  # degraded, did not raise
        assert telemetry.emit("b") is False
    warnings = [r for r in caplog.records
                if "event sink failed" in r.getMessage()]
    assert len(warnings) == 1  # ONE warning, then silence
    assert sink.broken and sink.dropped == 2 and sink.emitted == 0


def test_unconfigured_emit_is_cheap_noop(clean_sink):
    assert telemetry.emit("nobody.listening") is False


def test_env_var_funnel_and_explicit_override(tmp_path, clean_sink,
                                              monkeypatch):
    env_path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv(tevents.ENV_VAR, env_path)
    tevents.reset()
    # ensure_configured: the env var outranks the caller's default
    sink = tevents.ensure_configured(str(tmp_path / "default.jsonl"))
    assert sink.path == env_path
    telemetry.emit("env.owned")
    # a second ensure_configured never replaces an existing sink
    assert tevents.ensure_configured(str(tmp_path / "other.jsonl")) is sink
    # explicit configure outranks everything
    explicit = str(tmp_path / "explicit.jsonl")
    tevents.configure(explicit)
    telemetry.emit("explicit.owned")
    tevents.current_sink().close()
    assert [e["kind"] for e in tevents.read_events(env_path)] == ["env.owned"]
    assert [e["kind"] for e in tevents.read_events(explicit)] \
        == ["explicit.owned"]


# ---------------- spans ----------------

def test_span_nesting_paths_and_histograms(tmp_path, clean_sink):
    tevents.configure(str(tmp_path / "ev.jsonl"))
    reg = MetricsRegistry()
    with span("outer", registry=reg):
        assert current_span_path() == "outer"
        with span("inner", registry=reg, detail="x"):
            assert current_span_path() == "outer.inner"
        assert current_span_path() == "outer"
    assert current_span_path() is None
    assert reg.histogram("outer_ms").count == 1
    assert reg.histogram("outer.inner_ms").count == 1
    tevents.current_sink().close()
    events = tevents.read_events(str(tmp_path / "ev.jsonl"))
    assert [e["name"] for e in events] == ["outer.inner", "outer"]
    assert all(e["kind"] == "span" and e["ok"] for e in events)
    assert events[0]["detail"] == "x"


def test_span_unwinds_and_propagates_on_exception(clean_sink):
    reg = MetricsRegistry()
    with pytest.raises(RuntimeError):
        with span("boom", registry=reg):
            raise RuntimeError("inner failure")
    assert current_span_path() is None  # stack unwound
    assert reg.histogram("boom_ms").count == 1  # failure time still counts


# ---------------- the frozen st1 step line ----------------

_TIMES = {"step_ms": 812.04, "host_wait_ms": 590.1, "device_ms": 221.9,
          "h2d_ms": 35.25}


def test_stepline_format_parse_roundtrip():
    line = stepline.format_step_line(_TIMES, data_errors=7)
    assert line.startswith("time: schema=st1 ")
    # frozen key order — the schema contract, not a formatting accident
    assert line == ("time: schema=st1 step_ms=812.0 host_wait_ms=590.1 "
                    "device_ms=221.9 h2d_ms=35.2 data_errors=7")
    rec = stepline.parse_line("        " + line)
    assert rec == {"step": 812.0, "host_wait": 590.1, "device": 221.9,
                   "h2d": 35.2, "data_errors": 7}


def test_stepline_legacy_parity():
    """The pre-st1 printf form (with and without PR-4's data_errors tail)
    parses to the same record — old logs keep summarizing."""
    legacy = ("time: step = 812.0 ms host_wait = 590.1 ms "
              "device = 221.9 ms h2d = 35.2 ms")
    st1 = stepline.format_step_line(_TIMES, data_errors=0)
    assert stepline.parse_line(legacy) == stepline.parse_line(st1)
    with_errors = legacy + " data_errors = 7"
    assert stepline.parse_line(with_errors)["data_errors"] == 7


def test_stepline_append_only_evolution():
    # unknown APPENDED keys pass through; a different schema tag is skipped
    line = stepline.format_step_line(_TIMES, 0) + " new_metric_ms=1.5"
    rec = stepline.parse_line(line)
    assert rec["new_metric"] == 1.5 and rec["step"] == 812.0
    assert stepline.parse_line(
        line.replace("schema=st1", "schema=st99")) is None
    # torn line (missing required keys) is skipped, not misparsed
    assert stepline.parse_line("time: schema=st1 step_ms=1.0") is None


def test_parse_lines_aggregates_only_time_keys():
    lines = ["noise", stepline.format_step_line(_TIMES, 1),
             "time: step = 100.0 ms host_wait = 50.0 ms device = 50.0 ms "
             "h2d = 5.0 ms"]
    samples = stepline.parse_lines(lines)
    assert set(samples) == set(stepline.TIME_KEYS)
    assert samples["step"] == [812.0, 100.0]


def test_step_breakdown_tool_uses_shared_parser():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import step_breakdown
    assert step_breakdown.parse_lines is stepline.parse_lines
    assert step_breakdown.KEYS == stepline.TIME_KEYS


# ---------------- train-loop logging through the layer ----------------

def test_log_training_emits_st1_line_and_registry(tmp_path, clean_sink):
    """One _log_training call on a stubbed loop: the frozen st1 line lands
    in the log, train.* histograms and the train.step event are recorded —
    all from host floats (nothing here ever touches a device value)."""
    from types import SimpleNamespace

    from mine_tpu.train.loop import TIME_METER_KEYS, TrainLoop
    from mine_tpu.utils import AverageMeter
    from tests.test_train import tiny_config

    tevents.configure(str(tmp_path / "ev.jsonl"))
    telemetry.REGISTRY.reset()
    logged = []
    from collections import deque
    stub = SimpleNamespace(
        config=tiny_config(),
        trainer=SimpleNamespace(steps_per_epoch=10),
        telem=SimpleNamespace(enabled=True),
        time_meters={k: AverageMeter("time_" + k, ":.1f")
                     for k in TIME_METER_KEYS},
        train_meters={},
        _step_hist=deque(maxlen=64),  # ops-plane state (PR 12)
        recorder=None,  # flight recorder off (PR 15)
        _ops_state={"gstep": 0, "epoch": 0, "epochs": 0,
                    "guard_consecutive": 0.0, "data_errors": 0,
                    "data_errors_delta": 0},
        _log=lambda msg, *a: logged.append(msg % a if a else msg),
        _tb=lambda *a: None)
    m = {"loss": 1.5, "loss_rgb_src": 0.1, "loss_ssim_src": 0.2,
         "loss_disp_pt3dsrc": 0.3, "loss_rgb_tgt": 0.4, "loss_ssim_tgt": 0.5,
         "loss_disp_pt3dtgt": 0.6, "psnr_tgt": 20.0, "skipped_steps": 2.0}
    times = {"step_ms": 812.0, "host_wait_ms": 590.1, "device_ms": 221.9,
             "h2d_ms": 35.2}
    TrainLoop._log_training(stub, epoch=0, step=9, gstep=10, m=m, times=times)

    st1_lines = [ln for entry in logged for ln in entry.splitlines()
                 if stepline.parse_line(ln)]
    assert len(st1_lines) == 1
    assert stepline.parse_line(st1_lines[0])["step"] == 812.0
    assert "schema=st1" in st1_lines[0]
    for k in TIME_METER_KEYS:
        assert telemetry.REGISTRY.get("train." + k).count == 1
    assert telemetry.REGISTRY.get("train.guard.skipped_steps").value == 2.0
    tevents.current_sink().close()
    (ev,) = tevents.read_events(str(tmp_path / "ev.jsonl"))
    assert ev["kind"] == "train.step" and ev["gstep"] == 10
    assert ev["step_ms"] == 812.0 and ev["data_errors"] >= 0


# ---------------- profiler window ----------------

def test_profile_window_validation_and_resume_skip(tmp_path):
    from mine_tpu.telemetry.profiler import ProfileWindow

    with pytest.raises(ValueError):
        ProfileWindow([5, 3], str(tmp_path))
    with pytest.raises(ValueError):
        ProfileWindow([0, 3], str(tmp_path))
    with pytest.raises(ValueError):
        ProfileWindow([7], str(tmp_path))
    # no steps: permanently disabled, every hook is a cheap no-op
    w = ProfileWindow((), str(tmp_path))
    w.maybe_start(1)
    w.maybe_stop(1)
    w.stop()
    assert not w.active and w.done
    # resumed past the window start: skipped (a partial trace would lie
    # about the steps it claims), with a warning
    w = ProfileWindow([3, 5], str(tmp_path))
    w.maybe_start(10)
    assert w.done and not w.active


def test_profile_window_traces_exact_steps(tmp_path, clean_sink):
    """[2, 3] brackets exactly steps 2..3: idle before 2, active through 3,
    stopped after — and the trace dir lands in the event stream."""
    from mine_tpu.telemetry.profiler import ProfileWindow

    tevents.configure(str(tmp_path / "ev.jsonl"))
    trace_dir = str(tmp_path / "trace")
    w = ProfileWindow([2, 3], trace_dir)
    w.maybe_start(1)
    assert not w.active
    w.maybe_stop(1)
    w.maybe_start(2)
    if w.done and not w.active:  # profiler unavailable on this backend:
        return                   # the non-fatal degrade IS the contract
    assert w.active
    w.maybe_stop(2)
    assert w.active  # stop step not reached yet
    w.maybe_start(3)  # already active: no-op
    w.maybe_stop(3)
    assert not w.active and w.done
    tevents.current_sink().close()
    events = [e for e in tevents.read_events(str(tmp_path / "ev.jsonl"))
              if e["kind"] == "profile.window"]
    assert events and events[0]["trace_dir"] == trace_dir
    assert events[0]["start_step"] == 2 and events[0]["stop_step"] == 3
    assert os.path.isdir(trace_dir)


# ---------------- telemetry cannot change numerics ----------------

def test_serve_render_bitwise_unchanged_by_telemetry(tmp_path, clean_sink):
    """The acceptance contract: the instrumented serve path produces
    BITWISE-identical renders with telemetry fully on (sink + registry)
    vs fully off — metrics are host-side observations, never participants."""
    from mine_tpu.serve import MPICache, RenderEngine

    rng = np.random.RandomState(0)
    planes = rng.uniform(0.0, 1.0, (4, 4, 16, 16)).astype(np.float32)
    disparity = np.linspace(1.0, 0.1, 4).astype(np.float32)
    K = np.array([[20.0, 0, 8], [0, 20.0, 8], [0, 0, 1]], np.float32)
    poses = np.tile(np.eye(4, dtype=np.float32), (3, 1, 1))
    poses[:, 0, 3] = [0.0, 0.01, 0.02]

    def render_once():
        engine = RenderEngine(cache=MPICache(quant="bf16"))
        engine.put("img", planes[:, 0:3], planes[:, 3:4], disparity, K)
        return engine.render("img", poses)

    rgb_off, depth_off = render_once()  # sink unconfigured, cheap no-ops
    tevents.configure(str(tmp_path / "ev.jsonl"))
    telemetry.counter("serve.cache.hits")  # registry warm too
    rgb_on, depth_on = render_once()
    np.testing.assert_array_equal(rgb_off, rgb_on)
    np.testing.assert_array_equal(depth_off, depth_on)
    # and the instrumentation really observed the run
    assert telemetry.REGISTRY.get("serve.cache.hits").value >= 1
    tevents.current_sink().close()
    assert tevents.validate_file(str(tmp_path / "ev.jsonl")) == []


# ---------------- the SLO bench (subprocess smoke) ----------------

@pytest.mark.slow
def test_serve_slo_smoke_emits_parseable_curve(tmp_path):
    """bench.py serve_slo on CPU smoke: one parseable offered:p50:p99:
    achieved curve line, a knee line, and schema-clean slo_point events."""
    import re
    import subprocess

    events = str(tmp_path / "ev.jsonl")
    env = dict(os.environ, MINE_TPU_BENCH_SMOKE="1", JAX_PLATFORMS="cpu",
               MINE_TPU_TELEMETRY_EVENTS=events)
    out = subprocess.run(
        [sys.executable, "-c",
         "import bench; print(bench._measure('serve_slo')[0])"],
        capture_output=True, text=True, env=env, timeout=900,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    # bench routes variant progress to stderr (stdout carries the JSON
    # result line in a sweep); the curve/knee lines live there
    curve = [ln for ln in out.stderr.splitlines()
             if ln.strip().startswith("serve_slo curve:")]
    assert len(curve) == 1
    pts = re.findall(r"([\d.]+):([\d.]+):([\d.]+):([\d.]+)", curve[0])
    assert len(pts) == 5  # one point per SERVE_SLO_RATE_FRACS entry
    offered = [float(p[0]) for p in pts]
    assert offered == sorted(offered) and offered[0] > 0
    assert any("serve_slo knee:" in ln for ln in out.stderr.splitlines())
    # the knee qps _measure returned (printed to stdout) is positive
    assert float(out.stdout.splitlines()[-1]) > 0
    assert tevents.validate_file(events) == []
    points = [e for e in tevents.read_events(events)
              if e["kind"] == "serve.slo_point"]
    # 5 curve points plus the ONE deliberate admission-on overload point
    # (flagged overload=True so curve consumers can exclude it)
    assert sum(1 for e in points if not e.get("overload")) == 5
    assert sum(1 for e in points if e.get("overload")) == 1
