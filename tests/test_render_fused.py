"""Fused Pallas render megakernel (kernels/render_fused.py + the
"pallas_fused" warp backend).

The load-bearing contracts, each asserted here:
  * the megakernel (warp -> in-kernel dequant -> composite -> blend in one
    pass) matches the XLA dequant+gather+composite graph within house
    kernel tolerances — the measured CPU-interpreter divergence is
    <= 1.8e-7 rgb / 1.5e-6 depth (FMA/fusion-order ulps, never structure);
  * the dequant LOCATION is free: reading the CACHED (int8/bf16/f32)
    planes inside the kernel is BITWISE-identical to pre-dequantizing the
    same planes and running them through the same kernel, for all three
    cache quant modes — so the int8 round-trip bound |w - dq| <= scale/2
    survives the fused read unchanged;
  * the guard (fused_domain_ok + the lax.cond fallback) keeps out-of-band
    poses exact via the XLA branch and reports the fast-path fraction;
  * the custom-VJP twin backprops the XLA-equivalent graph: grads through
    the guarded kernel match grads through the reference;
  * the serve engines render identically through warp_impl="pallas_fused"
    vs the default XLA path — every cache quant mode, single-device and
    1x1/2x1/2x2/4x1 serve meshes with padded pose buckets — and the mesh
    fused program is BITWISE the single-device fused program;
  * the whole request is ONE kernel: the audited serve_render_fused
    program stages exactly one pallas_call and takes the int8 cache in
    un-dequantized (no separate dequant program), and a deliberately
    UNFUSED build of the same program trips the dot_budget gate.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mine_tpu.kernels.render_fused import (fused_domain_ok,
                                           fused_plane_render,
                                           fused_plane_render_guarded,
                                           xla_reference_render)
from mine_tpu.serve import MeshRenderEngine, MPICache, RenderEngine
from mine_tpu.serve.cache import quantize_planes

# house kernel-vs-XLA tolerances (tests/test_warp_kernel.py lineage);
# measured fused-vs-xla divergence at these fixtures: rgb <= 1.79e-7,
# depth <= 1.43e-6
RGB_TOL = dict(rtol=1e-5, atol=1e-6)
DEPTH_TOL = dict(rtol=1e-4, atol=1e-5)

H = W = 64
S = 4


# ---------------- kernel-level fixture (synthetic coords) ----------------

@pytest.fixture(scope="module")
def kin():
    """Near-identity per-plane warps over a [2,4,16,128] volume: every
    row-block's source span fits a 16-row band, so the guard admits the
    kernel; W=128 keeps the lane tile exact (no pad columns in play)."""
    rng = np.random.RandomState(0)
    B, S_, Hs, Ws = 2, 4, 16, 128
    vol = rng.uniform(-1, 1, (B, S_, 4, Hs, Ws)).astype(np.float32)
    vol[:, :, 3] = np.abs(vol[:, :, 3])  # nonnegative density
    xyz = rng.uniform(-1, 1, (B, S_, 3, Hs, Ws)).astype(np.float32)
    xyz[:, :, 2] += 2.0                  # in front of the camera
    cx = (np.arange(Ws)[None, None, None, :]
          + rng.uniform(-1.5, 1.5, (B, S_, Hs, 1))).astype(np.float32)
    cy = (np.arange(Hs)[None, None, :, None]
          + rng.uniform(-1.5, 1.5, (B, S_, 1, Ws))).astype(np.float32)
    return {"vol": vol, "xyz": xyz,
            "cx": np.broadcast_to(cx, (B, S_, Hs, Ws)).copy(),
            "cy": np.broadcast_to(cy, (B, S_, Hs, Ws)).copy()}


def _fused(vol, scales, kin, band=16):
    r, d = fused_plane_render(vol, scales, kin["xyz"], kin["cx"], kin["cy"],
                              band=band, rows_per_block=8, interpret=True)
    return np.asarray(r), np.asarray(d)


def _reference(vol, scales, kin):
    r, d = jax.jit(lambda v, sc, x, a, b:
                   xla_reference_render(v, sc, x, a, b))(
                       vol, scales, kin["xyz"], kin["cx"], kin["cy"])
    return np.asarray(r), np.asarray(d)


def test_fused_matches_xla_reference(kin):
    assert bool(fused_domain_ok(kin["vol"].shape, kin["vol"].dtype,
                                jnp.asarray(kin["cy"]), band=16))
    r_f, d_f = _fused(kin["vol"], None, kin)
    r_x, d_x = _reference(kin["vol"], None, kin)
    np.testing.assert_allclose(r_f, r_x, **RGB_TOL)
    np.testing.assert_allclose(d_f, d_x, **DEPTH_TOL)


@pytest.mark.parametrize("quant", ["float32", "bf16", "int8"])
def test_in_kernel_dequant_bitwise_vs_pre_dequant(kin, quant):
    """The tentpole's dequant pin: the quantized planes through the kernel
    (scales in SMEM, dequant in registers) equal the pre-dequantized f32
    planes through the SAME kernel exactly — the bf16 widen and the int8
    scale multiply commute with the fused read bit-for-bit."""
    q, scales = quantize_planes(jnp.asarray(kin["vol"][0]), quant)
    q = jnp.asarray(q)[None].repeat(2, axis=0)
    if scales is not None:
        scales = jnp.asarray(scales)[None].repeat(2, axis=0)
    dq = q.astype(jnp.float32)
    if scales is not None:
        dq = dq * scales
    r_q, d_q = _fused(np.asarray(q), scales, kin)
    r_dq, d_dq = _fused(np.asarray(dq), None, kin)
    np.testing.assert_array_equal(r_q, r_dq)
    np.testing.assert_array_equal(d_q, d_dq)


def test_int8_roundtrip_bound_survives_fused_read(kin):
    """|w - dq| <= scale/2 per element (symmetric round-to-nearest, no
    clipping past amax), and the fused read returns exactly the dq values
    (previous test) — so the bound holds through the megakernel too."""
    w = jnp.asarray(kin["vol"][0])
    q, scales = quantize_planes(w, "int8")
    dq = np.asarray(q, np.float32) * np.asarray(scales)
    bound = np.broadcast_to(np.asarray(scales) / 2.0, dq.shape)
    np.testing.assert_array_less(np.abs(np.asarray(w) - dq),
                                 bound + 1e-7)


# ---------------- guard + fallback ----------------

def test_guard_in_domain_is_bitwise_the_kernel(kin):
    r_f, d_f = _fused(kin["vol"], None, kin)
    r_g, d_g, ok = jax.jit(
        lambda v, x, a, b: fused_plane_render_guarded(
            v, None, x, a, b, band=16, interpret=True))(
                kin["vol"], kin["xyz"], kin["cx"], kin["cy"])
    assert bool(ok)
    np.testing.assert_array_equal(np.asarray(r_g), r_f)
    np.testing.assert_array_equal(np.asarray(d_g), d_f)


def test_guard_falls_back_out_of_band(kin):
    """A single row-block whose source span exceeds the band flips the
    guard; the cond's slow branch is the XLA graph, so values stay right
    (house tolerances — different fusion context than a standalone jit)."""
    cy = kin["cy"].copy()
    cy[0, 0, 0, 0] = 0.0
    cy[0, 0, 0, 1] = 15.0  # 15-row span inside one 8-row block
    r_g, d_g, ok = jax.jit(
        lambda v, x, a, b: fused_plane_render_guarded(
            v, None, x, a, b, band=8, interpret=True))(
                kin["vol"], kin["xyz"], kin["cx"], cy)
    assert not bool(ok)
    r_x, d_x = jax.jit(lambda v, x, a, b:
                       xla_reference_render(v, None, x, a, b))(
                           kin["vol"], kin["xyz"], kin["cx"], cy)
    np.testing.assert_allclose(np.asarray(r_g), np.asarray(r_x), **RGB_TOL)
    np.testing.assert_allclose(np.asarray(d_g), np.asarray(d_x), **DEPTH_TOL)


def test_guard_static_row_block_mismatch_never_stages_kernel(kin):
    """H_t not divisible by rows_per_block is a STATIC domain miss: the
    guarded wrapper must return the XLA path without tracing the kernel
    (lax.cond traces both branches, and the kernel asserts the tiling)."""
    r_g, d_g, ok = fused_plane_render_guarded(
        kin["vol"], None, kin["xyz"], kin["cx"], kin["cy"],
        band=16, rows_per_block=7, interpret=True)
    assert not bool(ok)
    r_x, d_x = xla_reference_render(kin["vol"], None, kin["xyz"],
                                    kin["cx"], kin["cy"])
    np.testing.assert_array_equal(np.asarray(r_g), np.asarray(r_x))
    np.testing.assert_array_equal(np.asarray(d_g), np.asarray(d_x))
    assert not bool(fused_domain_ok(kin["vol"].shape, kin["vol"].dtype,
                                    jnp.asarray(kin["cy"]), band=16,
                                    rows_per_block=7))


def test_guarded_grads_match_reference(kin):
    """The custom-VJP twin: forward is the megakernel, backward is the
    XLA-equivalent graph — grads match autodiff through the reference."""
    vol, xyz = jnp.asarray(kin["vol"]), jnp.asarray(kin["xyz"])
    cx, cy = jnp.asarray(kin["cx"]), jnp.asarray(kin["cy"])

    def loss(v, x):
        r, d, _ = fused_plane_render_guarded(v, None, x, cx, cy,
                                             band=16, interpret=True)
        return jnp.sum(r) + jnp.sum(d)

    def ref_loss(v, x):
        r, d = xla_reference_render(v, None, x, cx, cy)
        return jnp.sum(r) + jnp.sum(d)

    g_v, g_x = jax.grad(loss, argnums=(0, 1))(vol, xyz)
    r_v, r_x = jax.grad(ref_loss, argnums=(0, 1))(vol, xyz)
    assert bool(jnp.isfinite(g_v).all() & jnp.isfinite(g_x).all())
    np.testing.assert_allclose(np.asarray(g_v), np.asarray(r_v),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_x), np.asarray(r_x),
                               rtol=1e-4, atol=1e-6)


# ---------------- serve engines through the fused backend ----------------

@pytest.fixture(scope="module")
def scene():
    """The test_serve_fleet.py scene: one synthetic layered entry, 5 poses
    (padded to an 8-bucket by the engines)."""
    from mine_tpu.data.synthetic import SyntheticMPIDataset

    ds = SyntheticMPIDataset(seed=3, height=H, width=W, num_planes_gt=S)
    planes = np.concatenate([np.asarray(ds.mpi_rgb[0]),
                             np.asarray(ds.mpi_sigma[0])], axis=1)
    poses = np.tile(np.eye(4, dtype=np.float32), (5, 1, 1))
    poses[:, 0, 3] = np.linspace(0.0, 0.04, 5)
    poses[:, 2, 3] = np.linspace(0.0, -0.06, 5)
    return {"planes": planes.astype(np.float32),
            "disparity": np.asarray(ds.disparity[0]),
            "K": np.asarray(ds.K, np.float32),
            "poses": poses}


def _engine(scene, quant, warp_impl, mesh=None):
    # warp_band=64 = full source height: the band covers any in-image
    # coords, so the guard's alignment slack is zero for every cache dtype
    # and the fused fast path is live even for the int8 (32-row tile) cache
    kw = dict(cache=MPICache(quant=quant), max_bucket=8,
              warp_impl=warp_impl, warp_band=64)
    if mesh is None:
        eng = RenderEngine(**kw)
    else:
        eng = MeshRenderEngine(mesh_batch=mesh[0], mesh_model=mesh[1], **kw)
    p = scene["planes"]
    eng.put("img", p[:, 0:3], p[:, 3:4], scene["disparity"], scene["K"])
    return eng


@pytest.mark.parametrize("quant", ["float32", "bf16", "int8"])
def test_engine_fused_matches_xla_backend(scene, quant):
    """warp_impl="pallas_fused" vs the default XLA dequant+gather+composite
    on the single-device engine, per cache quant mode. House tolerances:
    the two are different XLA programs around the same math (measured
    divergence <= 1.8e-7 rgb / 1.5e-6 depth at this fixture)."""
    rgb_x, dep_x = _engine(scene, quant, "xla").render("img", scene["poses"])
    rgb_f, dep_f = _engine(scene, quant, "pallas_fused").render(
        "img", scene["poses"])
    np.testing.assert_allclose(rgb_f, rgb_x, **RGB_TOL)
    np.testing.assert_allclose(dep_f, dep_x, **DEPTH_TOL)


@pytest.mark.parametrize("mesh", [(1, 1), (2, 1), (2, 2), (4, 1)])
def test_mesh_engine_fused_bitwise_matches_single_fused(scene, mesh):
    """The fused mesh program (shard_map over the serve "batch" axis) is
    BITWISE the single-device fused program — int8 so the SMEM scales ride
    the shard_map too — and stays within house tolerances of the XLA mesh
    path."""
    single = _engine(scene, "int8", "pallas_fused")
    fleet = _engine(scene, "int8", "pallas_fused", mesh=mesh)
    assert fleet.num_devices() == mesh[0] * mesh[1]
    rgb_s, dep_s = single.render("img", scene["poses"])
    rgb_m, dep_m = fleet.render("img", scene["poses"])
    np.testing.assert_array_equal(rgb_m, rgb_s)
    np.testing.assert_array_equal(dep_m, dep_s)
    rgb_x, dep_x = _engine(scene, "int8", "xla", mesh=mesh).render(
        "img", scene["poses"])
    np.testing.assert_allclose(rgb_m, rgb_x, **RGB_TOL)
    np.testing.assert_allclose(dep_m, dep_x, **DEPTH_TOL)


# ---------------- one-kernel structure + the audit gate ----------------

def test_serve_render_fused_is_one_kernel():
    """The audited program (analysis/programs.py serve_render_fused) stages
    exactly ONE pallas_call — warp, dequant, composite and blend never
    split back into separate programs — and the int8 cache crosses the jit
    boundary un-dequantized (the float volume never exists outside the
    kernel)."""
    from mine_tpu.analysis.flops import iter_eqns
    from mine_tpu.analysis.programs import get_program

    prog = get_program("serve_render_fused")
    jaxpr = prog.jaxpr()
    n_pallas = sum(1 for e in iter_eqns(jaxpr)
                   if e.primitive.name == "pallas_call")
    assert n_pallas == 1, f"expected one fused kernel, saw {n_pallas}"
    in_dtypes = [v.aval.dtype for v in jaxpr.jaxpr.invars
                 if hasattr(v.aval, "dtype")]
    assert any(dt == jnp.int8 for dt in in_dtypes), (
        "int8 cache should enter the program un-dequantized")


def test_unfused_variant_trips_dot_budget():
    """Satellite 6's seeded violation: the SAME serve program built without
    the megakernel (warp_impl="xla" over the int8 cache) measured against
    serve_render_fused's committed baseline must FAIL dot_budget — the
    gate actually pins the one-kernel structure, not just a number."""
    from mine_tpu.analysis.framework import load_baseline
    from mine_tpu.analysis.passes import DotBudgetPass
    from mine_tpu.analysis.programs import serve_render_program

    unfused = serve_render_program("int8", None, "serve_render_fused", "xla")
    result = DotBudgetPass(load_baseline()).run(unfused)
    assert result.ok is False, (
        "an unfused build matched the fused baseline — dot_budget is "
        "blind to the fusion this program exists to pin")
    assert result.details
