"""Distributed plane-axis composite (ops/plane_scan.py) vs the serial
renderer: values AND gradients must match on the 8-device mesh — the
two-level transparency scan (local cumprod + shard-total prefix combine +
halo exchange) is exact, not approximate."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mine_tpu.ops import rendering
from mine_tpu.ops.plane_scan import plane_sharded_volume_render
from mine_tpu.parallel import mesh as mesh_lib


def _volume(seed, B=2, S=8, H=16, W=24):
    rng = np.random.RandomState(seed)
    rgb = jnp.asarray(rng.uniform(size=(B, S, 3, H, W)).astype(np.float32))
    sigma = jnp.asarray(
        rng.uniform(0.0, 3.0, size=(B, S, 1, H, W)).astype(np.float32))
    # plane point clouds at increasing depth with some xy jitter; a few
    # negative-z points exercise the z-mask
    z = np.linspace(1.0, 5.0, S)[None, :, None, None, None]
    xyz = np.concatenate([
        rng.normal(size=(B, S, 2, H, W)) * 0.05,
        np.broadcast_to(z, (B, S, 1, H, W)) +
        rng.normal(size=(B, S, 1, H, W)) * 0.01,
    ], axis=2).astype(np.float32)
    xyz[:, :, 2][rng.uniform(size=(B, S, H, W)) < 0.05] *= -1.0
    return rgb, sigma, jnp.asarray(xyz)


def _serial(rgb, sigma, xyz, z_mask, is_bg):
    if z_mask:
        sigma = jnp.where(xyz[:, :, 2:3] >= 0.0, sigma, 0.0)
    out_rgb, out_depth, _, _ = rendering.plane_volume_rendering(
        rgb, sigma, xyz, is_bg_depth_inf=is_bg)
    return out_rgb, out_depth


def test_matches_serial_composite():
    mesh = mesh_lib.make_mesh(data=2, plane=4)
    rgb, sigma, xyz = _volume(0)
    for z_mask in (False, True):
        for is_bg in (False, True):
            got = plane_sharded_volume_render(
                rgb, sigma, xyz, mesh, z_mask=z_mask, is_bg_depth_inf=is_bg)
            want = _serial(rgb, sigma, xyz, z_mask, is_bg)
            np.testing.assert_allclose(np.asarray(got[0]),
                                       np.asarray(want[0]),
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(np.asarray(got[1]),
                                       np.asarray(want[1]),
                                       rtol=1e-3, atol=1e-4)


def test_gradients_match_serial():
    mesh = mesh_lib.make_mesh(data=2, plane=4)
    rgb, sigma, xyz = _volume(1)
    cot_rgb = jnp.asarray(
        np.random.RandomState(2).normal(size=rgb.shape[:1] + (3,) +
                                        rgb.shape[3:]).astype(np.float32))

    def loss_dist(r, s, x):
        o_rgb, o_depth = plane_sharded_volume_render(
            r, s, x, mesh, z_mask=True, is_bg_depth_inf=False)
        return jnp.sum(o_rgb * cot_rgb) + 0.1 * jnp.sum(o_depth)

    def loss_ser(r, s, x):
        o_rgb, o_depth = _serial(r, s, x, True, False)
        return jnp.sum(o_rgb * cot_rgb) + 0.1 * jnp.sum(o_depth)

    g_dist = jax.grad(loss_dist, argnums=(0, 1, 2))(rgb, sigma, xyz)
    g_ser = jax.grad(loss_ser, argnums=(0, 1, 2))(rgb, sigma, xyz)
    for a, b, tol in zip(g_dist, g_ser, (1e-4, 1e-4, 1e-3)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=tol, atol=tol)


@pytest.mark.xfail(
    strict=False,
    reason="ROADMAP 'Mesh-vs-single numeric divergence at 8 CPU devices': "
           "GSPMD partitioner diverges ~2-3% on any 8-device CPU mesh "
           "(identical value for both factorizations, plain-XLA path too — "
           "not repo logic). Re-check on jax upgrade / real TPU.")
def test_train_step_plane_scan_matches_xla():
    """training.composite_backend=plane_scan on a plane-parallel mesh: the
    full train step matches the single-device XLA step numerically."""
    from mine_tpu.data.synthetic import make_batch
    from mine_tpu.train.step import SynthesisTrainer
    from tests.test_train import tiny_config, to_jnp

    cfg = tiny_config()
    cfg["data.per_gpu_batch_size"] = 4
    batch = to_jnp(make_batch(4, 64, 64, num_points=16))

    t_ref = SynthesisTrainer(cfg, steps_per_epoch=10)
    s0 = t_ref.init_state(batch_size=4)
    _, m_ref = t_ref.train_step(s0, batch)

    cfg_p = dict(cfg)
    cfg_p["training.composite_backend"] = "plane_scan"
    mesh = mesh_lib.make_mesh(data=4, plane=2)
    t_mesh = SynthesisTrainer(cfg_p, mesh=mesh, steps_per_epoch=10)
    s1 = t_mesh.init_state(batch_size=4)
    _, m_mesh = t_mesh.train_step(s1, batch)

    assert np.isfinite(float(m_mesh["loss"]))
    np.testing.assert_allclose(float(m_mesh["loss"]), float(m_ref["loss"]),
                               rtol=2e-3)


def test_single_plane_shard_degenerates_to_serial():
    """plane=1 mesh: the scan is just the serial composite under shard_map."""
    mesh = mesh_lib.make_mesh(data=8, plane=1)
    rgb, sigma, xyz = _volume(3, B=8, S=4)
    got = plane_sharded_volume_render(rgb, sigma, xyz, mesh,
                                      z_mask=False, is_bg_depth_inf=False)
    want = _serial(rgb, sigma, xyz, False, False)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                               rtol=1e-4, atol=1e-5)
