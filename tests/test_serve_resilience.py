"""Self-protecting serving (PR 11): admission control, the graceful
degradation ladder, deadlines, encode retry, and shard failover — every
behavior driven through the chaos seams in mine_tpu/testing/faults.py.

The load-bearing contracts, each asserted here:
  * the AdmissionController's level machine escalates immediately,
    de-escalates hysteretically, and emits ONE serve.admission event per
    transition (edge-triggered, like SLO breaches);
  * under a queue flood, tier-0 requests shed with `RequestShed` while
    tier-2 requests ALL complete, dispatched highest-tier-first;
  * the degradation ladder steps a degraded miss's encode down one cache
    quant, caps an all-degraded batch at half the pose bucket, and a
    mixed-dtype batch still renders correctly;
  * the deadline sweep purges already-expired requests at dispatch time —
    they resolve `DeadlineExceeded` and are NEVER rendered (fake clock);
  * transient sync-encode failures heal inside the bounded jittered-backoff
    retry, count exactly, and do NOT consume the one-time slow-path
    warning (the warning fires only on a clean first-attempt miss);
  * consecutive placement failures mark a shard dead (serve.shard_dead),
    its key range re-routes ring-wise, and mark_alive re-adopts it
    (serve.shard_revive) — with zero failed requests end to end;
  * rebalance() racing concurrent submit()s never corrupts results;
  * /healthz reports `degraded` (still HTTP 200) on budget burn or a dead
    shard;
  * with every feature at its default-off setting the serve path is
    bitwise-identical to the plain engine (the PR-10 parity bar).
"""

import json
import threading
import time
import urllib.request
import warnings

import numpy as np
import pytest

from mine_tpu import telemetry
from mine_tpu.serve import (MPICache, RenderEngine, ServeFleet,
                            ShardedPlaneCache)
from mine_tpu.serve.admission import (TIER_BEST_EFFORT, TIER_CRITICAL,
                                      AdmissionController, DeadlineExceeded,
                                      RequestShed)
from mine_tpu.serve.batcher import MicroBatcher
from mine_tpu.telemetry import events as tevents
from mine_tpu.telemetry.slo import SLOTracker
from mine_tpu.testing import faults
from mine_tpu.testing.faults import FaultPlan, InjectedEncodeError

S = 4
HW = 8
POSE = np.eye(4, dtype=np.float32)
IMG = np.zeros((HW, HW, 3), np.float32)


def _mpi_parts(seed=0):
    rng = np.random.RandomState(seed)
    p = rng.uniform(-1, 1, (S, 4, HW, HW)).astype(np.float32)
    return (p[:, 0:3], p[:, 3:4],
            np.linspace(1.0, 0.2, S, dtype=np.float32),
            np.eye(3, dtype=np.float32))


def _encode_fn(img_hwc):
    """Deterministic synchronous encode stand-in (image -> fixed MPI)."""
    return _mpi_parts(seed=0)


def _engine(quant="bf16", **kw):
    return RenderEngine(cache=MPICache(quant=quant), max_bucket=8,
                        encode_fn=_encode_fn, **kw)


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.set_plan(None)


@pytest.fixture
def event_stream(tmp_path, monkeypatch):
    """Route the event sink to a temp file; yields its path. Reset closes
    the sink so every line is on disk before validation."""
    monkeypatch.delenv(tevents.ENV_VAR, raising=False)
    tevents.reset()
    path = str(tmp_path / "ev.jsonl")
    tevents.configure(path)
    yield path
    tevents.reset()


# ---------------- admission controller unit ----------------

def test_admission_disabled_is_constant_admit():
    ctl = AdmissionController(enabled=False, queue_high=1)
    for depth in (0, 10, 10_000):
        assert ctl.decide(TIER_BEST_EFFORT, depth, depth) == "admit"
    assert ctl.state == "ok" and ctl.transitions == 0
    assert ctl.shed == 0 and ctl.degraded == 0


def test_admission_score_is_max_over_configured_signals():
    burn = [0.0]
    ctl = AdmissionController(enabled=True, burn_max=2.0, queue_high=10,
                              inflight_high=100, burn_fn=lambda: burn[0])
    assert ctl.score(5, 50) == 0.5          # max(0, 0.5, 0.5)
    burn[0] = 3.0
    assert ctl.score(0, 0) == 1.5           # burn dominates
    # threshold <= 0 disables that signal entirely
    off = AdmissionController(enabled=True, burn_max=0.0, queue_high=0,
                              inflight_high=100, burn_fn=lambda: 99.0)
    assert off.score(10_000, 50) == 0.5


def test_admission_tier_policy_matrix():
    ctl = AdmissionController(enabled=True, burn_max=0.0, queue_high=10,
                              inflight_high=0, shed_factor=2.0)
    # level ok: everything admits
    assert ctl.decide(TIER_BEST_EFFORT, 0, 0) == "admit"
    # level degrade (1.0 <= score < 2.0): tier 0 degrades, tier 1+ admits
    assert ctl.decide(TIER_BEST_EFFORT, 10, 0) == "degrade"
    assert ctl.decide(1, 15, 0) == "admit"
    # level shed (score >= 2.0): tier 0 sheds, tier 1 degrades, 2+ admits
    assert ctl.decide(TIER_BEST_EFFORT, 20, 0) == "shed"
    assert ctl.decide(1, 20, 0) == "degrade"
    assert ctl.decide(TIER_CRITICAL, 20, 0) == "admit"
    assert ctl.shed == 1 and ctl.degraded == 2


def test_admission_hysteresis_and_edge_triggered_events(event_stream):
    ctl = AdmissionController(enabled=True, burn_max=0.0, queue_high=10,
                              inflight_high=0, shed_factor=2.0,
                              hysteresis=0.7)
    # escalation is immediate (ok -> shed in one decide)
    ctl.decide(1, 25, 0)
    assert ctl.state == "shed" and ctl.transitions == 1
    # score back under the shed line but above hysteresis: state HOLDS
    ctl.decide(1, 15, 0)  # score 1.5 >= 2.0 * 0.7
    assert ctl.state == "shed" and ctl.transitions == 1
    # below 2.0*0.7: one step down per decide, never straight to ok
    ctl.decide(1, 13, 0)  # score 1.3 < 1.4
    assert ctl.state == "degrade" and ctl.transitions == 2
    ctl.decide(1, 13, 0)  # 1.3 >= 1.0: degrade holds
    assert ctl.state == "degrade"
    ctl.decide(1, 6, 0)   # 0.6 < 1.0 * 0.7
    assert ctl.state == "ok" and ctl.transitions == 3
    tevents.reset()
    events = [e for e in tevents.read_events(event_stream)
              if e["kind"] == "serve.admission"]
    assert [e["state"] for e in events] == ["shed", "degrade", "ok"]
    assert [e["prev"] for e in events] == ["ok", "shed", "degrade"]
    assert tevents.validate_file(event_stream, strict_kinds=True) == []


def test_admission_validates_parameters():
    with pytest.raises(ValueError, match="shed_factor"):
        AdmissionController(shed_factor=1.0)
    with pytest.raises(ValueError, match="hysteresis"):
        AdmissionController(hysteresis=0.0)


# ---------------- queue flood: shed low tiers, serve high ----------------

def test_queue_flood_sheds_tier0_serves_tier2(event_stream):
    """The headline chaos scenario: an instantaneous tier-0 flood (sized by
    the fault plan's queue_flood seam) against a tight admission config.
    Every tier-2 request completes; tier-0 sheds once the queue crosses the
    shed line; dispatch is highest-tier-first."""
    faults.set_plan(FaultPlan(queue_flood=24))
    flood_n = faults.queue_flood_n()
    assert flood_n == 24
    eng = _engine()
    eng.put("img", *_mpi_parts())
    admission = AdmissionController(enabled=True, burn_max=0.0,
                                    queue_high=4, inflight_high=0,
                                    shed_factor=2.0)
    b = MicroBatcher(eng, max_requests=4, start=False, admission=admission)
    flood = [b.submit("img", POSE, tier=TIER_BEST_EFFORT)
             for _ in range(flood_n)]
    crit = [b.submit("img", POSE, tier=TIER_CRITICAL) for _ in range(3)]
    # the flood crossed queue_high*shed_factor: controller is shedding,
    # and the shed futures resolved immediately (fast failure)
    assert admission.state == "shed"
    assert admission.shed > 0
    shed = [f for f in flood if f.done()]
    assert len(shed) == admission.shed
    for f in shed:
        with pytest.raises(RequestShed):
            f.result()
    # first dispatch is priority-ordered: every critical request rides it
    assert b.flush() == 4
    assert all(f.done() for f in crit)
    for f in crit:
        rgb, depth = f.result()
        assert rgb.shape == (3, HW, HW) and depth.shape == (1, HW, HW)
    while b.flush():
        pass
    for f in flood:  # everything admitted eventually rendered
        if f not in shed:
            f.result()
    tevents.reset()
    assert tevents.validate_file(event_stream, strict_kinds=True) == []
    kinds = [e["kind"] for e in tevents.read_events(event_stream)]
    assert "serve.admission" in kinds


# ---------------- degradation ladder ----------------

def test_degraded_miss_encodes_at_stepped_down_quant():
    eng = _engine(quant="bf16")
    eng.render_many([("deg", POSE)], images=[IMG], degraded=[True])
    import jax.numpy as jnp
    assert eng.cache._entries["deg"].planes.dtype == jnp.int8
    # a full-fidelity co-rider keeps the shared entry at the cache default
    eng2 = _engine(quant="bf16")
    eng2.render_many([("x", POSE), ("x", POSE)], images=[IMG, IMG],
                     degraded=[True, False])
    assert eng2.cache._entries["x"].planes.dtype == jnp.bfloat16
    # float32 default steps to bf16; int8 is already the floor
    eng3 = _engine(quant="float32")
    eng3.render_many([("y", POSE)], images=[IMG], degraded=[True])
    assert eng3.cache._entries["y"].planes.dtype == jnp.bfloat16
    eng4 = _engine(quant="int8")
    eng4.render_many([("z", POSE)], images=[IMG], degraded=[True])
    assert eng4.cache._entries["z"].planes.dtype == jnp.int8


def test_all_degraded_batch_caps_at_half_bucket():
    eng = _engine()
    eng.put("img", *_mpi_parts())
    admission = AdmissionController(enabled=True, burn_max=0.0,
                                    queue_high=1, inflight_high=0,
                                    shed_factor=100.0)  # degrade, never shed
    b = MicroBatcher(eng, max_requests=4, start=False, admission=admission)
    b.submit("img", POSE, tier=TIER_CRITICAL)  # not degraded (critical)
    futs = [b.submit("img", POSE, tier=TIER_BEST_EFFORT) for _ in range(7)]
    assert admission.degraded == 7
    # first batch mixes the critical rider in: full bucket, no cap
    assert b.flush() == 4
    # the remaining queue is ALL degraded: capped at max(1, 4//2) = 2
    assert b.flush() == 2
    assert b.flush() == 2
    assert b.flush() == 0
    for f in futs:
        f.result()


def test_mixed_dtype_batch_renders_via_host_dequant():
    """A degraded int8 placement coalescing with bf16 entries must render,
    and each row must match the same entry rendered alone."""
    eng = _engine(quant="bf16")
    eng.put("a", *_mpi_parts(seed=1))
    eng.render_many([("b", POSE)], images=[IMG], degraded=[True])  # int8
    import jax.numpy as jnp
    dtypes = {str(eng.cache._entries[k].planes.dtype) for k in ("a", "b")}
    assert dtypes == {"bfloat16", "int8"}
    mixed = eng.render_many([("a", POSE), ("b", POSE)])
    solo_a = eng.render_many([("a", POSE)])[0]
    solo_b = eng.render_many([("b", POSE)])[0]
    np.testing.assert_allclose(mixed[0][0], solo_a[0], atol=1e-6)
    np.testing.assert_allclose(mixed[1][0], solo_b[0], atol=1e-6)


# ---------------- deadline sweep ----------------

def test_deadline_sweep_purges_expired_before_dispatch():
    """Regression (fake clock): a request whose deadline passed while
    queued resolves DeadlineExceeded at dispatch time and is never
    rendered — the live request still dispatches in the same flush."""
    eng = _engine()
    eng.put("img", *_mpi_parts())
    b = MicroBatcher(eng, max_requests=4, start=False)
    clock = [100.0]
    b._now = lambda: clock[0]
    expired = b.submit("img", POSE, deadline_ms=50.0)
    alive = b.submit("img", POSE)           # no deadline
    later = b.submit("img", POSE, deadline_ms=500.0)
    before = eng.device_calls
    clock[0] = 100.2                         # 200ms later: only #1 expired
    n_exp = telemetry.counter("serve.batcher.expired").value
    assert b.flush() == 2
    with pytest.raises(DeadlineExceeded):
        expired.result()
    assert alive.result()[0].shape == (3, HW, HW)
    assert later.result()[0].shape == (3, HW, HW)
    assert b.expired == 1
    assert telemetry.counter("serve.batcher.expired").value == n_exp + 1
    # the expired request consumed NO device work beyond the live batch
    assert eng.device_calls == before + 1
    # an all-expired queue flushes to zero without any device call
    f = b.submit("img", POSE, deadline_ms=1.0)
    clock[0] = 101.0
    assert b.flush() == 0
    assert eng.device_calls == before + 1
    with pytest.raises(DeadlineExceeded):
        f.result()


def test_default_request_deadline_applies_when_unset():
    eng = _engine()
    eng.put("img", *_mpi_parts())
    b = MicroBatcher(eng, max_requests=4, start=False,
                     request_deadline_ms=50.0)
    clock = [0.0]
    b._now = lambda: clock[0]
    f_default = b.submit("img", POSE)                 # inherits 50ms
    f_override = b.submit("img", POSE, deadline_ms=0)  # opts out
    clock[0] = 1.0
    assert b.flush() == 1
    with pytest.raises(DeadlineExceeded):
        f_default.result()
    f_override.result()


# ---------------- encode retry / backoff ----------------

def test_transient_encode_failure_heals_inside_retry_budget():
    from mine_tpu.serve import engine as engine_mod

    faults.set_plan(FaultPlan(encode_raise_times=2))
    eng = _engine(encode_retries=3, encode_backoff_ms=0.1)
    engine_mod._warned_sync_encode.discard(id(eng))
    retry0 = telemetry.counter("serve.encode_retry").value
    rec0 = telemetry.counter("serve.encode_retry_recovered").value
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        rgb, depth = eng.render("t", POSE[None], image=IMG)
    # a recovered retry must NOT fire the one-time slow-path warning — the
    # slot stays unconsumed for a genuine clean-miss slow path
    assert not [w for w in rec if "SYNCHRONOUS" in str(w.message)]
    assert rgb.shape == (1, 3, HW, HW)
    assert eng.sync_encodes == 1  # one MISS, whatever the attempt count
    assert telemetry.counter("serve.encode_retry").value == retry0 + 2
    assert telemetry.counter(
        "serve.encode_retry_recovered").value == rec0 + 1
    assert "t" in eng.cache


def test_clean_miss_still_warns_once():
    from mine_tpu.serve import engine as engine_mod

    eng = _engine(encode_retries=3)
    engine_mod._warned_sync_encode.discard(id(eng))
    with pytest.warns(UserWarning, match="SYNCHRONOUS encode"):
        eng.render("w", POSE[None], image=IMG)


def test_encode_retry_exhaustion_raises():
    faults.set_plan(FaultPlan(encode_raise_times=5))
    eng = _engine(encode_retries=1, encode_backoff_ms=0.1)
    with pytest.raises(InjectedEncodeError):
        eng.render("t", POSE[None], image=IMG)
    assert eng.sync_encodes == 1
    assert "t" not in eng.cache
    # zero retries = the PR-10 behavior: first error propagates
    faults.set_plan(FaultPlan(encode_raise_times=1))
    eng0 = _engine(encode_retries=0)
    with pytest.raises(InjectedEncodeError):
        eng0.render("u", POSE[None], image=IMG)


# ---------------- shard failover ----------------

def test_shard_failover_reroutes_and_revives(event_stream):
    """Placement failures on shard 1 cross the threshold -> shard marked
    dead (serve.shard_dead), its key range re-routes ring-wise, and after
    the injected fault heals mark_alive re-adopts it (serve.shard_revive)."""
    faults.set_plan(FaultPlan(shard_kill=1, shard_kill_heal_after=2))
    cache = ShardedPlaneCache(num_shards=2, fail_threshold=2)
    iid = "c0000000aa"  # leading bits 0xc000... -> owner 1 at N=2
    assert cache.owner(iid) == 1
    for _ in range(2):
        with pytest.raises(faults.InjectedShardError):
            _ = cache.put(iid, *_mpi_parts())
    assert cache.dead_shards == [1]
    assert cache.failovers == 1
    # the fault healed after 2 injections, but shard 1 is dead: the same
    # key now routes to (and places on) the ring-next alive shard
    assert cache.alive_owner(iid) == 0
    cache.put(iid, *_mpi_parts())
    assert iid in cache and len(cache.shards[0]) == 1
    assert cache.get(iid) is not None
    # recovery: mark_alive moves the parked entry back to its true owner
    moved = cache.mark_alive(1)
    assert moved == 1
    assert cache.dead_shards == []
    assert len(cache.shards[1]) == 1 and len(cache.shards[0]) == 0
    assert cache.get(iid) is not None
    assert cache.mark_alive(1) == 0  # idempotent
    tevents.reset()
    events = tevents.read_events(event_stream)
    assert tevents.validate_file(event_stream, strict_kinds=True) == []
    dead = [e for e in events if e["kind"] == "serve.shard_dead"]
    revive = [e for e in events if e["kind"] == "serve.shard_revive"]
    assert len(dead) == 1 and dead[0]["shard"] == 1
    assert dead[0]["failures"] == 2
    assert len(revive) == 1 and revive[0]["moved"] == 1


def test_shard_failure_count_resets_on_success():
    """The dead threshold is CONSECUTIVE failures: a success in between
    resets the tally (one flaky placement never kills a shard)."""
    faults.set_plan(FaultPlan(shard_kill=1, shard_kill_heal_after=1))
    cache = ShardedPlaneCache(num_shards=2, fail_threshold=2)
    iid = "c0000000aa"
    with pytest.raises(faults.InjectedShardError):
        cache.put(iid, *_mpi_parts())     # failure #1, then the fault heals
    cache.put(iid, *_mpi_parts())         # success: tally resets
    assert cache.dead_shards == []
    assert cache._fail_counts == {}


def test_never_kills_the_last_alive_shard():
    faults.set_plan(FaultPlan(shard_kill=0, shard_kill_heal_after=-1))
    cache = ShardedPlaneCache(num_shards=1, fail_threshold=1)
    with pytest.raises(faults.InjectedShardError):
        cache.put("00aa", *_mpi_parts())
    assert cache.dead_shards == []  # a 1-shard cache can't fail over
    two = ShardedPlaneCache(num_shards=2)
    two.mark_dead(0)
    with pytest.raises(RuntimeError, match="last alive"):
        two.mark_dead(1)


def test_engine_retry_rides_through_shard_failover():
    """End to end: a dying shard's placement failures trip failover INSIDE
    one request's retry budget — the request succeeds with zero errors
    surfaced (the ISSUE's zero-failed-high-tier bar)."""
    faults.set_plan(FaultPlan(shard_kill=1, shard_kill_heal_after=-1))
    cache = ShardedPlaneCache(num_shards=2, fail_threshold=2)
    eng = RenderEngine(cache=cache, max_bucket=8, encode_fn=_encode_fn,
                       encode_retries=2, encode_backoff_ms=0.1)
    iid = "c0000000aa"  # owner 1: every placement there fails
    rgb, _ = eng.render(iid, POSE[None], image=IMG)
    assert rgb.shape == (1, 3, HW, HW)
    assert cache.dead_shards == [1]
    assert iid in cache  # parked on the fallback shard
    assert eng.sync_encodes == 1


def test_rebalance_clears_dead_marks():
    cache = ShardedPlaneCache(num_shards=4)
    _ = cache.put("00000000aa", *_mpi_parts())
    cache.mark_dead(2)
    assert cache.dead_shards == [2]
    cache.rebalance(2)
    assert cache.dead_shards == []
    assert "00000000aa" in cache


# ---------------- rebalance racing submit ----------------

def test_rebalance_races_concurrent_submits():
    """fleet.cache.rebalance() while a thread hammers submit(): every
    future resolves to the right shape, no exceptions, and the cache ends
    consistent. (The cache lock serializes the topology flips against the
    flush thread's routing/get/put.)"""
    fleet = ServeFleet(cache_shards=4, max_requests=4, max_wait_ms=1.0,
                       max_bucket=8)
    fleet.engine.put("img", *_mpi_parts())
    errors = []
    futs = []

    def hammer():
        try:
            for _ in range(24):
                futs.append(fleet.submit("img", POSE))
                time.sleep(0.001)
        except Exception as e:  # pragma: no cover - the failure signal
            errors.append(e)

    try:
        t = threading.Thread(target=hammer)
        t.start()
        for n in (2, 4, 2, 4):
            fleet.cache.rebalance(n)
            time.sleep(0.005)
        t.join(timeout=30)
        assert not t.is_alive()
        assert errors == []
        for f in futs:
            rgb, depth = f.result(timeout=30)
            assert rgb.shape == (3, HW, HW)
        assert "img" in fleet.cache
        stats = fleet.cache.stats()
        assert stats["entries"] == 1 and stats["rebalances"] == 4
    finally:
        fleet.close()


# ---------------- /healthz degraded ----------------

def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return json.loads(r.read())


def test_healthz_reports_degraded_on_dead_shard_and_burn():
    fleet = ServeFleet(cache_shards=2, start=False, ops_port=0,
                       slo_objective_ms=10.0)
    try:
        url = fleet.ops.url + "/healthz"
        assert _get_json(url)["status"] == "ok"
        # a dead shard degrades health — STILL HTTP 200 (the process is
        # up; degraded is a body field, not a probe failure)
        fleet.cache.mark_dead(1)
        h = _get_json(url)
        assert h["status"] == "degraded" and h["dead_shards"] == [1]
        fleet.cache.mark_alive(1)
        assert _get_json(url)["status"] == "ok"
        # error-budget burn > 1 degrades health too
        for _ in range(4):
            fleet.slo.record(100.0)  # all over the 10ms objective
        h = _get_json(url)
        assert h["status"] == "degraded"
        assert h["error_budget_burn"] > 1.0
        assert h["admission"] == "off"  # not enabled on this fleet
    finally:
        fleet.close()


# ---------------- per-tier SLO ----------------

def test_slo_snapshot_per_tier_percentiles():
    slo = SLOTracker(objective_ms=50.0)
    for ms in (5.0, 6.0, 7.0):
        slo.record(ms, tier=2)
    for ms in (80.0, 90.0):
        slo.record(ms, tier=0)
    slo.record(10.0)  # untiered: counted overall, absent from the table
    snap = slo.snapshot()
    assert snap["window_n"] == 6
    assert set(snap["tiers"]) == {"0", "2"}
    assert snap["tiers"]["2"]["n"] == 3
    assert snap["tiers"]["2"]["p99_ms"] < 10.0
    assert snap["tiers"]["0"]["p99_ms"] >= 80.0
    # the cached burn the admission controller reads lock-free
    assert round(slo.burn, 4) == snap["error_budget_burn"]


# ---------------- default-off parity ----------------

def test_defaults_off_bitwise_parity_with_plain_engine():
    """Every PR-11 knob at its default: the fleet's serve path must produce
    BITWISE-identical outputs to the plain single-device engine — admission
    off, no deadlines, uniform default tier (the stable sort reproduces
    FIFO exactly)."""
    from mine_tpu.config import serve_config_from_dict
    cfg = serve_config_from_dict({})
    assert not cfg.admission_enabled
    assert cfg.request_deadline_ms == 0.0 and cfg.encode_retries == 0
    single = _engine()
    single.put("img", *_mpi_parts())
    fleet = ServeFleet(cache_shards=2, max_requests=4, max_wait_ms=2.0,
                       max_bucket=8)
    fleet.engine.put("img", *_mpi_parts())
    assert fleet.admission is None
    try:
        poses = [POSE.copy() for _ in range(6)]
        for i, p in enumerate(poses):
            p[0, 3] = 0.01 * i
        futs = [fleet.submit("img", p) for p in poses]
        for p, f in zip(poses, futs):
            rgb, depth = f.result(timeout=30)
            ref_rgb, ref_depth = single.render("img", p[None])
            np.testing.assert_array_equal(rgb, ref_rgb[0])
            np.testing.assert_array_equal(depth, ref_depth[0])
        stats = fleet.stats()
        assert stats["shed"] == 0 and stats["degraded"] == 0
        assert stats["expired"] == 0 and stats["dead_shards"] == []
    finally:
        fleet.close()


def test_serve_config_parses_and_validates_resilience_keys():
    from mine_tpu.config import serve_config_from_dict
    cfg = serve_config_from_dict({
        "serve.default_tier": 2, "serve.request_deadline_ms": 250.0,
        "serve.encode_retries": 3, "serve.encode_backoff_ms": 5.0,
        "serve.shard_fail_threshold": 5,
        "serve.admission.enabled": True, "serve.admission.burn_max": 1.5,
        "serve.admission.queue_high": 32,
        "serve.admission.inflight_high": 128,
        "serve.admission.shed_factor": 3.0,
        "serve.admission.hysteresis": 0.5})
    assert cfg.default_tier == 2 and cfg.request_deadline_ms == 250.0
    assert cfg.encode_retries == 3 and cfg.shard_fail_threshold == 5
    assert cfg.admission_enabled and cfg.admission_shed_factor == 3.0
    fleet = ServeFleet.from_config(cfg, start=False)
    try:
        assert fleet.admission is not None
        assert fleet.batcher.default_tier == 2
        assert fleet.batcher.request_deadline_ms == 250.0
        assert fleet.engine.encode_retries == 3
        assert fleet.cache.fail_threshold == 5
    finally:
        fleet.close()
    for bad in ({"serve.default_tier": -1},
                {"serve.request_deadline_ms": -5},
                {"serve.encode_retries": -1},
                {"serve.shard_fail_threshold": 0},
                {"serve.admission.shed_factor": 1.0},
                {"serve.admission.hysteresis": 0.0}):
        with pytest.raises(ValueError):
            serve_config_from_dict(bad)
