import jax
import jax.numpy as jnp
import numpy as np

from mine_tpu.ops import sampling


def test_stratified_linspace_bins():
    key = jax.random.PRNGKey(0)
    B, S = 16, 32
    start, end = 1.0, 0.001
    d = np.asarray(sampling.uniformly_sample_disparity_from_linspace_bins(
        key, B, S, start, end))
    assert d.shape == (B, S)
    edges = np.linspace(start, end, S + 1)
    # every sample falls inside its own bin (edges descending)
    for s in range(S):
        assert np.all(d[:, s] <= edges[s] + 1e-6)
        assert np.all(d[:, s] >= edges[s + 1] - 1e-6)
    # strictly descending across bins
    assert np.all(d[:, :-1] > d[:, 1:])


def test_stratified_explicit_bins():
    key = jax.random.PRNGKey(1)
    edges = np.array([1.0, 0.5, 0.2, 0.05], dtype=np.float32)
    d = np.asarray(sampling.uniformly_sample_disparity_from_bins(key, 8, edges))
    assert d.shape == (8, 3)
    for s in range(3):
        assert np.all(d[:, s] <= edges[s] + 1e-6)
        assert np.all(d[:, s] >= edges[s + 1] - 1e-6)


def test_fixed_disparity():
    d = np.asarray(sampling.fixed_disparity_linspace(4, 8, 1.0, 0.1))
    np.testing.assert_allclose(d[0], np.linspace(1.0, 0.1, 8), rtol=1e-6)
    assert d.shape == (4, 8)


def test_sample_pdf_concentrates_mass():
    """All weight on one bin -> samples land in that bin's edge interval."""
    key = jax.random.PRNGKey(2)
    B, N, S = 2, 1, 8
    values = jnp.broadcast_to(jnp.linspace(1.0, 0.1, S), (B, 1, N, S))
    weights = jnp.zeros((B, 1, N, S)).at[..., 3].set(1.0)
    samples = np.asarray(sampling.sample_pdf(key, values, weights, 64))
    vals = np.asarray(values)[0, 0, 0]
    hi = (vals[2] + vals[3]) / 2  # upper edge of bin 3
    lo = (vals[3] + vals[4]) / 2  # lower edge
    assert samples.shape == (B, 1, N, 64)
    assert np.all(samples <= hi + 1e-5)
    assert np.all(samples >= lo - 1e-5)


def test_sample_pdf_uniform_statistics():
    key = jax.random.PRNGKey(3)
    B, N, S = 1, 1, 4
    values = jnp.broadcast_to(jnp.linspace(1.0, 0.0, S), (B, 1, N, S))
    weights = jnp.ones((B, 1, N, S))
    samples = np.asarray(sampling.sample_pdf(key, values, weights, 4096))
    # uniform over [0,1]-ish support: mean ~ 0.5
    assert abs(samples.mean() - 0.5) < 0.05


def test_gather_pixel_by_pxpy():
    B, C, H, W = 2, 3, 5, 7
    img = jnp.arange(B * C * H * W, dtype=jnp.float32).reshape(B, C, H, W)
    pxpy = jnp.asarray([[[0.2, 6.0, -3.0], [0.0, 4.4, 9.0]],
                        [[1.0, 2.0, 3.0], [1.0, 2.0, 3.0]]])  # [B,2,N]
    out = np.asarray(sampling.gather_pixel_by_pxpy(img, pxpy))
    ref = np.asarray(img)
    # (x=0.2->0, y=0->0): [0,0]; (x=6, y=4.4->4): [4,6]; (x=-3->0, y=9->4): [4,0]
    np.testing.assert_allclose(out[0, 0], [ref[0, 0, 0, 0], ref[0, 0, 4, 6],
                                           ref[0, 0, 4, 0]])
    np.testing.assert_allclose(out[1, 2], [ref[1, 2, 1, 1], ref[1, 2, 2, 2],
                                           ref[1, 2, 3, 3]])


def test_gather_matches_torch_reference():
    import torch

    rng = np.random.RandomState(0)
    B, C, H, W, N = 2, 1, 9, 11, 20
    img = rng.normal(size=(B, C, H, W)).astype(np.float32)
    pxpy = rng.uniform(-2, 12, size=(B, 2, N)).astype(np.float32)

    ours = np.asarray(sampling.gather_pixel_by_pxpy(jnp.asarray(img),
                                                    jnp.asarray(pxpy)))

    # direct port of rendering_utils.gather_pixel_by_pxpy (reference :27-44)
    t_img = torch.from_numpy(img)
    t_px = torch.round(torch.from_numpy(pxpy)).long()
    t_px[:, 0].clamp_(0, W - 1)
    t_px[:, 1].clamp_(0, H - 1)
    idx = t_px[:, 0:1] + W * t_px[:, 1:2]
    ref = torch.gather(t_img.view(B, C, H * W), 2, idx.repeat(1, C, 1)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-6)
