"""The torch->mine_tpu weight converter must emit exactly the key/shape space
of our Flax models — verified against fabricated torch-layout state dicts
(torchvision itself is not in this image)."""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "tools")
from convert_torch_weights import (_ref_key, convert_lpips,  # noqa: E402
                                   convert_mine_checkpoint, convert_resnet_sd)


class FakeTensor(np.ndarray):
    pass


def _t(*shape):
    return np.random.RandomState(0).normal(size=shape).astype(np.float32)


def fake_resnet18_sd(prefix=""):
    """State dict with torchvision resnet18 key layout + shapes."""
    sd = {}
    sd[prefix + "conv1.weight"] = _t(64, 3, 7, 7)
    for k in ("weight", "bias", "running_mean", "running_var"):
        sd[prefix + f"bn1.{k}"] = _t(64)
    chans = [(64, 64), (64, 128), (128, 256), (256, 512)]
    for layer, (cin, cout) in enumerate(chans, start=1):
        for b in range(2):
            base = prefix + f"layer{layer}.{b}"
            c_in = cin if b == 0 else cout
            sd[f"{base}.conv1.weight"] = _t(cout, c_in, 3, 3)
            sd[f"{base}.conv2.weight"] = _t(cout, cout, 3, 3)
            for n in (1, 2):
                for k in ("weight", "bias", "running_mean", "running_var"):
                    sd[f"{base}.bn{n}.{k}"] = _t(cout)
            if b == 0 and (cin != cout or layer > 1):
                sd[f"{base}.downsample.0.weight"] = _t(cout, c_in, 1, 1)
                for k in ("weight", "bias", "running_mean", "running_var"):
                    sd[f"{base}.downsample.1.{k}"] = _t(cout)
    return sd


def fake_resnet50_sd(prefix=""):
    """State dict with torchvision resnet50 key layout + real shapes
    (Bottleneck: conv1 1x1 / conv2 3x3 / conv3 1x1, expansion 4; every
    layer's block 0 has a downsample, including layer1 where 64 -> 256)."""
    sd = {}
    sd[prefix + "conv1.weight"] = _t(64, 3, 7, 7)
    for k in ("weight", "bias", "running_mean", "running_var"):
        sd[prefix + f"bn1.{k}"] = _t(64)
    blocks = [3, 4, 6, 3]
    widths = [64, 128, 256, 512]
    cin = 64
    for layer, (nb, w) in enumerate(zip(blocks, widths), start=1):
        for b in range(nb):
            base = prefix + f"layer{layer}.{b}"
            c_in = cin if b == 0 else w * 4
            sd[f"{base}.conv1.weight"] = _t(w, c_in, 1, 1)
            sd[f"{base}.conv2.weight"] = _t(w, w, 3, 3)
            sd[f"{base}.conv3.weight"] = _t(w * 4, w, 1, 1)
            for n, c in ((1, w), (2, w), (3, w * 4)):
                for k in ("weight", "bias", "running_mean", "running_var"):
                    sd[f"{base}.bn{n}.{k}"] = _t(c)
            if b == 0:
                sd[f"{base}.downsample.0.weight"] = _t(w * 4, c_in, 1, 1)
                for k in ("weight", "bias", "running_mean", "running_var"):
                    sd[f"{base}.downsample.1.{k}"] = _t(w * 4)
        cin = w * 4
    return sd


def fake_mine_decoder_sd(num_ch_enc=(64, 64, 128, 256, 512), E=21):
    """State dict with the reference DepthDecoder layout (depth_decoder.py)."""
    sd = {}
    enc = [c + E for c in num_ch_enc]
    dec = [16, 32, 64, 128, 256]

    def conv(name, cin, cout, k):
        sd[f"{name}.weight"] = _t(cout, cin, k, k)
        sd[f"{name}.bias"] = _t(cout)

    def conv_nobias(name, cin, cout, k):
        sd[f"{name}.weight"] = _t(cout, cin, k, k)

    def bn(name, c):
        for k in ("weight", "bias", "running_mean", "running_var"):
            sd[f"{name}.{k}"] = _t(c)

    # neck (depth_decoder.py:56-61): Sequential(conv(no bias), bn, lrelu)
    conv_nobias("conv_down1.0", num_ch_enc[-1], 512, 1)
    bn("conv_down1.1", 512)
    conv_nobias("conv_down2.0", 512, 256, 3)
    bn("conv_down2.1", 256)
    conv_nobias("conv_up1.0", 256, 256, 3)
    bn("conv_up1.1", 256)
    conv_nobias("conv_up2.0", 256, num_ch_enc[-1], 1)
    bn("conv_up2.1", num_ch_enc[-1])

    for i in range(4, -1, -1):
        cin = enc[-1] if i == 4 else dec[i + 1]
        key = f"convs.{_ref_key(('upconv', i, 0))}"
        conv(f"{key}.conv.conv", cin, dec[i], 3)
        bn(f"{key}.bn", dec[i])
        cin = dec[i] + (enc[i - 1] if i > 0 else 0)
        key = f"convs.{_ref_key(('upconv', i, 1))}"
        conv(f"{key}.conv.conv", cin, dec[i], 3)
        bn(f"{key}.bn", dec[i])
    for s in range(4):
        key = f"convs.{_ref_key(('dispconv', s))}"
        conv(f"{key}.conv", dec[s], 4, 3)
    return sd


def test_ref_key_matches_reference_tuple_to_str():
    """'-'.join(str(tuple)) joins the *characters* (depth_decoder.py:36-38)."""
    assert _ref_key(("upconv", 4, 0)) == "-".join(str(("upconv", 4, 0)))
    assert _ref_key(("dispconv", 2)).startswith("(-'-d-i-s-p")


def test_convert_resnet_covers_model_params_exactly():
    from mine_tpu.models.resnet import ResnetEncoder

    out = convert_resnet_sd(fake_resnet18_sd())
    model = ResnetEncoder(num_layers=18)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=False)

    def flatten(prefix, tree, into):
        for k, v in tree.items():
            key = f"{prefix}/{k}" if prefix else k
            if isinstance(v, dict):
                flatten(key, v, into)
            else:
                into[key] = v

    want_params, want_stats = {}, {}
    flatten("backbone", variables["params"], want_params)
    flatten("backbone", variables["batch_stats"], want_stats)

    got_params = {k: v for k, v in out.items() if not k.startswith("stats:")}
    got_stats = {k[len("stats:"):]: v for k, v in out.items()
                 if k.startswith("stats:")}

    assert set(got_params) == set(want_params), (
        set(got_params) ^ set(want_params))
    assert set(got_stats) == set(want_stats)
    for k in want_params:
        assert got_params[k].shape == tuple(want_params[k].shape), k


def test_convert_mine_checkpoint_covers_full_model():
    from mine_tpu.models.mpi import MPIPredictor

    ckpt = {"backbone": {("module.encoder." + k): v
                         for k, v in fake_resnet18_sd().items()},
            "decoder": {("module." + k): v
                        for k, v in fake_mine_decoder_sd().items()}}
    out = convert_mine_checkpoint(ckpt)

    model = MPIPredictor(num_layers=18)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)),
                           jnp.full((1, 2), 0.5), train=False)

    def flatten(prefix, tree, into):
        for k, v in tree.items():
            key = f"{prefix}/{k}" if prefix else k
            if isinstance(v, dict):
                flatten(key, v, into)
            else:
                into[key] = v

    want = {}
    flatten("", variables["params"], want)
    got = {k: v for k, v in out.items() if not k.startswith("stats:")}
    assert set(got) == set(want), sorted(set(got) ^ set(want))[:10]
    for k in want:
        assert got[k].shape == tuple(want[k].shape), (
            k, got[k].shape, want[k].shape)


def test_convert_lpips_covers_param_space():
    from mine_tpu.losses.lpips import _VGG_PLAN

    vgg_sd = {}
    idxs = [0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28]
    cin = 3
    i = 0
    for feat, n_convs in _VGG_PLAN:
        for _ in range(n_convs):
            vgg_sd[f"features.{idxs[i]}.weight"] = _t(feat, cin, 3, 3)
            vgg_sd[f"features.{idxs[i]}.bias"] = _t(feat)
            cin = feat
            i += 1
    lin_sd = {f"lin{k}.model.1.weight": _t(1, f, 1, 1)
              for k, (f, _) in enumerate(_VGG_PLAN)}
    out = convert_lpips(vgg_sd, lin_sd)
    assert len([k for k in out if k.startswith("conv")]) == 26
    for k, (f, _) in enumerate(_VGG_PLAN):
        assert out[f"lin{k}_w"].shape == (f,)
    # converted params drive the metric
    from mine_tpu.losses import lpips as lp
    params = {k: jnp.asarray(v) for k, v in out.items()}
    a = jnp.zeros((1, 3, 32, 32))
    d = np.asarray(lp.lpips_distance(params, a, a))
    np.testing.assert_allclose(d, 0.0, atol=1e-6)
