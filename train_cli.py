#!/usr/bin/env python
"""Training entry point — CLI-compatible with the reference's train.py.

  python train_cli.py --config_path mine_tpu/configs/params_llff.yaml \
      --workspace /path/ws --version v1 \
      --extra_config '{"training.epochs": 100}'

Differences from the reference launcher (reference: train.py +
start_training.sh): single-controller JAX replaces torch.distributed.launch —
no --local_rank, no CUDA_VISIBLE_DEVICES juggling, no NCCL rendezvous. On a
multi-host TPU pod, set the standard JAX coordination env vars and pass
--distributed to call jax.distributed.initialize(); the mesh then spans all
hosts and the loop shards data by process index.
"""

import argparse
import json
import os
import shutil
import sys


def main():
    parser = argparse.ArgumentParser(description="Training")
    parser.add_argument("--config_path", default=None, type=str)
    parser.add_argument("--workspace", type=str, required=True)
    parser.add_argument("--version", type=str, required=True)
    parser.add_argument("--extra_config", type=str, default="{}")
    parser.add_argument("--distributed", action="store_true",
                        help="call jax.distributed.initialize() (multi-host)")
    parser.add_argument("--plane_parallel", type=int, default=None,
                        help="override parallel.plane_parallel")
    args = parser.parse_args()

    import jax

    # Some containers register accelerator plugins that force-override
    # jax_platforms via jax.config; re-assert the user's JAX_PLATFORMS so the
    # standard env-var contract holds.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from mine_tpu.utils import configure_compile_cache
    configure_compile_cache()

    if args.distributed:
        jax.distributed.initialize()

    from mine_tpu.config import CONFIG_DIR, load_config, save_config
    from mine_tpu.data.llff import get_dataset
    from mine_tpu.losses import lpips as lpips_mod
    from mine_tpu.parallel.mesh import make_mesh
    from mine_tpu.train.loop import TrainLoop
    from mine_tpu.train.step import SynthesisTrainer
    from mine_tpu.utils import make_logger

    config_path = args.config_path or os.path.join(CONFIG_DIR,
                                                   "params_llff.yaml")
    config = load_config(config_path, extra_config=args.extra_config)
    if args.plane_parallel is not None:
        config["parallel.plane_parallel"] = args.plane_parallel

    # chaos-test seams (testing.fault_plan / MINE_TPU_FAULTS env JSON);
    # no-op in production. Must run before the trainer is constructed —
    # the NaN-grad injection is resolved at trace time.
    from mine_tpu.testing import faults
    fault_plan = faults.activate(config)

    workspace = os.path.join(args.workspace, args.version)
    is_lead = jax.process_index() == 0
    if is_lead:
        os.makedirs(workspace, exist_ok=True)
        save_config(config, os.path.join(workspace, "params.yaml"))

    log_file = os.path.join(workspace, "training.log") if is_lead else None
    logger = make_logger(log_file)
    logger.info("Training config: %s", json.dumps(
        {k: v for k, v in config.items() if isinstance(v, (str, int, float,
                                                           bool, list))},
        indent=0))
    logger.info("JAX devices: %s (process %d/%d)", jax.devices(),
                jax.process_index(), jax.process_count())

    tb_writer = None
    if is_lead:
        try:
            from tensorboardX import SummaryWriter
            tb_writer = SummaryWriter(log_dir=workspace)
        except ImportError:
            logger.warning("tensorboardX unavailable; scalar logging only")

    # mesh: data x plane over all devices
    plane = int(config.get("parallel.plane_parallel", 1))
    data = int(config.get("parallel.data_parallel", -1))
    n_dev = len(jax.devices())
    mesh = None
    if n_dev > 1 or plane > 1:
        mesh = make_mesh(data=data, plane=plane)
        logger.info("Mesh: %s", mesh)

    train_ds, val_ds = get_dataset(config, logger)

    lpips_params = lpips_mod.load_params(lpips_mod.default_weights_path())
    if lpips_params is None:
        logger.info("LPIPS weights not found (%s); lpips metric disabled",
                    lpips_mod.default_weights_path())

    # steps_per_epoch drives the LR schedule AND the loop's epoch accounting —
    # computed once from the global batch geometry (per-device batch x data
    # axis size), then owned by the trainer
    from mine_tpu.parallel.mesh import DATA_AXIS
    data_size = mesh.shape[DATA_AXIS] if mesh is not None else 1
    global_batch = int(config["data.per_gpu_batch_size"]) * data_size
    steps_per_epoch = max(1, len(train_ds) // global_batch)
    trainer = SynthesisTrainer(config, mesh=mesh,
                               steps_per_epoch=steps_per_epoch,
                               lpips_params=lpips_params)

    state = trainer.init_state(trainer.global_batch_size())
    pretrained = config.get("model.pretrained_weights_path") or \
        config.get("training.pretrained_checkpoint_path")
    if pretrained and str(pretrained).endswith(".npz"):
        from mine_tpu.train.checkpoint import load_pretrained_params
        new_params, new_stats = load_pretrained_params(
            pretrained, state.params, state.batch_stats, logger)
        state = state.replace(params=new_params, batch_stats=new_stats)
        logger.info("Loaded pretrained weights from %s", pretrained)

    if fault_plan is not None:
        logger.warning("FAULT INJECTION ACTIVE: %s", fault_plan)

    loop = TrainLoop(trainer, train_ds, val_ds, workspace,
                     logger=logger, tb_writer=tb_writer)
    loop.run(state)
    if loop.preempted:
        # clean preemption exit: the emergency checkpoint is on disk and a
        # relaunch resumes exactly; exit 0 so supervisors treat this as a
        # graceful drain, not a crash loop
        logger.info("Exiting after preemption checkpoint — relaunch to "
                    "resume")
        sys.exit(0)


if __name__ == "__main__":
    main()
