"""Single-image inference -> novel-view camera-path videos.

Replaces visualizations/image_to_video.py: encode ONE image into an MPI, then
render a camera trajectory by re-running only the warp+composite per pose
(VideoGenerator: infer once :112-153, render per frame :219-255).

TPU-first difference: poses are rendered in jitted *batches* (the pose axis is
just a batch axis of the warp), not one python-loop frame at a time. The
batched render itself lives in the serving engine (mine_tpu/serve): this
class encodes the image, caches the blended MPI in the engine's cache, and
drives `RenderEngine.render` per trajectory — the same compile-once,
render-only program the serving path uses. The default float32 cache keeps
frames bitwise-identical to the pre-engine private chunk loop
(tests/test_serve.py gates this).

Videos are written with imageio(+ffmpeg) when available, else PNG frames —
moviepy (the reference's writer) is not in this image.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from mine_tpu import geometry
from mine_tpu.config import mpi_config_from_dict, validate_model_shapes
from mine_tpu.models.mpi import MPIPredictor
from mine_tpu.ops import rendering
from mine_tpu.serve import (ContinuousBatcher, MPICache, RenderEngine,
                            SessionManager, image_id_for)
from mine_tpu.train.step import sample_disparity
from mine_tpu.utils import disparity_normalization_vis


def path_planning(num_frames: int, x: float, y: float, z: float,
                  path_type: str = "", s: float = 0.3):
    """Camera path generators (reference image_to_video.py:22-48):
    'straight-line' (quadratic through origin/mid/end), 'double-straight-line'
    (linear there-and-back), 'circle'."""
    if path_type == "straight-line":
        corner_points = np.array([[0, 0, 0],
                                  [(0 + x) * 0.5, (0 + y) * 0.5, (0 + z) * 0.5],
                                  [x, y, z]])
        t = np.linspace(0, 1, num_frames)
        # quadratic through the 3 corner points (t = 0, .5, 1)
        coeffs = np.polyfit(np.linspace(0, 1, 3), corner_points, 2)  # [3,3dims]
        spline = np.stack([np.polyval(coeffs[:, i], t) for i in range(3)], axis=1)
        xs, ys, zs = spline[:, 0], spline[:, 1], spline[:, 2]
    elif path_type == "double-straight-line":
        t = np.linspace(0, 1, int(num_frames * 0.5))
        start = np.array([s * x, s * y, s * z])
        end = np.array([-x, -y, -z])
        seg = start[None] * (1 - t[:, None]) + end[None] * t[:, None]
        xs = np.concatenate([seg[:, 0], np.flip(seg[:, 0])])
        ys = np.concatenate([seg[:, 1], np.flip(seg[:, 1])])
        zs = np.concatenate([seg[:, 2], np.flip(seg[:, 2])])
    elif path_type == "circle":
        xs, ys, zs = [], [], []
        for shift in np.arange(-2.0, 2.0, 4.0 / num_frames):
            xs.append(np.cos(shift * np.pi) * x)
            ys.append(np.sin(shift * np.pi) * y)
            zs.append(np.cos(shift * np.pi / 2.0) * z - s * z)
        xs, ys, zs = np.array(xs), np.array(ys), np.array(zs)
    else:
        raise ValueError(f"unknown path_type {path_type}")
    return xs, ys, zs


# band height of the Pallas warp gather (kernels/warp.py); poses whose
# row-block span (+ bilinear support + the kernel's sublane-alignment
# slack) exceeds it fall back to the XLA gather. 32 (was 16): the round-4
# alignment slack costs 7 rows of headroom, and forward-only banded cost
# scales only linearly with the band.
WARP_BAND = 32

TRAJECTORY_PRESETS = {
    # dataset -> (fps, num_frames, x_ranges, y_ranges, z_ranges, types, names)
    # (reference image_to_video.py:156-175)
    "kitti_raw": (30, 90, [0.0, -0.8], [0.0, -0.0], [-1.5, -1.0],
                  ["double-straight-line", "circle"], ["zoom-in", "swing"]),
    "realestate10k": (30, 90, [0.0, -0.16], [0.0, -0.0], [-0.30, -0.2],
                      ["double-straight-line", "circle"], ["zoom-in", "swing"]),
    "nyu": (30, 90, [0.0, -0.16], [0.0, -0.0], [-0.30, -0.2],
            ["double-straight-line", "circle"], ["zoom-in", "swing"]),
    "ibims": (30, 90, [0.0, -0.16], [0.0, -0.0], [-0.30, -0.2],
              ["double-straight-line", "circle"], ["zoom-in", "swing"]),
    # fallback used for llff/flowers/dtu (not covered upstream)
    "_default": (30, 60, [0.0, -0.12], [0.0, -0.0], [-0.24, -0.16],
                 ["double-straight-line", "circle"], ["zoom-in", "swing"]),
}


def generate_trajectories(dataset_name: str):
    preset = TRAJECTORY_PRESETS.get(dataset_name, TRAJECTORY_PRESETS["_default"])
    fps, num_frames, xr, yr, zr, types, names = preset
    trajectories = []
    for i, ttype in enumerate(types):
        sx, sy, sz = path_planning(num_frames, xr[i], yr[i], zr[i],
                                   path_type=ttype)
        poses = []
        for xx, yy, zz in zip(sx, sy, sz):
            G = np.eye(4, dtype=np.float32)
            G[:3, 3] = [xx, yy, zz]
            poses.append(G)
        trajectories.append(np.stack(poses))  # [F,4,4]
    return trajectories, {"fps": fps, "names": names}


def _blend_mpi(cfg, backend: str, mpi, img_1hw3, disparity, K_inv):
    """Source-blend the predicted MPI (the reference infer_network tail):
    render the blend weights at the source pose and mix the source pixels
    into the plane RGB. One code path shared by the single-image
    VideoGenerator and the per-frame streaming encode (StreamRenderer), so
    both produce bitwise-identical planes for the same pixels."""
    rgb = mpi[:, :, 0:3]
    sigma = mpi[:, :, 3:4]
    H, W = int(img_1hw3.shape[1]), int(img_1hw3.shape[2])
    grid = geometry.cached_pixel_grid(H, W)
    xyz_src = geometry.plane_xyz_src(grid, disparity, K_inv)
    src_nchw = jnp.transpose(img_1hw3, (0, 3, 1, 2))
    if backend == "pallas" and not cfg.use_alpha:
        # one fused pass: composite + src rgb blending + blended volume
        from mine_tpu.kernels import on_tpu_backend
        from mine_tpu.kernels.composite import fused_src_render_blend
        _, _, mpi_rgb = fused_src_render_blend(
            rgb, sigma, xyz_src, src_nchw,
            is_bg_depth_inf=cfg.is_bg_depth_inf,
            interpret=not on_tpu_backend())
    else:
        _, _, blend_weights, _ = rendering.render(
            rgb, sigma, xyz_src,
            use_alpha=cfg.use_alpha,
            is_bg_depth_inf=cfg.is_bg_depth_inf)
        mpi_rgb = blend_weights * src_nchw[:, None] + \
            (1.0 - blend_weights) * rgb
    return mpi_rgb, sigma


class VideoGenerator:
    """Encode one image, then render trajectories in jitted pose chunks."""

    def __init__(self, config: Dict, params, batch_stats,
                 img_hwc: np.ndarray,
                 chunk: int = 8,
                 dtype=jnp.bfloat16,
                 seed: int = 0,
                 backend: Optional[str] = None,
                 engine: Optional[RenderEngine] = None,
                 cache_quant: str = "float32",
                 encoder_quant: str = "off"):
        self.cfg = mpi_config_from_dict(config)
        validate_model_shapes(self.cfg)
        self.config = config
        self.chunk = chunk
        if backend is None:
            # fused Pallas composite on TPU-class backends, XLA elsewhere
            from mine_tpu.kernels import on_tpu_backend
            backend = "pallas" if on_tpu_backend() else "xla"
        self.backend = backend
        H, W = self.cfg.img_h, self.cfg.img_w

        img = _resize_bilinear(img_hwc, H, W)
        self.img = jnp.asarray(img, jnp.float32)[None]  # [1,H,W,3]

        self.K = jnp.asarray(geometry.intrinsics_from_fov(H, W, 90.0))[None]
        self.K_inv = geometry.inverse_intrinsics(self.K)

        model = MPIPredictor(
            num_layers=self.cfg.num_layers,
            pos_encoding_multires=self.cfg.pos_encoding_multires,
            use_alpha=self.cfg.use_alpha,
            dtype=dtype)

        # one network pass (reference infer_network :112-153)
        disparity = sample_disparity(jax.random.PRNGKey(seed), 1, self.cfg)
        if encoder_quant == "off":
            variables = {"params": params, "batch_stats": batch_stats}
            mpi = model.apply(variables, self.img, disparity, train=False)[0]
        else:
            # serve.encoder_quant=int8: weights stored per-channel int8 with
            # the widening dequant fused into the jitted encode
            # (mine_tpu/serve/encoder.py); a pre-quantized params tree
            # (serve_cli quantizes once for all images) passes through
            from mine_tpu.serve.encoder import make_encode_fn
            encode = make_encode_fn(model, params, batch_stats,
                                    encoder_quant=encoder_quant)
            mpi = encode(self.img, disparity)
        self.disparity = disparity

        self.mpi_rgb, self.mpi_sigma = _blend_mpi(
            self.cfg, self.backend, mpi, self.img, disparity, self.K_inv)

        # hand the encode to the serving engine's cache; trajectories render
        # through its bucketed jitted program (one compile set per warp impl)
        if engine is None:
            engine = RenderEngine(
                use_alpha=self.cfg.use_alpha,
                is_bg_depth_inf=self.cfg.is_bg_depth_inf,
                backend=self.backend,
                warp_band=WARP_BAND,
                max_bucket=chunk,
                cache=MPICache(quant=cache_quant))
        self.engine = engine
        self.image_id = image_id_for(np.asarray(self.img))
        engine.put(self.image_id, self.mpi_rgb[0], self.mpi_sigma[0],
                   self.disparity[0], self.K[0])

    def _max_row_block_span(self, poses_F44: np.ndarray,
                            rows_per_block: int = 8, step: int = 8) -> float:
        """Host-side (numpy) upper estimate of the per-row-block source-row
        span of the warp, over all poses and planes — decides whether the
        banded Pallas gather's correctness domain holds for a trajectory
        (kernels/warp.py module docstring)."""
        H, W = self.cfg.img_h, self.cfg.img_w
        F = poses_F44.shape[0]
        depths = 1.0 / np.asarray(self.disparity[0])  # [S]
        S = depths.shape[0]

        # one source of truth: the same homography composition the device
        # warp uses (geometry.homography_tgt_src), batched over [F,S]
        G = jnp.broadcast_to(jnp.asarray(poses_F44)[:, None], (F, S, 4, 4))
        d = jnp.broadcast_to(jnp.asarray(depths)[None, :], (F, S))
        Hts = geometry.homography_tgt_src(
            jnp.broadcast_to(self.K[0], (F, S, 3, 3)),
            jnp.broadcast_to(self.K_inv[0], (F, S, 3, 3)),
            G, d)
        Hst = np.asarray(geometry.inverse_3x3(Hts))          # [F,S,3,3]

        # block-boundary rows x coarse columns
        rows = np.stack([np.arange(0, H, rows_per_block),
                         np.arange(0, H, rows_per_block) + rows_per_block - 1],
                        axis=1).reshape(-1).astype(np.float32)  # [2*NB]
        cols = np.arange(0, W, step, dtype=np.float32)
        ii, jj = np.meshgrid(rows, cols, indexing="ij")      # [NR,NJ]
        pts = np.stack([jj, ii, np.ones_like(ii)], axis=0)   # [3,NR,NJ]

        num = np.einsum("fsab,brj->fsarj", Hst, pts)         # [F,S,3,NR,NJ]
        y = num[:, :, 1] / num[:, :, 2]                      # [F,S,NR,NJ]
        y = np.clip(y, 0.0, H - 1.0)
        yb = y.reshape(y.shape[0], y.shape[1], -1, 2, y.shape[-1])  # per block
        span = yb.max(axis=(3, 4)) - yb.min(axis=(3, 4))
        return float(span.max())

    def render_poses(self, poses_F44: np.ndarray):
        """[F,4,4] -> (rgb [F,3,H,W], disparity [F,1,H,W]) numpy."""
        warp_impl = "xla"
        if self.backend == "pallas" and self.cfg.img_h % 8 == 0:
            # banded Pallas gather only when the trajectory's warp fits the
            # band: span + 2 rows of bilinear support + the kernel's
            # sublane-alignment slack (kernels/warp.py _align_slack — the
            # floored band start can sit up to 7 rows above the ideal one),
            # + 2 extra margin for the coarse span estimate
            from mine_tpu.kernels.warp import _align_slack
            span = self._max_row_block_span(poses_F44)
            slack = _align_slack(WARP_BAND, int(self.cfg.img_h))
            if span + 4 + slack <= WARP_BAND:
                warp_impl = "pallas"
        rgb, depth = self.engine.render(
            self.image_id, np.asarray(poses_F44, np.float32),
            warp_impl=warp_impl)
        # floor matches the loss graph's safe inversion: fully-transparent
        # pixels composite to depth 0 and would otherwise make inf frames
        return rgb, np.float32(1.0) / np.maximum(depth, np.float32(1e-8))

    def render_videos(self, output_dir: str, output_name: str) -> List[str]:
        trajectories, meta = generate_trajectories(self.config.get("data.name",
                                                                   "_default"))
        os.makedirs(output_dir, exist_ok=True)
        written = []
        for poses, name in zip(trajectories, meta["names"]):
            rgb, disp = self.render_poses(poses)
            disp_vis = disparity_normalization_vis(disp)
            rgb_u8 = _to_uint8_frames(rgb)
            disp_u8 = _colormap_frames(disp_vis)
            for frames, tag in ((rgb_u8, "rgb"), (disp_u8, "disp")):
                path = os.path.join(output_dir,
                                    f"{output_name}_{name}_{tag}")
                written.append(_write_video(frames, path, meta["fps"]))
        return written


class StreamRenderer:
    """Keyframe-cadenced streaming video over the serving session plane.

    Where `VideoGenerator` encodes ONE image and renders a trajectory from
    it, this drives a live frame sequence through a `StreamSession`
    (mine_tpu/serve/session.py): the network runs only at keyframes (every
    `keyframe_every` frames, or earlier when the drift proxy trips), and
    every other frame is warp+composite from its keyframe's cached MPI —
    through the SAME bucketed jitted render program and AOT store static
    serving uses, so streaming adds no compile surface.

    `keyframe_every=1` degenerates to encode-every-frame and is bitwise
    identical to the per-frame `VideoGenerator` path (the K=1 parity test
    in tests/test_stream_session.py pins this).

    Pass `manager=` to ride an existing serving backend (a `ServeFleet`'s
    SessionManager); by default the renderer owns a private
    RenderEngine + ContinuousBatcher + SessionManager and closes them.
    """

    def __init__(self, config: Dict, params, batch_stats,
                 chunk: int = 8,
                 dtype=jnp.bfloat16,
                 seed: int = 0,
                 backend: Optional[str] = None,
                 manager: Optional[SessionManager] = None,
                 cache_quant: str = "float32",
                 encoder_quant: str = "off",
                 keyframe_every: int = 1,
                 drift_budget: float = 0.0,
                 drift_mode: str = "probe",
                 probe_stride: int = 4,
                 max_wait_ms: float = 2.0):
        self.cfg = mpi_config_from_dict(config)
        validate_model_shapes(self.cfg)
        self.config = config
        if backend is None:
            from mine_tpu.kernels import on_tpu_backend
            backend = "pallas" if on_tpu_backend() else "xla"
        self.backend = backend
        H, W = self.cfg.img_h, self.cfg.img_w

        self.K = jnp.asarray(geometry.intrinsics_from_fov(H, W, 90.0))[None]
        self.K_inv = geometry.inverse_intrinsics(self.K)

        model = MPIPredictor(
            num_layers=self.cfg.num_layers,
            pos_encoding_multires=self.cfg.pos_encoding_multires,
            use_alpha=self.cfg.use_alpha,
            dtype=dtype)
        # one fixed disparity set for the whole stream (same sampling the
        # single-image path uses) — keyframes share plane geometry, so the
        # render program's disparity input never changes shape or value
        self.disparity = sample_disparity(jax.random.PRNGKey(seed), 1,
                                          self.cfg)
        if encoder_quant == "off":
            variables = {"params": params, "batch_stats": batch_stats}

            def _network(img_1hw3):
                return model.apply(variables, img_1hw3, self.disparity,
                                   train=False)[0]
        else:
            from mine_tpu.serve.encoder import make_encode_fn
            encode = make_encode_fn(model, params, batch_stats,
                                    encoder_quant=encoder_quant)

            def _network(img_1hw3):
                return encode(img_1hw3, self.disparity)

        def _encode_frame(img_hwc):
            """engine encode_fn: full network pass + source blend for ONE
            observed frame — the keyframe path (identical ops to
            VideoGenerator.__init__, via _blend_mpi)."""
            img = jnp.asarray(img_hwc, jnp.float32)[None]
            mpi = _network(img)
            mpi_rgb, mpi_sigma = _blend_mpi(self.cfg, self.backend, mpi,
                                            img, self.disparity, self.K_inv)
            return (mpi_rgb[0], mpi_sigma[0], self.disparity[0], self.K[0])

        self.encode_frame = _encode_frame
        self._owned_batcher = None
        if manager is None:
            engine = RenderEngine(
                use_alpha=self.cfg.use_alpha,
                is_bg_depth_inf=self.cfg.is_bg_depth_inf,
                backend=self.backend,
                warp_band=WARP_BAND,
                max_bucket=chunk,
                cache=MPICache(quant=cache_quant),
                encode_fn=_encode_frame)
            self._owned_batcher = ContinuousBatcher(engine,
                                                    max_requests=chunk,
                                                    max_wait_ms=max_wait_ms)
            manager = SessionManager(self._owned_batcher,
                                     keyframe_every=keyframe_every,
                                     drift_budget=drift_budget,
                                     drift_mode=drift_mode,
                                     probe_stride=probe_stride)
        self.manager = manager
        self.last_stats: Optional[dict] = None

    def prepare_frame(self, frame_hwc: np.ndarray) -> np.ndarray:
        """Resize/normalize one observed frame to the model's [H,W,3] f32."""
        return np.asarray(
            _resize_bilinear(frame_hwc, self.cfg.img_h, self.cfg.img_w),
            np.float32)

    def stream(self, frames, poses_F44: Optional[np.ndarray] = None,
               session_id: Optional[str] = None):
        """Drive a frame sequence through one session; returns
        (rgb [F,3,H,W], disparity [F,1,H,W]) f32 numpy in frame order.
        `poses_F44` are per-frame camera poses relative to the stream's
        world (default: identity — re-render each observed viewpoint)."""
        session = self.manager.open(session_id)
        futures = []
        try:
            for n, frame in enumerate(frames):
                prepared = self.prepare_frame(np.asarray(frame))
                pose = None if poses_F44 is None else \
                    np.asarray(poses_F44[n], np.float32)
                futures.append(session.process_frame(prepared, pose))
            results = [f.result() for f in futures]
        finally:
            self.last_stats = session.stats()
            session.close()
        rgb = np.stack([r[0] for r in results])
        depth = np.stack([r[1] for r in results])
        return rgb, np.float32(1.0) / np.maximum(depth, np.float32(1e-8))

    def close(self) -> None:
        self.manager.close()
        if self._owned_batcher is not None:
            self._owned_batcher.close()


# ---------------- image helpers ----------------

def _resize_bilinear(img_hwc: np.ndarray, H: int, W: int) -> np.ndarray:
    import cv2
    img = cv2.resize(img_hwc, (W, H), interpolation=cv2.INTER_LINEAR)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    return img


def _to_uint8_frames(rgb_f3hw: np.ndarray) -> np.ndarray:
    x = np.clip(np.round(rgb_f3hw * 255.0), 0, 255).astype(np.uint8)
    return np.transpose(x, (0, 2, 3, 1))  # [F,H,W,3]


def _colormap_frames(disp_f1hw: np.ndarray) -> np.ndarray:
    import cv2
    frames = []
    for d in disp_f1hw[:, 0]:
        u8 = np.clip(np.round(d * 255.0), 0, 255).astype(np.uint8)
        c = cv2.applyColorMap(u8, cv2.COLORMAP_HOT)
        frames.append(cv2.cvtColor(c, cv2.COLOR_BGR2RGB))
    return np.stack(frames)


def _write_video(frames_fhwc: np.ndarray, path_base: str, fps: int) -> str:
    """mp4 via imageio/ffmpeg; PNG frame directory as fallback."""
    try:
        import imageio
        path = path_base + ".mp4"
        imageio.mimwrite(path, list(frames_fhwc), fps=fps)
        return path
    except Exception:
        os.makedirs(path_base, exist_ok=True)
        from PIL import Image as PILImage
        for i, f in enumerate(frames_fhwc):
            PILImage.fromarray(f).save(
                os.path.join(path_base, f"frame_{i:04d}.png"))
        return path_base
