from mine_tpu.infer.video import VideoGenerator, path_planning  # noqa: F401
