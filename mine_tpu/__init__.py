"""mine_tpu — a TPU-native (JAX/XLA/Flax/Pallas) single-image novel view
synthesis framework with the capabilities of MINE (ICCV 2021,
vincentfung13/MINE): an encoder–decoder predicts an N-plane multiplane image
(per-plane RGB + density sigma) from one RGB image plus N sampled disparities;
novel views are rendered by warping each plane with a per-plane homography and
volume-compositing.

Built TPU-first, not as a port:
  * pure-functional geometry/rendering ops (explicit PRNG keys, static shapes)
  * Flax NHWC models compiled by XLA onto the MXU
  * data/plane parallelism via `jax.sharding.Mesh` + jit sharding constraints
    (GSPMD inserts the collectives; BatchNorm statistics become global — the
    SPMD equivalent of the reference's SyncBatchNorm, synthesis_task.py:106-111)
  * Pallas kernels for the HBM-bound homography warp/composite hot path

Layer map (mirrors SURVEY.md section 1; modules land milestone by milestone):
  cli       train_cli.py, infer (image -> video)
  trainer   mine_tpu.train     (step fn, loop, checkpointing, eval)
  models    mine_tpu.models    (ResNet encoder, MPI decoder, embedder)
  ops       mine_tpu.ops       (rendering, warp, sampling) + mine_tpu.kernels
  data      mine_tpu.data      (COLMAP reader, LLFF dataset, synthetic scenes)
  runtime   mine_tpu.parallel  (mesh, shardings) — XLA collectives over ICI/DCN
"""

__version__ = "0.1.0"
