"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The repo's observability was ad-hoc per-subsystem state (MPICache's `hits`
attribute, PIPELINE_STATS' lock-guarded ints, the train loop's AverageMeter
dict). This module is the one place those numbers now live: a dependency-free
registry any layer can reach without plumbing handles through constructors —
`telemetry.counter("serve.cache.hits").inc()` from the cache is visible to
serve_cli's stats line, obs_report, and the SLO bench alike.

Design constraints (the same ones the PR-4 guard obeyed):
  * HOST-SIDE ONLY. Nothing here is traced; recording a metric never touches
    a jax array, so instrumentation cannot add a device sync or perturb a
    jitted program. Callers convert to python floats BEFORE recording.
  * Thread-safe: serve's batcher flush thread, the pipeline's assembler
    workers and the train loop all record concurrently.
  * Fixed-bucket histograms, not reservoirs: O(buckets) memory forever,
    mergeable, and quantiles are bounded by bucket width (documented below)
    — the standard latency-histogram trade (Prometheus/HdrHistogram shape).

Naming convention: dotted lowercase paths, unit-suffixed where a unit exists
(`train.step_ms`, `serve.cache.bytes`). The README "Observability" section
holds the catalog.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from mine_tpu.analysis.locks import ordered_lock


def default_latency_buckets_ms() -> Tuple[float, ...]:
    """Geometric bucket edges covering 0.05 ms .. ~2 min with ~1.3x growth:
    relative quantile error is bounded by the growth factor (a reported p99
    lies within the true p99's bucket, i.e. within +-30%) at 56 buckets of
    constant memory. Wide enough for a jit compile (tens of s), fine enough
    for a sub-ms cache-hit render."""
    edges, e = [], 0.05
    while e < 120_000.0:
        edges.append(e)
        e *= 1.3
    return tuple(edges)


def pow2_buckets(max_edge: int = 4096) -> Tuple[float, ...]:
    """1, 2, 4, ... edges for size-ish histograms (coalesce sizes, pose
    counts): exact counts per power-of-two bucket, matching the serving
    engine's pow2 shape-bucketing so the histogram reads as 'how often did
    each compiled bucket run'."""
    edges, e = [], 1
    while e <= max_edge:
        edges.append(float(e))
        e *= 2
    return tuple(edges)


class Counter:
    """Monotonic counter. `inc` only; resets only with its registry."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = ordered_lock("telemetry.registry.metric")
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc by {n} < 0")
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins scalar (cache residency bytes, cumulative counters
    owned elsewhere and mirrored here at log cadence)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = ordered_lock("telemetry.registry.metric")
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with p50/p90/p99 extraction.

    `edges` are bucket UPPER bounds (ascending); a sample lands in the first
    bucket whose edge is >= the sample, with one implicit overflow bucket
    past the last edge. Quantiles linearly interpolate within the containing
    bucket, so the reported value is within that bucket's span of the exact
    order statistic — the error contract default_latency_buckets_ms
    documents, pinned against numpy in tests/test_telemetry.py.
    """

    __slots__ = ("name", "edges", "_lock", "_counts", "_count", "_sum",
                 "_min", "_max")

    def __init__(self, name: str, edges: Optional[Sequence[float]] = None):
        self.name = name
        self.edges = tuple(float(e) for e in
                           (edges if edges is not None
                            else default_latency_buckets_ms()))
        if list(self.edges) != sorted(self.edges) or len(self.edges) < 1:
            raise ValueError(f"histogram {name}: edges must ascend, "
                             f"got {self.edges[:4]}...")
        self._lock = ordered_lock("telemetry.registry.metric")
        self._counts = [0] * (len(self.edges) + 1)  # +1 overflow
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def record(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            return  # a NaN sample would poison sum/quantiles silently
        # binary search for the first edge >= v
        lo, hi = 0, len(self.edges)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.edges[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        with self._lock:
            self._counts[lo] += 1
            self._count += 1
            self._sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else float("nan")

    def quantile(self, q: float) -> float:
        """Interpolated quantile (0 <= q <= 1); NaN on an empty histogram.
        Clamped to the observed [min, max] so a sparse tail bucket can't
        report a value beyond anything actually recorded."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile q must be in [0, 1], got {q}")
        with self._lock:
            if self._count == 0:
                return float("nan")
            target = q * self._count
            cum = 0.0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                if cum + c >= target:
                    lo = self.edges[i - 1] if i > 0 else 0.0
                    hi = self.edges[i] if i < len(self.edges) else self._max
                    frac = (target - cum) / c
                    v = lo + (hi - lo) * frac
                    return min(max(v, self._min), self._max)
                cum += c
            return self._max

    def bucket_counts(self):
        """-> (edges, counts): the raw per-bucket counts, counts[i] holding
        samples <= edges[i] (counts[-1] is the overflow bucket past the last
        edge). Consistent snapshot under the lock — what the Prometheus
        exporter (telemetry/export.py) cumulates into `_bucket{le=...}`."""
        with self._lock:
            return self.edges, tuple(self._counts)

    def percentiles(self) -> Dict[str, float]:
        return {"p50": self.quantile(0.50),
                "p90": self.quantile(0.90),
                "p99": self.quantile(0.99)}

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if self._count == 0:
                return {"count": 0}
            out = {"count": self._count, "sum": self._sum,
                   "mean": self._sum / self._count,
                   "min": self._min, "max": self._max}
        out.update(self.percentiles())
        return out


class MetricsRegistry:
    """Get-or-create registry of named metrics.

    Asking for an existing name with a different type (or a histogram with
    different edges) raises — two subsystems silently sharing a name under
    different semantics is the bug registries exist to prevent.
    """

    def __init__(self):
        self._lock = ordered_lock("telemetry.registry.registry")
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str,
                  edges: Optional[Sequence[float]] = None) -> Histogram:
        h = self._get_or_create(name, Histogram, edges)
        if edges is not None and h.edges != tuple(float(e) for e in edges):
            raise ValueError(
                f"histogram {name!r} already registered with different "
                f"bucket edges")
        return h

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self, prefix: str = "") -> Dict[str, object]:
        """Point-in-time dict of every metric under `prefix`: counters ->
        int, gauges -> float, histograms -> their stat dict. JSON-safe by
        construction — this is what the `metrics.snapshot` event carries."""
        with self._lock:
            items = [(n, m) for n, m in sorted(self._metrics.items())
                     if n.startswith(prefix)]
        out: Dict[str, object] = {}
        for n, m in items:
            if isinstance(m, Counter):
                out[n] = m.value
            elif isinstance(m, Gauge):
                out[n] = m.value
            else:
                out[n] = m.snapshot()
        return out

    def reset(self) -> None:
        """Drop every metric (tests; a long-lived process never calls it)."""
        with self._lock:
            self._metrics.clear()


# THE process-wide registry. Module functions below are the idiomatic call
# sites (`telemetry.counter(...)` via the package re-exports); passing an
# explicit registry is for tests that need isolation.
REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str,
              edges: Optional[Sequence[float]] = None) -> Histogram:
    return REGISTRY.histogram(name, edges)
