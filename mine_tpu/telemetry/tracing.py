"""Request-level tracing: one trace per serve request, spans across threads.

The registry answers "how slow is the p99"; this module answers "WHY was
that one request slow". Every request entering the serve path
(`ServeFleet.submit`, serve_cli's per-image loop, the SLO bench) can start
a trace; the stages it passes through — front-end routing, batcher queue
wait, a sync encode, bucket padding, the jitted render — each record a
child span, and every span lands in the mtpu-ev1 event stream as one

    {"kind": "trace.span", "trace": <id>, "span": <id>, "parent": <id|null>,
     "name": ..., "ms": ..., "t_off_ms": ..., ...fields}

line, so `tools/obs_report.py` (and anything else reading the stream) can
reassemble a request's full latency anatomy offline. The root span's event
is emitted LAST, at `finish()` — a stream containing a trace's root is a
stream containing the whole trace.

Design constraints, same as the rest of the package:
  * HOST-SIDE ONLY and stdlib-only. Starting a trace never touches a jax
    array; the bitwise-parity test in tests/test_serve_trace_e2e.py holds
    rendering identical with tracing on vs off.
  * Cross-THREAD by explicit handoff, not thread-locals: a request's
    TraceContext rides inside the batcher's pending tuple from the
    submitting thread to the flush thread (contrast spans.py, whose
    nesting is deliberately thread-local). TraceContext is therefore
    thread-safe.
  * Sampling is decided ONCE at `start()` (head sampling): an unsampled
    request costs one RNG draw and nothing else — no context object, no
    span records, no events.

Completed traces additionally land in a small in-memory ring buffer
(`recent()`) so the ops endpoint's `/traces/recent` can show live anatomy
without re-reading the event file.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from mine_tpu.analysis.locks import ordered_lock
from mine_tpu.telemetry import events as _events
from mine_tpu.telemetry import registry as _registry

EVENT_KIND = "trace.span"
DEFAULT_RECENT = 256


def _new_id() -> str:
    """64-bit random hex id (os.urandom: unique across processes too, so
    multi-process streams funneled into one file never collide)."""
    return os.urandom(8).hex()


class TraceContext:
    """One in-flight request's trace: a root span plus child spans recorded
    from any thread. Obtain via `tracing.start(...)`; close via
    `tracing.finish(ctx)`. All methods are safe to call concurrently;
    spans recorded after finish are dropped (the trace is sealed)."""

    __slots__ = ("trace_id", "root_id", "name", "fields", "ts",
                 "_t0", "_lock", "spans", "finished", "total_ms", "ok")

    def __init__(self, name: str, **fields):
        self.trace_id = _new_id()
        self.root_id = _new_id()
        self.name = str(name)
        self.fields = dict(fields)
        self.ts = time.time()           # wall clock, for the recent() view
        self._t0 = time.perf_counter()  # monotonic origin for t_off_ms
        self._lock = ordered_lock("telemetry.tracing.ctx")
        self.spans: List[Dict] = []
        self.finished = False
        self.total_ms: Optional[float] = None
        self.ok = True

    def _off_ms(self, t_perf: float) -> float:
        return (t_perf - self._t0) * 1e3

    def add_span(self, name: str, ms: float,
                 t0: Optional[float] = None,
                 parent: Optional[str] = None, **fields) -> Optional[Dict]:
        """Record one already-measured child span. `ms` is the duration;
        `t0` is the span's start as a time.perf_counter() reading (used for
        the trace-relative offset `t_off_ms`; defaults to now - ms).
        `parent` defaults to the root span. Returns the span record (None
        if the trace was already finished)."""
        now = time.perf_counter()
        if t0 is None:
            t0 = now - ms / 1e3
        rec = {"trace": self.trace_id, "span": _new_id(),
               "parent": parent if parent is not None else self.root_id,
               "name": str(name), "ms": round(float(ms), 3),
               # clamp: a span cannot start before its trace (the default
               # now-ms back-dating of a pre-measured duration may land
               # fractionally before the root's origin)
               "t_off_ms": round(max(0.0, self._off_ms(t0)), 3)}
        rec.update(fields)
        with self._lock:
            if self.finished:
                return None
            self.spans.append(rec)
        _events.emit(EVENT_KIND, **rec)
        return rec

    class _Child:
        __slots__ = ("ctx", "name", "parent", "fields", "_t0")

        def __init__(self, ctx, name, parent, fields):
            self.ctx, self.name = ctx, name
            self.parent, self.fields = parent, fields
            self._t0 = 0.0

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, exc_type, exc, tb):
            ms = (time.perf_counter() - self._t0) * 1e3
            if exc_type is not None:
                self.fields.setdefault("ok", False)
            self.ctx.add_span(self.name, ms, t0=self._t0,
                              parent=self.parent, **self.fields)
            return False

    def child(self, name: str, parent: Optional[str] = None, **fields):
        """Context manager measuring a block as a child span:

            with ctx.child("route", owner_shard=o):
                ...
        """
        return TraceContext._Child(self, name, parent, dict(fields))

    def annotate(self, **fields) -> None:
        """Attach fields to the ROOT span (carried on its finish event)."""
        with self._lock:
            self.fields.update(fields)


class _Tracer:
    """Process-wide tracer state: sampling rate + completed-trace ring."""

    def __init__(self):
        self._lock = ordered_lock("telemetry.tracing.tracer")
        self.sample = 0.0
        self._rng = random.Random()
        self._recent: deque = deque(maxlen=DEFAULT_RECENT)

    def configure(self, sample: Optional[float] = None,
                  recent_capacity: Optional[int] = None) -> None:
        with self._lock:
            if sample is not None:
                s = float(sample)
                if not 0.0 <= s <= 1.0:
                    raise ValueError(
                        f"trace sample rate must be in [0, 1], got {s}")
                self.sample = s
            if recent_capacity is not None:
                if recent_capacity < 1:
                    raise ValueError(
                        f"recent_capacity must be >= 1, "
                        f"got {recent_capacity}")
                self._recent = deque(self._recent,
                                     maxlen=int(recent_capacity))

    def start(self, name: str, sample: Optional[float] = None,
              **fields) -> Optional[TraceContext]:
        with self._lock:
            rate = self.sample if sample is None else float(sample)
            if rate <= 0.0:
                return None
            if rate < 1.0 and self._rng.random() >= rate:
                return None
        _registry.counter("serve.trace.sampled").inc()
        return TraceContext(name, **fields)

    def finish(self, ctx: Optional[TraceContext], ok: bool = True,
               **fields) -> None:
        if ctx is None:
            return
        now = time.perf_counter()
        with ctx._lock:
            if ctx.finished:
                return
            ctx.finished = True
            ctx.ok = bool(ok)
            ctx.total_ms = round(ctx._off_ms(now), 3)
            ctx.fields.update(fields)
            root = {"trace": ctx.trace_id, "span": ctx.root_id,
                    "parent": None, "name": ctx.name, "ms": ctx.total_ms,
                    "t_off_ms": 0.0, "ok": ctx.ok}
            root.update(ctx.fields)
            spans = [root] + list(ctx.spans)
        _events.emit(EVENT_KIND, **root)
        _registry.histogram("serve.trace.e2e_ms").record(ctx.total_ms)
        _registry.counter("serve.trace.finished").inc()
        summary = {"trace": ctx.trace_id, "name": ctx.name, "ts": ctx.ts,
                   "ms": ctx.total_ms, "ok": ctx.ok, "spans": spans}
        with self._lock:
            self._recent.append(summary)

    def recent(self, n: Optional[int] = None) -> List[Dict]:
        """Most-recent completed traces, newest first (JSON-safe dicts:
        what /traces/recent serves)."""
        with self._lock:
            out = list(self._recent)
        out.reverse()
        return out if n is None else out[:max(0, int(n))]

    def reset(self) -> None:
        """Tests only: sampling off, ring cleared."""
        with self._lock:
            self.sample = 0.0
            self._recent = deque(maxlen=DEFAULT_RECENT)


_TRACER = _Tracer()


def configure(sample: Optional[float] = None,
              recent_capacity: Optional[int] = None) -> None:
    """Set the process-wide head-sampling rate (0 disables, 1 traces every
    request) and/or the completed-trace ring capacity."""
    _TRACER.configure(sample=sample, recent_capacity=recent_capacity)


def start(name: str, sample: Optional[float] = None,
          **fields) -> Optional[TraceContext]:
    """Begin a trace, or return None when the sampling decision says no —
    every downstream hook (`add_span`, `finish`) accepts/ignores None, so
    call sites never branch. `sample` overrides the configured rate for
    this one decision (the bench and tests pass 1.0)."""
    return _TRACER.start(name, sample=sample, **fields)


def finish(ctx: Optional[TraceContext], ok: bool = True, **fields) -> None:
    """Seal a trace: emits the root trace.span event (parent null), records
    serve.trace.e2e_ms, and files the trace into the recent() ring.
    Idempotent; no-op on None."""
    _TRACER.finish(ctx, ok=ok, **fields)


def recent(n: Optional[int] = None) -> List[Dict]:
    return _TRACER.recent(n)


def reset() -> None:
    _TRACER.reset()
