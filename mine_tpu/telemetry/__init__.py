"""Unified telemetry layer: metrics registry + event stream + span timers.

Every subsystem that used to keep private observability state (the train
loop's hand-formatted step line, serve's cache-attribute stats, the
pipeline's error counters, one-time warnings standing in for counters) now
also reports through this package, so train, serve and chaos paths emit one
coherent, parseable surface:

  registry.py  process-wide counters / gauges / fixed-bucket histograms
               with p50/p90/p99 extraction (README "Observability" has the
               metric catalog)
  events.py    append-only schema-versioned JSONL event sink — non-fatal on
               write failure, validated in CI (tools/validate_events.py),
               consumed by tools/obs_report.py
  spans.py     scoped wall-clock timers feeding both of the above
  tracing.py   request-level traces: per-request span trees carried across
               threads, emitted as trace.span events (serve path anatomy)
  slo.py       rolling-window SLO tracker: sliding p50/p99 vs a
               configurable objective, error-budget burn, breach events
  export.py    Prometheus text exposition of the registry + the opt-in
               HTTP ops endpoint (/metrics /healthz /slo /traces/recent)
  stepline.py  the frozen "time: schema=st1 ..." step-time line + its one
               shared parser
  profiler.py  opt-in jax.profiler trace windows over exact train-loop step
               ranges (telemetry.profile_steps = [start, stop])
  recorder.py  flight recorder: bounded ring buffers of the recent past
               (events/steplines/metric snapshots) that dump atomic
               incident bundles on triggers — rendered by
               tools/postmortem.py, listed at /incidents
  resource.py  opt-in process-vitals sampler thread (RSS, threads, fds,
               GC) publishing into the registry
  hostsync.py  host_readback(reason): declared device->host syncs — the
               transfer-guard sanitizer's allowlist (tools/audit.py)

Dependency-free (stdlib only) and strictly host-side: nothing in here is
ever traced, so instrumentation cannot change jitted numerics or add a
device sync — the bitwise-parity tests in tests/test_telemetry.py and
tests/test_serve_trace_e2e.py hold the package to that.
"""

from mine_tpu.telemetry import recorder, resource, tracing
from mine_tpu.telemetry.events import (KIND_FIELDS, emit, ensure_configured,
                                       validate_file, validate_line)
from mine_tpu.telemetry.export import (OpsServer, parse_prometheus,
                                       render_prometheus)
from mine_tpu.telemetry.hostsync import host_readback, readback_counts
from mine_tpu.telemetry.profiler import ProfileWindow
from mine_tpu.telemetry.recorder import FlightRecorder
from mine_tpu.telemetry.resource import ResourceSampler
from mine_tpu.telemetry.registry import (REGISTRY, Counter, Gauge, Histogram,
                                         MetricsRegistry, counter,
                                         default_latency_buckets_ms, gauge,
                                         histogram, pow2_buckets)
from mine_tpu.telemetry.slo import SLOTracker
from mine_tpu.telemetry.spans import current_span_path, span
from mine_tpu.telemetry.stepline import (STEP_KEYS, STEP_SCHEMA, TIME_KEYS,
                                         format_step_line, parse_line,
                                         parse_lines)
from mine_tpu.telemetry.tracing import TraceContext

__all__ = [
    "FlightRecorder", "KIND_FIELDS", "OpsServer", "REGISTRY", "Counter",
    "Gauge", "Histogram", "MetricsRegistry", "ProfileWindow",
    "ResourceSampler", "SLOTracker", "TraceContext",
    "STEP_KEYS", "STEP_SCHEMA", "TIME_KEYS", "counter", "current_span_path",
    "default_latency_buckets_ms", "emit", "ensure_configured",
    "format_step_line", "gauge", "histogram", "host_readback", "parse_line",
    "parse_lines", "parse_prometheus", "pow2_buckets", "readback_counts",
    "recorder", "render_prometheus", "resource", "span", "tracing",
    "validate_file", "validate_line",
]
