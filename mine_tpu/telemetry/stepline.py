"""The train loop's parseable step-time line: ONE frozen schema, ONE parser.

PR 1 shipped the breakdown as a hand-formatted log fragment
("time: step = 812.0 ms host_wait = 590.1 ms ...") and
tools/step_breakdown.py grew its own regex; PR 4 appended data_errors.
Anything scraping logs was then coupled to printf details three files away.
This module freezes the contract:

  schema "st1" (emitted by train/loop.py since the telemetry PR):

    time: schema=st1 step_ms=812.0 host_wait_ms=590.1 device_ms=221.9 \
h2d_ms=35.2 data_errors=0

  * key=value pairs, space-separated, in exactly STEP_KEYS order
  * the literal "schema=st1" tag directly after the "time:" marker
  * times are milliseconds with one decimal; data_errors is an int
  * new keys may only be APPENDED (parsers must ignore unknown tails);
    any other change bumps the schema tag
  * appended keys so far: the pipeline executor's per-stage breakdown
    (stage_encode_ms ... stage_update_ms, parallel/pipeline.py
    STAGE_MS_KEYS), present only when training.pipeline.enabled — emitted
    via format_step_line's `extra` dict, sorted, after data_errors

parse_line/parse_lines also accept the LEGACY pre-st1 form, so logs from
older runs keep summarizing (pinned by tests/test_step_breakdown.py).
Consumers: tools/step_breakdown.py, tools/obs_report.py — both import THIS
parser; neither carries a private regex anymore.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional

STEP_SCHEMA = "st1"

# time components (ms), in frozen emit order; the keys public consumers
# iterate (tools/step_breakdown.py re-exports this as its KEYS)
TIME_KEYS = ("step", "host_wait", "device", "h2d")
# full frozen key order of the st1 line
STEP_KEYS = ("step_ms", "host_wait_ms", "device_ms", "h2d_ms", "data_errors")

_ST1_RE = re.compile(r"time:\s+schema=(\w+)\s+(.*)")
_KV_RE = re.compile(r"(\w+)=([0-9.+-eE]+)")
_LEGACY_RE = re.compile(
    r"time: step = ([0-9.]+) ms host_wait = ([0-9.]+) ms "
    r"device = ([0-9.]+) ms h2d = ([0-9.]+) ms"
    r"(?: data_errors = ([0-9]+))?")


def format_step_line(times_ms: Dict[str, float], data_errors: int,
                     extra: Optional[Dict[str, float]] = None) -> str:
    """The st1 line (sans indentation). `times_ms` uses the train loop's
    meter keys (step_ms/host_wait_ms/device_ms/h2d_ms). `extra` holds
    APPENDED numeric keys (e.g. the pipeline executor's stage_*_ms
    breakdown), written after data_errors in sorted order — legal under
    the append-only rule, and old parsers ignore them."""
    parts = ["time:", "schema=" + STEP_SCHEMA]
    for k in STEP_KEYS[:-1]:
        parts.append("%s=%.1f" % (k, float(times_ms[k])))
    parts.append("data_errors=%d" % int(data_errors))
    for k in sorted(extra or {}):
        parts.append("%s=%.1f" % (k, float(extra[k])))
    return " ".join(parts)


def parse_line(line: str) -> Optional[Dict[str, float]]:
    """One log line -> {"step": ms, "host_wait": ms, "device": ms,
    "h2d": ms, "data_errors": n} or None (not a step-time line).

    Accepts the st1 schema and the legacy pre-st1 form; unknown st1 keys
    (appended by a future minor revision) are carried through verbatim.
    """
    m = _ST1_RE.search(line)
    if m:
        if m.group(1) != STEP_SCHEMA:
            return None  # an incompatible future schema: skip, don't guess
        kv = dict(_KV_RE.findall(m.group(2)))
        if not all(k in kv for k in STEP_KEYS):
            return None  # torn/truncated line
        out: Dict[str, float] = {}
        for k, v in kv.items():
            key = k[:-3] if k.endswith("_ms") else k
            try:
                out[key] = float(v)
            except ValueError:
                return None
        out["data_errors"] = int(out.get("data_errors", 0))
        return out
    m = _LEGACY_RE.search(line)
    if m:
        out = {k: float(v) for k, v in zip(TIME_KEYS, m.groups()[:4])}
        out["data_errors"] = int(m.group(5) or 0)
        return out
    return None


def parse_lines(lines: Iterable[str]) -> Dict[str, List[float]]:
    """Aggregate many log lines -> {time key: [ms samples...]}. The four
    TIME_KEYS are always present (the tools/step_breakdown.py contract;
    data_errors is per-line via parse_line for consumers that want it);
    appended time keys that actually occur — e.g. the pipeline stage_*
    breakdown — aggregate under their stripped (sans _ms) names too."""
    samples: Dict[str, List[float]] = {k: [] for k in TIME_KEYS}
    for line in lines:
        rec = parse_line(line)
        if rec is None:
            continue
        for k in TIME_KEYS:
            samples[k].append(rec[k])
        for k, v in rec.items():
            if k in TIME_KEYS or k == "data_errors":
                continue
            samples.setdefault(k, []).append(v)
    return samples
