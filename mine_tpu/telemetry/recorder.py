"""Flight recorder: always-on ring buffers + triggered incident bundles.

A production fleet's failure narrative ("p99 breached, admission went to
shed, shard 2 died, then the budget recovered") is spread across the event
stream, the metrics registry, the trace ring and the SLO window — and by
the time a human looks, the moment is gone. The `FlightRecorder` keeps a
bounded, host-side black box of the recent past:

  * the last N events (a tee on `events.emit` — every emitter feeds it,
    sink configured or not),
  * rolling registry snapshots at the caller's cadence (the pre-incident
    baseline postmortems diff against),
  * recent frozen `st1` step lines (train plane),
  * recent completed traces (read from `tracing.recent` at dump time),
  * the config dict + hash and the mtpu-aot1 environment fingerprint.

On a TRIGGER it atomically writes a self-contained incident bundle
directory `incidents/<utc-ts>-<reason>/` (manifest, events tail,
metrics.prom + metrics.json, snapshots, traces, SLO window, registered
state providers, config, environment, step lines), debounced so a breach
storm yields ONE bundle, with keep-last-K retention. Triggers arrive
three ways: watched event kinds through the tee (`serve.slo_breach`,
`serve.shard_dead`, admission escalation to shed, session failed frames,
`train.guard_abort`), the explicit `trigger()` API (chaos soaks, the train
loop's preemption/data-burst hooks), and SIGUSR2. A dump can also arm a
profiler window over the next K steps (`take_profile_request`, consumed
by the train loop) — retroactive-ish profiling of the aftermath.

Overhead discipline: the tee does one deque append + a dict lookup under
its own lock; dumps run on a dedicated worker thread (auto triggers) or
the caller's thread (explicit sync triggers), never inside an emitter's
critical section. Everything is host-side — nothing here touches jax
arrays, so recorder-on vs recorder-off outputs are bitwise identical
(test-pinned). Failure policy matches the event sink: a dump that cannot
write warns once and the run continues.

Lock order (analysis/locks.py): the bundle writer holds `recorder.dump`
(rank 2, below the whole serve plane) across state-provider callbacks
that re-enter fleet/batcher locks; the ring lock (`recorder.ring`, 18)
sits above every lock held at emit time. See LOCK_RANKS for derivation.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import signal
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from mine_tpu.analysis.locks import ordered_condition, ordered_lock
from mine_tpu.telemetry import events as _events
from mine_tpu.telemetry import registry as _registry
from mine_tpu.telemetry import tracing as _tracing

_log = logging.getLogger(__name__)

BUNDLE_SCHEMA = "mtpu-inc1"

# Files every complete bundle carries; tools/postmortem.py refuses a
# bundle missing any of them (append-only: new files may join the set).
BUNDLE_FILES = ("manifest.json", "events.jsonl", "metrics.prom",
                "metrics.json", "snapshots.jsonl", "traces.json",
                "slo.json", "state.json", "config.json", "environment.json",
                "steplines.txt")

# Event kinds the tee auto-triggers on. A predicate (or None = always)
# decides from the payload; edge-triggered sources (SLO breach, admission
# transitions, shard death) already emit once per edge, so the predicate
# never needs its own hysteresis — debounce caps the bundle rate anyway.
TRIGGER_KINDS: Dict[str, Optional[Callable[[Dict], bool]]] = {
    "serve.slo_breach": None,
    "serve.shard_dead": None,
    "train.guard_abort": None,
    "serve.admission": lambda f: f.get("state") == "shed",
    "serve.session_frame": lambda f: f.get("ok") is False,
    # a host leaving the ring (preemption/SIGTERM) is always postmortem-
    # worthy: the bundle captures the drain, the re-covered key range and
    # whatever pressure preceded it
    "serve.host_drain": None,
    # a circuit OPENING means a host ate breaker_threshold consecutive
    # transport failures — a breaker-open storm (several hosts at once)
    # is the fleet-wide network incident; debounce coalesces the storm
    # into one bundle instead of one per edge
    "serve.breaker": lambda f: f.get("state") == "open",
}


def _sanitize(reason: str) -> str:
    out = "".join(c if c.isalnum() or c in "._-" else "_"
                  for c in str(reason))
    return out[:64] or "trigger"


def _config_hash(config: Optional[Dict]) -> Optional[str]:
    if not config:
        return None
    try:
        blob = json.dumps(config, sort_keys=True, default=str)
    except Exception:
        return None
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _environment() -> Dict:
    """mtpu-aot1 environment fingerprint (serve/aot.py). Imported lazily:
    the telemetry package stays jax-free at import time."""
    try:
        from mine_tpu.serve.aot import env_fingerprint
        return env_fingerprint()
    except Exception as e:  # no jax / no devices: record that instead
        return {"schema": "mtpu-aot1", "error": str(e)}


class FlightRecorder:
    """Bounded black-box capture + triggered bundle dumps. Construct, then
    install as the process recorder via module `configure()` (which wires
    the events tee); `close()` joins the worker thread."""

    def __init__(self, out_dir: str, *,
                 events_tail: int = 256,
                 steplines: int = 64,
                 snapshots: int = 16,
                 debounce_s: float = 60.0,
                 keep: int = 5,
                 arm_profile_steps: int = 0,
                 traces_limit: int = 32,
                 config: Optional[Dict] = None):
        self.out_dir = str(out_dir)
        self.debounce_s = float(debounce_s)
        self.keep = max(1, int(keep))
        self.arm_profile_steps = max(0, int(arm_profile_steps))
        self.traces_limit = int(traces_limit)
        self.config = dict(config) if config else None
        self.config_hash = _config_hash(self.config)
        # ring state: everything below the cv's lock (rank 18 — above any
        # lock an emitter holds while the tee fires)
        self._cv = ordered_condition("telemetry.recorder.ring")
        self._events: deque = deque(maxlen=max(1, int(events_tail)))
        self._steplines: deque = deque(maxlen=max(1, int(steplines)))
        self._snapshots: deque = deque(maxlen=max(1, int(snapshots)))
        self._pending: List[tuple] = []  # (reason, trigger_event) queue
        self._last_dump: Optional[float] = None  # monotonic; debounce
        self._profile_request = 0
        self._signal_pending = False  # set by the SIGUSR2 handler, lockless
        self._prev_sigusr2 = None  # (our_handler, displaced_handler)
        self._stop = False
        self.triggers = 0
        self.dumps = 0
        self.suppressed = 0
        self.dump_failures = 0
        # the bundle writer's lock: rank 2, BELOW the serve plane, because
        # a dump calls state providers that re-enter batcher/fleet locks
        self._dump_lock = ordered_lock("telemetry.recorder.dump")
        self._slo = None
        self._providers: List[tuple] = []  # (name, callable) -> state.json
        self._bundle_seq = 0
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="mine-tpu-flight-recorder")
        self._thread.start()

    # ---------------- feeds ----------------

    def observe(self, kind: str, fields: Dict) -> None:
        """The events tee: called from `events.emit` for EVERY event, under
        whatever locks the emitter holds. One append + a trigger-table
        lookup; never dumps inline."""
        event = {"schema": _events.SCHEMA, "ts": time.time(),
                 "kind": str(kind)}
        event.update(fields)
        pred = TRIGGER_KINDS.get(kind, False)
        fire = pred is None or (pred is not False and bool(pred(fields)))
        with self._cv:
            self._events.append(event)
            if fire and self._reserve_locked(force=False):
                self._pending.append((str(kind), event))
                self._cv.notify()

    def observe_event(self, event: Dict) -> None:
        """Preload one already-built mtpu-ev1 event dict (original ts kept)
        into the ring — the offline path chaos_soak uses to bundle a dead
        leg's stream. Never triggers."""
        with self._cv:
            self._events.append(dict(event))

    def observe_stepline(self, line: str) -> None:
        with self._cv:
            self._steplines.append(str(line).strip())

    def snapshot_metrics(self, scope: str = "") -> None:
        """Append one rolling registry snapshot (call at log cadence): the
        pre-incident baseline `tools/postmortem.py` diffs metric values
        against."""
        snap = {"ts": time.time(), "scope": scope,
                "metrics": _registry.REGISTRY.snapshot()}
        with self._cv:
            self._snapshots.append(snap)

    def set_slo(self, slo) -> None:
        """Wire an SLOTracker; its snapshot() becomes the bundle's
        slo.json."""
        self._slo = slo

    def add_state_provider(self, name: str, fn: Callable[[], Dict]) -> None:
        """Register a `() -> dict` captured into state.json at dump time
        (fleet stats, health, train ops state). Called with NO recorder
        ring lock held, so providers may take serve-plane locks."""
        self._providers.append((str(name), fn))

    # ---------------- triggers ----------------

    def _reserve_locked(self, force: bool) -> bool:
        """Debounce/rate-limit decision; caller holds the ring lock. The
        slot is reserved at REQUEST time, so a storm of triggers inside one
        debounce window collapses to the single bundle already reserved."""
        self.triggers += 1
        now = time.monotonic()
        if not force:
            if self._pending:
                self.suppressed += 1
                return False
            if (self._last_dump is not None
                    and now - self._last_dump < self.debounce_s):
                self.suppressed += 1
                return False
        self._last_dump = now
        return True

    def trigger(self, reason: str, *, force: bool = False,
                sync: bool = True, **context) -> Optional[str]:
        """Explicit trigger (API / soaks / train hooks). `sync=True` writes
        the bundle on the calling thread and returns its path (None when
        debounced); `sync=False` enqueues to the worker. `force` bypasses
        the debounce (operator-initiated captures always land)."""
        event = {"reason": str(reason)}
        event.update(context)
        with self._cv:
            if not self._reserve_locked(force):
                return None
            if not sync:
                self._pending.append((str(reason), event))
                self._cv.notify()
                return None
        return self._dump(str(reason), event)

    def install_sigusr2(self) -> bool:
        """Arm `kill -USR2 <pid>` -> bundle. Best-effort: signal handlers
        install only on the main thread (False when that fails). The
        handler just sets a flag — it must not take locks the interrupted
        frame might hold — and the worker services it within its poll."""
        def _handler(signum, frame):
            self._signal_pending = True
        try:
            old = signal.signal(signal.SIGUSR2, _handler)
            # remember the displaced handler so close() can restore it —
            # the signal table is process-global and would otherwise pin
            # this recorder (and every state-provider closure behind it)
            # for the life of the process
            self._prev_sigusr2 = (_handler, old)
            return True
        except (ValueError, OSError):  # non-main thread / no signals here
            return False

    def take_profile_request(self) -> int:
        """Consume a pending profiler-arming request: the number of steps
        to profile (0 = none). The train loop polls this each step and
        opens a ProfileWindow over [next, next+K-1]."""
        with self._cv:
            k, self._profile_request = self._profile_request, 0
            return k

    # ---------------- dump ----------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not (self._pending or self._stop
                           or self._signal_pending):
                    self._cv.wait(timeout=0.5)
                job = self._pending.pop(0) if self._pending else None
                sig, self._signal_pending = self._signal_pending, False
                if sig and job is None:
                    # operator signal: force past the debounce
                    self._reserve_locked(force=True)
                    job = ("sigusr2", {"reason": "sigusr2"})
                if job is None and self._stop:
                    return
            if job is not None:
                self._dump(*job)

    def _dump(self, reason: str, trigger_event: Optional[Dict]) -> \
            Optional[str]:
        try:
            return self._dump_inner(reason, trigger_event)
        except Exception:
            with self._cv:
                self.dump_failures += 1
            _log.warning("flight recorder: bundle dump failed (%s) — "
                         "continuing", reason, exc_info=True)
            return None

    def _dump_inner(self, reason: str,
                    trigger_event: Optional[Dict]) -> str:
        with self._dump_lock:
            with self._cv:  # copy the rings; release before any callout
                events_tail = list(self._events)
                steplines = list(self._steplines)
                snapshots = list(self._snapshots)
                self._bundle_seq += 1
                seq = self._bundle_seq
            state: Dict[str, Dict] = {}
            for name, fn in self._providers:
                try:
                    state[name] = fn()
                except Exception as e:  # a dead provider can't kill a dump
                    state[name] = {"error": str(e)}
            slo = {}
            if self._slo is not None:
                try:
                    slo = self._slo.snapshot()
                except Exception as e:
                    slo = {"error": str(e)}
            traces = _tracing.recent(self.traces_limit)
            metrics = _registry.REGISTRY.snapshot()
            from mine_tpu.telemetry.export import render_prometheus
            prom = render_prometheus()
            ts = time.time()
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(ts))
            name = f"{stamp}-{_sanitize(reason)}"
            manifest = {
                "schema": BUNDLE_SCHEMA, "reason": str(reason), "ts": ts,
                "bundle": name, "trigger": trigger_event,
                "config_hash": self.config_hash,
                "counts": {"events": len(events_tail),
                           "snapshots": len(snapshots),
                           "steplines": len(steplines),
                           "traces": len(traces)},
                "recorder": {"events_tail": self._events.maxlen,
                             "debounce_s": self.debounce_s,
                             "keep": self.keep, "seq": seq},
            }
            os.makedirs(self.out_dir, exist_ok=True)
            # stage in a tmp dir, then one atomic rename: readers (the
            # /incidents route, postmortem) never see a half-written bundle
            tmp = tempfile.mkdtemp(dir=self.out_dir, prefix=".tmp-")
            try:
                self._write_files(tmp, manifest, events_tail, steplines,
                                  snapshots, traces, slo, state, metrics,
                                  prom)
                final = os.path.join(self.out_dir, name)
                n = 2
                while os.path.exists(final):  # same-second re-trigger
                    final = os.path.join(self.out_dir, f"{name}-{n}")
                    n += 1
                os.replace(tmp, final)
            except Exception:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            self._prune()
            with self._cv:
                self.dumps += 1
                if self.arm_profile_steps:
                    self._profile_request = self.arm_profile_steps
        # outside the dump lock: the emit re-enters the tee (ring rank 18)
        # and obs.incident is not a watched kind, so no re-trigger loop
        _events.emit("obs.incident", reason=str(reason), bundle=final,
                     events=len(events_tail), config_hash=self.config_hash)
        _log.warning("flight recorder: incident bundle written: %s (%s)",
                     final, reason)
        return final

    def _write_files(self, d, manifest, events_tail, steplines, snapshots,
                     traces, slo, state, metrics, prom) -> None:
        def jdump(fname, obj):
            with open(os.path.join(d, fname), "w") as f:
                json.dump(obj, f, indent=2, sort_keys=True, default=str)
                f.write("\n")
        jdump("manifest.json", manifest)
        jdump("traces.json", {"traces": traces})
        jdump("slo.json", slo)
        jdump("state.json", state)
        jdump("metrics.json", metrics)
        jdump("config.json", {"config_hash": self.config_hash,
                              "config": self.config})
        jdump("environment.json", _environment())
        with open(os.path.join(d, "events.jsonl"), "w") as f:
            for e in events_tail:
                f.write(json.dumps(e, default=_events._jsonify) + "\n")
        with open(os.path.join(d, "snapshots.jsonl"), "w") as f:
            for s in snapshots:
                f.write(json.dumps(s, default=_events._jsonify) + "\n")
        with open(os.path.join(d, "metrics.prom"), "w") as f:
            f.write(prom)
        with open(os.path.join(d, "steplines.txt"), "w") as f:
            f.write("\n".join(steplines) + ("\n" if steplines else ""))

    def _prune(self) -> None:
        """Keep-last-K retention over completed bundle dirs (lexicographic
        = chronological: names lead with the UTC stamp)."""
        try:
            names = sorted(n for n in os.listdir(self.out_dir)
                           if not n.startswith(".tmp-")
                           and os.path.isdir(os.path.join(self.out_dir, n)))
        except OSError:
            return
        for n in names[:max(0, len(names) - self.keep)]:
            shutil.rmtree(os.path.join(self.out_dir, n),
                          ignore_errors=True)

    # ---------------- introspection ----------------

    def list_incidents(self) -> Dict:
        """/incidents body: bundles newest-first with their manifests'
        headline fields, plus recorder counters."""
        bundles = []
        try:
            names = sorted((n for n in os.listdir(self.out_dir)
                            if not n.startswith(".tmp-")
                            and os.path.isdir(
                                os.path.join(self.out_dir, n))),
                           reverse=True)
        except OSError:
            names = []
        for n in names:
            entry = {"bundle": n,
                     "path": os.path.join(self.out_dir, n)}
            try:
                with open(os.path.join(self.out_dir, n,
                                       "manifest.json")) as f:
                    man = json.load(f)
                entry.update(reason=man.get("reason"), ts=man.get("ts"),
                             counts=man.get("counts"))
            except Exception as e:
                entry["error"] = str(e)
            bundles.append(entry)
        with self._cv:
            counters = {"triggers": self.triggers, "dumps": self.dumps,
                        "suppressed": self.suppressed,
                        "dump_failures": self.dump_failures}
        return {"dir": self.out_dir, "incidents": bundles,
                "recorder": counters}

    def close(self) -> None:
        if self._prev_sigusr2 is not None:
            ours, displaced = self._prev_sigusr2
            self._prev_sigusr2 = None
            try:
                # only restore if the table still points at OUR handler —
                # someone re-arming SIGUSR2 after us keeps their handler
                if signal.getsignal(signal.SIGUSR2) is ours:
                    signal.signal(signal.SIGUSR2, displaced)
            except (ValueError, OSError):  # non-main thread: leave it
                pass
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=10)


# ------------------------------------------------------------- module state

# swap-only under the state lock (rank 3); close() of a replaced recorder
# runs OUTSIDE it, so the lock never nests into the worker join
_state_lock = ordered_lock("telemetry.recorder.state")
_recorder: Optional[FlightRecorder] = None


def configure(out_dir: str, **kwargs) -> FlightRecorder:
    """Install a process-wide FlightRecorder dumping into `out_dir` and
    wire the events tee to it. Replaces (and closes) any existing one."""
    global _recorder
    new = FlightRecorder(out_dir, **kwargs)
    with _state_lock:
        old, _recorder = _recorder, new
    _events.set_tee(new.observe)
    if old is not None:
        old.close()
    return new


def current_recorder() -> Optional[FlightRecorder]:
    with _state_lock:
        return _recorder


def maybe_trigger(reason: str, **context) -> None:
    """Fire-and-forget trigger for instrumented call sites (train loop's
    preemption/data-burst hooks): no-op without a configured recorder,
    async so any caller lock context is safe."""
    rec = current_recorder()
    if rec is not None:
        rec.trigger(reason, sync=False, **context)


def record_stepline(line: str) -> None:
    rec = current_recorder()
    if rec is not None:
        rec.observe_stepline(line)


def release(rec: Optional[FlightRecorder]) -> None:
    """Owner teardown: reset the module state if `rec` is still the
    installed recorder, else just close it (a later configure() won)."""
    global _recorder
    if rec is None:
        return
    with _state_lock:
        if _recorder is rec:
            _recorder = None
            current = True
        else:
            current = False
    if current:
        _events.set_tee(None)
    rec.close()


def reset() -> None:
    """Tests only: drop the recorder and the events tee."""
    global _recorder
    with _state_lock:
        old, _recorder = _recorder, None
    _events.set_tee(None)
    if old is not None:
        old.close()
