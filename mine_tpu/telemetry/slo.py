"""Rolling-window SLO tracker for the serving path.

The registry's histograms are cumulative-forever — right for "how has this
process done since boot", wrong for "are we in breach RIGHT NOW". This
tracker keeps the last `window_s` seconds of request latencies in a
bounded deque, computes exact sliding-window p50/p99 (exact order
statistics over <= `max_samples` floats, not bucket-interpolated — a
breach decision should not carry bucket-width error), and compares the
rolling p99 against a configurable objective:

  * gauges `serve.slo.p50_ms` / `serve.slo.p99_ms` / `serve.slo.window_n`
    and `serve.slo.error_budget_burn` mirror the window into the registry
    (so /metrics and metrics.snapshot carry them);
  * crossing INTO breach emits one `serve.slo_breach` event (edge-
    triggered: one event per excursion, not one per request while bad);
  * `snapshot()` returns the JSON the ops endpoint's `/slo` route serves,
    including per-bucket percentiles (bucket = the dispatch batch's pow2
    size, so tail latency reads per compiled shape) and per-tier
    percentiles (tier = the request's admission priority class, so the
    queue-flood tests can prove high-tier latency held while low tiers
    shed — serve/admission.py).

Error-budget burn is the standard SRE ratio: with target 0.99, the budget
is 1% of requests over objective; burn = (observed bad fraction) /
(1 - target). burn > 1 means the window is eating budget faster than
allowed.

Host-side, stdlib-only, thread-safe (the batcher's flush thread records
while the ops endpoint snapshots). `now` is injectable for tests.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from mine_tpu.analysis.locks import ordered_lock
from mine_tpu.telemetry import events as _events
from mine_tpu.telemetry import registry as _registry

# below this many samples in the window, p99 is noise — never declare a
# breach on it (a single slow warmup request must not page anyone)
MIN_BREACH_SAMPLES = 20


def _pct(sorted_vals, q: float) -> float:
    """Exact order statistic (nearest-rank with linear interpolation)."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    i = int(pos)
    frac = pos - i
    if i + 1 >= len(sorted_vals):
        return sorted_vals[-1]
    return sorted_vals[i] + (sorted_vals[i + 1] - sorted_vals[i]) * frac


class SLOTracker:
    """See module docstring. `objective_ms=0` disables breach detection
    (the tracker still serves rolling percentiles)."""

    def __init__(self, objective_ms: float = 0.0, target: float = 0.99,
                 window_s: float = 60.0, max_samples: int = 8192,
                 metric_prefix: str = "serve.slo"):
        if not 0.0 < target < 1.0:
            raise ValueError(f"slo target must be in (0, 1), got {target}")
        if window_s <= 0:
            raise ValueError(f"slo window_s must be > 0, got {window_s}")
        if objective_ms < 0:
            raise ValueError(
                f"slo objective_ms must be >= 0, got {objective_ms}")
        self.objective_ms = float(objective_ms)
        self.target = float(target)
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self.metric_prefix = metric_prefix
        self._lock = ordered_lock("telemetry.slo")
        # (t_monotonic, latency_ms, bucket, tier) — bounded twice: by age
        # (window_s, pruned on every record/snapshot) and by count
        # (max_samples, the deque's maxlen)
        self._samples: deque = deque(maxlen=self.max_samples)
        self._breaching = False
        self.breaches = 0
        self.recorded = 0
        # cached burn from the last record()/snapshot(): read LOCK-FREE by
        # the admission controller's pressure score (serve/admission.py) —
        # a shed decision must never contend with the window's lock
        self._last_burn = 0.0

    # ---------------- internals (callers hold self._lock) ----------------

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def _window_stats(self) -> Dict:
        vals = sorted(s[1] for s in self._samples)
        n = len(vals)
        bad = sum(1 for s in self._samples
                  if self.objective_ms and s[1] > self.objective_ms)
        burn = 0.0
        if self.objective_ms and n:
            burn = (bad / n) / (1.0 - self.target)
        return {"n": n, "p50_ms": _pct(vals, 0.50),
                "p99_ms": _pct(vals, 0.99), "bad": bad, "burn": burn}

    # ---------------- recording ----------------

    def record(self, latency_ms: float, bucket: Optional[int] = None,
               now: Optional[float] = None,
               tier: Optional[int] = None) -> None:
        """Record one request's end-to-end latency. `bucket` tags the
        dispatch batch's pow2 size, `tier` the request's priority class
        (per-shape and per-tier tails in snapshot())."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            self._samples.append((now, float(latency_ms), bucket, tier))
            self.recorded += 1
            self._prune(now)
            st = self._window_stats()
            self._last_burn = st["burn"]
            breach_edge = False
            if (self.objective_ms and st["n"] >= MIN_BREACH_SAMPLES
                    and st["p99_ms"] > self.objective_ms):
                if not self._breaching:
                    self._breaching = True
                    self.breaches += 1
                    breach_edge = True
            elif self._breaching and (not self.objective_ms
                                      or st["p99_ms"] <= self.objective_ms):
                self._breaching = False
        pre = self.metric_prefix
        _registry.gauge(pre + ".p50_ms").set(st["p50_ms"])
        _registry.gauge(pre + ".p99_ms").set(st["p99_ms"])
        _registry.gauge(pre + ".window_n").set(st["n"])
        _registry.gauge(pre + ".error_budget_burn").set(st["burn"])
        if breach_edge:
            _events.emit("serve.slo_breach",
                         p99_ms=round(st["p99_ms"], 3),
                         objective_ms=self.objective_ms,
                         window_s=self.window_s, window_n=st["n"],
                         target=self.target,
                         error_budget_burn=round(st["burn"], 4))

    @property
    def breaching(self) -> bool:
        with self._lock:
            return self._breaching

    @property
    def burn(self) -> float:
        """Error-budget burn as of the last record()/snapshot() — a plain
        cached float, read WITHOUT the lock (atomic in CPython) so the
        admission controller's per-request pressure score costs nothing."""
        return self._last_burn

    # ---------------- reporting ----------------

    def snapshot(self, now: Optional[float] = None) -> Dict:
        """JSON-safe rolling-window view (what /slo serves): overall +
        per-bucket percentiles, objective, breach state, budget burn."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            self._prune(now)
            st = self._window_stats()
            self._last_burn = st["burn"]
            per_bucket: Dict = {}
            per_tier: Dict = {}
            for _, ms, bucket, tier in self._samples:
                per_bucket.setdefault(bucket, []).append(ms)
                if tier is not None:
                    per_tier.setdefault(tier, []).append(ms)
            def _pct_table(groups):
                table = {}
                for key in sorted(groups, key=lambda k: (k is None, k)):
                    vals = sorted(groups[key])
                    table[str(key)] = {
                        "n": len(vals),
                        "p50_ms": round(_pct(vals, 0.50), 3),
                        "p99_ms": round(_pct(vals, 0.99), 3)}
                return table
            buckets = _pct_table(per_bucket)
            tiers = _pct_table(per_tier)
            breaching = self._breaching
            breaches = self.breaches
            recorded = self.recorded
        out = {"objective_ms": self.objective_ms, "target": self.target,
               "window_s": self.window_s, "window_n": st["n"],
               "recorded": recorded, "breaching": breaching,
               "breaches": breaches,
               "error_budget_burn": round(st["burn"], 4),
               "buckets": buckets, "tiers": tiers}
        for k in ("p50_ms", "p99_ms"):
            v = st[k]
            out[k] = round(v, 3) if v == v else None  # NaN -> null (JSON)
        return out
