"""Live ops plane: Prometheus text exposition + stdlib HTTP ops endpoint.

`render_prometheus` serializes the metrics registry into the Prometheus
text exposition format (version 0.0.4) — counters as `<name>_total`,
gauges verbatim, histograms as the standard cumulative
`_bucket{le="..."}` / `_sum` / `_count` family — so any off-the-shelf
scraper can consume the PR-6 registry without this repo growing a client
dependency. `parse_prometheus` is the matching minimal parser the tests
round-trip through (it validates the grammar we emit, not the full spec).

`OpsServer` is the opt-in endpoint behind `serve.ops_port`
(`ThreadingHTTPServer` on a daemon thread, loopback by default):

    /metrics        Prometheus text from the registry
    /healthz        200 JSON liveness: {"status": "ok"} or, with a `health`
                    callable wired (the fleet's — serve/fleet.py), that
                    callable's dict — `status` flips to "degraded" (STILL
                    HTTP 200: the process is up and serving; "degraded" is
                    a body-level signal for dashboards, not a probe
                    failure) when the error budget burns > 1x or a cache
                    shard is marked dead
    /slo            rolling-window SLO snapshot (telemetry/slo.py), JSON
    /traces/recent  last completed traces (telemetry/tracing.py), JSON
    /progress       with a `progress` callable wired (the train loop's —
                    train/loop.py behind `training.ops_port`), that
                    callable's dict: step/epoch position plus an ETA
                    derived from the recent st1 step-time history; 404
                    when no callable is wired
    /incidents      with an `incidents` callable wired (the flight
                    recorder's list_incidents — telemetry/recorder.py),
                    the captured incident bundles newest-first plus the
                    recorder's trigger/dump/suppression counters; 404
                    when no recorder is configured

Port 0 binds an ephemeral port (tests read `.port`). Everything here is
host-side and stdlib-only; request handling never touches jax state — the
handlers only READ registry/tracker/ring snapshots, each of which takes
its own internal locks.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Dict, Optional

from mine_tpu.telemetry import registry as _registry
from mine_tpu.telemetry import tracing as _tracing

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")
# one sample line: name{labels} value   (labels optional; value a float
# literal, inf/nan included). This is the grammar render_prometheus emits.
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{([a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*)\})?'
    r' (-?(?:[0-9.e+-]+|[+-]?Inf|NaN))$')


def prom_name(name: str, prefix: str = "mtpu_") -> str:
    """Dotted registry path -> Prometheus metric name: `serve.cache.hits`
    -> `mtpu_serve_cache_hits`."""
    return prefix + _NAME_SANITIZE.sub("_", name)


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    # integral values print without the trailing .0 (Prometheus accepts
    # either; the compact form diffs cleanly in tests)
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(
        registry: Optional[_registry.MetricsRegistry] = None) -> str:
    """Serialize every registered metric; deterministic order (registry
    names are sorted). Ends with a newline per the format spec."""
    reg = registry if registry is not None else _registry.REGISTRY
    lines = []
    for name in reg.names():
        m = reg.get(name)
        if m is None:  # racing a reset(): skip, never crash a scrape
            continue
        pn = prom_name(name)
        if isinstance(m, _registry.Counter):
            lines.append(f"# TYPE {pn}_total counter")
            lines.append(f"{pn}_total {_fmt(m.value)}")
        elif isinstance(m, _registry.Gauge):
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {_fmt(m.value)}")
        elif isinstance(m, _registry.Histogram):
            edges, counts = m.bucket_counts()
            lines.append(f"# TYPE {pn} histogram")
            cum = 0
            for edge, c in zip(edges, counts):
                cum += c
                lines.append(f'{pn}_bucket{{le="{_fmt(edge)}"}} {cum}')
            cum += counts[-1]  # overflow bucket
            lines.append(f'{pn}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{pn}_sum {_fmt(m.sum)}")
            lines.append(f"{pn}_count {cum}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> Dict[str, float]:
    """Parse text exposition into {'name' or 'name{labels}': value};
    raises ValueError on any malformed line. Validates what we emit: the
    tests' proof that /metrics output is scrapable."""
    out: Dict[str, float] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        mt = _SAMPLE_RE.match(line)
        if mt is None:
            raise ValueError(f"line {i}: not a metric sample: {line!r}")
        name, labels, value = mt.groups()
        key = f"{name}{{{labels}}}" if labels else name
        if key in out:
            raise ValueError(f"line {i}: duplicate sample {key!r}")
        out[key] = float(value.replace("Inf", "inf").replace("NaN", "nan"))
    return out


class OpsServer:
    """Opt-in HTTP ops endpoint; see module docstring. Construct bound
    (but not serving), then `.start()`; `.close()` shuts down and joins."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[_registry.MetricsRegistry] = None,
                 slo=None, traces_limit: int = 32, health=None,
                 progress=None, incidents=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        ops = self
        self.registry = registry if registry is not None \
            else _registry.REGISTRY
        self.slo = slo
        self.traces_limit = int(traces_limit)
        # optional () -> dict with at least a "status" key; None = bare
        # liveness (the process answering IS the health signal)
        self.health = health
        # optional () -> dict for /progress (step/epoch/ETA); None = 404
        self.progress = progress
        # optional () -> dict for /incidents (the flight recorder's
        # bundle listing); None = 404
        self.incidents = incidents

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib handler API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        body = ops.health() if ops.health is not None \
                            else {"status": "ok"}
                        self._send(200, (json.dumps(body) + "\n").encode())
                    elif path == "/metrics":
                        body = render_prometheus(ops.registry)
                        self._send(200, body.encode(), CONTENT_TYPE)
                    elif path == "/slo":
                        snap = ops.slo.snapshot() if ops.slo is not None \
                            else {}
                        self._send(200, (json.dumps(snap) + "\n").encode())
                    elif path == "/traces/recent":
                        traces = _tracing.recent(ops.traces_limit)
                        body = json.dumps({"traces": traces}) + "\n"
                        self._send(200, body.encode())
                    elif path == "/progress" and ops.progress is not None:
                        body = json.dumps(ops.progress()) + "\n"
                        self._send(200, body.encode())
                    elif path == "/incidents" and ops.incidents is not None:
                        body = json.dumps(ops.incidents()) + "\n"
                        self._send(200, body.encode())
                    else:
                        self._send(404, b'{"error": "not found"}\n')
                except BrokenPipeError:  # client went away mid-response
                    pass

            def log_message(self, fmt, *args):  # silence per-request noise
                pass

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "OpsServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mine-tpu-ops-server")
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
