"""Opt-in process resource gauges: a sampler thread for the registry.

A wedged fleet usually telegraphs itself in process vitals long before a
request fails — RSS creep (cache leak), thread-count creep (unjoined
workers), fd exhaustion (socket leak in the ops plane). This module
publishes those into the shared metrics registry at a fixed cadence so
`/metrics`, incident bundles and obs_report all see them:

    process.rss_bytes        resident set size (/proc/self/statm; falls
                             back to ru_maxrss peak where /proc is absent)
    process.threads          live python threads (threading.active_count)
    process.open_fds         open descriptors (/proc/self/fd; absent -> -1)
    process.gc_collections   cumulative gc runs across generations
    process.gc_pending       objects tracked since the last collection

Default OFF (`telemetry.resource_sample_s: 0`); stdlib-only, host-side,
and never touches jax — bitwise parity of instrumented runs is unchanged.
The thread name is registered in analysis.locks.OWNED_THREAD_NAMES, so
the conftest thread-leak tripwire (and the concurrency audit pass) fail
any owner that forgets `close()`.
"""

from __future__ import annotations

import gc
import os
import threading
from typing import Optional

from mine_tpu.telemetry import registry as _registry

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_bytes() -> Optional[float]:
    try:
        with open("/proc/self/statm") as f:
            return float(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, IndexError, ValueError):
        try:
            import resource
            return float(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024.0
        except Exception:
            return None


def open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def sample_once(registry: Optional[_registry.MetricsRegistry] = None) -> None:
    """One gauge sweep (the sampler's body; also callable directly from
    tests or a log-cadence hook)."""
    reg = registry if registry is not None else _registry.REGISTRY
    rss = rss_bytes()
    if rss is not None:
        reg.gauge("process.rss_bytes").set(rss)
    reg.gauge("process.threads").set(float(threading.active_count()))
    reg.gauge("process.open_fds").set(float(open_fds()))
    stats = gc.get_stats()
    reg.gauge("process.gc_collections").set(
        float(sum(s.get("collections", 0) for s in stats)))
    reg.gauge("process.gc_pending").set(float(sum(gc.get_count())))


class ResourceSampler:
    """Daemon sampler thread; construct started, `close()` joins. A
    non-positive interval constructs a no-op (nothing to close-but-safe),
    mirroring the ProfileWindow degrade pattern."""

    def __init__(self, interval_s: float,
                 registry: Optional[_registry.MetricsRegistry] = None):
        self.interval_s = float(interval_s)
        self._registry = registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="mine-tpu-resource-sampler")
            self._thread.start()

    @property
    def active(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                sample_once(self._registry)
            except Exception:  # a vitals read must never kill the run
                pass
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
