"""Opt-in jax.profiler trace windows over exact train-loop step ranges.

`telemetry.profile_steps: [start, stop]` brackets global steps start..stop
INCLUSIVE: the trace starts before step `start` runs and stops after step
`stop` completes, so the captured window is exactly the requested steps —
no warmup compiles, no eval/checkpoint pauses unless they fall inside the
range. The trace directory lands in the event stream ("profile.window"), so
obs_report can point at it next to the step-time record of the same steps.

Failure policy matches the rest of the telemetry layer: a profiler that
cannot start (unwritable dir, unsupported backend) warns once and the
window degrades to a no-op — profiling must never kill the run it profiles.

bench.py's MINE_TPU_BENCH_PROFILE env knob keeps its own whole-variant
trace; this module is the finer train-loop instrument the ROADMAP's chip
windows want (bracket the 3 steps after a cadence boundary, not the sweep).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from mine_tpu.telemetry import events as _events

_log = logging.getLogger(__name__)


class ProfileWindow:
    """Drive jax.profiler.start_trace/stop_trace from step-counter edges.

    Call `maybe_start(next_step)` immediately before dispatching a step and
    `maybe_stop(completed_step)` after it; both are cheap int compares when
    the window is disabled, done, or out of range. A resume that lands past
    `start` (mid-window restore) skips the window entirely rather than
    capturing a partial, misleading range.
    """

    def __init__(self, steps: Sequence[int], trace_dir: str,
                 logger: Optional[logging.Logger] = None):
        steps = tuple(int(s) for s in (steps or ()))
        if steps and (len(steps) != 2 or steps[0] < 1
                      or steps[1] < steps[0]):
            raise ValueError(
                "telemetry.profile_steps must be [start, stop] with "
                f"1 <= start <= stop, got {list(steps)}")
        self.start_step = steps[0] if steps else 0
        self.stop_step = steps[1] if steps else 0
        self.trace_dir = trace_dir
        self.active = False
        self.done = not steps
        self._logger = logger or _log

    @property
    def enabled(self) -> bool:
        return not self.done or self.active

    def maybe_start(self, next_step: int) -> None:
        if self.done or self.active:
            return
        if next_step > self.start_step:
            # resumed past the window: a partial trace would misreport the
            # steps it claims to cover — skip, say so, move on
            self.done = True
            self._logger.warning(
                "telemetry.profile_steps [%d, %d] skipped: run resumed at "
                "step %d, past the window start",
                self.start_step, self.stop_step, next_step)
            return
        if next_step == self.start_step:
            try:
                import jax
                jax.profiler.start_trace(self.trace_dir)
                self.active = True
                self._logger.info(
                    "profiler trace started at step %d (stops after %d): %s",
                    self.start_step, self.stop_step, self.trace_dir)
            except Exception:
                self.done = True
                self._logger.warning(
                    "jax.profiler.start_trace(%s) failed — profile window "
                    "disabled", self.trace_dir, exc_info=True)

    def maybe_stop(self, completed_step: int) -> None:
        if not self.active or completed_step < self.stop_step:
            return
        self.stop()

    def stop(self) -> None:
        """Stop an active trace (also the end-of-run safety net for a
        window whose stop step was never reached)."""
        if not self.active:
            return
        self.active = False
        self.done = True
        try:
            import jax
            jax.profiler.stop_trace()
            self._logger.info("profiler trace written: %s", self.trace_dir)
            _events.emit("profile.window", trace_dir=self.trace_dir,
                         start_step=self.start_step,
                         stop_step=self.stop_step)
        except Exception:
            self._logger.warning("jax.profiler.stop_trace failed",
                                 exc_info=True)
