"""Declared host readbacks: the transfer-guard allowlist.

The host-sync sanitizer (mine_tpu/analysis/passes.py) runs hot paths under
`jax.transfer_guard("disallow")`, which rejects every IMPLICIT device
transfer. Some readbacks are intentional — the train loop's log-cadence
`metrics_to_float`, the guard monitor's abort-policy scalars, eval metric
gathers, the serve engine's output fetch — and those call sites declare it:

    with host_readback("train.log_metrics"):
        m = metrics_to_float(metrics)

The declaration does three things: (1) opens a `jax.transfer_guard("allow")`
scope so the sanitizer passes by DECLARATION rather than by path-string
exemption; (2) counts the readback per reason (`readback_counts()`), so a
hot loop syncing more often than its cadence promises is visible; (3) marks
the site for a reader — the string is the documentation.

Host-side and lock-free on the hot path apart from one dict update under a
plain lock; jax is imported lazily so importing telemetry stays stdlib-only
(the package contract).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict

_lock = threading.Lock()
_counts: Dict[str, int] = {}


@contextlib.contextmanager
def host_readback(reason: str):
    """Declare an intentional device->host (or host->device) sync. Use the
    dotted-path naming convention of the metrics registry for `reason`."""
    reason = str(reason)
    with _lock:
        _counts[reason] = _counts.get(reason, 0) + 1
    import jax  # lazy: telemetry imports must stay stdlib-only
    with jax.transfer_guard("allow"):
        yield


def readback_counts() -> Dict[str, int]:
    """Per-reason counts of declared readbacks since process start (or the
    last `reset`)."""
    with _lock:
        return dict(_counts)


def reset() -> None:
    """Tests only."""
    with _lock:
        _counts.clear()
