"""Scoped wall-clock span timers over the metrics registry + event sink.

    with telemetry.span("ckpt.save_latest", step=1234):
        ...

records the block's wall-clock into the histogram named after the span's
DOTTED PATH — nested spans compose their names, so a span "restore" opened
inside "ckpt" shows up as "ckpt.restore" — and (when a sink is configured)
emits one {"kind": "span", "name": ..., "ms": ...} event carrying any extra
fields. Exceptions propagate untouched; the duration still records with
ok=false so a failing save's cost is visible, not lost.

Nesting is thread-local: concurrent threads (batcher flush vs train loop)
each have their own stack, so paths never interleave across threads.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from mine_tpu.telemetry import events as _events
from mine_tpu.telemetry import registry as _registry

_tls = threading.local()


def _stack():
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_span_path() -> Optional[str]:
    """Dotted path of the innermost open span on this thread, or None."""
    s = _stack()
    return ".".join(s) if s else None


class span:
    """Context manager; see module docstring. `emit=False` keeps a
    high-frequency span out of the event stream (histogram only)."""

    def __init__(self, name: str, emit: bool = True,
                 registry: Optional[_registry.MetricsRegistry] = None,
                 **fields):
        if not name:
            raise ValueError("span needs a non-empty name")
        self.name = str(name)
        self.emit_event = emit
        self.registry = registry if registry is not None \
            else _registry.REGISTRY
        self.fields = fields
        self.path: Optional[str] = None
        self.ms: Optional[float] = None
        self._t0 = 0.0

    def __enter__(self) -> "span":
        stack = _stack()
        stack.append(self.name)
        self.path = ".".join(stack)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.ms = (time.perf_counter() - self._t0) * 1e3
        stack = _stack()
        # unwind to OUR frame even if an inner span leaked (an inner
        # __exit__ that never ran because its thread died): the stack must
        # not corrupt every later span on this thread
        while stack and stack[-1] != self.name:
            stack.pop()
        if stack:
            stack.pop()
        try:
            self.registry.histogram(self.path + "_ms").record(self.ms)
            if self.emit_event:
                _events.emit("span", name=self.path, ms=round(self.ms, 3),
                             ok=exc_type is None, **self.fields)
        except Exception:
            pass  # telemetry never turns a timed block's success into a fail
        return False  # propagate exceptions
