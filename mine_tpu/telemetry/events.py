"""Structured JSONL event sink: append-only, schema-versioned, non-fatal.

Low-frequency, high-value happenings (a checkpoint save, a bucket compile, a
guard abort, a profiler window, a per-log-interval step-time record) go here
as one JSON object per line, so train, serve and chaos paths all emit ONE
parseable stream that tools/obs_report.py consumes and
tools/validate_events.py checks in CI. High-frequency numbers (per-request
latencies, cache hits) belong in the metrics registry instead — the sink is
not a firehose.

Schema v1: every line is an object with
    schema  literal "mtpu-ev1" (version tag; bump on breaking change)
    ts      float unix seconds (host clock; ordering hint, not a vector)
    kind    dotted event type, e.g. "ckpt.save", "serve.bucket_compile"
plus kind-specific payload fields (JSON scalars/arrays/objects only).

Failure policy is the PR-4 tensorboard precedent verbatim: an unwritable
path, full disk, or dead filesystem degrades the sink to a no-op with ONE
warning — observability must never kill a multi-hour run. Writes are single
`write()` calls of complete lines on an O_APPEND stream, so concurrent
emitters (threads, or chaos-test subprocesses sharing a path via the
MINE_TPU_TELEMETRY_EVENTS env var) interleave at line granularity.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional

from mine_tpu.analysis.locks import ordered_lock

SCHEMA = "mtpu-ev1"
REQUIRED_FIELDS = ("schema", "ts", "kind")

# Env override: when set, the first emit() in a process with no configured
# sink appends there. This is how the tier-1 wrapper funnels every test's
# events into one file for the schema-validation pass (tools/verify_tier1.sh)
# and how chaos-test subprocesses inherit their parent's stream.
ENV_VAR = "MINE_TPU_TELEMETRY_EVENTS"

_log = logging.getLogger(__name__)


class EventSink:
    """One append-only JSONL stream. Opens lazily on first emit; any IO
    failure (open or write) warns once and disables the sink.

    Size-capped rotation (telemetry.events_max_mb): with `max_mb` > 0 the
    stream rotates when it crosses the cap — `path` -> `path.1`,
    `path.1` -> `path.2`, ... keeping the newest `keep` rotated segments
    (a long-running fleet no longer grows one JSONL file forever).
    `max_mb=0` (the default) is today's unbounded behavior. Readers
    (`read_events`/`validate_file`) walk segments oldest-first via
    `segment_paths`."""

    def __init__(self, path: str, max_mb: float = 0.0, keep: int = 3):
        self.path = path
        self.max_bytes = int(float(max_mb) * (1 << 20))
        self.keep = max(1, int(keep))
        self._lock = ordered_lock("telemetry.events.sink")
        self._file = None
        self._bytes = 0
        self._broken = False
        self.emitted = 0
        self.dropped = 0
        self.rotations = 0

    def emit(self, kind: str, **fields) -> bool:
        """Append one event; returns False when the sink is broken (the
        caller never needs to check — this is for tests)."""
        event = {"schema": SCHEMA, "ts": time.time(), "kind": str(kind)}
        event.update(fields)
        line = json.dumps(event, sort_keys=False, default=_jsonify)
        with self._lock:
            if self._broken:
                self.dropped += 1
                return False
            try:
                if self._file is None:
                    parent = os.path.dirname(self.path)
                    if parent:
                        os.makedirs(parent, exist_ok=True)
                    self._file = open(self.path, "a", buffering=1)
                    self._bytes = self._file.tell()
                self._file.write(line + "\n")
                self._bytes += len(line) + 1
                self.emitted += 1
                if self.max_bytes and self._bytes >= self.max_bytes:
                    self._rotate()
                return True
            except Exception:
                self._broken = True
                self.dropped += 1
                _log.warning(
                    "telemetry event sink failed (%s) — events disabled for "
                    "the rest of the run", self.path, exc_info=True)
                return False

    def _rotate(self) -> None:
        """Shift segments up (caller holds the lock; any failure
        propagates into emit's degrade-to-broken policy). The live file
        reopens lazily on the next emit."""
        self._file.close()
        self._file = None
        oldest = f"{self.path}.{self.keep}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._bytes = 0
        self.rotations += 1

    @property
    def broken(self) -> bool:
        with self._lock:
            return self._broken

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except Exception:
                    pass
                self._file = None


def _jsonify(v):
    """Last-resort encoder: numpy scalars/arrays from call sites that forgot
    to convert — degrade to python types instead of killing the emit."""
    if hasattr(v, "item") and getattr(v, "shape", None) == ():
        return v.item()
    if hasattr(v, "tolist"):
        return v.tolist()
    return str(v)


# configure() closes the old sink while holding this — the one sanctioned
# nesting (state rank 60 < sink rank 70 in analysis.locks.LOCK_RANKS)
_state_lock = ordered_lock("telemetry.events.state")
_sink: Optional[EventSink] = None
_env_checked = False

# Optional observer on EVERY module-level emit(), sink configured or not:
# the flight recorder (telemetry/recorder.py) installs its ring-buffer
# feed here — a hook slot instead of an import, so events stays the leaf
# module. Called as fn(kind, fields) BEFORE the sink write, under whatever
# locks the emitter holds (the recorder's ring rank accounts for that). A
# raising tee is uninstalled with one warning — same never-kill-the-run
# policy as the sink.
_tee = None


def set_tee(fn) -> None:
    global _tee
    _tee = fn


def configure(path: Optional[str], max_mb: float = 0.0,
              keep: int = 3) -> Optional[EventSink]:
    """Point the process-wide sink at `path` (None disables). Replaces any
    existing sink (closed first). Returns the new sink."""
    global _sink, _env_checked
    with _state_lock:
        if _sink is not None:
            _sink.close()
        _sink = EventSink(path, max_mb=max_mb, keep=keep) if path else None
        _env_checked = True  # an explicit choice outranks the env default
        return _sink


def ensure_configured(default_path: Optional[str] = None,
                      max_mb: float = 0.0,
                      keep: int = 3) -> Optional[EventSink]:
    """Configure only if nothing is configured yet: the env var wins, then
    `default_path`. This is the train-loop/serve_cli entry point — an outer
    harness (tier-1, chaos soak) that exported MINE_TPU_TELEMETRY_EVENTS
    keeps owning the stream."""
    global _sink, _env_checked
    with _state_lock:
        if _sink is not None:
            return _sink
        env = os.environ.get(ENV_VAR)
        path = env or default_path
        _env_checked = True
        if path:
            _sink = EventSink(path, max_mb=max_mb, keep=keep)
        return _sink


def current_sink() -> Optional[EventSink]:
    with _state_lock:
        return _sink


def emit(kind: str, **fields) -> bool:
    """Append one event to the process sink. Unconfigured (and no env
    default): a cheap no-op returning False, so instrumented libraries cost
    nothing when nobody asked for events. The recorder tee (when installed)
    sees the event either way."""
    global _sink, _env_checked, _tee
    tee = _tee
    if tee is not None:
        try:
            tee(kind, fields)
        except Exception:
            _tee = None
            _log.warning("telemetry event tee failed — tee uninstalled",
                         exc_info=True)
    sink = _sink
    if sink is None:
        if _env_checked:
            return False
        with _state_lock:
            if not _env_checked:
                env = os.environ.get(ENV_VAR)
                if env:
                    _sink = EventSink(env)
                _env_checked = True
            sink = _sink
        if sink is None:
            return False
    return sink.emit(kind, **fields)


def reset() -> None:
    """Tests only: drop the sink and the tee, re-arm the env-var check."""
    global _sink, _env_checked, _tee
    _tee = None
    with _state_lock:
        if _sink is not None:
            _sink.close()
        _sink = None
        _env_checked = False


# ---------------------------------------------------------------- validation

# Required payload fields per DOCUMENTED kind (MIGRATION.md holds the full
# schemas). mtpu-ev1 evolution is append-only: emitters may ADD fields to a
# kind, never remove or rename one listed here — `--strict` validation
# (tools/validate_events.py) is the drift tripwire. Kinds absent from this
# table pass strict mode on the base schema alone (new kinds are free to
# appear; they become pinned once documented here).
KIND_FIELDS: Dict[str, tuple] = {
    "train.step": ("gstep", "step_ms"),
    "train.layers": ("gstep", "groups"),
    "span": ("name", "ms"),
    "trace.span": ("trace", "span", "name", "ms", "t_off_ms"),
    "serve.sync_encode": ("image_id",),
    # "backend" appended (mtpu-ev1 append-only): the kernel backend the
    # bucket's program compiled against — same value as warp_impl today,
    # carried separately so obs_report can attribute render-time movement
    # to the backend without parsing program keys
    "serve.bucket_compile": ("entries_bucket", "poses_bucket", "warp_impl",
                             "dtype", "compile_ms", "store_hit", "backend"),
    "serve.slo_point": ("offered_qps", "achieved_qps", "p50_ms", "p99_ms"),
    "serve.coldstart_point": ("cold_p99_on_ms", "cold_p99_off_ms",
                              "warm_p99_ms", "boot_on_ms", "loads",
                              "compiles_off", "n_requests"),
    "serve.slo_breach": ("p99_ms", "objective_ms", "window_s"),
    "serve.shard.place": ("image_id", "shard", "shards"),
    "serve.shard.rebalance": ("from_shards", "to_shards", "moved"),
    "serve.admission": ("state", "prev", "queue_depth", "inflight"),
    "serve.shard_dead": ("shard", "shards", "failures", "dropped"),
    "serve.shard_revive": ("shard", "shards", "moved"),
    "metrics.snapshot": ("scope", "metrics"),
    "profile.window": ("start_step", "stop_step", "trace_dir"),
    "serve.session_start": ("session", "keyframe_every", "drift_mode"),
    "serve.session_keyframe": ("session", "frame", "image_id", "reason"),
    "serve.session_frame": ("session", "frame", "age", "drift"),
    "serve.session_end": ("session", "frames", "keyframes"),
    "serve.stream_point": ("knee_cadence", "knee_fps", "n_frames"),
    "obs.incident": ("reason", "bundle"),
    # multi-host ring (serve/ring.py, serve/hostnet.py; mtpu-ev1
    # append-only). host = the joining/draining member's id; hosts = the
    # alive count AFTER the transition as the emitter knows it (0 = the
    # emitter — a standalone draining host — has no ring view). host_join
    # pins the zero-compile-join evidence (AOT bucket loads vs live
    # compiles at boot); host_drain may additionally carry the host's
    # lifetime owner_hits/remote_routes.
    "serve.host_join": ("host", "hosts", "aot_loads", "aot_compiles"),
    "serve.host_drain": ("host", "hosts", "inflight"),
    # membership change re-cutting key ranges (the host-level analogue of
    # serve.shard.rebalance); may carry a "routes" per-host split dict
    "serve.ring_rebalance": ("from_hosts", "to_hosts"),
    # one event per autoscaler DECISION (grow|shrink), edge-triggered like
    # serve.admission — a hysteretic trail never shows grow/shrink flapping
    "serve.autoscale": ("action", "from_hosts", "to_hosts", "score"),
    # one point per serve_multihost bench arm (bench.py): ring size vs
    # aggregate throughput and the front's remote-route fraction
    "serve.multihost_point": ("hosts", "views_per_sec", "remote_frac"),
    # wire hardening (serve.net.*, PR 19). serve.breaker: one event per
    # circuit-breaker TRANSITION (state = open|half_open|closed; failures
    # = the consecutive-failure count at the edge) — edge-triggered like
    # serve.admission, and "open" is a flight-recorder trigger kind.
    # serve.host_suspect: the heartbeat detector's front-local verdict
    # trail (state = suspect|alive|dead; misses = consecutive probe
    # misses at the edge) — suspect routes around the host WITHOUT a
    # membership write, alive is the post-heal re-convergence edge, dead
    # accompanies the mark_dead membership edge on confirmed refusal.
    "serve.breaker": ("host", "state", "failures"),
    "serve.host_suspect": ("host", "state", "misses"),
    # one point per serve_multihost_wire bench arm (bench.py, PR 20):
    # wire codec (json|bin_f32|bin_int8) vs aggregate throughput and the
    # measured payload bytes per rendered view — the binary-wire cost
    # ledger the conductor and the soak's wire phase diff against
    "serve.wire_point": ("codec", "views_per_sec", "bytes_per_view"),
}


def validate_line(line: str, strict_kinds: bool = False) -> Optional[str]:
    """Schema check of one JSONL line; None when valid, else a short error
    string. Blank lines are valid (a crashed writer's trailing newline must
    not fail CI). Shared by tools/validate_events.py and obs_report.
    `strict_kinds` additionally requires every documented kind (KIND_FIELDS)
    to carry its pinned payload fields."""
    s = line.strip()
    if not s:
        return None
    try:
        obj = json.loads(s)
    except ValueError as e:
        return f"not JSON: {e}"
    if not isinstance(obj, dict):
        return "not a JSON object"
    for k in REQUIRED_FIELDS:
        if k not in obj:
            return f"missing required field {k!r}"
    if obj["schema"] != SCHEMA:
        return f"unknown schema {obj['schema']!r} (expected {SCHEMA!r})"
    if not isinstance(obj["ts"], (int, float)):
        return f"ts must be numeric, got {type(obj['ts']).__name__}"
    if not isinstance(obj["kind"], str) or not obj["kind"]:
        return "kind must be a non-empty string"
    if strict_kinds:
        missing = [k for k in KIND_FIELDS.get(obj["kind"], ())
                   if k not in obj]
        if missing:
            return (f"kind {obj['kind']!r} missing documented field(s) "
                    f"{missing}")
    return None


def segment_paths(path: str) -> List[str]:
    """All on-disk segments of a (possibly rotated) stream, oldest-first:
    `path.K` ... `path.1`, then the live `path`. An unrotated stream is
    just `[path]` (even when the file is missing — callers keep their
    existing missing-file behavior)."""
    rotated = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        rotated.append(f"{path}.{i}")
        i += 1
    return list(reversed(rotated)) + [path]


def validate_file(path: str, max_errors: int = 20,
                  strict_kinds: bool = False) -> List[str]:
    """-> list of "line N: error" strings (empty = file is schema-clean).
    Walks rotated segments oldest-first; errors in a rotated segment are
    prefixed with its basename."""
    errors = []
    segs = segment_paths(path)
    for seg in segs:
        if seg == path and len(segs) > 1 and not os.path.exists(seg):
            continue  # rotated out, next emit not yet arrived
        tag = "" if seg == path else os.path.basename(seg) + " "
        with open(seg) as f:
            for i, line in enumerate(f, 1):
                err = validate_line(line, strict_kinds=strict_kinds)
                if err is not None:
                    errors.append(f"{tag}line {i}: {err}")
                    if len(errors) >= max_errors:
                        errors.append("... (truncated)")
                        return errors
    return errors


def read_events(path: str) -> List[Dict]:
    """Parse a JSONL event file — rotated segments included, oldest-first —
    skipping invalid lines (the validator is the strict path; readers are
    lenient so a torn tail line from a killed run doesn't hide the rest of
    the stream)."""
    out = []
    segs = segment_paths(path)
    for seg in segs:
        if seg == path and len(segs) > 1 and not os.path.exists(seg):
            continue
        with open(seg) as f:
            for line in f:
                if validate_line(line) is None and line.strip():
                    out.append(json.loads(line))
    return out
