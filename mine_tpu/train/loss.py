"""The training loss graph — all four scales in one fused pyramid pass.

Replaces SynthesisTask.loss_fcn / loss_fcn_per_scale / render_novel_view /
compute_scale_factor (synthesis_task.py:211-401). Where the reference runs
each scale's rendering and losses as dozens of separate CUDA kernels, here the
whole graph (forward, 4x render, all loss terms) is a single jit region that
XLA fuses; multi-device runs shard it over the ("data", "plane") mesh via
sharding constraints and GSPMD-inserted collectives.

Fused pyramid pass (the PR-2 restructure): instead of four independent scale
subgraphs that each re-derive their inputs, `build_scale_plan` computes the
batch-only-dependent work ONCE per step —
  * src/tgt nearest-neighbor pyramids as a cascade (scale s is scale s-1
    strided by 2; stride composition from index 0 makes x[::2][::2] the same
    elements as x[::4], so every level is bit-identical to slicing full-res)
  * per-scale intrinsics / inverse intrinsics / cached pixel grids
  * the sobel edge masks and finite-diff image gradients the edge-aware
    smoothness terms need (functions of the images only, previously
    recomputed inside every edge_aware_loss call site)
and `loss_per_scale` consumes its precomputed `ScaleInputs`. The two SSIM
evaluations per scale (src + tgt pairs) run through one stacked
`ssim_pairs` call — 2 Toeplitz blur einsums per scale instead of 20 (see
losses/ssim.py) — and the |syn - gt| diffs feed the rgb terms from named
intermediates instead of being re-expressed per term.

Semantics preserved (checked term by term against the reference):
  * nearest-neighbor image pyramid via strided slicing (== nn.Upsample(size),
    synthesis_task.py:129-134)
  * intrinsics scaling with K[2,2]=1 (:238-241)
  * source-view render + optional src rgb blending + re-composite (:260-275)
  * log-disparity scale factor from sparse COLMAP points at scale 0, reused
    at scales 1-3 (:211-220,282-283)
  * novel-view render with scale-factor-corrected, stop-gradient translation
    (:439-442)
  * loss terms and their exact aggregation across scales (:296-351,394-400)
  * src-view photometric terms are logged but carry no gradient (:301-306)

Deviations (documented):
  * terms whose reference lambda is exactly 0 are skipped instead of
    multiplied by 0 — identical totals, but avoids 0*NaN poisoning when a
    term is degenerate (e.g. log of behind-camera points with disp_lambda=0).
  * LPIPS runs only when converted weights are provided (no egress here).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from mine_tpu import geometry
from mine_tpu.config import MPIConfig
from mine_tpu.losses import (edge_aware_image_masks, edge_aware_loss,
                             edge_aware_loss_v2, image_mean_abs_grads, psnr,
                             ssim_pairs)
from mine_tpu.losses import lpips as lpips_mod
from mine_tpu.ops import rendering, sampling
from mine_tpu.parallel.mesh import DATA_AXIS, PLANE_AXIS, constrain

Batch = Dict[str, jnp.ndarray]

NUM_SCALES = 4


def nchw(img_nhwc: jnp.ndarray) -> jnp.ndarray:
    return jnp.transpose(img_nhwc, (0, 3, 1, 2))


class ScaleInputs(NamedTuple):
    """Batch-derived inputs for one pyramid scale, precomputed once per step
    by build_scale_plan. Mask/grad fields are None when the config never
    consumes them (their loss term's lambda is 0), so no dead subgraph is
    traced."""
    src_imgs: jnp.ndarray            # [B,3,Hs,Ws] nearest pyramid level
    tgt_imgs: jnp.ndarray            # [B,3,Hs,Ws]
    K_src: jnp.ndarray               # [B,3,3] scaled intrinsics
    K_tgt: jnp.ndarray               # [B,3,3]
    K_src_inv: jnp.ndarray           # [B,3,3]
    grid: jnp.ndarray                # [3,Hs*Ws] homogeneous pixel grid
    src_edge_masks: Optional[Tuple[jnp.ndarray, jnp.ndarray]]
    tgt_edge_masks: Optional[Tuple[jnp.ndarray, jnp.ndarray]]
    src_img_grads: Optional[Tuple[jnp.ndarray, jnp.ndarray]]
    tgt_img_grads: Optional[Tuple[jnp.ndarray, jnp.ndarray]]


def build_scale_plan(batch: Batch, cfg: MPIConfig,
                     num_scales: int = NUM_SCALES) -> Tuple[ScaleInputs, ...]:
    """Precompute every batch-only-dependent per-scale input.

    The pyramids are built as a cascade — each level strided from the level
    above. Strides compose from index 0 (x[::2][::2] picks exactly the
    elements of x[::4]), so every level is bit-identical to the old per-scale
    `full[:, :, ::2**s, ::2**s]` while touching 1/4 the data per level.
    Intrinsics halving is exact in binary floating point, so the hoisted
    `scale_intrinsics` results match the old per-scale calls bitwise.
    """
    src = nchw(batch["src_img"])
    tgt = nchw(batch["tgt_img"])

    # src edge masks feed the always-logged loss_smooth_src; the others are
    # gated by their term's lambda exactly as the loss terms themselves are.
    need_src_masks = True
    need_tgt_masks = cfg.smoothness_lambda_v1 != 0.0
    need_grads = cfg.smoothness_lambda_v2 != 0.0

    plan = []
    for scale in range(num_scales):
        if scale > 0:
            src = src[:, :, ::2, ::2]
            tgt = tgt[:, :, ::2, ::2]
        Hs, Ws = src.shape[2], src.shape[3]
        K_src = geometry.scale_intrinsics(batch["K_src"], scale)
        K_tgt = geometry.scale_intrinsics(batch["K_tgt"], scale)
        plan.append(ScaleInputs(
            src_imgs=src,
            tgt_imgs=tgt,
            K_src=K_src,
            K_tgt=K_tgt,
            K_src_inv=geometry.inverse_intrinsics(K_src),
            grid=geometry.cached_pixel_grid(Hs, Ws),
            src_edge_masks=(edge_aware_image_masks(
                src, cfg.smoothness_grad_ratio) if need_src_masks else None),
            tgt_edge_masks=(edge_aware_image_masks(
                tgt, cfg.smoothness_grad_ratio) if need_tgt_masks else None),
            src_img_grads=(image_mean_abs_grads(src) if need_grads else None),
            tgt_img_grads=(image_mean_abs_grads(tgt) if need_grads else None),
        ))
    return tuple(plan)


def compute_scale_factor(disparity_syn_pt3d: jnp.ndarray,
                         pt3d_disp: jnp.ndarray) -> jnp.ndarray:
    """exp(mean(log disp_syn - log disp_gt)) per batch element.

    Reference: synthesis_task.compute_scale_factor (:211-220).
    Args: [B,1,N] each. Returns [B].
    """
    return jnp.exp(jnp.mean(
        _safe_log(disparity_syn_pt3d) - _safe_log(pt3d_disp), axis=2))[:, 0]


def _project_points(K: jnp.ndarray, pt3d: jnp.ndarray) -> jnp.ndarray:
    """[B,3,3] x [B,3,N] -> pixel coords [B,2,N]."""
    p = jnp.einsum("bij,bjn->bin", K, pt3d)
    return p[:, 0:2] / p[:, 2:3]


def _safe_log(x: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    """log with a floor: degenerate synthesized disparities (all planes
    transparent at a pixel, e.g. under heavy sigma dropout -> depth ~ 0 ->
    disparity -> inf/0) produce a huge-but-finite loss instead of inf/NaN
    poisoning the parameters. The reference has no guard and infs there."""
    return jnp.log(jnp.maximum(x, eps))


def _safe_reciprocal_depth(depth: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    """depth -> disparity with a floor. A pixel where every plane is fully
    transparent (sigma dropout can zero whole planes) composites to depth
    exactly 0; the reference's torch.reciprocal returns inf there and the
    loss NaNs. A finite 1/eps keeps training recoverable; no gradient flows
    through floored pixels."""
    return 1.0 / jnp.maximum(depth, eps)


def _disp_loss(disp_syn_at_pts: jnp.ndarray, pt3d_disp: jnp.ndarray,
               scale_factor: jnp.ndarray) -> jnp.ndarray:
    """Per-example sparse-disparity loss [B] (callers aggregate)."""
    scaled = disp_syn_at_pts / scale_factor[:, None, None]
    return jnp.mean(jnp.abs(_safe_log(scaled) - _safe_log(pt3d_disp)),
                    axis=(1, 2))


# warp backends with a runtime band-fit guard: render results carry a
# warp_in_domain diagnostic that loss_terms_per_scale surfaces as the
# warp_fallback metric (key absent on unguarded backends)
GUARDED_WARP_BACKENDS = ("pallas_diff", "xla_banded", "separable",
                         "pallas_sep")


def render_per_scale(scale: int,
                     plan_s: ScaleInputs,
                     mpi: jnp.ndarray,
                     disparity: jnp.ndarray,
                     batch: Batch,
                     G_tgt_src: jnp.ndarray,
                     cfg: MPIConfig,
                     scale_factor: Optional[jnp.ndarray],
                     mesh=None) -> Dict[str, jnp.ndarray]:
    """Render half of one scale: src composite (+ rgb blending), scale
    factor, novel-view warp/composite (synthesis_task.py:230-295,435-474).

    This is the warp/composite STAGE of the staged train step — its return
    dict is the stage-boundary pytree the pipeline executor differentiates
    the loss stage with respect to (mine_tpu/parallel/pipeline.py). The
    fused path composes it with loss_terms_per_scale via loss_per_scale,
    tracing exactly the ops of the pre-split function.

    Returns a dict with src_syn, src_disp_syn, tgt_syn, tgt_mask,
    tgt_disp_syn, scale_factor [B] (computed here at scale 0 when the
    incoming one is None), plus src_pt_disp/src_pt_disp_syn when the
    sparse-disparity loss is on and warp_in_domain on guarded backends.
    """
    src_imgs = plan_s.src_imgs
    B = src_imgs.shape[0]

    K_src, K_tgt, K_src_inv = plan_s.K_src, plan_s.K_tgt, plan_s.K_src_inv

    xyz_src = geometry.plane_xyz_src(plan_s.grid, disparity, K_src_inv)
    xyz_src = constrain(xyz_src, mesh, DATA_AXIS, PLANE_AXIS)

    mpi = constrain(mpi, mesh, DATA_AXIS, PLANE_AXIS)
    mpi_rgb = mpi[:, :, 0:3]
    mpi_sigma = mpi[:, :, 3:4]

    with jax.named_scope(f"render_src_s{scale}"):
        src_syn, src_depth, blend_weights, weights = rendering.render(
            mpi_rgb, mpi_sigma, xyz_src,
            use_alpha=cfg.use_alpha, is_bg_depth_inf=cfg.is_bg_depth_inf)

        if cfg.src_rgb_blending:
            # visible-from-src planes take the real pixels
            # (synthesis_task.py:267-274)
            mpi_rgb = blend_weights * src_imgs[:, None] \
                + (1.0 - blend_weights) * mpi_rgb
            src_syn, src_depth = rendering.weighted_sum_mpi(
                mpi_rgb, xyz_src, weights,
                is_bg_depth_inf=cfg.is_bg_depth_inf)

    src_disp_syn = _safe_reciprocal_depth(src_depth)

    # sparse-point disparity at src + scale factor
    if cfg.use_disparity_loss or cfg.use_scale_factor:
        src_pt3d = batch["pt3d_src"]  # [B,3,N] camera-frame points
        src_pt_disp = 1.0 / src_pt3d[:, 2:3]
        src_pt_pxpy = _project_points(K_src, src_pt3d)
        src_pt_disp_syn = sampling.gather_pixel_by_pxpy(src_disp_syn, src_pt_pxpy)
    if scale_factor is None:
        if cfg.use_scale_factor:
            scale_factor = compute_scale_factor(src_pt_disp_syn, src_pt_disp)
        else:
            scale_factor = jnp.ones((B,), jnp.float32)

    # novel view (synthesis_task.render_novel_view :435-474)
    t_scaled = G_tgt_src[:, 0:3, 3] / scale_factor[:, None]
    G_render = jax.lax.stop_gradient(
        G_tgt_src.at[:, 0:3, 3].set(t_scaled))
    xyz_tgt = geometry.plane_xyz_tgt(xyz_src, G_render)
    xyz_tgt = constrain(xyz_tgt, mesh, DATA_AXIS, PLANE_AXIS)
    with jax.named_scope(f"warp_composite_tgt_s{scale}"):
        res = rendering.render_tgt_rgb_depth(
            mpi_rgb, mpi_sigma, disparity, xyz_tgt, G_render,
            K_src_inv, K_tgt,
            use_alpha=cfg.use_alpha, is_bg_depth_inf=cfg.is_bg_depth_inf,
            backend=cfg.composite_backend,
            warp_impl=cfg.warp_backend, warp_band=cfg.warp_band,
            warp_dtype=cfg.warp_dtype, warp_sep_tol=cfg.warp_sep_tol,
            mesh=mesh if (mesh is not None and mesh.size > 1) else None)
    tgt_syn, tgt_mask = res.rgb, res.mask
    tgt_disp_syn = _safe_reciprocal_depth(res.depth)

    rendered = {
        "src_syn": src_syn,
        "src_disp_syn": src_disp_syn,
        "tgt_syn": tgt_syn,
        "tgt_mask": tgt_mask,
        "tgt_disp_syn": tgt_disp_syn,
        "scale_factor": scale_factor,
    }
    if cfg.use_disparity_loss:
        rendered["src_pt_disp"] = src_pt_disp
        rendered["src_pt_disp_syn"] = src_pt_disp_syn
    if cfg.warp_backend in GUARDED_WARP_BACKENDS:
        rendered["warp_in_domain"] = res.warp_in_domain
    return rendered


def loss_terms_per_scale(scale: int,
                         plan_s: ScaleInputs,
                         rendered: Dict[str, jnp.ndarray],
                         batch: Batch,
                         cfg: MPIConfig,
                         is_val: bool = False,
                         lpips_params=None,
                         example_weight: Optional[jnp.ndarray] = None,
                         ) -> Tuple[Dict[str, jnp.ndarray],
                                    Dict[str, jnp.ndarray]]:
    """Loss-terms half of one scale over render_per_scale's output
    (synthesis_task.py:296-373) — the LOSS stage of the staged step.

    Every metric is computed per-example first ([B]) and then aggregated —
    mathematically identical to the reference's whole-batch means because
    all examples share one image size.
    """
    src_imgs = plan_s.src_imgs
    tgt_imgs = plan_s.tgt_imgs
    K_tgt = plan_s.K_tgt
    src_syn = rendered["src_syn"]
    src_disp_syn = rendered["src_disp_syn"]
    tgt_syn = rendered["tgt_syn"]
    tgt_mask = rendered["tgt_mask"]
    tgt_disp_syn = rendered["tgt_disp_syn"]
    scale_factor = rendered["scale_factor"]

    # ---- loss terms ----
    zero = jnp.zeros((), jnp.float32)

    if example_weight is None:
        agg = jnp.mean  # [B] per-example values -> batch mean
    else:
        w = example_weight
        w_sum = jnp.maximum(jnp.sum(w), 1e-8)

        def agg(v):
            # where() first: 0-weight padding may hold NaN/inf and NaN*0=NaN
            return jnp.sum(jnp.where(w > 0, v, 0.0) * w) / w_sum

    def pex(x):  # per-example mean, [B,...] -> [B]
        return jnp.mean(x, axis=tuple(range(1, x.ndim)))

    # shared photometric intermediates: each |syn - gt| diff is one named
    # tensor feeding its rgb term (and XLA reuses it wherever else it fuses)
    abs_diff_src = jnp.abs(src_syn - src_imgs)
    abs_diff_tgt = jnp.abs(tgt_syn - tgt_imgs)

    # both SSIM pairs (tgt drives gradient, src is logged) through ONE
    # stacked blur pass: 2 Toeplitz einsums for the whole scale
    with jax.named_scope(f"ssim_pairs_s{scale}"):
        ssim_both = ssim_pairs(
            jnp.stack([tgt_syn, src_syn]), jnp.stack([tgt_imgs, src_imgs]),
            size_average=False, precision=cfg.ssim_precision)  # [2,B]

    # src-view photometrics: logged, no gradient (synthesis_task.py:301-306)
    loss_rgb_src = jax.lax.stop_gradient(agg(pex(abs_diff_src)))
    loss_ssim_src = jax.lax.stop_gradient(agg(1.0 - ssim_both[1]))
    loss_smooth_src = jax.lax.stop_gradient(
        agg(edge_aware_loss(src_imgs, src_disp_syn,
                            gmin=cfg.smoothness_gmin,
                            grad_ratio=cfg.smoothness_grad_ratio,
                            size_average=False,
                            edge_masks=plan_s.src_edge_masks)))

    if cfg.use_disparity_loss:
        loss_disp_src = agg(_disp_loss(rendered["src_pt_disp_syn"],
                                       rendered["src_pt_disp"],
                                       scale_factor))
        tgt_pt3d = batch["pt3d_tgt"]
        tgt_pt_disp = 1.0 / tgt_pt3d[:, 2:3]
        tgt_pt_pxpy = _project_points(K_tgt, tgt_pt3d)
        tgt_pt_disp_syn = sampling.gather_pixel_by_pxpy(tgt_disp_syn, tgt_pt_pxpy)
        loss_disp_tgt = agg(_disp_loss(tgt_pt_disp_syn, tgt_pt_disp,
                                       scale_factor))
    else:
        loss_disp_src = zero
        loss_disp_tgt = zero

    # tgt rgb, masked to pixels covered by enough warped planes (:324-328)
    valid = (tgt_mask >= cfg.valid_mask_threshold).astype(jnp.float32)
    loss_rgb_tgt = agg(pex(abs_diff_tgt * valid))
    loss_ssim_tgt = agg(1.0 - ssim_both[0])

    if cfg.smoothness_lambda_v1 != 0.0:
        loss_smooth_tgt = cfg.smoothness_lambda_v1 * agg(edge_aware_loss(
            tgt_imgs, tgt_disp_syn,
            gmin=cfg.smoothness_gmin, grad_ratio=cfg.smoothness_grad_ratio,
            size_average=False, edge_masks=plan_s.tgt_edge_masks))
    else:
        loss_smooth_tgt = zero
    if cfg.smoothness_lambda_v2 != 0.0:
        loss_smooth_src_v2 = cfg.smoothness_lambda_v2 * agg(
            edge_aware_loss_v2(src_imgs, src_disp_syn, size_average=False,
                               img_grads=plan_s.src_img_grads))
        loss_smooth_tgt_v2 = cfg.smoothness_lambda_v2 * agg(
            edge_aware_loss_v2(tgt_imgs, tgt_disp_syn, size_average=False,
                               img_grads=plan_s.tgt_img_grads))
    else:
        loss_smooth_src_v2 = zero
        loss_smooth_tgt_v2 = zero

    psnr_tgt = jax.lax.stop_gradient(
        agg(psnr(tgt_syn, tgt_imgs, size_average=False)))
    if is_val and scale == 0:
        if lpips_params is not None:
            lpips_tgt = agg(lpips_mod.lpips_distance(
                lpips_params, tgt_syn, tgt_imgs))
        else:
            # absent weights must NOT read as a perfect 0.0 score — report
            # NaN so downstream consumers can't mistake it for a measurement
            # (losses/lpips.py module contract; VERDICT r1 weak item 5)
            lpips_tgt = jnp.full((), jnp.nan, jnp.float32)
    else:
        lpips_tgt = zero

    loss = (loss_disp_tgt + loss_disp_src
            + loss_rgb_tgt + loss_ssim_tgt
            + loss_smooth_tgt
            + loss_smooth_src_v2 + loss_smooth_tgt_v2)

    loss_dict = {
        "loss": loss,
        "loss_rgb_src": loss_rgb_src,
        "loss_ssim_src": loss_ssim_src,
        "loss_disp_pt3dsrc": loss_disp_src,
        "loss_smooth_src": loss_smooth_src,
        "loss_smooth_tgt": loss_smooth_tgt,
        "loss_smooth_src_v2": loss_smooth_src_v2,
        "loss_smooth_tgt_v2": loss_smooth_tgt_v2,
        "loss_rgb_tgt": loss_rgb_tgt,
        "loss_ssim_tgt": loss_ssim_tgt,
        "lpips_tgt": lpips_tgt,
        "psnr_tgt": psnr_tgt,
        "loss_disp_pt3dtgt": loss_disp_tgt,
    }
    if "warp_in_domain" in rendered:
        # guard diagnostic, not a loss: 1.0 when this scale's guarded warp
        # backend bailed to the gather (key absent on unguarded backends)
        loss_dict["warp_fallback"] = jax.lax.stop_gradient(
            1.0 - rendered["warp_in_domain"])
    visuals = {
        "src_disparity_syn": src_disp_syn,
        "tgt_disparity_syn": tgt_disp_syn,
        "tgt_imgs_syn": tgt_syn,
        "tgt_mask_syn": tgt_mask,
        "src_imgs_syn": src_syn,
    }
    return loss_dict, visuals


def loss_per_scale(scale: int,
                   plan_s: ScaleInputs,
                   mpi: jnp.ndarray,
                   disparity: jnp.ndarray,
                   batch: Batch,
                   G_tgt_src: jnp.ndarray,
                   cfg: MPIConfig,
                   scale_factor: Optional[jnp.ndarray],
                   mesh=None,
                   is_val: bool = False,
                   lpips_params=None,
                   example_weight: Optional[jnp.ndarray] = None,
                   ) -> Tuple[Dict[str, jnp.ndarray],
                              Dict[str, jnp.ndarray],
                              jnp.ndarray]:
    """One pyramid scale of the loss graph (synthesis_task.py:230-373):
    render_per_scale composed with loss_terms_per_scale — the exact op
    sequence of the pre-split function, so the fused step's trace (and its
    pinned dot/cost baselines) is unchanged by the stage refactor.

    Args:
      plan_s: this scale's precomputed ScaleInputs (build_scale_plan)
      mpi: [B,S,4,Hs,Ws] decoder output at this scale
      disparity: [B,S]
      scale_factor: [B] or None (computed here at scale 0)
      example_weight: optional [B] weights for the batch-mean aggregation
        (masked padded eval batches: 0-weight examples are excluded exactly;
        jnp.where guards keep any garbage/NaN in padding examples out of the
        weighted sum). None = plain batch mean (the training path).
    Returns: (loss_dict, visuals, scale_factor)
    """
    rendered = render_per_scale(scale, plan_s, mpi, disparity, batch,
                                G_tgt_src, cfg, scale_factor, mesh=mesh)
    loss_dict, visuals = loss_terms_per_scale(
        scale, plan_s, rendered, batch, cfg, is_val=is_val,
        lpips_params=lpips_params, example_weight=example_weight)
    return loss_dict, visuals, rendered["scale_factor"]


def compute_losses(mpi_list,
                   disparity: jnp.ndarray,
                   batch: Batch,
                   cfg: MPIConfig,
                   mesh=None,
                   is_val: bool = False,
                   lpips_params=None,
                   example_weight=None):
    """All scales + aggregation (synthesis_task.loss_fcn :375-401).

    Builds the shared ScalePlan once, then evaluates every scale against its
    precomputed inputs. Total = full term set at scale 0, plus per extra
    scale: rgb+ssim (if use_multi_scale), the two sparse-disparity terms,
    and both v2 smoothness terms (:394-400).
    Returns: (total_loss, metrics_dict_scale0, visuals_scale0)
    """
    G_tgt_src = geometry.rigid_inverse(batch["G_src_tgt"])
    plan = build_scale_plan(batch, cfg, num_scales=NUM_SCALES)

    scale_factor = None
    dicts = []
    visuals0 = None
    for scale in range(NUM_SCALES):
        ld, vis, scale_factor = loss_per_scale(
            scale, plan[scale], mpi_list[scale], disparity, batch, G_tgt_src,
            cfg, scale_factor, mesh=mesh, is_val=is_val,
            lpips_params=lpips_params, example_weight=example_weight)
        dicts.append(ld)
        if scale == 0:
            visuals0 = vis

    total, metrics = aggregate_scale_losses(dicts, cfg)
    return total, metrics, visuals0


def aggregate_scale_losses(dicts, cfg: MPIConfig):
    """Cross-scale total + metrics over the per-scale loss dicts
    (synthesis_task.loss_fcn :394-400) — shared by the fused compute_losses
    and the staged loss_from_rendered so the two paths aggregate with the
    identical sum order."""
    total = dicts[0]["loss"]
    for s in range(1, NUM_SCALES):
        if cfg.use_multi_scale:
            total = total + dicts[s]["loss_rgb_tgt"] + dicts[s]["loss_ssim_tgt"]
        total = total + dicts[s]["loss_disp_pt3dsrc"] + dicts[s]["loss_disp_pt3dtgt"]
        total = total + dicts[s]["loss_smooth_src_v2"] + dicts[s]["loss_smooth_tgt_v2"]

    metrics = dict(dicts[0])
    metrics["loss"] = total
    if "warp_fallback" in metrics:
        # fraction of this step's 4 scale-warps that hit the gather
        # fallback (VERDICT r4 weak item 5 — anchors the `auto` backend's
        # perf claim); key absent for backends with no runtime guard
        del metrics["warp_fallback"]
        metrics["warp_fallback_frac"] = jnp.mean(
            jnp.stack([d["warp_fallback"] for d in dicts]))
    return total, metrics


def render_all_scales(mpi_list, disparity: jnp.ndarray, batch: Batch,
                      cfg: MPIConfig, mesh=None):
    """The warp/composite STAGE of the staged train step: the render half
    of all 4 scales, threading the scale-0 scale factor forward exactly as
    compute_losses does. Returns a list of per-scale rendered dicts — the
    stage-boundary pytree mine_tpu/parallel/pipeline.py carries cotangents
    through."""
    G_tgt_src = geometry.rigid_inverse(batch["G_src_tgt"])
    plan = build_scale_plan(batch, cfg, num_scales=NUM_SCALES)
    scale_factor = None
    rendered = []
    for scale in range(NUM_SCALES):
        r = render_per_scale(scale, plan[scale], mpi_list[scale], disparity,
                             batch, G_tgt_src, cfg, scale_factor, mesh=mesh)
        scale_factor = r["scale_factor"]
        rendered.append(r)
    return rendered


def loss_from_rendered(rendered_list, batch: Batch, cfg: MPIConfig,
                       is_val: bool = False, lpips_params=None,
                       example_weight=None):
    """The fused-loss STAGE of the staged train step: loss terms + the
    cross-scale aggregation over render_all_scales output. Composing
    render_all_scales with this function computes the same math as
    compute_losses (the scale plan is rebuilt here — pyramids/masks are
    batch-only functions, cheaper to recompute than to ship across the
    stage boundary). Returns (total, metrics, visuals_scale0)."""
    plan = build_scale_plan(batch, cfg, num_scales=NUM_SCALES)
    dicts = []
    visuals0 = None
    for scale in range(NUM_SCALES):
        ld, vis = loss_terms_per_scale(
            scale, plan[scale], rendered_list[scale], batch, cfg,
            is_val=is_val, lpips_params=lpips_params,
            example_weight=example_weight)
        dicts.append(ld)
        if scale == 0:
            visuals0 = vis
    total, metrics = aggregate_scale_losses(dicts, cfg)
    return total, metrics, visuals0
