"""Training-resilience layer: the host-side halves of fault tolerance.

Three failure modes dominate real TPU-pod training and each gets a
coordinated device+host treatment here:

  * numeric blow-ups — the all-finite step guard lives INSIDE the jitted
    train step (train/step.py) so skipping a poisoned step costs no host
    sync; this module supplies the pure tree-select (`select_tree`) and
    the host-side abort policy (`GuardMonitor`) that reads the guard
    counters off the metrics at log cadence and aborts after too many
    CONSECUTIVE skips (a persistent blow-up means the run is dead —
    looping forever on zero-updates just burns the reservation).
  * preemption — `PreemptionHandler` turns SIGTERM/SIGINT into a host
    flag; the loop folds it into a tiny all-host agreement at each
    checkpoint-cadence boundary (`global_any`) so every process saves the
    same emergency `checkpoint_latest` and exits cleanly. Single-process
    runs skip the collective entirely.
  * data corruption — handled in data/common.py (bounded per-item retry +
    deterministic quarantine) and data/pipeline.py (worker respawn); the
    loop surfaces the counters via data/common.PIPELINE_STATS.

Checkpoint hardening (commit markers, retention, the restore fallback
chain) lives with the manager in train/checkpoint.py.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def select_tree(keep_new, new_tree, old_tree):
    """Elementwise tree select: `keep_new` (bool scalar) picks every leaf of
    new_tree, else old_tree — the zero-update primitive of the step guard.
    Fuses into the step program; no extra memory beyond the selects."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(keep_new, n, o), new_tree, old_tree)


def global_any(flag: bool) -> bool:
    """All-host agreement on a host-side boolean.

    Multi-host SPMD requires every process to take the same
    save-and-exit branch or the next collective deadlocks; a SIGTERM
    often reaches only some hosts (maintenance drains one VM at a time).
    Single process: the local flag, no device work. Multi-host: a tiny
    allgather-any over one int32 per host — called at checkpoint-cadence
    boundaries only, never per step.
    """
    if jax.process_count() == 1:
        return bool(flag)
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(
        np.asarray([1 if flag else 0], np.int32))
    return bool(np.asarray(flags).sum() > 0)


class PreemptionHandler:
    """SIGTERM/SIGINT -> a sticky host flag, read at cadence boundaries.

    The handler only flips a flag — no I/O, no jax calls — so it is safe
    at any interrupt point. A second SIGINT restores Python's default
    KeyboardInterrupt so a stuck run can still be killed interactively.
    `install()`/`uninstall()` nest safely; uninstall restores whatever
    handlers were active before.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, logger=None):
        self._logger = logger
        self._flag = threading.Event()
        self._prev = None

    def _handle(self, signum, frame):
        if self._flag.is_set() and signum == signal.SIGINT:
            # second Ctrl-C: the user means it — stop swallowing
            signal.signal(signal.SIGINT, signal.default_int_handler)
            raise KeyboardInterrupt
        self._flag.set()
        if self._logger is not None:
            try:
                self._logger.info(
                    "Signal %d received — will checkpoint and exit at the "
                    "next checkpoint boundary", signum)
            except Exception:
                pass  # logging must never break the handler

    def install(self) -> "PreemptionHandler":
        if self._prev is None and \
                threading.current_thread() is threading.main_thread():
            self._prev = {s: signal.signal(s, self._handle)
                          for s in self.SIGNALS}
        return self

    def uninstall(self):
        if self._prev is not None:
            for s, h in self._prev.items():
                signal.signal(s, h)
            self._prev = None

    @property
    def requested(self) -> bool:
        """This host's local flag (free; no collective)."""
        return self._flag.is_set()

    def global_requested(self) -> bool:
        """All-host agreement — call at checkpoint-cadence boundaries."""
        return global_any(self._flag.is_set())


class GuardMonitor:
    """Host policy over the step guard's counters (read at log cadence).

    The device guard (train/step.py) swaps poisoned updates for
    zero-updates and counts them; this monitor decides when skipping has
    gone from "rode out a transient" to "the run is dead". `threshold`
    consecutive skips -> GuardAbort. threshold <= 0 disables the abort
    (the guard itself still skips).
    """

    def __init__(self, threshold: int, logger=None):
        self.threshold = int(threshold)
        self._logger = logger
        self._last_reported = 0

    def check(self, metrics: dict, gstep: int):
        """`metrics` is the host-side float dict of a LOG step (the only
        cadence at which metrics are synced anyway)."""
        skipped = int(metrics.get("skipped_steps", 0))
        consecutive = int(metrics.get("guard_consecutive", 0))
        if skipped > self._last_reported and self._logger is not None:
            self._logger.info(
                "Non-finite step guard: %d step(s) skipped so far "
                "(last bad step %d, %d consecutive)", skipped,
                int(metrics.get("guard_last_bad_step", -1)), consecutive)
            self._last_reported = skipped
        if self.threshold > 0 and consecutive >= self.threshold:
            raise GuardAbort(
                f"{consecutive} consecutive non-finite training steps at "
                f"global step {gstep} (threshold "
                f"training.guard_skip_threshold={self.threshold}): the "
                f"blow-up is persistent, aborting instead of looping on "
                f"zero-updates. Last good params are in the emergency "
                f"checkpoint.")


class GuardAbort(RuntimeError):
    """Persistent non-finite steps: training aborted by the guard."""
