"""Train state + optimizer.

Optimizer semantics match the reference (synthesis_task.py:83-87,116-118):
Adam with L2 weight decay folded into the gradient *before* the moment
updates (torch.optim.Adam's weight_decay), two parameter groups with separate
learning rates (backbone vs decoder), and a MultiStepLR schedule that decays
both by gamma at epoch milestones.

Unlike the reference's checkpoints — which drop step/epoch and RNG
(synthesis_task.py:629-631,650-652; SURVEY.md section 5) — the state carries
step and the PRNG key, so checkpoint/resume is exact.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray          # int32 scalar
    params: Any                # {'backbone': ..., 'decoder': ...}
    batch_stats: Any
    opt_state: Any
    rng: jax.Array             # folded with step per training step
    guard: jax.Array           # int32 [3] non-finite-step-guard counters


# Indices into TrainState.guard — kept as one small device buffer (not
# separate fields) so the checkpoint layer can strip/inject it wholesale:
# on-disk checkpoints keep the stable 5-key tree and stay readable across
# guard changes, and the counters reset on restore (they are diagnostics
# of THIS run, not model state — see MIGRATION.md).
GUARD_SKIPPED = 0    # total steps skipped (non-finite loss/grad-norm)
GUARD_CONSEC = 1     # current run of consecutive skips (abort signal)
GUARD_LAST_BAD = 2   # state.step of the most recent skipped step, -1 never


def make_guard_buffer() -> jnp.ndarray:
    return jnp.asarray([0, 0, -1], jnp.int32)


def multistep_lr(base_lr: float, decay_epochs, gamma: float,
                 steps_per_epoch: int, accum: int = 1) -> optax.Schedule:
    """MultiStepLR: multiply by gamma at each epoch milestone.

    With gradient accumulation the schedule's clock is OPTIMIZER steps, so
    each epoch milestone is rounded from the micro-step product
    (e * steps_per_epoch // accum), not from a truncated per-epoch quotient
    — keeps the device schedule aligned with the host-side micro-step clock
    (current_lrs) even when accum does not divide steps_per_epoch. When
    several milestones land between the same two optimizer steps (accum >
    steps_per_epoch) their gammas compound on that one boundary."""
    boundaries: dict = {}
    for e in decay_epochs:
        b = int(e) * int(steps_per_epoch) // int(accum)
        boundaries[b] = boundaries.get(b, 1.0) * gamma
    return optax.piecewise_constant_schedule(base_lr, boundaries)


def make_optimizer(config: Dict[str, Any], steps_per_epoch: int) -> optax.GradientTransformation:
    """Two-group Adam(+L2) with MultiStepLR, matching the reference groups
    {backbone: lr.backbone_lr, decoder: lr.decoder_lr} and lr.weight_decay.

    training.grad_accum_steps > 1 wraps the whole thing in optax.MultiSteps
    (no reference equivalent — SURVEY.md section 2c "Gradient accumulation:
    NO"; added because one v5e chip caps the per-step batch at B<=4 at LLFF
    shapes, BENCH_NOTES_r02.md): every micro-batch goes through the normal
    train_step, updates are emitted every k-th call with mean gradients,
    and state.step stays in micro-batch units everywhere (logging,
    checkpoint cadence, resume epoch math, current_lrs). The inner LR
    schedule ticks once per OPTIMIZER step, so its epoch boundaries are
    rescaled by 1/k to stay aligned with micro-step epochs. BN statistics
    remain per micro-batch (the standard accumulation trade)."""
    wd = float(config.get("lr.weight_decay", 0.0))
    gamma = float(config.get("lr.decay_gamma", 0.1))
    decay_epochs = config.get("lr.decay_steps", [])
    accum = int(config.get("training.grad_accum_steps", 1))
    assert accum >= 1, accum

    def group(base_lr: float) -> optax.GradientTransformation:
        return optax.chain(
            optax.add_decayed_weights(wd),
            optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8),
            optax.scale_by_learning_rate(
                multistep_lr(base_lr, decay_epochs, gamma,
                             steps_per_epoch, accum=accum)),
        )

    def label_fn(params):
        return {k: k for k in params}  # top-level keys: backbone / decoder

    tx = optax.multi_transform(
        {"backbone": group(float(config["lr.backbone_lr"])),
         "decoder": group(float(config["lr.decoder_lr"]))},
        label_fn)
    if accum > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=accum)
    return tx


def create_train_state(model, config: Dict[str, Any], steps_per_epoch: int,
                       sample_img, sample_disparity, seed: int = 0) -> TrainState:
    """Initialize params/batch_stats and the optimizer state."""
    init_key, state_key = jax.random.split(jax.random.PRNGKey(seed))
    variables = model.init(init_key, sample_img, sample_disparity, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = make_optimizer(config, steps_per_epoch)
    opt_state = tx.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32),
                      params=params,
                      batch_stats=batch_stats,
                      opt_state=opt_state,
                      rng=state_key,
                      guard=make_guard_buffer())


def current_lrs(config: Dict[str, Any], steps_per_epoch: int, step: int):
    """Host-side LR readback for logging (reference logs encoder lr,
    synthesis_task.py:572). `step` is the micro-step clock (state.step);
    with grad accumulation the decay lands on the optimizer-step boundary
    e*spe//accum, which corresponds to micro-step (e*spe//accum)*accum —
    mirrored here so the logged LR always equals the applied one."""
    gamma = float(config.get("lr.decay_gamma", 0.1))
    decay_epochs = config.get("lr.decay_steps", [])
    accum = int(config.get("training.grad_accum_steps", 1))
    lrs = {}
    for name, key in (("backbone", "lr.backbone_lr"), ("decoder", "lr.decoder_lr")):
        lr = float(config[key])
        for e in decay_epochs:
            # piecewise_constant_schedule applies the scale for counts >=
            # boundary (empirically: sched(boundary) is already decayed);
            # the optimizer count at micro-step `step` is step // accum
            if step // accum >= int(e) * steps_per_epoch // accum:
                lr *= gamma
        lrs[name] = lr
    return lrs
