from mine_tpu.train.state import TrainState, create_train_state  # noqa: F401
from mine_tpu.train.step import SynthesisTrainer  # noqa: F401
