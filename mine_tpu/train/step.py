"""Jitted train/eval steps over a device mesh.

One `train_step` = forward (encoder + disparity-conditioned decoder, with
optional coarse-to-fine), all 4 loss scales, backward, and the two-group Adam
update — a single XLA program (the reference runs this as separate eager
stages, synthesis_task.py:604-615). Data parallelism is the sharded batch
axis; the gradient all-reduce the reference got from DDP and the SyncBN
statistics both fall out of GSPMD on the ("data", "plane") mesh.

RNG: the reference samples disparities with unseeded global RNG per step
(rendering_utils.py:86); here every step folds the state's PRNG key with the
step counter — reproducible and resumable by construction.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mine_tpu import geometry
from mine_tpu.config import (MPIConfig, mpi_config_from_dict,
                             pipeline_config_from_dict,
                             validate_model_shapes)
from mine_tpu.models.mpi import MPIPredictor
from mine_tpu.ops import rendering, sampling
from mine_tpu.parallel import mesh as mesh_lib
from mine_tpu.testing import faults
from mine_tpu.train import resilience
from mine_tpu.train.loss import (compute_losses, loss_from_rendered,
                                 render_all_scales)
from mine_tpu.train.state import (GUARD_CONSEC, GUARD_LAST_BAD, GUARD_SKIPPED,
                                  TrainState, create_train_state,
                                  make_optimizer)


def _remat_policy(value):
    """training.remat -> (enabled, jax.checkpoint policy).

    false/"none": no remat; true/"full": save nothing (recompute the whole
    model forward in backward); "dots": save MXU results (recompute only
    elementwise work — the usual TPU sweet spot); "dots_no_batch": the
    variant excluding batch dims (finer-grained memory saving).
    """
    if value in (False, None, "none", "false"):
        return False, None
    if value in (True, "full", "true"):
        return True, None  # jax.checkpoint default: save nothing
    policies = {
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch":
            jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    }
    if value not in policies:
        raise ValueError(
            f"training.remat must be false|true|dots|dots_no_batch, "
            f"got {value!r}")
    return True, policies[value]


def sample_disparity(key: jax.Array, batch_size: int, cfg: MPIConfig) -> jnp.ndarray:
    """Coarse plane disparities for one step (synthesis_task._get_disparity_list
    :31-60): stratified per-bin samples, explicit bin edges when provided,
    or a fixed linspace when mpi.fix_disparity."""
    S = cfg.num_bins_coarse
    has_list = len(cfg.disparity_list) == S + 1
    if cfg.fix_disparity:
        if has_list:
            d = jnp.asarray(cfg.disparity_list[1:], jnp.float32)
            return jnp.broadcast_to(d[None], (batch_size, S))
        return sampling.fixed_disparity_linspace(
            batch_size, S, cfg.disparity_start, cfg.disparity_end)
    if has_list:
        return sampling.uniformly_sample_disparity_from_bins(
            key, batch_size, np.asarray(cfg.disparity_list, np.float32))
    return sampling.uniformly_sample_disparity_from_linspace_bins(
        key, batch_size, S, cfg.disparity_start, cfg.disparity_end)


class SynthesisTrainer:
    """Owns the model + optimizer and builds the jitted step functions.

    The reference's SynthesisTask god-object (synthesis_task.py:63-670) is
    split: this class is the step compiler; the host loop (logging, eval
    cadence, checkpointing) lives in mine_tpu.train.loop.
    """

    def __init__(self, config: Dict[str, Any],
                 mesh=None,
                 steps_per_epoch: int = 1000,
                 lpips_params=None,
                 compiler_options: Optional[Dict[str, Any]] = None):
        self.config = config
        self.cfg = mpi_config_from_dict(config)
        self.mesh = mesh
        self.steps_per_epoch = steps_per_epoch
        validate_model_shapes(self.cfg)

        # Pallas backends compose with multi-device meshes via shard_map
        # (ops/rendering.py, ops/warp.py): warp splits B*S over data*plane,
        # composite batches over "data" with the plane axis gathered.

        dtype_name = config.get("training.dtype", "bfloat16")
        dtype = {"bfloat16": jnp.bfloat16, "float32": None}[dtype_name]
        self.model = MPIPredictor(
            num_layers=self.cfg.num_layers,
            pos_encoding_multires=self.cfg.pos_encoding_multires,
            use_alpha=self.cfg.use_alpha,
            sigma_dropout_rate=self.cfg.sigma_dropout_rate,
            dtype=dtype,
            mesh=mesh if (mesh is not None and mesh.size > 1) else None,
            plane_chunks=int(config.get("training.decoder_plane_chunks", 1)),
            decoder_variant=str(config.get("model.decoder_variant",
                                           "reference")))
        chunks = self.model.plane_chunks
        if chunks > 1:
            # fail at construction, not as a silent unchunked (full-B*S HBM)
            # run or an opaque GSPMD sharding error on the chip — the r2
            # grant wedge was exactly that footprint
            if self.cfg.num_bins_coarse % chunks != 0:
                raise ValueError(
                    f"training.decoder_plane_chunks={chunks} must divide "
                    f"mpi.num_bins_coarse={self.cfg.num_bins_coarse}")
            plane = mesh.shape.get(mesh_lib.PLANE_AXIS, 1) if mesh else 1
            if plane > 1 and (self.cfg.num_bins_coarse // chunks) % plane:
                raise ValueError(
                    f"chunk size {self.cfg.num_bins_coarse // chunks} "
                    f"(= mpi.num_bins_coarse/{chunks}) must be divisible "
                    f"by the mesh plane axis ({plane}) so each chunk's "
                    f"B*S block still shards over ('data','plane')")
        self.remat, self.remat_policy = _remat_policy(
            config.get("training.remat", False))
        self.grad_accum_steps = int(config.get("training.grad_accum_steps", 1))
        assert self.grad_accum_steps >= 1, self.grad_accum_steps
        self.tx = make_optimizer(config, steps_per_epoch)
        self.lpips_params = lpips_params
        # Non-finite step guard (training.guard_nonfinite, default on): the
        # all-finite check and zero-update swap are traced INTO the step —
        # no extra host sync, guard counters ride in TrainState.guard and
        # surface through the (already log-cadence-synced) metrics.
        self.guard_nonfinite = bool(config.get("training.guard_nonfinite",
                                               True))
        # Per-layer-group training telemetry (training.layer_stats, default
        # off): per-group grad norms, update-to-weight ratios, and plane
        # alpha distribution summaries, computed INSIDE the jitted step as
        # scalar metrics. They ride the existing log-cadence metrics
        # readback — zero additional host syncs (the transfer_guard audit
        # pass runs with this enabled), and no new dot_generals (norms and
        # moments are elementwise + reductions), so dot budgets are
        # unchanged.
        self.layer_stats = bool(config.get("training.layer_stats", False))
        # Fault injection is resolved at TRACE time (set the plan before
        # constructing the trainer): None in production, so the injected
        # jnp.where never enters the compiled program.
        self._nan_grad_window = faults.nan_grad_window()

        # compiler_options reach every jitted step — the multichip dry run
        # certifies CORRECTNESS of the sharded programs on a single-core
        # CPU host and passes xla_backend_optimization_level=0 there (the
        # SPMD partitioner and numerics are unaffected; only backend
        # codegen effort drops, ~2.3x faster compiles). None for training.
        jit = functools.partial(jax.jit, compiler_options=compiler_options) \
            if compiler_options else jax.jit
        # training.donate_batch: also donate the BATCH buffers to the train
        # step, so XLA reuses the staged input memory instead of holding
        # both the live batch and the step's workspace. Valid only when
        # every step gets a freshly staged batch (the async input pipeline,
        # train/loop.py + data/pipeline.py); callers that re-feed one
        # resident batch (bench.py's device-step variants, overfit tests)
        # must leave it off or the second call hits deleted buffers.
        donate_train = (0, 1) if bool(
            config.get("training.donate_batch", False)) else (0,)
        if mesh is not None:
            batch_s = mesh_lib.batch_sharding(mesh)
            repl = mesh_lib.replicated(mesh)
            self._train_step = jit(self._train_step_impl,
                                   in_shardings=(repl, batch_s),
                                   out_shardings=(repl, repl),
                                   donate_argnums=donate_train)
            self._eval_step = jit(self._eval_step_impl,
                                  in_shardings=(repl, batch_s, repl),
                                  out_shardings=repl)
            # padded remainder batches: same collective shape as _eval_step
            # plus a [B] 0/1 validity weight sharded with the batch — every
            # host participates (lockstep) and padding examples are excluded
            # exactly from the weighted metric means
            self._eval_step_masked = jit(
                self._eval_step_masked_impl,
                in_shardings=(repl, batch_s, repl, batch_s),
                out_shardings=repl)
        else:
            self._train_step = jit(self._train_step_impl,
                                   donate_argnums=donate_train)
            self._eval_step = jit(self._eval_step_impl)
            self._eval_step_masked = jit(self._eval_step_masked_impl)
        # Encode-once eval (serve.eval_encode_once, train/loop.py run_eval):
        # the eval step split into its two halves so the host loop can cache
        # the encode per DISTINCT source image (serve.PyramidCache) and pay
        # only the loss/render half per (src, tgt) pair. Gated to
        # single-host in the loop; plain jit suffices on mesh>1 too (GSPMD
        # reshards the replicated-state inputs on the fly).
        self._eval_encode = jit(self._eval_encode_impl)
        self._eval_encode_c2f = jit(self._eval_encode_c2f_impl,
                                    static_argnames=("batch_size",))
        self._eval_losses = jit(self._eval_losses_impl)
        self._eval_losses_masked = jit(self._eval_losses_masked_impl)

        # Pipeline-staged training (training.pipeline.*, default off):
        # enabled routes train_step through the staged GPipe-style executor
        # (mine_tpu/parallel/pipeline.py). With enabled=False nothing is
        # constructed and the fused jitted step above runs untouched —
        # bitwise-identical outputs, same-compiled program.
        self.pipeline_cfg = pipeline_config_from_dict(config)
        self._pipeline = None
        if self.pipeline_cfg.enabled:
            from mine_tpu.parallel.pipeline import PipelineExecutor
            self._pipeline = PipelineExecutor(self, self.pipeline_cfg)

    # ---------------- batch geometry ----------------

    def global_batch_size(self) -> int:
        """data.per_gpu_batch_size is per *device on the data axis* (the
        reference's per-GPU batch, train.py:84); the jitted step sees the
        global batch."""
        per_device = int(self.config.get("data.per_gpu_batch_size", 2))
        data_size = self.mesh.shape[mesh_lib.DATA_AXIS] if self.mesh else 1
        return per_device * data_size

    def local_batch_size(self) -> int:
        """Examples each host must feed per step."""
        assert self.global_batch_size() % jax.process_count() == 0
        return self.global_batch_size() // jax.process_count()

    def put_batch(self, np_batch):
        """Host batch -> (possibly multi-host global) device batch, committed
        under the mesh's input sharding (parallel/mesh.put_batch) so the
        jitted step consumes it without a reshard. Called by the train
        loop's DeviceStager from a background thread — keep it free of
        trainer state mutation."""
        return mesh_lib.put_batch(np_batch, self.mesh)

    # ---------------- state ----------------

    def init_state(self, batch_size: int, seed: Optional[int] = None) -> TrainState:
        if seed is None:
            seed = int(self.config.get("training.seed", 0))
        H, W = self.cfg.img_h, self.cfg.img_w
        img = jnp.zeros((batch_size, H, W, 3), jnp.float32)
        disp = jnp.full((batch_size, self.cfg.num_bins_total), 0.5, jnp.float32)
        return create_train_state(self.model, self.config, self.steps_per_epoch,
                                  img, disp, seed=seed)

    # ---------------- forward ----------------

    def _apply_model(self, params, batch_stats, img, disparity, train, drop_key):
        variables = {"params": params, "batch_stats": batch_stats}
        if self.remat and train:
            apply = jax.checkpoint(
                lambda v, i, d: self.model.apply(
                    v, i, d, train=True, mutable=["batch_stats"],
                    rngs={"dropout": drop_key}),
                policy=self.remat_policy)
            return apply(variables, img, disparity)
        if train:
            return self.model.apply(variables, img, disparity, train=True,
                                    mutable=["batch_stats"],
                                    rngs={"dropout": drop_key})
        return self.model.apply(variables, img, disparity, train=False), None

    def _forward(self, params, batch_stats, batch, disparity, fine_key,
                 drop_key, train: bool):
        """Model forward incl. optional coarse-to-fine plane refinement."""
        state = {"bs": batch_stats}

        def predictor(img, disp):
            out, mutated = self._apply_model(params, state["bs"], img, disp,
                                             train, drop_key)
            if mutated is not None:
                state["bs"] = mutated["batch_stats"]
            return out

        if self.cfg.num_bins_fine > 0:
            H, W = batch["src_img"].shape[1:3]
            grid = geometry.cached_pixel_grid(H, W)
            K_src_inv = geometry.inverse_intrinsics(batch["K_src"])
            xyz_coarse = geometry.plane_xyz_src(grid, disparity, K_src_inv)
        else:
            xyz_coarse = None
        mpi_list, disparity_all = rendering.predict_mpi_coarse_to_fine(
            predictor, fine_key, batch["src_img"], xyz_coarse, disparity,
            self.cfg.num_bins_fine, self.cfg.is_bg_depth_inf)
        return mpi_list, disparity_all, state["bs"]

    # ---------------- steps ----------------

    def _grads_and_metrics(self, state: TrainState, batch, key):
        """One micro-batch's (grads, metrics, new_batch_stats)."""
        d_key, f_key, drop_key = jax.random.split(key, 3)
        B = batch["src_img"].shape[0]
        disparity = sample_disparity(d_key, B, self.cfg)

        def loss_fn(params):
            mpi_list, disparity_all, new_stats = self._forward(
                params, state.batch_stats, batch, disparity, f_key, drop_key,
                train=True)
            total, metrics, _ = compute_losses(
                mpi_list, disparity_all, batch, self.cfg, mesh=self.mesh)
            if self.layer_stats:
                # plane content health at the full-resolution scale: alpha
                # collapse (everything transparent/opaque) is the classic
                # silent MPI failure mode — [B,S,4,h,w], channel 3 = alpha.
                # optimization_barrier keeps the stat reductions from
                # CSE/fusing with the loss graph: the numeric step must be
                # bitwise-identical with layer_stats on or off
                with jax.named_scope("layer_stats_planes"):
                    # stop_gradient lowers the AD tracer to its primal
                    # (optimization_barrier has no differentiation rule)
                    mpi0 = jax.lax.optimization_barrier(
                        jax.lax.stop_gradient(mpi_list[0]))
                    alpha = mpi0[:, :, 3].astype(jnp.float32)
                    metrics = dict(
                        metrics,
                        **{"layers/planes.alpha_mean": jnp.mean(alpha),
                           "layers/planes.alpha_std": jnp.std(alpha),
                           "layers/planes.alpha_sat_lo":
                               jnp.mean((alpha < 0.01).astype(jnp.float32)),
                           "layers/planes.alpha_sat_hi":
                               jnp.mean((alpha > 0.99).astype(jnp.float32))})
            return total, (metrics, new_stats)

        (_, (metrics, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        return grads, metrics, new_stats

    # ---------------- staged sub-programs (pipeline path) ----------------
    # The fused step above, cut at its natural seams: encoder -> decoder ->
    # warp/composite -> fused loss. Each is a pure function of explicit
    # param/stat subtrees, so the pipeline executor
    # (mine_tpu/parallel/pipeline.py) can jit, place, and differentiate
    # them independently, and analysis/programs.py registers each with its
    # own dot/cost baseline row. Restricted to mpi.num_bins_fine == 0 (the
    # coarse-to-fine refinement re-enters the model mid-render and has no
    # stage boundary); the executor enforces that.

    def stage_encode(self, backbone_params, backbone_stats, src_img,
                     drop_key):
        """Encoder stage: src images -> backbone feature pyramid.
        Returns (feats, new_backbone_stats). Flax resolves the partial
        {"backbone": ...} subtrees lazily, so only the backbone's
        params/stats ever live on this stage's devices."""
        feats, mut = self.model.apply(
            {"params": {"backbone": backbone_params},
             "batch_stats": {"backbone": backbone_stats}},
            src_img, True, method="encode", mutable=["batch_stats"],
            rngs={"dropout": drop_key})
        return feats, mut["batch_stats"]["backbone"]

    def stage_decode(self, decoder_params, decoder_stats, feats, disparity,
                     drop_key):
        """Decoder stage: feature pyramid + disparity -> 4-scale MPI list.
        Returns (mpi_list, new_decoder_stats). The dropout rng folds the
        same module path as the fused apply, so sigma-dropout masks match
        the fused step exactly."""
        mpi_list, mut = self.model.apply(
            {"params": {"decoder": decoder_params},
             "batch_stats": {"decoder": decoder_stats}},
            list(feats), disparity, True, method="decode",
            mutable=["batch_stats"], rngs={"dropout": drop_key})
        return mpi_list, mut["batch_stats"]["decoder"]

    def stage_render(self, mpi_list, disparity, batch, mesh=None):
        """Warp/composite stage: the render half of all 4 loss scales
        (train/loss.render_all_scales) -> list of per-scale rendered
        pytrees, the boundary the loss stage's cotangent flows back
        through."""
        return render_all_scales(mpi_list, disparity, batch, self.cfg,
                                 mesh=mesh)

    def stage_loss(self, rendered, batch):
        """Fused-loss stage: loss terms + cross-scale aggregation over the
        rendered pytrees -> (total, metrics)."""
        total, metrics, _ = loss_from_rendered(rendered, batch, self.cfg)
        return total, metrics

    def _train_step_impl(self, state: TrainState, batch) -> Tuple[TrainState, Dict]:
        key = jax.random.fold_in(state.rng, state.step)
        grads, metrics, new_stats = self._grads_and_metrics(state, batch, key)
        return self._apply_update(state, grads, metrics, new_stats)

    def _apply_update(self, state: TrainState, grads, metrics,
                      new_stats) -> Tuple[TrainState, Dict]:
        """Optimizer update + non-finite guard + layer telemetry over
        already-computed (possibly pipeline-accumulated) gradients. The
        fused step traces this inline; the pipeline executor jits it as its
        own update program — one body, so both paths apply the identical
        update/guard/metrics semantics."""
        if self._nan_grad_window is not None:
            # chaos-test seam: poison the gradients at the planned step(s);
            # absent a plan this branch is not traced at all
            at_step, from_step = self._nan_grad_window
            poison = jnp.zeros((), bool)
            if at_step >= 0:
                poison |= state.step == at_step
            if from_step >= 0:
                poison |= state.step >= from_step
            grads = jax.tree_util.tree_map(
                lambda g: jnp.where(poison, jnp.asarray(jnp.nan, g.dtype), g),
                grads)
        with jax.named_scope("adam_update"):
            updates, new_opt_state = self.tx.update(grads, state.opt_state,
                                                    state.params)
            new_params = optax.apply_updates(state.params, updates)
        guard = state.guard
        if self.guard_nonfinite:
            with jax.named_scope("nonfinite_guard"):
                gnorm = optax.global_norm(grads)
                ok = jnp.isfinite(metrics["loss"]) & jnp.isfinite(gnorm)
                # poisoned step -> zero-update: keep the old params /
                # opt_state / batch_stats (step still advances, so the RNG
                # stream and cadences stay aligned with an unpoisoned run)
                new_params = resilience.select_tree(ok, new_params,
                                                    state.params)
                new_opt_state = resilience.select_tree(ok, new_opt_state,
                                                       state.opt_state)
                new_stats = resilience.select_tree(ok, new_stats,
                                                   state.batch_stats)
                bad = (~ok).astype(jnp.int32)
                skipped = state.guard[GUARD_SKIPPED] + bad
                consec = (state.guard[GUARD_CONSEC] + bad) * bad
                last_bad = jnp.where(ok, state.guard[GUARD_LAST_BAD],
                                     state.step.astype(jnp.int32))
                guard = jnp.stack([skipped, consec, last_bad])
                metrics = dict(metrics,
                               grad_norm=gnorm,
                               skipped_steps=skipped,
                               guard_consecutive=consec,
                               guard_last_bad_step=last_bad)
        if self.layer_stats:
            # per-top-level-group (backbone / decoder) optimization health:
            # grad norm, and the update-to-weight ratio that flags a group
            # whose effective learning rate has gone degenerate. Scalars
            # only — they merge into the metrics dict and reach the host
            # exclusively through the log-cadence readback. Placement is
            # deliberate: the numeric step must be bitwise-identical with
            # layer_stats on or off, so the norms only touch values that
            # are materialized either way — grads (whose per-leaf square
            # sums CSE with the nonfinite guard's global norm), the input
            # params, and the POST-guard new_params that the step returns.
            # Consuming the optax `updates` tree (or the pre-guard
            # new_params) re-fuses the adam update and drifts a leaf, so
            # the applied-update norm is taken as ||new - old|| instead —
            # which also truthfully reads 0 on a guard-skipped step.
            with jax.named_scope("layer_stats_groups"):
                layer_metrics = {}
                for group in state.params:
                    gn = optax.global_norm(grads[group])
                    un = optax.global_norm(jax.tree_util.tree_map(
                        lambda n, o: n - o, new_params[group],
                        state.params[group]))
                    wn = optax.global_norm(state.params[group])
                    layer_metrics[f"layers/{group}.grad_norm"] = gn
                    layer_metrics[f"layers/{group}.param_norm"] = wn
                    layer_metrics[f"layers/{group}.update_ratio"] = \
                        un / (wn + 1e-12)
                metrics = dict(metrics, **layer_metrics)
        new_state = TrainState(step=state.step + 1,
                               params=new_params,
                               batch_stats=new_stats,
                               opt_state=new_opt_state,
                               rng=state.rng,
                               guard=guard)
        return new_state, metrics

    def _eval_step_impl(self, state: TrainState, batch, eval_key,
                        example_weight=None):
        """Validation step: eval-mode BN, LPIPS at scale 0 when weights are
        available (synthesis_task.py:341-344,476-507)."""
        d_key, f_key = jax.random.split(eval_key)
        B = batch["src_img"].shape[0]
        disparity = sample_disparity(d_key, B, self.cfg)
        mpi_list, disparity_all, _ = self._forward(
            state.params, state.batch_stats, batch, disparity, f_key, None,
            train=False)
        _, metrics, visuals = compute_losses(
            mpi_list, disparity_all, batch, self.cfg, mesh=self.mesh,
            is_val=True, lpips_params=self.lpips_params,
            example_weight=example_weight)
        return metrics, visuals

    def _eval_step_masked_impl(self, state: TrainState, batch, eval_key,
                               example_weight):
        metrics, _ = self._eval_step_impl(state, batch, eval_key,
                                          example_weight)
        return metrics

    def _eval_encode_impl(self, state: TrainState, src_img, disparity):
        """Encode half of the eval step: model forward only (eval-mode BN,
        no coarse-to-fine). Returns the 4-scale MPI pyramid. Configs with
        mpi.num_bins_fine > 0 go through _eval_encode_c2f_impl instead."""
        return self.model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            src_img, disparity, train=False)

    def _eval_encode_c2f_impl(self, state: TrainState, src_img, disparity,
                              fine_key, row, K_src, batch_size: int):
        """Coarse-to-fine encode half for ONE example of a fused eval batch.

        Replays exactly the fine-plane draws the fused _eval_step_impl makes
        for batch row `row`: the uniforms behind sample_pdf are drawn at the
        FULL eval-batch shape (`batch_size` static) from `fine_key` and this
        example's row is sliced out (rendering.predict_mpi_coarse_to_fine
        fine_rows=...), so per-example encode-once metrics match the fused
        batch bit-for-bit in the sampling and to float tolerance overall.
        Returns (mpi_list, disparity_all) — both cacheable per src image.
        """
        def predictor(img, disp):
            return self.model.apply(
                {"params": state.params, "batch_stats": state.batch_stats},
                img, disp, train=False)

        H, W = src_img.shape[1:3]
        grid = geometry.cached_pixel_grid(H, W)
        xyz_coarse = geometry.plane_xyz_src(
            grid, disparity, geometry.inverse_intrinsics(K_src))
        return rendering.predict_mpi_coarse_to_fine(
            predictor, fine_key, src_img, xyz_coarse, disparity,
            self.cfg.num_bins_fine, self.cfg.is_bg_depth_inf,
            fine_rows=(batch_size, row))

    def _eval_losses_impl(self, state: TrainState, mpi_list, disparity_all,
                          batch, example_weight=None):
        """Render+loss half of the eval step, fed a (possibly cache-replayed)
        MPI pyramid instead of re-running the encoder."""
        del state  # same call signature family as the other eval steps
        _, metrics, visuals = compute_losses(
            mpi_list, disparity_all, batch, self.cfg, mesh=self.mesh,
            is_val=True, lpips_params=self.lpips_params,
            example_weight=example_weight)
        return metrics, visuals

    def _eval_losses_masked_impl(self, state: TrainState, mpi_list,
                                 disparity_all, batch, example_weight):
        metrics, _ = self._eval_losses_impl(state, mpi_list, disparity_all,
                                            batch, example_weight)
        return metrics

    # ---------------- public API ----------------

    def train_step(self, state: TrainState, batch) -> Tuple[TrainState, Dict]:
        if self._pipeline is not None:
            return self._pipeline.step(state, batch)
        return self._train_step(state, batch)

    def eval_step(self, state: TrainState, batch, eval_key):
        return self._eval_step(state, batch, eval_key)

    def eval_step_masked(self, state: TrainState, batch, eval_key,
                         example_weight):
        """Collective eval for padded remainder batches: `example_weight`
        [global_B] is 1 for real examples, 0 for padding; metrics come back
        as weighted means over the real examples only (no dropped val
        examples on any host count — VERDICT r2 weak item 4)."""
        return self._eval_step_masked(state, batch, eval_key, example_weight)

    def eval_encode(self, state: TrainState, src_img, disparity):
        """[B,H,W,3] src + [B,S] disparity -> 4-scale MPI pyramid (list of
        [B,S,4,h,w]); the cacheable half of the encode-once eval path."""
        return self._eval_encode(state, src_img, disparity)

    def eval_encode_c2f(self, state: TrainState, src_img, disparity,
                        fine_key, row, K_src, batch_size: int):
        """Coarse-to-fine encode of eval-batch row `row` (1-example inputs;
        `batch_size` is the FULL fused batch size, static). Returns
        (mpi_list, disparity_all) matching the fused eval step's fine-plane
        RNG for that row — the encode-once path for num_bins_fine > 0."""
        return self._eval_encode_c2f(state, src_img, disparity, fine_key,
                                     jnp.asarray(row, jnp.int32), K_src,
                                     batch_size=batch_size)

    def eval_losses(self, state: TrainState, mpi_list, disparity_all, batch):
        return self._eval_losses(state, mpi_list, disparity_all, batch)

    def eval_losses_masked(self, state: TrainState, mpi_list, disparity_all,
                           batch, example_weight):
        return self._eval_losses_masked(state, mpi_list, disparity_all,
                                        batch, example_weight)

    def put_example_array(self, v):
        """[local_B,...] host array -> global batch-sharded device array."""
        if self.mesh is None or jax.process_count() == 1:
            return jnp.asarray(v)
        return jax.make_array_from_process_local_data(
            mesh_lib.batch_sharding(self.mesh), v)
