"""Host training loop: epochs, logging, eval cadence, checkpointing.

The driver half of the reference's SynthesisTask.train/train_epoch/run_eval
(synthesis_task.py:476-670) — same cadences (log every 10 steps, rolling
checkpoint every 5000, eval at step 2000 and every eval_interval with a step
checkpoint), same meters and tensorboard tags, but:
  * the whole step is one jitted call; the loop only feeds batches and logs
  * checkpoints carry step+RNG (resume is exact; reference restarts counters)
  * rank gating is jax.process_index()==0 (multi-host single-controller)
"""

from __future__ import annotations

import itertools
import os
import time
from collections import deque
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mine_tpu import telemetry
from mine_tpu.config import (resilience_config_from_dict,
                             serve_config_from_dict,
                             telemetry_config_from_dict)
from mine_tpu.data.common import PIPELINE_STATS, RetryPolicy, set_retry_policy
# prefetch is re-exported here for backward compatibility; it moved to the
# input-pipeline module alongside the threaded assembler + device stager
from mine_tpu.data.pipeline import DeviceStager, StagedBatch, prefetch  # noqa: F401
from mine_tpu.serve import PyramidCache, image_id_for
from mine_tpu.testing import faults
from mine_tpu.train import resilience
from mine_tpu.train.checkpoint import CheckpointManager
from mine_tpu.train.state import TrainState, current_lrs
from mine_tpu.train.step import SynthesisTrainer, sample_disparity
from mine_tpu.utils import AverageMeter, disparity_normalization_vis, metrics_to_float

TRAIN_METER_KEYS = ("loss", "loss_rgb_src", "loss_ssim_src",
                    "loss_disp_pt3dsrc", "loss_rgb_tgt", "loss_ssim_tgt",
                    "lpips_tgt", "psnr_tgt", "loss_disp_pt3dtgt")

# host-side step-time breakdown (milliseconds, averaged per log interval):
#   step       wall-clock per step
#   host_wait  blocked waiting for the NEXT staged batch (host-bound time)
#   device     step minus host_wait (device compute + dispatch backpressure)
#   h2d        host->device copy of the step's batch, measured in the
#              stager thread (overlapped with compute unless host-bound)
# Printed per log interval as the FROZEN st1 step-time line
# (telemetry/stepline.py) and mirrored into the telemetry registry's
# train.* histograms + the JSONL event stream ("train.step" events).
TIME_METER_KEYS = ("step_ms", "host_wait_ms", "device_ms", "h2d_ms")


class TrainLoop:
    def __init__(self, trainer: SynthesisTrainer,
                 train_dataset, val_dataset,
                 workspace: str,
                 logger=None,
                 tb_writer=None):
        self.trainer = trainer
        self.config = trainer.config
        self.train_dataset = train_dataset
        self.val_dataset = val_dataset
        self.logger = logger
        self.tb = tb_writer
        self._tb_broken = False  # a failing TB writer degrades, not kills
        self.resil = resilience_config_from_dict(self.config)
        self.ckpt = CheckpointManager(
            workspace,
            mirror_cmd=str(self.config.get("training.checkpoint_mirror_cmd",
                                           "") or ""),
            keep=self.resil.checkpoint_keep,
            logger=logger)
        set_retry_policy(RetryPolicy(
            max_item_retries=self.resil.max_item_retries,
            backoff_s=self.resil.item_retry_backoff))
        # SIGTERM/SIGINT -> emergency checkpoint at the next cadence
        # boundary; all hosts agree via resilience.global_any before the
        # collective save (installed for the duration of run())
        self.preempt = resilience.PreemptionHandler(logger)
        self.preempted = False
        self.guard_monitor = resilience.GuardMonitor(
            self.resil.guard_skip_threshold
            if self.resil.guard_nonfinite else 0, logger)

        self.is_lead = jax.process_index() == 0
        self.train_meters = {k: AverageMeter("train_" + k)
                             for k in TRAIN_METER_KEYS}
        self.val_meters = {k: AverageMeter("val_" + k)
                           for k in TRAIN_METER_KEYS}
        self.time_meters = {k: AverageMeter("time_" + k, ":.1f")
                            for k in TIME_METER_KEYS}

        # --- input pipeline knobs (see data/pipeline.py) ---
        # data.num_workers: assembler threads (0 = synchronous, the
        # reference's num_workers=0 semantics); batches are identical for
        # any worker count (counter-based per-item PRNG in data/common.py)
        self.num_workers = int(self.config.get("data.num_workers", 0) or 0)
        # bounded host-side queue depth of assembled numpy batches
        self.prefetch_batches = max(1, int(
            self.config.get("data.prefetch_batches", 2)))
        # device-resident staged batches in flight; >=2 overlaps the H2D
        # copy of batch k+1 with compute of step k, <=1 stages on the
        # training thread (synchronous, for debugging/A-B)
        self.staging_buffers = int(self.config.get("data.staging_buffers", 2))

        # meters update at log steps only (pulling metrics to host every
        # step would sync the device pipeline); clamp so epochs shorter
        # than the interval still log/meter instead of averaging nothing
        self.log_interval = max(1, min(
            int(self.config.get("training.log_interval", 10)),
            trainer.steps_per_epoch))
        self.ckpt_interval = int(self.config.get("training.checkpoint_interval", 5000))
        self.eval_interval = int(self.config.get("training.eval_interval", 10000))
        # per-host examples per step (per_gpu_batch_size x data-axis devices,
        # split across hosts); the jitted step sees the global batch
        self.local_batch_size = trainer.local_batch_size()
        self.seed = int(self.config.get("training.seed", 0))

        # --- encode-once eval (serve.eval_encode_once; README "Serving") ---
        # Encode each DISTINCT val source image once per eval and replay its
        # cached MPI pyramid for every target view — the eval-loop face of
        # the serving engine's encode/render asymmetry. Restricted to runs
        # where the split eval step needs no collectives and the pyramid is
        # a pure function of (src, disparity): otherwise fall back to the
        # fused eval_step with a logged reason.
        # --- telemetry (mine_tpu/telemetry; README "Observability") ---
        # events: low-frequency JSONL records (step-time at log cadence,
        # checkpoint spans, guard aborts, profiler windows); metrics: the
        # process registry obs_report/serve share. An outer harness that
        # exported MINE_TPU_TELEMETRY_EVENTS keeps owning the stream.
        self.telem = telemetry_config_from_dict(self.config)
        if self.telem.enabled:
            telemetry.ensure_configured(
                self.telem.events_path
                or os.path.join(workspace, "events.jsonl"),
                max_mb=self.telem.events_max_mb,
                keep=self.telem.events_keep)
        # flight recorder (telemetry.recorder.*, default off): black-box
        # rings fed at log cadence below; triggers on guard aborts (via
        # the events tee), preemption shutdown and data-error bursts
        # (explicit hooks), and SIGUSR2. Lead host only — one bundle
        # stream per run, like the profiler windows.
        self.recorder = None
        if (self.telem.enabled and self.telem.recorder_enabled
                and jax.process_index() == 0):
            self.recorder = telemetry.recorder.configure(
                self.telem.recorder_dir
                or os.path.join(workspace, "incidents"),
                events_tail=self.telem.recorder_events,
                steplines=self.telem.recorder_steplines,
                snapshots=self.telem.recorder_snapshots,
                debounce_s=self.telem.recorder_debounce_s,
                keep=self.telem.recorder_keep,
                arm_profile_steps=self.telem.recorder_arm_profile_steps,
                config=dict(self.config))
            self.recorder.install_sigusr2()
        # opt-in process-vitals gauges (telemetry.resource_sample_s)
        self._resource = telemetry.ResourceSampler(
            self.telem.resource_sample_s if self.telem.enabled else 0.0)
        # opt-in jax.profiler window over an exact step range, lead host
        # only (a per-host trace dir free-for-all helps nobody)
        self.profile = telemetry.ProfileWindow(
            self.telem.profile_steps if (self.telem.enabled
                                         and jax.process_index() == 0)
            else (),
            self.telem.profile_dir or os.path.join(workspace, "profile"),
            logger)

        # --- train-side ops plane (training.ops_port, default off) ---
        # The serve stack's OpsServer reused for training: /metrics (the
        # shared registry), /healthz (degraded on a live guard-skip streak
        # or data errors burning in the last log interval), /progress
        # (step/epoch position + ETA from the st1 step-time history). Lead
        # host only. The handlers read only this host-side state dict,
        # which is written at log cadence — the server can never add a
        # device sync, and with the port at 0 nothing is constructed, so
        # training outputs are bitwise identical on vs off.
        self.ops_port = int(self.config.get("training.ops_port", 0) or 0)
        self._ops = None
        self._step_hist = deque(maxlen=64)  # recent step_ms, log cadence
        self._ops_state = {"gstep": 0, "epoch": 0, "epochs": 0,
                           "guard_consecutive": 0.0, "data_errors": 0,
                           "data_errors_delta": 0}

        self.serve_cfg = serve_config_from_dict(self.config)
        self.eval_encode_once = bool(self.serve_cfg.eval_encode_once)
        if self.eval_encode_once:
            # Single remaining gate: multi-host (the split eval halves would
            # need collectives). Single-host mesh>1 works — the plain-jit
            # eval halves let GSPMD reshard on the fly — and num_bins_fine>0
            # goes through trainer.eval_encode_c2f, which replays the fused
            # step's fine-plane draws per example (train/step.py).
            if jax.process_count() > 1:
                self.eval_encode_once = False
                self._log("serve.eval_encode_once disabled: %s",
                          "multi-host run (eval steps are collective)")

    # ---------------- top-level ----------------

    def run(self, state: Optional[TrainState] = None,
            epochs: Optional[int] = None) -> TrainState:
        if state is None:
            state = self.trainer.init_state(self.trainer.global_batch_size())
        # Resume is attempted for a PASSED state too — train_cli always
        # passes one (it may carry pretrained weights), and gating restore
        # on `state is None` silently restarted CLI runs from scratch
        # (caught by the r5 on-TPU soak's kill/resume leg). A workspace
        # checkpoint outranks pretrained init, like the reference's
        # resume-from-workspace flow (synthesis_task.py:121-136).
        restored = self.ckpt.restore(state)
        if restored is not None:
            state = restored
            self._log("Resumed from checkpoint at step %d" % int(state.step))

        epochs = epochs or int(self.config.get("training.epochs", 1))
        steps_per_epoch = self.trainer.steps_per_epoch
        start_epoch = int(state.step) // steps_per_epoch + 1

        self._ops_state.update(epochs=epochs, gstep=int(state.step),
                               epoch=start_epoch)
        if self.recorder is not None:
            self.recorder.add_state_provider(
                "train", lambda: dict(self._ops_state))
        if self.ops_port and self.is_lead:
            self._ops = telemetry.OpsServer(
                port=self.ops_port, health=self._train_health,
                progress=self._train_progress,
                incidents=(self.recorder.list_incidents
                           if self.recorder is not None else None)).start()
            self._log("train ops endpoint at %s" % self._ops.url)

        self.preempt.install()
        try:
            for epoch in range(start_epoch, epochs + 1):
                state = self.train_epoch(state, epoch)
                if not self.preempted and self.preempt.global_requested():
                    self.preempted = True
                if self.preempted:
                    break
                if self.is_lead:
                    self._log("Epoch %d finished, average losses:" % epoch)
                    for m in self.train_meters.values():
                        self._log("    %s" % m)
                    if self.time_meters["step_ms"].count:
                        self._log("Epoch %d step-time breakdown (ms):" % epoch)
                        for m in self.time_meters.values():
                            self._log("    %s" % m)
            # final save: runs shorter than checkpoint_interval otherwise
            # leave NO checkpoint_latest at all — the fixture end-to-end
            # chain dies at eval and a killed short run has nothing to
            # resume from (advisor r5; collective, every process
            # participates). Under preemption this IS the emergency
            # checkpoint.
            self.ckpt.save_latest(state)
            self._log("%s checkpoint saved at step %d"
                      % ("Preemption" if self.preempted else "Final",
                         int(state.step)))
            self.ckpt.wait()
            if self.preempted and self.recorder is not None:
                # preemption-shutdown trigger: the emergency checkpoint is
                # on disk, so the bundle captures the final state the
                # resumed run will diff against (sync: the process is
                # about to exit — the worker thread might not get there)
                self.recorder.trigger("train.preempted",
                                      gstep=int(state.step))
        finally:
            self.preempt.uninstall()
            self.profile.stop()  # a window whose stop step never arrived
            if self._ops is not None:
                self._ops.close()  # join before the thread-leak tripwire
                self._ops = None
            self._resource.close()
            # one end-of-run registry snapshot into the event stream so
            # obs_report sees final counter values without scraping logs
            telemetry.emit(
                "metrics.snapshot", scope="train.run_end",
                gstep=int(state.step),
                metrics=telemetry.REGISTRY.snapshot())
            if self.recorder is not None:
                # after the snapshot emit: the tee puts it in any
                # triggered-but-pending bundle's tail, then the worker
                # joins here
                telemetry.recorder.release(self.recorder)
                self.recorder = None
        return state

    # ---------------- epoch ----------------

    def _epoch_host_batches(self, epoch: int):
        """Numpy-batch iterator for one epoch: the multi-worker assembler
        when the loader supports it (all in-repo loaders route
        batch_iterator through data/common.iterate_pair_batches), else the
        loader's own iterator behind a single prefetch thread."""
        kwargs = dict(batch_size=self.local_batch_size,
                      shuffle=True,
                      seed=self.seed,
                      epoch=epoch,
                      drop_last=True,
                      shard_index=jax.process_index(),
                      num_shards=jax.process_count())
        try:
            return self.train_dataset.batch_iterator(
                workers=self.num_workers,
                prefetch_batches=self.prefetch_batches, **kwargs)
        except TypeError:  # out-of-tree loader without pipeline kwargs
            return prefetch(self.train_dataset.batch_iterator(**kwargs),
                            depth=self.prefetch_batches)

    def _staged_batches(self, host_batches):
        """StagedBatch iterator: background double-buffered device staging
        (data/pipeline.DeviceStager), or on-thread staging when
        data.staging_buffers <= 1 (the synchronous A/B reference)."""
        if self.staging_buffers >= 2:
            return iter(DeviceStager(host_batches, self.trainer.put_batch,
                                     depth=self.staging_buffers))

        def sync():
            for np_batch in host_batches:
                t0 = time.perf_counter()
                batch = self.trainer.put_batch(np_batch)
                jax.block_until_ready(batch)
                yield StagedBatch(batch, (time.perf_counter() - t0) * 1e3)
        return sync()

    def train_epoch(self, state: TrainState, epoch: int) -> TrainState:
        for m in self.train_meters.values():
            m.reset()
        for m in self.time_meters.values():
            m.reset()

        # gstep is tracked on the HOST (the jitted step increments
        # state.step by exactly 1): reading int(state.step) every
        # iteration would block on the step's completion and serialize
        # device compute with the host feed — the pre-pipeline loop paid
        # that sync each step. It is reconciled against the device counter
        # at every checkpoint boundary (below), so drift can't silently
        # shift the ckpt/eval cadence after resume.
        gstep = int(state.step)
        host_batches = self._epoch_host_batches(epoch)
        offset = gstep - (epoch - 1) * self.trainer.steps_per_epoch
        if offset > 0:
            # mid-epoch resume: the epoch iterator always starts at batch 0,
            # but the restored step counter is past it — skip the
            # already-trained host batches so the resumed sequence continues
            # exactly where the interrupted run stopped (cheap: skipped
            # batches never reach the device stager)
            self._log("Resuming epoch %d mid-way: skipping %d "
                      "already-trained batches" % (epoch, offset))
            host_batches = itertools.islice(host_batches, offset, None)
        staged = self._staged_batches(host_batches)

        step_in_epoch = offset if offset > 0 else 0
        t_last = time.perf_counter()
        host_wait_s = 0.0
        h2d_ms_acc = 0.0
        steps_since_log = 0
        stage_ms_acc = {}  # pipeline executor's per-stage breakdown
        while True:
            t0 = time.perf_counter()
            try:
                sb = next(staged)
            except StopIteration:
                break
            host_wait_s += time.perf_counter() - t0
            h2d_ms_acc += sb.h2d_ms
            # profiler window edges (telemetry.profile_steps; cheap int
            # compares when disabled): trace starts before step `start`
            # dispatches and stops after step `stop` completes. A flight-
            # recorder dump may ARM a window over the next K steps
            # (telemetry.recorder.arm_profile_steps) — retroactive-ish
            # profiling of an incident's aftermath; an already-armed or
            # active window is never clobbered.
            if self.recorder is not None and not self.profile.enabled:
                k = self.recorder.take_profile_request()
                if k:
                    self.profile = telemetry.ProfileWindow(
                        (gstep + 1, gstep + k),
                        self.profile.trace_dir, self.logger)
            self.profile.maybe_start(gstep + 1)
            state, metrics = self.trainer.train_step(state, sb.batch)
            step_in_epoch += 1
            gstep += 1
            steps_since_log += 1
            pipe = self.trainer._pipeline
            if pipe is not None and pipe.last_stage_ms:
                # host-side wall times the executor already measured — no
                # device sync here beyond what its own timing did
                for k, v in pipe.last_stage_ms.items():
                    stage_ms_acc[k] = stage_ms_acc.get(k, 0.0) + v
            self.profile.maybe_stop(gstep)
            faults.maybe_sigterm(gstep)  # chaos-test seam (no-op unplanned)

            at_log = step_in_epoch % self.log_interval == 0
            if at_log and self.guard_monitor.threshold > 0:
                # abort policy over the replicated guard counters: EVERY
                # host syncs the same two scalars and reaches the same
                # verdict (raising on the lead only would deadlock the
                # others in the next collective)
                with telemetry.host_readback("train.guard_monitor"):
                    gm = {k: float(metrics[k])
                          for k in ("skipped_steps", "guard_consecutive",
                                    "guard_last_bad_step") if k in metrics}
                try:
                    self.guard_monitor.check(gm, gstep)
                except resilience.GuardAbort:
                    # params are still at their last good values (the guard
                    # zero-updates poisoned steps) — save them before dying
                    telemetry.counter("train.guard.aborts").inc()
                    telemetry.emit("train.guard_abort", gstep=gstep, **gm)
                    self.ckpt.save_latest(state)
                    self.ckpt.wait()
                    raise

            if at_log and self.is_lead:
                with telemetry.host_readback("train.log_metrics"):
                    m = metrics_to_float(metrics)  # device sync, log steps only
                dt = (time.perf_counter() - t_last) / steps_since_log
                times = {
                    "step_ms": dt * 1e3,
                    "host_wait_ms": host_wait_s / steps_since_log * 1e3,
                    "h2d_ms": h2d_ms_acc / steps_since_log,
                }
                times["device_ms"] = max(
                    0.0, times["step_ms"] - times["host_wait_ms"])
                stage_ms = {k: v / steps_since_log
                            for k, v in stage_ms_acc.items()}
                self._log_training(epoch, step_in_epoch, gstep, m, times,
                                   stage_ms=stage_ms)
                t_last = time.perf_counter()
                host_wait_s = h2d_ms_acc = 0.0
                steps_since_log = 0
                stage_ms_acc = {}

            # checkpoint saves and eval are collective over the mesh: EVERY
            # process participates (orbax + jit would deadlock otherwise);
            # only logging/TB writes are lead-gated.
            did_pause = False
            if gstep > 0 and gstep % self.ckpt_interval == 0:
                # reconcile the host counter with the device's before the
                # cadence-bearing save (satellite: a drifted counter must
                # not silently shift ckpt/eval cadence after resume)
                dev_step = int(state.step)
                if dev_step != gstep:
                    if self.logger is not None:
                        self.logger.warning(
                            "host step counter drifted (host %d, device %d)"
                            " — reconciling to the device", gstep, dev_step)
                    gstep = dev_step
                self.ckpt.save_latest(state)
                self._log("Latest checkpoint saved at step %d" % gstep)
                did_pause = True
                if self.preempt.global_requested():
                    # all hosts agreed: the boundary save above is the
                    # emergency checkpoint — stop feeding and unwind
                    self.preempted = True
                    self._log("Preemption requested — stopping after the "
                              "step-%d checkpoint" % gstep)
                    break

            if gstep > 0 and (gstep == 2000 or gstep % self.eval_interval == 0) \
                    and self.val_dataset is not None:
                self.run_eval(state)
                self.ckpt.save_step(state)
                did_pause = True
            if did_pause:
                # don't charge checkpoint/eval wall-time to the step
                # breakdown of the next log interval
                t_last = time.perf_counter()
                host_wait_s = h2d_ms_acc = 0.0
                steps_since_log = 0
        return state

    # ---------------- eval ----------------

    def run_eval(self, state: TrainState) -> Dict[str, float]:
        """Full-val-set evaluation (synthesis_task.run_eval :476-507).

        Covers EVERY val example on any host count (reference: train.py:97-99
        drop_last=False). Hosts must make the same number of collective
        eval_step calls or the mesh jit deadlocks; stride-sharding is
        deterministic, so every host computes every host's batch counts
        locally and agrees without communicating. Full batches beyond the
        cross-host common count and remainder batches go through padded
        collective batches with a per-example validity weight — padding is
        excluded exactly from the weighted metrics (VERDICT r2 weak item 4
        closed: nothing is dropped multi-host)."""
        self._log("Start running evaluation on validation set:")
        for m in self.val_meters.values():
            m.reset()

        lbs = self.local_batch_size
        n_total = len(self.val_dataset)
        num_shards = jax.process_count()
        shard_counts = [(n_total - h + num_shards - 1) // num_shards
                        for h in range(num_shards)]
        common_full = min(c // lbs for c in shard_counts)
        leftover_counts = [c - common_full * lbs for c in shard_counts]
        tail_batches = -(-max(leftover_counts) // lbs)
        global_bs = self.trainer.global_batch_size()

        it = self.val_dataset.batch_iterator(
            batch_size=lbs, shuffle=False, drop_last=False,
            shard_index=jax.process_index(), num_shards=num_shards)
        eval_rng = jax.random.PRNGKey(0)
        gstep = int(state.step)
        # Fresh pyramid cache per eval: entries are keyed by image id only,
        # and the params this eval sees differ from the last one's.
        eval_cache = PyramidCache(
            capacity_bytes=self.serve_cfg.cache_bytes,
            quant=self.serve_cfg.eval_cache_quant) \
            if self.eval_encode_once else None
        full_seen = 0
        leftover = []  # host-local single-example dicts beyond common_full
        template = None  # any local example, for padding
        for i, np_batch in enumerate(it):
            n = np_batch["src_img"].shape[0]
            if template is None:
                template = {k: v[0:1] for k, v in np_batch.items()}
            if not (n == lbs and full_seen < common_full):
                leftover.extend({k: v[j:j + 1] for k, v in np_batch.items()}
                                for j in range(n))
                continue
            full_seen += 1
            if eval_cache is not None:
                batch, metrics, visuals = self._eval_batch_encode_once(
                    state, np_batch, jax.random.fold_in(eval_rng, i),
                    eval_cache)
            else:
                batch = self.trainer.put_batch(np_batch)
                metrics, visuals = self.trainer.eval_step(
                    state, batch, jax.random.fold_in(eval_rng, i))
            with telemetry.host_readback("eval.metrics"):
                m = metrics_to_float(metrics)
            for k, meter in self.val_meters.items():
                meter.update(m[k], n=global_bs)
            if i == 0 and self.tb is not None:
                self._log_val_images(gstep, batch, visuals)

        if tail_batches and template is None:
            # this host's stride shard was empty (val set smaller than the
            # host count) but it must still join the collective tail calls;
            # any real example serves as 0-weight padding content, so read
            # one through an unsharded iterator
            template = {k: v[0:1] for k, v in next(iter(
                self.val_dataset.batch_iterator(
                    batch_size=1, shuffle=False, drop_last=False,
                    shard_index=0, num_shards=1))).items()}

        for j in range(tail_batches):
            chunk = leftover[j * lbs:(j + 1) * lbs]
            w_local = np.zeros((lbs,), np.float32)
            w_local[:len(chunk)] = 1.0
            chunk = chunk + [template] * (lbs - len(chunk))
            local = {k: np.concatenate([c[k] for c in chunk], axis=0)
                     for k in chunk[0]}
            if eval_cache is not None:
                _, metrics, _ = self._eval_batch_encode_once(
                    state, local,
                    jax.random.fold_in(eval_rng, 1_000_000 + j),
                    eval_cache, w_local=w_local)
            else:
                batch = self.trainer.put_batch(local)
                weight = self.trainer.put_example_array(w_local)
                metrics = self.trainer.eval_step_masked(
                    state, batch,
                    jax.random.fold_in(eval_rng, 1_000_000 + j), weight)
            with telemetry.host_readback("eval.metrics"):
                m = metrics_to_float(metrics)
            # valid examples in THIS tail batch across all hosts
            # (deterministic from the shard counts)
            g_valid = sum(min(max(c - j * lbs, 0), lbs)
                          for c in leftover_counts)
            for k, meter in self.val_meters.items():
                meter.update(m[k], n=g_valid)

        self._log("Evaluation finished, average losses:")
        for m in self.val_meters.values():
            self._log("    %s" % m)
        if eval_cache is not None:
            s = eval_cache.stats()
            self._log("Encode-once eval: %d encodes, %d replays (%s cache, "
                      "%.1f MB)", s["misses"], s["hits"], s["quant"],
                      s["nbytes"] / 1e6)
        for k, meter in self.val_meters.items():
            self._tb("add_scalar", k + "/val", meter.avg, gstep)
        return {k: meter.avg for k, meter in self.val_meters.items()}

    def _eval_batch_encode_once(self, state: TrainState, np_batch, key,
                                eval_cache, w_local=None):
        """One eval batch with the encoder amortized across target views.

        Derives the SAME per-batch disparity sample as the fused eval step
        (fold_in(eval_rng, i) -> split -> sample_disparity), encodes only
        source images whose pyramid isn't cached (coarse-to-fine configs use
        the RNG-replaying eval_encode_c2f), and runs the batched
        render+loss half on the replayed pyramids. A source seen again
        reuses its first-seen disparity row — an RNG-level shift vs. the
        fused path (identical when val sources are distinct; the metric-
        parity test runs on a distinct-source set)."""
        B = np_batch["src_img"].shape[0]
        d_key, f_key = jax.random.split(key)  # split mirrors _eval_step_impl
        disparity = np.asarray(sample_disparity(d_key, B, self.trainer.cfg))
        c2f = self.trainer.cfg.num_bins_fine > 0
        rows = []
        for b in range(B):
            img_b = np_batch["src_img"][b:b + 1]
            iid = image_id_for(img_b)
            cached = eval_cache.get(iid)
            if cached is None:
                if c2f:
                    # coarse-to-fine: per-example encode replaying the fused
                    # step's row-b fine-plane draws (fine_rows slicing in
                    # ops/rendering.py); cache the FULL coarse+fine
                    # disparities alongside the pyramid
                    mpi_b, disp_all_b = self.trainer.eval_encode_c2f(
                        state, jnp.asarray(img_b),
                        jnp.asarray(disparity[b:b + 1]), f_key, b,
                        jnp.asarray(np_batch["K_src"][b:b + 1]), B)
                    disp_row = np.asarray(disp_all_b[0])
                else:
                    mpi_b = self.trainer.eval_encode(
                        state, jnp.asarray(img_b),
                        jnp.asarray(disparity[b:b + 1]))
                    disp_row = disparity[b]
                eval_cache.put(iid, [m[0] for m in mpi_b], disp_row)
                cached = eval_cache.get(iid)
            rows.append(cached)
        num_scales = len(rows[0][0])
        mpi_list = [jnp.stack([r[0][s] for r in rows], axis=0)
                    for s in range(num_scales)]
        disparity_all = jnp.stack([r[1] for r in rows], axis=0)
        batch = self.trainer.put_batch(np_batch)
        if w_local is None:
            metrics, visuals = self.trainer.eval_losses(
                state, mpi_list, disparity_all, batch)
            return batch, metrics, visuals
        metrics = self.trainer.eval_losses_masked(
            state, mpi_list, disparity_all, batch,
            self.trainer.put_example_array(w_local))
        return batch, metrics, None

    # ---------------- logging ----------------

    def _log(self, msg, *args):
        if self.logger is not None and self.is_lead:
            self.logger.info(msg, *args)

    def _tb(self, method, *args):
        """Non-fatal tensorboard write: a broken writer (full disk, dead
        tensorboardX backend) degrades to scalar-log-only instead of
        killing a multi-hour run; one warning, then silence."""
        if self.tb is None or self._tb_broken:
            return
        try:
            getattr(self.tb, method)(*args)
        except Exception:
            self._tb_broken = True
            if self.logger is not None:
                self.logger.warning(
                    "tensorboard writer failed — disabling TB output for "
                    "the rest of the run", exc_info=True)

    # ---------------- train-side ops plane ----------------

    def _train_health(self):
        """/healthz body: "degraded" while the non-finite guard is in a
        live skip streak or data errors burned in the last log interval.
        Reads only the log-cadence state dict — never a device value."""
        s = self._ops_state
        reasons = []
        if s["guard_consecutive"] > 0:
            reasons.append("guard skip streak: %d consecutive "
                           "non-finite steps" % int(s["guard_consecutive"]))
        if s["data_errors_delta"] > 0:
            reasons.append("%d data errors in the last log interval"
                           % int(s["data_errors_delta"]))
        return {"status": "degraded" if reasons else "ok",
                "reasons": reasons, "gstep": int(s["gstep"]),
                "data_errors": int(s["data_errors"])}

    def _train_progress(self):
        """/progress body: position plus an ETA extrapolated from the
        recent st1 step_ms history (None until the first log interval)."""
        s = self._ops_state
        total = int(s["epochs"]) * self.trainer.steps_per_epoch
        avg_ms = (sum(self._step_hist) / len(self._step_hist)
                  if self._step_hist else None)
        remaining = max(0, total - int(s["gstep"]))
        return {"gstep": int(s["gstep"]), "epoch": int(s["epoch"]),
                "epochs": int(s["epochs"]),
                "steps_per_epoch": self.trainer.steps_per_epoch,
                "total_steps": total,
                "step_ms_avg": None if avg_ms is None else round(avg_ms, 3),
                "eta_s": None if avg_ms is None
                else round(remaining * avg_ms / 1e3, 1)}

    def _log_training(self, epoch, step, gstep, m, times, stage_ms=None):
        lrs = current_lrs(self.config, self.trainer.steps_per_epoch, gstep)
        data_stats = PIPELINE_STATS.snapshot()
        # ops-plane state: written only here (log cadence, lead host), read
        # by the /healthz and /progress handlers
        self._step_hist.append(times["step_ms"])
        prev_errors = self._ops_state["data_errors"]
        self._ops_state.update(
            gstep=gstep, epoch=epoch,
            guard_consecutive=m.get("guard_consecutive", 0.0),
            data_errors=data_stats["data_errors"],
            data_errors_delta=max(
                0, data_stats["data_errors"] - prev_errors))
        # the FROZEN parseable step-time line (schema st1 — see
        # telemetry/stepline.py; tools/step_breakdown.py and obs_report
        # both read it through the one shared parser)
        # appended stage_*_ms keys (pipeline executor breakdown) ride the
        # same line under the append-only rule — absent when pipelining
        # is off, so non-pipeline logs are byte-identical to before
        step_line = telemetry.format_step_line(times,
                                               data_stats["data_errors"],
                                               extra=stage_ms or None)
        self._log(
            "epoch [%.3d] step [%d] global_step = %d total_loss = %.4f "
            "encoder_lr = %.7f step_time = %.3fs\n"
            "        src: rgb = %.4f ssim = %.4f disp_pt3d = %.4f\n"
            "        tgt: rgb = %.4f ssim = %.4f disp_pt3d = %.4f psnr = %.2f\n"
            "        %s"
            % (epoch, step, gstep, m["loss"], lrs["backbone"],
               times["step_ms"] / 1e3,
               m["loss_rgb_src"], m["loss_ssim_src"], m["loss_disp_pt3dsrc"],
               m["loss_rgb_tgt"], m["loss_ssim_tgt"], m["loss_disp_pt3dtgt"],
               m["psnr_tgt"], step_line))
        if self.telem.enabled:
            # registry mirror: per-interval time breakdown histograms, the
            # guard's cumulative counters as gauges (they live in the
            # TrainState buffer; the registry mirrors at log cadence only —
            # no new per-step host sync), pipeline health gauges
            for k in TIME_METER_KEYS:
                telemetry.histogram("train." + k).record(times[k])
            for src_key, gauge_name in (
                    ("skipped_steps", "train.guard.skipped_steps"),
                    ("guard_consecutive", "train.guard.consecutive"),
                    ("warp_fallback_frac", "train.warp_fallback_frac")):
                if src_key in m:
                    telemetry.gauge(gauge_name).set(m[src_key])
            telemetry.emit(
                "train.step", gstep=gstep, epoch=epoch,
                loss=round(float(m["loss"]), 6),
                psnr_tgt=round(float(m.get("psnr_tgt", 0.0)), 4),
                **{k: round(times[k], 3) for k in TIME_METER_KEYS},
                data_errors=data_stats["data_errors"])
            # flight-recorder feeds, log cadence only: the st1 line and a
            # rolling registry snapshot land in the black-box rings; a
            # data-error burst past the configured floor trips a bundle
            # (async — this is the hot loop's logging path)
            if self.recorder is not None:
                self.recorder.observe_stepline(step_line)
                self.recorder.snapshot_metrics(scope="train")
                burst = self.telem.recorder_data_error_burst
                delta = self._ops_state["data_errors_delta"]
                if burst > 0 and delta >= burst:
                    self.recorder.trigger(
                        "train.data_error_burst", sync=False, gstep=gstep,
                        data_errors_delta=int(delta))
            # per-layer-group stats (training.layer_stats): the jitted step
            # returns them as "layers/<group>.<stat>" scalar metrics — they
            # arrived in the same log-cadence readback as everything else.
            # Regrouped into one train.layers event + registry histograms.
            layer_groups: Dict[str, Dict[str, float]] = {}
            for k in m:
                if not k.startswith("layers/"):
                    continue
                group, stat = k[len("layers/"):].split(".", 1)
                layer_groups.setdefault(group, {})[stat] = \
                    round(float(m[k]), 6)
                telemetry.histogram(
                    "train.layers." + k[len("layers/"):]).record(m[k])
            if layer_groups:
                telemetry.emit("train.layers", gstep=gstep,
                               groups=layer_groups)
        for k, meter in self.time_meters.items():
            meter.update(times[k])
            self._tb("add_scalar", "time/" + k, times[k], gstep)
        self._tb("add_scalar", "data/errors", data_stats["data_errors"],
                 gstep)
        # diagnostics beyond the fixed reference meter set (e.g.
        # warp_fallback_frac from the guarded warp backends, the
        # non-finite-guard counters) get meters on first sight so they
        # reach the epoch summaries and TB too
        for k in m:
            if k not in self.train_meters:
                self.train_meters[k] = AverageMeter("train_" + k)
        for k, meter in self.train_meters.items():
            if k not in m:
                continue  # meter from a previous backend config
            meter.update(m[k])
            self._tb("add_scalar", k + "/train", m[k], gstep)

    def _log_val_images(self, gstep, batch, visuals):
        """Tensorboard image grids (synthesis_task.log_val :509-548);
        non-fatal — see _tb. Declared readback: whole image tensors come
        to host here, once per eval."""
        with telemetry.host_readback("eval.val_images"):
            self._log_val_images_inner(gstep, batch, visuals)

    def _log_val_images_inner(self, gstep, batch, visuals):
        def grid(x_bchw):
            x = np.asarray(x_bchw)
            return np.clip(np.concatenate(list(x), axis=2), 0.0, 1.0)

        src = np.transpose(np.asarray(batch["src_img"]), (0, 3, 1, 2))
        tgt = np.transpose(np.asarray(batch["tgt_img"]), (0, 3, 1, 2))
        self._tb("add_image", "00_src_images", grid(src), gstep)
        self._tb("add_image", "01_gt_tgt_images", grid(tgt), gstep)
        self._tb("add_image", "02_syn_src_images/step_%d" % gstep,
                 grid(visuals["src_imgs_syn"]), gstep)
        self._tb("add_image", "03_syn_src_disparity_map/step_%d" % gstep,
                 grid(disparity_normalization_vis(
                     np.asarray(visuals["src_disparity_syn"]))), gstep)
        self._tb("add_image", "04_syn_tgt_images/step_%d" % gstep,
                 grid(visuals["tgt_imgs_syn"]), gstep)
        self._tb("add_image", "05_syn_tgt_disparity_map/step_%d" % gstep,
                 grid(disparity_normalization_vis(
                     np.asarray(visuals["tgt_disparity_syn"]))), gstep)
