"""Orbax checkpointing of the full train state.

Fixes the reference's resume gaps (SURVEY.md section 5): the reference saves
only {backbone, decoder, optimizer} state dicts — no step/epoch, no RNG, and
eval-interval checkpoints even omit the optimizer (synthesis_task.py:625-659)
— so resume restarts counters and reshuffles data. Here the whole TrainState
(params, batch_stats, opt_state, step, rng) round-trips, and saves are async
so the TPU never waits on the filesystem.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from mine_tpu.train.state import TrainState

LATEST_NAME = "checkpoint_latest"
STEP_FMT = "checkpoint_%012d"


class CheckpointManager:
    def __init__(self, workspace: str):
        self.workspace = os.path.abspath(workspace)
        os.makedirs(self.workspace, exist_ok=True)
        self._ckptr = ocp.StandardCheckpointer()

    def _path(self, name: str) -> str:
        return os.path.join(self.workspace, name)

    def save_latest(self, state: TrainState):
        """Rolling checkpoint (reference: checkpoint_latest.pth every 5000
        steps, synthesis_task.py:625-632)."""
        path = self._path(LATEST_NAME)
        self._ckptr.save(path, state, force=True)

    def save_step(self, state: TrainState):
        """Immutable per-eval checkpoint — unlike the reference's, it keeps
        the optimizer state (synthesis_task.py:650-652 drops it)."""
        path = self._path(STEP_FMT % int(state.step))
        if not os.path.exists(path):
            self._ckptr.save(path, state)

    def wait(self):
        self._ckptr.wait_until_finished()

    def restore(self, template: TrainState,
                name: Optional[str] = None) -> Optional[TrainState]:
        """Restore into the template's structure/shardings; returns None when
        no checkpoint exists."""
        name = name or LATEST_NAME
        path = name if os.path.isabs(name) else self._path(name)
        if not os.path.exists(path):
            return None
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          template)
        try:
            return self._ckptr.restore(path, abstract)
        except (ValueError, KeyError, TypeError) as e:
            # tree/structure mismatch out of orbax — almost always a config
            # change between runs; surface the original error text so IO or
            # corruption causes (which also raise ValueError) stay visible
            raise RuntimeError(
                f"Failed to restore checkpoint at {path}: {e}\n"
                "If this is a tree-structure mismatch, the optimizer config "
                "likely changed between runs (e.g. training.grad_accum_steps "
                "toggled, which nests opt_state under optax.MultiSteps). "
                "Resume with the original config, or load weights only via "
                "training.pretrained_checkpoint_path (.npz).") from e

    def latest_exists(self) -> bool:
        return os.path.exists(self._path(LATEST_NAME))


def load_pretrained_params(path: str, params, batch_stats=None, logger=None):
    """Non-strict restore from a converted .npz checkpoint (flattened 'a/b/c'
    keys; BatchNorm running stats under 'stats:a/b/c') — the torch-interop
    path, mirroring restore_model's tolerant model load (utils.py:40-67).

    Missing/extra keys are logged, matching keys replaced. Returns new params
    (and new batch_stats when a template is given).
    """
    data = np.load(path)

    def merge(tree, prefix_tag, tag):
        flat = _flatten("", tree)
        missing = [k for k in flat if prefix_tag + k not in data]
        if logger:
            logger.info("[MODEL_RESTORE] %s keys missing in checkpoint: %s",
                        tag, missing)

        def rebuild(prefix, t):
            out = {}
            for k, v in t.items():
                key = f"{prefix}/{k}" if prefix else k
                if isinstance(v, dict):
                    out[k] = rebuild(key, v)
                elif prefix_tag + key in data:
                    arr = np.asarray(data[prefix_tag + key])
                    out[k] = arr.astype(np.asarray(v).dtype).reshape(v.shape)
                else:
                    out[k] = v
            return out

        return rebuild("", tree)

    new_params = merge(params, "", "param")
    if logger:
        known = set(_flatten("", params))
        if batch_stats is not None:
            known |= {"stats:" + k for k in _flatten("", batch_stats)}
        extra = [k for k in data.files
                 if k not in known and not (k.startswith("stats:")
                                            and batch_stats is None)]
        logger.info("[MODEL_RESTORE] unused checkpoint keys: %s", extra)
    if batch_stats is None:
        return new_params
    return new_params, merge(batch_stats, "stats:", "batch_stats")


def _flatten(prefix, tree):
    flat = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            flat.update(_flatten(key, v))
        else:
            flat[key] = v
    return flat
