"""Orbax checkpointing of the full train state.

Fixes the reference's resume gaps (SURVEY.md section 5): the reference saves
only {backbone, decoder, optimizer} state dicts — no step/epoch, no RNG, and
eval-interval checkpoints even omit the optimizer (synthesis_task.py:625-659)
— so resume restarts counters and reshuffles data. Here the whole TrainState
(params, batch_stats, opt_state, step, rng) round-trips, and saves are async
so the TPU never waits on the filesystem.

Hardening (the fault-tolerance PR):
  * On disk a checkpoint is always the stable 5-key plain tree
    {step, params, batch_stats, opt_state, rng} — diagnostic TrainState
    fields (the non-finite-guard counter buffer) are stripped on save and
    re-injected fresh on restore, so old workspaces stay restorable and
    future guard changes never invalidate checkpoints.
  * Each finished save gets a sidecar commit marker `<dir>.commit`
    (flushed once the async save settles). Markers are ADVISORY on read
    (pre-marker workspaces restore fine) but authoritative on write:
    `save_step` overwrites a marker-less partial directory instead of the
    old `os.path.exists` guard that refused to ever re-save that step.
  * keep-last-K retention for immutable step checkpoints (`keep`),
    lead-host only, never touching in-flight saves.
  * `restore()` without an explicit name walks a fallback chain — latest,
    then step checkpoints newest-first — logging and degrading on
    corruption instead of dying; only when every candidate fails does it
    raise (with the config-mismatch hint, since that is the common cause).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from mine_tpu import telemetry
from mine_tpu.train.state import TrainState

LATEST_NAME = "checkpoint_latest"
STEP_FMT = "checkpoint_%012d"
STEP_RE = re.compile(r"^checkpoint_(\d{12})$")
MARKER_SUFFIX = ".commit"

# the on-disk tree: stable across TrainState diagnostic-field changes
SAVE_KEYS = ("step", "params", "batch_stats", "opt_state", "rng")


# hard bound on waiting for an in-flight mirror upload before a save may
# overwrite its source directory (or the process exits): past this the
# uploader is killed and the incident logged — a hung remote store must
# not wedge training (this repo's watchdog lesson applies to itself)
MIRROR_REAP_TIMEOUT_S = 600.0


class CheckpointManager:
    def __init__(self, workspace: str, mirror_cmd: str = "",
                 keep: int = 0, logger=None):
        """`mirror_cmd`: optional shell command run (lead host only) after
        each finished save, with the literal token `{path}` replaced by the
        shell-quoted checkpoint directory — the generic counterpart of the
        reference's hard-wired HDFS upload (synthesis_task.py:634-638).
        E.g. `gsutil -m rsync -r {path} gs://bucket/ckpts/` or
        `hdfs dfs -put -f {path} /ckpts/`. The upload runs detached; an
        in-flight upload is reaped (bounded by MIRROR_REAP_TIMEOUT_S, then
        killed) before a save may overwrite its source directory and at
        wait(). Mirror problems log warnings, never raise.

        `keep`: retain only the newest `keep` committed step checkpoints
        (0 = keep all, the old behavior)."""
        self.workspace = os.path.abspath(workspace)
        os.makedirs(self.workspace, exist_ok=True)
        self._ckptr = ocp.StandardCheckpointer()
        self.mirror_cmd = mirror_cmd
        self._mirror_proc = None
        self.keep = int(keep)
        self._logger = logger
        # (path, step) of async saves whose commit marker is still owed;
        # flushed (wait_until_finished + marker write) at the next save,
        # restore, or wait() — never per step
        self._pending_commits: List[Tuple[str, int]] = []

    def _path(self, name: str) -> str:
        return os.path.join(self.workspace, name)

    def _warn(self, msg, *args):
        if self._logger is not None:
            self._logger.warning(msg, *args)
        else:
            import logging
            logging.getLogger(__name__).warning(msg, *args)

    # ---------------- commit markers ----------------

    @staticmethod
    def marker_path(path: str) -> str:
        return path + MARKER_SUFFIX

    def has_marker(self, path: str) -> bool:
        return os.path.exists(self.marker_path(path))

    def _remove_marker(self, path: str):
        if jax.process_index() != 0:
            return
        try:
            os.remove(self.marker_path(path))
        except FileNotFoundError:
            pass

    def _flush_commits(self):
        """Settle in-flight async saves, then certify them with markers."""
        if not self._pending_commits:
            return
        self._ckptr.wait_until_finished()
        for path, step in self._pending_commits:
            if jax.process_index() != 0 or not os.path.isdir(path):
                continue
            marker = {"name": os.path.basename(path), "step": int(step),
                      "unix_time": time.time()}
            tmp = self.marker_path(path) + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(marker, fh)
            os.replace(tmp, self.marker_path(path))
        self._pending_commits = []

    # ---------------- directory scan ----------------

    def step_checkpoints(self) -> List[Tuple[int, str]]:
        """Committed-or-not step checkpoint dirs as (step, path), newest
        first. The strict 12-digit regex skips orbax tmp dirs and markers."""
        out = []
        for entry in os.listdir(self.workspace):
            m = STEP_RE.match(entry)
            path = self._path(entry)
            if m and os.path.isdir(path):
                out.append((int(m.group(1)), path))
        return sorted(out, reverse=True)

    def _retain(self):
        """Delete committed step checkpoints beyond the newest `keep`.
        Lead host only; uncommitted (marker-less) dirs beyond the window
        are stale partial saves from a crashed run and go too. Never
        touches a path with a pending (in-flight) save."""
        if self.keep <= 0 or jax.process_index() != 0:
            return
        pending = {p for p, _ in self._pending_commits}
        for _, path in self.step_checkpoints()[self.keep:]:
            if path in pending:
                continue
            shutil.rmtree(path, ignore_errors=True)
            self._remove_marker(path)

    def _mirror(self, path: str):
        """Launch the detached uploader for a finished save (lead host)."""
        if not self.mirror_cmd or jax.process_index() != 0:
            return
        try:
            import shlex
            import subprocess
            self._ckptr.wait_until_finished()  # files on disk before upload
            # plain token replace + shell quoting: no str.format, so shell
            # braces (${USER}, awk '{print}') in the command are untouched
            cmd = self.mirror_cmd.replace("{path}", shlex.quote(path))
            self._mirror_proc = (cmd, subprocess.Popen(
                cmd, shell=True, start_new_session=True))
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "checkpoint mirror launch failed", exc_info=True)

    def _reap_mirror(self, block: bool = False):
        """Collect the previous uploader; bounded kill when block=True."""
        if self._mirror_proc is None:
            return
        import logging
        import subprocess
        cmd, proc = self._mirror_proc
        try:
            rc = proc.wait(MIRROR_REAP_TIMEOUT_S) if block else proc.poll()
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            logging.getLogger(__name__).warning(
                "checkpoint mirror still running after %.0fs — killed: %s",
                MIRROR_REAP_TIMEOUT_S, cmd)
            self._mirror_proc = None
            return
        if rc is None:
            return  # still running (non-blocking poll)
        if rc != 0:
            logging.getLogger(__name__).warning(
                "checkpoint mirror command failed (rc=%d): %s", rc, cmd)
        self._mirror_proc = None

    @staticmethod
    def _save_tree(state: TrainState) -> dict:
        return {k: getattr(state, k) for k in SAVE_KEYS}

    def save_latest(self, state: TrainState):
        """Rolling checkpoint (reference: checkpoint_latest.pth every 5000
        steps, synthesis_task.py:625-632)."""
        # the span covers dispatch only — the save itself is async, so
        # this measures how long the TPU-side loop was actually held up
        # (mirror reap + previous-save settle + save dispatch)
        with telemetry.span("ckpt.save_latest", step=int(state.step)):
            # an in-flight mirror may still be reading checkpoint_latest;
            # finish (or kill) it before force-overwriting its source
            self._reap_mirror(block=True)
            self._flush_commits()
            path = self._path(LATEST_NAME)
            # the old marker must not certify the dir while the overwrite
            # is in flight — a crash mid-save then correctly reads as
            # uncommitted
            self._remove_marker(path)
            self._ckptr.save(path, self._save_tree(state), force=True)
            self._pending_commits.append((path, int(state.step)))
            self._mirror(path)

    def save_step(self, state: TrainState):
        """Immutable per-eval checkpoint — unlike the reference's, it keeps
        the optimizer state (synthesis_task.py:650-652 drops it). A dir
        with a commit marker is final and skipped; a marker-less dir is a
        partial save from a crashed run and is overwritten (the old
        os.path.exists guard refused to ever re-save that step)."""
        with telemetry.span("ckpt.save_step", step=int(state.step)):
            self._flush_commits()
            path = self._path(STEP_FMT % int(state.step))
            if os.path.exists(path):
                if self.has_marker(path):
                    return
                self._warn("overwriting incomplete step checkpoint %s "
                           "(no commit marker — previous save did not "
                           "finish)", path)
            self._reap_mirror(block=True)  # one uploader at a time
            self._ckptr.save(path, self._save_tree(state), force=True)
            self._pending_commits.append((path, int(state.step)))
            self._mirror(path)
            self._retain()

    def wait(self):
        self._flush_commits()
        self._ckptr.wait_until_finished()
        # the final save's mirror must complete before the job exits, or
        # container teardown kills the detached upload mid-transfer
        self._reap_mirror(block=True)

    # ---------------- restore ----------------

    def _restore_tree(self, path: str, template: TrainState) -> TrainState:
        """One restore attempt against the stable 5-key on-disk tree; the
        guard buffer is re-injected from the template (counters are
        diagnostics of the CURRENT run — they reset on resume)."""
        abstract = {k: jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                              getattr(template, k))
                    for k in SAVE_KEYS}
        tree = self._ckptr.restore(path, abstract)
        return TrainState(guard=template.guard,
                          **{k: tree[k] for k in SAVE_KEYS})

    @staticmethod
    def _mismatch_hint(path: str, e: Exception) -> RuntimeError:
        # tree/structure mismatch out of orbax — almost always a config
        # change between runs; surface the original error text so IO or
        # corruption causes (which also raise ValueError) stay visible
        return RuntimeError(
            f"Failed to restore checkpoint at {path}: {e}\n"
            "If this is a tree-structure mismatch, the optimizer config "
            "likely changed between runs (e.g. training.grad_accum_steps "
            "toggled, which nests opt_state under optax.MultiSteps). "
            "Resume with the original config, or load weights only via "
            "training.pretrained_checkpoint_path (.npz).")

    def restore(self, template: TrainState,
                name: Optional[str] = None) -> Optional[TrainState]:
        """Restore into the template's structure/shardings; returns None when
        no checkpoint exists.

        With an explicit `name` only that checkpoint is tried. Without one
        the fallback chain runs: checkpoint_latest, then step checkpoints
        newest-first — a corrupt candidate logs a warning and degrades to
        the next instead of killing the run. Markers are advisory here
        (pre-marker workspaces restore fine). Only when every candidate
        fails does the chain raise, with the config-mismatch hint."""
        with telemetry.span("ckpt.restore"):
            return self._restore(template, name)

    def _restore(self, template: TrainState,
                 name: Optional[str] = None) -> Optional[TrainState]:
        self._flush_commits()
        if name is not None:
            path = name if os.path.isabs(name) else self._path(name)
            if not os.path.exists(path):
                return None
            try:
                return self._restore_tree(path, template)
            except (ValueError, KeyError, TypeError) as e:
                raise self._mismatch_hint(path, e) from e

        candidates = []
        latest = self._path(LATEST_NAME)
        if os.path.exists(latest):
            candidates.append(latest)
        candidates.extend(path for _, path in self.step_checkpoints())
        last = None  # (path, exception)
        for path in candidates:
            try:
                restored = self._restore_tree(path, template)
            except Exception as e:
                self._warn("failed to restore %s (%s: %s)%s", path,
                           type(e).__name__, e,
                           "" if self.has_marker(path) else
                           " — no commit marker, likely a partial save")
                last = (path, e)
                continue
            if last is not None:
                # a corrupt/partial candidate was skipped: count it — a
                # nonzero ckpt.restore_fallback after an incident review
                # means the durability story was load-bearing, not luck
                telemetry.counter("ckpt.restore_fallback").inc()
                telemetry.emit(
                    "ckpt.restore_fallback", restored=path,
                    step=int(np.asarray(restored.step)),
                    failed=last[0], error=f"{type(last[1]).__name__}")
                self._warn("restored fallback checkpoint %s at step %d",
                           path, int(np.asarray(restored.step)))
            return restored
        if last is not None:
            raise self._mismatch_hint(*last) from last[1]
        return None

    def latest_exists(self) -> bool:
        return os.path.exists(self._path(LATEST_NAME))


def load_pretrained_params(path: str, params, batch_stats=None, logger=None):
    """Non-strict restore from a converted .npz checkpoint (flattened 'a/b/c'
    keys; BatchNorm running stats under 'stats:a/b/c') — the torch-interop
    path, mirroring restore_model's tolerant model load (utils.py:40-67).

    Missing/extra keys are logged, matching keys replaced. Returns new params
    (and new batch_stats when a template is given).
    """
    data = np.load(path)

    def merge(tree, prefix_tag, tag):
        flat = _flatten("", tree)
        missing = [k for k in flat if prefix_tag + k not in data]
        if logger:
            logger.info("[MODEL_RESTORE] %s keys missing in checkpoint: %s",
                        tag, missing)

        def rebuild(prefix, t):
            out = {}
            for k, v in t.items():
                key = f"{prefix}/{k}" if prefix else k
                if isinstance(v, dict):
                    out[k] = rebuild(key, v)
                elif prefix_tag + key in data:
                    arr = np.asarray(data[prefix_tag + key])
                    out[k] = arr.astype(np.asarray(v).dtype).reshape(v.shape)
                else:
                    out[k] = v
            return out

        return rebuild("", tree)

    new_params = merge(params, "", "param")
    if logger:
        known = set(_flatten("", params))
        if batch_stats is not None:
            known |= {"stats:" + k for k in _flatten("", batch_stats)}
        extra = [k for k in data.files
                 if k not in known and not (k.startswith("stats:")
                                            and batch_stats is None)]
        logger.info("[MODEL_RESTORE] unused checkpoint keys: %s", extra)
    if batch_stats is None:
        return new_params
    return new_params, merge(batch_stats, "stats:", "batch_stats")


def _flatten(prefix, tree):
    flat = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            flat.update(_flatten(key, v))
        else:
            flat[key] = v
    return flat
