"""Orbax checkpointing of the full train state.

Fixes the reference's resume gaps (SURVEY.md section 5): the reference saves
only {backbone, decoder, optimizer} state dicts — no step/epoch, no RNG, and
eval-interval checkpoints even omit the optimizer (synthesis_task.py:625-659)
— so resume restarts counters and reshuffles data. Here the whole TrainState
(params, batch_stats, opt_state, step, rng) round-trips, and saves are async
so the TPU never waits on the filesystem.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from mine_tpu.train.state import TrainState

LATEST_NAME = "checkpoint_latest"
STEP_FMT = "checkpoint_%012d"


# hard bound on waiting for an in-flight mirror upload before a save may
# overwrite its source directory (or the process exits): past this the
# uploader is killed and the incident logged — a hung remote store must
# not wedge training (this repo's watchdog lesson applies to itself)
MIRROR_REAP_TIMEOUT_S = 600.0


class CheckpointManager:
    def __init__(self, workspace: str, mirror_cmd: str = ""):
        """`mirror_cmd`: optional shell command run (lead host only) after
        each finished save, with the literal token `{path}` replaced by the
        shell-quoted checkpoint directory — the generic counterpart of the
        reference's hard-wired HDFS upload (synthesis_task.py:634-638).
        E.g. `gsutil -m rsync -r {path} gs://bucket/ckpts/` or
        `hdfs dfs -put -f {path} /ckpts/`. The upload runs detached; an
        in-flight upload is reaped (bounded by MIRROR_REAP_TIMEOUT_S, then
        killed) before a save may overwrite its source directory and at
        wait(). Mirror problems log warnings, never raise."""
        self.workspace = os.path.abspath(workspace)
        os.makedirs(self.workspace, exist_ok=True)
        self._ckptr = ocp.StandardCheckpointer()
        self.mirror_cmd = mirror_cmd
        self._mirror_proc = None

    def _path(self, name: str) -> str:
        return os.path.join(self.workspace, name)

    def _mirror(self, path: str):
        """Launch the detached uploader for a finished save (lead host)."""
        if not self.mirror_cmd or jax.process_index() != 0:
            return
        try:
            import shlex
            import subprocess
            self._ckptr.wait_until_finished()  # files on disk before upload
            # plain token replace + shell quoting: no str.format, so shell
            # braces (${USER}, awk '{print}') in the command are untouched
            cmd = self.mirror_cmd.replace("{path}", shlex.quote(path))
            self._mirror_proc = (cmd, subprocess.Popen(
                cmd, shell=True, start_new_session=True))
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "checkpoint mirror launch failed", exc_info=True)

    def _reap_mirror(self, block: bool = False):
        """Collect the previous uploader; bounded kill when block=True."""
        if self._mirror_proc is None:
            return
        import logging
        import subprocess
        cmd, proc = self._mirror_proc
        try:
            rc = proc.wait(MIRROR_REAP_TIMEOUT_S) if block else proc.poll()
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            logging.getLogger(__name__).warning(
                "checkpoint mirror still running after %.0fs — killed: %s",
                MIRROR_REAP_TIMEOUT_S, cmd)
            self._mirror_proc = None
            return
        if rc is None:
            return  # still running (non-blocking poll)
        if rc != 0:
            logging.getLogger(__name__).warning(
                "checkpoint mirror command failed (rc=%d): %s", rc, cmd)
        self._mirror_proc = None

    def save_latest(self, state: TrainState):
        """Rolling checkpoint (reference: checkpoint_latest.pth every 5000
        steps, synthesis_task.py:625-632)."""
        # an in-flight mirror may still be reading checkpoint_latest;
        # finish (or kill) it before force-overwriting its source
        self._reap_mirror(block=True)
        path = self._path(LATEST_NAME)
        self._ckptr.save(path, state, force=True)
        self._mirror(path)

    def save_step(self, state: TrainState):
        """Immutable per-eval checkpoint — unlike the reference's, it keeps
        the optimizer state (synthesis_task.py:650-652 drops it)."""
        path = self._path(STEP_FMT % int(state.step))
        if not os.path.exists(path):
            self._reap_mirror(block=True)  # one uploader at a time
            self._ckptr.save(path, state)
            self._mirror(path)

    def wait(self):
        self._ckptr.wait_until_finished()
        # the final save's mirror must complete before the job exits, or
        # container teardown kills the detached upload mid-transfer
        self._reap_mirror(block=True)

    def restore(self, template: TrainState,
                name: Optional[str] = None) -> Optional[TrainState]:
        """Restore into the template's structure/shardings; returns None when
        no checkpoint exists."""
        name = name or LATEST_NAME
        path = name if os.path.isabs(name) else self._path(name)
        if not os.path.exists(path):
            return None
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          template)
        try:
            return self._ckptr.restore(path, abstract)
        except (ValueError, KeyError, TypeError) as e:
            # tree/structure mismatch out of orbax — almost always a config
            # change between runs; surface the original error text so IO or
            # corruption causes (which also raise ValueError) stay visible
            raise RuntimeError(
                f"Failed to restore checkpoint at {path}: {e}\n"
                "If this is a tree-structure mismatch, the optimizer config "
                "likely changed between runs (e.g. training.grad_accum_steps "
                "toggled, which nests opt_state under optax.MultiSteps). "
                "Resume with the original config, or load weights only via "
                "training.pretrained_checkpoint_path (.npz).") from e

    def latest_exists(self) -> bool:
        return os.path.exists(self._path(LATEST_NAME))


def load_pretrained_params(path: str, params, batch_stats=None, logger=None):
    """Non-strict restore from a converted .npz checkpoint (flattened 'a/b/c'
    keys; BatchNorm running stats under 'stats:a/b/c') — the torch-interop
    path, mirroring restore_model's tolerant model load (utils.py:40-67).

    Missing/extra keys are logged, matching keys replaced. Returns new params
    (and new batch_stats when a template is given).
    """
    data = np.load(path)

    def merge(tree, prefix_tag, tag):
        flat = _flatten("", tree)
        missing = [k for k in flat if prefix_tag + k not in data]
        if logger:
            logger.info("[MODEL_RESTORE] %s keys missing in checkpoint: %s",
                        tag, missing)

        def rebuild(prefix, t):
            out = {}
            for k, v in t.items():
                key = f"{prefix}/{k}" if prefix else k
                if isinstance(v, dict):
                    out[k] = rebuild(key, v)
                elif prefix_tag + key in data:
                    arr = np.asarray(data[prefix_tag + key])
                    out[k] = arr.astype(np.asarray(v).dtype).reshape(v.shape)
                else:
                    out[k] = v
            return out

        return rebuild("", tree)

    new_params = merge(params, "", "param")
    if logger:
        known = set(_flatten("", params))
        if batch_stats is not None:
            known |= {"stats:" + k for k in _flatten("", batch_stats)}
        extra = [k for k in data.files
                 if k not in known and not (k.startswith("stats:")
                                            and batch_stats is None)]
        logger.info("[MODEL_RESTORE] unused checkpoint keys: %s", extra)
    if batch_stats is None:
        return new_params
    return new_params, merge(batch_stats, "stats:", "batch_stats")


def _flatten(prefix, tree):
    flat = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            flat.update(_flatten(key, v))
        else:
            flat[key] = v
    return flat
