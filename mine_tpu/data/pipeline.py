"""Asynchronous input pipeline: host-side batch assembly + device staging.

Closes the real-loop vs device-step gap measured in the round-5 soak
(train_cli ~0.8 s/step vs bench's 0.22 s jitted step): the host-side feed —
item decode/sampling, collate, and a single blocking `device_put` on the
critical path — left the chip idle most of the wall-clock. Three layers,
each independently knobbed:

  1. `threaded_pair_batches` — a multi-worker batch assembler over the
     data/common.py batching core. Determinism is free because batch
     assembly is counter-based (common.item_rng): batch b is a pure
     function of (seed, epoch, b), so N workers building batches out of
     order still yield the exact sequence the synchronous loop yields,
     and checkpoint resume reproduces batch k bitwise.
  2. `prefetch` — a single background producer thread with a bounded
     queue (for iterators with no parallelizable structure, e.g. a
     custom batch_iterator that does not go through the common core).
  3. `DeviceStager` — double-buffered host->device staging: a background
     thread runs the sharding-aware transfer (`put_fn`, typically
     SynthesisTrainer.put_batch) and keeps `depth` device-resident
     batches in flight, so the H2D copy of batch k+1 overlaps the device
     compute of step k. Each staged batch carries its measured `h2d_ms`
     for the train loop's step-time breakdown.

Worker threads (not processes): the assembly work is numpy slicing/stacking
and (for real loaders) libmtio/PIL decodes that release the GIL, and the
main thread spends its step time blocked in the JAX runtime — also outside
the GIL — so threads overlap where it matters without process-spawn or
pickling costs.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterator, NamedTuple

import numpy as np

from mine_tpu.data import common

_END = object()


def prefetch(iterator: Iterator, depth: int = 2) -> Iterator:
    """Background-thread prefetch: overlaps producing `iterator`'s items
    with whatever the consumer does between `next()` calls.

    Abandoning the generator (consumer raised / broke out) stops the
    producer promptly instead of leaving a thread blocked on a full queue
    holding batch memory. Producer exceptions re-raise on the consumer.
    """
    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    err = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in iterator:
                if not _put(item):
                    return
        except BaseException as e:  # surface loader errors on the consumer
            err.append(e)
        finally:
            _put(_END)

    t = threading.Thread(target=producer, daemon=True,
                         name="mine-tpu-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()


def threaded_pair_batches(num_items: int,
                          get_pair,
                          batch_size: int,
                          shuffle: bool,
                          seed: int = 0,
                          epoch: int = 0,
                          drop_last: bool = True,
                          shard_index: int = 0,
                          num_shards: int = 1,
                          workers: int = 2,
                          prefetch_batches: int = 2
                          ) -> Iterator[Dict[str, np.ndarray]]:
    """Multi-worker batch assembly, yielded strictly in batch order.

    Same arguments and same batch sequence as
    common.iterate_pair_batches(workers=0); the pool only changes WHO
    assembles each batch. At most max(workers, prefetch_batches) batches
    are held assembled-but-unconsumed (bounded memory), enforced by a
    credit semaphore the consumer refills. A worker exception is re-raised
    on the consumer at the failing batch's position; abandoning the
    generator stops the pool promptly.

    A worker that DIES (thread killed by a non-Exception, e.g. the chaos
    suite's WorkerKill) does not end the epoch: its claimed batch is
    requeued for the surviving workers, and when the whole pool is dead
    the consumer respawns it (bounded budget, counted in
    common.PIPELINE_STATS.worker_respawns) instead of raising.
    """
    order = common.shard_order(num_items, shuffle, seed, epoch, shard_index,
                               num_shards)
    nb = common.num_batches(len(order), batch_size, drop_last)

    pool_size = max(1, workers)
    credits = threading.Semaphore(max(workers, prefetch_batches, 1))
    cv = threading.Condition()
    results: Dict[int, Dict] = {}
    errors = []
    requeue = []  # batch indices whose claiming worker died mid-assembly
    next_batch = [0]  # next index to hand to a worker
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            if not credits.acquire(timeout=0.1):
                continue
            with cv:
                if errors or (next_batch[0] >= nb and not requeue):
                    credits.release()
                    return
                if requeue:
                    b = requeue.pop()
                else:
                    b = next_batch[0]
                    next_batch[0] += 1
            try:
                batch = common.assemble_batch(get_pair, order, b, batch_size,
                                              seed, epoch)
            except Exception as e:
                with cv:
                    errors.append((b, e))
                    cv.notify_all()
                return
            except BaseException:
                # the thread is dying (injected kill / interpreter teardown):
                # hand the claimed batch back so the pool can finish it
                with cv:
                    requeue.append(b)
                    cv.notify_all()
                credits.release()
                return
            with cv:
                results[b] = batch
                cv.notify_all()

    def spawn(i):
        t = threading.Thread(target=worker, daemon=True,
                             name="mine-tpu-assembler-%d" % i)
        t.start()
        return t

    threads = [spawn(i) for i in range(pool_size)]
    # a dead pool is respawned rather than fatal, but boundedly — a pool
    # that keeps dying (systemic failure, not one bad worker) must still
    # surface instead of flapping forever
    respawn_budget = 3 * pool_size
    try:
        for b in range(nb):
            with cv:
                while b not in results:
                    # fail at the EARLIEST failing batch position so the
                    # consumer sees errors in sequence order
                    pending_err = [e for eb, e in errors if eb <= b]
                    if pending_err:
                        raise pending_err[0]
                    if not any(t.is_alive() for t in threads) \
                            and b not in results:
                        if respawn_budget > 0 and not errors:
                            respawn_budget -= 1
                            common.PIPELINE_STATS.record_respawn()
                            threads = [t for t in threads if t.is_alive()]
                            threads.append(spawn(3 * pool_size
                                                 - respawn_budget))
                            continue
                        raise RuntimeError(
                            "assembler workers died without producing "
                            "batch %d" % b)
                    cv.wait(0.1)
                batch = results.pop(b)
            yield batch
            credits.release()
    finally:
        stop.set()
        with cv:
            cv.notify_all()


class StagedBatch(NamedTuple):
    """A device-resident batch plus the measured host->device copy time."""
    batch: Dict
    h2d_ms: float


class DeviceStager:
    """Double-buffered host->device staging.

    A background thread pulls host batches from `host_batches`, runs the
    sharding-aware transfer `put_fn` (e.g. SynthesisTrainer.put_batch —
    `jax.device_put` with the mesh's input sharding), blocks until the
    copy lands (in the BACKGROUND thread — the consumer never waits on a
    copy that finished overlapped), and enqueues up to `depth` staged
    batches. depth>=2 gives the double buffer: while the device computes
    step k on buffer A, the copy of batch k+1 fills buffer B.

    Iterating yields StagedBatch(batch, h2d_ms). Producer exceptions
    re-raise on the consumer; abandoning the iterator stops the thread.
    """

    def __init__(self, host_batches: Iterator[Dict],
                 put_fn: Callable[[Dict], Dict],
                 depth: int = 2):
        self.depth = max(1, int(depth))
        self._host_batches = host_batches
        self._put_fn = put_fn

    def __iter__(self) -> Iterator[StagedBatch]:
        def stage():
            import jax
            for np_batch in self._host_batches:
                t0 = time.perf_counter()
                dev = self._put_fn(np_batch)
                jax.block_until_ready(dev)
                yield StagedBatch(dev, (time.perf_counter() - t0) * 1e3)

        return prefetch(stage(), depth=self.depth)
