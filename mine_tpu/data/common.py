"""Shared batching machinery for all dataset loaders.

One implementation of shuffle -> host-shard -> collate (the reference's
DistributedSampler + DataLoader + collate + set_data L=1 squeeze,
train.py:83-87, synthesis_task.py:184-209) used by the LLFF, RealEstate10K,
and synthetic loaders, so the semantics (shuffle the GLOBAL index list with
the epoch-seeded RNG, then stride-shard across hosts — DistributedSampler
order) cannot drift between them.

Batch assembly is COUNTER-BASED: every item draws from its own PRNG stream
keyed by (seed, epoch, position-in-shard-order), so batch b is a pure
function of (dataset, seed, epoch, b). That makes the sequence independent
of who assembles it — the sequential loop below and the multi-worker
threaded assembler (mine_tpu.data.pipeline) produce bitwise-identical
batches, and an interrupted run reproduces batch k exactly on resume.
(The pre-pipeline implementation threaded ONE RandomState through all
items in consumption order, which serializes assembly by construction.)
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Tuple

import numpy as np


def _mix64(x: int) -> int:
    """splitmix64 finalizer — decorrelates nearby (seed, epoch, position)
    keys into independent-looking 64-bit values."""
    mask = (1 << 64) - 1
    x = (x + 0x9E3779B97F4A7C15) & mask
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & mask
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & mask
    return x ^ (x >> 31)


def item_rng(seed: int, epoch: int, position: int) -> np.random.RandomState:
    """The PRNG stream of one item slot.

    `position` is the index into the host's shard order (NOT the dataset
    index): two epochs sampling the same item get different streams, and
    the stream does not depend on worker count or consumption order.
    """
    key = _mix64(((int(seed) + 1) << 40)
                 ^ ((int(epoch) + 1) << 20)
                 ^ int(position))
    return np.random.RandomState(key % (1 << 32))


def shard_order(num_items: int, shuffle: bool, seed: int, epoch: int,
                shard_index: int, num_shards: int) -> np.ndarray:
    """This host's item order: epoch-seeded global shuffle, then stride-shard
    (DistributedSampler semantics)."""
    order = np.arange(num_items)
    if shuffle:
        np.random.RandomState(seed + epoch).shuffle(order)
    return order[shard_index::num_shards]


def num_batches(num_items: int, batch_size: int, drop_last: bool) -> int:
    if drop_last:
        return num_items // batch_size
    return -(-num_items // batch_size)


def assemble_batch(get_pair: Callable[[int, np.random.RandomState],
                                      Tuple[Dict, Dict]],
                   order: np.ndarray,
                   batch_index: int,
                   batch_size: int,
                   seed: int,
                   epoch: int) -> Dict[str, np.ndarray]:
    """Assemble + collate batch `batch_index` of the shard order.

    Pure in (order, batch_index, seed, epoch): any worker can build any
    batch, in any order, and get the same bytes.
    """
    lo = batch_index * batch_size
    idxs = order[lo:lo + batch_size]
    pairs = [get_pair(int(idx), item_rng(seed, epoch, lo + j))
             for j, idx in enumerate(idxs)]
    return collate_pairs(pairs)


def iterate_pair_batches(num_items: int,
                         get_pair: Callable[[int, np.random.RandomState],
                                            Tuple[Dict, Dict]],
                         batch_size: int,
                         shuffle: bool,
                         seed: int = 0,
                         epoch: int = 0,
                         drop_last: bool = True,
                         shard_index: int = 0,
                         num_shards: int = 1,
                         workers: int = 0,
                         prefetch_batches: int = 2
                         ) -> Iterator[Dict[str, np.ndarray]]:
    """Yield collated framework batches of (src, tgt) item pairs.

    workers=0: assemble on the calling thread (the original synchronous
    path). workers>0: delegate to the threaded assembler
    (mine_tpu.data.pipeline.threaded_pair_batches) — same batch sequence,
    assembled by a worker pool with at most ~max(workers, prefetch_batches)
    batches in flight.
    """
    if workers > 0:
        from mine_tpu.data.pipeline import threaded_pair_batches
        yield from threaded_pair_batches(
            num_items, get_pair, batch_size, shuffle, seed=seed, epoch=epoch,
            drop_last=drop_last, shard_index=shard_index,
            num_shards=num_shards, workers=workers,
            prefetch_batches=prefetch_batches)
        return
    order = shard_order(num_items, shuffle, seed, epoch, shard_index,
                        num_shards)
    for b in range(num_batches(len(order), batch_size, drop_last)):
        yield assemble_batch(get_pair, order, b, batch_size, seed, epoch)


def collate_pairs(pairs) -> Dict[str, np.ndarray]:
    """(src, tgt) item dicts -> the framework batch contract (NHWC images,
    [B,3,3] intrinsics, [B,4,4] src<-tgt pose, [B,3,N] camera-frame points)."""
    return {
        "src_img": np.stack([s["img"] for s, _ in pairs]),
        "tgt_img": np.stack([t["img"] for _, t in pairs]),
        "K_src": np.stack([s["K"] for s, _ in pairs]),
        "K_tgt": np.stack([t["K"] for _, t in pairs]),
        "G_src_tgt": np.stack([t["G_src_tgt"] for _, t in pairs]),
        "pt3d_src": np.stack([s["xyzs"] for s, _ in pairs]),
        "pt3d_tgt": np.stack([t["xyzs"] for _, t in pairs]),
    }
