"""Shared batching machinery for all dataset loaders.

One implementation of shuffle -> host-shard -> collate (the reference's
DistributedSampler + DataLoader + collate + set_data L=1 squeeze,
train.py:83-87, synthesis_task.py:184-209) used by the LLFF, RealEstate10K,
and synthetic loaders, so the semantics (shuffle the GLOBAL index list with
the epoch-seeded RNG, then stride-shard across hosts — DistributedSampler
order) cannot drift between them.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Tuple

import numpy as np


def iterate_pair_batches(num_items: int,
                         get_pair: Callable[[int, np.random.RandomState],
                                            Tuple[Dict, Dict]],
                         batch_size: int,
                         shuffle: bool,
                         seed: int = 0,
                         epoch: int = 0,
                         drop_last: bool = True,
                         shard_index: int = 0,
                         num_shards: int = 1) -> Iterator[Dict[str, np.ndarray]]:
    """Yield collated framework batches of (src, tgt) item pairs."""
    order = np.arange(num_items)
    if shuffle:
        np.random.RandomState(seed + epoch).shuffle(order)
    order = order[shard_index::num_shards]

    rng = np.random.RandomState((seed + 1) * 7919 + epoch)
    batch: List = []
    for idx in order:
        batch.append(get_pair(int(idx), rng))
        if len(batch) == batch_size:
            yield collate_pairs(batch)
            batch = []
    if batch and not drop_last:
        yield collate_pairs(batch)


def collate_pairs(pairs) -> Dict[str, np.ndarray]:
    """(src, tgt) item dicts -> the framework batch contract (NHWC images,
    [B,3,3] intrinsics, [B,4,4] src<-tgt pose, [B,3,N] camera-frame points)."""
    return {
        "src_img": np.stack([s["img"] for s, _ in pairs]),
        "tgt_img": np.stack([t["img"] for _, t in pairs]),
        "K_src": np.stack([s["K"] for s, _ in pairs]),
        "K_tgt": np.stack([t["K"] for _, t in pairs]),
        "G_src_tgt": np.stack([t["G_src_tgt"] for _, t in pairs]),
        "pt3d_src": np.stack([s["xyzs"] for s, _ in pairs]),
        "pt3d_tgt": np.stack([t["xyzs"] for _, t in pairs]),
    }
