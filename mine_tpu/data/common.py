"""Shared batching machinery for all dataset loaders.

One implementation of shuffle -> host-shard -> collate (the reference's
DistributedSampler + DataLoader + collate + set_data L=1 squeeze,
train.py:83-87, synthesis_task.py:184-209) used by the LLFF, RealEstate10K,
and synthetic loaders, so the semantics (shuffle the GLOBAL index list with
the epoch-seeded RNG, then stride-shard across hosts — DistributedSampler
order) cannot drift between them.

Batch assembly is COUNTER-BASED: every item draws from its own PRNG stream
keyed by (seed, epoch, position-in-shard-order), so batch b is a pure
function of (dataset, seed, epoch, b). That makes the sequence independent
of who assembles it — the sequential loop below and the multi-worker
threaded assembler (mine_tpu.data.pipeline) produce bitwise-identical
batches, and an interrupted run reproduces batch k exactly on resume.
(The pre-pipeline implementation threaded ONE RandomState through all
items in consumption order, which serializes assembly by construction.)
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, Iterator, List, Tuple

import numpy as np

from mine_tpu import telemetry
from mine_tpu.testing import faults


# ---------------- degradation policy + counters ----------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded per-item retry (data.max_item_retries /
    data.item_retry_backoff): a transient decode/IO failure is retried
    with a fresh-but-identical PRNG stream (so a healed retry yields the
    exact bytes an unfailed load would have), then the item is quarantined
    and deterministically replaced."""
    max_item_retries: int = 2
    backoff_s: float = 0.05


_retry_policy = RetryPolicy()


def set_retry_policy(policy: RetryPolicy):
    global _retry_policy
    _retry_policy = policy


def get_retry_policy() -> RetryPolicy:
    return _retry_policy


class _PipelineStats:
    """Process-wide data-degradation counters, surfaced through the train
    loop's step-time log line (`data_errors`). Thread-safe: assembler
    workers bump them concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self.data_errors = 0       # failed item-load attempts
            self.quarantined = set()   # dataset indices proven persistently bad
            self.worker_respawns = 0

    def record_error(self, n: int = 1):
        with self._lock:
            self.data_errors += n
        telemetry.counter("data.errors").inc(n)

    def record_quarantine(self, index: int):
        with self._lock:
            new = int(index) not in self.quarantined
            self.quarantined.add(int(index))
        if new:
            telemetry.counter("data.quarantined").inc()
            telemetry.emit("data.quarantine", index=int(index))

    def is_quarantined(self, index: int) -> bool:
        with self._lock:
            return int(index) in self.quarantined

    def record_respawn(self):
        with self._lock:
            self.worker_respawns += 1
        telemetry.counter("data.worker_respawns").inc()

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"data_errors": self.data_errors,
                    "quarantined": len(self.quarantined),
                    "worker_respawns": self.worker_respawns}


PIPELINE_STATS = _PipelineStats()


def _mix64(x: int) -> int:
    """splitmix64 finalizer — decorrelates nearby (seed, epoch, position)
    keys into independent-looking 64-bit values."""
    mask = (1 << 64) - 1
    x = (x + 0x9E3779B97F4A7C15) & mask
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & mask
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & mask
    return x ^ (x >> 31)


def item_rng(seed: int, epoch: int, position: int) -> np.random.RandomState:
    """The PRNG stream of one item slot.

    `position` is the index into the host's shard order (NOT the dataset
    index): two epochs sampling the same item get different streams, and
    the stream does not depend on worker count or consumption order.
    """
    key = _mix64(((int(seed) + 1) << 40)
                 ^ ((int(epoch) + 1) << 20)
                 ^ int(position))
    return np.random.RandomState(key % (1 << 32))


def shard_order(num_items: int, shuffle: bool, seed: int, epoch: int,
                shard_index: int, num_shards: int) -> np.ndarray:
    """This host's item order: epoch-seeded global shuffle, then stride-shard
    (DistributedSampler semantics)."""
    order = np.arange(num_items)
    if shuffle:
        np.random.RandomState(seed + epoch).shuffle(order)
    return order[shard_index::num_shards]


def num_batches(num_items: int, batch_size: int, drop_last: bool) -> int:
    if drop_last:
        return num_items // batch_size
    return -(-num_items // batch_size)


def load_item(get_pair: Callable[[int, np.random.RandomState],
                                 Tuple[Dict, Dict]],
              order: np.ndarray,
              position: int,
              seed: int,
              epoch: int) -> Tuple[Dict, Dict]:
    """Load shard-order slot `position` with bounded retry, then
    deterministic quarantine-and-replace.

    Retries rebuild item_rng from scratch each attempt, so a transient
    failure that heals produces bytes identical to a run that never
    failed. A persistently-bad item (all retries exhausted) is quarantined
    and replaced by the next non-bad dataset index in shard order —
    `order[(position + k) % len(order)]`, probed with the SAME rng stream
    (still keyed to the original position): the replacement depends only
    on (order, position) and which items are persistently bad, never on
    worker count or assembly timing, so batches stay bitwise-deterministic.
    The quarantine set is a cost memo (skip the doomed retries when the
    same index comes around again), not an input to the result.
    """
    policy = _retry_policy
    n = len(order)
    last_err: Exception = None
    for k in range(n):
        idx = int(order[(position + k) % n])
        if k > 0 and PIPELINE_STATS.is_quarantined(idx):
            continue
        for attempt in range(policy.max_item_retries + 1):
            try:
                faults.on_item_load(idx)
                pair = get_pair(idx, item_rng(seed, epoch, position))
            except Exception as e:
                last_err = e
                PIPELINE_STATS.record_error()
                if attempt < policy.max_item_retries:
                    time.sleep(policy.backoff_s * (2 ** attempt))
                continue
            if k > 0:
                logging.getLogger(__name__).warning(
                    "item %d (slot %d) quarantined after %d attempts — "
                    "substituting item %d: %s", int(order[position]),
                    position, policy.max_item_retries + 1, idx, last_err)
            return pair
        PIPELINE_STATS.record_quarantine(idx)
    raise RuntimeError(
        f"every candidate item for slot {position} failed "
        f"(dataset unusable); last error: {last_err!r}") from last_err


def assemble_batch(get_pair: Callable[[int, np.random.RandomState],
                                      Tuple[Dict, Dict]],
                   order: np.ndarray,
                   batch_index: int,
                   batch_size: int,
                   seed: int,
                   epoch: int) -> Dict[str, np.ndarray]:
    """Assemble + collate batch `batch_index` of the shard order.

    Pure in (order, batch_index, seed, epoch): any worker can build any
    batch, in any order, and get the same bytes. Item loads go through
    `load_item` (bounded retry + deterministic quarantine), so one bad
    example degrades the batch, not the epoch.
    """
    lo = batch_index * batch_size
    idxs = order[lo:lo + batch_size]
    pairs = [load_item(get_pair, order, lo + j, seed, epoch)
             for j in range(len(idxs))]
    return collate_pairs(pairs)


def iterate_pair_batches(num_items: int,
                         get_pair: Callable[[int, np.random.RandomState],
                                            Tuple[Dict, Dict]],
                         batch_size: int,
                         shuffle: bool,
                         seed: int = 0,
                         epoch: int = 0,
                         drop_last: bool = True,
                         shard_index: int = 0,
                         num_shards: int = 1,
                         workers: int = 0,
                         prefetch_batches: int = 2
                         ) -> Iterator[Dict[str, np.ndarray]]:
    """Yield collated framework batches of (src, tgt) item pairs.

    workers=0: assemble on the calling thread (the original synchronous
    path). workers>0: delegate to the threaded assembler
    (mine_tpu.data.pipeline.threaded_pair_batches) — same batch sequence,
    assembled by a worker pool with at most ~max(workers, prefetch_batches)
    batches in flight.
    """
    if workers > 0:
        from mine_tpu.data.pipeline import threaded_pair_batches
        yield from threaded_pair_batches(
            num_items, get_pair, batch_size, shuffle, seed=seed, epoch=epoch,
            drop_last=drop_last, shard_index=shard_index,
            num_shards=num_shards, workers=workers,
            prefetch_batches=prefetch_batches)
        return
    order = shard_order(num_items, shuffle, seed, epoch, shard_index,
                        num_shards)
    for b in range(num_batches(len(order), batch_size, drop_last)):
        yield assemble_batch(get_pair, order, b, batch_size, seed, epoch)


def collate_pairs(pairs) -> Dict[str, np.ndarray]:
    """(src, tgt) item dicts -> the framework batch contract (NHWC images,
    [B,3,3] intrinsics, [B,4,4] src<-tgt pose, [B,3,N] camera-frame points)."""
    return {
        "src_img": np.stack([s["img"] for s, _ in pairs]),
        "tgt_img": np.stack([t["img"] for _, t in pairs]),
        "K_src": np.stack([s["K"] for s, _ in pairs]),
        "K_tgt": np.stack([t["K"] for _, t in pairs]),
        "G_src_tgt": np.stack([t["G_src_tgt"] for _, t in pairs]),
        "pt3d_src": np.stack([s["xyzs"] for s, _ in pairs]),
        "pt3d_tgt": np.stack([t["xyzs"] for _, t in pairs]),
    }
