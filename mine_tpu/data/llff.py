"""LLFF / COLMAP dataset — RAM-cached, host-sharded, fixed-shape batches.

Replaces input_pipelines/llff/nerf_dataset.py. Same data semantics:
  * scans scene dirs under root, loads each scene's COLMAP `sparse/0` model
    (nerf_dataset.py:61-65); images come from `images_{ratio}` (+`_val` for
    validation, :47-53)
  * caches every image in RAM at init, bicubic-resized to (img_w, img_h)
    (:79-81,133-136)
  * per image: G_cam_world from qvec/tvec (:143-148), K from SIMPLE_RADIAL
    params scaled by the true downsample ratio (:152-161), visible-3D-point
    camera coords and reprojected depths with P-matrix sign/norm handling
    (:164-194)
  * item = (src view, target views from the same scene): random targets for
    training, deterministic for validation (:197-234); a random fixed-size
    subset of visible 3D points per item (:118-126)

TPU-first differences:
  * explicit numpy RNG per item (reproducible; the reference uses the global
    `random` module, :118,204,229)
  * the batch iterator shards by example index across hosts — the
    DistributedSampler equivalent (train.py:83-87) — and emits the framework
    batch dict (fixed shapes, NHWC images) ready for the jitted train step
  * L=1 supervision is squeezed at batch level like set_data (:198-206)
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mine_tpu import native
from mine_tpu.data import colmap


class LLFFDataset:
    def __init__(self,
                 root: str,
                 is_validation: bool,
                 img_size: Tuple[int, int],
                 supervision_count: int = 1,
                 visible_points_count: int = 256,
                 img_pre_downsample_ratio: Optional[float] = 7.875,
                 logger=None):
        self.img_w, self.img_h = img_size
        self.is_validation = is_validation
        self.visible_points_count = visible_points_count
        self.supervision_count = supervision_count

        if img_pre_downsample_ratio is None or img_pre_downsample_ratio <= 1:
            image_folder = "images"
            pre_ratio = 1.0
        else:
            image_folder = "images_" + str(img_pre_downsample_ratio)
            pre_ratio = float(img_pre_downsample_ratio)
        if is_validation:
            image_folder += "_val"

        self.infos: List[Dict] = []           # flat list of per-image items
        self.scene_of: List[str] = []
        self.scene_to_indices: Dict[str, List[int]] = {}

        # two-phase cache fill: collect every image path + its metadata
        # first, then decode through the threaded native batch loader
        # (mine_tpu.native; sequential PIL when not built) in bounded
        # chunks — peak RAM stays dataset + one chunk, and the decode also
        # reports each image's pre-resize size (no separate header probe)
        records = []  # (scene, img_path, item, camera, points3d)
        for scene_name in sorted(os.listdir(root)):
            scene_dir = os.path.join(root, scene_name)
            sparse = os.path.join(scene_dir, "sparse/0")
            if not os.path.isdir(sparse):
                continue
            cameras, images, points3d = colmap.read_model(sparse, ext=".bin")
            assert len(cameras) == 1, scene_name

            for img_id in sorted(images.keys()):
                item = images[img_id]
                img_path = os.path.join(scene_dir, image_folder, item.name)
                if not os.path.exists(img_path):
                    continue
                records.append((scene_name, img_path, item,
                                cameras[item.camera_id], points3d))

        CHUNK = 64
        for c0 in range(0, len(records), CHUNK):
            chunk = records[c0:c0 + CHUNK]
            imgs, dims = native.load_batch_rgb(
                [r[1] for r in chunk], (self.img_w, self.img_h),
                with_src_sizes=True)
            for (scene_name, img_path, item, camera, points3d), img, (w, h) \
                    in zip(chunk, imgs, dims):
                ratios = (w * pre_ratio / self.img_w,
                          h * pre_ratio / self.img_h)
                # copy: `img` is a view into the chunk batch — the cache
                # must not pin the whole chunk per kept image
                info = self._build_info(item, camera, points3d, img.copy(),
                                        ratios)
                if info is None:
                    continue
                assert info["xyzs"].shape[1] >= visible_points_count, (
                    f"{img_path}: {info['xyzs'].shape[1]} < "
                    f"{visible_points_count} visible points")
                idx = len(self.infos)
                self.infos.append(info)
                self.scene_of.append(scene_name)
                self.scene_to_indices.setdefault(scene_name, []).append(idx)

        if logger:
            logger.info("Dataset root: %s, is_validation: %s, images: %d",
                        root, is_validation, len(self.infos))

    # ---------------- per-image preprocessing ----------------

    @staticmethod
    def _build_info(img_item: colmap.Image, camera: colmap.Camera,
                    points3d, img: np.ndarray, ratios) -> Optional[Dict]:
        ratio_x, ratio_y = ratios

        R = colmap.qvec2rotmat(img_item.qvec).astype(np.float32)
        t = img_item.tvec.astype(np.float32)
        G_cam_world = np.eye(4, dtype=np.float32)
        G_cam_world[:3, :3] = R
        G_cam_world[:3, 3] = t

        # SIMPLE_RADIAL: params = (f, cx, cy, k); focal scaled per axis by the
        # true downsample ratio (nerf_dataset.py:152-161)
        K = np.array([[camera.params[0] / ratio_x, 0, camera.params[1] / ratio_x],
                      [0, camera.params[0] / ratio_y, camera.params[2] / ratio_y],
                      [0, 0, 1]], dtype=np.float32)

        tracked = img_item.point3D_ids != -1
        if tracked.sum() == 0:
            return None
        pids = img_item.point3D_ids[tracked]
        xys = img_item.xys[tracked].T.astype(np.float32)  # [2,N] original px
        xys = xys / np.array([[ratio_x], [ratio_y]], dtype=np.float32)
        xyz_world = np.stack([points3d[p].xyz for p in pids], axis=1)  # [3,N]

        # camera-frame coords + projective depths with sign/norm handling
        # (nerf_dataset.py:164-194)
        I0 = np.eye(3, 4, dtype=np.float32)
        P = K @ I0 @ G_cam_world
        det_sign = np.sign(np.linalg.det(P[:, :-1]))
        m3_norm = np.linalg.norm(P[2, :-1])

        xyz_world_h = np.concatenate(
            [xyz_world, np.ones((1, xyz_world.shape[1]), np.float32)], axis=0)
        xyz_cam_h = G_cam_world @ xyz_world_h.astype(np.float32)
        xyz_cam_h = xyz_cam_h / xyz_cam_h[-1:]
        reproj = K @ I0 @ xyz_cam_h
        depths = (det_sign * reproj[-1]) / m3_norm

        return {
            "img": np.ascontiguousarray(img),                # [H,W,3]
            "G_cam_world": G_cam_world,
            "K": K,
            "K_inv": np.linalg.inv(K).astype(np.float32),
            "xyzs": xyz_cam_h[:3].astype(np.float32),        # [3,N] camera frame
            "xyzs_ids": pids,
            "depths": depths.astype(np.float32),
        }

    # ---------------- item sampling ----------------

    def __len__(self) -> int:
        return len(self.infos)

    def get_item(self, index: int, rng: np.random.RandomState):
        """(src_item, [tgt_items]) with per-item point subsampling.

        Mirrors NeRFDataset.__getitem__ + _sample_tgt_items
        (nerf_dataset.py:105-127,197-234).
        """
        scene = self.scene_of[index]
        src = dict(self.infos[index])
        src = self._subsample_points(src, rng)

        indices = [i for i in self.scene_to_indices[scene] if i != index]
        if not self.is_validation:
            chosen = rng.choice(len(indices), size=self.supervision_count,
                                replace=False)
            chosen = [indices[c] for c in chosen]
        else:
            chosen = [indices[(index + 1) % len(indices) - 1]]

        G_src_world = src["G_cam_world"]
        tgts = []
        for j in chosen:
            tgt = dict(self.infos[j])
            tgt = self._subsample_points(tgt, rng)
            tgt["G_src_tgt"] = (
                G_src_world @ np.linalg.inv(tgt["G_cam_world"])).astype(np.float32)
            tgts.append(tgt)
        return src, tgts

    def _subsample_points(self, info: Dict, rng: np.random.RandomState) -> Dict:
        n = info["xyzs"].shape[1]
        sel = rng.choice(n, size=self.visible_points_count, replace=False)
        out = dict(info)
        out["xyzs"] = info["xyzs"][:, sel]
        out["xyzs_ids"] = info["xyzs_ids"][sel]
        out["depths"] = info["depths"][sel]
        return out

    # ---------------- batching ----------------

    def batch_iterator(self,
                       batch_size: int,
                       shuffle: bool,
                       seed: int = 0,
                       epoch: int = 0,
                       drop_last: bool = True,
                       shard_index: int = 0,
                       num_shards: int = 1,
                       workers: int = 0,
                       prefetch_batches: int = 2
                       ) -> Iterator[Dict[str, np.ndarray]]:
        """Fixed-shape framework batches, sharded across hosts by index.

        Equivalent to DistributedSampler(set_epoch) + DataLoader + collate +
        set_data's L=1 squeeze (train.py:83-87, synthesis_task.py:184-209).
        """
        from mine_tpu.data.common import iterate_pair_batches

        def get_pair(idx, rng):
            src, tgts = self.get_item(idx, rng)
            return src, tgts[0]

        yield from iterate_pair_batches(
            len(self.infos), get_pair, batch_size, shuffle, seed=seed,
            epoch=epoch, drop_last=drop_last, shard_index=shard_index,
            num_shards=num_shards, workers=workers,
            prefetch_batches=prefetch_batches)


def get_dataset(config: Dict, logger=None) -> Tuple[LLFFDataset, LLFFDataset]:
    """Build (train, val) datasets per config — the reference's get_dataset
    (train.py:69-103). Only the LLFF/COLMAP loader exists upstream; other
    dataset names raise NotImplementedError there too (train.py:100-101)."""
    name = config["data.name"]
    if name == "synthetic":
        # procedural scene, no files needed: smoke-tests the full
        # train/eval/CLI stack (mine_tpu.data.synthetic)
        from mine_tpu.data.synthetic import SyntheticPairDataset
        mk = lambda seed: SyntheticPairDataset(  # noqa: E731
            num_views=int(config.get("data.num_seq_per_gpu", 4)) + 2,
            num_points=int(config.get("data.visible_point_count", 256)),
            height=int(config["data.img_h"]),
            width=int(config["data.img_w"]),
            seed=seed)
        return mk(0), mk(1)
    if name == "realestate10k":
        # capability beyond the reference (its get_dataset raises for
        # everything but llff, train.py:100-101) — see data/realestate10k.py
        from mine_tpu.data.realestate10k import RealEstate10KDataset
        common = dict(
            img_size=(config["data.img_w"], config["data.img_h"]),
            # default matches mpi_config_from_dict (256): a missing key must
            # not silently pair dummy points with an enabled disparity loss
            visible_points_count=config.get("data.visible_point_count", 256),
            frames_apart=config.get("testing.frames_apart", "random"),
            max_frame_gap=config.get("data.max_frame_gap", 30),
            points_root=config.get("data.points_root"),
            logger=logger)
        train = RealEstate10KDataset(
            root=config["data.training_set_path"],
            is_validation=False, **common)
        val = RealEstate10KDataset(
            root=config["data.val_set_path"],
            is_validation=True,
            pairs_json=config.get("data.val_pairs_json"),
            tgt_key=config.get("data.val_pairs_tgt", "tgt_img_obj_5_frames"),
            **common)
        return train, val
    if name == "flowers":
        # capability beyond the reference: consumes its shipped calibration
        # assets (input_pipelines/flowers/) — see data/flowers.py
        from mine_tpu.data.flowers import FlowersDataset
        common = dict(
            img_size=(config["data.img_w"], config["data.img_h"]),
            cam_params_path=config.get("data.cam_params_path"),
            grid=config.get("data.lenslet_grid", 8),
            lenslet_stride=config.get("data.lenslet_stride", 14),
            logger=logger)
        train = FlowersDataset(root=config["data.training_set_path"],
                               is_validation=False, **common)
        val = FlowersDataset(root=config["data.val_set_path"],
                             is_validation=True, **common)
        return train, val
    if name == "kitti_raw":
        # capability beyond the reference: rectified stereo pairs from the
        # public KITTI raw layout — see data/kitti.py
        from mine_tpu.data.kitti import KITTIRawDataset
        sz = (config["data.img_w"], config["data.img_h"])
        train = KITTIRawDataset(root=config["data.training_set_path"],
                                is_validation=False, img_size=sz,
                                logger=logger)
        val = KITTIRawDataset(root=config["data.val_set_path"],
                              is_validation=True, img_size=sz, logger=logger)
        return train, val
    if name == "dtu":
        # capability beyond the reference: MVSNet-preprocessed DTU layout,
        # honoring its dtu-only config keys — see data/dtu.py
        from mine_tpu.data.dtu import DTUDataset
        common = dict(
            img_size=(config["data.img_w"], config["data.img_h"]),
            rotation_pi_ratio=float(config.get("data.rotation_pi_ratio", 3)),
            is_exclude_views=bool(config.get("data.is_exclude_views", False)),
            intrinsics_scale=float(
                config.get("data.dtu_intrinsics_scale", 4) or 4),
            logger=logger)
        train = DTUDataset(root=config["data.training_set_path"],
                           is_validation=False, **common)
        val = DTUDataset(root=config["data.val_set_path"],
                         is_validation=True, **common)
        return train, val
    if name != "llff":
        raise NotImplementedError(
            f"dataset '{name}': unknown dataset name (the reference itself "
            f"ships only the LLFF loader, train.py:100-101; this framework "
            f"adds realestate10k/kitti_raw/flowers/dtu/synthetic)")
    train = LLFFDataset(
        root=config["data.training_set_path"],
        is_validation=False,
        img_size=(config["data.img_w"], config["data.img_h"]),
        supervision_count=config.get("data.num_tgt_views", 1),
        visible_points_count=config.get("data.visible_point_count", 256),
        img_pre_downsample_ratio=config.get("data.img_pre_downsample_ratio"),
        logger=logger)
    val = LLFFDataset(
        root=config["data.training_set_path"],
        is_validation=True,
        img_size=(config["data.img_w"], config["data.img_h"]),
        supervision_count=config.get("data.num_tgt_views", 1),
        visible_points_count=config.get("data.visible_point_count", 256),
        img_pre_downsample_ratio=config.get("data.img_pre_downsample_ratio"),
        logger=logger)
    return train, val
