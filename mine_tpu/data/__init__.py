from mine_tpu.data.synthetic import SyntheticMPIDataset, make_batch  # noqa: F401
