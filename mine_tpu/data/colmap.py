"""COLMAP sparse-model reader (numpy, clean-room from the public format).

Provides what the reference vendors in input_pipelines/colmap_utils.py
(read_model :420, read_cameras/images/points3d_* :128-418, qvec2rotmat :454):
cameras / images / points3D from `.bin` or `.txt` sparse models, used
read-only at dataset init.

Binary layout (COLMAP's documented on-disk format):
  cameras.bin:  u64 count, then per camera: i32 id, i32 model_id, u64 w, u64 h,
                f64 params[num_params(model)]
  images.bin:   u64 count, then per image: i32 id, f64 qvec[4], f64 tvec[3],
                i32 camera_id, name '\0'-terminated, u64 n_pts,
                (f64 x, f64 y, i64 point3D_id) * n_pts
  points3D.bin: u64 count, then per point: i64 id, f64 xyz[3], u8 rgb[3],
                f64 error, u64 track_len, (i32 image_id, i32 pt2d_idx) * len
"""

from __future__ import annotations

import os
import struct
from typing import Dict, NamedTuple, Tuple

import numpy as np


class Camera(NamedTuple):
    id: int
    model: str
    width: int
    height: int
    params: np.ndarray


class Image(NamedTuple):
    id: int
    qvec: np.ndarray          # [4] (w, x, y, z)
    tvec: np.ndarray          # [3]
    camera_id: int
    name: str
    xys: np.ndarray           # [N, 2] keypoint pixel coords
    point3D_ids: np.ndarray   # [N] int64, -1 if untracked


class Point3D(NamedTuple):
    id: int
    xyz: np.ndarray           # [3]
    rgb: np.ndarray           # [3] uint8
    error: float
    image_ids: np.ndarray
    point2D_idxs: np.ndarray


# model_id -> (name, num_params)
CAMERA_MODELS = {
    0: ("SIMPLE_PINHOLE", 3), 1: ("PINHOLE", 4), 2: ("SIMPLE_RADIAL", 4),
    3: ("RADIAL", 5), 4: ("OPENCV", 8), 5: ("OPENCV_FISHEYE", 8),
    6: ("FULL_OPENCV", 12), 7: ("FOV", 5), 8: ("SIMPLE_RADIAL_FISHEYE", 4),
    9: ("RADIAL_FISHEYE", 5), 10: ("THIN_PRISM_FISHEYE", 12),
}
CAMERA_MODEL_IDS = {name: (mid, n) for mid, (name, n) in CAMERA_MODELS.items()}


def qvec2rotmat(qvec: np.ndarray) -> np.ndarray:
    """Unit quaternion (w,x,y,z) -> 3x3 rotation matrix."""
    w, x, y, z = qvec
    return np.array([
        [1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)],
        [2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)],
        [2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)],
    ])


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, fmt: str):
        size = struct.calcsize(fmt)
        out = struct.unpack_from(fmt, self.data, self.pos)
        self.pos += size
        return out

    def take_string(self) -> str:
        end = self.data.index(b"\x00", self.pos)
        s = self.data[self.pos:end].decode("utf-8")
        self.pos = end + 1
        return s


def read_cameras_binary(path: str) -> Dict[int, Camera]:
    with open(path, "rb") as f:
        r = _Reader(f.read())
    (n,) = r.take("<Q")
    cameras = {}
    for _ in range(n):
        cam_id, model_id, width, height = r.take("<iiQQ")
        name, n_params = CAMERA_MODELS[model_id]
        params = np.array(r.take(f"<{n_params}d"))
        cameras[cam_id] = Camera(cam_id, name, width, height, params)
    return cameras


def read_images_binary(path: str) -> Dict[int, Image]:
    with open(path, "rb") as f:
        r = _Reader(f.read())
    (n,) = r.take("<Q")
    images = {}
    for _ in range(n):
        (img_id,) = r.take("<i")
        qvec = np.array(r.take("<4d"))
        tvec = np.array(r.take("<3d"))
        (cam_id,) = r.take("<i")
        name = r.take_string()
        (n_pts,) = r.take("<Q")
        raw = np.frombuffer(r.data, dtype=np.dtype("<f8,<f8,<i8"),
                            count=n_pts, offset=r.pos)
        r.pos += 24 * n_pts
        xys = np.stack([raw["f0"], raw["f1"]], axis=1) if n_pts else np.zeros((0, 2))
        ids = raw["f2"].astype(np.int64) if n_pts else np.zeros((0,), np.int64)
        images[img_id] = Image(img_id, qvec, tvec, cam_id, name, xys, ids)
    return images


def read_points3d_binary(path: str) -> Dict[int, Point3D]:
    with open(path, "rb") as f:
        r = _Reader(f.read())
    (n,) = r.take("<Q")
    points = {}
    for _ in range(n):
        (pid,) = r.take("<q")
        xyz = np.array(r.take("<3d"))
        rgb = np.array(r.take("<3B"), dtype=np.uint8)
        (error,) = r.take("<d")
        (track_len,) = r.take("<Q")
        track = np.frombuffer(r.data, dtype="<i4", count=2 * track_len,
                              offset=r.pos).reshape(-1, 2)
        r.pos += 8 * track_len
        points[pid] = Point3D(pid, xyz, rgb, error,
                              track[:, 0].copy(), track[:, 1].copy())
    return points


def read_cameras_text(path: str) -> Dict[int, Camera]:
    cameras = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            cam_id = int(parts[0])
            model = parts[1]
            cameras[cam_id] = Camera(cam_id, model, int(parts[2]), int(parts[3]),
                                     np.array([float(p) for p in parts[4:]]))
    return cameras


def read_images_text(path: str) -> Dict[int, Image]:
    images = {}
    with open(path) as f:
        lines = [l.strip() for l in f
                 if l.strip() and not l.strip().startswith("#")]
    for i in range(0, len(lines), 2):
        parts = lines[i].split()
        img_id = int(parts[0])
        qvec = np.array([float(p) for p in parts[1:5]])
        tvec = np.array([float(p) for p in parts[5:8]])
        cam_id = int(parts[8])
        name = parts[9]
        pts = lines[i + 1].split() if i + 1 < len(lines) else []
        trip = np.array([float(p) for p in pts]).reshape(-1, 3) if pts else \
            np.zeros((0, 3))
        images[img_id] = Image(img_id, qvec, tvec, cam_id, name,
                               trip[:, :2], trip[:, 2].astype(np.int64))
    return images


def read_points3d_text(path: str) -> Dict[int, Point3D]:
    points = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            pid = int(parts[0])
            xyz = np.array([float(p) for p in parts[1:4]])
            rgb = np.array([int(p) for p in parts[4:7]], dtype=np.uint8)
            error = float(parts[7])
            track = np.array([int(p) for p in parts[8:]]).reshape(-1, 2) \
                if len(parts) > 8 else np.zeros((0, 2), np.int64)
            points[pid] = Point3D(pid, xyz, rgb, error,
                                  track[:, 0], track[:, 1])
    return points


def read_model(path: str, ext: str = ".bin") -> Tuple[Dict, Dict, Dict]:
    """Load (cameras, images, points3D) from a COLMAP sparse dir.

    Same entry point shape as the reference's colmap_utils.read_model(:420).
    """
    if ext == ".bin":
        cameras = read_cameras_binary(os.path.join(path, "cameras.bin"))
        images = read_images_binary(os.path.join(path, "images.bin"))
        points3d = read_points3d_binary(os.path.join(path, "points3D.bin"))
    elif ext == ".txt":
        cameras = read_cameras_text(os.path.join(path, "cameras.txt"))
        images = read_images_text(os.path.join(path, "images.txt"))
        points3d = read_points3d_text(os.path.join(path, "points3D.txt"))
    else:
        raise ValueError(f"unknown model extension {ext}")
    return cameras, images, points3d


def write_model_binary(path: str, cameras: Dict[int, Camera],
                       images: Dict[int, Image],
                       points3d: Dict[int, Point3D]) -> None:
    """Write a sparse model in binary format (round-trip tests / tooling)."""
    with open(os.path.join(path, "cameras.bin"), "wb") as f:
        f.write(struct.pack("<Q", len(cameras)))
        for cam in cameras.values():
            model_id, n_params = CAMERA_MODEL_IDS[cam.model]
            f.write(struct.pack("<iiQQ", cam.id, model_id, cam.width, cam.height))
            f.write(struct.pack(f"<{n_params}d", *cam.params[:n_params]))
    with open(os.path.join(path, "images.bin"), "wb") as f:
        f.write(struct.pack("<Q", len(images)))
        for img in images.values():
            f.write(struct.pack("<i", img.id))
            f.write(struct.pack("<4d", *img.qvec))
            f.write(struct.pack("<3d", *img.tvec))
            f.write(struct.pack("<i", img.camera_id))
            f.write(img.name.encode("utf-8") + b"\x00")
            f.write(struct.pack("<Q", len(img.xys)))
            for xy, pid in zip(img.xys, img.point3D_ids):
                f.write(struct.pack("<ddq", xy[0], xy[1], int(pid)))
    with open(os.path.join(path, "points3D.bin"), "wb") as f:
        f.write(struct.pack("<Q", len(points3d)))
        for pt in points3d.values():
            f.write(struct.pack("<q", pt.id))
            f.write(struct.pack("<3d", *pt.xyz))
            f.write(struct.pack("<3B", *pt.rgb))
            f.write(struct.pack("<d", pt.error))
            f.write(struct.pack("<Q", len(pt.image_ids)))
            for iid, pidx in zip(pt.image_ids, pt.point2D_idxs):
                f.write(struct.pack("<ii", int(iid), int(pidx)))
