"""DTU MVS dataset — calibrated scan views as (src, tgt), rotation-limited.

Capability beyond the reference's code: it ships a dtu config
(configs/params_dtu.yaml, with `data.rotation_pi_ratio` and
`data.is_exclude_views` that only this dataset uses, plus
`mpi.is_bg_depth_inf: true`) but no loader (train.py:100-101 raises). This
loader consumes the standard MVSNet-preprocessed DTU layout:

  <root>/Cameras/<VVVVVVVV>_cam.txt       per-view calibration:
                                            extrinsic\n<4x4 world->cam>
                                            intrinsic\n<3x3>
                                            <depth_min> <depth_interval>
  <root>/Rectified/scanN_train/rect_<VVV>_<L>_r5000.png
                                          view VVV (1-based), light L

Pairing honors the dtu config keys: a target view qualifies when the
relative rotation angle between its camera and the source's is at most
pi / rotation_pi_ratio (the dataset is a hemisphere rig — unrestricted
pairs have near-zero overlap), and `is_exclude_views` drops the standard
MVS evaluation views from training. Training picks a random qualifying
target and a random light; validation is deterministic.

DTU's MPI mode: depth is composited against an infinite background
(`mpi.is_bg_depth_inf`, weighted_sum_mpi, mpi_rendering.py:74-77) and the
valid-mask threshold is 0. Sparse SfM points are not part of the MVSNet
distribution: dtu is in the no-disparity-loss set (synthesis_task.py:
213-214), so items carry dummy points.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from mine_tpu import native

# the customary DTU evaluation view subset (MVS protocol) dropped when
# data.is_exclude_views is set
EVAL_VIEWS = (3, 13, 23, 33, 43)


def parse_dtu_cam(path: str) -> Dict[str, np.ndarray]:
    """MVSNet cam txt -> {extrinsic [4,4], intrinsic [3,3], depth [2]}."""
    with open(path) as f:
        tokens = f.read().split()
    out = {}
    i = 0
    while i < len(tokens):
        t = tokens[i].lower()
        if t == "extrinsic":
            out["extrinsic"] = np.asarray(
                [float(x) for x in tokens[i + 1:i + 17]],
                np.float32).reshape(4, 4)
            i += 17
        elif t == "intrinsic":
            out["intrinsic"] = np.asarray(
                [float(x) for x in tokens[i + 1:i + 10]],
                np.float32).reshape(3, 3)
            i += 10
        else:
            try:
                out.setdefault("depth", []).append(float(t))
            except ValueError:
                pass
            i += 1
    if "depth" in out:
        out["depth"] = np.asarray(out["depth"], np.float32)
    return out


def rotation_angle(R_a: np.ndarray, R_b: np.ndarray) -> float:
    """Geodesic angle between two rotations (radians)."""
    R = R_a @ R_b.T
    c = np.clip((np.trace(R) - 1.0) / 2.0, -1.0, 1.0)
    return float(np.arccos(c))


class DTUDataset:
    def __init__(self,
                 root: str,
                 is_validation: bool,
                 img_size: Tuple[int, int],
                 rotation_pi_ratio: float = 3.0,
                 is_exclude_views: bool = False,
                 intrinsics_scale: float = 4.0,
                 logger=None):
        self.img_w, self.img_h = img_size
        self.is_validation = is_validation
        self.max_angle = np.pi / float(rotation_pi_ratio)
        # MVSNet cam files store intrinsics at quarter resolution (they
        # match the 160x128 depth maps, not the 640x512 Rectified images);
        # this factor maps cam-file pixels -> Rectified-image pixels
        self.intrinsics_scale = float(intrinsics_scale)

        # ---- calibrations (shared across scans) ----
        # standard training distribution nests them in Cameras/train/
        self.cams: Dict[int, Dict[str, np.ndarray]] = {}
        cam_dir = os.path.join(root, "Cameras")
        paths = sorted(glob.glob(os.path.join(cam_dir, "*_cam.txt"))) \
            or sorted(glob.glob(os.path.join(cam_dir, "train", "*_cam.txt")))
        for p in paths:
            view = int(os.path.basename(p).split("_")[0])
            cam = parse_dtu_cam(p)
            if "extrinsic" in cam and "intrinsic" in cam:
                self.cams[view] = cam
        if not self.cams:
            raise ValueError(
                f"no camera files under {cam_dir} (or {cam_dir}/train)")

        # ---- scan image index: scan -> view -> {light: path} ----
        pat = re.compile(r"rect_(\d+)_(\w+)_r5000\.png$")
        self.scans: Dict[str, Dict[int, Dict[str, str]]] = {}
        for scan_dir in sorted(glob.glob(os.path.join(root, "Rectified",
                                                      "scan*"))):
            scan = os.path.basename(scan_dir)
            views: Dict[int, Dict[str, str]] = {}
            for img in sorted(glob.glob(os.path.join(scan_dir, "rect_*.png"))):
                m = pat.search(os.path.basename(img))
                if not m:
                    continue
                view = int(m.group(1)) - 1  # filenames are 1-based
                if view not in self.cams:
                    continue
                if is_exclude_views and not is_validation \
                        and view in EVAL_VIEWS:
                    continue
                views.setdefault(view, {})[m.group(2)] = img
            if len(views) >= 2:
                self.scans[scan] = views

        # ---- qualifying (src, tgt) view pairs per the rotation limit ----
        self.pair_views: Dict[int, List[int]] = {}
        views_all = sorted(self.cams)
        for a in views_all:
            Ra = self.cams[a]["extrinsic"][:3, :3]
            self.pair_views[a] = [
                b for b in views_all if b != a
                and rotation_angle(Ra, self.cams[b]["extrinsic"][:3, :3])
                <= self.max_angle]

        # flat item list: (scan, src_view) with >=1 qualifying target present
        self.items: List[Tuple[str, int]] = []
        for scan, views in sorted(self.scans.items()):
            for v in sorted(views):
                if any(t in views for t in self.pair_views.get(v, ())):
                    self.items.append((scan, v))
        if logger is not None:
            logger.info(
                "DTU %s: %d scans, %d items, rotation limit %.1f deg",
                "val" if is_validation else "train", len(self.scans),
                len(self.items), np.degrees(self.max_angle))

    def __len__(self) -> int:
        return len(self.items)

    # ---------------- items ----------------

    def _view_info(self, scan: str, view: int, light: str) -> Dict:
        path = self.scans[scan][view][light]
        img, (w0, h0) = native.load_image_rgb(
            path, (self.img_w, self.img_h), with_src_size=True)
        K = self.cams[view]["intrinsic"] * self.intrinsics_scale
        K[2, 2] = 1.0
        K[0] *= self.img_w / w0
        K[1] *= self.img_h / h0
        return {"img": img, "K": K.astype(np.float32),
                "G_cam_world": self.cams[view]["extrinsic"],
                "xyzs": np.ones((3, 1), np.float32)}

    def get_item(self, index: int, rng: np.random.RandomState):
        scan, v_src = self.items[index]
        views = self.scans[scan]
        candidates = [t for t in self.pair_views[v_src] if t in views]
        if self.is_validation:
            v_tgt = candidates[index % len(candidates)]
            light_s = sorted(views[v_src])[0]
            light_t = light_s if light_s in views[v_tgt] \
                else sorted(views[v_tgt])[0]
        else:
            v_tgt = candidates[rng.randint(len(candidates))]
            light_s = sorted(views[v_src])[rng.randint(len(views[v_src]))]
            light_t = light_s if light_s in views[v_tgt] \
                else sorted(views[v_tgt])[0]
        src = self._view_info(scan, v_src, light_s)
        tgt = self._view_info(scan, v_tgt, light_t)
        tgt["G_src_tgt"] = (
            src["G_cam_world"]
            @ np.linalg.inv(tgt["G_cam_world"])).astype(np.float32)
        return src, tgt

    def batch_iterator(self,
                       batch_size: int,
                       shuffle: bool,
                       seed: int = 0,
                       epoch: int = 0,
                       drop_last: bool = True,
                       shard_index: int = 0,
                       num_shards: int = 1,
                       workers: int = 0,
                       prefetch_batches: int = 2
                       ) -> Iterator[Dict[str, np.ndarray]]:
        from mine_tpu.data.common import iterate_pair_batches
        yield from iterate_pair_batches(
            len(self), self.get_item, batch_size, shuffle, seed=seed,
            epoch=epoch, drop_last=drop_last, shard_index=shard_index,
            num_shards=num_shards, workers=workers,
            prefetch_batches=prefetch_batches)
