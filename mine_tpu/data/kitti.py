"""KITTI raw dataset — rectified stereo pairs as (src, tgt).

Capability beyond the reference's code: it ships a kitti_raw config
(configs/params_kitti_raw.yaml, 384x128) but no loader (train.py:100-101
raises). Following the single-image-MPI lineage MINE builds on, KITTI
training pairs are the rectified stereo views: after rectification both
cameras share the rotation and differ by a pure x-baseline, which the
standard calib files give exactly — no SfM needed.

On-disk layout (the public KITTI raw sync+rect distribution):
  <root>/<date>/calib_cam_to_cam.txt        P_rect_02 / P_rect_03 (3x4)
  <root>/<date>/<date>_drive_XXXX_sync/image_02/data/NNNNNNNNNN.png  (left)
  <root>/<date>/<date>_drive_XXXX_sync/image_03/data/NNNNNNNNNN.png  (right)

Geometry: P_rect_0i = K_rect [I | t_i] with t_i,x = P[0,3]/fx relative to
the rectified cam-0 frame; the right-from-left transform is a pure
translation of (t_3x - t_2x) (~ -0.54 m x-baseline, right camera sits at
more negative rectified x). Training randomly swaps which eye is src so the
model sees both directions; validation is deterministic left->right.

kitti_raw is a no-SfM-points dataset (synthesis_task.py:213-214): items
carry dummy points and the sparse-disparity loss / scale factor are off.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np
from PIL import Image as PILImage

from mine_tpu import native


def parse_calib_cam_to_cam(path: str) -> Dict[str, np.ndarray]:
    """calib_cam_to_cam.txt -> {key: array} (P_rect_02/03 as [3,4],
    S_rect_02 as [w, h])."""
    out = {}
    with open(path) as f:
        for ln in f:
            if ":" not in ln:
                continue
            key, val = ln.split(":", 1)
            try:
                arr = np.asarray([float(x) for x in val.split()], np.float32)
            except ValueError:
                continue
            if key.startswith("P_rect"):
                arr = arr.reshape(3, 4)
            out[key.strip()] = arr
    return out


def stereo_geometry(calib: Dict[str, np.ndarray]):
    """(K_rect [3,3] at native resolution, native [w,h], right-from-left
    x-baseline in meters)."""
    P2, P3 = calib["P_rect_02"], calib["P_rect_03"]
    K = P2[:, :3].copy()
    fx = P2[0, 0]
    tx2, tx3 = P2[0, 3] / fx, P3[0, 3] / fx
    size = calib.get("S_rect_02")
    return K, size, float(tx3 - tx2)


class KITTIRawDataset:
    def __init__(self,
                 root: str,
                 is_validation: bool,
                 img_size: Tuple[int, int],
                 drives: Optional[List[str]] = None,
                 logger=None):
        self.img_w, self.img_h = img_size
        self.is_validation = is_validation

        # (left_path, right_path, K_scaled, baseline) per frame
        self.items: List[Tuple[str, str, np.ndarray, float]] = []
        for date_dir in sorted(glob.glob(os.path.join(root, "*"))):
            calib_path = os.path.join(date_dir, "calib_cam_to_cam.txt")
            if not os.path.isfile(calib_path):
                continue
            calib = parse_calib_cam_to_cam(calib_path)
            if "P_rect_02" not in calib or "P_rect_03" not in calib:
                continue
            K_native, size, baseline = stereo_geometry(calib)
            for drive in sorted(glob.glob(os.path.join(date_dir,
                                                       "*_sync"))):
                if drives and os.path.basename(drive) not in drives:
                    continue
                left_dir = os.path.join(drive, "image_02", "data")
                right_dir = os.path.join(drive, "image_03", "data")
                if not os.path.isdir(left_dir):
                    continue
                for lp in sorted(glob.glob(os.path.join(left_dir, "*.png"))):
                    rp = os.path.join(right_dir, os.path.basename(lp))
                    if not os.path.exists(rp):
                        continue
                    if size is not None:
                        w0, h0 = float(size[0]), float(size[1])
                    else:
                        with PILImage.open(lp) as im:
                            w0, h0 = im.size
                    K = K_native.copy()
                    K[0] *= self.img_w / w0
                    K[1] *= self.img_h / h0
                    self.items.append((lp, rp, K.astype(np.float32),
                                       baseline))
        if logger is not None:
            logger.info("KITTI raw %s: %d stereo pairs",
                        "val" if is_validation else "train", len(self.items))

    def __len__(self) -> int:
        return len(self.items)

    def _load(self, path: str) -> np.ndarray:
        # native decode+resize (C++ libjpeg/libpng; PIL-parity fallback)
        return native.load_image_rgb(path, (self.img_w, self.img_h))

    def get_item(self, index: int, rng: np.random.RandomState):
        lp, rp, K, baseline = self.items[index]
        swap = (not self.is_validation) and bool(rng.randint(2))
        src_p, tgt_p = (rp, lp) if swap else (lp, rp)
        # src <- tgt transform: pure x-translation of the baseline (rectified
        # frames share rotation). right-from-left = +baseline as src<-tgt
        # when src is the left eye, negated when swapped.
        t = -baseline if swap else baseline
        G_src_tgt = np.eye(4, dtype=np.float32)
        G_src_tgt[0, 3] = -t
        src = {"img": self._load(src_p), "K": K,
               "xyzs": np.ones((3, 1), np.float32)}
        tgt = {"img": self._load(tgt_p), "K": K,
               "G_src_tgt": G_src_tgt,
               "xyzs": np.ones((3, 1), np.float32)}
        return src, tgt

    def batch_iterator(self,
                       batch_size: int,
                       shuffle: bool,
                       seed: int = 0,
                       epoch: int = 0,
                       drop_last: bool = True,
                       shard_index: int = 0,
                       num_shards: int = 1,
                       workers: int = 0,
                       prefetch_batches: int = 2
                       ) -> Iterator[Dict[str, np.ndarray]]:
        from mine_tpu.data.common import iterate_pair_batches
        yield from iterate_pair_batches(
            len(self), self.get_item, batch_size, shuffle, seed=seed,
            epoch=epoch, drop_last=drop_last, shard_index=shard_index,
            num_shards=num_shards, workers=workers,
            prefetch_batches=prefetch_batches)
