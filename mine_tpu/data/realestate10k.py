"""RealEstate10K dataset — video-sequence pairs, RAM-cached, host-sharded.

Capability beyond the reference's code: its released-model grid is headlined
by RealEstate10K (README.md:43-46) and it ships the eval-pair protocol file
(input_pipelines/realestate10k/test_data_jsons/validation_pairs.json), but
its get_dataset raises NotImplementedError for everything except LLFF
(train.py:100-101). This loader supplies the missing pipeline with the same
batch contract as data/llff.py, so the whole trainer/eval stack works
unchanged.

On-disk layout (the public dataset's standard extraction):
  <root>/<seq>.txt            camera file: line 1 = video URL; each further
                              line = ts fx fy cx cy k1 k2 r11 r12 r13 t1 r21
                              ... t3 (normalized intrinsics, 3x4 world->cam)
  <root>/<seq>/<ts>.png|jpg   extracted frames named by timestamp

Pairing:
  * training: for each frame, a target sampled within +-max_frame_gap frames
    of the source (testing.frames_apart: "random", or an int for a fixed
    offset) — the video-sequence analog of LLFF's same-scene target pick.
  * validation: the reference's released protocol — one JSONL line per pair
    with src_img_obj and tgt_img_obj_{5,10}_frames / tgt_img_obj_random
    entries carrying (sequence_id, frame_ts, camera_intrinsics 4-vector,
    camera_pose 3x4). `tgt_key` picks the protocol column.

Sparse 3D points: the public dataset carries none (the reference's internal
pipeline evidently had them — visible_point_count: 256 in its realestate
config). Two supported modes:
  * points_root/<seq>.npz with key "xyz" [N,3] world-frame points (e.g. from
    an offline SfM pass) -> per-view camera-frame visible subsets, exactly
    like the LLFF loader.
  * data.visible_point_count: 0 -> dummy points; mpi_config_from_dict then
    disables the sparse-disparity loss and scale factor (documented
    TPU-native config extension).
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from mine_tpu import native

_FRAME_EXTS = (".png", ".jpg", ".jpeg")


def parse_camera_file(path: str) -> Dict[str, Dict[str, np.ndarray]]:
    """Parse one RealEstate10K camera txt -> {ts: {intrinsics[4], pose[3,4]}}.

    Lines: timestamp fx fy cx cy k1 k2 p11..p34 (19 floats); intrinsics are
    resolution-normalized; pose is world->camera [R|t] row-major.
    """
    out = {}
    with open(path) as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    for ln in lines:
        parts = ln.split()
        if len(parts) < 19:
            continue  # the URL header line (or malformed)
        try:
            vals = [float(x) for x in parts]
        except ValueError:
            continue
        ts = parts[0]
        out[ts] = {
            "intrinsics": np.asarray(vals[1:5], np.float32),
            "pose": np.asarray(vals[7:19], np.float32).reshape(3, 4),
        }
    return out


def _g_cam_world(pose_34: np.ndarray) -> np.ndarray:
    G = np.eye(4, dtype=np.float32)
    G[:3, :4] = pose_34
    return G


def _intrinsics_matrix(norm_k: np.ndarray, w: int, h: int) -> np.ndarray:
    fx, fy, cx, cy = [float(v) for v in norm_k]
    return np.asarray([[fx * w, 0.0, cx * w],
                       [0.0, fy * h, cy * h],
                       [0.0, 0.0, 1.0]], np.float32)


class RealEstate10KDataset:
    def __init__(self,
                 root: str,
                 is_validation: bool,
                 img_size: Tuple[int, int],
                 visible_points_count: int = 0,
                 frames_apart="random",
                 max_frame_gap: int = 30,
                 pairs_json: Optional[str] = None,
                 tgt_key: str = "tgt_img_obj_5_frames",
                 points_root: Optional[str] = None,
                 cache_frames: int = 4096,
                 logger=None):
        self.img_w, self.img_h = img_size
        self.is_validation = is_validation
        self.visible_points_count = int(visible_points_count)
        self.frames_apart = frames_apart
        self.max_frame_gap = int(max_frame_gap)
        self.tgt_key = tgt_key
        # decoded-frame LRU — frames decode lazily (the full RE10K split is
        # hundreds of GB decoded; eager RAM caching like the LLFF loader is
        # only viable for its ~8-scene datasets)
        self._img_cache: "collections.OrderedDict[str, np.ndarray]" = \
            collections.OrderedDict()
        self._cache_frames = int(cache_frames)

        if self.visible_points_count > 0 and points_root is None:
            raise ValueError(
                "RealEstate10K ships no sparse 3D points: either supply "
                "points_root (<seq>.npz with world-frame 'xyz' [N,3]) or set "
                "data.visible_point_count: 0 (disables the sparse-disparity "
                "loss and scale factor)")

        # ---- scan sequences: cameras + frame PATHS only (lazy decode) ----
        self.frames: Dict[Tuple[str, str], Dict] = {}   # (seq, ts) -> info
        self.seq_ts: Dict[str, list] = {}               # ordered ts per seq
        self.points: Dict[str, np.ndarray] = {}

        for entry in sorted(os.listdir(root)):
            if not entry.endswith(".txt"):
                continue
            seq = entry[:-4]
            frame_dir = os.path.join(root, seq)
            if not os.path.isdir(frame_dir):
                continue
            cams = parse_camera_file(os.path.join(root, entry))
            ts_list = []
            for ts in sorted(cams, key=lambda t: int(t)):
                img_path = None
                for ext in _FRAME_EXTS:
                    cand = os.path.join(frame_dir, ts + ext)
                    if os.path.exists(cand):
                        img_path = cand
                        break
                if img_path is None:
                    continue
                self.frames[(seq, ts)] = {
                    "img_path": img_path,
                    "G_cam_world": _g_cam_world(cams[ts]["pose"]),
                    "K": _intrinsics_matrix(cams[ts]["intrinsics"],
                                            self.img_w, self.img_h),
                }
                ts_list.append(ts)
            if len(ts_list) >= 2:
                self.seq_ts[seq] = ts_list
            if points_root is not None:
                ppath = os.path.join(points_root, seq + ".npz")
                if os.path.exists(ppath):
                    self.points[seq] = np.load(ppath)["xyz"].astype(np.float32)

        # ---- item index ----
        if is_validation and pairs_json:
            self.pairs = self._load_pairs_json(pairs_json)
        else:
            # one item per cached frame with >=1 in-gap neighbor
            self.items = [(seq, i) for seq, tss in sorted(self.seq_ts.items())
                          for i in range(len(tss))]

        if logger is not None:
            n = len(self.pairs) if (is_validation and pairs_json) \
                else len(self.items)
            logger.info("RealEstate10K %s: %d sequences, %d items",
                        "val" if is_validation else "train",
                        len(self.seq_ts), n)

    # ---------------- eval-protocol pairs ----------------

    def _load_pairs_json(self, path: str) -> List[Tuple[Dict, Dict]]:
        """Parse the reference's validation_pairs.json protocol (JSONL); keep
        pairs whose frames exist in the local extraction."""
        pairs = []
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                rec = json.loads(ln)
                src, tgt = rec["src_img_obj"], rec[self.tgt_key]
                ks = (src["sequence_id"], str(src["frame_ts"]))
                kt = (tgt["sequence_id"], str(tgt["frame_ts"]))
                if ks in self.frames and kt in self.frames:
                    pairs.append((self._protocol_info(src),
                                  self._protocol_info(tgt)))
        return pairs

    def _protocol_info(self, obj: Dict) -> Dict:
        """Frame info with the protocol's own camera (the JSON carries pose +
        intrinsics; images come from the local extraction). Keeps the lazy
        img_path; get_item decodes."""
        key = (obj["sequence_id"], str(obj["frame_ts"]))
        info = dict(self.frames[key])
        info["seq"] = obj["sequence_id"]
        info["G_cam_world"] = _g_cam_world(
            np.asarray(obj["camera_pose"], np.float32).reshape(3, 4))
        info["K"] = _intrinsics_matrix(
            np.asarray(obj["camera_intrinsics"], np.float32),
            self.img_w, self.img_h)
        return info

    # ---------------- item sampling ----------------

    def __len__(self) -> int:
        if self.is_validation and hasattr(self, "pairs"):
            return len(self.pairs)
        return len(self.items)

    def _decode(self, path: str) -> np.ndarray:
        img = self._img_cache.get(path)
        if img is not None:
            self._img_cache.move_to_end(path)
            return img
        img = native.load_image_rgb(path, (self.img_w, self.img_h))
        self._img_cache[path] = img
        while len(self._img_cache) > self._cache_frames:
            self._img_cache.popitem(last=False)
        return img

    def _info(self, seq: str, ts: str) -> Dict:
        info = dict(self.frames[(seq, ts)])
        info["seq"] = seq
        info["img"] = self._decode(info.pop("img_path"))
        return info

    def get_item(self, index: int, rng: np.random.RandomState):
        if self.is_validation and hasattr(self, "pairs"):
            src, tgt = (dict(d) for d in self.pairs[index])
            src["img"] = self._decode(src.pop("img_path"))
            tgt["img"] = self._decode(tgt.pop("img_path"))
        else:
            seq, i = self.items[index]
            tss = self.seq_ts[seq]
            if isinstance(self.frames_apart, int) \
                    or str(self.frames_apart).lstrip("-").isdigit():
                # fixed offset; when it runs off the sequence end, step
                # BACKWARD by the same gap (never wrap to frame 0 — that
                # would pair across the whole video)
                off = int(self.frames_apart)
                j = i + off
                if not 0 <= j < len(tss):
                    j = i - off
                j = min(max(j, 0), len(tss) - 1)
                if j == i:  # degenerate short sequence
                    j = i + 1 if i + 1 < len(tss) else i - 1
            else:
                lo = max(0, i - self.max_frame_gap)
                hi = min(len(tss) - 1, i + self.max_frame_gap)
                j = i
                while j == i:
                    j = rng.randint(lo, hi + 1)
            src = self._info(seq, tss[i])
            tgt = self._info(seq, tss[j])
        tgt = dict(tgt)
        tgt["G_src_tgt"] = (
            src["G_cam_world"]
            @ np.linalg.inv(tgt["G_cam_world"])).astype(np.float32)
        src = self._attach_points(src, rng)
        tgt = self._attach_points(tgt, rng)
        return src, tgt

    def _attach_points(self, info: Dict, rng: np.random.RandomState) -> Dict:
        n_want = self.visible_points_count
        if n_want <= 0:
            # dummy (unused: visible_point_count==0 disables the losses);
            # z=1 keeps any accidental 1/z finite
            info["xyzs"] = np.ones((3, 1), np.float32)
            return info
        pts = self.points.get(info["seq"])
        if pts is None or len(pts) == 0:
            raise ValueError(
                f"no sparse points for sequence {info['seq']} "
                f"(points_root npz missing)")
        G = info["G_cam_world"]
        cam = (G[:3, :3] @ pts.T + G[:3, 3:4]).astype(np.float32)  # [3,N]
        pix = info["K"] @ cam
        with np.errstate(divide="ignore", invalid="ignore"):
            uv = pix[:2] / pix[2:3]
        vis = (cam[2] > 1e-3) \
            & (uv[0] >= 0) & (uv[0] < self.img_w) \
            & (uv[1] >= 0) & (uv[1] < self.img_h)
        cam = cam[:, vis]
        if cam.shape[1] == 0:
            raise ValueError(f"no visible points for sequence {info['seq']}")
        sel = rng.choice(cam.shape[1], size=n_want,
                         replace=cam.shape[1] < n_want)
        info["xyzs"] = cam[:, sel]
        return info

    # ---------------- batching (LLFF contract) ----------------

    def batch_iterator(self,
                       batch_size: int,
                       shuffle: bool,
                       seed: int = 0,
                       epoch: int = 0,
                       drop_last: bool = True,
                       shard_index: int = 0,
                       num_shards: int = 1,
                       workers: int = 0,
                       prefetch_batches: int = 2
                       ) -> Iterator[Dict[str, np.ndarray]]:
        from mine_tpu.data.common import iterate_pair_batches
        yield from iterate_pair_batches(
            len(self), self.get_item, batch_size, shuffle, seed=seed,
            epoch=epoch, drop_last=drop_last, shard_index=shard_index,
            num_shards=num_shards, workers=workers,
            prefetch_batches=prefetch_batches)
