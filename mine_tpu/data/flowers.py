"""Flowers light-field dataset — lenslet sub-aperture views as (src, tgt).

Capability beyond the reference's code: it ships the calibration and split
assets for this dataset (input_pipelines/flowers/cam_params.txt — an 8x8
camera grid keyed "r_c" with normalized intrinsics + [R|t] — and
dataset_list/{train,test}.list of `imgs/*_eslf.png` paths) plus a flowers
config (configs/params_flowers.yaml), but no loader (train.py:100-101
raises). This loader consumes exactly those asset formats.

The underlying data is the Stanford light-field flowers set: each
`*_eslf.png` is a lenslet image in ESLF layout — sub-aperture view (u, v)
is the pixel grid `eslf[u::S, v::S]` for lenslet stride S (14 for the real
data); the calibrated views are the central GxG (G=8) of the SxS grid, so
camera "r_c" maps to (u, v) = (r, c) + (S-G)//2.

Items: src = the central calibrated view, tgt = a random other view of the
same scene (deterministic for validation) — a light-field camera array is a
dense novel-view rig, which is what MINE trains on here. Flowers carries no
sparse SfM points; it is in the no-disparity-loss dataset set
(synthesis_task.py:213-214), so items get dummy points.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np
from PIL import Image as PILImage

from mine_tpu import native


def parse_cam_params(path: str) -> Dict[Tuple[int, int], Dict[str, np.ndarray]]:
    """cam_params.txt -> {(r, c): {intrinsics[4], pose[3,4]}}.

    Line: `r_c fx fy cx cy k1 k2 r11 r12 r13 t1 r21 ... t3` (19 fields,
    intrinsics normalized by resolution, pose world->camera row-major).
    """
    out = {}
    with open(path) as f:
        for ln in f:
            parts = ln.split()
            if len(parts) < 19:
                continue
            r, c = (int(x) for x in parts[0].split("_"))
            vals = [float(x) for x in parts[1:]]
            out[(r, c)] = {
                "intrinsics": np.asarray(vals[0:4], np.float32),
                "pose": np.asarray(vals[6:18], np.float32).reshape(3, 4),
            }
    return out


def extract_subaperture(eslf: np.ndarray, u: int, v: int,
                        stride: int) -> np.ndarray:
    """ESLF lenslet image [H*S, W*S, 3] -> sub-aperture view (u, v) [H, W, 3]."""
    return eslf[u::stride, v::stride]


class FlowersDataset:
    def __init__(self,
                 root: str,
                 is_validation: bool,
                 img_size: Tuple[int, int],
                 cam_params_path: Optional[str] = None,
                 list_path: Optional[str] = None,
                 grid: int = 8,
                 lenslet_stride: int = 14,
                 logger=None):
        self.img_w, self.img_h = img_size
        self.is_validation = is_validation
        self.grid = int(grid)
        self.stride = int(lenslet_stride)
        self.offset = (self.stride - self.grid) // 2
        self.root = root

        cam_params_path = cam_params_path or os.path.join(root, "cam_params.txt")
        if list_path is None:
            list_path = os.path.join(
                root, "dataset_list",
                "test.list" if is_validation else "train.list")
        self.cams = parse_cam_params(cam_params_path)
        if not self.cams:
            raise ValueError(f"no camera entries in {cam_params_path}")

        with open(list_path) as f:
            self.paths = [os.path.join(root, ln.strip())
                          for ln in f if ln.strip()]
        self.paths = [p for p in self.paths if os.path.exists(p)]
        if logger is not None:
            logger.info("Flowers %s: %d scenes, %dx%d view grid",
                        "val" if is_validation else "train",
                        len(self.paths), self.grid, self.grid)

        self.center = (self.grid // 2, self.grid // 2)
        self.others = [(r, c) for r in range(self.grid)
                       for c in range(self.grid) if (r, c) != self.center
                       and (r, c) in self.cams]

    def __len__(self) -> int:
        return len(self.paths)

    # ---------------- views ----------------

    def _load_view(self, eslf: np.ndarray, rc: Tuple[int, int]) -> Dict:
        """eslf: uint8 lenslet image (decoded once per item in get_item)."""
        u, v = rc[0] + self.offset, rc[1] + self.offset
        view = np.ascontiguousarray(
            extract_subaperture(eslf, u, v, self.stride))
        img = native.resize_rgb_u8(view, (self.img_w, self.img_h))

        cam = self.cams[rc]
        fx, fy, cx, cy = (float(x) for x in cam["intrinsics"])
        K = np.asarray([[fx * self.img_w, 0, cx * self.img_w],
                        [0, fy * self.img_h, cy * self.img_h],
                        [0, 0, 1]], np.float32)
        G = np.eye(4, dtype=np.float32)
        G[:3, :4] = cam["pose"]
        return {"img": img, "K": K, "G_cam_world": G,
                "xyzs": np.ones((3, 1), np.float32)}  # no SfM points

    def get_item(self, index: int, rng: np.random.RandomState):
        eslf = np.asarray(
            PILImage.open(self.paths[index]).convert("RGB"))  # uint8
        src = self._load_view(eslf, self.center)
        if self.is_validation:
            tgt_rc = self.others[index % len(self.others)]
        else:
            tgt_rc = self.others[rng.randint(len(self.others))]
        tgt = self._load_view(eslf, tgt_rc)
        tgt["G_src_tgt"] = (
            src["G_cam_world"]
            @ np.linalg.inv(tgt["G_cam_world"])).astype(np.float32)
        return src, tgt

    def batch_iterator(self,
                       batch_size: int,
                       shuffle: bool,
                       seed: int = 0,
                       epoch: int = 0,
                       drop_last: bool = True,
                       shard_index: int = 0,
                       num_shards: int = 1,
                       workers: int = 0,
                       prefetch_batches: int = 2
                       ) -> Iterator[Dict[str, np.ndarray]]:
        from mine_tpu.data.common import iterate_pair_batches
        yield from iterate_pair_batches(
            len(self), self.get_item, batch_size, shuffle, seed=seed,
            epoch=epoch, drop_last=drop_last, shard_index=shard_index,
            num_shards=num_shards, workers=workers,
            prefetch_batches=prefetch_batches)
