"""Procedural multi-view scenes with exact geometry.

Purpose: deterministic training/eval data for tests and benchmarks without
real datasets (the reference has no equivalent — its smoke tests used the
author's local photos, operations/test_rendering.py:13). A ground-truth MPI
(textured layers at known disparities) is rendered into V camera poses with
the same verified renderer the model trains against, so a correctly wired
trainer can drive the loss toward zero (SURVEY.md section 7 build-order
step 2: "overfitting one synthetic scene").

Batch layout (the framework-wide contract, see SynthesisTrainer):
  src_img, tgt_img: [B, H, W, 3] float32 in [0, 1]  (NHWC for the encoder)
  K_src, K_tgt:     [B, 3, 3]
  G_src_tgt:        [B, 4, 4]   (tgt camera -> src camera, like the reference)
  pt3d_src, pt3d_tgt: [B, 3, N] camera-frame points of the view
(the reference's per-item dict, nerf_dataset.py:105-127, squeezed to L=1
supervision like synthesis_task.set_data:184-209).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

import jax.numpy as jnp

from mine_tpu import geometry
from mine_tpu.ops import rendering


def _smooth_noise(rng: np.random.RandomState, h: int, w: int, c: int,
                  base: int = 8) -> np.ndarray:
    """Low-frequency texture in [0,1]: upsampled random grid."""
    small = rng.uniform(size=(base, base, c)).astype(np.float32)
    ys = np.linspace(0, base - 1, h)
    xs = np.linspace(0, base - 1, w)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, base - 1)
    x1 = np.minimum(x0 + 1, base - 1)
    ty = (ys - y0)[:, None, None]
    tx = (xs - x0)[None, :, None]
    top = small[y0][:, x0] * (1 - tx) + small[y0][:, x1] * tx
    bot = small[y1][:, x0] * (1 - tx) + small[y1][:, x1] * tx
    return top * (1 - ty) + bot * ty


class SyntheticMPIDataset:
    """V views of a fixed layered scene.

    The scene is an S_gt-plane MPI in the world frame: each plane has a
    low-frequency texture; densities make the nearest plane opaque in a
    blob region and transparent elsewhere, so views exhibit real parallax
    and dis-occlusion.
    """

    def __init__(self, seed: int = 0, height: int = 64, width: int = 64,
                 num_views: int = 6, num_planes_gt: int = 4,
                 num_points: int = 32, max_shift: float = 0.08):
        rng = np.random.RandomState(seed)
        H, W, S = height, width, num_planes_gt
        self.height, self.width = H, W
        self.num_points = num_points

        K = geometry.intrinsics_from_fov(H, W, fov_degrees=60.0)
        self.K = K

        # ground-truth MPI in the world(=plane) frame
        disparity = np.linspace(1.0, 0.2, S).astype(np.float32)  # depth 1..5
        rgb = np.stack([_smooth_noise(rng, H, W, 3) for _ in range(S)], axis=0)
        sigma = np.full((S, 1, H, W), 0.05, dtype=np.float32)
        # opaque blobs on the near planes (parallax + occlusion)
        yy, xx = np.mgrid[0:H, 0:W].astype(np.float32)
        for s in range(S - 1):
            cy, cx = rng.uniform(0.25, 0.75, 2) * [H, W]
            r = 0.18 * min(H, W) * rng.uniform(0.8, 1.4)
            blob = ((yy - cy) ** 2 + (xx - cx) ** 2) < r ** 2
            sigma[s, 0][blob] = 60.0
        sigma[S - 1] = 60.0  # far plane opaque background

        # rgb: [S,H,W,3] -> [1,S,3,H,W]
        self.mpi_rgb = jnp.asarray(rgb.transpose(0, 3, 1, 2))[None]
        self.mpi_sigma = jnp.asarray(sigma)[None]  # [1,S,1,H,W]
        self.disparity = jnp.asarray(disparity)[None]  # [1,S]

        # camera poses: world -> camera, small random motions
        self.G_cam_world: List[np.ndarray] = []
        for v in range(num_views):
            G = np.eye(4, dtype=np.float32)
            if v > 0:
                t = rng.uniform(-max_shift, max_shift, 3).astype(np.float32)
                t[2] *= 0.5
                angle = rng.uniform(-0.02, 0.02, 3)
                Rx = _rot(angle)
                G[:3, :3] = Rx
                G[:3, 3] = t
            self.G_cam_world.append(G)

        # render every view from the canonical MPI
        K_j = jnp.asarray(K)[None]
        K_inv_j = geometry.inverse_intrinsics(K_j)
        grid = geometry.cached_pixel_grid(H, W)
        xyz_world = geometry.plane_xyz_src(grid, self.disparity, K_inv_j)

        self.images: List[np.ndarray] = []
        self.depths: List[np.ndarray] = []
        for G in self.G_cam_world:
            Gj = jnp.asarray(G)[None]
            xyz_v = geometry.plane_xyz_tgt(xyz_world, Gj)
            res = rendering.render_tgt_rgb_depth(
                self.mpi_rgb, self.mpi_sigma, self.disparity, xyz_v, Gj,
                K_inv_j, K_j)
            img = np.asarray(res.rgb[0])          # [3,H,W]
            self.images.append(np.clip(img, 0.0, 1.0))
            self.depths.append(np.asarray(res.depth[0, 0]))  # [H,W]

        # per-view camera-frame 3D points from rendered depth
        self.pt3d: List[np.ndarray] = []
        K_inv = np.linalg.inv(K)
        for v in range(num_views):
            px = rng.randint(2, W - 2, size=num_points)
            py = rng.randint(2, H - 2, size=num_points)
            z = self.depths[v][py, px]
            pix = np.stack([px, py, np.ones_like(px)], axis=0).astype(np.float32)
            xyz = (K_inv @ pix) * z[None, :]
            self.pt3d.append(xyz.astype(np.float32))

        self.num_views = num_views

    def pair_batch(self, pairs) -> Dict[str, np.ndarray]:
        """Build a batch from (src_view, tgt_view) index pairs."""
        b = {
            "src_img": [], "tgt_img": [], "K_src": [], "K_tgt": [],
            "G_src_tgt": [], "pt3d_src": [], "pt3d_tgt": [],
        }
        for i, j in pairs:
            G_src_tgt = self.G_cam_world[i] @ np.linalg.inv(self.G_cam_world[j])
            b["src_img"].append(self.images[i].transpose(1, 2, 0))  # HWC
            b["tgt_img"].append(self.images[j].transpose(1, 2, 0))
            b["K_src"].append(self.K)
            b["K_tgt"].append(self.K)
            b["G_src_tgt"].append(G_src_tgt.astype(np.float32))
            b["pt3d_src"].append(self.pt3d[i])
            b["pt3d_tgt"].append(self.pt3d[j])
        return {k: np.stack(v, axis=0) for k, v in b.items()}


def _rot(angles) -> np.ndarray:
    ax, ay, az = angles
    cx, sx = np.cos(ax), np.sin(ax)
    cy, sy = np.cos(ay), np.sin(ay)
    cz, sz = np.cos(az), np.sin(az)
    Rx = np.array([[1, 0, 0], [0, cx, -sx], [0, sx, cx]])
    Ry = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]])
    Rz = np.array([[cz, -sz, 0], [sz, cz, 0], [0, 0, 1]])
    return (Rz @ Ry @ Rx).astype(np.float32)


def make_batch(batch_size: int = 1, height: int = 64, width: int = 64,
               num_points: int = 32, seed: int = 0) -> Dict[str, np.ndarray]:
    """One fixed batch for benchmarks / smoke tests."""
    ds = SyntheticMPIDataset(seed=seed, height=height, width=width,
                             num_views=batch_size + 1, num_points=num_points)
    pairs = [(v, v + 1) for v in range(batch_size)]
    return ds.pair_batch(pairs)


class SyntheticPairDataset:
    """SyntheticMPIDataset behind the LLFFDataset batch_iterator contract.

    Lets every consumer of get_dataset (train_cli, eval_cli, TrainLoop) run
    without real data: `data.name: synthetic` in the config. Consecutive-view
    pairs play the role of (src, tgt) items; the geometry/points are exact,
    so losses and PSNR/SSIM behave like a real (tiny) scene.
    """

    def __init__(self, num_views: int = 6, num_points: int = 32,
                 height: int = 64, width: int = 64, seed: int = 0):
        self.ds = SyntheticMPIDataset(seed=seed, height=height, width=width,
                                      num_views=num_views,
                                      num_points=num_points)
        self.pairs = [(i, i + 1) for i in range(num_views - 1)]

    def __len__(self):
        return len(self.pairs)

    def _view_info(self, v: int) -> Dict:
        return {
            "img": self.ds.images[v].transpose(1, 2, 0),  # HWC
            "K": self.ds.K,
            "G_cam_world": self.ds.G_cam_world[v],
            "xyzs": self.ds.pt3d[v],
        }

    def get_pair(self, index: int, rng=None):
        i, j = self.pairs[index]
        src = self._view_info(i)
        tgt = self._view_info(j)
        tgt["G_src_tgt"] = (
            src["G_cam_world"]
            @ np.linalg.inv(tgt["G_cam_world"])).astype(np.float32)
        return src, tgt

    def batch_iterator(self, batch_size, shuffle, seed=0, epoch=0,
                       drop_last=True, shard_index=0, num_shards=1,
                       workers=0, prefetch_batches=2):
        from mine_tpu.data.common import iterate_pair_batches
        yield from iterate_pair_batches(
            len(self.pairs), self.get_pair, batch_size, shuffle, seed=seed,
            epoch=epoch, drop_last=drop_last, shard_index=shard_index,
            num_shards=num_shards, workers=workers,
            prefetch_batches=prefetch_batches)
