"""Pure-functional camera/plane geometry.

Replaces the math of the reference's operations/homography_sampler.py (plane
homographies, pixel meshgrids) and operations/rendering_utils.py
(transform_G_xyz), plus utils.py:96-117 (its CUDA `torch.inverse` retry hack —
unnecessary under XLA: we use closed-form adjugate/rigid inverses which are
exact and fuse cleanly).

Conventions (same as reference):
  * pixel coordinates: x right, y down; homogeneous pixel = [x, y, 1]
  * K maps camera coords to pixels; G_a_b maps points in frame b to frame a
  * MPI planes are fronto-parallel in the source frame, plane s at depth
    d_s = 1 / disparity_s, plane equation n^T X - d = 0 with n = [0, 0, 1]

All functions are shape-polymorphic over leading batch dims where noted and
safe to call under jit; meshgrids become compile-time constants.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np


def pixel_grid_homogeneous(height: int, width: int) -> np.ndarray:
    """Homogeneous pixel-center grid, shape [3, H, W] rows (x, y, 1).

    Matches reference HomographySample.grid_generation
    (homography_sampler.py:24-33): x in [0, W-1], y in [0, H-1].

    Returned as numpy (not jnp) on purpose: callers may run under different
    jit traces, and a host-cached numpy constant embeds safely in each —
    whereas a cached jnp array created inside one trace would leak its tracer
    into the next.
    """
    x = np.arange(width, dtype=np.float32)
    y = np.arange(height, dtype=np.float32)
    xv, yv = np.meshgrid(x, y)  # HxW each
    return np.stack([xv, yv, np.ones_like(xv)], axis=0)  # 3xHxW


def inverse_3x3(mat: jnp.ndarray) -> jnp.ndarray:
    """Closed-form adjugate inverse of [..., 3, 3] matrices."""
    a, b, c = mat[..., 0, 0], mat[..., 0, 1], mat[..., 0, 2]
    d, e, f = mat[..., 1, 0], mat[..., 1, 1], mat[..., 1, 2]
    g, h, i = mat[..., 2, 0], mat[..., 2, 1], mat[..., 2, 2]

    co_a = e * i - f * h
    co_b = -(d * i - f * g)
    co_c = d * h - e * g
    det = a * co_a + b * co_b + c * co_c

    adj = jnp.stack([
        jnp.stack([co_a, -(b * i - c * h), b * f - c * e], axis=-1),
        jnp.stack([co_b, a * i - c * g, -(a * f - c * d)], axis=-1),
        jnp.stack([co_c, -(a * h - b * g), a * e - b * d], axis=-1),
    ], axis=-2)
    return adj / det[..., None, None]


def inverse_intrinsics(K: jnp.ndarray) -> jnp.ndarray:
    """Exact inverse of [..., 3, 3] intrinsics [[fx,0,cx],[0,fy,cy],[0,0,1]]."""
    fx, fy = K[..., 0, 0], K[..., 1, 1]
    cx, cy = K[..., 0, 2], K[..., 1, 2]
    zero = jnp.zeros_like(fx)
    one = jnp.ones_like(fx)
    rows = [
        jnp.stack([1.0 / fx, zero, -cx / fx], axis=-1),
        jnp.stack([zero, 1.0 / fy, -cy / fy], axis=-1),
        jnp.stack([zero, zero, one], axis=-1),
    ]
    return jnp.stack(rows, axis=-2)


def rigid_inverse(G: jnp.ndarray) -> jnp.ndarray:
    """Inverse of [..., 4, 4] rigid transforms: [R|t] -> [R^T | -R^T t].

    The reference inverts G_src_tgt with a retrying `torch.inverse`
    (synthesis_task.py:208, utils.py:96-117); G is always a relative camera
    pose (product of rigid world-to-camera transforms, nerf_dataset.py:216),
    so the closed form is exact.
    """
    R = G[..., :3, :3]
    t = G[..., :3, 3]
    Rt = jnp.swapaxes(R, -1, -2)
    t_inv = -jnp.einsum("...ij,...j->...i", Rt, t)
    top = jnp.concatenate([Rt, t_inv[..., :, None]], axis=-1)  # [...,3,4]
    bottom = jnp.broadcast_to(
        jnp.asarray([0.0, 0.0, 0.0, 1.0], dtype=G.dtype), G.shape[:-2] + (1, 4))
    return jnp.concatenate([top, bottom], axis=-2)


def scale_intrinsics(K: jnp.ndarray, scale: int) -> jnp.ndarray:
    """Intrinsics for a 2**scale-downsampled image: K/2**s with K[2,2]=1.

    Reference: synthesis_task.py:238-241.
    """
    K_scaled = K / (2.0 ** scale)
    return K_scaled.at[..., 2, 2].set(1.0)


def transform_points(G: jnp.ndarray, xyz: jnp.ndarray) -> jnp.ndarray:
    """Apply [..., 4, 4] homogeneous transforms to [..., 3, N] points.

    Reference: rendering_utils.transform_G_xyz (rendering_utils.py:5-24).
    """
    R = G[..., :3, :3]
    t = G[..., :3, 3]
    return jnp.einsum("...ij,...jn->...in", R, xyz) + t[..., :, None]


def homography_tgt_src(K_tgt: jnp.ndarray,
                       K_src_inv: jnp.ndarray,
                       G_tgt_src: jnp.ndarray,
                       d_src: jnp.ndarray) -> jnp.ndarray:
    """Plane-induced homography mapping src pixels to tgt pixels.

    H_tgt_src = K_tgt (R - t n^T / -d) K_src^-1 for the fronto-parallel source
    plane n=[0,0,1], n^T X - d = 0 (reference: homography_sampler.py:101-108).

    Args:
      K_tgt, K_src_inv: [..., 3, 3]
      G_tgt_src: [..., 4, 4]
      d_src: [...] plane depth in the source frame
    Returns: [..., 3, 3]
    """
    R = G_tgt_src[..., :3, :3]
    t = G_tgt_src[..., :3, 3]
    n = jnp.asarray([0.0, 0.0, 1.0], dtype=K_tgt.dtype)
    t_nT = t[..., :, None] * n[None, :]  # [..., 3, 3]
    R_tnd = R - t_nT / (-d_src)[..., None, None]
    return K_tgt @ R_tnd @ K_src_inv


def plane_xyz_src(meshgrid_homo: jnp.ndarray,
                  mpi_disparity_src: jnp.ndarray,
                  K_src_inv: jnp.ndarray) -> jnp.ndarray:
    """Per-plane 3D points of the MPI in the source frame.

    xyz(s, p) = K^-1 * pixel_p / disparity_s for every plane s and pixel p.
    Reference: mpi_rendering.get_src_xyz_from_plane_disparity
    (mpi_rendering.py:140-163).

    Args:
      meshgrid_homo: [3, H, W]
      mpi_disparity_src: [B, S]
      K_src_inv: [B, 3, 3]
    Returns: xyz_src [B, S, 3, H, W]
    """
    _, H, W = meshgrid_homo.shape
    depth = 1.0 / mpi_disparity_src  # [B, S]
    # K^-1 * grid: [B, 3, HW] (independent of s)
    rays = jnp.einsum("bij,jn->bin", K_src_inv, meshgrid_homo.reshape(3, H * W))
    xyz = rays[:, None, :, :] * depth[:, :, None, None]  # [B, S, 3, HW]
    return xyz.reshape(depth.shape[0], depth.shape[1], 3, H, W)


def plane_xyz_tgt(xyz_src_BS3HW: jnp.ndarray, G_tgt_src: jnp.ndarray) -> jnp.ndarray:
    """Rigid-transform per-plane source points into the target frame.

    Reference: mpi_rendering.get_tgt_xyz_from_plane_disparity
    (mpi_rendering.py:166-178).

    Args:
      xyz_src_BS3HW: [B, S, 3, H, W]
      G_tgt_src: [B, 4, 4]
    Returns: [B, S, 3, H, W]
    """
    B, S, _, H, W = xyz_src_BS3HW.shape
    R = G_tgt_src[:, :3, :3]
    t = G_tgt_src[:, :3, 3]
    xyz = jnp.einsum("bij,bsjn->bsin", R, xyz_src_BS3HW.reshape(B, S, 3, H * W))
    xyz = xyz + t[:, None, :, None]
    return xyz.reshape(B, S, 3, H, W)


def intrinsics_from_fov(height: int, width: int, fov_degrees: float = 90.0) -> np.ndarray:
    """Pinhole K from a horizontal FoV (reference: image_to_video.py:192-202)."""
    fov = np.deg2rad(fov_degrees)
    fx = width * 0.5 / np.tan(fov * 0.5)
    return np.array([[fx, 0.0, width * 0.5],
                     [0.0, fx, height * 0.5],
                     [0.0, 0.0, 1.0]], dtype=np.float32)


@functools.lru_cache(maxsize=None)
def cached_pixel_grid(height: int, width: int) -> np.ndarray:
    """Host-cached numpy meshgrid; becomes an XLA constant in each jit trace."""
    return pixel_grid_homogeneous(height, width)
