"""Flat-key YAML configuration, CLI-compatible with the reference.

The reference merges three levels (default YAML <- dataset YAML <- extra JSON)
and rejects unknown keys with asserts (reference: train.py:30-56). We keep the
exact same key space (reference: configs/params_default.yaml) so reference
configs remain usable, and add a typed accessor layer on top.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

import yaml

# Directory with our shipped configs (same key space as reference configs/).
CONFIG_DIR = os.path.join(os.path.dirname(__file__), "configs")


def load_config(config_path: str,
                extra_config: Optional[str] = None,
                default_config_path: Optional[str] = None) -> Dict[str, Any]:
    """3-level config merge: default YAML <- dataset YAML <- extra JSON string.

    Unknown keys in the dataset/extra levels raise (reference: train.py:39,43).
    """
    if default_config_path is None:
        default_config_path = os.path.join(os.path.dirname(config_path) or CONFIG_DIR,
                                           "params_default.yaml")
        if not os.path.exists(default_config_path):
            default_config_path = os.path.join(CONFIG_DIR, "params_default.yaml")

    with open(default_config_path, "r") as f:
        config = yaml.safe_load(f)

    if config_path and os.path.abspath(config_path) != os.path.abspath(default_config_path):
        with open(config_path, "r") as f:
            dataset_config = yaml.safe_load(f) or {}
        for k in dataset_config:
            if k not in config:
                raise KeyError(f"Unknown config key in {config_path}: {k}")
        config.update(dataset_config)

    if extra_config:
        extra = json.loads(extra_config) if isinstance(extra_config, str) else extra_config
        for k in extra:
            if k not in config:
                raise KeyError(f"Unknown extra config key: {k}")
        config.update(extra)

    return postprocess(config)


def postprocess(config: Dict[str, Any]) -> Dict[str, Any]:
    """Comma-string -> int list for gpus/decay steps (reference: train.py:54-55)."""
    for key in ("training.gpus", "lr.decay_steps"):
        if key in config and not isinstance(config[key], list):
            config[key] = [int(s) for s in str(config[key]).split(",")]
    return config


def save_config(config: Dict[str, Any], path: str) -> None:
    cfg = {k: v for k, v in config.items() if _is_yaml_safe(v)}
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)


def _is_yaml_safe(v: Any) -> bool:
    if isinstance(v, (str, int, float, bool, type(None))):
        return True
    if isinstance(v, (list, tuple)):
        return all(_is_yaml_safe(x) for x in v)
    if isinstance(v, dict):
        return all(_is_yaml_safe(x) for x in v.values())
    return False


@dataclasses.dataclass(frozen=True)
class MPIConfig:
    """Static (trace-time) hyperparameters of the MPI rendering path.

    Hashable so it can close over jitted functions. Mirrors the `mpi.*`,
    `loss.*` and relevant `training.*`/`data.*` keys of the reference config.
    """
    # mpi.*
    num_bins_coarse: int = 32
    num_bins_fine: int = 0
    disparity_start: float = 1.0
    disparity_end: float = 0.001
    use_alpha: bool = False
    is_bg_depth_inf: bool = False
    valid_mask_threshold: float = 2.0
    fix_disparity: bool = False
    # loss.*
    smoothness_lambda_v1: float = 0.0
    smoothness_lambda_v2: float = 0.01
    smoothness_gmin: float = 2.0
    smoothness_grad_ratio: float = 0.1
    # training.* / data.*
    src_rgb_blending: bool = True
    use_multi_scale: bool = True
    # "xla" | "pallas_diff" | "plane_scan": backend for the novel-view
    # composite inside the loss graph (pallas_diff = fused Pallas forward +
    # custom-VJP backward; plane_scan = distributed plane-axis transparency
    # scan for plane-parallel meshes, ops/plane_scan.py)
    # dataclass defaults are the NEUTRAL xla backends (safe on any
    # platform); the shipped YAML default is "auto", resolved by
    # mpi_config_from_dict to pallas_diff on TPU / xla elsewhere
    composite_backend: str = "xla"
    # "xla" | "xla_banded" | "pallas_diff" | "separable" | "pallas_sep" |
    # "pallas_fused": training-path homography warp ("xla_banded" = banded
    # one-hot-matmul in pure XLA, ops/warp_banded.py; "pallas_diff" =
    # banded MXU kernel fwd+bwd, kernels/warp_vjp.py; "separable" =
    # row-then-column 1D one-hot matmuls in pure XLA,
    # ops/warp_separable.py; "pallas_sep" = Pallas fwd+bwd pair of the
    # separable form, kernels/warp_sep.py; "pallas_fused" = the
    # warp+dequant+composite render megakernel, kernels/render_fused.py —
    # in the render path it replaces the composite backend too; all five
    # guarded backends carry a runtime gather fallback for out-of-domain
    # poses)
    warp_backend: str = "xla"
    # fwd AND bwd band: since the round-4 transposed-splat backward the
    # Pallas VJP mirrors the forward's band placement, so one knob covers
    # both (the earlier backward-specific "oband" — sized for the 54+-row
    # target touch spans of vertically-compressing near planes — is gone;
    # the transposed form has no such constraint)
    warp_band: int = 48
    # warp value dtype ("float32" | "bfloat16"): matmul operands in the
    # banded backends (bf16 doubles MXU rate) AND gather storage on the
    # default xla backend (bf16 halves the volume's HBM traffic); either
    # way ~2^-8 relative value rounding, accumulation/lerp stays f32
    warp_dtype: str = "float32"
    # separable backends only: max admitted per-row anchor deviation in
    # source rows (value error is bounded by sep_tol * the image's vertical
    # Lipschitz constant; ops/warp_separable.py docstring). Poses above it
    # take the runtime gather fallback.
    warp_sep_tol: float = 0.5
    # SSIM Toeplitz-einsum matmul precision ("highest" | "default"):
    # "highest" forces f32 MXU passes for the 11x11 Gaussian blur —
    # matches the reference's conv2d numerics exactly; "default" lets the
    # platform pick (bf16 passes on TPU: ~2e-3 blur / ~3e-3 SSIM shift,
    # but 57ms -> 2ms on v5e). Mirrors the warp_dtype speed/accuracy knob.
    ssim_precision: str = "highest"
    use_disparity_loss: bool = True   # disp_lambda=0 for flowers/kitti_raw/dtu
    use_scale_factor: bool = True     # scale_factor=1 for flowers/kitti_raw/dtu
    img_h: int = 384
    img_w: int = 512
    # model.*
    pos_encoding_multires: int = 10
    num_layers: int = 50
    sigma_dropout_rate: float = 0.0
    # optional explicit disparity bin edges (S+1 descending values); active
    # only when its length is num_bins_coarse+1 (synthesis_task.py:36,46)
    disparity_list: tuple = ()

    @property
    def num_bins_total(self) -> int:
        return self.num_bins_coarse + self.num_bins_fine


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Fault-tolerance knobs (train/resilience.py; README "Fault
    tolerance"). All host-side policy — nothing here changes the numerics
    of a healthy run."""
    # training.guard_nonfinite: all-finite check over loss + global
    # grad-norm inside the jitted step; a poisoned step becomes a
    # zero-update (step still increments)
    guard_nonfinite: bool = True
    # training.guard_skip_threshold: abort after this many CONSECUTIVE
    # skipped steps (<=0: never abort, keep skipping)
    guard_skip_threshold: int = 25
    # training.checkpoint_keep: retain only the newest K step checkpoints
    # (0 = keep all)
    checkpoint_keep: int = 0
    # data.max_item_retries / data.item_retry_backoff: bounded per-item
    # load retry before deterministic quarantine-and-replace
    max_item_retries: int = 2
    item_retry_backoff: float = 0.05


def resilience_config_from_dict(config: Dict[str, Any]) -> ResilienceConfig:
    g = config.get
    out = ResilienceConfig(
        guard_nonfinite=bool(g("training.guard_nonfinite", True)),
        guard_skip_threshold=int(g("training.guard_skip_threshold", 25)),
        checkpoint_keep=int(g("training.checkpoint_keep", 0) or 0),
        max_item_retries=int(g("data.max_item_retries", 2)),
        item_retry_backoff=float(g("data.item_retry_backoff", 0.05)),
    )
    if out.checkpoint_keep < 0:
        raise ValueError(
            f"training.checkpoint_keep must be >= 0, got {out.checkpoint_keep}")
    if out.max_item_retries < 0:
        raise ValueError(
            f"data.max_item_retries must be >= 0, got {out.max_item_retries}")
    if out.item_retry_backoff < 0:
        raise ValueError(f"data.item_retry_backoff must be >= 0, "
                         f"got {out.item_retry_backoff}")
    return out


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Pipeline-parallel training knobs (mine_tpu/parallel/pipeline.py;
    README "Pipeline training"). All default off: with enabled=False the
    fused train step runs untouched (bitwise-parity bar, like the other
    default-off subsystems)."""
    # training.pipeline.enabled: route train_step through the staged
    # GPipe-style executor instead of the fused jitted step
    enabled: bool = False
    # training.pipeline.microbatches: microbatches per optimizer step; the
    # global batch must divide evenly. Grads/metrics are averaged over
    # microbatches; BN stats thread sequentially (ghost BN, like
    # training.decoder_plane_chunks)
    microbatches: int = 1
    # training.pipeline.stages: mesh sub-slices the stage chain is placed
    # on; must divide the mesh's data axis (1 = all stages share the full
    # mesh, the single-host default)
    stages: int = 1
    # training.pipeline.hbm_budget_gb: per-chip HBM budget the planner
    # (tools/pipeline_plan.py) cuts stages under; 0 = unconstrained
    hbm_budget_gb: float = 0.0


def pipeline_config_from_dict(config: Dict[str, Any]) -> PipelineConfig:
    g = config.get

    def val(key, default):
        # None (an empty YAML value) means the default; an explicit 0 does
        # NOT — it must reach the range checks below, not coerce to 1
        v = g(key, default)
        return default if v is None else v

    out = PipelineConfig(
        enabled=bool(g("training.pipeline.enabled", False)),
        microbatches=int(val("training.pipeline.microbatches", 1)),
        stages=int(val("training.pipeline.stages", 1)),
        hbm_budget_gb=float(val("training.pipeline.hbm_budget_gb", 0.0)),
    )
    if out.microbatches < 1:
        raise ValueError(
            f"training.pipeline.microbatches must be >= 1, "
            f"got {out.microbatches}")
    if out.stages < 1:
        raise ValueError(
            f"training.pipeline.stages must be >= 1, got {out.stages}")
    if out.stages > 4:
        # the stage chain is encoder -> decoder -> render -> loss: there is
        # nothing to place on a fifth slice
        raise ValueError(
            f"training.pipeline.stages must be <= 4 (the staged step has "
            f"4 sub-programs), got {out.stages}")
    if out.hbm_budget_gb < 0:
        raise ValueError(
            f"training.pipeline.hbm_budget_gb must be >= 0, "
            f"got {out.hbm_budget_gb}")
    return out


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Render-only serving knobs (mine_tpu/serve; README "Serving").

    Host-side policy plus trace-time shape/quant choices — nothing here
    changes the numerics of the bf16/float32 render paths (bf16 dequant is
    a widening cast; serve/cache.py)."""
    # serve.cache_bytes: LRU byte budget for cached quantized MPI planes
    # (0 = unbounded)
    cache_bytes: int = 0
    # serve.cache_quant: float32 | bf16 | int8 cache storage (serve/cache.py)
    cache_quant: str = "bf16"
    # serve.max_bucket: poses per device call; pose counts pad to
    # power-of-two buckets <= this, bounding the compile set
    max_bucket: int = 8
    # serve.max_requests / serve.max_wait_ms: request coalescing — the
    # batch the scheduler fills / the deadline it holds a request to
    # (serve/batcher.py)
    max_requests: int = 8
    max_wait_ms: float = 2.0
    # serve.mesh_batch / serve.mesh_model: serving mesh axes (pow2) — poses
    # along "batch", the S plane axis along "model" (serve/shardmap.py);
    # 1x1 keeps the single-device engine
    mesh_batch: int = 1
    mesh_model: int = 1
    # serve.cache_shards: key-range partition of the plane cache; each
    # shard owns a contiguous hash range under cache_bytes/shards
    # (serve/fleet.py)
    cache_shards: int = 1
    # serve.scheduler: continuous (deadline loop keeping pow2 buckets
    # filled, the fleet default) | micro (the PR-5 one-shot linger)
    scheduler: str = "continuous"
    # serve.eval_encode_once: eval loop encodes each DISTINCT source image
    # once and reuses the cached MPI pyramid for all its target views
    # (single-host, num_bins_fine=0; train/loop.py run_eval)
    eval_encode_once: bool = False
    # serve.eval_cache_quant: quantization of the eval-loop encode cache;
    # float32 (default) keeps metric parity with the per-pair path exact
    eval_cache_quant: str = "float32"
    # serve.ops_port: opt-in HTTP ops endpoint (/metrics /healthz /slo
    # /traces/recent; telemetry/export.py) on 127.0.0.1:<port>; 0 = off
    ops_port: int = 0
    # serve.slo_objective_ms / slo_target / slo_window_s: rolling-window
    # SLO tracking (telemetry/slo.py) — breach when the window's p99
    # exceeds the objective; objective 0 disables breach detection while
    # the window percentiles keep flowing to /slo and the gauges
    slo_objective_ms: float = 0.0
    slo_target: float = 0.99
    slo_window_s: float = 60.0
    # serve.default_tier: priority class for requests that don't name one
    # (0 best-effort, 1 standard, >= 2 critical; serve/admission.py)
    default_tier: int = 1
    # serve.request_deadline_ms: default end-to-end deadline — requests
    # still queued past it are purged un-rendered and resolve to
    # DeadlineExceeded; 0 = no deadline
    request_deadline_ms: float = 0.0
    # serve.encode_retries / encode_backoff_ms: bounded retry of transient
    # sync-encode failures with exponential jittered backoff
    # (serve/engine.py); 0 retries = fail on first error (PR-10 behavior)
    encode_retries: int = 0
    encode_backoff_ms: float = 10.0
    # serve.shard_fail_threshold: consecutive placement failures that mark
    # a cache shard dead and fail its key range over (serve/fleet.py)
    shard_fail_threshold: int = 3
    # serve.admission.*: load-shedding controller (serve/admission.py) —
    # disabled by default so the serve path is bitwise-identical to the
    # pre-admission behavior until opted in. Signals with threshold <= 0
    # are ignored; shed_factor scales each threshold up to the shed level;
    # hysteresis < 1 makes de-escalation sticky (no flapping).
    admission_enabled: bool = False
    admission_burn_max: float = 1.0
    admission_queue_high: int = 64
    admission_inflight_high: int = 256
    admission_shed_factor: float = 2.0
    admission_hysteresis: float = 0.7
    # serve.aot_store_dir: directory of serialized compiled render
    # executables (serve/aot.py) — warmup loads instead of tracing, live
    # compiles write back; "" (default) disables the store entirely
    aot_store_dir: str = ""
    # serve.encoder_quant: off | int8 — int8 stores the sync-encode
    # encoder weights symmetric per-output-channel with dequant fused into
    # the jitted encode (serve/encoder.py); off is byte-identical to the
    # pre-quantization path
    encoder_quant: str = "off"
    # serve.session.*: streaming video sessions (serve/session.py) — every
    # Kth frame keyframe-encodes, the frames between render against the
    # cached keyframe MPI. keyframe_every=1 (the default) encodes EVERY
    # frame: bitwise-identical to the per-frame-encode path, i.e. the
    # feature is effectively off until the cadence is raised.
    session_keyframe_every: int = 1
    # serve.session.drift_budget: adaptive re-key threshold; 0 (default)
    # disables adaptive mode (the fixed cadence alone decides)
    session_drift_budget: float = 0.0
    # serve.session.drift_mode: probe (mean |rendered - observed| on a
    # stride-downsampled probe, causal/lagged) | pose (pose-delta norm
    # against the keyframe pose, gates the current frame)
    session_drift_mode: str = "probe"
    # serve.session.probe_stride: downsample stride of the probe proxy
    session_probe_stride: int = 4
    # serve.session.keyframe_tier: priority of keyframe encodes (default
    # critical — under admission pressure interpolation sheds first)
    session_keyframe_tier: int = 2
    # serve.warp_backend: warp/render backend of the serving engine (same
    # value space as training.warp_backend minus "auto"); "pallas_fused"
    # selects the one-pass render megakernel (kernels/render_fused.py) —
    # the engine skips the pre-dequant and the kernel reads the quantized
    # cache directly. "xla" (default) is byte-identical to the
    # pre-megakernel engine.
    warp_backend: str = "xla"
    # serve.ring.*: multi-host elastic ring (serve/ring.py, serve/hostnet.py)
    # — a front tier routes requests by content-hash key range to owner
    # HOSTS (the fleet.shard_for_key discipline, one ring across the
    # fleet), each host running today's ServeFleet as its local slice
    # behind a stdlib HTTP/JSON transport. Disabled by default: ring-off
    # is bitwise-identical to the single-process fleet.
    ring_enabled: bool = False
    # serve.ring.hosts: comma-separated host:port peers forming the ring
    # (ring-slot order = list order); "" with ring enabled = a one-host
    # ring of this process only
    ring_hosts: str = ""
    # serve.ring.drain_timeout_s: max seconds a SIGTERM'd/drained host
    # waits for in-flight requests before closing anyway
    ring_drain_timeout_s: float = 30.0
    # serve.ring.autoscale.*: the pressure-driven host autoscaler
    # (serve/ring.py Autoscaler). Pressure >= 1.0 for `evals` consecutive
    # evaluations grows the fleet one host; pressure < hysteresis for
    # `evals` consecutive evaluations shrinks it one host; cooldown_s of
    # quiet follows every action — the admission ladder's stickiness, so
    # it never oscillates. Off constructs nothing.
    autoscale_enabled: bool = False
    autoscale_min_hosts: int = 1
    autoscale_max_hosts: int = 4
    autoscale_evals: int = 3
    autoscale_hysteresis: float = 0.5
    autoscale_cooldown_s: float = 30.0
    # serve.net.*: wire hardening of the ring transport (serve/hostnet.py
    # NetPolicy) — split connect/read timeouts, bounded jittered retries,
    # per-host circuit breakers, deadline propagation over the hop, and
    # the front's heartbeat failure detector (suspect = route around,
    # front-local; only sustained connection-REFUSED marks dead).
    # Disabled by default: net-off constructs none of it and the wire
    # behavior is bitwise-identical to the unhardened transport.
    net_enabled: bool = False
    net_connect_timeout_s: float = 5.0
    net_read_timeout_s: float = 60.0
    net_retries: int = 2
    net_backoff_ms: float = 20.0
    net_breaker_threshold: int = 5
    net_breaker_reset_s: float = 10.0
    net_probe_interval_s: float = 0.0
    net_suspect_misses: int = 3
    net_dead_misses: int = 10
    net_revive_probes: int = 2
    # serve.wire.*: the binary wire fabric (serve/wire.py WirePolicy) —
    # mtpu-wire1 length-prefixed frames with raw little-endian tensors
    # instead of JSON/base64, an f32|bf16|int8 tensor codec for
    # image/rgb/depth payloads, and the front's owner-coalescer (N
    # same-owner requests per linger window leave as ONE batch frame).
    # ALL default off: wire-off negotiates nothing, frames nothing, and
    # the transport is bitwise-identical to the JSON path (test-pinned).
    wire_format: str = "json"
    wire_codec: str = "f32"
    wire_coalesce_ms: float = 0.0
    wire_coalesce_max: int = 8


def serve_config_from_dict(config: Dict[str, Any]) -> ServeConfig:
    g = config.get
    out = ServeConfig(
        cache_bytes=int(g("serve.cache_bytes", 0) or 0),
        cache_quant=str(g("serve.cache_quant", "bf16")),
        max_bucket=int(g("serve.max_bucket", 8)),
        max_requests=int(g("serve.max_requests", 8)),
        max_wait_ms=float(g("serve.max_wait_ms", 2.0)),
        mesh_batch=int(g("serve.mesh_batch", 1)),
        mesh_model=int(g("serve.mesh_model", 1)),
        cache_shards=int(g("serve.cache_shards", 1)),
        scheduler=str(g("serve.scheduler", "continuous")),
        eval_encode_once=bool(g("serve.eval_encode_once", False)),
        eval_cache_quant=str(g("serve.eval_cache_quant", "float32")),
        ops_port=int(g("serve.ops_port", 0) or 0),
        slo_objective_ms=float(g("serve.slo_objective_ms", 0.0) or 0.0),
        slo_target=float(g("serve.slo_target", 0.99)),
        slo_window_s=float(g("serve.slo_window_s", 60.0)),
        default_tier=int(g("serve.default_tier", 1)),
        request_deadline_ms=float(g("serve.request_deadline_ms", 0.0) or 0.0),
        encode_retries=int(g("serve.encode_retries", 0) or 0),
        encode_backoff_ms=float(g("serve.encode_backoff_ms", 10.0)),
        shard_fail_threshold=int(g("serve.shard_fail_threshold", 3)),
        admission_enabled=bool(g("serve.admission.enabled", False)),
        admission_burn_max=float(g("serve.admission.burn_max", 1.0) or 0.0),
        admission_queue_high=int(g("serve.admission.queue_high", 64) or 0),
        admission_inflight_high=int(
            g("serve.admission.inflight_high", 256) or 0),
        admission_shed_factor=float(g("serve.admission.shed_factor", 2.0)),
        admission_hysteresis=float(g("serve.admission.hysteresis", 0.7)),
        aot_store_dir=str(g("serve.aot_store_dir", "") or ""),
        # YAML 1.1 reads a bare `off` as boolean False — accept it
        encoder_quant=("off" if g("serve.encoder_quant", "off") is False
                       else str(g("serve.encoder_quant", "off"))),
        session_keyframe_every=int(g("serve.session.keyframe_every", 1)),
        session_drift_budget=float(
            g("serve.session.drift_budget", 0.0) or 0.0),
        session_drift_mode=str(g("serve.session.drift_mode", "probe")),
        session_probe_stride=int(g("serve.session.probe_stride", 4)),
        session_keyframe_tier=int(g("serve.session.keyframe_tier", 2)),
        warp_backend=str(g("serve.warp_backend", "xla")),
        ring_enabled=bool(g("serve.ring.enabled", False)),
        ring_hosts=str(g("serve.ring.hosts", "") or ""),
        ring_drain_timeout_s=float(
            g("serve.ring.drain_timeout_s", 30.0) or 0.0),
        autoscale_enabled=bool(g("serve.ring.autoscale.enabled", False)),
        autoscale_min_hosts=int(g("serve.ring.autoscale.min_hosts", 1)),
        autoscale_max_hosts=int(g("serve.ring.autoscale.max_hosts", 4)),
        autoscale_evals=int(g("serve.ring.autoscale.evals", 3)),
        autoscale_hysteresis=float(
            g("serve.ring.autoscale.hysteresis", 0.5)),
        autoscale_cooldown_s=float(
            g("serve.ring.autoscale.cooldown_s", 30.0) or 0.0),
        net_enabled=bool(g("serve.net.enabled", False)),
        net_connect_timeout_s=float(
            g("serve.net.connect_timeout_s", 5.0)),
        net_read_timeout_s=float(g("serve.net.read_timeout_s", 60.0)),
        net_retries=int(g("serve.net.retries", 2)),
        net_backoff_ms=float(g("serve.net.backoff_ms", 20.0)),
        net_breaker_threshold=int(g("serve.net.breaker_threshold", 5)),
        net_breaker_reset_s=float(g("serve.net.breaker_reset_s", 10.0)),
        net_probe_interval_s=float(
            g("serve.net.probe_interval_s", 0.0) or 0.0),
        net_suspect_misses=int(g("serve.net.suspect_misses", 3)),
        net_dead_misses=int(g("serve.net.dead_misses", 10)),
        net_revive_probes=int(g("serve.net.revive_probes", 2)),
        wire_format=str(g("serve.wire.format", "json")),
        wire_codec=str(g("serve.wire.codec", "f32")),
        wire_coalesce_ms=float(g("serve.wire.coalesce_ms", 0.0) or 0.0),
        wire_coalesce_max=int(g("serve.wire.coalesce_max", 8)),
    )
    from mine_tpu.serve.cache import QUANT_MODES
    for key, val in (("serve.cache_quant", out.cache_quant),
                     ("serve.eval_cache_quant", out.eval_cache_quant)):
        if val not in QUANT_MODES:
            raise ValueError(
                f"{key} must be one of {'|'.join(QUANT_MODES)}, got {val!r}")
    if out.cache_bytes < 0:
        raise ValueError(
            f"serve.cache_bytes must be >= 0, got {out.cache_bytes}")
    if out.max_bucket < 1 or (out.max_bucket & (out.max_bucket - 1)) != 0:
        raise ValueError(
            f"serve.max_bucket must be a power of two >= 1, "
            f"got {out.max_bucket}")
    if out.max_requests < 1:
        raise ValueError(
            f"serve.max_requests must be >= 1, got {out.max_requests}")
    if out.max_wait_ms < 0:
        raise ValueError(
            f"serve.max_wait_ms must be >= 0, got {out.max_wait_ms}")
    for key, val in (("serve.mesh_batch", out.mesh_batch),
                     ("serve.mesh_model", out.mesh_model)):
        # pow2 mesh axes compose with the engine's pow2 shape buckets:
        # every bucket divides evenly across the mesh (serve/shardmap.py)
        if val < 1 or (val & (val - 1)) != 0:
            raise ValueError(
                f"{key} must be a power of two >= 1, got {val}")
    if out.cache_shards < 1:
        raise ValueError(
            f"serve.cache_shards must be >= 1, got {out.cache_shards}")
    if out.scheduler not in ("continuous", "micro"):
        raise ValueError(
            f"serve.scheduler must be continuous|micro, "
            f"got {out.scheduler!r}")
    if out.warp_backend not in ("xla", "xla_banded", "pallas_diff",
                                "separable", "pallas_sep", "pallas_fused"):
        raise ValueError(
            f"serve.warp_backend must be xla|xla_banded|pallas_diff|"
            f"separable|pallas_sep|pallas_fused, got {out.warp_backend!r}")
    if not 0 <= out.ops_port <= 65535:
        raise ValueError(
            f"serve.ops_port must be in [0, 65535], got {out.ops_port}")
    if out.slo_objective_ms < 0:
        raise ValueError(
            f"serve.slo_objective_ms must be >= 0, "
            f"got {out.slo_objective_ms}")
    if not 0.0 < out.slo_target < 1.0:
        raise ValueError(
            f"serve.slo_target must be in (0, 1), got {out.slo_target}")
    if out.slo_window_s <= 0:
        raise ValueError(
            f"serve.slo_window_s must be > 0, got {out.slo_window_s}")
    if out.default_tier < 0:
        raise ValueError(
            f"serve.default_tier must be >= 0, got {out.default_tier}")
    if out.request_deadline_ms < 0:
        raise ValueError(
            f"serve.request_deadline_ms must be >= 0, "
            f"got {out.request_deadline_ms}")
    if out.encode_retries < 0:
        raise ValueError(
            f"serve.encode_retries must be >= 0, got {out.encode_retries}")
    if out.encode_backoff_ms < 0:
        raise ValueError(
            f"serve.encode_backoff_ms must be >= 0, "
            f"got {out.encode_backoff_ms}")
    if out.shard_fail_threshold < 1:
        raise ValueError(
            f"serve.shard_fail_threshold must be >= 1, "
            f"got {out.shard_fail_threshold}")
    if out.admission_shed_factor <= 1.0:
        raise ValueError(
            f"serve.admission.shed_factor must be > 1, "
            f"got {out.admission_shed_factor}")
    if not 0.0 < out.admission_hysteresis <= 1.0:
        raise ValueError(
            f"serve.admission.hysteresis must be in (0, 1], "
            f"got {out.admission_hysteresis}")
    from mine_tpu.serve.encoder import ENCODER_QUANT_MODES
    if out.encoder_quant not in ENCODER_QUANT_MODES:
        raise ValueError(
            f"serve.encoder_quant must be one of "
            f"{'|'.join(ENCODER_QUANT_MODES)}, got {out.encoder_quant!r}")
    if out.session_keyframe_every < 1:
        raise ValueError(
            f"serve.session.keyframe_every must be >= 1, "
            f"got {out.session_keyframe_every}")
    if out.session_drift_budget < 0:
        raise ValueError(
            f"serve.session.drift_budget must be >= 0, "
            f"got {out.session_drift_budget}")
    from mine_tpu.serve.session import DRIFT_MODES
    if out.session_drift_mode not in DRIFT_MODES:
        raise ValueError(
            f"serve.session.drift_mode must be one of "
            f"{'|'.join(DRIFT_MODES)}, got {out.session_drift_mode!r}")
    if out.session_probe_stride < 1:
        raise ValueError(
            f"serve.session.probe_stride must be >= 1, "
            f"got {out.session_probe_stride}")
    if out.session_keyframe_tier < 0:
        raise ValueError(
            f"serve.session.keyframe_tier must be >= 0, "
            f"got {out.session_keyframe_tier}")
    if out.ring_drain_timeout_s < 0:
        raise ValueError(
            f"serve.ring.drain_timeout_s must be >= 0, "
            f"got {out.ring_drain_timeout_s}")
    for host in (h.strip() for h in out.ring_hosts.split(",") if h.strip()):
        # host:port peers; the split-off tail must be a port number
        if ":" not in host or not host.rsplit(":", 1)[1].isdigit():
            raise ValueError(
                f"serve.ring.hosts entries must be host:port, got {host!r}")
    if out.autoscale_min_hosts < 1:
        raise ValueError(
            f"serve.ring.autoscale.min_hosts must be >= 1, "
            f"got {out.autoscale_min_hosts}")
    if out.autoscale_max_hosts < out.autoscale_min_hosts:
        raise ValueError(
            f"serve.ring.autoscale.max_hosts must be >= min_hosts "
            f"({out.autoscale_min_hosts}), got {out.autoscale_max_hosts}")
    if out.autoscale_evals < 1:
        raise ValueError(
            f"serve.ring.autoscale.evals must be >= 1, "
            f"got {out.autoscale_evals}")
    if not 0.0 < out.autoscale_hysteresis < 1.0:
        raise ValueError(
            f"serve.ring.autoscale.hysteresis must be in (0, 1), "
            f"got {out.autoscale_hysteresis}")
    if out.autoscale_cooldown_s < 0:
        raise ValueError(
            f"serve.ring.autoscale.cooldown_s must be >= 0, "
            f"got {out.autoscale_cooldown_s}")
    if out.net_connect_timeout_s <= 0:
        raise ValueError(
            f"serve.net.connect_timeout_s must be > 0, "
            f"got {out.net_connect_timeout_s}")
    if out.net_read_timeout_s <= 0:
        raise ValueError(
            f"serve.net.read_timeout_s must be > 0, "
            f"got {out.net_read_timeout_s}")
    if out.net_retries < 0:
        raise ValueError(
            f"serve.net.retries must be >= 0, got {out.net_retries}")
    if out.net_backoff_ms < 0:
        raise ValueError(
            f"serve.net.backoff_ms must be >= 0, got {out.net_backoff_ms}")
    if out.net_breaker_threshold < 1:
        raise ValueError(
            f"serve.net.breaker_threshold must be >= 1, "
            f"got {out.net_breaker_threshold}")
    if out.net_breaker_reset_s < 0:
        raise ValueError(
            f"serve.net.breaker_reset_s must be >= 0, "
            f"got {out.net_breaker_reset_s}")
    if out.net_probe_interval_s < 0:
        raise ValueError(
            f"serve.net.probe_interval_s must be >= 0, "
            f"got {out.net_probe_interval_s}")
    if out.net_suspect_misses < 1:
        raise ValueError(
            f"serve.net.suspect_misses must be >= 1, "
            f"got {out.net_suspect_misses}")
    if out.net_dead_misses < 1:
        raise ValueError(
            f"serve.net.dead_misses must be >= 1, "
            f"got {out.net_dead_misses}")
    if out.net_revive_probes < 1:
        raise ValueError(
            f"serve.net.revive_probes must be >= 1, "
            f"got {out.net_revive_probes}")
    from mine_tpu.serve.wire import WIRE_CODECS, WIRE_FORMATS
    if out.wire_format not in WIRE_FORMATS:
        raise ValueError(
            f"serve.wire.format must be one of {'|'.join(WIRE_FORMATS)}, "
            f"got {out.wire_format!r}")
    if out.wire_codec not in WIRE_CODECS:
        raise ValueError(
            f"serve.wire.codec must be one of {'|'.join(WIRE_CODECS)}, "
            f"got {out.wire_codec!r}")
    if out.wire_coalesce_ms < 0:
        raise ValueError(
            f"serve.wire.coalesce_ms must be >= 0, "
            f"got {out.wire_coalesce_ms}")
    if out.wire_coalesce_max < 1:
        raise ValueError(
            f"serve.wire.coalesce_max must be >= 1, "
            f"got {out.wire_coalesce_max}")
    return out


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Observability knobs (mine_tpu/telemetry; README "Observability").

    Entirely host-side — nothing here changes jitted numerics or adds a
    per-step device sync (tests/test_telemetry.py pins that bitwise)."""
    # telemetry.enabled: master switch for the metrics registry mirror and
    # the JSONL event sink wiring in the train loop / serve CLI (the frozen
    # step-time LOG line prints regardless — it predates this layer)
    enabled: bool = True
    # telemetry.events_path: JSONL event stream destination; "" defaults to
    # <workspace>/events.jsonl (train loop) or <output_dir>/events.jsonl
    # (serve_cli). The MINE_TPU_TELEMETRY_EVENTS env var outranks both.
    events_path: str = ""
    # telemetry.profile_steps: [start, stop] global-step range (inclusive)
    # to capture under jax.profiler; empty/null disables
    profile_steps: tuple = ()
    # telemetry.profile_dir: trace destination; "" -> <workspace>/profile
    profile_dir: str = ""
    # telemetry.trace_sample: request-trace head-sampling rate in [0, 1]
    # (telemetry/tracing.py); 0 disables tracing, 1 traces every request.
    # Sampling gates TRACES only — metrics/SLO see every request.
    trace_sample: float = 0.0
    # telemetry.events_max_mb: rotate the JSONL event stream when it
    # crosses this size (MiB), keeping telemetry.events_keep rotated
    # segments; 0 = today's unbounded single file
    events_max_mb: float = 0.0
    # telemetry.events_keep: rotated segments retained alongside the live
    # file (events.jsonl.1 newest ... .K oldest)
    events_keep: int = 3
    # telemetry.resource_sample_s: process-vitals sampler cadence in
    # seconds (telemetry/resource.py: RSS/threads/fds/GC gauges); 0 = off
    resource_sample_s: float = 0.0
    # telemetry.recorder.*: the flight recorder (telemetry/recorder.py).
    # enabled=False constructs nothing — bitwise-parity bar unchanged.
    recorder_enabled: bool = False
    # telemetry.recorder.dir: incident bundle directory; "" defaults to
    # <workspace>/incidents (train) or alongside the events stream (serve)
    recorder_dir: str = ""
    # telemetry.recorder.events: ring size of the retained event tail
    recorder_events: int = 256
    # telemetry.recorder.steplines: retained recent st1 step lines
    recorder_steplines: int = 64
    # telemetry.recorder.snapshots: retained rolling registry snapshots
    # (the pre-incident baselines tools/postmortem.py diffs against)
    recorder_snapshots: int = 16
    # telemetry.recorder.debounce_s: minimum seconds between bundles — a
    # breach storm inside one window collapses to ONE bundle
    recorder_debounce_s: float = 60.0
    # telemetry.recorder.keep: keep-last-K bundle retention
    recorder_keep: int = 5
    # telemetry.recorder.arm_profile_steps: after a train-plane dump, arm
    # a profiler window over the next K steps (0 = off)
    recorder_arm_profile_steps: int = 0
    # telemetry.recorder.data_error_burst: trigger a bundle when one log
    # interval absorbs >= this many NEW data-pipeline errors (0 = off)
    recorder_data_error_burst: int = 0


def telemetry_config_from_dict(config: Dict[str, Any]) -> TelemetryConfig:
    g = config.get
    steps = g("telemetry.profile_steps") or ()
    if isinstance(steps, (int, float, str)):
        raise ValueError(
            f"telemetry.profile_steps must be a [start, stop] list, "
            f"got {steps!r}")
    out = TelemetryConfig(
        enabled=bool(g("telemetry.enabled", True)),
        events_path=str(g("telemetry.events_path", "") or ""),
        profile_steps=tuple(int(s) for s in steps),
        profile_dir=str(g("telemetry.profile_dir", "") or ""),
        trace_sample=float(g("telemetry.trace_sample", 0.0) or 0.0),
        events_max_mb=float(g("telemetry.events_max_mb", 0.0) or 0.0),
        events_keep=int(g("telemetry.events_keep", 3) or 3),
        resource_sample_s=float(
            g("telemetry.resource_sample_s", 0.0) or 0.0),
        recorder_enabled=bool(g("telemetry.recorder.enabled", False)),
        recorder_dir=str(g("telemetry.recorder.dir", "") or ""),
        recorder_events=int(g("telemetry.recorder.events", 256) or 256),
        recorder_steplines=int(
            g("telemetry.recorder.steplines", 64) or 64),
        recorder_snapshots=int(
            g("telemetry.recorder.snapshots", 16) or 16),
        recorder_debounce_s=float(
            g("telemetry.recorder.debounce_s", 60.0) or 0.0),
        recorder_keep=int(g("telemetry.recorder.keep", 5) or 5),
        recorder_arm_profile_steps=int(
            g("telemetry.recorder.arm_profile_steps", 0) or 0),
        recorder_data_error_burst=int(
            g("telemetry.recorder.data_error_burst", 0) or 0),
    )
    if out.profile_steps and (
            len(out.profile_steps) != 2 or out.profile_steps[0] < 1
            or out.profile_steps[1] < out.profile_steps[0]):
        raise ValueError(
            "telemetry.profile_steps must be [start, stop] with "
            f"1 <= start <= stop, got {list(out.profile_steps)}")
    if not 0.0 <= out.trace_sample <= 1.0:
        raise ValueError(
            f"telemetry.trace_sample must be in [0, 1], "
            f"got {out.trace_sample}")
    if out.events_max_mb < 0:
        raise ValueError(
            f"telemetry.events_max_mb must be >= 0, got {out.events_max_mb}")
    if out.events_keep < 1:
        raise ValueError(
            f"telemetry.events_keep must be >= 1, got {out.events_keep}")
    if out.resource_sample_s < 0:
        raise ValueError(
            f"telemetry.resource_sample_s must be >= 0, "
            f"got {out.resource_sample_s}")
    for field, floor in (("recorder_events", 1), ("recorder_steplines", 1),
                         ("recorder_snapshots", 1), ("recorder_keep", 1),
                         ("recorder_arm_profile_steps", 0),
                         ("recorder_data_error_burst", 0)):
        v = getattr(out, field)
        if v < floor:
            key = "telemetry.recorder." + field[len("recorder_"):]
            raise ValueError(f"{key} must be >= {floor}, got {v}")
    if out.recorder_debounce_s < 0:
        raise ValueError(
            f"telemetry.recorder.debounce_s must be >= 0, "
            f"got {out.recorder_debounce_s}")
    return out


# Datasets for which the sparse-3D-point disparity loss and scale factor are
# disabled (reference: synthesis_task.py:213-214,297).
_NO_DISP_DATASETS = ("flowers", "kitti_raw", "dtu")


def validate_model_shapes(cfg: "MPIConfig") -> None:
    """The encoder taps strides 2..32 and the decoder's upsample ladder
    doubles back up — non-multiple-of-32 shapes desync the skip concats
    deep in the graph (opaque concatenate errors). Model consumers
    (SynthesisTrainer, VideoGenerator) call this; dataset loaders don't,
    since loader-side resizing has no stride constraint."""
    for k in ("img_h", "img_w"):
        v = int(getattr(cfg, k))
        if v % 32 != 0:
            raise ValueError(
                f"data.{k}={v} must be a multiple of 32 (encoder stride-32 "
                f"taps + decoder upsample ladder); nearest valid: "
                f"{v // 32 * 32} or {-(-v // 32) * 32}")


def _resolve_auto_backend(value: str) -> str:
    """"auto" -> the measured-best backend for the RUNNING platform: the
    Pallas custom-VJP pair on TPU (13.4x the gather path on v5e, round-4
    measurement), plain XLA elsewhere (on CPU the Pallas kernels would run
    in interpret mode — orders of magnitude slower than XLA)."""
    if value != "auto":
        return value
    from mine_tpu.kernels import on_tpu_backend
    return "pallas_diff" if on_tpu_backend() else "xla"


def mpi_config_from_dict(config: Dict[str, Any]) -> MPIConfig:
    g = config.get
    name = g("data.name", "llff")
    backend = _resolve_auto_backend(g("training.composite_backend", "auto"))
    # "pallas" (forward-only) is an internal render-path backend; the training
    # loss graph differentiates through the composite, so only the custom-VJP
    # variant is valid here.
    if backend not in ("xla", "pallas_diff", "plane_scan"):
        raise ValueError(
            f"training.composite_backend must be auto|xla|pallas_diff|"
            f"plane_scan, got {backend!r}")
    warp_backend = _resolve_auto_backend(g("training.warp_backend", "auto"))
    if warp_backend not in ("xla", "xla_banded", "pallas_diff",
                            "separable", "pallas_sep", "pallas_fused"):
        raise ValueError(
            f"training.warp_backend must be auto|xla|xla_banded|pallas_diff|"
            f"separable|pallas_sep|pallas_fused, got {warp_backend!r}")
    warp_sep_tol = float(g("training.warp_sep_tol", 0.5))
    if warp_sep_tol < 0.0:
        raise ValueError(
            f"training.warp_sep_tol must be >= 0, got {warp_sep_tol!r}")
    warp_dtype = g("training.warp_dtype", "float32")
    if warp_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"training.warp_dtype must be float32|bfloat16, "
            f"got {warp_dtype!r}")
    ssim_precision = g("training.ssim_precision", "highest")
    if ssim_precision not in ("highest", "default"):
        raise ValueError(
            f"training.ssim_precision must be highest|default, "
            f"got {ssim_precision!r}")
    return MPIConfig(
        num_bins_coarse=g("mpi.num_bins_coarse", 32),
        num_bins_fine=g("mpi.num_bins_fine", 0),
        disparity_start=g("mpi.disparity_start", 1.0),
        disparity_end=g("mpi.disparity_end", 0.001),
        use_alpha=g("mpi.use_alpha", False),
        # NOTE: the reference passes config["mpi.render_tgt_rgb_depth"] (a key
        # that never exists -> always False) where it means is_bg_depth_inf
        # (synthesis_task.py:265,273,427). We honor the key that exists.
        is_bg_depth_inf=g("mpi.is_bg_depth_inf", False),
        valid_mask_threshold=float(g("mpi.valid_mask_threshold", 2)),
        fix_disparity=g("mpi.fix_disparity", False),
        smoothness_lambda_v1=g("loss.smoothness_lambda_v1", 0.5),
        smoothness_lambda_v2=g("loss.smoothness_lambda_v2", 1.0),
        smoothness_gmin=g("loss.smoothness_gmin", 2.0),
        smoothness_grad_ratio=g("loss.smoothness_grad_ratio", 0.1),
        src_rgb_blending=g("training.src_rgb_blending", True),
        use_multi_scale=g("training.use_multi_scale", True),
        composite_backend=backend,
        warp_backend=warp_backend,
        warp_band=int(g("training.warp_band", 48)),
        warp_dtype=warp_dtype,
        warp_sep_tol=warp_sep_tol,
        ssim_precision=ssim_precision,
        # visible_point_count == 0 also disables the sparse-point terms —
        # datasets with no SfM points (public RealEstate10K) train scale-free
        use_disparity_loss=(name not in _NO_DISP_DATASETS
                            and int(g("data.visible_point_count", 256) or 0) > 0),
        use_scale_factor=(name not in _NO_DISP_DATASETS
                          and int(g("data.visible_point_count", 256) or 0) > 0),
        img_h=g("data.img_h", 384),
        img_w=g("data.img_w", 512),
        pos_encoding_multires=g("model.pos_encoding_multires", 10),
        num_layers=g("model.num_layers", 50),
        sigma_dropout_rate=float(g("model.sigma_dropout_rate", 0.0) or 0.0),
        disparity_list=tuple(float(d) for d in (g("mpi.disparity_list") or ())),
    )
