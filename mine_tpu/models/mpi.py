"""Full MPI predictor: encoder + disparity-conditioned decoder.

Replaces SynthesisTask.mpi_predictor (synthesis_task.py:222-228) as a single
Flax module so the whole forward lives in one XLA graph.

Plane-chunked decoding (`plane_chunks > 1`): the decoder's effective batch is
B*S (depth_decoder.py:105-116) and its activations are the step's HBM peak —
B=8 at LLFF shapes overflows a 16 GB v5e (BENCH_NOTES_r02.md). Chunking runs
the decoder plane_chunks times on S/plane_chunks planes each, with each call
under jax.checkpoint, so the backward pass holds ONE chunk's activations at
a time instead of all B*S.

BN-statistics decision (made explicit, was deferred in ROADMAP): the decoder
ConvBlocks BatchNorm over the B*S batch; chunked training normalizes each
chunk by its OWN batch statistics ("ghost batch norm" over B*S/plane_chunks
examples) and the running averages see every chunk sequentially. The
receptive-field neck (whose batch is B, not B*S — plane-independent) is
computed ONCE per step outside the chunk loop, so its statistics and FLOPs
are identical to the unchunked model. Eval-mode outputs (running stats, no
dropout) are bitwise-independent of chunking, so converted reference
checkpoints behave identically; only training dynamics differ, in the
well-understood ghost-BN direction. GroupNorm was rejected: it would break
released-checkpoint compatibility.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from mine_tpu.models.decoder import MPIDecoder
from mine_tpu.models.resnet import ResnetEncoder, num_ch_enc


class MPIPredictor(nn.Module):
    num_layers: int = 50
    pos_encoding_multires: int = 10
    use_alpha: bool = False
    scales: Sequence[int] = (0, 1, 2, 3)
    sigma_dropout_rate: float = 0.0
    dtype: Optional[jnp.dtype] = None
    mesh: Optional[Any] = None  # forwarded to the decoder's B*S sharding
    plane_chunks: int = 1  # decoder calls over the S axis (memory knob)
    decoder_variant: str = "reference"  # "packed": stride-2 output stage
    # with 4x channels + depth-to-space head (models/decoder.py variant doc)

    def setup(self):
        if self.decoder_variant not in ("reference", "packed"):
            # fail at construction: a typo ("packed_head", "Packed") would
            # otherwise silently build the reference geometry and train the
            # wrong architecture under the right name
            raise ValueError(
                f"model.decoder_variant must be 'reference' or 'packed', "
                f"got {self.decoder_variant!r}")
        self.backbone = ResnetEncoder(num_layers=self.num_layers,
                                      dtype=self.dtype, name="backbone")
        decoder_cls = MPIDecoder
        if self.plane_chunks > 1:
            # per-chunk remat is the point of chunking: backward recomputes
            # one chunk's decoder forward at a time (train and neck_only
            # args are static)
            decoder_cls = nn.remat(MPIDecoder, static_argnums=(3, 4))
        self.decoder = decoder_cls(
            num_ch_enc=num_ch_enc(self.num_layers),
            pos_encoding_multires=self.pos_encoding_multires,
            use_alpha=self.use_alpha,
            scales=tuple(self.scales),
            sigma_dropout_rate=self.sigma_dropout_rate,
            variant=self.decoder_variant,
            dtype=self.dtype,
            mesh=self.mesh,
            name="decoder")

    def __call__(self, src_imgs, disparity, train: bool):
        """src_imgs [B,H,W,3] in [0,1]; disparity [B,S] ->
        list of 4 volumes [B,S,4,H/2^s,W/2^s] (scale order 0,1,2,3)."""
        return self.decode(self.encode(src_imgs, train), disparity, train)

    def encode(self, src_imgs, train: bool):
        """Backbone half, exposed as a stage boundary: src_imgs [B,H,W,3]
        -> tuple of 5 feature maps (strides 2..32). Applied standalone via
        `method="encode"` with only the backbone param/stat subtrees
        (mine_tpu/parallel/pipeline.py); __call__ composes encode+decode so
        the fused trace is unchanged."""
        # named_scope -> HLO metadata: profiler traces attribute time to
        # encoder vs decoder without guesswork
        with jax.named_scope("encoder"):
            return self.backbone(src_imgs, train)

    def decode(self, feats, disparity, train: bool):
        """Decoder half (plane-chunk logic included): encoder feature tuple
        + disparity [B,S] -> the 4-scale MPI list. Stage-boundary
        counterpart of `encode` (applied via `method="decode"`)."""
        S = disparity.shape[1]
        chunks = self.plane_chunks
        if chunks > 1 and S % chunks != 0:
            # e.g. the coarse-to-fine refinement pass with a different S; a
            # single unchunked call stays correct but holds the full B*S
            # activations — warn loudly, since at B=8 LLFF shapes that is
            # the HBM overflow this knob exists to prevent (the trainer
            # rejects non-divisible num_bins_coarse statically; this path
            # is for secondary passes with their own S)
            _warn_unchunked(S, chunks)
            chunks = 1
        with jax.named_scope("decoder"):
            if chunks == 1:
                # the remat-wrapped decoder's static_argnums cover the
                # neck args, so pass them explicitly on every path
                outputs = self.decoder(list(feats), disparity, train,
                                       False, None)
            else:
                cs = S // chunks
                neck = self.decoder(list(feats), disparity, train, True, None)
                outs = [self.decoder(list(feats),
                                     disparity[:, c * cs:(c + 1) * cs],
                                     train, False, neck)
                        for c in range(chunks)]
                outputs = {s: jnp.concatenate([o[s] for o in outs], axis=1)
                           for s in outs[0]}
        return [outputs[s] for s in sorted(outputs)]


_warned_unchunked = set()


def _warn_unchunked(S: int, chunks: int) -> None:
    """One-time trace-time notice when plane chunking is bypassed."""
    if (S, chunks) in _warned_unchunked:
        return
    _warned_unchunked.add((S, chunks))
    import warnings
    warnings.warn(
        f"plane_chunks={chunks} does not divide S={S}; decoder runs "
        f"UNCHUNKED for this pass (full B*S activation footprint)")
