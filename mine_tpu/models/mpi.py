"""Full MPI predictor: encoder + disparity-conditioned decoder.

Replaces SynthesisTask.mpi_predictor (synthesis_task.py:222-228) as a single
Flax module so the whole forward lives in one XLA graph.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from mine_tpu.models.decoder import MPIDecoder
from mine_tpu.models.resnet import ResnetEncoder, num_ch_enc


class MPIPredictor(nn.Module):
    num_layers: int = 50
    pos_encoding_multires: int = 10
    use_alpha: bool = False
    scales: Sequence[int] = (0, 1, 2, 3)
    sigma_dropout_rate: float = 0.0
    dtype: Optional[jnp.dtype] = None
    mesh: Optional[Any] = None  # forwarded to the decoder's B*S sharding

    def setup(self):
        self.backbone = ResnetEncoder(num_layers=self.num_layers,
                                      dtype=self.dtype, name="backbone")
        self.decoder = MPIDecoder(
            num_ch_enc=num_ch_enc(self.num_layers),
            pos_encoding_multires=self.pos_encoding_multires,
            use_alpha=self.use_alpha,
            scales=tuple(self.scales),
            sigma_dropout_rate=self.sigma_dropout_rate,
            dtype=self.dtype,
            mesh=self.mesh,
            name="decoder")

    def __call__(self, src_imgs, disparity, train: bool):
        """src_imgs [B,H,W,3] in [0,1]; disparity [B,S] ->
        list of 4 volumes [B,S,4,H/2^s,W/2^s] (scale order 0,1,2,3)."""
        # named_scope -> HLO metadata: profiler traces attribute time to
        # encoder vs decoder without guesswork
        with jax.named_scope("encoder"):
            feats = self.backbone(src_imgs, train)
        with jax.named_scope("decoder"):
            outputs = self.decoder(list(feats), disparity, train)
        return [outputs[s] for s in sorted(outputs)]
