"""Disparity-conditioned MPI decoder (monodepth2-style U-Net).

Reference: network/monodepth2/depth_decoder.py. Semantics preserved:
  * each of the S disparities is positionally encoded (21-dim for multires=10)
    and appended as constant channel maps to every skip feature
  * features are replicated S times — the effective batch through the decoder
    is B*S (depth_decoder.py:105-116); this axis is the natural sharding axis
    for data*plane parallelism on a TPU mesh
  * a downsample-conv-upsample "receptive-field extension" neck on the last
    encoder feature (depth_decoder.py:56-61,97-101)
  * 5 up-stages with skip connections, 4-channel output heads at scales 0-3
  * rgb = sigmoid, sigma = |x|+1e-4 (or sigmoid in alpha mode), optional
    whole-plane sigma dropout (depth_decoder.py:138-144)

TPU-first: NHWC compute (bfloat16-able); outputs are returned as float32
[B, S, 4, H_s, W_s] volumes for the rendering ops.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mine_tpu.models import embedder
from mine_tpu.models.layers import (Conv, ConvBlock, ConvBNLeaky,
                                    max_pool_3x3_s2, upsample_nearest_2x)
from mine_tpu.parallel.mesh import DATA_AXIS, PLANE_AXIS, constrain

NUM_CH_DEC = (16, 32, 64, 128, 256)


def depth_to_space_2x(x):
    """[N, h, w, 4*C] -> [N, 2h, 2w, C]; phase layout (dy, dx, c) so phase
    groups are contiguous blocks of C channels (the layout the packed-head
    weight transform in tools/convert_torch_weights.py emits)."""
    N, h, w, C4 = x.shape
    C = C4 // 4
    x = x.reshape(N, h, w, 2, 2, C)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))  # N, h, dy, w, dx, C
    return x.reshape(N, 2 * h, 2 * w, C)


class MPIDecoder(nn.Module):
    num_ch_enc: Tuple[int, ...]  # encoder channels, e.g. (64,256,512,1024,2048)
    pos_encoding_multires: int = 10
    use_alpha: bool = False
    scales: Sequence[int] = (0, 1, 2, 3)
    num_output_channels: int = 4
    use_skips: bool = True
    sigma_dropout_rate: float = 0.0
    # "reference": the monodepth2 geometry exactly (checkpoint-parity
    #   default).
    # "packed": the stride-2->1 stage (upconv_0_* + dispconv_0 — the
    #   largest-pixel-count convs, capped at 16/128 MXU lanes by the
    #   reference's tiny channel counts; BENCH_NOTES_r03.md lane table)
    #   computes at stride 2 with 4x channels and a depth-to-space at the
    #   head, lifting that stage to 64-lane occupancy. Conversion story: a
    #   nearest-upsample followed by a 3x3 conv is exactly a 4-phase conv
    #   at the low resolution (each output phase (dy,dx) sees a fixed
    #   subset of taps collapsed onto the half-res grid), so reference
    #   upconv_0_0/upconv_0_1/dispconv_0 weights map EXACTLY onto the
    #   packed kernels (phase-replicated BN params; interior-exact —
    #   reflect padding at stride 2 differs from stride 1 in a 2px border).
    variant: str = "reference"
    dtype: Optional[jnp.dtype] = None
    # jax.sharding.Mesh (hashable): when set, the B*S decoder batch is
    # constrained to shard over ("data","plane") so GSPMD distributes the
    # conv stack instead of replicating it across the plane axis — this is
    # where B*S lives (depth_decoder.py:105-116) and the point of
    # parallel.plane_parallel (VERDICT r1 weak item 3: annotation depth)
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, features, disparity, train: bool,
                 neck_only: bool = False, neck_out=None):
        """
        Args:
          features: 5 NHWC encoder maps at strides 2/4/8/16/32
          disparity: [B, S]
          neck_only: compute and return ONLY the receptive-field neck output
            (batch B — plane-independent). The plane-chunked predictor calls
            this once, then feeds the result back as `neck_out` to every
            chunk call, so the neck isn't recomputed (and its BN running
            stats aren't re-updated) per chunk.
          neck_out: precomputed neck output (skips the neck modules' calls;
            their params still exist from the neck_only call of the same
            apply, so checkpoint structure is unchanged).
        Returns:
          dict {scale: [B, S, 4, H_s, W_s] float32}, scale 0 = full res —
          or the neck output [B, h, w, C] when neck_only.
        """
        dd = features[-1].dtype if self.dtype is None else self.dtype

        if neck_only or neck_out is None:
            # receptive-field extension neck on the deepest feature
            x = features[-1].astype(dd)
            x = ConvBNLeaky(512, 1, dtype=self.dtype, name="conv_down1")(
                max_pool_3x3_s2(x), train)
            x = ConvBNLeaky(256, 3, dtype=self.dtype, name="conv_down2")(
                max_pool_3x3_s2(x), train)
            x = ConvBNLeaky(256, 3, dtype=self.dtype, name="conv_up1")(
                upsample_nearest_2x(x), train)
            x = ConvBNLeaky(self.num_ch_enc[-1], 1, dtype=self.dtype,
                            name="conv_up2")(upsample_nearest_2x(x), train)
            # The down/up round trip overshoots when H/32 is not a multiple
            # of 4 (maxpool ceils, upsample doubles); crop back. No-op at
            # the reference's training resolutions (H, W multiples of 128).
            x = x[:, :features[-1].shape[1], :features[-1].shape[2], :]
            if neck_only:
                return x
        else:
            x = neck_out

        B, S = disparity.shape

        emb = embedder.positional_encoding(
            disparity.reshape(B * S, 1).astype(jnp.float32),
            self.pos_encoding_multires).astype(dd)  # [B*S, E]

        def shard_bs(t):
            """Pin the flat B*S axis over data*plane (B-major flat index, so
            the chunking lines up with [B/data, S/plane] blocks per device)."""
            return constrain(t, self.mesh, (DATA_AXIS, PLANE_AXIS))

        def expand(feat):
            """[B,h,w,C] -> [B*S,h,w,C] (plane-major per example)."""
            _, h, w, C = feat.shape
            f = jnp.broadcast_to(feat[:, None], (B, S, h, w, C))
            return shard_bs(f.reshape(B * S, h, w, C))

        # The plane embedding is spatially CONSTANT, so every conv that
        # consumes an [..., E]-suffixed concat instead receives the E
        # values as a const_tail (layers.Conv): identical parameters and
        # math (reflect padding preserves constants — the conv's E-channel
        # contribution is exactly a per-plane bias), but the [B*S, h, w, E]
        # broadcasts are never materialized, convolved, or differentiated.
        # The kernel channel order stays [x, skip, emb] / [neck, emb], so
        # converted reference checkpoints drop in unchanged.
        x = expand(x)  # replaces features[-1] as the decoder stem
        tail = emb     # pending const-tail for the NEXT ConvBlock

        outputs = {}
        for i in range(4, -1, -1):
            packed = self.variant == "packed" and i == 0
            width = NUM_CH_DEC[i] * (4 if packed else 1)
            x = ConvBlock(width, dtype=self.dtype,
                          name=f"upconv_{i}_0{'p' if packed else ''}")(
                              x, train, const_tail=tail)
            tail = None
            if not packed:  # packed stage 0 stays at stride 2 until its head
                x = shard_bs(upsample_nearest_2x(x))
            else:
                # keep the B*S sharding constraint on the widest stage even
                # though the packed branch skips the upsample it was
                # attached to (advisor r4) — GSPMD would otherwise have to
                # infer stage 0's layout on multi-device meshes
                x = shard_bs(x)
            if self.use_skips and i > 0:
                x = jnp.concatenate(
                    [x, expand(features[i - 1].astype(dd))], axis=-1)
                tail = emb
            x = ConvBlock(width, dtype=self.dtype,
                          name=f"upconv_{i}_1{'p' if packed else ''}")(
                              x, train, const_tail=tail)
            tail = None
            if i in self.scales:
                out = Conv(self.num_output_channels * (4 if packed else 1),
                           3, pad_mode="reflect", dtype=self.dtype,
                           name=f"dispconv_{i}{'p' if packed else ''}")(x)
                if packed:
                    out = depth_to_space_2x(out)
                out = out.astype(jnp.float32)  # rendering happens in fp32
                rgb = nn.sigmoid(out[..., 0:3])
                if self.use_alpha:
                    sigma = nn.sigmoid(out[..., 3:4])
                else:
                    sigma = jnp.abs(out[..., 3:4]) + 1e-4
                if self.sigma_dropout_rate > 0.0 and train:
                    # whole-plane dropout (reference F.dropout2d on sigma)
                    sigma = nn.Dropout(
                        rate=self.sigma_dropout_rate,
                        broadcast_dims=(1, 2, 3),
                        deterministic=not train)(sigma)
                mpi = jnp.concatenate([rgb, sigma], axis=-1)  # [B*S,h,w,4]
                h, w = mpi.shape[1], mpi.shape[2]
                # -> [B,S,4,h,w] for the rendering ops
                outputs[i] = jnp.transpose(
                    mpi.reshape(B, S, h, w, 4), (0, 1, 4, 2, 3))
        return outputs
