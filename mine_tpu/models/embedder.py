"""NeRF positional encoding for scalar disparity conditioning.

Reference: utils.Embedder/get_embedder (utils.py:144-193) with input_dims=1,
include_input, log-sampled frequencies 2^0..2^(multires-1), sin+cos per
frequency -> output dim 1 + 2*multires (21 for multires=10).

Output ordering matches the reference's embed_fns concatenation:
[x, sin(2^0 x), cos(2^0 x), sin(2^1 x), cos(2^1 x), ...].
"""

from __future__ import annotations

import jax.numpy as jnp


def embedding_dim(multires: int, input_dims: int = 1) -> int:
    return input_dims * (1 + 2 * multires)


def positional_encoding(x: jnp.ndarray, multires: int = 10) -> jnp.ndarray:
    """Encode [..., 1] scalars to [..., 1 + 2*multires] features."""
    freqs = 2.0 ** jnp.arange(multires, dtype=x.dtype)  # [F]
    ang = x[..., None] * freqs  # [..., 1, F]
    sin = jnp.sin(ang)
    cos = jnp.cos(ang)
    # interleave sin/cos per frequency: [..., 1, F, 2] -> [..., 2F]
    sc = jnp.stack([sin, cos], axis=-1)
    sc = sc.reshape(x.shape[:-1] + (x.shape[-1] * 2 * multires,))
    return jnp.concatenate([x, sc], axis=-1)
