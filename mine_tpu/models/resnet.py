"""Flax ResNet encoder matching torchvision layouts (18/34/50/101/152).

Reference: network/monodepth2/resnet_encoder.py — ImageNet-normalizes the
input and returns 5 feature maps (conv1+relu, then the 4 residual stages) at
strides 2/4/8/16/32 with channels num_ch_enc = [64,64,128,256,512] (*4 on the
last four for Bottleneck variants, resnet_encoder.py:86).

TPU-first: NHWC, explicit symmetric padding (so converted torchvision weights
reproduce torch outputs bit-for-bit up to conv reassociation), bfloat16-able
compute with float32 BatchNorm. Converted checkpoints load via the weight
conversion tool (tools/, ships with the checkpointing milestone).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mine_tpu.models import layers
from mine_tpu.models.layers import BatchNorm, Conv, resnet_kernel_init

# ImageNet normalization (resnet_encoder.py:88-91)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

_BLOCKS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3),
           101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
_BOTTLENECK = {18: False, 34: False, 50: True, 101: True, 152: True}


def num_ch_enc(num_layers: int) -> Tuple[int, ...]:
    base = [64, 64, 128, 256, 512]
    if num_layers > 34:
        base[1:] = [c * 4 for c in base[1:]]
    return tuple(base)


class BasicBlock(nn.Module):
    planes: int
    strides: int = 1
    downsample: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool):
        residual = x
        y = Conv(self.planes, 3, strides=self.strides, use_bias=False,
                 kernel_init=resnet_kernel_init, dtype=self.dtype, name="conv1")(x)
        y = BatchNorm(use_running_average=not train, dtype=self.dtype, name="bn1")(y)
        y = nn.relu(y)
        y = Conv(self.planes, 3, use_bias=False, kernel_init=resnet_kernel_init,
                 dtype=self.dtype, name="conv2")(y)
        y = BatchNorm(use_running_average=not train, dtype=self.dtype, name="bn2")(y)
        if self.downsample:
            residual = Conv(self.planes, 1, strides=self.strides, use_bias=False,
                            kernel_init=resnet_kernel_init, dtype=self.dtype,
                            name="downsample_conv")(x)
            residual = BatchNorm(use_running_average=not train, dtype=self.dtype,
                                 name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """torchvision-style bottleneck (stride on the 3x3 conv, 'ResNet v1.5')."""
    planes: int
    strides: int = 1
    downsample: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool):
        residual = x
        y = Conv(self.planes, 1, use_bias=False, kernel_init=resnet_kernel_init,
                 dtype=self.dtype, name="conv1")(x)
        y = BatchNorm(use_running_average=not train, dtype=self.dtype, name="bn1")(y)
        y = nn.relu(y)
        y = Conv(self.planes, 3, strides=self.strides, use_bias=False,
                 kernel_init=resnet_kernel_init, dtype=self.dtype, name="conv2")(y)
        y = BatchNorm(use_running_average=not train, dtype=self.dtype, name="bn2")(y)
        y = nn.relu(y)
        y = Conv(self.planes * 4, 1, use_bias=False, kernel_init=resnet_kernel_init,
                 dtype=self.dtype, name="conv3")(y)
        y = BatchNorm(use_running_average=not train, dtype=self.dtype, name="bn3")(y)
        if self.downsample:
            residual = Conv(self.planes * 4, 1, strides=self.strides,
                            use_bias=False, kernel_init=resnet_kernel_init,
                            dtype=self.dtype, name="downsample_conv")(x)
            residual = BatchNorm(use_running_average=not train, dtype=self.dtype,
                                 name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResnetEncoder(nn.Module):
    """5-feature-map ResNet backbone.

    __call__(img [B,H,W,3] in [0,1], train) ->
        (conv1_out [B,H/2,W/2,64], block1..block4 at /4../32).
    """
    num_layers: int = 50
    dtype: Optional[jnp.dtype] = None

    @property
    def num_ch_enc(self) -> Tuple[int, ...]:
        return num_ch_enc(self.num_layers)

    @nn.compact
    def __call__(self, img, train: bool):
        if self.num_layers not in _BLOCKS:
            raise ValueError(f"{self.num_layers} is not a valid resnet depth")
        blocks = _BLOCKS[self.num_layers]
        block_cls = Bottleneck if _BOTTLENECK[self.num_layers] else BasicBlock
        expansion = 4 if _BOTTLENECK[self.num_layers] else 1

        mean = jnp.asarray(IMAGENET_MEAN, img.dtype)
        std = jnp.asarray(IMAGENET_STD, img.dtype)
        x = (img - mean) / std
        if self.dtype is not None:
            x = x.astype(self.dtype)

        x = Conv(64, 7, strides=2, padding=3, use_bias=False,
                 kernel_init=resnet_kernel_init, dtype=self.dtype, name="conv1")(x)
        x = BatchNorm(use_running_average=not train, dtype=self.dtype, name="bn1")(x)
        conv1_out = nn.relu(x)

        x = layers.max_pool_3x3_s2(conv1_out)
        feats = []
        inplanes = 64
        for stage, (n_blocks, planes) in enumerate(
                zip(blocks, (64, 128, 256, 512))):
            strides = 1 if stage == 0 else 2
            for b in range(n_blocks):
                s = strides if b == 0 else 1
                need_down = (b == 0) and (s != 1 or inplanes != planes * expansion)
                x = block_cls(planes, strides=s, downsample=need_down,
                              dtype=self.dtype,
                              name=f"layer{stage + 1}_{b}")(x, train)
                inplanes = planes * expansion
            feats.append(x)

        return (conv1_out, *feats)
