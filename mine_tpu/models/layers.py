"""Shared Flax building blocks with torch-compatible semantics.

All convs use NHWC (TPU-native) with *explicit* padding so outputs match
torch's symmetric padding exactly (flax 'SAME' pads asymmetrically for even
strides). Initializers reproduce torch defaults so from-scratch training is
distributionally comparable and converted checkpoints drop in unchanged.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.nn import initializers

Dtype = jnp.dtype

# torch Conv2d default: kaiming_uniform(a=sqrt(5)) == U(+-sqrt(1/fan_in))
torch_conv_kernel_init = initializers.variance_scaling(
    1.0 / 3.0, "fan_in", "uniform")
# torchvision ResNet conv init: kaiming_normal(mode='fan_out')
resnet_kernel_init = initializers.variance_scaling(2.0, "fan_out", "normal")


def torch_bias_init(key, shape, dtype, fan_in: int):
    bound = 1.0 / math.sqrt(fan_in) if fan_in > 0 else 0.0
    return jax.random.uniform(key, shape, dtype, -bound, bound)


class _SplitTailConv(nn.Module):
    """Conv whose last `tail` input channels are spatially CONSTANT.

    Holds the FULL [k, k, C+E, F] kernel (checkpoint-identical to the
    plain conv over the concatenated input) but receives only the first C
    channels as a tensor plus the E constant values per batch element.
    Because a constant map stays constant under reflect padding, the conv's
    contribution from those channels is exactly a per-example bias:
    values @ sum_kl W[k, l, C:, :]. Skipping them saves materializing,
    convolving, and differentiating a [B, H, W, E] broadcast — the
    positional-encoding channels of the MPI decoder's skip concats
    (models/decoder.py, the const-tail block above its stage loop;
    measured r5, BENCH_NOTES_r05.md).
    """
    features: int
    kernel_size: int
    full_in: int           # C + E — the checkpoint kernel's fan-in
    strides: int
    padding: Tuple          # lax-style ((t, b), (l, r)) spatial padding
    use_bias: bool
    kernel_init: Callable
    bias_init: Callable
    dtype: Optional[Dtype]

    @nn.compact
    def __call__(self, x, tail_values):
        k = self.kernel_size
        kernel = self.param("kernel", self.kernel_init,
                            (k, k, self.full_in, self.features), jnp.float32)
        bias = self.param("bias", self.bias_init, (self.features,),
                          jnp.float32) if self.use_bias else None
        C = x.shape[-1]
        assert C + tail_values.shape[-1] == self.full_in, \
            (C, tail_values.shape, self.full_in)
        dt = self.dtype or jnp.promote_types(x.dtype, jnp.float32)
        y = jax.lax.conv_general_dilated(
            x.astype(dt), kernel[:, :, :C, :].astype(dt),
            window_strides=(self.strides, self.strides),
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        w_tail = jnp.sum(kernel[:, :, C:, :], axis=(0, 1))  # [E, F]
        y = y + (tail_values.astype(dt) @ w_tail.astype(dt))[:, None, None, :]
        if bias is not None:
            y = y + bias.astype(dt)
        return y


class Conv(nn.Module):
    """NHWC conv with torch-style symmetric padding and init.

    `const_tail` ([B, E], optional call arg): the conv behaves as if the
    input were concat([x, broadcast(const_tail)], -1) — same parameter
    shapes/paths as that conv — without the broadcast ever existing (see
    _SplitTailConv). Only valid with reflect padding (or none): zero
    padding breaks the constant-map identity at borders.
    """
    features: int
    kernel_size: int = 3
    strides: int = 1
    padding: Optional[int] = None  # default: (k-1)//2 like torch common usage
    use_bias: bool = True
    pad_mode: str = "zeros"  # "zeros" | "reflect"
    kernel_init: Callable = torch_conv_kernel_init
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x, const_tail=None):
        k = self.kernel_size
        p = (k - 1) // 2 if self.padding is None else self.padding
        if p > 0 and self.pad_mode == "reflect":
            x = jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)), mode="reflect")
            pad = ((0, 0), (0, 0))
        else:
            pad = ((p, p), (p, p))
        tail = 0 if const_tail is None else const_tail.shape[-1]
        fan_in = k * k * (x.shape[-1] + tail)
        bias_init = lambda key, shape, dtype=jnp.float32: torch_bias_init(  # noqa: E731
            key, shape, dtype, fan_in)
        if const_tail is not None:
            assert self.pad_mode == "reflect" or p == 0, \
                "const_tail needs reflect (or no) padding"
            return _SplitTailConv(
                features=self.features, kernel_size=k,
                full_in=x.shape[-1] + tail,
                strides=self.strides, padding=pad,
                use_bias=self.use_bias, kernel_init=self.kernel_init,
                bias_init=bias_init, dtype=self.dtype,
                name="conv")(x, const_tail)
        conv = nn.Conv(
            features=self.features,
            kernel_size=(k, k),
            strides=(self.strides, self.strides),
            padding=pad,
            use_bias=self.use_bias,
            kernel_init=self.kernel_init,
            bias_init=bias_init,
            dtype=self.dtype,
            name="conv",
        )
        return conv(x)


class BatchNorm(nn.Module):
    """torch-compatible BatchNorm2d (momentum 0.1, eps 1e-5), float32 stats.

    Without an axis_name this is still *synchronized* across data-parallel
    shards under GSPMD/jit: the batch axis is a plain array axis of the global
    computation, so the mean/var are global means and XLA inserts the
    cross-replica collectives — the SPMD equivalent of the reference's
    SyncBatchNorm (synthesis_task.py:106-111).
    """
    use_running_average: bool
    momentum: float = 0.1
    epsilon: float = 1e-5
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        out_dtype = x.dtype if self.dtype is None else self.dtype
        norm = nn.BatchNorm(
            use_running_average=self.use_running_average,
            momentum=1.0 - self.momentum,  # flax: ra = m*ra + (1-m)*batch
            epsilon=self.epsilon,
            dtype=jnp.float32,
            name="bn",
        )
        return norm(x.astype(jnp.float32)).astype(out_dtype)


def max_pool_3x3_s2(x):
    """torch MaxPool2d(3, stride=2, padding=1) — pads with -inf, not zeros."""
    return nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))


def upsample_nearest_2x(x):
    """torch UpsamplingNearest2d(scale_factor=2) on NHWC."""
    return jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)


def downsample_nearest(x, factor: int):
    """torch nn.Upsample(size=H/2**s) nearest for exact integer factors is a
    strided slice (index floor(i*factor)). Reference: synthesis_task.py:129-133.
    """
    if factor == 1:
        return x
    return x[:, ::factor, ::factor, :]


class ConvBlock(nn.Module):
    """Reflect-pad 3x3 conv (with bias) + BN + ELU.

    Reference: monodepth2/layers.py:106-120 (ConvBlock = Conv3x3 + BN + ELU,
    Conv3x3 uses ReflectionPad2d).
    """
    features: int
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x, train: bool, const_tail=None):
        x = Conv(self.features, 3, pad_mode="reflect", dtype=self.dtype,
                 name="conv3x3")(x, const_tail=const_tail)
        x = BatchNorm(use_running_average=not train, dtype=self.dtype,
                      name="bn")(x)
        return nn.elu(x)


class ConvBNLeaky(nn.Module):
    """kxk conv (no bias, zero pad) + BN + LeakyReLU(0.1).

    Reference: depth_decoder.conv (depth_decoder.py:17-32, batchnorm branch).
    """
    features: int
    kernel_size: int
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = Conv(self.features, self.kernel_size, use_bias=False,
                 dtype=self.dtype, name="conv")(x)
        x = BatchNorm(use_running_average=not train, dtype=self.dtype,
                      name="bn")(x)
        return nn.leaky_relu(x, negative_slope=0.1)
