from mine_tpu.models.embedder import positional_encoding, embedding_dim  # noqa: F401
from mine_tpu.models.resnet import ResnetEncoder  # noqa: F401
from mine_tpu.models.decoder import MPIDecoder  # noqa: F401
