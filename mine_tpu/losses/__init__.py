from mine_tpu.losses.photometric import (edge_aware_image_masks,  # noqa: F401
                                         edge_aware_loss, edge_aware_loss_v2,
                                         image_mean_abs_grads, psnr)
from mine_tpu.losses.ssim import resolve_precision, ssim, ssim_pairs  # noqa: F401
