from mine_tpu.losses.photometric import (edge_aware_loss, edge_aware_loss_v2,  # noqa: F401
                                         psnr)
from mine_tpu.losses.ssim import ssim  # noqa: F401
