"""LPIPS perceptual metric (VGG16 backbone), eval-only.

Reference usage: synthesis_task.py:91-92,341-344 — `lpips.LPIPS(net="vgg")`
evaluated at scale 0 during validation, rank-0 only. The reference feeds
images in [0,1] without the package's `normalize=True` flag (i.e. the inputs
are NOT remapped to [-1,1]); we reproduce that behavior exactly for metric
parity.

Architecture (per the public LPIPS formulation):
  scaling layer -> VGG16 features at relu1_2/relu2_2/relu3_3/relu4_3/relu5_3
  -> unit-normalize channels -> squared diff -> 1x1 non-negative linear head
  -> spatial mean -> sum over the 5 taps.

This container has no network egress and no pretrained weights, so the module
is *gated*: `load_params(path)` loads weights converted offline by
tools/convert_torch_weights.py (from torchvision vgg16 + the lpips package's
linear heads); without a weights file, `available()` is False and the eval
harness reports lpips as NaN.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# VGG16 conv plan: (features, num_convs) per block; taps after each block's relu
_VGG_PLAN: Tuple[Tuple[int, int], ...] = (
    (64, 2), (128, 2), (256, 3), (512, 3), (512, 3))

# LPIPS scaling layer constants (public lpips implementation)
_SHIFT = np.array([-0.030, -0.088, -0.188], dtype=np.float32)
_SCALE = np.array([0.458, 0.448, 0.450], dtype=np.float32)


def _conv(x, w, b):
    """3x3 SAME conv, NHWC, HWIO kernel."""
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + b


def _vgg_features(params: Dict[str, jnp.ndarray], x: jnp.ndarray) -> List[jnp.ndarray]:
    """Run VGG16 conv stack, returning the 5 relu taps. x: [B,H,W,3]."""
    taps = []
    idx = 0
    for block, (feat, n_convs) in enumerate(_VGG_PLAN):
        for c in range(n_convs):
            x = jax.nn.relu(_conv(x, params[f"conv{idx}_w"], params[f"conv{idx}_b"]))
            idx += 1
        taps.append(x)
        if block < len(_VGG_PLAN) - 1:
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    return taps


def _unit_normalize(x: jnp.ndarray, eps: float = 1e-10) -> jnp.ndarray:
    norm = jnp.sqrt(jnp.sum(x ** 2, axis=-1, keepdims=True))
    return x / (norm + eps)


def lpips_distance(params: Dict[str, jnp.ndarray],
                   img1: jnp.ndarray, img2: jnp.ndarray) -> jnp.ndarray:
    """LPIPS distance per batch element.

    Args:
      params: dict with conv{i}_w/b (HWIO/bias) and lin{k}_w ([C] non-negative)
      img1, img2: [B, 3, H, W] (rendering-domain layout), values as-fed by the
        caller (the reference feeds [0,1] without remapping).
    Returns: [B]
    """
    def prep(img):
        x = jnp.transpose(img, (0, 2, 3, 1))  # NHWC
        return (x - jnp.asarray(_SHIFT)) / jnp.asarray(_SCALE)

    taps1 = _vgg_features(params, prep(img1))
    taps2 = _vgg_features(params, prep(img2))

    total = 0.0
    for k, (t1, t2) in enumerate(zip(taps1, taps2)):
        d = (_unit_normalize(t1) - _unit_normalize(t2)) ** 2  # [B,h,w,C]
        w = params[f"lin{k}_w"]  # [C]
        total = total + jnp.mean(jnp.sum(d * w, axis=-1), axis=(1, 2))
    return total


def load_params(path: str) -> Optional[Dict[str, jnp.ndarray]]:
    """Load converted LPIPS weights (.npz). Returns None if missing."""
    if not path or not os.path.exists(path):
        return None
    data = np.load(path)
    return {k: jnp.asarray(data[k]) for k in data.files}


def default_weights_path() -> str:
    return os.environ.get(
        "MINE_TPU_LPIPS_WEIGHTS",
        os.path.join(os.path.dirname(__file__), "..", "..", "weights",
                     "lpips_vgg.npz"))
