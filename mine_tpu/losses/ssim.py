"""Gaussian-window SSIM.

Reference: network/ssim.py — 11x11 window, sigma 1.5, per-channel grouped
conv with padding window//2, C1=0.01^2, C2=0.03^2, biased local variances.
The training loss uses 1 - ssim (synthesis_task.py:303,338).

TPU formulation: the gaussian window is separable (outer product of a 1D
gaussian with itself), and the images have only C=3 channels — a depthwise
11x11 conv puts those 3 channels on the 128 vector lanes and runs at ~2%
occupancy (measured r5: 57 ms/step across the train step's SSIM terms, the
single largest tail item after the warp). Instead the two 1D blurs are
expressed as BANDED TOEPLITZ MATMULS: out = M_h @ x @ M_w^T per channel,
with M built so border rows simply drop out-of-image taps — bit-equal
semantics to the reference conv's zero padding. The contraction runs on
the MXU at full lane width regardless of C, and autodiff's transpose of an
einsum is the same-shaped einsum, so the backward inherits the layout for
free. Measured on v5e (BENCH_NOTES_r05.md): 57.2 -> ~2 ms/step.

Dispatch fusion (the PR-2 pass): one SSIM evaluation needs 5 blurred
fields (x, y, x², y², xy) and the training loss evaluates TWO image pairs
per pyramid scale (src and tgt) — as independent `ssim()` calls that was
5 blurs x 2 einsums x 2 pairs = 20 MXU dispatches per scale, 80 per step.
`ssim_pairs` stacks every blur operand of every pair along the batch axis
of ONE Toeplitz pass, so a scale costs exactly 2 einsums (8 per step); the
batch axis of the einsum is elementwise-independent, so each image's blur
is bit-identical to its standalone call.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def resolve_precision(precision):
    """The ONE `training.ssim_precision` -> einsum-precision translation.

    "highest" / None -> Precision.HIGHEST: full-f32 MXU passes, matching the
    reference conv2d bit-for-bit on CPU and to f32 rounding on TPU (the
    shipped default). "default" -> None: the platform picks (bf16 operand
    splitting on TPU — ~2e-3 blur / ~3e-3 SSIM shift; with the Toeplitz
    form both settings measure ~2 ms/step on v5e, BENCH_NOTES_r05.md).
    A `jax.lax.Precision` passes through untouched.

    History note: this used to be TWO stacked maps (train/loss.py sent
    "highest"->None, `_blur` sent None->HIGHEST and "default"->None) — a
    double negation one refactor away from silently flipping the default.
    Every entry point now funnels through this helper instead.
    """
    if isinstance(precision, jax.lax.Precision):
        return precision
    if precision in (None, "highest"):
        return jax.lax.Precision.HIGHEST
    if precision == "default":
        return None
    raise ValueError(
        f"ssim precision must be 'highest', 'default', None, or a "
        f"jax.lax.Precision, got {precision!r}")


@functools.lru_cache(maxsize=None)
def _gaussian_1d(window_size: int, sigma: float) -> np.ndarray:
    x = np.arange(window_size, dtype=np.float64) - window_size // 2
    g = np.exp(-(x ** 2) / (2.0 * sigma ** 2))
    return (g / g.sum()).astype(np.float64)


@functools.lru_cache(maxsize=None)
def _blur_matrix(n: int, window_size: int, sigma: float) -> np.ndarray:
    """[n, n] banded Toeplitz blur: row i holds the window centered at i,
    with taps falling outside [0, n) dropped — exactly the reference conv's
    zero padding (window//2 each side)."""
    g = _gaussian_1d(window_size, sigma)
    half = window_size // 2
    M = np.zeros((n, n), np.float64)
    for t in range(window_size):
        off = t - half
        j0, j1 = max(0, -off), min(n, n - off)
        for i in range(j0, j1):
            M[i, i + off] = g[t]
    return M.astype(np.float32)


def _blur(x_nhwc: jnp.ndarray, window_size: int, sigma: float,
          precision) -> jnp.ndarray:
    """Separable gaussian blur of [B, H, W, C] via two Toeplitz matmuls.
    `precision` must already be resolved (see resolve_precision)."""
    H, W = x_nhwc.shape[1], x_nhwc.shape[2]
    Mh = jnp.asarray(_blur_matrix(H, window_size, sigma))
    Mw = jnp.asarray(_blur_matrix(W, window_size, sigma))
    x = jnp.einsum("ih,bhwc->biwc", Mh, x_nhwc,
                   preferred_element_type=jnp.float32,
                   precision=precision)
    return jnp.einsum("jw,bhwc->bhjc", Mw, x,
                      preferred_element_type=jnp.float32,
                      precision=precision)


def ssim_pairs(img1s: jnp.ndarray, img2s: jnp.ndarray,
               window_size: int = 11, sigma: float = 1.5,
               size_average: bool = False, precision=None) -> jnp.ndarray:
    """SSIM of P same-shape image pairs through ONE stacked blur pass.

    All 5 blur operands (x, y, x², y², xy) of all P pairs ride the batch
    axis of a single Toeplitz pass — 2 einsums total, vs 10 per pair as
    standalone `ssim()` calls. The einsum's batch dimension contracts each
    image independently, so every per-pair result is bit-identical to its
    standalone call; the transposed (autodiff) einsums inherit the same
    stacking, and pairs whose output is consumed under stop_gradient simply
    contribute zero cotangent slices.

    Args:
      img1s, img2s: [P, B, C, H, W]
      precision: "highest" | "default" | None | jax.lax.Precision
        (resolve_precision semantics)
    Returns: per-image means [P, B], or per-pair means [P] if size_average.
    """
    prec = resolve_precision(precision)
    P, B, C, H, W = img1s.shape
    x = jnp.transpose(img1s, (0, 1, 3, 4, 2)).astype(jnp.float32)
    y = jnp.transpose(img2s, (0, 1, 3, 4, 2)).astype(jnp.float32)
    x = x.reshape(P * B, H, W, C)
    y = y.reshape(P * B, H, W, C)

    stacked = jnp.concatenate([x, y, x * x, y * y, x * y], axis=0)
    blurred = _blur(stacked, window_size, sigma, prec)
    mu1, mu2, e_xx, e_yy, e_xy = jnp.split(blurred, 5, axis=0)

    mu1_sq = mu1 * mu1
    mu2_sq = mu2 * mu2
    mu1_mu2 = mu1 * mu2
    sigma1_sq = e_xx - mu1_sq
    sigma2_sq = e_yy - mu2_sq
    sigma12 = e_xy - mu1_mu2

    c1 = 0.01 ** 2
    c2 = 0.03 ** 2
    ssim_map = ((2 * mu1_mu2 + c1) * (2 * sigma12 + c2)) / (
        (mu1_sq + mu2_sq + c1) * (sigma1_sq + sigma2_sq + c2))

    per_image = jnp.mean(ssim_map, axis=(1, 2, 3)).reshape(P, B)
    return jnp.mean(per_image, axis=1) if size_average else per_image


def ssim(img1: jnp.ndarray, img2: jnp.ndarray,
         window_size: int = 11, sigma: float = 1.5,
         size_average: bool = True, precision=None) -> jnp.ndarray:
    """SSIM between [B, C, H, W] images. Returns a scalar (size_average) or
    per-image [B] means. Single-pair convenience wrapper over ssim_pairs;
    `precision` follows resolve_precision (None -> Precision.HIGHEST)."""
    per_image = ssim_pairs(img1[None], img2[None], window_size, sigma,
                           size_average=False, precision=precision)[0]
    return jnp.mean(per_image) if size_average else per_image
