"""Gaussian-window SSIM.

Reference: network/ssim.py — 11x11 window, sigma 1.5, per-channel grouped
conv with padding window//2, C1=0.01^2, C2=0.03^2, biased local variances.
The training loss uses 1 - ssim (synthesis_task.py:303,338).

TPU formulation: the gaussian window is separable (outer product of a 1D
gaussian with itself), and the images have only C=3 channels — a depthwise
11x11 conv puts those 3 channels on the 128 vector lanes and runs at ~2%
occupancy (measured r5: 57 ms/step across the train step's SSIM terms, the
single largest tail item after the warp). Instead the two 1D blurs are
expressed as BANDED TOEPLITZ MATMULS: out = M_h @ x @ M_w^T per channel,
with M built so border rows simply drop out-of-image taps — bit-equal
semantics to the reference conv's zero padding. The contraction runs on
the MXU at full lane width regardless of C, and autodiff's transpose of an
einsum is the same-shaped einsum, so the backward inherits the layout for
free. Measured on v5e (BENCH_NOTES_r05.md): 57.2 -> ~2 ms/step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def _gaussian_1d(window_size: int, sigma: float) -> np.ndarray:
    x = np.arange(window_size, dtype=np.float64) - window_size // 2
    g = np.exp(-(x ** 2) / (2.0 * sigma ** 2))
    return (g / g.sum()).astype(np.float64)


@functools.lru_cache(maxsize=None)
def _blur_matrix(n: int, window_size: int, sigma: float) -> np.ndarray:
    """[n, n] banded Toeplitz blur: row i holds the window centered at i,
    with taps falling outside [0, n) dropped — exactly the reference conv's
    zero padding (window//2 each side)."""
    g = _gaussian_1d(window_size, sigma)
    half = window_size // 2
    M = np.zeros((n, n), np.float64)
    for t in range(window_size):
        off = t - half
        j0, j1 = max(0, -off), min(n, n - off)
        for i in range(j0, j1):
            M[i, i + off] = g[t]
    return M.astype(np.float32)


def _blur(x_nhwc: jnp.ndarray, window_size: int, sigma: float,
          precision=None) -> jnp.ndarray:
    """Separable gaussian blur of [B, H, W, C] via two Toeplitz matmuls.

    precision defaults to Precision.HIGHEST: full-f32 MXU passes, matching
    the reference conv2d bit-for-bit on CPU and to f32 rounding on TPU.
    precision=None-as-passed ("default") lets the platform split operands
    into bf16 passes — on v5e that shifted the blur by ~2e-3 and the final
    SSIM by ~3e-3 while cutting the step's SSIM terms from 57 ms to ~2 ms
    pre-Toeplitz; with the Toeplitz form both run ~2 ms, so HIGHEST is the
    shipped default and "default" stays as the training.ssim_precision
    escape hatch."""
    if precision is None:
        precision = jax.lax.Precision.HIGHEST
    elif precision == "default":
        precision = None
    H, W = x_nhwc.shape[1], x_nhwc.shape[2]
    Mh = jnp.asarray(_blur_matrix(H, window_size, sigma))
    Mw = jnp.asarray(_blur_matrix(W, window_size, sigma))
    x = jnp.einsum("ih,bhwc->biwc", Mh, x_nhwc,
                   preferred_element_type=jnp.float32,
                   precision=precision)
    return jnp.einsum("jw,bhwc->bhjc", Mw, x,
                      preferred_element_type=jnp.float32,
                      precision=precision)


def ssim(img1: jnp.ndarray, img2: jnp.ndarray,
         window_size: int = 11, sigma: float = 1.5,
         size_average: bool = True, precision=None) -> jnp.ndarray:
    """SSIM between [B, C, H, W] images. Returns a scalar (size_average) or
    per-image [B] means. `precision` feeds the blur einsums: None ->
    Precision.HIGHEST, "default" -> platform default (see _blur)."""
    x = jnp.transpose(img1, (0, 2, 3, 1)).astype(jnp.float32)
    y = jnp.transpose(img2, (0, 2, 3, 1)).astype(jnp.float32)

    blur = functools.partial(_blur, window_size=window_size, sigma=sigma,
                             precision=precision)
    mu1 = blur(x)
    mu2 = blur(y)
    mu1_sq = mu1 * mu1
    mu2_sq = mu2 * mu2
    mu1_mu2 = mu1 * mu2

    sigma1_sq = blur(x * x) - mu1_sq
    sigma2_sq = blur(y * y) - mu2_sq
    sigma12 = blur(x * y) - mu1_mu2

    c1 = 0.01 ** 2
    c2 = 0.03 ** 2
    ssim_map = ((2 * mu1_mu2 + c1) * (2 * sigma12 + c2)) / (
        (mu1_sq + mu2_sq + c1) * (sigma1_sq + sigma2_sq + c2))

    if size_average:
        return jnp.mean(ssim_map)
    return jnp.mean(ssim_map, axis=(1, 2, 3))
