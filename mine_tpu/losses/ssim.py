"""Gaussian-window SSIM.

Reference: network/ssim.py — 11x11 window, sigma 1.5, per-channel grouped
conv with padding window//2, C1=0.01^2, C2=0.03^2, biased local variances.
The training loss uses 1 - ssim (synthesis_task.py:303,338).

Implemented as a depthwise NHWC convolution (single XLA conv per moment,
fuses cleanly); inputs are [B, C, H, W] float in [0, 1] to match the
rendering-domain layout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def _gaussian_window(window_size: int, sigma: float) -> np.ndarray:
    x = np.arange(window_size, dtype=np.float64) - window_size // 2
    g = np.exp(-(x ** 2) / (2.0 * sigma ** 2))
    g = g / g.sum()
    w2d = np.outer(g, g).astype(np.float32)
    return w2d  # [k, k]


def _depthwise_blur(x_nhwc: jnp.ndarray, window: jnp.ndarray) -> jnp.ndarray:
    C = x_nhwc.shape[-1]
    k = window.shape[0]
    kern = jnp.broadcast_to(window[:, :, None, None], (k, k, 1, C))
    pad = k // 2
    return jax.lax.conv_general_dilated(
        x_nhwc, kern,
        window_strides=(1, 1),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=C)


def ssim(img1: jnp.ndarray, img2: jnp.ndarray,
         window_size: int = 11, sigma: float = 1.5,
         size_average: bool = True) -> jnp.ndarray:
    """SSIM between [B, C, H, W] images. Returns a scalar (size_average) or
    per-image [B] means."""
    x = jnp.transpose(img1, (0, 2, 3, 1))
    y = jnp.transpose(img2, (0, 2, 3, 1))
    window = jnp.asarray(_gaussian_window(window_size, sigma))

    mu1 = _depthwise_blur(x, window)
    mu2 = _depthwise_blur(y, window)
    mu1_sq = mu1 * mu1
    mu2_sq = mu2 * mu2
    mu1_mu2 = mu1 * mu2

    sigma1_sq = _depthwise_blur(x * x, window) - mu1_sq
    sigma2_sq = _depthwise_blur(y * y, window) - mu2_sq
    sigma12 = _depthwise_blur(x * y, window) - mu1_mu2

    c1 = 0.01 ** 2
    c2 = 0.03 ** 2
    ssim_map = ((2 * mu1_mu2 + c1) * (2 * sigma12 + c2)) / (
        (mu1_sq + mu2_sq + c1) * (sigma1_sq + sigma2_sq + c2))

    if size_average:
        return jnp.mean(ssim_map)
    return jnp.mean(ssim_map, axis=(1, 2, 3))
