"""Photometric / smoothness losses and PSNR.

Reference: network/layers.py (psnr :48, edge_aware_loss :54,
edge_aware_loss_v2 :83). All functions take rendering-domain [B, C, H, W]
tensors.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Sobel kernels (x: horizontal derivative, y: vertical). The reference uses
# kornia.filters.spatial_gradient: 3x3 sobel, replicate padding, kernels
# normalized by their |sum| (=8) when normalized=True. Only |grad| is ever
# used downstream, so kernel sign/flip conventions drop out.
_SOBEL_X = np.array([[-1.0, 0.0, 1.0],
                     [-2.0, 0.0, 2.0],
                     [-1.0, 0.0, 1.0]], dtype=np.float32)
_SOBEL_Y = _SOBEL_X.T


def sobel_gradients(x: jnp.ndarray, normalized: bool = True) -> jnp.ndarray:
    """Per-channel sobel dx/dy with replicate padding.

    Args: x [B, C, H, W]
    Returns: [B, C, 2, H, W] (dim 2: x-grad, y-grad)
    """
    B, C, H, W = x.shape
    kx = _SOBEL_X / 8.0 if normalized else _SOBEL_X
    ky = _SOBEL_Y / 8.0 if normalized else _SOBEL_Y
    # depthwise conv in NHWC with both kernels stacked on the output axis
    xn = jnp.transpose(x, (0, 2, 3, 1))  # [B,H,W,C]
    xn = jnp.pad(xn, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="edge")
    kern = jnp.stack([jnp.asarray(kx), jnp.asarray(ky)], axis=-1)  # [3,3,2]
    kern = jnp.tile(kern[:, :, None, :], (1, 1, 1, C))  # [3,3,1,2*? ]
    kern = kern.reshape(3, 3, 1, 2 * C)  # order: (grad, channel) fastest=C
    out = jax.lax.conv_general_dilated(
        xn, kern, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=C)  # [B,H,W,C*2]? -> grouped: per input channel 2 outputs
    out = out.reshape(B, H, W, C, 2)
    return jnp.transpose(out, (0, 3, 4, 1, 2))  # [B,C,2,H,W]


def _instance_norm(x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """F.instance_norm (no affine): per-(B,C) standardization, biased var."""
    mean = jnp.mean(x, axis=(2, 3), keepdims=True)
    var = jnp.var(x, axis=(2, 3), keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps)


def psnr(img1: jnp.ndarray, img2: jnp.ndarray,
         size_average: bool = True) -> jnp.ndarray:
    """Mean PSNR over the batch for [0,1] images (network/layers.py:48-51).
    size_average=False returns per-image PSNR [B] (masked-eval aggregation)."""
    mse = jnp.mean((img1 - img2) ** 2, axis=(1, 2, 3))
    per_image = 20.0 * jnp.log10(1.0 / jnp.sqrt(mse))
    return jnp.mean(per_image) if size_average else per_image


def edge_aware_image_masks(img: jnp.ndarray, grad_ratio: float):
    """The image-only half of edge_aware_loss: per-image sobel edge masks
    (normalized by each image's own max gradient and grad_ratio, clamped at
    1). Depends on nothing but the image, so the training loss computes it
    once per pyramid scale and shares it across the src-logging and tgt
    smoothness terms instead of re-running the sobel conv per call site.

    Args: img [B,3,H,W]. Returns (edge_mask_x, edge_mask_y), each [B,1,H,W].
    """
    grad_img = jnp.sum(jnp.abs(sobel_gradients(img, normalized=True)),
                       axis=1, keepdims=True)  # [B,1,2,H,W]
    grad_img_x = grad_img[:, :, 0]
    grad_img_y = grad_img[:, :, 1]
    gmax_x = jnp.max(grad_img_x, axis=(1, 2, 3), keepdims=True)
    gmax_y = jnp.max(grad_img_y, axis=(1, 2, 3), keepdims=True)

    edge_mask_x = jnp.minimum(grad_img_x / (gmax_x * grad_ratio), 1.0)
    edge_mask_y = jnp.minimum(grad_img_y / (gmax_y * grad_ratio), 1.0)
    return edge_mask_x, edge_mask_y


def edge_aware_loss(img: jnp.ndarray, disp: jnp.ndarray,
                    gmin: float, grad_ratio: float,
                    size_average: bool = True,
                    edge_masks=None) -> jnp.ndarray:
    """Edge-masked hinge smoothness on instance-normalized disparity
    gradients (network/layers.py:54-80).

    Image gradients build a per-image edge mask (normalized by the image's own
    max gradient and grad_ratio, clamped at 1); disparity gradients are
    instance-normalized, hinged at gmin, and penalized away from edges.

    Args: img [B,3,H,W]; disp [B,1,H,W]; edge_masks optionally carries a
    precomputed `edge_aware_image_masks(img, grad_ratio)` result (callers
    evaluating several disparities against one image amortize the sobel).
    """
    if edge_masks is None:
        edge_masks = edge_aware_image_masks(img, grad_ratio)
    edge_mask_x, edge_mask_y = edge_masks

    grad_disp = jnp.abs(sobel_gradients(disp, normalized=False))
    grad_disp_x = _instance_norm(grad_disp[:, :, 0]) - gmin
    grad_disp_y = _instance_norm(grad_disp[:, :, 1]) - gmin

    loss_x = jax.nn.relu(grad_disp_x) * (1.0 - edge_mask_x)
    loss_y = jax.nn.relu(grad_disp_y) * (1.0 - edge_mask_y)
    if size_average:
        return jnp.mean(loss_x + loss_y)
    return jnp.mean(loss_x + loss_y, axis=(1, 2, 3))


def image_mean_abs_grads(img: jnp.ndarray):
    """The image-only half of edge_aware_loss_v2: channel-mean |finite-diff|
    gradients. Precomputable per pyramid scale and shared across the src/tgt
    v2 smoothness terms.

    Args: img [B,3,H,W]. Returns (grad_i_x [B,1,H,W-1], grad_i_y [B,1,H-1,W]).
    """
    grad_i_x = jnp.mean(jnp.abs(img[:, :, :, :-1] - img[:, :, :, 1:]),
                        axis=1, keepdims=True)
    grad_i_y = jnp.mean(jnp.abs(img[:, :, :-1, :] - img[:, :, 1:, :]),
                        axis=1, keepdims=True)
    return grad_i_x, grad_i_y


def edge_aware_loss_v2(img: jnp.ndarray, disp: jnp.ndarray,
                       size_average: bool = True,
                       img_grads=None) -> jnp.ndarray:
    """Classic monodepth2 edge-aware smoothness on mean-normalized disparity
    (network/layers.py:83-99).

    Args: img [B,3,H,W]; disp [B,1,H,W]; img_grads optionally carries a
    precomputed `image_mean_abs_grads(img)` result.
    """
    mean_disp = jnp.mean(disp, axis=(2, 3), keepdims=True)
    d = disp / (mean_disp + 1e-7)

    grad_d_x = jnp.abs(d[:, :, :, :-1] - d[:, :, :, 1:])
    grad_d_y = jnp.abs(d[:, :, :-1, :] - d[:, :, 1:, :])

    if img_grads is None:
        img_grads = image_mean_abs_grads(img)
    grad_i_x, grad_i_y = img_grads

    grad_d_x = grad_d_x * jnp.exp(-grad_i_x)
    grad_d_y = grad_d_y * jnp.exp(-grad_i_y)
    if size_average:
        return jnp.mean(grad_d_x) + jnp.mean(grad_d_y)
    return (jnp.mean(grad_d_x, axis=(1, 2, 3))
            + jnp.mean(grad_d_y, axis=(1, 2, 3)))
