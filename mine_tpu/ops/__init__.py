from mine_tpu.ops import rendering, sampling, warp  # noqa: F401
