"""Plane-sharded MPI volume rendering — a distributed transparency scan.

This is the workload's true "sequence parallelism" (SURVEY.md section 5,
long-context row): the reference keeps the whole S-plane volume on one
device and composites with a serial cumprod (mpi_rendering.py:42-67); the
GSPMD fallback for an S-sharded volume is an all-gather of the full
7-channel volume. Here each device composites ONLY its local planes and the
cross-shard combination rides two tiny collectives:

  1. one `ppermute` halo exchange of the FIRST plane's xyz per shard (the
     plane-distance term needs the next plane, so shard boundaries need one
     neighbor slice — [B,3,H,W] instead of the whole volume);
  2. one `all_gather` of each shard's TOTAL transparency product
     ([B,1,H,W] per shard) from which every shard forms the exclusive
     prefix product entering its block — the classic two-level scan
     (local scan + combine on block aggregates);
  3. one `psum` of the per-shard weighted rgb/depth/weight partials.

Per-device HBM traffic scales with S/P planes plus three plane-count-
independent exchanges, vs. the all-gather's full S. All math matches
ops/rendering.plane_volume_rendering bit-for-bit semantics, including the
reference's +1e-6 cumprod stabilizer (mpi_rendering.py:59) and the 1e3
far-plane distance, and everything is plain differentiable jnp + JAX
collectives, so jax.grad flows through the shard_map.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mine_tpu.parallel.mesh import DATA_AXIS, PLANE_AXIS, axis_size


def _local_composite(rgb, sigma, xyz, z_mask: bool, axis: str):
    """Per-shard body: local chain + cross-shard combine. Shapes are the
    LOCAL shard's [B, S_loc, C, H, W]."""
    B, S_loc, _, H, W = rgb.shape
    idx = jax.lax.axis_index(axis)
    n_shards = axis_size(axis)

    if z_mask:
        sigma = jnp.where(xyz[:, :, 2:3] >= 0.0, sigma, 0.0)

    # ---- halo: first xyz plane of the NEXT shard (left-shift permute) ----
    first_xyz = xyz[:, :1]  # [B,1,3,H,W]
    perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]
    next_first_xyz = jax.lax.ppermute(first_xyz, axis, perm)

    # plane distances: within-shard diffs + boundary diff to the halo slice;
    # the GLOBAL last plane gets the reference's 1e3 far distance
    xyz_ext = jnp.concatenate([xyz, next_first_xyz], axis=1)
    dist = jnp.linalg.norm(xyz_ext[:, 1:] - xyz_ext[:, :-1],
                           axis=2, keepdims=True)  # [B,S_loc,1,H,W]
    is_last_shard = idx == n_shards - 1
    last_dist = jnp.where(is_last_shard, 1e3, dist[:, -1])
    dist = dist.at[:, -1].set(last_dist)

    transparency = jnp.exp(-sigma * dist)
    alpha = 1.0 - transparency
    stabilized = transparency + 1e-6

    # local exclusive cumulative product + the shard's total product
    cum = jnp.cumprod(stabilized, axis=1)
    excl = jnp.concatenate([jnp.ones_like(cum[:, :1]), cum[:, :-1]], axis=1)
    total = cum[:, -1]  # [B,1,H,W]

    # ---- combine: exclusive prefix over shard totals ----
    totals = jax.lax.all_gather(total, axis)          # [P,B,1,H,W]
    shard_ids = jax.lax.broadcasted_iota(jnp.int32, (n_shards, 1, 1, 1, 1), 0)
    masked = jnp.where(shard_ids < idx, totals, jnp.ones_like(totals))
    prefix = jnp.prod(masked, axis=0)                 # [B,1,H,W]

    weights = prefix[:, None] * excl * alpha          # [B,S_loc,1,H,W]
    rgb_part = jnp.sum(weights * rgb, axis=1)         # [B,3,H,W]
    depth_part = jnp.sum(weights * xyz[:, :, 2:3], axis=1)
    wsum_part = jnp.sum(weights, axis=1)

    out = jax.lax.psum(
        jnp.concatenate([rgb_part, depth_part, wsum_part], axis=1), axis)
    return out  # [B,5,H,W] replicated over the plane axis


@functools.partial(jax.jit, static_argnames=("z_mask", "is_bg_depth_inf",
                                             "mesh"))
def plane_sharded_volume_render(rgb_BS3HW: jnp.ndarray,
                                sigma_BS1HW: jnp.ndarray,
                                xyz_BS3HW: jnp.ndarray,
                                mesh,
                                z_mask: bool = False,
                                is_bg_depth_inf: bool = False
                                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Distributed equivalent of rendering.plane_volume_rendering (+ z-mask).

    The volume stays sharded: batch over "data", planes over "plane". Falls
    back assertion-free only when S divides the plane axis; callers guard.
    Returns (rgb [B,3,H,W], depth [B,1,H,W]).
    """
    from mine_tpu.parallel.mesh import shard_map

    S = rgb_BS3HW.shape[1]
    n_plane = mesh.shape[PLANE_AXIS]
    assert S % n_plane == 0, (S, n_plane)

    body = functools.partial(_local_composite, z_mask=z_mask,
                             axis=PLANE_AXIS)
    vol = P(DATA_AXIS, PLANE_AXIS)
    f = shard_map(body, mesh=mesh,
                  in_specs=(vol, vol, vol),
                  out_specs=P(DATA_AXIS))
    out = f(rgb_BS3HW.astype(jnp.float32), sigma_BS1HW.astype(jnp.float32),
            xyz_BS3HW.astype(jnp.float32))
    from mine_tpu.ops.rendering import finalize_depth
    rgb_out = out[:, 0:3]
    depth_out = finalize_depth(out[:, 3:4], out[:, 4:5], is_bg_depth_inf)
    return rgb_out, depth_out
