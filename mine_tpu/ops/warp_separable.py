"""Separable row/column banded warp in pure XLA.

Fourth implementation of the homography-warp contract (reference hot op:
grid_sample over the B*S x 7 x H x W plane volume, homography_sampler.py:138).
The 2D banded backends (ops/warp_banded.py, kernels/warp_vjp.py) express
bilinear resampling as ONE one-hot matmul over the whole [C*BAND, W_s] band
per target row — every band row multiplies every output column, so MXU work
scales with band*W_t even though at most two band rows carry nonzero weight.

Per-plane homographies are translation-dominated: within one target row the
source-row coordinate cy(i, j) is nearly constant in j (it varies with j only
through perspective/shear terms). This module exploits that by factoring the
2D resample into two 1D one-hot resamples:

  * y pass (banded 1D matmul): per block of RT target rows, slice the same
    [C, BAND, W_s] source band as the 2D backends, then contract it against
    per-ROW tent weights wy[r, k] built from a scalar per-row anchor
    y^(i) = midrange_j cy(i, j) — one [RT, BAND] @ [C, BAND, W_s] matmul per
    block (2*C*BAND*W_s FLOPs per row);
  * x pass (1D matmul): per target row, contract the y-resampled row
    [C, W_s] against the EXACT per-pixel x tent weights [W_s, W_t]
    (2*C*W_s*W_t FLOPs per row) — identical wx form to the 2D backends.

dot FLOPs per target row: 2*C*W_s*(BAND + W_t) here vs 2*C*BAND*W_s*W_t for
xla_banded — a (BAND + W_t)/(BAND*W_t) ratio, ~0.023x at the flagship shape
(BAND=48, W_t=384), comfortably under the headline (2*BAND/W_t)x bound that
tests/test_warp_separable.py gates on the traced jaxpr.

Correctness domain (guard_ok, enforced by the lax.cond gather fallback in
separable_bilinear_sample_guarded):

  * band fit: each row-block's span of ANCHORS (not of the full 2D field)
    plus 2 rows of bilinear support must fit the band. Within-row cy
    variation no longer inflates the band requirement — poses whose joint
    2D span overflows the band can still take this fast path;
  * separability: the within-row variation instead becomes approximation
    error. The y pass samples every column of row i at the single anchor
    y^(i), so the value error is bounded by
        max_j |cy(i, j) - y^(i)| * L_y,   L_y = max adjacent-row |src delta|
    (the source's vertical Lipschitz constant under bilinear interpolation).
    The guard admits a pose only when the anchor deviation
    sep_err = max |cy - y^| is <= sep_tol (training.warp_sep_tol,
    default 0.5 px — sub-pixel error even on unit-Lipschitz content).

Exactness criterion (asserted in tests/test_warp_separable.py):
  * integer translations: BITWISE equal to ops.warp.bilinear_sample — the
    anchor is exact (cy constant per row; x+x and 0.5*x are exact in f32),
    the tent weights are exactly {0, 1}, and zero-weight terms are exact
    additive identities;
  * fractional translations (either axis): within ~1 ulp (atol 2.5e-7
    gated). Two benign f32 effects: the tent form computes the upper
    interpolation weight as 1-(1-t) — one extra rounding vs the gather's
    direct t — and the factorization lerps y-then-x where the gather
    lerps x-then-y (different association). Same weight property as the
    2D banded backends (their equivalence gates are atol 1e-5);
  * general in-domain poses: within the sep_err * L_y bound above (gated
    against the measured per-image bound);
  * out-of-domain poses: the lax.cond fallback IS ops.warp.bilinear_sample,
    so guarded output is bitwise the gather backend COMPILED THE SAME WAY
    (compare jitted-vs-jitted; XLA's eager lerp differs from its jitted
    lerp by ~1 ulp, which a bitwise gate must not conflate with this op).

Selected with `training.warp_backend: separable` (opt-in; `auto` still
resolves to pallas_diff/xla). kernels/warp_sep.py is the Pallas fwd+bwd
twin of this formulation.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from mine_tpu.kernels.warp import band_start, fwd_domain_ok


def row_anchor(coords_y_clipped: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row scalar y anchor + worst-case anchor deviation.

    The anchor is the midrange 0.5*(min_j + max_j) of the row's
    (border-clipped) source-y field — the minimax choice: it halves the
    worst deviation vs either extreme, and it is EXACT (bitwise cy) for
    translation poses where cy is constant along the row.

    Args:
      coords_y_clipped: [B', H_t, W_t], already clipped to [0, H_s-1]
    Returns:
      anchor [B', H_t] f32, sep_err scalar f32 = max |cy - anchor|
    """
    lo = jnp.min(coords_y_clipped, axis=2)
    hi = jnp.max(coords_y_clipped, axis=2)
    # 0.5*(lo+hi) is exact when lo == hi (x+x and 0.5*x are exact in f32),
    # which is what makes translation poses bitwise
    anchor = 0.5 * (lo + hi)
    sep_err = 0.5 * jnp.max(hi - lo)
    return anchor, sep_err


@functools.partial(jax.jit, static_argnames=("band", "rows_per_block",
                                             "mxu_dtype"))
def separable_bilinear_sample(src: jnp.ndarray,
                              coords_x: jnp.ndarray,
                              coords_y: jnp.ndarray,
                              band: int = 16,
                              rows_per_block: int = 8,
                              mxu_dtype=jnp.float32) -> jnp.ndarray:
    """Separable two-pass equivalent of ops.warp.bilinear_sample (see module
    docstring for the domain requirement and error bound).

    Args:
      src: [B', C, H_s, W_s]; coords_x/coords_y: [B', H_t, W_t]
      mxu_dtype: contraction dtype (bfloat16 doubles MXU rate; weights AND
        the y-resampled intermediate round at ~2^-8 relative — one more
        value rounding than the 2D banded path — accumulation stays f32)
    Returns: [B', C, H_t, W_t] float32
    """
    Bp, C, H_s, W_s = src.shape
    _, H_t, W_t = coords_x.shape
    RT = rows_per_block
    assert H_t % RT == 0, (H_t, RT)
    NB = H_t // RT
    band = min(band, H_s)

    src = src.astype(jnp.float32)
    xc = jnp.clip(coords_x, 0.0, W_s - 1.0).astype(jnp.float32)
    yc = jnp.clip(coords_y, 0.0, H_s - 1.0).astype(jnp.float32)

    anchor, _ = row_anchor(yc)                      # [B', H_t]
    # shared band placement rule, fed the anchor field (W_t axis of size 1):
    # the band follows the per-row anchors, not the full 2D span
    y0 = band_start(anchor[:, :, None], H_s, band, RT)  # [B', NB]

    xs = jax.lax.broadcasted_iota(jnp.float32, (W_s, W_t), 0)   # src x pos
    ks = jax.lax.broadcasted_iota(jnp.float32, (1, band), 1)    # band y pos

    xc_blocks = xc.reshape(Bp, NB, RT, W_t)
    anchor_blocks = anchor.reshape(Bp, NB, RT)

    def slice_band(img_chw, y):
        return jax.lax.dynamic_slice(img_chw, (0, y, 0), (C, band, W_s))

    def block_step(_, nb):
        bands = jax.vmap(slice_band)(src, y0[:, nb])  # [B', C, band, W_s]

        sy = anchor_blocks[:, nb] - y0[:, nb, None].astype(jnp.float32)
        sy = jnp.clip(sy, 0.0, band - 1.0)  # band coverage clamp
        # [B', RT, band] one-hot y tents (<=2 nonzeros per row) -> the
        # banded 1D y matmul: every row of the block in ONE contraction
        wy = jnp.maximum(1.0 - jnp.abs(ks - sy[:, :, None]), 0.0)
        tmp = jnp.einsum("brk,bcks->bcrs", wy.astype(mxu_dtype),
                         bands.astype(mxu_dtype),
                         preferred_element_type=jnp.float32)
        tmp = tmp.astype(mxu_dtype)  # [B', C, RT, W_s]

        def row_step(__, r):
            sx = xc_blocks[:, nb, r]                         # [B', W_t]
            # exact per-pixel x weights — the x pass carries ALL of the
            # within-row coordinate variation (same wx form as warp_banded)
            wx = jnp.maximum(1.0 - jnp.abs(xs[None] - sx[:, None, :]), 0.0)
            out_r = jnp.einsum("bcs,bst->bct", tmp[:, :, r],
                               wx.astype(mxu_dtype),
                               preferred_element_type=jnp.float32)
            return None, out_r  # [B', C, W_t]

        _, rows = jax.lax.scan(row_step, None, jnp.arange(RT))
        return None, rows  # [RT, B', C, W_t]

    _, blocks = jax.lax.scan(block_step, None, jnp.arange(NB))
    # [NB, RT, B', C, W_t] -> [B', C, NB*RT, W_t]
    return blocks.transpose(2, 3, 0, 1, 4).reshape(Bp, C, H_t, W_t)


def guard_ok(src_shape, coords_y, band: int = 16,
             rows_per_block: int = 8,
             sep_tol: float = 0.5) -> jnp.ndarray:
    """THE fallback decision of separable_bilinear_sample_guarded, as a
    scalar bool — exposed so diagnostics (ops/warp.homography_warp's
    with_domain_flag) consume the same logic instead of mirroring it.

    Two conditions (module docstring "correctness domain"):
      * the per-row ANCHORS' block span fits the band (fwd_domain_ok on the
        anchor field, aligned=False: pure-XLA band starts need no sublane
        slack) — strictly weaker than the 2D backends' joint-span check;
      * the anchor deviation sep_err = max |cy - y^| is <= sep_tol, keeping
        the separability error below sep_tol * L_y.
    """
    H_s = src_shape[2]
    H_t = coords_y.shape[1]
    if H_t % rows_per_block != 0:
        return jnp.zeros((), jnp.bool_)
    yc = jnp.clip(coords_y, 0.0, H_s - 1.0)
    anchor, sep_err = row_anchor(yc)
    band_fits = fwd_domain_ok(anchor[:, :, None], H_s, band,
                              rows_per_block, aligned=False)
    return band_fits & (sep_err <= sep_tol)


def separable_bilinear_sample_guarded(src, coords_x, coords_y,
                                      band: int = 16,
                                      rows_per_block: int = 8,
                                      mxu_dtype=jnp.float32,
                                      sep_tol: float = 0.5):
    """Separable XLA warp with the runtime gather fallback.

    Same guard pattern as ops/warp_banded.py: lax.cond on the pose-derived
    domain check; both branches are XLA-differentiable, so this drops into
    the training step directly. The fallback branch IS
    ops.warp.bilinear_sample, so out-of-domain output is bitwise the
    gather backend's.
    """
    from mine_tpu.ops.warp import bilinear_sample

    # the gather fallback honors the same value dtype (bf16 storage keeps
    # the HBM-traffic benefit when the separable path bails); both paths
    # return f32, so the cond branches agree (f32 is a no-op knob)
    gather_dtype = mxu_dtype

    src = src.astype(jnp.float32)
    H_t = coords_x.shape[1]
    if H_t % rows_per_block != 0:
        return bilinear_sample(src, coords_x, coords_y,
                               gather_dtype=gather_dtype)

    ok = guard_ok(src.shape, coords_y, band, rows_per_block, sep_tol)
    return jax.lax.cond(
        ok,
        lambda s, x, y: separable_bilinear_sample(
            s, x, y, band=band, rows_per_block=rows_per_block,
            mxu_dtype=mxu_dtype),
        lambda s, x, y: bilinear_sample(s, x, y, gather_dtype=gather_dtype),
        src, coords_x, coords_y)
