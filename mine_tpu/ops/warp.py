"""Homography warping of the MPI plane volume.

Replaces the reference's HomographySample (homography_sampler.py:10-141),
whose hot op is `F.grid_sample(padding_mode='border', align_corners=False)`
over a B*S x 7 x H x W volume. On TPU this is a gather; the XLA path below is
the reference implementation, designed so a Pallas kernel with the same
contract can slot in as the fused fast path.

Sampling semantics (must match for checkpoint parity — SURVEY.md section 7
"hard parts" #1): the reference normalizes pixel coords p to grid
g = (p+0.5)/(0.5*size) - 1 (homography_sampler.py:136-137) and then
grid_sample with align_corners=False maps g back to pixels as
(g+1)*size/2 - 0.5 == p. Net effect: bilinear sampling at continuous pixel
coordinates with border clamping. We implement that directly, skipping the
[-1,1] round trip.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from mine_tpu import geometry


def bilinear_sample(src: jnp.ndarray,
                    coords_x: jnp.ndarray,
                    coords_y: jnp.ndarray,
                    gather_dtype=None) -> jnp.ndarray:
    """Bilinear sample with border padding at continuous pixel coords.

    Equivalent to torch grid_sample(border, align_corners=False) after the
    reference's grid normalization (see module docstring).

    Args:
      src: [B, C, H, W]
      coords_x, coords_y: [B, Ho, Wo] sample locations in src pixel coords
      gather_dtype: optional storage dtype for the gathered FORWARD values
        (jnp.bfloat16 halves the forward HBM read of the hot
        B*S x 7 x H x W volume at ~2^-8 relative value rounding; the lerp
        runs in float32 and the BACKWARD scatter-add accumulates in float32
        via a custom VJP — a bf16 scatter would drop contributions below
        ~2^-8 of the running sum wherever many target pixels hit the same
        source texel. The bf16 path returns zero coordinate cotangents,
        matching kernels/warp_vjp.py; every training caller stop-gradients
        coords anyway.)
    Returns: [B, C, Ho, Wo] float32
    """
    # float32 (or None) is the identity storage dtype -> plain autodiff path;
    # any reduced dtype ALWAYS routes through the f32-accumulating custom VJP
    # (even when src already arrives reduced — the plain path's backward
    # would scatter-accumulate in the reduced dtype).
    if gather_dtype is not None and jnp.dtype(gather_dtype) != jnp.float32:
        return _bilinear_sample_cast(src.astype(jnp.float32), coords_x,
                                     coords_y, jnp.dtype(gather_dtype).name)
    return _lerp_gather(src, coords_x, coords_y)


def _lerp_gather(src: jnp.ndarray, coords_x: jnp.ndarray,
                 coords_y: jnp.ndarray) -> jnp.ndarray:
    """Autodiffable core: gather in src's dtype, lerp in float32."""
    B, C, H, W = src.shape
    # Border padding == clamp the sampling location into the pixel-center box.
    x = jnp.clip(coords_x, 0.0, W - 1.0)
    y = jnp.clip(coords_y, 0.0, H - 1.0)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    tx = x - x0
    ty = y - y0

    x0i = x0.astype(jnp.int32)
    y0i = y0.astype(jnp.int32)
    x1i = jnp.minimum(x0i + 1, W - 1)
    y1i = jnp.minimum(y0i + 1, H - 1)

    def gather_one(img_chw, yi, xi):
        # img_chw [C,H,W]; yi/xi [Ho,Wo] -> [C,Ho,Wo]
        return img_chw[:, yi, xi]

    g = jax.vmap(gather_one)
    v00 = g(src, y0i, x0i)
    v01 = g(src, y0i, x1i)
    v10 = g(src, y1i, x0i)
    v11 = g(src, y1i, x1i)

    tx = tx[:, None, :, :]
    ty = ty[:, None, :, :]
    if src.dtype != jnp.float32:  # lerp in f32 regardless of storage dtype
        v00, v01, v10, v11 = (v.astype(jnp.float32)
                              for v in (v00, v01, v10, v11))
    top = v00 * (1.0 - tx) + v01 * tx
    bot = v10 * (1.0 - tx) + v11 * tx
    return top * (1.0 - ty) + bot * ty


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bilinear_sample_cast(src, coords_x, coords_y, gather_dtype: str):
    """bf16-storage forward, f32-accumulating backward (see bilinear_sample)."""
    return _lerp_gather(src.astype(gather_dtype), coords_x, coords_y)


def _bsc_fwd(src, coords_x, coords_y, gather_dtype):
    out = _bilinear_sample_cast(src, coords_x, coords_y, gather_dtype)
    return out, (src.shape, coords_x, coords_y)


def _bsc_bwd(gather_dtype, residuals, g):
    src_shape, coords_x, coords_y = residuals
    # The op is linear in src, so its transpose (the scatter-add) can run on
    # the f32 core regardless of the forward's storage dtype; d/dsrc of the
    # bf16 cast is identity (same as autodiff's astype VJP).
    d_src, = jax.linear_transpose(
        lambda s: _lerp_gather(s, coords_x, coords_y),
        jax.ShapeDtypeStruct(src_shape, jnp.float32))(g.astype(jnp.float32))
    return d_src, jnp.zeros_like(coords_x), jnp.zeros_like(coords_y)


_bilinear_sample_cast.defvjp(_bsc_fwd, _bsc_bwd)


def warp_coords(d_src: jnp.ndarray,
                G_tgt_src: jnp.ndarray,
                K_src_inv: jnp.ndarray,
                K_tgt: jnp.ndarray,
                meshgrid_tgt: jnp.ndarray,
                src_hw: Tuple[int, int]):
    """Source-pixel sampling coords for the inverse-homography warp.

    The shared front half of `homography_warp`, factored out so the fused
    render path (ops/rendering.py warp_impl="pallas_fused") computes coords
    through the SAME ops as every other backend — one graph, one rounding
    behavior.

    Args: as homography_warp; src_hw = (H, W) of the source planes.
    Returns: (x [B',Ht,Wt], y [B',Ht,Wt], valid [B',Ht,Wt] bool)
    """
    H, W = src_hw
    Bp = d_src.shape[0]
    _, Ht, Wt = meshgrid_tgt.shape
    H_tgt_src = geometry.homography_tgt_src(K_tgt, K_src_inv, G_tgt_src, d_src)
    H_src_tgt = jax.lax.stop_gradient(geometry.inverse_3x3(H_tgt_src))

    grid = meshgrid_tgt.reshape(3, Ht * Wt)
    src_homo = jnp.einsum("bij,jn->bin", H_src_tgt, grid)  # [B',3,HtWt]
    src_xy = src_homo[:, 0:2, :] / src_homo[:, 2:3, :]
    x = src_xy[:, 0, :].reshape(Bp, Ht, Wt)
    y = src_xy[:, 1, :].reshape(Bp, Ht, Wt)

    valid = ((x > -1.0) & (x < float(W)) & (y > -1.0) & (y < float(H)))
    return x, y, valid


def homography_warp(src_BCHW: jnp.ndarray,
                    d_src: jnp.ndarray,
                    G_tgt_src: jnp.ndarray,
                    K_src_inv: jnp.ndarray,
                    K_tgt: jnp.ndarray,
                    meshgrid_tgt: jnp.ndarray,
                    impl: str = "xla",
                    band: int = 16,
                    mesh=None,
                    mxu_dtype=jnp.float32,
                    with_domain_flag: bool = False,
                    sep_tol: float = 0.5):
    """Warp source-plane images into the target camera via inverse homography.

    For each batch element: compose H_tgt_src = K_tgt (R - t n^T / -d) K_src^-1,
    invert it (closed form, no grad — matching the reference's no_grad inverse,
    homography_sampler.py:112-113), map the target pixel grid into source
    pixels, bilinear-sample with border padding, and report which target pixels
    landed inside the source image.

    Reference: HomographySample.sample (homography_sampler.py:58-141).

    Args:
      src_BCHW: [B', C, H, W] plane images (B' is typically B*S)
      d_src: [B'] plane depths
      G_tgt_src: [B', 4, 4]
      K_src_inv, K_tgt: [B', 3, 3]
      meshgrid_tgt: [3, Ht, Wt] homogeneous target pixel grid
      impl: "xla" (gather; autodiffed), "xla_banded" (banded one-hot-matmul
        in pure XLA with a runtime gather fallback — autodiffed, trainable,
        GSPMD-partitionable; ops/warp_banded.py), "separable" (row-then-
        column 1D one-hot matmuls in pure XLA — ~(band+W)/(band*W) the
        banded dot FLOPs, anchor-banded so the guard drops the within-row
        span term; autodiffed, GSPMD-partitionable; ops/warp_separable.py),
        "pallas" (banded MXU gather kernel, forward-only; caller must
        validate the band via kernels.warp.band_span), "pallas_diff"
        (banded fwd+bwd kernels with a built-in runtime gather fallback —
        the Pallas training backend), "pallas_sep" (Pallas fwd+bwd pair
        of the separable form; kernels/warp_sep.py), or "pallas_fused"
        (under THIS warp-only contract: identical to pallas_diff; inside
        render_tgt_rgb_depth it selects the warp+dequant+composite
        megakernel, kernels/render_fused.py)
      mesh: ("data","plane") jax Mesh. With impl="pallas_diff"/"pallas_sep"
        on a multi-device mesh the kernel runs under shard_map with the
        flat B' axis split over data*plane (matching the decoder's B*S
        layout, models/decoder.py shard_bs) — each device warps its local
        planes, no cross-device traffic.
      with_domain_flag: also return `in_domain`, a scalar f32 diagnostic —
        the FRACTION of this call that took the guarded banded backends'
        (pallas_diff / pallas_sep / xla_banded / separable) fast path:
        1.0 all-fast, 0.0 all on the runtime gather fallback, NaN for
        backends with no guard (plain xla / forward-only pallas). Under a
        sharded Pallas mesh the cond decides per shard, and the flag is
        the pmean of the per-shard guards over data*plane — e.g. 0.75 when
        one of four shards drew an out-of-band pose (the pre-r6
        global-coords flag reported 0.0 for that step). Powers the
        `warp_fallback_frac` training metric (VERDICT r4 weak item 5).
      sep_tol: separable backends only (training.warp_sep_tol) — max
        admitted per-row anchor deviation in source rows; poses above it
        take the gather fallback (ops/warp_separable.py error bound).
    Returns:
      tgt [B', C, Ht, Wt], valid_mask [B', Ht, Wt] (bool)
      [, in_domain scalar f32 — only when with_domain_flag]
    """
    Bp, C, H, W = src_BCHW.shape
    _, Ht, Wt = meshgrid_tgt.shape

    x, y, valid = warp_coords(d_src, G_tgt_src, K_src_inv, K_tgt,
                              meshgrid_tgt, (H, W))

    # diagnostic only — mirrors each guarded backend's fallback decision
    # (NaN = backend has no runtime guard to measure)
    in_domain = jnp.full((), jnp.nan, jnp.float32)

    if impl == "pallas":
        from mine_tpu.kernels import on_tpu_backend
        from mine_tpu.kernels.warp import pallas_bilinear_sample
        tgt = pallas_bilinear_sample(src_BCHW, x, y, band=band,
                                     interpret=not on_tpu_backend())
    elif impl in ("xla_banded", "separable"):
        # banded / separable one-hot-matmul warps in pure XLA: both are
        # differentiable by autodiff and GSPMD-partitionable directly, so
        # no shard_map wrapper or mesh-divisibility guard is needed
        xs = jax.lax.stop_gradient(x)
        ys = jax.lax.stop_gradient(y)
        if impl == "xla_banded":
            from mine_tpu.ops import warp_banded
            in_domain = warp_banded.guard_ok(
                src_BCHW.shape, ys, band).astype(jnp.float32)
            tgt = warp_banded.banded_bilinear_sample_guarded(
                src_BCHW, xs, ys, band=band, mxu_dtype=mxu_dtype)
        else:
            from mine_tpu.ops import warp_separable
            in_domain = warp_separable.guard_ok(
                src_BCHW.shape, ys, band, sep_tol=sep_tol).astype(
                    jnp.float32)
            tgt = warp_separable.separable_bilinear_sample_guarded(
                src_BCHW, xs, ys, band=band, mxu_dtype=mxu_dtype,
                sep_tol=sep_tol)
    elif impl in ("pallas_diff", "pallas_sep", "pallas_fused"):
        # training paths: Pallas fwd+bwd with runtime gather fallback
        # outside each backend's domain (kernels/warp_vjp.py — 2D band;
        # kernels/warp_sep.py — anchor band + separability). Coords are
        # non-learnable (no-grad inverse above), so stop_gradient keeps the
        # two branches' autodiff structurally identical.
        from mine_tpu.kernels import on_tpu_backend
        if impl in ("pallas_diff", "pallas_fused"):
            # "pallas_fused" fuses warp+dequant+composite inside
            # render_tgt_rgb_depth (kernels/render_fused.py); under the
            # warp-only contract here it is the banded pallas_diff warp —
            # same band geometry, same guard, same VJP
            from mine_tpu.kernels.warp_vjp import (
                bilinear_sample_diff_guarded, guard_ok)
            fn = functools.partial(bilinear_sample_diff_guarded,
                                   band=band,
                                   interpret=not on_tpu_backend(),
                                   mxu_dtype=mxu_dtype)
            _diff_guard_ok = functools.partial(guard_ok, band=band)
        else:
            from mine_tpu.kernels.warp_sep import (
                guard_ok, separable_sample_diff_guarded)
            fn = functools.partial(separable_sample_diff_guarded,
                                   band=band,
                                   interpret=not on_tpu_backend(),
                                   mxu_dtype=mxu_dtype,
                                   sep_tol=sep_tol)
            _diff_guard_ok = functools.partial(guard_ok, band=band,
                                               sep_tol=sep_tol)
        xs = jax.lax.stop_gradient(x)
        ys = jax.lax.stop_gradient(y)
        if mesh is not None and mesh.size > 1:
            if Bp % mesh.size == 0:
                # split the flat B' (=B*S, B-major) axis over data*plane:
                # lines up with the decoder's shard_bs layout, so the volume
                # is already local — the per-device kernel sees only its
                # planes (and the band-domain cond decides per shard)
                from jax.sharding import PartitionSpec as P

                from mine_tpu.parallel.mesh import (DATA_AXIS, PLANE_AXIS,
                                                    shard_map)
                bs_axes = (DATA_AXIS, PLANE_AXIS)

                def sharded(kernel_fn, s, cx, cy):
                    # the guard runs on the LOCAL shard's coords — exactly
                    # the cond each device's kernel takes — and pmean over
                    # both mesh axes yields the FRACTION of shards on the
                    # fast path (the old global-coords flag collapsed any
                    # single out-of-band shard to fallback=1.0 for the whole
                    # step, VERDICT r5: per-shard accounting)
                    ok = _diff_guard_ok(s.shape, cy).astype(jnp.float32)
                    ok = jax.lax.pmean(jax.lax.pmean(ok, DATA_AXIS),
                                       PLANE_AXIS)
                    return kernel_fn(s, cx, cy), ok

                sharded = shard_map(
                    functools.partial(sharded, fn), mesh=mesh,
                    in_specs=(P(bs_axes), P(bs_axes), P(bs_axes)),
                    out_specs=(P(bs_axes), P()))
                tgt, in_domain = sharded(src_BCHW, xs, ys)
                if with_domain_flag:
                    return tgt, valid, in_domain
                return tgt, valid
            # a bare pallas_call inside a GSPMD-partitioned program has
            # no partitioning spec — fall back to the autodiffed gather
            # for non-divisible batches (e.g. remainder eval examples);
            # keep the reduced-precision storage knob on this path too
            fn = functools.partial(bilinear_sample,
                                   gather_dtype=mxu_dtype)
            in_domain = jnp.zeros((), jnp.float32)
        else:
            in_domain = _diff_guard_ok(src_BCHW.shape,
                                       ys).astype(jnp.float32)
        tgt = fn(src_BCHW, xs, ys)
    else:
        # training.warp_dtype reaches the gather too: bf16 storage halves
        # the volume's HBM traffic, lerp stays f32 (f32 is a no-op knob)
        tgt = bilinear_sample(src_BCHW, x, y, gather_dtype=mxu_dtype)
    if with_domain_flag:
        return tgt, valid, in_domain
    return tgt, valid
