"""Differentiable MPI volume rendering.

Replaces the reference's operations/mpi_rendering.py with pure jnp functions.
Array convention: plane volumes are [B, S, C, H, W] (S = number of MPI planes,
nearest first), matching the reference's documented shapes; W is the
minor-most axis so elementwise work vectorizes over full TPU lanes.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from mine_tpu import geometry
from mine_tpu.ops import warp


def alpha_composition(alpha_BK1HW: jnp.ndarray,
                      value_BKCHW: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Classic MPI over-compositing: w_k = a_k * prod_{j<k}(1 - a_j).

    k=0 is the nearest plane. Reference: mpi_rendering.alpha_composition
    (mpi_rendering.py:23-39).

    Returns: (composed [B,C,H,W], weights [B,K,1,H,W])
    """
    preserve = jnp.cumprod(1.0 - alpha_BK1HW, axis=1)
    preserve = jnp.concatenate(
        [jnp.ones_like(preserve[:, :1]), preserve[:, :-1]], axis=1)
    weights = alpha_BK1HW * preserve
    composed = jnp.sum(value_BKCHW * weights, axis=1)
    return composed, weights


def finalize_depth(depth_acc: jnp.ndarray,
                   weights_sum: jnp.ndarray,
                   is_bg_depth_inf: bool) -> jnp.ndarray:
    """Depth finalization shared by every composite backend: weight-normalize,
    or add a far background (+1000*(1-w_sum)) when `is_bg_depth_inf` (DTU
    mode). Reference: mpi_rendering.weighted_sum_mpi (mpi_rendering.py:74-77).
    """
    if is_bg_depth_inf:
        return depth_acc + (1.0 - weights_sum) * 1000.0
    return depth_acc / (weights_sum + 1e-5)


def weighted_sum_mpi(rgb_BS3HW: jnp.ndarray,
                     xyz_BS3HW: jnp.ndarray,
                     weights: jnp.ndarray,
                     is_bg_depth_inf: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Composite rgb and depth from per-plane weights.

    Reference: mpi_rendering.weighted_sum_mpi (mpi_rendering.py:70-82).
    """
    weights_sum = jnp.sum(weights, axis=1)  # [B,1,H,W]
    rgb_out = jnp.sum(weights * rgb_BS3HW, axis=1)  # [B,3,H,W]
    depth_acc = jnp.sum(weights * xyz_BS3HW[:, :, 2:3], axis=1)
    return rgb_out, finalize_depth(depth_acc, weights_sum, is_bg_depth_inf)


def plane_volume_rendering(rgb_BS3HW: jnp.ndarray,
                           sigma_BS1HW: jnp.ndarray,
                           xyz_BS3HW: jnp.ndarray,
                           is_bg_depth_inf: bool):
    """Volume rendering over MPI planes with density sigma.

    transparency_s = exp(-sigma_s * dist_s) where dist_s is the distance
    between consecutive plane points along the ray (last plane: 1e3);
    accumulated transparency is the exclusive cumulative product (with the
    reference's +1e-6 stabilizer, mpi_rendering.py:59); weights = T_acc*alpha.
    Reference: mpi_rendering.plane_volume_rendering (mpi_rendering.py:42-67).

    Returns: (rgb [B,3,H,W], depth [B,1,H,W],
              transparency_acc [B,S,1,H,W], weights [B,S,1,H,W])
    """
    xyz_diff = xyz_BS3HW[:, 1:] - xyz_BS3HW[:, :-1]  # [B,S-1,3,H,W]
    dist = jnp.linalg.norm(xyz_diff, axis=2, keepdims=True)  # [B,S-1,1,H,W]
    dist = jnp.concatenate(
        [dist, jnp.full_like(dist[:, :1], 1e3)], axis=1)  # [B,S,1,H,W]

    transparency = jnp.exp(-sigma_BS1HW * dist)
    alpha = 1.0 - transparency

    transparency_acc = jnp.cumprod(transparency + 1e-6, axis=1)
    transparency_acc = jnp.concatenate(
        [jnp.ones_like(transparency_acc[:, :1]), transparency_acc[:, :-1]], axis=1)

    weights = transparency_acc * alpha
    rgb_out, depth_out = weighted_sum_mpi(rgb_BS3HW, xyz_BS3HW, weights,
                                          is_bg_depth_inf)
    return rgb_out, depth_out, transparency_acc, weights


def render(rgb_BS3HW: jnp.ndarray,
           sigma_BS1HW: jnp.ndarray,
           xyz_BS3HW: jnp.ndarray,
           use_alpha: bool = False,
           is_bg_depth_inf: bool = False):
    """Dispatch sigma-density vs alpha compositing modes.

    Reference: mpi_rendering.render (mpi_rendering.py:7-20).

    Returns: (rgb [B,3,H,W], depth [B,1,H,W], blend_weights, weights
              [B,S,1,H,W]). blend_weights is transparency_acc [B,S,1,H,W] in
              sigma mode but zeros_like(rgb) [B,S,3,H,W] in alpha mode — the
              mode-dependent shape mirrors the reference (mpi_rendering.py:19).
    """
    if not use_alpha:
        return plane_volume_rendering(rgb_BS3HW, sigma_BS1HW, xyz_BS3HW,
                                      is_bg_depth_inf)
    imgs_syn, weights = alpha_composition(sigma_BS1HW, rgb_BS3HW)
    depth_syn, _ = alpha_composition(sigma_BS1HW, xyz_BS3HW[:, :, 2:3])
    blend_weights = jnp.zeros_like(rgb_BS3HW)
    return imgs_syn, depth_syn, blend_weights, weights


_warned_fallbacks = set()


def _warn_backend_fallback(backend: str, why: str) -> None:
    """One-time trace-time notice when a configured composite backend is
    silently overridden (runs during tracing, so it fires once per compile,
    not per step)."""
    key = (backend, why)
    if key not in _warned_fallbacks:
        _warned_fallbacks.add(key)
        import warnings
        warnings.warn(
            f"composite backend {backend!r} falling back to 'xla': {why}")


def _render_fused(mpi_rgb_src, mpi_sigma_src, planes_q, planes_scales,
                  mpi_depth_src, xyz_tgt_BS3HW, G_tgt_src, K_src_inv, K_tgt,
                  is_bg_depth_inf, warp_band, mesh) -> "TgtRender":
    """warp_impl="pallas_fused": the warp -> dequant -> composite -> blend
    megakernel (kernels/render_fused.py). Never materializes the 7-channel
    float volume — the planes enter the kernel in CACHE form (planes_q) or
    as the predictor's float rgb+sigma, and only the composited rgb/depth
    come back. Guarded the house way: out-of-band poses take the XLA
    dequant+gather+composite inside the kernel's lax.cond, reported through
    warp_in_domain like every guarded backend."""
    from mine_tpu.kernels import on_tpu_backend
    from mine_tpu.kernels import render_fused as rf

    if planes_q is not None:
        vol4, scales = planes_q, planes_scales
    else:
        # training path: the predictor's float planes, no dequant step
        vol4 = jnp.concatenate([mpi_rgb_src, mpi_sigma_src], axis=2)
        scales = None
    B, S, _, H, W = vol4.shape

    grid = geometry.cached_pixel_grid(H, W)

    def expand(x):
        return jnp.repeat(x, S, axis=0)

    x, y, valid = warp.warp_coords(
        mpi_depth_src.reshape(B * S), expand(G_tgt_src), expand(K_src_inv),
        expand(K_tgt), grid, (H, W))
    xs = jax.lax.stop_gradient(x).reshape(B, S, H, W)
    ys = jax.lax.stop_gradient(y).reshape(B, S, H, W)
    xyz = xyz_tgt_BS3HW.astype(jnp.float32)

    rpb = next(r for r in (8, 4, 2, 1) if H % r == 0)
    interp = not on_tpu_backend()

    def call(v, sc, xz, cx, cy):
        return rf.fused_plane_render_guarded(
            v, sc, xz, cx, cy, band=warp_band, rows_per_block=rpb,
            is_bg_depth_inf=is_bg_depth_inf, interpret=interp)

    if mesh is not None and mesh.size > 1:
        # GSPMD meshes: batch over the mesh's leading axis — "data" on the
        # training mesh, "batch" on the serve mesh — with the plane axis
        # local to each device (the transparency chain reduces over S).
        batch_axis = mesh.axis_names[0]
        if B % mesh.shape[batch_axis] == 0:
            from jax.sharding import PartitionSpec as P

            from mine_tpu.parallel.mesh import shard_map

            def sharded(v, sc, xz, cx, cy):
                rgb, depth, ok = call(v, sc, xz, cx, cy)
                # per-shard cond, pmean'd to the fraction on the fast path
                okf = ok.astype(jnp.float32)
                for ax in mesh.axis_names:
                    okf = jax.lax.pmean(okf, ax)
                return rgb, depth, okf

            spec = P(batch_axis)
            fn = shard_map(sharded, mesh=mesh,
                           in_specs=(spec, spec, spec, spec, spec),
                           out_specs=(spec, spec, P()))
            rgb_syn, depth_syn, in_domain = fn(vol4, scales, xyz, xs, ys)
        else:
            _warn_backend_fallback(
                "pallas_fused", "batch not divisible by the mesh batch axis")
            rgb_syn, depth_syn = rf.xla_reference_render(
                vol4, scales, xyz, xs, ys, is_bg_depth_inf)
            in_domain = jnp.zeros((), jnp.float32)
    else:
        rgb_syn, depth_syn, ok = call(vol4, scales, xyz, xs, ys)
        in_domain = ok.astype(jnp.float32)

    mask = jnp.sum(valid.reshape(B, S, H, W).astype(jnp.float32),
                   axis=1, keepdims=True)
    return TgtRender(rgb=rgb_syn, depth=depth_syn, mask=mask,
                     warp_in_domain=in_domain)


class TgtRender(NamedTuple):
    rgb: jnp.ndarray    # [B,3,H,W]
    depth: jnp.ndarray  # [B,1,H,W]
    mask: jnp.ndarray   # [B,1,H,W] — number of planes whose warp was in-bounds
    # scalar f32 guard diagnostic: 1.0 = guarded warp backend took its fast
    # path this call, 0.0 = runtime gather fallback, NaN = backend has no
    # guard (ops/warp.homography_warp with_domain_flag)
    warp_in_domain: jnp.ndarray = None


def render_tgt_rgb_depth(mpi_rgb_src: jnp.ndarray,
                         mpi_sigma_src: jnp.ndarray,
                         mpi_disparity_src: jnp.ndarray,
                         xyz_tgt_BS3HW: jnp.ndarray,
                         G_tgt_src: jnp.ndarray,
                         K_src_inv: jnp.ndarray,
                         K_tgt: jnp.ndarray,
                         use_alpha: bool = False,
                         is_bg_depth_inf: bool = False,
                         backend: str = "xla",
                         warp_impl: str = "xla",
                         warp_band: int = 16,
                         warp_dtype: str = "float32",
                         warp_sep_tol: float = 0.5,
                         mesh=None,
                         planes_q: jnp.ndarray = None,
                         planes_scales: jnp.ndarray = None) -> TgtRender:
    """Render the MPI into a target camera.

    Concatenates [rgb, sigma, xyz_tgt] into a 7-channel plane volume, warps all
    S planes with per-plane homographies (flattened to a B*S batch), zeroes
    density where the warped point is behind the target camera (z<0), and
    composites. Reference: mpi_rendering.render_tgt_rgb_depth
    (mpi_rendering.py:181-241).

    Args:
      mpi_rgb_src: [B,S,3,H,W]; mpi_sigma_src: [B,S,1,H,W]
      mpi_disparity_src: [B,S]; xyz_tgt_BS3HW: [B,S,3,H,W]
      G_tgt_src: [B,4,4]; K_src_inv, K_tgt: [B,3,3]
      mesh: ("data","plane") Mesh — on multi-device meshes the Pallas
        backends run under shard_map (warp: B*S split over data*plane;
        composite: batch over "data" with the plane axis gathered locally,
        since the transparency chain reduces over S). warp_impl=
        "pallas_fused" accepts the serve ("batch","model") mesh too —
        it shards over whichever axis is first.
      planes_q: warp_impl="pallas_fused" only — the [B,S,4,H,W] rgb+sigma
        planes in CACHE form (float32/bfloat16/int8). The serve engine
        passes its quantized cache slice here INSTEAD of pre-dequantizing;
        the megakernel widens/dequantizes in registers. When given,
        mpi_rgb_src/mpi_sigma_src are shape/dtype carriers only.
      planes_scales: [B,S,4,1,1] f32 int8 dequant scales (None for
        float32/bfloat16 caches — the cast is exact, no multiply runs).

    With warp_impl="pallas_fused" (and sigma mode) the `backend` composite
    arg is bypassed entirely: warp, dequant, z-mask, composite and blend
    are one Pallas program (kernels/render_fused.py) and the 7-channel
    float volume is never materialized.
    """
    B, S, _, H, W = mpi_rgb_src.shape
    mpi_depth_src = 1.0 / mpi_disparity_src  # [B,S]

    if warp_impl == "pallas_fused" and use_alpha:
        # the megakernel implements the sigma-density composite only
        _warn_backend_fallback("pallas_fused", "mpi.use_alpha uses the XLA "
                               "alpha-compositing path")
        if planes_q is not None:
            xq = planes_q.astype(jnp.float32)
            if planes_scales is not None:
                xq = xq * planes_scales
            mpi_rgb_src, mpi_sigma_src = xq[:, :, 0:3], xq[:, :, 3:4]
            planes_q = planes_scales = None
        warp_impl = "xla"

    if warp_impl == "pallas_fused":
        return _render_fused(mpi_rgb_src, mpi_sigma_src, planes_q,
                             planes_scales, mpi_depth_src, xyz_tgt_BS3HW,
                             G_tgt_src, K_src_inv, K_tgt, is_bg_depth_inf,
                             warp_band, mesh)

    volume = jnp.concatenate([mpi_rgb_src, mpi_sigma_src, xyz_tgt_BS3HW], axis=2)
    volume_bs = volume.reshape(B * S, 7, H, W)

    def expand(x):
        return jnp.repeat(x, S, axis=0)  # [B,...] -> [B*S,...] (plane-major per b)

    grid = geometry.cached_pixel_grid(H, W)
    warped, valid, warp_in_domain = warp.homography_warp(
        volume_bs,
        mpi_depth_src.reshape(B * S),
        expand(G_tgt_src),
        expand(K_src_inv),
        expand(K_tgt),
        grid,
        impl=warp_impl,
        band=warp_band,
        mesh=mesh,
        mxu_dtype=jnp.bfloat16 if warp_dtype == "bfloat16" else jnp.float32,
        with_domain_flag=True,
        sep_tol=warp_sep_tol,
    )

    warped = warped.reshape(B, S, 7, H, W)
    tgt_rgb = warped[:, :, 0:3]
    tgt_sigma = warped[:, :, 3:4]
    tgt_xyz = warped[:, :, 4:7]

    if mesh is not None and mesh.size > 1 \
            and B % mesh.shape.get("data", 1) != 0 and backend != "xla":
        # non-divisible batch (e.g. a remainder eval example): a bare
        # pallas_call inside a GSPMD program carries no partitioning spec,
        # so use the XLA composite instead of shard_map
        _warn_backend_fallback(backend, "batch not divisible by data axis")
        backend = "xla"

    if backend == "plane_scan":
        # distributed two-level transparency scan over the plane axis
        # (ops/plane_scan.py) — the volume stays plane-sharded end to end.
        # Requires a multi-device plane-divisible mesh (see the config
        # comment in params_default.yaml); otherwise the XLA composite.
        from mine_tpu.parallel.mesh import PLANE_AXIS
        if not (mesh is not None and mesh.size > 1 and not use_alpha
                and S % mesh.shape.get(PLANE_AXIS, 1) == 0):
            _warn_backend_fallback(
                backend, "needs a multi-device mesh with S divisible by the "
                "plane axis (and sigma mode)")
            backend = "xla"

    if backend in ("pallas", "pallas_diff") and use_alpha:
        # the fused kernels implement the sigma-density composite only
        _warn_backend_fallback(backend, "mpi.use_alpha uses the XLA "
                               "alpha-compositing path")
        backend = "xla"

    # Arbitrary heights are fine on the Pallas backends: the kernel
    # wrappers pad rows to a Mosaic-legal multiple of 8 internally
    # (kernels/composite.py pad_rows) and slice the outputs.

    if backend == "plane_scan":
        from mine_tpu.ops.plane_scan import plane_sharded_volume_render
        rgb_syn, depth_syn = plane_sharded_volume_render(
            tgt_rgb, tgt_sigma, tgt_xyz, mesh,
            z_mask=True, is_bg_depth_inf=is_bg_depth_inf)
    elif backend in ("pallas", "pallas_diff"):
        # fused composite: z-masking + volume rendering in one HBM pass
        # (mine_tpu.kernels.composite). "pallas" is forward-only;
        # "pallas_diff" adds the custom-VJP backward kernel for training.
        from mine_tpu.kernels import on_tpu_backend
        interp = not on_tpu_backend()
        if backend == "pallas_diff":
            from mine_tpu.kernels.composite_vjp import fused_volume_render_diff
            fn = lambda r, s, x: fused_volume_render_diff(  # noqa: E731
                r, s, x, True, is_bg_depth_inf, interp)
        else:
            from mine_tpu.kernels.composite import fused_volume_render
            fn = lambda r, s, x: fused_volume_render(  # noqa: E731
                r, s, x, z_mask=True,
                is_bg_depth_inf=is_bg_depth_inf, interpret=interp)
        if mesh is not None and mesh.size > 1:
            # batch over "data"; the plane axis is gathered to each device
            # (the transparency cumprod chains over S — a distributed scan
            # over "plane" is possible but the all-gather of the 7ch volume
            # matches what GSPMD inserts for the XLA composite anyway)
            from jax.sharding import PartitionSpec as P

            from mine_tpu.parallel.mesh import DATA_AXIS, shard_map
            fn = shard_map(fn, mesh=mesh,
                           in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
                           out_specs=(P(DATA_AXIS), P(DATA_AXIS)))
        rgb_syn, depth_syn = fn(tgt_rgb, tgt_sigma, tgt_xyz)
    else:
        tgt_z = tgt_xyz[:, :, 2:3]
        tgt_sigma = jnp.where(tgt_z >= 0.0, tgt_sigma, 0.0)
        rgb_syn, depth_syn, _, _ = render(tgt_rgb, tgt_sigma, tgt_xyz,
                                          use_alpha=use_alpha,
                                          is_bg_depth_inf=is_bg_depth_inf)
    mask = jnp.sum(valid.reshape(B, S, H, W).astype(jnp.float32),
                   axis=1, keepdims=True)  # [B,1,H,W]
    return TgtRender(rgb=rgb_syn, depth=depth_syn, mask=mask,
                     warp_in_domain=warp_in_domain)


def predict_mpi_coarse_to_fine(mpi_predictor,
                               key: jax.Array,
                               src_imgs: jnp.ndarray,
                               xyz_src_BS3HW_coarse: jnp.ndarray,
                               disparity_coarse_src: jnp.ndarray,
                               s_fine: int,
                               is_bg_depth_inf: bool,
                               fine_rows=None):
    """Optional coarse-to-fine plane placement.

    With s_fine > 0: run a stop-gradient coarse pass, convert per-plane mean
    compositing weights into a pdf over disparity, importance-sample s_fine
    extra disparities (inverse CDF), merge + sort descending, and run the full
    pass on the S_coarse+s_fine planes. Both passes have static shapes.
    Reference: mpi_rendering.predict_mpi_coarse_to_fine
    (mpi_rendering.py:244-271).

    Args:
      mpi_predictor: fn (src_imgs, disparity [B,S]) -> list of 4 per-scale
        MPI volumes [B,S,4,Hs,Ws]
      fine_rows: optional (full_batch, row) for a per-example caller
        standing in for rows [row:row+B] of a `full_batch`-sized batched
        call: the fine-plane uniforms are drawn with `key` at the FULL
        batch shape and this caller's rows sliced out, so the importance
        samples match the batched pass's for the same example (the
        encode-once eval path, train/step.py eval_encode_c2f).
    Returns: (mpi_all_src_list, disparity_all_src [B, S_coarse+s_fine])
    """
    from mine_tpu.ops import sampling  # local import to avoid cycle

    if s_fine <= 0:
        return mpi_predictor(src_imgs, disparity_coarse_src), disparity_coarse_src

    B, S_coarse = disparity_coarse_src.shape

    coarse_list = mpi_predictor(src_imgs, disparity_coarse_src)
    coarse = jax.lax.stop_gradient(coarse_list[0])
    rgb_c = coarse[:, :, 0:3]
    sigma_c = coarse[:, :, 3:4]
    _, _, _, weights = plane_volume_rendering(
        rgb_c, sigma_c, jax.lax.stop_gradient(xyz_src_BS3HW_coarse),
        is_bg_depth_inf)
    weights = jnp.mean(weights, axis=(2, 3, 4))[:, None, None, :]  # [B,1,1,S]

    if fine_rows is None:
        disp_fine = sampling.sample_pdf(
            key, disparity_coarse_src[:, None, None, :], weights, s_fine)
    else:
        full_batch, row = fine_rows
        u = jax.random.uniform(key, (full_batch, 1, 1, s_fine),
                               dtype=weights.dtype)
        u = jax.lax.dynamic_slice_in_dim(u, row, B, axis=0)
        disp_fine = sampling.sample_pdf_from_u(
            u, disparity_coarse_src[:, None, None, :], weights)
    disp_fine = disp_fine[:, 0, 0, :]  # [B, s_fine]

    disparity_all = jnp.concatenate([disparity_coarse_src, disp_fine], axis=1)
    disparity_all = -jnp.sort(-disparity_all, axis=1)  # descending
    disparity_all = jax.lax.stop_gradient(disparity_all)

    return mpi_predictor(src_imgs, disparity_all), disparity_all
