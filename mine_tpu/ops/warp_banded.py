"""Banded one-hot-matmul bilinear warp in pure XLA.

Third implementation of the homography-warp contract (reference hot op:
grid_sample over the B*S x 7 x H x W plane volume, homography_sampler.py:138
called from mpi_rendering.py:214), sitting between the autodiffed gather
(ops/warp.bilinear_sample — worst-case TPU memory pattern) and the Pallas
banded kernel pair (kernels/warp.py + warp_vjp.py — fastest, but needs a
first on-device compile through the flaky tunnel before it can be trusted):

  * same banded structure as the Pallas kernel: per block of RT target rows,
    slice a [C, BAND, W_s] source band (translation-dominated homographies
    keep each row-block's source span narrow), then express bilinear
    interpolation as a tent-weight contraction the MXU executes as a matmul
    ([C*BAND, W_s] @ [W_s, W_t] per row) plus a VPU reduction over the band;
  * expressed entirely with lax.scan + lax.dynamic_slice + einsum, so XLA
    differentiates it (dynamic_slice adjoint = padded accumulation — no
    custom VJP needed), it runs on any backend, and the compiler owns
    scheduling/fusion;
  * identical band-coverage semantics to kernels/warp.py: sampling rows are
    clamped into the band, so results match ops.warp.bilinear_sample exactly
    whenever each row-block's source span fits BAND-2 rows (band_span), and
    `banded_bilinear_sample_guarded` falls back to the gather per-call via
    lax.cond outside that domain.

Selected with `training.warp_backend: xla_banded` (the training path; the
video renderer picks between "xla" and the forward-only Pallas kernel by
host-known band checks, infer/video.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from mine_tpu.kernels.warp import band_start, fwd_domain_ok


@functools.partial(jax.jit, static_argnames=("band", "rows_per_block",
                                             "mxu_dtype"))
def banded_bilinear_sample(src: jnp.ndarray,
                           coords_x: jnp.ndarray,
                           coords_y: jnp.ndarray,
                           band: int = 16,
                           rows_per_block: int = 8,
                           mxu_dtype=jnp.float32) -> jnp.ndarray:
    """Banded-matmul equivalent of ops.warp.bilinear_sample (see module
    docstring for the domain requirement).

    Args:
      src: [B', C, H_s, W_s]; coords_x/coords_y: [B', H_t, W_t]
      mxu_dtype: contraction dtype (bfloat16 doubles MXU rate; tent weights
        round at ~2^-8 relative, accumulation stays f32)
    Returns: [B', C, H_t, W_t] float32
    """
    Bp, C, H_s, W_s = src.shape
    _, H_t, W_t = coords_x.shape
    RT = rows_per_block
    assert H_t % RT == 0, (H_t, RT)
    NB = H_t // RT
    band = min(band, H_s)

    src = src.astype(jnp.float32)
    xc = jnp.clip(coords_x, 0.0, W_s - 1.0).astype(jnp.float32)
    yc = jnp.clip(coords_y, 0.0, H_s - 1.0).astype(jnp.float32)

    y0 = band_start(yc, H_s, band, RT)  # [B', NB] — shared placement rule

    xs = jax.lax.broadcasted_iota(jnp.float32, (W_s, W_t), 0)   # src x pos
    ys = jax.lax.broadcasted_iota(jnp.float32, (band, W_t), 0)  # band y pos

    xc_blocks = xc.reshape(Bp, NB, RT, W_t)
    yc_blocks = yc.reshape(Bp, NB, RT, W_t)

    def slice_band(img_chw, y):
        return jax.lax.dynamic_slice(img_chw, (0, y, 0), (C, band, W_s))

    def block_step(_, nb):
        bands = jax.vmap(slice_band)(src, y0[:, nb])      # [B', C, band, W_s]
        bands2 = bands.reshape(Bp, C * band, W_s).astype(mxu_dtype)

        def row_step(__, r):
            sx = xc_blocks[:, nb, r]                             # [B', W_t]
            sy = yc_blocks[:, nb, r] - y0[:, nb, None].astype(jnp.float32)
            sy = jnp.clip(sy, 0.0, band - 1.0)  # band coverage clamp
            # [B', W_s, W_t] one-hot tent weights -> MXU contraction
            wx = jnp.maximum(1.0 - jnp.abs(xs[None] - sx[:, None, :]), 0.0)
            t = jnp.einsum("bks,bst->bkt", bands2, wx.astype(mxu_dtype),
                           preferred_element_type=jnp.float32)
            t = t.reshape(Bp, C, band, W_t)
            wy = jnp.maximum(1.0 - jnp.abs(ys[None] - sy[:, None, :]), 0.0)
            return None, jnp.sum(t * wy[:, None], axis=2)  # [B', C, W_t]

        _, rows = jax.lax.scan(row_step, None, jnp.arange(RT))
        return None, rows  # [RT, B', C, W_t]

    _, blocks = jax.lax.scan(block_step, None, jnp.arange(NB))
    # [NB, RT, B', C, W_t] -> [B', C, NB*RT, W_t]
    return blocks.transpose(2, 3, 0, 1, 4).reshape(Bp, C, H_t, W_t)


def guard_ok(src_shape, coords_y, band: int = 16,
             rows_per_block: int = 8) -> jnp.ndarray:
    """THE fallback decision of banded_bilinear_sample_guarded, as a scalar
    bool — exposed so diagnostics (ops/warp.homography_warp's
    with_domain_flag) consume the same logic instead of mirroring it.

    aligned=False: this path keeps unaligned band starts, so it need not
    budget the Pallas sublane slack — poses within SUBLANE_ALIGN-1 rows of
    the band limit stay on the fast path here (advisor r4)."""
    H_s = src_shape[2]
    H_t = coords_y.shape[1]
    if H_t % rows_per_block != 0:
        return jnp.zeros((), jnp.bool_)
    yc = jnp.clip(coords_y, 0.0, H_s - 1.0)
    return fwd_domain_ok(yc, H_s, band, rows_per_block, aligned=False)


def banded_bilinear_sample_guarded(src, coords_x, coords_y,
                                   band: int = 16,
                                   rows_per_block: int = 8,
                                   mxu_dtype=jnp.float32):
    """Banded XLA warp with the runtime gather fallback.

    Same guard pattern as kernels.warp_vjp.bilinear_sample_diff_guarded:
    lax.cond on the pose-derived band-domain check; both branches are
    XLA-differentiable, so this drops into the training step directly.
    """
    from mine_tpu.ops.warp import bilinear_sample

    # the gather fallback honors the same value dtype (bf16 storage keeps
    # the HBM-traffic benefit when the banded path bails); both paths
    # return f32, so the cond branches agree (f32 is a no-op knob)
    gather_dtype = mxu_dtype

    src = src.astype(jnp.float32)
    H_t = coords_x.shape[1]
    if H_t % rows_per_block != 0:
        return bilinear_sample(src, coords_x, coords_y,
                               gather_dtype=gather_dtype)

    ok = guard_ok(src.shape, coords_y, band, rows_per_block)
    return jax.lax.cond(
        ok,
        lambda s, x, y: banded_bilinear_sample(
            s, x, y, band=band, rows_per_block=rows_per_block,
            mxu_dtype=mxu_dtype),
        lambda s, x, y: bilinear_sample(s, x, y, gather_dtype=gather_dtype),
        src, coords_x, coords_y)
