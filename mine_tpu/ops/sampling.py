"""Disparity sampling + sparse-point gathers, with explicit PRNG keys.

Replaces operations/rendering_utils.py of the reference. The reference draws
from the unseeded global torch RNG (rendering_utils.py:65,86,115); we thread
`jax.random` keys, making training reproducible by construction without
changing the sampling distributions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def uniformly_sample_disparity_from_linspace_bins(key: jax.Array,
                                                  batch_size: int,
                                                  num_bins: int,
                                                  start: float,
                                                  end: float) -> jnp.ndarray:
    """Stratified disparity samples: one uniform draw inside each of S equal
    bins spanning [start, end], start > end (disparity large -> small, i.e.
    depth near -> far). Reference: rendering_utils.py:70-88.

    Returns: [B, S], strictly descending in expectation (bin order).
    """
    assert start > end
    bin_edges = jnp.linspace(start, end, num_bins + 1, dtype=jnp.float32)
    interval = bin_edges[1] - bin_edges[0]  # negative scalar
    u = jax.random.uniform(key, (batch_size, num_bins), dtype=jnp.float32)
    return bin_edges[None, :-1] + interval * u


def uniformly_sample_disparity_from_bins(key: jax.Array,
                                         batch_size: int,
                                         disparity_np) -> jnp.ndarray:
    """Stratified samples from explicit (possibly non-uniform) bin edges,
    descending. Reference: rendering_utils.py:47-67.

    Args: disparity_np: [S+1] descending bin edges.
    Returns: [B, S]
    """
    bin_edges = jnp.asarray(disparity_np, dtype=jnp.float32)
    starts = bin_edges[:-1]
    intervals = bin_edges[1:] - bin_edges[:-1]
    S = starts.shape[0]
    u = jax.random.uniform(key, (batch_size, S), dtype=jnp.float32)
    return starts[None, :] + intervals[None, :] * u


def fixed_disparity_linspace(batch_size: int, num_bins: int,
                             start: float, end: float) -> jnp.ndarray:
    """Deterministic plane disparities (mpi.fix_disparity / inference).

    Reference: synthesis_task.py:41-44.
    """
    d = jnp.linspace(start, end, num_bins, dtype=jnp.float32)
    return jnp.broadcast_to(d[None, :], (batch_size, num_bins))


def sample_pdf(key: jax.Array,
               values: jnp.ndarray,
               weights: jnp.ndarray,
               n_samples: int) -> jnp.ndarray:
    """NeRF-style inverse-CDF importance sampling.

    Draw `n_samples` from the distribution approximated by point masses
    `weights` at `values` (converted to bin edges at midpoints). Degenerate
    zero-width CDF intervals (from edge clamping) fall back to the bin middle.
    Reference: rendering_utils.sample_pdf (rendering_utils.py:91-140).

    Args:
      values: [B, 1, N, S]
      weights: [B, 1, N, S]
    Returns: samples [B, 1, N, n_samples]
    """
    B, _, N, S = weights.shape
    u = jax.random.uniform(key, (B, 1, N, n_samples), dtype=weights.dtype)
    return sample_pdf_from_u(u, values, weights)


def sample_pdf_from_u(u: jnp.ndarray,
                      values: jnp.ndarray,
                      weights: jnp.ndarray) -> jnp.ndarray:
    """Inverse-CDF transform of PRE-DRAWN uniforms `u` [B, 1, N, n].

    The deterministic half of sample_pdf, split out so a caller can draw
    one batch-level u and feed per-example ROWS of it: the encode-once
    eval path (train/step.py eval_encode) replays exactly the fine-plane
    draws the fused batched eval step makes for the same example.
    """
    B, _, N, S = weights.shape
    n_samples = u.shape[-1]

    mid = (values[..., 1:] + values[..., :-1]) * 0.5
    bin_edges = jnp.concatenate([values[..., :1], mid, values[..., -1:]], axis=-1)  # [B,1,N,S+1]

    pdf = weights / (jnp.sum(weights, axis=-1, keepdims=True) + 1e-5)
    cdf = jnp.cumsum(pdf, axis=-1)
    cdf = jnp.concatenate([jnp.zeros_like(cdf[..., :1]), cdf], axis=-1)  # [B,1,N,S+1]

    # searchsorted over the last axis, batched
    cdf_flat = cdf.reshape(B * N, S + 1)
    u_flat = u.reshape(B * N, n_samples)
    idx = jax.vmap(lambda c, uu: jnp.searchsorted(c, uu, side="right"))(cdf_flat, u_flat)
    idx = idx.reshape(B, 1, N, n_samples)
    lower = jnp.clip(idx - 1, 0, S)
    upper = jnp.clip(idx, None, S)

    cdf_lo = jnp.take_along_axis(cdf, lower, axis=-1)
    cdf_hi = jnp.take_along_axis(cdf, upper, axis=-1)
    bin_lo = jnp.take_along_axis(bin_edges, lower, axis=-1)
    bin_hi = jnp.take_along_axis(bin_edges, upper, axis=-1)

    cdf_interval = cdf_hi - cdf_lo
    t = (u - cdf_lo) / jnp.clip(cdf_interval, 1e-5, None)
    t = jnp.where(cdf_interval <= 1e-4, 0.5, t)
    return bin_lo + t * (bin_hi - bin_lo)


def gather_pixel_by_pxpy(img: jnp.ndarray, pxpy: jnp.ndarray) -> jnp.ndarray:
    """Read image values at (rounded, clamped) sparse pixel locations.

    Gradients flow through the gathered values, not the indices — same as the
    reference, which computes indices under no_grad
    (rendering_utils.py:27-44).

    Args:
      img: [B, C, H, W]
      pxpy: [B, 2, N] float pixel coords (x, y)
    Returns: [B, C, N]
    """
    B, C, H, W = img.shape
    px = jnp.clip(jnp.round(pxpy[:, 0, :]).astype(jnp.int32), 0, W - 1)  # [B,N]
    py = jnp.clip(jnp.round(pxpy[:, 1, :]).astype(jnp.int32), 0, H - 1)
    flat_idx = py * W + px  # [B, N]
    img_flat = img.reshape(B, C, H * W)
    return jnp.take_along_axis(img_flat, flat_idx[:, None, :], axis=2)
