"""Program cost/memory model: compiled-executable FLOP/byte/HBM accounting.

Where analysis/flops.py counts dot_generals in the *jaxpr* (a structural
budget), this module prices the *compiled executable*: it AOT-compiles each
registry program via ``jit_fn.lower(*args).compile()`` and reads

  * ``cost_analysis()``   — flops and bytes-accessed of the optimized HLO
    (post-fusion, so bytes here are the real traffic estimate, unlike the
    unfused upper bound the old tools/flops_report.py printed);
  * ``memory_analysis()`` — argument / output / temp / alias buffer sizes,
    from which ``peak_hbm_bytes = argument + output + temp - alias`` (alias
    bytes are donated-input space the output reuses, counted once).

These numbers are deterministic per (program, jax version, platform), so
the ``cost_budget`` audit pass pins them exactly in the ``"cost"`` section
of tools/analysis_baseline.json with the same update discipline as the dot
budgets: a change in EITHER direction fails until `tools/audit.py
--update-baseline` re-records them in the same commit as the intentional
program change. This is the HBM-fit oracle the ROADMAP's MPMD-pipeline and
AOT-cold-start items need: "does this program's working set fit one chip"
becomes a table lookup instead of an OOM on silicon.

The roofline estimate prices a program against a chip model given
``MINE_TPU_BENCH_PEAK_TFLOPS`` (bench.py's knob, v5e bf16 default) and
``MINE_TPU_BENCH_HBM_GBPS``: expected step time is the max of the compute
and memory legs, and the binding leg names the bottleneck. Env-dependent,
so it is *reported* (pass details, flops_report) but never baseline-gated.

tools/flops_report.py is now a thin CLI shim over `attribution_report`
below (same precedent as tools/dtype_audit.py -> analysis/dtype.py).
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Optional

# keys pinned per program in analysis_baseline.json's "cost" section;
# append-only (removing or renaming one invalidates every checked-in entry)
COST_KEYS = ("flops", "bytes_accessed", "argument_bytes", "output_bytes",
             "temp_bytes", "alias_bytes", "peak_hbm_bytes")

# chip model defaults: v5e bf16 peak (bench.py's CHIP_PEAK_TFLOPS default)
# and v5e HBM bandwidth. Both overridable via the bench env knobs.
DEFAULT_PEAK_TFLOPS = 197.0
DEFAULT_HBM_GBPS = 819.0


def chip_model() -> Dict[str, float]:
    """The (peak TFLOP/s, HBM GB/s) pair the roofline prices against."""
    return {
        "peak_tflops": float(os.environ.get("MINE_TPU_BENCH_PEAK_TFLOPS",
                                            DEFAULT_PEAK_TFLOPS)),
        "hbm_gbps": float(os.environ.get("MINE_TPU_BENCH_HBM_GBPS",
                                         DEFAULT_HBM_GBPS)),
    }


def _unwrap_cost_analysis(compiled) -> Dict:
    """jax 0.4.x returns one properties-dict per partition as a list;
    newer versions return the dict directly. Normalize to the dict."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def compiled_cost(jit_fn, args) -> Dict[str, int]:
    """AOT-compile ``jit_fn(*args)`` and return the pinned cost dict
    (COST_KEYS). Works on CPU: XLA's cost and buffer-assignment analyses
    run on the optimized HLO regardless of backend."""
    compiled = jit_fn.lower(*args).compile()
    ca = _unwrap_cost_analysis(compiled)
    ma = compiled.memory_analysis()
    arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(ma, "output_size_in_bytes", 0) or 0)
    temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
    alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
    return {
        "flops": int(ca.get("flops", 0) or 0),
        "bytes_accessed": int(ca.get("bytes accessed", 0) or 0),
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "peak_hbm_bytes": arg + out + temp - alias,
    }


def measure_program(program) -> Dict[str, int]:
    """`compiled_cost` over a registry Program's canonical arguments."""
    return compiled_cost(program.jit_fn, program.args_fn())


def roofline(cost: Dict[str, int],
             peak_tflops: Optional[float] = None,
             hbm_gbps: Optional[float] = None) -> Dict[str, object]:
    """Two-leg roofline: expected time is max(flops/peak, bytes/bandwidth),
    the binding leg is the bottleneck, and arithmetic intensity (flops per
    byte accessed) tells how far from the ridge the program sits."""
    chip = chip_model()
    peak = peak_tflops if peak_tflops is not None else chip["peak_tflops"]
    bw = hbm_gbps if hbm_gbps is not None else chip["hbm_gbps"]
    compute_ms = cost["flops"] / (peak * 1e12) * 1e3
    memory_ms = cost["bytes_accessed"] / (bw * 1e9) * 1e3
    expected_ms = max(compute_ms, memory_ms)
    return {
        "compute_ms": compute_ms,
        "memory_ms": memory_ms,
        "expected_ms": expected_ms,
        "bound": "compute" if compute_ms >= memory_ms else "memory",
        "intensity_flops_per_byte": (
            cost["flops"] / cost["bytes_accessed"]
            if cost["bytes_accessed"] else float("inf")),
        "peak_tflops": peak,
        "hbm_gbps": bw,
    }


# ------------------------------------------------- flops_report attribution

V5E_BF16_PEAK_TFLOPS = 197.0


def attribution_report(argv=None) -> None:
    """The original tools/flops_report.py body, relocated verbatim in
    behavior: static per-component cost attribution at the benchmark
    config, human table on stderr, JSON on stdout under --json. Uses the
    *lowered* (unfused) cost_analysis deliberately — its bytes column is
    the labeled upper bound the historical reports printed."""
    import json

    import jax
    jax.config.update("jax_platforms", "cpu")

    import bench
    from tools import microbench

    argv = sys.argv if argv is None else argv
    rows = {}

    def add(name, fn, *args):
        ca = jax.jit(fn).lower(*args).cost_analysis()
        rows[name] = {
            "tflops": round(ca.get("flops", float("nan")) / 1e12, 4),
            "gbytes_unfused_upper_bound": round(
                ca.get("bytes accessed", float("nan")) / 1e9, 2),
        }
        print("%-28s %8.4f TFLOP   %8.2f GB (unfused upper bound)"
              % (name, rows[name]["tflops"],
                 rows[name]["gbytes_unfused_upper_bound"]), file=sys.stderr)

    # full train step at the benchmark's headline variant (shared builder:
    # this attribution is of exactly the benchmarked program)
    trainer, state, batch = bench.build_variant_program("xla_b4")
    add("train_step_b4", trainer._train_step_impl, state, batch)

    # isolated components at the microbench shapes (B=2, S=32, 256x384)
    for case in ("encoder_fwd", "model_fwd", "warp_xla_fwd",
                 "warp_xla_fwdbwd", "comp_xla_fwd", "comp_xla_fwdbwd"):
        fn, args = microbench._case_fn(case)
        add(case + "_b2", fn, *args)

    step = rows["train_step_b4"]["tflops"]
    out = {
        "config": "LLFF 384x256 N=32 bf16 ResNet-50 (bench.py)",
        "components": rows,
        "peak_bound_images_per_sec": {
            "v5e_bf16_peak_tflops": V5E_BF16_PEAK_TFLOPS,
            "at_100pct_mxu": round(4 * V5E_BF16_PEAK_TFLOPS / step, 1),
            "at_40pct_mxu": round(0.4 * 4 * V5E_BF16_PEAK_TFLOPS / step, 1),
        },
    }
    # stdout JSON only under --json; the human-readable table already went
    # to stderr line by line via add()
    if "--json" in argv:
        print(json.dumps(out, indent=2))
    else:
        pb = out["peak_bound_images_per_sec"]
        print("peak-bound img/s: %.1f @100%% MXU, %.1f @40%% (v5e %.0f TFLOP/s)"
              % (pb["at_100pct_mxu"], pb["at_40pct_mxu"],
                 pb["v5e_bf16_peak_tflops"]), file=sys.stderr)
