"""Pipeline stage planner over the AOT cost model.

Consumes the per-stage cost rows the audit baseline pins for the four
staged train-step sub-programs (analysis/programs.py: pipe_encode,
pipe_decode, pipe_render, pipe_loss — COST_KEYS from costmodel.py, i.e.
XLA's own post-fusion flops/bytes/peak-HBM numbers) and proposes how to
cut the chain into `training.pipeline.stages` contiguous groups under a
declared per-chip HBM budget.

The arithmetic is deliberately transparent and EXACT where it can be:

  * a candidate stage's peak-HBM is the plain integer sum of its member
    programs' `peak_hbm_bytes` rows — a conservative bound (members of one
    stage run back-to-back inside one group of devices, so their peaks
    don't in general coincide, but params+boundary buffers do persist) and
    the quantity tests assert EXACTLY against the cost model;
  * a candidate stage's step-time estimate is the sum of its members'
    roofline expected_ms (costmodel.roofline — max of the compute and
    memory legs under the declared chip model);
  * feasibility = every stage's peak-HBM sum fits the budget; the planner
    picks the FEWEST stages with any feasible partition (the fused step is
    strictly better when it fits — no fill/drain bubble, no boundary
    transfers, both unmodeled costs), and among partitions at that count
    minimizes the BOTTLENECK stage time (pipeline throughput is set by the
    slowest stage).

The microbatch proposal is advisory scheduling math, not a memory model:
GPipe's bubble fraction is (stages-1)/(M+stages-1), so the planner
proposes the smallest M that keeps the bubble at or under 20% —
M = 4*(stages-1), floored at 1.

Consumers: tools/pipeline_plan.py (CLI) and the `pipeline_plan` audit pass
(analysis/passes.py), which gates that the baselined cost rows still admit
a feasible plan under the declared budget.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from mine_tpu.analysis import costmodel as _costmodel

# the staged sub-programs, in dataflow order (must match
# parallel/pipeline.py STAGE_NAMES and the analysis/programs.py registry)
PIPE_PROGRAMS = ("pipe_encode", "pipe_decode", "pipe_render", "pipe_loss")

MAX_BUBBLE_FRAC = 0.20


class PlanInfeasibleError(ValueError):
    """No contiguous stage partition fits the declared HBM budget."""


def contiguous_partitions(n: int, max_groups: int) \
        -> Iterator[Tuple[Tuple[int, ...], ...]]:
    """All partitions of range(n) into 1..max_groups CONTIGUOUS non-empty
    groups, in (group count, lexicographic cut) order. n=4, max_groups=4
    yields 8 partitions — small enough to enumerate exhaustively."""
    for groups in range(1, min(max_groups, n) + 1):
        yield from _cuts(tuple(range(n)), groups)


def _cuts(items: Tuple[int, ...], groups: int) \
        -> Iterator[Tuple[Tuple[int, ...], ...]]:
    if groups == 1:
        yield (items,)
        return
    # first group takes 1..len-(groups-1) items; recurse on the rest
    for take in range(1, len(items) - groups + 2):
        for rest in _cuts(items[take:], groups - 1):
            yield (items[:take],) + rest


def propose_microbatches(stages: int) -> int:
    """Smallest M with GPipe bubble (stages-1)/(M+stages-1) <= 20%."""
    if stages <= 1:
        return 1
    m = 1
    while (stages - 1) / (m + stages - 1) > MAX_BUBBLE_FRAC:
        m += 1
    return m


def plan_stages(cost_table: Dict[str, Dict[str, int]],
                hbm_budget_bytes: int,
                max_stages: int = 4,
                programs: Sequence[str] = PIPE_PROGRAMS) -> Dict:
    """Propose stage cuts for `programs` under `hbm_budget_bytes` per chip.

    cost_table: {program name: COST_KEYS dict} (the audit baseline's
    "cost" rows, or live costmodel.measure_program output).

    Returns a plan dict:
      stages        chosen stage count
      cuts          list of per-stage program-name lists
      per_stage     [{programs, peak_hbm_bytes (EXACT int sum of member
                     rows), expected_ms}]
      bottleneck_ms max per-stage expected_ms (pipeline throughput bound)
      total_ms      sum of all stages' expected_ms (the fill latency)
      microbatches  advisory M (propose_microbatches)
      hbm_budget_bytes  echoed budget

    Raises PlanInfeasibleError when no partition fits, KeyError when a
    program's cost row is missing.
    """
    missing = [p for p in programs if p not in cost_table]
    if missing:
        raise KeyError(
            f"cost rows missing for {missing}: run tools/audit.py "
            "--update-baseline (or pass --measure to tools/pipeline_plan.py)")
    hbm = [int(cost_table[p]["peak_hbm_bytes"]) for p in programs]
    ms = [float(_costmodel.roofline(cost_table[p])["expected_ms"])
          for p in programs]

    best = None
    tightest = None  # least-over-budget partition, for the error message
    for part in contiguous_partitions(len(programs), max_stages):
        if best is not None and len(part) > best["stages"]:
            break  # fewest feasible stage count wins; done at that count
        peaks = [sum(hbm[i] for i in grp) for grp in part]
        times = [sum(ms[i] for i in grp) for grp in part]
        worst_peak = max(peaks)
        if worst_peak > hbm_budget_bytes:
            if tightest is None or worst_peak < tightest[0]:
                tightest = (worst_peak, part)
            continue
        bottleneck = max(times)
        # strict < : ties keep the earlier (lexicographically-first) cut
        if best is None or bottleneck < best["bottleneck_ms"]:
            best = {
                "stages": len(part),
                "cuts": [[programs[i] for i in grp] for grp in part],
                "per_stage": [
                    {"programs": [programs[i] for i in grp],
                     "peak_hbm_bytes": int(peaks[g]),
                     "expected_ms": times[g]}
                    for g, grp in enumerate(part)],
                "bottleneck_ms": bottleneck,
                "total_ms": sum(times),
            }
    if best is None:
        worst_peak, part = tightest
        raise PlanInfeasibleError(
            f"no contiguous partition of {list(programs)} into <= "
            f"{max_stages} stages fits hbm_budget_bytes="
            f"{hbm_budget_bytes}: the best candidate "
            f"{[[programs[i] for i in g] for g in part]} still peaks at "
            f"{worst_peak} bytes; raise the budget, shrink the model, or "
            f"add microbatching/remat headroom")
    best["microbatches"] = propose_microbatches(best["stages"])
    best["hbm_budget_bytes"] = int(hbm_budget_bytes)
    # the invariant the acceptance test pins: every stage's reported
    # peak-HBM is exactly the integer sum of its members' cost rows
    for st in best["per_stage"]:
        assert st["peak_hbm_bytes"] == sum(
            int(cost_table[p]["peak_hbm_bytes"]) for p in st["programs"])
    return best
