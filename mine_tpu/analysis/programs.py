"""Registry of the core jitted programs at canonical (tiny, CPU) shapes.

Every pass in passes.py runs over these Programs: the train step, the fused
loss forward and backward, all five warp backends, the serve render engine
(single-device and mesh), and the eval encode. Shapes are the smallest ones
that exercise the real program structure (the same 64x64 / 4-plane /
resnet18 family the test suite's tiny_setup uses), so the full audit gate
runs on the CPU container in minutes.

A Program owns one jitted callable plus an `args_fn` that materializes
FRESH canonical arguments on every call — donation passes consume buffers,
and the recompile-churn pass needs two independently-constructed but
aval-identical argument sets. Arguments are rebuilt from cached HOST copies
(numpy trees), so repeated materialization costs a device_put, not a model
re-init.

Builders are lazy and cached: importing this module imports the train and
serve stacks, but nothing is traced or compiled until a pass asks.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from mine_tpu.analysis import dtype as _dtype

# canonical tiny-trainer shape (tools/dtype_audit.py --small): 64x64,
# 4 coarse planes, resnet18, batch 1
TINY = dict(height=64, width=64, planes=4, layers=18, batch=1)

# serve-engine canonical shape: R cached entries of S planes at HxW,
# P poses. S=2 divides the mesh "model" axis; H=W=16 keeps compiles sub-s.
SERVE = dict(R=1, S=2, H=16, W=16, P=2)

WARP_IMPLS = ("xla", "xla_banded", "separable", "pallas_diff", "pallas_sep",
              "pallas_fused")


@dataclasses.dataclass
class Program:
    """One audited program: a jitted callable + canonical argument factory.

    tags:
      "train" / "serve" / "warp" / "loss"  subsystem, for --programs filters
      "mesh"      runs on a multi-device CPU mesh
      "pallas"    body contains pallas_call (interpret mode on CPU)
    donate_argnums: positions whose buffers the program donates (the
      donation pass audits exactly these).
    workload: optional host-side hot path (no arguments) for the transfer
      sanitizer — e.g. the serve engine's full _call including its output
      readback; defaults to dispatching the jitted callable.
    """

    name: str
    jit_fn: Callable
    args_fn: Callable[[], Tuple]
    tags: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    workload: Optional[Callable[[], None]] = None
    _jaxpr: Optional[object] = dataclasses.field(default=None, repr=False)
    _hlo: Optional[str] = dataclasses.field(default=None, repr=False)

    def jaxpr(self):
        if self._jaxpr is None:
            self._jaxpr = jax.make_jaxpr(self.jit_fn)(*self.args_fn())
        return self._jaxpr

    def stablehlo(self) -> str:
        if self._hlo is None:
            lowered = self.jit_fn.lower(*self.args_fn())
            self._hlo = _dtype.stablehlo_text(lowered)
        return self._hlo

    def run(self):
        return self.jit_fn(*self.args_fn())

    def cache_size(self) -> Optional[int]:
        fn = getattr(self.jit_fn, "_cache_size", None)
        return fn() if fn is not None else None


def _host_tree(tree):
    """Pytree -> numpy host copies (device-independent canonical form)."""
    return jax.tree_util.tree_map(np.asarray, tree)


def _device_tree(tree):
    """Host tree -> fresh device buffers, preserving dtypes exactly."""
    return jax.tree_util.tree_map(jnp.asarray, tree)


# ------------------------------------------------------------ tiny trainer

@functools.lru_cache(maxsize=2)
def _tiny_trainer(dtype: str = "bfloat16"):
    """The shared 64x64/4-plane/resnet18 trainer behind the train, loss and
    eval programs. bf16 by default so the dtype-upcast pass audits the
    mixed-precision program the bench runs, not an f32 stand-in."""
    from mine_tpu.config import CONFIG_DIR, load_config
    from mine_tpu.data.synthetic import make_batch
    from mine_tpu.train.step import SynthesisTrainer

    t = TINY
    config = load_config(os.path.join(CONFIG_DIR, "params_llff.yaml"))
    config.update({
        "data.img_h": t["height"], "data.img_w": t["width"],
        "mpi.num_bins_coarse": t["planes"],
        "model.num_layers": t["layers"],
        "data.per_gpu_batch_size": t["batch"],
        "training.dtype": dtype,
        # audit the portable program, not a TPU-only lowering
        "training.warp_backend": "xla",
        "training.composite_backend": "xla",
        # audit the telemetry-enabled step: the transfer_guard pass staying
        # green here is the proof that per-layer stats add no host syncs
        "training.layer_stats": True,
    })
    trainer = SynthesisTrainer(config, steps_per_epoch=10_000)
    state_host = _host_tree(trainer.init_state(batch_size=t["batch"]))
    batch_host = {k: np.asarray(v) for k, v in
                  make_batch(t["batch"], t["height"], t["width"],
                             num_points=64).items()}
    return trainer, state_host, batch_host


def _build_train_step() -> Program:
    trainer, state_host, batch_host = _tiny_trainer()

    def args_fn():
        return _device_tree(state_host), _device_tree(batch_host)

    # mirrors the donate_argnums the trainer's constructor chose
    donate = (0, 1) if bool(
        trainer.config.get("training.donate_batch", False)) else (0,)
    return Program(name="train_step", jit_fn=trainer._train_step,
                   args_fn=args_fn, tags=("train",),
                   donate_argnums=donate)


def _build_eval_encode() -> Program:
    trainer, state_host, batch_host = _tiny_trainer()
    S = TINY["planes"]
    disparity = np.tile(np.linspace(1.0, 0.2, S, dtype=np.float32)[None],
                        (TINY["batch"], 1))

    def args_fn():
        return (_device_tree(state_host),
                jnp.asarray(batch_host["src_img"]),
                jnp.asarray(disparity))

    return Program(name="eval_encode", jit_fn=trainer._eval_encode,
                   args_fn=args_fn, tags=("train",))


# ------------------------------------------------------------- fused loss

@functools.lru_cache(maxsize=1)
def _loss_fixture():
    from mine_tpu.data.synthetic import make_batch
    from mine_tpu.train import loss as loss_mod

    trainer, _, _ = _tiny_trainer()
    cfg = trainer.cfg
    B, S, side = TINY["batch"], TINY["planes"], TINY["height"]
    batch_host = {k: np.asarray(v) for k, v in
                  make_batch(B, side, side, num_points=64).items()}
    mpi_host = [np.zeros((B, S, 4, side // 2 ** s, side // 2 ** s),
                         np.float32) for s in range(4)]
    disp_host = np.tile(np.linspace(1.0, 0.2, S, dtype=np.float32)[None],
                        (B, 1))

    def total(m, d, bt):
        return loss_mod.compute_losses(m, d, bt, cfg)[0]

    return total, mpi_host, disp_host, batch_host


def _loss_args_fn():
    _, mpi_host, disp_host, batch_host = _loss_fixture()
    return (_device_tree(mpi_host), jnp.asarray(disp_host),
            _device_tree(batch_host))


def _build_fused_loss_fwd() -> Program:
    total, _, _, _ = _loss_fixture()
    return Program(name="fused_loss_fwd", jit_fn=jax.jit(total),
                   args_fn=_loss_args_fn, tags=("loss",))


def _build_fused_loss_bwd() -> Program:
    total, _, _, _ = _loss_fixture()
    return Program(name="fused_loss_bwd",
                   jit_fn=jax.jit(jax.grad(total)),
                   args_fn=_loss_args_fn, tags=("loss",))


# ------------------------------------------------------- pipeline stages

@functools.lru_cache(maxsize=1)
def _stage_fixture():
    """Canonical inputs for the four pipeline stage programs (train/step.py
    stage_encode/stage_decode/stage_render/stage_loss): the boundary
    activations are materialized ONCE by running the real stage chain on
    the tiny trainer, then cached as host trees — so pipe_decode is audited
    on genuine encoder features, pipe_loss on genuine rendered pytrees.
    These are the programs the pipeline executor jits per stage; their
    cost rows feed tools/pipeline_plan.py."""
    trainer, state_host, batch_host = _tiny_trainer()
    B, S = TINY["batch"], TINY["planes"]
    state = _device_tree(state_host)
    batch = _device_tree(batch_host)
    disp_host = np.tile(np.linspace(1.0, 0.2, S, dtype=np.float32)[None],
                        (B, 1))
    key = jax.random.PRNGKey(0)
    feats, _ = trainer.stage_encode(state.params["backbone"],
                                    state.batch_stats["backbone"],
                                    batch["src_img"], key)
    mpi, _ = trainer.stage_decode(state.params["decoder"],
                                  state.batch_stats["decoder"],
                                  feats, jnp.asarray(disp_host), key)
    rendered = trainer.stage_render(mpi, jnp.asarray(disp_host), batch)
    return (trainer, state_host, batch_host, disp_host,
            _host_tree(feats), _host_tree(mpi), _host_tree(rendered))


def _build_pipe_encode() -> Program:
    trainer, state_host, batch_host, _, _, _, _ = _stage_fixture()

    def args_fn():
        state = _device_tree(state_host)
        return (state.params["backbone"], state.batch_stats["backbone"],
                jnp.asarray(batch_host["src_img"]), jax.random.PRNGKey(0))

    return Program(name="pipe_encode", jit_fn=jax.jit(trainer.stage_encode),
                   args_fn=args_fn, tags=("train", "pipeline"))


def _build_pipe_decode() -> Program:
    trainer, state_host, _, disp_host, feats_host, _, _ = _stage_fixture()

    def args_fn():
        state = _device_tree(state_host)
        return (state.params["decoder"], state.batch_stats["decoder"],
                _device_tree(feats_host), jnp.asarray(disp_host),
                jax.random.PRNGKey(0))

    return Program(name="pipe_decode", jit_fn=jax.jit(trainer.stage_decode),
                   args_fn=args_fn, tags=("train", "pipeline"))


def _build_pipe_render() -> Program:
    trainer, _, batch_host, disp_host, _, mpi_host, _ = _stage_fixture()

    def args_fn():
        return (_device_tree(mpi_host), jnp.asarray(disp_host),
                _device_tree(batch_host))

    return Program(name="pipe_render", jit_fn=jax.jit(trainer.stage_render),
                   args_fn=args_fn, tags=("train", "pipeline"))


def _build_pipe_loss() -> Program:
    trainer, _, batch_host, _, _, _, rendered_host = _stage_fixture()

    def args_fn():
        return (_device_tree(rendered_host), _device_tree(batch_host))

    return Program(name="pipe_loss", jit_fn=jax.jit(trainer.stage_loss),
                   args_fn=args_fn, tags=("train", "pipeline"))


# ------------------------------------------------------------- warp backends

def _build_warp(impl: str) -> Program:
    from mine_tpu import geometry
    from mine_tpu.ops.warp import homography_warp

    Bp, C, H, W, band = 4, 4, 32, 32, 8
    rng = np.random.RandomState(0)
    src = rng.uniform(-1, 1, (Bp, C, H, W)).astype(np.float32)
    d_src = np.linspace(1.0, 0.25, Bp).astype(np.float32)
    G = np.tile(np.eye(4, dtype=np.float32), (Bp, 1, 1))
    G[:, 0, 3] = np.linspace(0.0, 0.02, Bp)
    K = np.tile(np.asarray([[W, 0.0, W / 2], [0.0, H, H / 2],
                            [0.0, 0.0, 1.0]], np.float32), (Bp, 1, 1))
    K_inv = np.asarray(geometry.inverse_intrinsics(jnp.asarray(K)))
    grid = np.asarray(geometry.cached_pixel_grid(H, W))

    def warp(src, d_src, G, K_inv, K, grid):
        return homography_warp(src, d_src, G, K_inv, K, grid,
                               impl=impl, band=band)

    def args_fn():
        return tuple(jnp.asarray(a) for a in
                     (src, d_src, G, K_inv, K, grid))

    tags: Tuple[str, ...] = ("warp",)
    if impl.startswith("pallas"):
        tags += ("pallas",)
    return Program(name=f"warp_{impl}", jit_fn=jax.jit(warp),
                   args_fn=args_fn, tags=tags)


# ------------------------------------------------------------- serve render

def _serve_scene(quant: str):
    """Canonical cached-entry pytree for the serve render program."""
    from mine_tpu.serve.cache import quantize_planes

    s = SERVE
    rng = np.random.RandomState(7)
    planes = rng.uniform(0.0, 1.0,
                         (s["R"], s["S"], 4, s["H"], s["W"])).astype(
                             np.float32)
    q, scales = [], []
    for r in range(s["R"]):
        qr, sr = quantize_planes(planes[r], quant)
        q.append(np.asarray(qr))
        if sr is not None:
            scales.append(np.asarray(sr))
    planes_q = np.stack(q)
    scales_q = np.stack(scales) if scales else None
    disp = np.tile(np.linspace(1.0, 0.2, s["S"], dtype=np.float32)[None],
                   (s["R"], 1))
    K = np.tile(np.asarray([[s["W"], 0.0, s["W"] / 2],
                            [0.0, s["H"], s["H"] / 2],
                            [0.0, 0.0, 1.0]], np.float32),
                (s["R"], 1, 1))
    idx = np.zeros((s["P"],), np.int32)
    G = np.tile(np.eye(4, dtype=np.float32), (s["P"], 1, 1))
    G[:, 0, 3] = np.linspace(0.0, 0.01, s["P"])
    return planes_q, scales_q, disp, K, idx, G


def serve_render_program(quant: str = "bf16",
                         mesh: Optional[Tuple[int, int]] = None,
                         name: Optional[str] = None,
                         warp_impl: str = "xla") -> Program:
    """Build the serve render Program for one cache quant mode ("float32",
    "bf16", "int8"), optionally over a (mesh_batch, mesh_model) CPU mesh,
    with the given warp backend ("pallas_fused" audits the render
    megakernel reading the quantized cache in-kernel). Exposed so tests can
    sweep quant modes; the registry registers the default-quant
    single-device and 2x2 mesh variants plus the fused int8 program."""
    from mine_tpu import geometry
    from mine_tpu.serve.engine import RenderEngine
    from mine_tpu.serve.shardmap import MeshRenderEngine

    if mesh is None:
        engine = RenderEngine(max_bucket=SERVE["P"])
        out_shardings = None
        name = name or f"serve_render[{quant}]"
        tags: Tuple[str, ...] = ("serve",)
    else:
        engine = MeshRenderEngine(mesh_batch=mesh[0], mesh_model=mesh[1],
                                  max_bucket=SERVE["P"])
        out_shardings = engine._shardings["out"]
        name = name or f"serve_render_mesh[{quant},{mesh[0]}x{mesh[1]}]"
        tags = ("serve", "mesh")
    if warp_impl.startswith("pallas"):
        tags += ("pallas",)

    planes, scales, disp, K, idx, G = _serve_scene(quant)
    K_inv = np.asarray(geometry.inverse_intrinsics(jnp.asarray(K)))

    def render(planes, scales, disp, K, K_inv, idx, G):
        return engine._render_impl(planes, scales, disp, K, K_inv, idx, G,
                                   warp_impl)

    jit_fn = (jax.jit(render) if out_shardings is None else
              jax.jit(render, out_shardings=(out_shardings, out_shardings)))

    def args_fn():
        raw = (jnp.asarray(planes),
               None if scales is None else jnp.asarray(scales),
               jnp.asarray(disp), jnp.asarray(K), jnp.asarray(K_inv),
               jnp.asarray(idx), jnp.asarray(G))
        # the mesh engine commits operands under NamedShardings — the
        # placement is part of the audited program's canonical inputs
        return engine._place(*raw)

    def workload():
        # the host hot path, including the output readback the engine
        # declares via host_readback — what the transfer sanitizer runs
        rgb, depth = jit_fn(*args_fn())
        from mine_tpu.telemetry.hostsync import host_readback
        with host_readback("analysis.serve_render"):
            np.asarray(rgb), np.asarray(depth)

    return Program(name=name, jit_fn=jit_fn, args_fn=args_fn, tags=tags,
                   workload=workload)


# --------------------------------------------------------------- registry

_BUILDERS: Dict[str, Callable[[], Program]] = {}
_CACHE: Dict[str, Program] = {}


def _register(name: str, builder: Callable[[], Program]) -> None:
    _BUILDERS[name] = builder


_register("train_step", _build_train_step)
_register("fused_loss_fwd", _build_fused_loss_fwd)
_register("fused_loss_bwd", _build_fused_loss_bwd)
for _impl in WARP_IMPLS:
    _register(f"warp_{_impl}", functools.partial(_build_warp, _impl))
_register("serve_render",
          functools.partial(serve_render_program, "bf16", None,
                            "serve_render"))
_register("serve_render_mesh",
          functools.partial(serve_render_program, "bf16", (2, 2),
                            "serve_render_mesh"))
# the fused megakernel serving the int8 cache: the quantized planes cross
# into the kernel (in-register dequant) — dot_budget pins the one-kernel
# structure (a deliberately unfused build trips it, tests/test_analysis)
_register("serve_render_fused",
          functools.partial(serve_render_program, "int8", None,
                            "serve_render_fused", "pallas_fused"))
_register("eval_encode", _build_eval_encode)
# the staged train step's four sub-programs (parallel/pipeline.py): their
# cost rows are the planner's input (tools/pipeline_plan.py) and their dot
# budgets pin each stage's trace independently of the fused step's
_register("pipe_encode", _build_pipe_encode)
_register("pipe_decode", _build_pipe_decode)
_register("pipe_render", _build_pipe_render)
_register("pipe_loss", _build_pipe_loss)


def program_names() -> List[str]:
    return list(_BUILDERS)


def get_program(name: str) -> Program:
    if name not in _BUILDERS:
        raise KeyError(f"unknown program {name!r}; "
                       f"known: {', '.join(_BUILDERS)}")
    if name not in _CACHE:
        _CACHE[name] = _BUILDERS[name]()
    return _CACHE[name]


def get_programs(names=None) -> List[Program]:
    return [get_program(n) for n in (names or program_names())]
