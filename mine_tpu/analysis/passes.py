"""The registered audit passes. Each detects one silent program regression:

  dtype_upcast      bf16->f32 converts inside conv-stack scopes (StableHLO)
  dot_budget        dot_general count / FLOPs vs tools/analysis_baseline.json
  cost_budget       compiled-executable flops/bytes/HBM vs the baseline's
                    "cost" section (analysis/costmodel.py), with a roofline
                    expected-time estimate in the details
  recompile_churn   a second identically-shaped call must hit the jit cache
  transfer_guard    hot paths run clean under jax.transfer_guard("disallow")
  donation          donated buffers actually consumed (deleted, no warning)
  concurrency       global lock-acquisition order + thread-leak check over a
                    live threaded serve workload (global pass)
  aot_staleness     serving AOT executable store artifacts current for this
                    jax version / backend / topology (global pass; skips
                    when no store is configured)

Every pass ships `selftest()`: it seeds the violation the pass exists to
catch (an unjustified conv-scope upcast, a budget mismatch, a weak-type
retrace, an implicit host transfer, a dropped donation, a lock-order
inversion) and returns the pass's verdict on that fixture — which MUST be
a failure. `tools/audit.py --selftest` gates on exactly that.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from mine_tpu.analysis import costmodel as _costmodel
from mine_tpu.analysis import dtype as _dtype
from mine_tpu.analysis import flops as _flops
from mine_tpu.analysis import locks as _locks
from mine_tpu.analysis.framework import AuditPass, PassResult


# ------------------------------------------------------------ dtype upcast

class DtypeUpcastPass(AuditPass):
    """Generalizes tools/dtype_audit.py to every registered program: fail
    on any bf16->f32 convert inside an encoder/decoder conv scope that no
    JUSTIFIED annotation covers (f32 BN stats, loss graph, optimizer math
    remain allowed by declaration)."""

    name = "dtype_upcast"

    def _check_text(self, program_name: str, text: str) -> PassResult:
        upcasts = _dtype.collect_upcasts(text)
        bad = _dtype.suspects(upcasts)
        if bad:
            el = sum(u["elements"] for u in bad)
            worst = sorted(bad, key=lambda u: -u["elements"])[:3]
            det = (f"{len(bad)} unjustified conv-stack upcasts "
                   f"({el / 1e6:.2f} M elements); worst: "
                   + "; ".join(f"{u['shape']} @ {u['scope'][:48]}"
                               for u in worst))
            return self._result(program_name, ok=False, details=det,
                                suspects=len(bad), elements=el)
        return self._result(
            program_name, ok=True,
            details=f"{len(upcasts)} converts, conv-stack clean",
            converts=len(upcasts))

    def run(self, program) -> PassResult:
        return self._check_text(program.name, program.stablehlo())

    def selftest(self) -> PassResult:
        seeded = """
module @jit_bad {
  func.func public @main() {
    %0 = stablehlo.convert %a : (tensor<2x64x96x256xbf16>) -> tensor<2x64x96x256xf32> loc(#loc1)
  }
}
#loc1 = loc("jit(step)/encoder/resnet/conv3/convert_element_type"(#loc9))
"""
        return self._check_text("selftest[conv-upcast]", seeded)


# -------------------------------------------------------------- dot budget

class DotBudgetPass(AuditPass):
    """Per-program dot_general count and FLOP budget, pinned exactly in
    tools/analysis_baseline.json (one source of truth, absorbing the old
    in-test dot-count gates). Mismatch in EITHER direction fails; update
    with `tools/audit.py --update-baseline` in the same commit as the
    intentional program change."""

    name = "dot_budget"

    def __init__(self, baseline: Dict):
        self.baseline = baseline

    def measure(self, program) -> Dict:
        jaxpr = program.jaxpr()
        out = {"dots": _flops.count_dots(jaxpr),
               "dot_flops": _flops.dot_flops(jaxpr)}
        if program.name.startswith("fused_loss"):
            # the PR-2 acceptance gate, now framework-owned: Toeplitz blur
            # einsums in the loss graph (tests assert the same number)
            out["blur_dots"] = _flops.count_blur_dots(jaxpr)
        return out

    def run(self, program) -> PassResult:
        measured = self.measure(program)
        expected = self.baseline.get("programs", {}).get(program.name)
        if expected is None:
            return self._result(
                program, ok=False,
                details="no baseline entry — run tools/audit.py "
                        "--update-baseline on a green build",
                measured=measured)
        diffs = [f"{k}: measured {measured[k]} != baseline {expected[k]}"
                 for k in sorted(set(measured) | set(expected))
                 if measured.get(k) != expected.get(k)]
        if diffs:
            return self._result(program, ok=False,
                                details="; ".join(diffs),
                                measured=measured, expected=expected)
        det = ", ".join(f"{k}={measured[k]}" for k in sorted(measured))
        return self._result(program, ok=True, details=det,
                            measured=measured)

    def selftest(self) -> PassResult:
        from mine_tpu.analysis.programs import Program

        def mm(a, b):
            return a @ b

        x = jnp.zeros((4, 8), jnp.float32)
        y = jnp.zeros((8, 2), jnp.float32)
        prog = Program(name="selftest[budget]", jit_fn=jax.jit(mm),
                       args_fn=lambda: (x, y))
        seeded = DotBudgetPass(
            {"programs": {"selftest[budget]": {"dots": 0, "dot_flops": 0}}})
        return seeded.run(prog)


# -------------------------------------------------------------- cost budget

class CostBudgetPass(AuditPass):
    """Compiled-executable cost/memory budget: AOT-compile each program and
    pin cost_analysis() flops/bytes plus memory_analysis() argument/output/
    temp/alias/peak-HBM bytes, exactly, in the baseline's "cost" section.
    These are post-fusion numbers — the real traffic and residency of the
    program XLA actually runs — so any drift means the generated code
    changed; update with `tools/audit.py --update-baseline` in the same
    commit as the intentional change. Details carry the roofline estimate
    (env-dependent chip model, reported but never gated)."""

    name = "cost_budget"

    def __init__(self, baseline: Dict):
        self.baseline = baseline

    def measure(self, program) -> Dict:
        return _costmodel.measure_program(program)

    def run(self, program) -> PassResult:
        measured = self.measure(program)
        expected = self.baseline.get("cost", {}).get(program.name)
        if expected is None:
            return self._result(
                program, ok=False,
                details="no cost baseline entry — run tools/audit.py "
                        "--update-baseline on a green build",
                measured=measured)
        diffs = [f"{k}: measured {measured[k]} != baseline {expected[k]}"
                 for k in sorted(set(measured) | set(expected))
                 if measured.get(k) != expected.get(k)]
        if diffs:
            return self._result(program, ok=False,
                                details="; ".join(diffs),
                                measured=measured, expected=expected)
        rl = _costmodel.roofline(measured)
        det = (f"flops={measured['flops']} "
               f"bytes={measured['bytes_accessed']} "
               f"peak_hbm={measured['peak_hbm_bytes']}; "
               f"roofline {rl['expected_ms']:.3f} ms "
               f"({rl['bound']}-bound @ {rl['peak_tflops']:.0f} TFLOP/s, "
               f"{rl['hbm_gbps']:.0f} GB/s)")
        return self._result(program, ok=True, details=det,
                            measured=measured, roofline=rl)

    def selftest(self) -> PassResult:
        from mine_tpu.analysis.programs import Program

        def mm(a, b):
            return a @ b

        x = jnp.zeros((4, 8), jnp.float32)
        y = jnp.zeros((8, 2), jnp.float32)
        prog = Program(name="selftest[cost]", jit_fn=jax.jit(mm),
                       args_fn=lambda: (x, y))
        # seeded violation: an inflated flops entry the measurement can
        # never reproduce — the exact-match gate must fail on it
        seeded = CostBudgetPass({"cost": {"selftest[cost]": {
            "flops": 10 ** 15, "bytes_accessed": 0, "argument_bytes": 0,
            "output_bytes": 0, "temp_bytes": 0, "alias_bytes": 0,
            "peak_hbm_bytes": 0}}})
        return seeded.run(prog)


# --------------------------------------------------------- recompile churn

class RecompileChurnPass(AuditPass):
    """Dispatch each program twice with independently materialized but
    aval-identical inputs: the second call must hit the jit cache. A miss
    means input construction churns weak_type/dtype/sharding — the compile-
    churn failure mode that silently serializes a serving fleet."""

    name = "recompile_churn"

    def _check_fn(self, program_name: str, jit_fn, args_fn) -> PassResult:
        size0 = getattr(jit_fn, "_cache_size", lambda: None)()
        if size0 is None:
            return self._skip(program_name,
                              "jit cache not introspectable on this fn")
        out = jit_fn(*args_fn())
        jax.block_until_ready(out)
        size1 = jit_fn._cache_size()
        out = jit_fn(*args_fn())
        jax.block_until_ready(out)
        size2 = jit_fn._cache_size()
        if size2 > size1:
            return self._result(
                program_name, ok=False,
                details=f"cache miss on identical-aval re-dispatch "
                        f"(entries {size1} -> {size2}): argument "
                        f"construction churns weak_type/dtype/sharding",
                cache=(size0, size1, size2))
        return self._result(program_name, ok=True,
                            details=f"cache stable at {size1} entries",
                            cache=(size0, size1, size2))

    def run(self, program) -> PassResult:
        return self._check_fn(program.name, program.jit_fn, program.args_fn)

    def selftest(self) -> PassResult:
        f = jax.jit(lambda x: x * 2.0)
        calls = iter((lambda: (jnp.float32(1.0),),   # strong f32 scalar
                      lambda: (1.0,)))               # weak python float

        def churny_args():
            return next(calls)()

        return self._check_fn("selftest[churn]", f, churny_args)


# ---------------------------------------------------------- transfer guard

class TransferGuardPass(AuditPass):
    """Run the hot path under jax.transfer_guard("disallow"): any IMPLICIT
    device transfer (a raw numpy array flowing into a jitted call, a python
    scalar promoted mid-graph) fails. Intentional readbacks declare
    themselves with telemetry.host_readback(reason) — the allowlist — so a
    clean run passes by declaration, not path-string exemption. Arguments
    are materialized OUTSIDE the guard: explicit staging is the sanctioned
    pattern, and device_put/jnp.asarray remain allowed inside too."""

    name = "transfer_guard"

    def _check_workload(self, program_name: str, workload) -> PassResult:
        try:
            with jax.transfer_guard("disallow"):
                jax.block_until_ready(workload())
        except Exception as e:
            msg = str(e)
            if "transfer" in msg.lower():
                return self._result(
                    program_name, ok=False,
                    details="implicit transfer on the hot path: "
                            + msg.splitlines()[0][:120],
                    error=msg[:400])
            raise
        return self._result(program_name, ok=True,
                            details="clean under transfer_guard(disallow)")

    def run(self, program) -> PassResult:
        if program.workload is not None:
            return self._check_workload(program.name, program.workload)
        args = program.args_fn()  # staged before the guard closes
        return self._check_workload(
            program.name, lambda: program.jit_fn(*args))

    def selftest(self) -> PassResult:
        f = jax.jit(lambda x: x + 1.0)
        host_arr = np.ones((4,), np.float32)
        # raw numpy jit argument = implicit h2d — the seeded violation
        return self._check_workload("selftest[transfer]",
                                    lambda: f(host_arr))


# --------------------------------------------------------------- donation

class DonationPass(AuditPass):
    """Donated argument buffers must actually be consumed: after one
    dispatch, every donated jax.Array leaf is deleted and no
    donation-dropped warning fired. A dropped donation silently doubles
    the train step's peak memory — exactly the class of regression that
    only shows up as an OOM at the flagship shape."""

    name = "donation"

    def applies_to(self, program) -> bool:
        return bool(program.donate_argnums)

    def _check_call(self, program_name: str, jit_fn, args,
                    donate_argnums) -> PassResult:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            out = jit_fn(*args)
            jax.block_until_ready(out)
        dropped_warn = [str(w.message) for w in caught
                        if "donated" in str(w.message).lower()]
        undeleted = []
        for argnum in donate_argnums:
            for leaf in jax.tree_util.tree_leaves(args[argnum]):
                if isinstance(leaf, jax.Array) and not leaf.is_deleted():
                    undeleted.append((argnum, leaf.shape, str(leaf.dtype)))
        if dropped_warn or undeleted:
            bits = []
            if dropped_warn:
                bits.append("donation-dropped warning: "
                            + dropped_warn[0][:100])
            if undeleted:
                bits.append(f"{len(undeleted)} donated buffers NOT "
                            f"deleted, e.g. {undeleted[0]}")
            return self._result(program_name, ok=False,
                                details="; ".join(bits),
                                undeleted=len(undeleted),
                                warnings=dropped_warn[:3])
        n = sum(len(jax.tree_util.tree_leaves(args[a]))
                for a in donate_argnums)
        return self._result(program_name, ok=True,
                            details=f"all {n} donated buffers consumed",
                            leaves=n)

    def run(self, program) -> PassResult:
        return self._check_call(program.name, program.jit_fn,
                                program.args_fn(), program.donate_argnums)

    def selftest(self) -> PassResult:
        # scalar output matches no input shape -> donation dropped
        f = jax.jit(lambda x: jnp.sum(x), donate_argnums=(0,))
        args = (jnp.ones((16, 16), jnp.float32),)
        return self._check_call("selftest[donation]", f, args, (0,))


# ------------------------------------------------------------- concurrency

class ConcurrencyPass(AuditPass):
    """Host-side concurrency lint over a LIVE threaded serve workload:
    concurrent submitters + the ContinuousBatcher flush thread + the ops
    endpoint's handler threads + full-rate tracing, all crossing the
    instrumented telemetry locks. Fails on any recorded lock-order
    violation (mine_tpu/analysis/locks.py holds the global order) or on a
    thread that survives close() — the unjoined-thread regression the
    PR-8 close() fix addressed."""

    name = "concurrency"
    scope = "global"

    N_SUBMITTERS = 3
    N_REQUESTS = 8  # per submitter

    def run_global(self) -> PassResult:
        import urllib.request

        from mine_tpu.serve.batcher import ContinuousBatcher
        from mine_tpu.serve.engine import RenderEngine
        from mine_tpu.telemetry import OpsServer, tracing
        from mine_tpu.telemetry.slo import SLOTracker

        baseline_threads = set(threading.enumerate())
        _locks.violations(clear=True)

        rng = np.random.RandomState(3)
        S, H, W = 2, 16, 16
        engine = RenderEngine(max_bucket=4)
        engine.put("scene", rng.rand(S, 3, H, W).astype(np.float32),
                   rng.rand(S, 1, H, W).astype(np.float32),
                   np.linspace(1.0, 0.2, S, dtype=np.float32),
                   np.asarray([[W, 0, W / 2], [0, H, H / 2], [0, 0, 1]],
                              np.float32))
        slo = SLOTracker(objective_ms=60_000.0)
        tracing.configure(sample=1.0)
        batcher = ContinuousBatcher(engine, max_requests=4, max_wait_ms=1.0,
                                    start=True, slo=slo)
        ops = OpsServer(slo=slo).start()
        pose = np.eye(4, dtype=np.float32)
        errors: List[str] = []

        def submitter(k: int) -> None:
            futs = [batcher.submit("scene", pose)
                    for _ in range(self.N_REQUESTS)]
            for f in futs:
                try:
                    f.result(timeout=60)
                except Exception as e:  # pragma: no cover - device failure
                    errors.append(f"submitter {k}: {e}")

        threads = [threading.Thread(target=submitter, args=(k,))
                   for k in range(self.N_SUBMITTERS)]
        try:
            for t in threads:
                t.start()
            # ops endpoint traffic concurrently with the render threads:
            # handler threads walk the registry + slo + trace-ring locks
            for path in ("/metrics", "/slo", "/traces/recent", "/healthz"):
                urllib.request.urlopen(ops.url + path, timeout=10).read()
            for t in threads:
                t.join(timeout=120)
        finally:
            closed = batcher.close()
            ops.close()
            tracing.configure(sample=0.0)
            tracing.reset()

        time.sleep(0.05)  # give joined threads a beat to leave enumerate()
        viol = _locks.violations(clear=True)
        leaked = _locks.leaked_threads(baseline=baseline_threads)
        problems = []
        if errors:
            problems.append(f"{len(errors)} request errors "
                            f"({errors[0][:80]})")
        if not closed:
            problems.append("batcher.close() failed to join flush thread")
        if viol:
            v = viol[0]
            problems.append(
                f"{len(viol)} lock-order violations, e.g. {v['thread']} "
                f"acquired {v['acquiring']} (rank {v['acquiring_rank']}) "
                f"while holding {v['held']}")
        if leaked:
            problems.append("leaked threads: "
                            + ", ".join(t.name for t in leaked))
        if problems:
            return self._result("-", ok=False, details="; ".join(problems),
                                violations=viol[:5],
                                leaked=[t.name for t in leaked])
        total = self.N_SUBMITTERS * self.N_REQUESTS
        return self._result(
            "-", ok=True,
            details=f"{total} requests over {self.N_SUBMITTERS} threads: "
                    f"lock order clean, no leaked threads")

    def selftest(self) -> PassResult:
        # seeded lock-order inversion: acquire rank 2 then rank 1
        _locks.violations(clear=True)
        hi = _locks.OrderedLock("selftest.hi", rank=2)
        lo = _locks.OrderedLock("selftest.lo", rank=1)
        with hi:
            with lo:
                pass
        viol = _locks.violations(clear=True)
        ours = [v for v in viol if v["acquiring"] == "selftest.lo"]
        if ours:
            v = ours[0]
            return self._result(
                "selftest[lock-order]", ok=False,
                details=f"lock-order inversion detected: acquired "
                        f"{v['acquiring']} (rank {v['acquiring_rank']}) "
                        f"while holding {v['held']}",
                violations=ours)
        # the monitor MISSED the inversion — selftest must surface that as
        # a (wrongly) passing result so --selftest fails loudly
        return self._result("selftest[lock-order]", ok=True,
                            details="monitor failed to record inversion")


# --------------------------------------------------------- AOT staleness

class AOTStalenessPass(AuditPass):
    """Audits the serving AOT executable store (serve/aot.py): every
    artifact's environment fingerprint must match the CURRENT jax/jaxlib
    version, backend, and device topology, and every sidecar must be
    readable and consistent with its content address. A stale artifact is
    harmless at runtime (content addressing makes it a miss, never a wrong
    load) but it means a replica believed warm will silently pay live
    compiles — exactly the regression this store exists to kill — so the
    gate fails until `tools/aot_warmstore.py --gc` (or a rebuild) clears
    it. Skips when no store is configured (MINE_TPU_AOT_STORE)."""

    name = "aot_staleness"
    scope = "global"

    def __init__(self, root: Optional[str] = None):
        # explicit root for tools/aot_warmstore.py --check; the audit gate
        # reads the env var so CI without a store skips cleanly
        self.root = root

    def run_global(self) -> PassResult:
        import os
        from mine_tpu.serve import aot as _aot
        root = self.root or os.environ.get("MINE_TPU_AOT_STORE", "")
        if not root or not os.path.isdir(root):
            return self._skip(
                "-", "no AOT store configured (set MINE_TPU_AOT_STORE or "
                     "serve.aot_store_dir to audit one)")
        store = _aot.AOTStore(root)
        entries = store.entries()
        stale = store.stale_entries()
        if stale:
            corrupt = [e for e in stale if e["corrupt"]]
            fp = _aot.env_fingerprint()
            return self._result(
                "-", ok=False,
                details=f"{len(stale)}/{len(entries)} artifacts stale for "
                        f"current environment (jax {fp['jax']}, "
                        f"{fp['backend']}, {fp['devices']}; "
                        f"{len(corrupt)} corrupt) — rebuild or run "
                        f"tools/aot_warmstore.py --gc",
                stale=[e["digest"][:12] for e in stale[:8]],
                fingerprint=fp)
        return self._result(
            "-", ok=True,
            details=f"{len(entries)} artifacts current for jax "
                    f"{_aot.env_fingerprint()['jax']}")

    def selftest(self) -> PassResult:
        # seeded violation: an artifact whose fingerprint claims another
        # jax version — the staleness check MUST flag it
        import json
        import tempfile
        from mine_tpu.serve import aot as _aot
        with tempfile.TemporaryDirectory() as root:
            store = _aot.AOTStore(root)
            key = {"program": "selftest",
                   "fingerprint": dict(_aot.env_fingerprint(),
                                       jax="0.0.0-selftest")}
            digest = _aot.key_digest(key)
            art, side = store._paths(digest)
            with open(art, "wb") as f:
                f.write(b"not a real executable")
            with open(side, "w", encoding="utf-8") as f:
                json.dump({"key": key, "nbytes": 0}, f)
            check = AOTStalenessPass(root=root)
            return check.run_global()


# ------------------------------------------------------------ pipeline plan

class PipelinePlanPass(AuditPass):
    """Plans the staged train step (parallel/pipeline.py) from the
    baseline's pipe_* cost rows (analysis/planner.py): the pass fails when
    a stage program has no pinned cost row, or when no contiguous stage
    partition fits the declared per-chip HBM budget
    (MINE_TPU_PIPELINE_HBM_BUDGET_GB, default 16.0 — a v5e chip). A red
    gate here means the cost rows drifted to where the documented pipeline
    deployment no longer fits — the regression must be acknowledged (budget
    raised, or the growth reverted) before it ships."""

    name = "pipeline_plan"
    scope = "global"

    DEFAULT_BUDGET_GB = 16.0

    def __init__(self, baseline: Dict, budget_gb: Optional[float] = None):
        self.baseline = baseline
        self.budget_gb = budget_gb

    def _budget_bytes(self) -> int:
        import os
        gb = self.budget_gb
        if gb is None:
            gb = float(os.environ.get("MINE_TPU_PIPELINE_HBM_BUDGET_GB",
                                      self.DEFAULT_BUDGET_GB))
        return int(gb * 2 ** 30)

    def run_global(self) -> PassResult:
        from mine_tpu.analysis import planner as _planner
        cost = self.baseline.get("cost", {})
        missing = [p for p in _planner.PIPE_PROGRAMS if p not in cost]
        if missing:
            return self._result(
                "-", ok=False,
                details="no cost baseline entry for "
                        + ", ".join(missing)
                        + " — run tools/audit.py --update-baseline on a "
                          "green build",
                missing=missing)
        budget = self._budget_bytes()
        try:
            plan = _planner.plan_stages(cost, budget)
        except _planner.PlanInfeasibleError as e:
            return self._result("-", ok=False,
                                details=str(e)[:300],
                                budget_bytes=budget)
        cuts = " | ".join("+".join(n.removeprefix("pipe_") for n in names)
                          for names in plan["cuts"])
        det = (f"{plan['stages']} stage(s) [{cuts}] fit "
               f"{budget / 2 ** 30:.1f} GiB/chip; bottleneck "
               f"{plan['bottleneck_ms']:.3f} ms, advisory "
               f"microbatches={plan['microbatches']}")
        return self._result("-", ok=True, details=det, plan=plan)

    def selftest(self) -> PassResult:
        # seeded violation: a synthetic cost table no partition of which
        # can fit a one-KiB budget — the infeasibility path MUST fail
        from mine_tpu.analysis.planner import PIPE_PROGRAMS
        row = {"flops": 10 ** 9, "bytes_accessed": 10 ** 6,
               "argument_bytes": 10 ** 5, "output_bytes": 10 ** 5,
               "temp_bytes": 10 ** 5, "alias_bytes": 0,
               "peak_hbm_bytes": 10 ** 8}
        seeded = PipelinePlanPass(
            {"cost": {p: dict(row) for p in PIPE_PROGRAMS}},
            budget_gb=1024 / 2 ** 30)  # 1 KiB
        return seeded.run_global()


# ---------------------------------------------------------------- suites

def default_passes(baseline: Dict) -> List[AuditPass]:
    return [DtypeUpcastPass(), DotBudgetPass(baseline),
            CostBudgetPass(baseline), RecompileChurnPass(),
            TransferGuardPass(), DonationPass(), ConcurrencyPass(),
            AOTStalenessPass(), PipelinePlanPass(baseline)]


def pass_by_name(name: str, baseline: Optional[Dict] = None) -> AuditPass:
    for p in default_passes(baseline or {"programs": {}, "budgets": {},
                                         "cost": {}}):
        if p.name == name:
            return p
    raise KeyError(f"unknown pass {name!r}")
