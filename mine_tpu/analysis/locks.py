"""Rank-ordered locks: the global acquisition order for host-side threads.

The serve plane is genuinely multithreaded — submitters, the batcher flush
thread, the ops-server handler threads and the train loop all cross the
telemetry locks — and a deadlock there would wedge a fleet, not a test. The
classic discipline is a GLOBAL LOCK ORDER: every named lock carries a rank,
and a thread may only acquire ranks strictly above everything it already
holds. This module enforces that dynamically: each wrapped lock records
itself in a thread-local held-stack on acquire, and an acquisition at a
rank <= the highest held rank records a LockOrderViolation (it does NOT
raise — the lint must observe production code paths without changing their
behavior; the concurrency audit pass fails on the recorded evidence).

LOCK_RANKS below is the canonical order, derived from the one real nesting
in the codebase (events.configure holds the state lock while closing the
sink) plus the call sequences of every instrumented path; it is asserted by
the concurrency pass under a live threaded serve workload.

Stdlib-only and import-light by design: telemetry/ and serve/ modules
import this at module load, so it must never import jax or mine_tpu.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# The global acquisition order (ascending = allowed nesting direction).
# Adding a lock: pick a rank consistent with every path that can hold it
# together with another instrumented lock, and note the path here.
#   recorder.dump     the flight recorder's bundle writer
#                     (telemetry/recorder.py): held across state-provider
#                     callbacks that re-enter fleet/batcher/session locks
#                     and across the obs.incident emit (tee -> ring, sink)
#                     — so it must rank BELOW the entire serve plane and
#                     every telemetry lock
#   recorder.state    swap-only guard of the module recorder pointer;
#                     never held while acquiring anything above it except
#                     trivially ascending reads
#   session manager / session  the streaming-session plane (serve/stream.py,
#                     serve/session.py): the manager lock guards the session
#                     table and may create/close sessions (which take their
#                     own lock), and a session's process_frame holds its lock
#                     across fleet.submit — so both sit BELOW batcher.cv and
#                     fleet.cache, manager below session
#   batcher.cv        held around queue list ops + the admission decision,
#                     whose edge events nest ASCENDING into telemetry
#   fleet.cache       guards the shard list / dead set across route, put,
#                     rebalance and failover; the per-shard LRU counters and
#                     the shard_dead/place events nest ascending under it.
#                     Never held together with batcher.cv (routing happens
#                     before submit; the flush thread holds neither), so its
#                     rank only needs to sit below telemetry.
#   recorder.ring     the flight recorder's ring-buffer Condition: the
#                     events tee acquires it INSIDE emitters that still
#                     hold their own lock — _mark_dead emits shard_dead
#                     under fleet.cache (15), admission transitions emit
#                     under batcher.cv (10) — so it ranks above both; the
#                     dump path only ever COPIES under it and releases
#                     before calling out, so nothing above it is needed
#                     below 20
#   tracing ctx       add_span/finish take it, release, then emit events
#   tracing tracer    start/finish take it alone or after ctx released
#   slo               record() releases it before setting registry gauges
#   registry/metric   registry lock creates metrics; metric locks nest never
#   events state->sink  configure() closes the old sink under the state lock
#                       — the one genuine nesting, hence state < sink
#   hostnet.state     the host server's drain/inflight Condition
#                     (serve/hostnet.py): handler threads hold it only for
#                     counter flips and release BEFORE calling
#                     fleet.submit; the drain path waits on it, releases,
#                     then closes the fleet — so it sits below the whole
#                     serve plane
#   ring front / ring the multi-host route tallies and the ring membership
#                     table (serve/ring.py): the front resolves the owner
#                     under its tally lock by calling into the ring
#                     (front < ring), both release before any host handle
#                     call (which re-enters batcher.cv/fleet.cache on a
#                     local host) — so both rank below batcher.cv; the
#                     membership-change events nest ascending under ring
#   net.breaker       a HostClient's per-host circuit-breaker state
#                     (serve/hostnet.py CircuitBreaker): taken on the
#                     request path AFTER the front/ring locks release
#                     (handle calls hold neither), and the prober's
#                     miss bookkeeping may hold ring.front (7) while a
#                     breaker snapshot reads it — so it ranks above ring
#                     (8); transitions emit AFTER release, so nothing
#                     above it is ever taken under it
LOCK_RANKS: Dict[str, int] = {
    "telemetry.recorder.dump": 2,
    "telemetry.recorder.state": 3,
    "serve.session.manager": 4,
    "serve.session": 5,
    "serve.hostnet.state": 6,
    "serve.ring.front": 7,
    "serve.ring": 8,
    "serve.net.breaker": 9,
    "serve.batcher.cv": 10,
    # serve.wire.* (PR 20): the front's owner-coalescer queue is acquired
    # from submit() holding nothing and released before any dispatch; the
    # client's negotiation flag guards one bool and is released before the
    # probe round — neither ever nests under or over another serve lock,
    # so both sit in the unused gap above the breaker.
    "serve.wire.coalesce": 11,
    "serve.wire.negotiate": 12,
    "serve.fleet.cache": 15,
    "telemetry.recorder.ring": 18,
    "telemetry.tracing.ctx": 20,
    "telemetry.tracing.tracer": 30,
    "telemetry.slo": 40,
    "telemetry.registry.registry": 50,
    "telemetry.registry.metric": 55,
    "telemetry.events.state": 60,
    "telemetry.events.sink": 70,
}

_MAX_VIOLATIONS = 256  # bounded evidence; a runaway path can't eat memory

_tls = threading.local()
_violations_lock = threading.Lock()
_violations: List[Dict] = []


class LockOrderViolation(RuntimeError):
    """Raised only by tests that opt in; the monitor itself records."""


def _held() -> List["OrderedLock"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _record_violation(lock: "OrderedLock", held: List["OrderedLock"]) -> None:
    rec = {"thread": threading.current_thread().name,
           "acquiring": lock.name, "acquiring_rank": lock.rank,
           "held": [(h.name, h.rank) for h in held]}
    with _violations_lock:
        if len(_violations) < _MAX_VIOLATIONS:
            _violations.append(rec)


def violations(clear: bool = False) -> List[Dict]:
    """Recorded lock-order violations (process-wide). `clear` resets —
    the concurrency pass clears before its workload and asserts after."""
    with _violations_lock:
        out = list(_violations)
        if clear:
            del _violations[:]
    return out


class OrderedLock:
    """A threading.Lock wrapper carrying a (name, rank) and feeding the
    order monitor. API-compatible where the codebase needs it: acquire/
    release/context manager/locked, and usable as the `lock=` argument of
    threading.Condition (whose non-blocking `_is_owned` probe is handled:
    a FAILED acquire never touches the held-stack or the monitor)."""

    __slots__ = ("name", "rank", "_lock")

    def __init__(self, name: str, rank: Optional[int] = None):
        if rank is None:
            if name not in LOCK_RANKS:
                raise KeyError(
                    f"lock {name!r} has no entry in LOCK_RANKS; add one "
                    f"(with a comment deriving its rank) or pass rank=")
            rank = LOCK_RANKS[name]
        self.name = name
        self.rank = int(rank)
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            held = _held()
            # order check on SUCCESSFUL acquisition: any already-held lock
            # at an equal-or-higher rank means this thread is nesting
            # against the global order (equal ranks are unordered peers —
            # nesting two of them is a violation too)
            if held and max(h.rank for h in held) >= self.rank:
                _record_violation(self, held)
            held.append(self)
        return ok

    def release(self) -> None:
        held = _held()
        # release order is unconstrained; drop the most recent entry for
        # this lock object (locks are non-reentrant: at most one entry)
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"OrderedLock({self.name!r}, rank={self.rank})"


def ordered_lock(name: str, rank: Optional[int] = None) -> OrderedLock:
    """The instrumented replacement for `threading.Lock()` at a named
    call site: `self._lock = ordered_lock("telemetry.slo")`."""
    return OrderedLock(name, rank)


def ordered_condition(name: str,
                      rank: Optional[int] = None) -> threading.Condition:
    """A threading.Condition over an OrderedLock (Condition accepts any
    lock object with acquire/release): wait/notify work unchanged, and
    every acquisition of the underlying lock feeds the order monitor."""
    return threading.Condition(lock=OrderedLock(name, rank))


# --------------------------------------------------------------- threads

# the thread names the serve plane owns and must JOIN on close() — an
# alive one after teardown is the unjoined-thread regression (PR-8).
# The flight-recorder dump worker and the resource-gauge sampler joined
# the list with PR 15: both have explicit close() paths; the ring front's
# heartbeat prober (serve/ring.py, serve.net.probe_interval_s) joined
# with PR 19 — RingFront.close() stops and joins it; the front's
# owner-coalescer flusher (serve.wire.coalesce_ms, PR 20) follows the
# same close() discipline.
OWNED_THREAD_NAMES = ("mine-tpu-serve-batcher", "mine-tpu-ops-server",
                      "mine-tpu-flight-recorder",
                      "mine-tpu-resource-sampler",
                      "mine-tpu-ring-prober",
                      "mine-tpu-wire-coalescer")


def leaked_threads(baseline=None):
    """Threads that should not survive a clean teardown: non-daemon
    threads other than the main thread, plus alive daemons with an
    OWNED_THREAD_NAMES name (those have explicit close()/join paths, so
    one still alive means somebody forgot to close). `baseline` is an
    optional set of threads to ignore (captured before the workload)."""
    baseline = baseline or ()
    out = []
    for t in threading.enumerate():
        if t is threading.main_thread() or t in baseline or not t.is_alive():
            continue
        if not t.daemon:
            out.append(t)
        elif any(t.name.startswith(n) for n in OWNED_THREAD_NAMES):
            out.append(t)
    return out
