"""bf16 -> f32 upcast collection over StableHLO text (dtype-upcast pass).

Moved verbatim from tools/dtype_audit.py (which is now a thin CLI shim over
this module) so the dtype-upcast audit pass can run over EVERY registered
program, not just the train step. The report format is unchanged — the CLI
output is byte-compatible with the pre-framework tool.

XLA inserts converts for good reasons too (f32 BN statistics, the f32 loss
graph, optimizer math), so the audit REPORTS AND RANKS rather than blanket-
fails: the pass only fails on un-justified converts inside encoder/decoder
conv scopes — the ones that double a tensor's HBM traffic and drag the
surrounding fusion to f32 VPU throughput.
"""

from __future__ import annotations

import re

# convert ops in StableHLO text:
#   %5 = stablehlo.convert %4 : (tensor<2x64x96x256xbf16>) -> tensor<...xf32> loc(#loc123)
_CONVERT_RE = re.compile(
    r"stablehlo\.convert\s+%[\w.#]+\s*:\s*"
    r"\(tensor<([0-9x]*?)x?bf16>\)\s*->\s*tensor<[0-9x]*?x?f32>"
    r"(?:\s+loc\((#?\w+|\"[^\"]*\".*?)\))?")
# location table entries at the bottom of a debug_info=True module:
#   #loc123 = loc("jit(_train_step_impl)/convert_element_type"(#loc7))
_LOCDEF_RE = re.compile(r"^(#\w+)\s*=\s*loc\((.*)\)\s*$", re.M)
_LOCNAME_RE = re.compile(r"\"([^\"]+)\"")

# scope substrings whose bf16->f32 converts are expected and justified —
# annotated in the report, never counted as conv-stack suspects
JUSTIFIED = (
    ("batch_norm", "f32 BN statistics (SyncBN numerics)"),
    ("/bn", "f32 BN statistics (SyncBN numerics)"),
    ("_bn", "f32 BN statistics (SyncBN numerics)"),
    ("loss", "loss graph is f32 by design"),
    ("ssim", "loss graph is f32 by design"),
    ("adam", "f32 optimizer math"),
    ("opt", "f32 optimizer math"),
    ("transpose(jvp", "autodiff of an f32 region"),
    # the decoder module's OWN top-level convert (not one inside a sublayer):
    # the final [S,H,W,4] mpi outputs widening into the f32 loss boundary
    ("decoder/convert_element_type", "decoder output -> f32 loss boundary"),
)


def _elements(shape_str: str) -> int:
    n = 1
    for d in shape_str.split("x"):
        if d:
            n *= int(d)
    return n


def _loc_names(text: str):
    """#locN -> innermost quoted name (resolving one level of nesting)."""
    raw = dict(_LOCDEF_RE.findall(text))
    names = {}
    for key, body in raw.items():
        m = _LOCNAME_RE.search(body)
        if m is None:  # alias like #loc5 = loc(#loc3)
            ref = re.search(r"#\w+", body)
            body2 = raw.get(ref.group(0), "") if ref else ""
            m = _LOCNAME_RE.search(body2)
        names[key] = m.group(1) if m else "?"
    return names


def collect_upcasts(stablehlo_text: str):
    """All bf16->f32 converts in a StableHLO module.

    Returns a list of dicts {shape: str, elements: int, scope: str}; scope
    is the jax name-stack string when the module was lowered with
    debug_info=True, else "?".
    """
    loc_names = _loc_names(stablehlo_text)
    out = []
    for m in _CONVERT_RE.finditer(stablehlo_text):
        shape, loc = m.group(1), m.group(2)
        if loc is None:
            scope = "?"
        elif loc.startswith("#"):
            scope = loc_names.get(loc, "?")
        else:
            nm = _LOCNAME_RE.search(loc)
            scope = nm.group(1) if nm else "?"
        # drop the shared jit(...)/jit(main)/ prefix — pure column noise
        scope = re.sub(r"^(jit\([^)]*\)/)+", "", scope)
        out.append({"shape": shape or "scalar",
                    "elements": _elements(shape),
                    "scope": scope})
    return out


def justification(scope: str) -> str:
    s = scope.lower()
    for pat, why in JUSTIFIED:
        if pat in s:
            return why
    return ""


_CONV_STACK_RE = re.compile(r"conv(?!ert)|resnet|decoder|encoder")


def in_conv_stack(scope: str) -> bool:
    """Scopes inside the encoder/decoder conv stacks (the model forward),
    where a widening convert means bf16 discipline was lost. `conv(?!ert)`:
    every convert op's own scope component spells "convert_element_type",
    which must not read as a conv layer."""
    return _CONV_STACK_RE.search(scope.lower()) is not None


def suspects(upcasts):
    """The converts worth failing on: inside a conv-stack scope AND not
    covered by a JUSTIFIED annotation."""
    return [u for u in upcasts
            if in_conv_stack(u["scope"]) and not justification(u["scope"])]


def summarize(upcasts, top: int = 25) -> str:
    if not upcasts:
        return ("no bf16->f32 converts found "
                "(f32-only program, or bf16 never widened)")
    groups = {}
    for u in upcasts:
        key = (u["scope"], u["shape"])
        g = groups.setdefault(key, {"count": 0, "elements": 0})
        g["count"] += 1
        g["elements"] += u["elements"]
    rows = sorted(groups.items(), key=lambda kv: -kv[1]["elements"])
    total_el = sum(u["elements"] for u in upcasts)
    out = ["bf16 -> f32 convert_element_type report: %d converts, %.2f M "
           "elements total" % (len(upcasts), total_el / 1e6),
           "  %-12s %6s %10s  %-40s %s"
           % ("shape", "count", "elements", "scope", "why")]
    for (scope, shape), g in rows[:top]:
        out.append("  %-12s %6d %10d  %-40s %s"
                   % (shape[:12], g["count"], g["elements"], scope[:40],
                      justification(scope)))
    if len(rows) > top:
        out.append("  ... %d more groups (--top to widen)" % (len(rows) - top))

    bad = suspects(upcasts)
    if bad:
        el = sum(u["elements"] for u in bad)
        out.append("CONV-STACK SUSPECTS: %d converts / %.2f M elements widen "
                   "bf16 activations inside encoder/decoder scopes — chase "
                   "these first" % (len(bad), el / 1e6))
    else:
        out.append("conv-stack: clean (every convert is outside the "
                   "encoder/decoder scopes or justified)")
    return "\n".join(out)


def stablehlo_text(lowered) -> str:
    """StableHLO text WITH the loc table (name-stack scopes) for a
    jax.stages.Lowered. The MLIR asm printer is the one path that emits
    debug locations on this jax version; Lowered.as_text() drops them —
    the fallback still counts converts, but every scope reads "?"."""
    try:
        return lowered.compiler_ir(dialect="stablehlo").operation.get_asm(
            enable_debug_info=True, large_elements_limit=8)
    except Exception:  # pragma: no cover - printer fallback
        return lowered.as_text()
