"""Static program analysis: jaxpr/StableHLO lints + runtime sanitizers.

The hot paths of this repo are stock-op XLA programs, so the regressions
that hurt are silent program-level ones — dtype upcasts, recompile churn,
accidental host syncs, dropped donation, and (host-side) lock-order bugs in
the serve threads. This package turns the one-off checks that used to live
in `tools/dtype_audit.py` and per-test dot-count asserts into a pass
framework with checked-in baselines and a loud CI gate
(`tools/audit.py --gate`, run by `tools/verify_tier1.sh`):

  flops.py      jaxpr walkers: dot_general counts / FLOPs / blur-einsum
                counts (the shared source of truth the tests assert with)
  dtype.py      StableHLO bf16->f32 upcast collection + report (the old
                tools/dtype_audit.py internals; the CLI is now a shim)
  locks.py      rank-ordered lock/condition wrappers + the global
                acquisition order for the serve/telemetry threads, plus
                thread-leak helpers (stdlib-only; no jax, no mine_tpu)
  programs.py   the registry of core jitted programs at canonical CPU
                shapes (train step, fused loss fwd/bwd, five warp
                backends, serve render single-device + mesh, eval_encode)
  framework.py  AuditPass / PassResult / run_audit + baseline file IO
                (tools/analysis_baseline.json)
  passes.py     the six registered passes, each with a seeded-violation
                selftest proving it actually detects its failure mode

Imports are lazy (PEP 562): `mine_tpu.analysis.locks` must be importable
from telemetry/serve modules without dragging in `programs` (which imports
the train and serve stacks and would create an import cycle).
"""

_SUBMODULES = ("dtype", "flops", "framework", "locks", "passes", "programs")


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib
        return importlib.import_module(f"mine_tpu.analysis.{name}")
    raise AttributeError(f"module 'mine_tpu.analysis' has no attribute "
                         f"{name!r}")


__all__ = list(_SUBMODULES)
