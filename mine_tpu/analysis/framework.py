"""Audit pass framework: AuditPass / PassResult / run_audit + baseline IO.

A pass inspects one program (jaxpr, StableHLO text, or an instrumented
execution) and returns a PassResult; `run_audit` crosses the registered
pass suite with the program registry and collects every result. Global
passes (scope="global", e.g. the concurrency lint) run once per audit
instead of once per program.

Budgets live in ONE checked-in file, tools/analysis_baseline.json, with the
same update discipline as tools/tier1_baseline.txt: `tools/audit.py
--update-baseline` rewrites it only from a green measurement run, in the
same commit as the intentional program change (with a CHANGES.md line
saying why). A budget mismatch in either direction fails — a silent FLOP
DROP is as suspicious as growth (an optimization landed untested, or a
term went missing).

Every pass implements `selftest()`: build a seeded violation fixture, run
the pass's detection logic on it, and return the (necessarily failing)
PassResult — `tools/audit.py --selftest` asserts each one fails, proving
the lint actually detects what it claims to.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

BASELINE_SCHEMA = "mtpu-audit1"
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE_PATH = os.path.join(REPO_ROOT, "tools",
                                     "analysis_baseline.json")


@dataclasses.dataclass
class PassResult:
    pass_name: str
    program: str           # program name, or "-" for global passes
    ok: bool
    details: str = ""
    data: Dict = dataclasses.field(default_factory=dict)
    skipped: bool = False

    def line(self) -> str:
        status = "SKIP" if self.skipped else ("ok" if self.ok else "FAIL")
        head = f"[{status:>4}] {self.pass_name:<16} {self.program:<20}"
        if not self.details:
            return head
        first, *rest = self.details.splitlines()
        out = f"{head} {first}"
        for r in rest:
            out += "\n" + " " * 8 + r
        return out


class AuditPass:
    """Base pass. Subclasses set `name`, implement `run(program)` and
    `selftest()`, and may narrow `applies_to`."""

    name = "abstract"
    scope = "program"  # or "global"

    def applies_to(self, program) -> bool:
        return True

    def run(self, program) -> PassResult:  # pragma: no cover - interface
        raise NotImplementedError

    def run_global(self) -> PassResult:  # pragma: no cover - interface
        raise NotImplementedError

    def selftest(self) -> PassResult:  # pragma: no cover - interface
        raise NotImplementedError

    def _result(self, program, ok: bool, details: str = "",
                **data) -> PassResult:
        pname = program if isinstance(program, str) else program.name
        return PassResult(pass_name=self.name, program=pname, ok=ok,
                          details=details, data=data)

    def _skip(self, program, why: str) -> PassResult:
        r = self._result(program, ok=True, details=why)
        r.skipped = True
        return r


# ------------------------------------------------------------- baseline IO

def load_baseline(path: str = DEFAULT_BASELINE_PATH) -> Dict:
    """Checked-in budget file; a missing file returns an empty skeleton so
    the budget pass can say 'run --update-baseline' per program instead of
    crashing the whole audit."""
    if not os.path.exists(path):
        return {"schema": BASELINE_SCHEMA, "programs": {}, "budgets": {},
                "cost": {}}
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: unknown baseline schema {data.get('schema')!r} "
            f"(expected {BASELINE_SCHEMA!r})")
    data.setdefault("programs", {})
    data.setdefault("budgets", {})
    # compiled-executable cost/memory budgets (costmodel.py) live in their
    # own section: the dot_budget pass diffs the full key set of each
    # "programs" entry, so cost keys must not leak into it
    data.setdefault("cost", {})
    return data


def save_baseline(data: Dict, path: str = DEFAULT_BASELINE_PATH) -> None:
    data = dict(data)
    data["schema"] = BASELINE_SCHEMA
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------- running

def run_audit(programs: List, passes: List[AuditPass]) -> List[PassResult]:
    """Cross the pass suite with the programs. Per-program passes run for
    every program they apply to; global passes run once, last (so e.g. the
    concurrency lint's thread-leak check isn't confused by lazily-built
    program state mid-audit)."""
    results: List[PassResult] = []
    for p in passes:
        if p.scope == "global":
            continue
        for prog in programs:
            if not p.applies_to(prog):
                continue
            try:
                results.append(p.run(prog))
            except Exception as e:  # a crashing pass is a failing pass
                results.append(PassResult(
                    pass_name=p.name, program=prog.name, ok=False,
                    details=f"pass crashed: {type(e).__name__}: {e}"))
    for p in passes:
        if p.scope != "global":
            continue
        try:
            results.append(p.run_global())
        except Exception as e:
            results.append(PassResult(
                pass_name=p.name, program="-", ok=False,
                details=f"pass crashed: {type(e).__name__}: {e}"))
    return results


def format_report(results: List[PassResult]) -> str:
    lines = [r.line() for r in results]
    n_fail = sum(1 for r in results if not r.ok)
    n_skip = sum(1 for r in results if r.skipped)
    lines.append(f"audit: {len(results)} checks, {n_fail} failed, "
                 f"{n_skip} skipped")
    return "\n".join(lines)
