"""Jaxpr walkers for contraction budgets: dot counts, dot FLOPs, blur dots.

These used to live as private helpers inside tests/test_fused_loss.py and
tests/test_warp_separable.py; the FLOP-budget pass (passes.py) and those
tests now share this single implementation, and the numeric gates live in
tools/analysis_baseline.json instead of inline test constants.

All walkers recurse into sub-jaxprs found in eqn params (pjit bodies, cond
branches, scan/while carries, custom_vjp calls), so counting a jitted
function's jaxpr and counting its unjitted body agree.
"""

from __future__ import annotations

import numpy as np


def _jaxpr_of(j):
    """Accept a ClosedJaxpr, a Jaxpr, or anything carrying `.jaxpr`."""
    inner = getattr(j, "jaxpr", j)
    # ClosedJaxpr.jaxpr is a Jaxpr; a Jaxpr has .eqns directly
    return getattr(inner, "jaxpr", inner)


def iter_eqns(jaxpr):
    """Yield every eqn in `jaxpr` and, recursively, in any sub-jaxpr held
    by an eqn's params (the walker idiom shared by all passes)."""
    jaxpr = _jaxpr_of(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    yield from iter_eqns(inner)


def count_dots(jaxpr) -> int:
    """Number of dot_general eqns in the program (static count: a dot
    inside a scan body counts once — the budget tracks program structure;
    `dot_flops` weights trip counts)."""
    return sum(1 for eqn in iter_eqns(jaxpr)
               if eqn.primitive.name == "dot_general")


def dot_flops(jaxpr, mult: int = 1) -> int:
    """Sum dot_general FLOPs (2 * batch * lhs_free * rhs_free * contract),
    recursing into sub-jaxprs; scan bodies multiply by the trip count."""
    jaxpr = _jaxpr_of(jaxpr)
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval.shape
            rhs = eqn.invars[1].aval.shape
            batch = int(np.prod([lhs[i] for i in lb], initial=1))
            contract = int(np.prod([lhs[i] for i in lc], initial=1))
            lfree = int(np.prod([lhs[i] for i in range(len(lhs))
                                 if i not in tuple(lc) + tuple(lb)],
                                initial=1))
            rfree = int(np.prod([rhs[i] for i in range(len(rhs))
                                 if i not in tuple(rc) + tuple(rb)],
                                initial=1))
            total += 2 * mult * batch * contract * lfree * rfree
            continue
        m = mult
        if eqn.primitive.name == "scan":
            m = mult * int(eqn.params["length"])
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    total += dot_flops(inner, m)
    return total


def count_blur_dots(jaxpr, sizes=(64, 32, 16, 8)) -> int:
    """dot_generals attributable to SSIM blurs: a Toeplitz blur einsum is
    the only contraction in the loss graph whose operand is a square 2-D
    matrix sized like a pyramid level (everything else contracts [B,3,3]
    intrinsics-style batches or non-square grids)."""
    n = 0
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        for var in eqn.invars:
            shape = var.aval.shape
            if (len(shape) == 2 and shape[0] == shape[1]
                    and shape[0] in sizes):
                n += 1
                break
    return n
