"""Config/env-driven fault injection — the chaos-test seams.

Production TPU runs die to preemption, transient data corruption, and
numeric blow-ups; the resilience layer (train/resilience.py, the step
guard in train/step.py, the checkpoint fallback chain, the pipeline
retry/respawn paths) exists to absorb those. This module injects each
failure mode on demand so the chaos suite (tests/test_chaos.py,
tools/chaos_soak.py) can drive the recovery paths end-to-end:

  * ``nan_grads_at_step`` / ``nan_grads_from_step`` — poison the
    gradients inside the jitted train step (consulted at TRACE time by
    SynthesisTrainer, so the injection itself costs no host sync).
  * ``sigterm_at_step`` — deliver SIGTERM to our own pid when the host
    loop reaches that global step (exercises the preemption-safe
    shutdown: flag -> all-host agreement -> emergency checkpoint).
  * ``item_raise_index`` / ``item_raise_times`` — a dataset item whose
    load raises; times=k makes it transient (first k loads fail, then
    heal — the retry path), times=-1 makes it persistent (the
    quarantine path).
  * ``kill_worker_at_call`` — the nth item load (1-based, counted
    across all workers) raises WorkerKill, a BaseException that skips
    the per-item retry/quarantine machinery and kills the assembler
    thread outright (the worker-respawn path).

Serve-side seams (tests/test_serve_resilience.py, tools/serve_chaos_soak.py
drive the PR-11 self-protecting-serving layer through them):

  * ``encode_raise_times`` — the first k synchronous encodes raise
    InjectedEncodeError (transient: exercises the engine's bounded
    retry-with-backoff path; a large k exhausts the retries).
  * ``shard_kill`` / ``shard_kill_heal_after`` — placements on that cache
    shard raise InjectedShardError until ``heal_after`` failures have been
    injected (-1 = never heals): the shard-failover path — consecutive
    failures mark the shard dead, its key range re-routes, and
    ``ShardedPlaneCache.mark_alive`` re-adopts it after the heal.
  * ``slow_render_ms`` — host-side sleep before every render dispatch
    (builds queue depth for the admission/deadline paths).
  * ``queue_flood`` — a burst size the soak/test harness reads via
    ``queue_flood_n`` and submits as one instantaneous tier-0 flood.

Transport seams (the wire-hardening layer: serve/hostnet.py HostClient
calls ``net_request``/``net_truncate`` on every wire attempt, so network
chaos never monkeypatches hostnet):

  * ``net_latency_ms`` — client-side sleep before every wire attempt
    (a slow link; builds toward the split read timeout).
  * ``net_refuse_times`` — the first k wire attempts raise
    ConnectionRefusedError (a vanished host: nothing is listening).
  * ``net_drop_every`` — every Nth wire attempt (global counter) raises
    ConnectionResetError mid-request: the flaky link the client's
    bounded retry must absorb. Deterministic — two consecutive attempts
    of one request can never both land on the modulus.
  * ``net_truncate_times`` — the first k responses are truncated
    mid-body. Format-aware damage (PR 20): a JSON response raises
    IncompleteRead as before, while an ``mtpu-wire1`` binary frame is
    CUT IN HALF and handed up, so the frame decoder's truncated-frame
    tripwire must reject it (WireError) — either way the client's
    bounded retry re-requests, proving corruption is retried not
    crashed on.
  * ``net_partition`` — an asymmetric partition matrix as a
    comma-separated list of directed ``src>dst`` links to sever
    (``"h1>n2,h2>n1"``: the fronts named h1/h2 cannot reach the hosts
    named n2/n1, while every unlisted pair — e.g. an external front —
    connects normally). Matching is by the client's (net_src, net_name)
    identity pair; severed links raise ConnectionRefusedError.

The plan comes from ``set_plan`` (tests), the MINE_TPU_FAULTS env var
(subprocess legs of the chaos soak), or a config's ``testing.fault_plan``
JSON (train_cli). With no plan active every hook is a cheap no-op, so the
seams can stay in the production paths permanently.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time
from typing import Dict, Optional

ENV_VAR = "MINE_TPU_FAULTS"


class WorkerKill(BaseException):
    """Kills an assembler worker thread outright (not an Exception, so the
    per-item retry and the worker's error-recording handler both pass it
    through) — simulates a worker dying mid-assembly."""


class InjectedItemError(ValueError):
    """The injected per-item load failure (transient or persistent)."""


class InjectedEncodeError(RuntimeError):
    """The injected synchronous-encode failure (the engine retry path)."""


class InjectedShardError(RuntimeError):
    """The injected cache-shard placement failure (the failover path)."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """-1 disables a fault everywhere below."""
    nan_grads_at_step: int = -1    # poison grads at exactly this state.step
    nan_grads_from_step: int = -1  # poison grads at every state.step >= this
    sigterm_at_step: int = -1      # SIGTERM own pid at this host global step
    item_raise_index: int = -1     # dataset index whose load raises
    item_raise_times: int = -1     # -1: always; k>0: first k loads only
    kill_worker_at_call: int = -1  # nth item load (1-based) dies WorkerKill
    encode_raise_times: int = -1   # first k sync encodes raise (transient)
    shard_kill: int = -1           # cache shard whose placements fail
    shard_kill_heal_after: int = -1  # injected failures before it heals
    slow_render_ms: int = -1       # host sleep before each render dispatch
    queue_flood: int = -1          # burst size the soak reads (queue_flood_n)
    net_latency_ms: int = -1       # client sleep before each wire attempt
    net_refuse_times: int = -1     # first k wire attempts refused
    net_drop_every: int = -1       # every Nth wire attempt resets mid-request
    net_truncate_times: int = -1   # first k responses truncated mid-body
    net_partition: str = ""        # severed "src>dst" links, comma-separated

    @property
    def active(self) -> bool:
        # int faults disable at -1, string faults at "" — any other value
        # anywhere arms the plan
        return any(v not in (-1, "")
                   for v in dataclasses.asdict(self).values())


_lock = threading.Lock()
_plan: Optional[FaultPlan] = None
_counts: Dict[str, int] = {}


def set_plan(plan: Optional[FaultPlan]):
    """Install (or clear, with None) the active plan; resets fault counters."""
    global _plan
    with _lock:
        _plan = plan if (plan is not None and plan.active) else None
        _counts.clear()


def get_plan() -> Optional[FaultPlan]:
    return _plan


def plan_from_env(environ=None) -> Optional[FaultPlan]:
    """MINE_TPU_FAULTS='{"sigterm_at_step": 5, ...}' -> FaultPlan."""
    raw = (environ or os.environ).get(ENV_VAR, "")
    if not raw:
        return None
    return plan_from_spec(json.loads(raw))


def plan_from_spec(spec) -> Optional[FaultPlan]:
    """dict or JSON string -> FaultPlan; unknown keys raise (typo guard)."""
    if spec in (None, "", {}):
        return None
    if isinstance(spec, str):
        spec = json.loads(spec)
    fields = {f.name: f for f in dataclasses.fields(FaultPlan)}
    unknown = set(spec) - set(fields)
    if unknown:
        raise KeyError(f"unknown fault plan keys: {sorted(unknown)} "
                       f"(known: {sorted(fields)})")
    # coerce by declared field type: int faults take counts/steps, string
    # faults (the partition matrix) pass through verbatim
    return FaultPlan(**{k: (str(v) if fields[k].type in ("str", str)
                            else int(v))
                        for k, v in spec.items()})


def activate(config=None):
    """Install the env plan (wins) or the config's testing.fault_plan."""
    plan = plan_from_env()
    if plan is None and config is not None:
        plan = plan_from_spec(config.get("testing.fault_plan"))
    if plan is not None:
        set_plan(plan)
    return plan


# ---------------- hooks (no-ops without an active plan) ----------------

def on_item_load(index: int):
    """Called by data/common.load_item before every get_pair. Raises per
    plan; the global call counter feeds kill_worker_at_call."""
    plan = _plan
    if plan is None:
        return
    with _lock:
        call = _counts.get("item_calls", 0) + 1
        _counts["item_calls"] = call
        if call == plan.kill_worker_at_call:
            raise WorkerKill(f"injected worker kill at item load #{call}")
        if index == plan.item_raise_index:
            seen = _counts.get("item_fails", 0)
            if plan.item_raise_times < 0 or seen < plan.item_raise_times:
                _counts["item_fails"] = seen + 1
                raise InjectedItemError(
                    f"injected load failure for item {index} "
                    f"(occurrence {seen + 1})")


def nan_grad_window() -> Optional[tuple]:
    """(at_step, from_step) for the trainer's trace-time injection, or None.
    Read once at SynthesisTrainer construction — set the plan BEFORE
    building the trainer."""
    plan = _plan
    if plan is None:
        return None
    if plan.nan_grads_at_step < 0 and plan.nan_grads_from_step < 0:
        return None
    return (plan.nan_grads_at_step, plan.nan_grads_from_step)


def maybe_sigterm(gstep: int):
    """Host-loop hook: deliver SIGTERM to our own pid once when gstep
    reaches the planned step (the preemption drill)."""
    plan = _plan
    if plan is None or plan.sigterm_at_step < 0:
        return
    with _lock:
        if gstep >= plan.sigterm_at_step and not _counts.get("sigterm_sent"):
            _counts["sigterm_sent"] = 1
        else:
            return
    os.kill(os.getpid(), signal.SIGTERM)


def on_encode(image_id: str = ""):
    """Called by the engine at the top of every synchronous-encode attempt
    (serve/engine.py _entry). The first `encode_raise_times` attempts raise
    — PROCESS-wide, not per-image, so a retry loop sees consecutive
    transient failures exactly like a flaky encoder would produce."""
    plan = _plan
    if plan is None or plan.encode_raise_times < 0:
        return
    with _lock:
        seen = _counts.get("encode_fails", 0)
        if seen >= plan.encode_raise_times:
            return
        _counts["encode_fails"] = seen + 1
    raise InjectedEncodeError(
        f"injected sync-encode failure #{seen + 1} "
        f"(image {str(image_id)[:12]})")


def on_shard_put(shard: int):
    """Called by ShardedPlaneCache.put with the target shard before the
    placement lands. Placements on `shard_kill` fail until
    `shard_kill_heal_after` failures have been injected (-1: never heals) —
    the consecutive-failure signal that marks a shard dead."""
    plan = _plan
    if plan is None or plan.shard_kill < 0 or shard != plan.shard_kill:
        return
    with _lock:
        n = _counts.get("shard_put_fails", 0)
        if 0 <= plan.shard_kill_heal_after <= n:
            return  # healed: further placements succeed
        _counts["shard_put_fails"] = n + 1
    raise InjectedShardError(
        f"injected placement failure on shard {shard} (#{n + 1})")


def on_render():
    """Called by the engine before each render dispatch; sleeps
    `slow_render_ms` to simulate a slow device call (queue pressure for
    the admission / deadline paths)."""
    plan = _plan
    if plan is None or plan.slow_render_ms < 0:
        return
    time.sleep(plan.slow_render_ms / 1e3)


def net_request(src: str, dst: str):
    """Called by HostClient at the top of EVERY wire attempt with the
    client's identity pair (net_src, net_name). Raises the planned
    transport failure — partition first (a severed link refuses before
    anything else can happen), then bounded refusals, then latency, then
    the deterministic every-Nth drop — so one seam drives every network
    failure mode the hardened client must absorb."""
    plan = _plan
    if plan is None:
        return
    if plan.net_partition:
        links = {tuple(p.split(">", 1))
                 for p in plan.net_partition.split(",") if ">" in p}
        if (src, dst) in links:
            raise ConnectionRefusedError(
                f"injected partition: link {src}>{dst} severed")
    if plan.net_refuse_times >= 0:
        with _lock:
            n = _counts.get("net_refused", 0)
            if n < plan.net_refuse_times:
                _counts["net_refused"] = n + 1
                raise ConnectionRefusedError(
                    f"injected connection refusal #{n + 1} ({src}->{dst})")
    if plan.net_latency_ms > 0:
        time.sleep(plan.net_latency_ms / 1e3)
    if plan.net_drop_every > 0:
        with _lock:
            call = _counts.get("net_calls", 0) + 1
            _counts["net_calls"] = call
        if call % plan.net_drop_every == 0:
            raise ConnectionResetError(
                f"injected mid-request drop (wire attempt #{call})")


def net_truncate() -> bool:
    """Called by HostClient after reading a response body; True means this
    response must be treated as truncated mid-body (the first
    `net_truncate_times` responses only — a retry then reads it whole)."""
    plan = _plan
    if plan is None or plan.net_truncate_times < 0:
        return False
    with _lock:
        n = _counts.get("net_truncated", 0)
        if n >= plan.net_truncate_times:
            return False
        _counts["net_truncated"] = n + 1
    return True


def queue_flood_n() -> int:
    """Burst size for the soak/test harness's instantaneous tier-0 flood
    (the harness submits; this just carries the number through the same
    plan plumbing as every other fault)."""
    plan = _plan
    if plan is None or plan.queue_flood < 0:
        return 0
    return plan.queue_flood


# ---------------- checkpoint corruption (test/soak helper) ----------------

def truncate_checkpoint(path: str, keep_files: int = 1):
    """Corrupt a checkpoint directory the way a mid-write crash does: keep
    the first `keep_files` entries (sorted), truncate one survivor to half
    its bytes, delete the rest. Works on the nested orbax layout."""
    entries = []
    for root, _, files in os.walk(path):
        entries.extend(os.path.join(root, f) for f in files)
    entries.sort()
    if not entries:
        raise FileNotFoundError(f"no files under checkpoint dir {path}")
    for f in entries[keep_files:]:
        os.remove(f)
    victim = entries[0]
    size = os.path.getsize(victim)
    with open(victim, "r+b") as fh:
        fh.truncate(size // 2)
