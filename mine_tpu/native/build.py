"""Build the native data-IO library: `python -m mine_tpu.native.build`.

One translation unit, no build system needed — g++ -O3 -shared against the
libjpeg/libpng the image ships. The wrapper (mine_tpu.native) loads the
resulting .so from this directory and silently falls back to PIL when it is
absent, so building is an optimization, never a requirement.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "dataio.cpp")
OUT = os.path.join(HERE, "libmtio.so")


def build(verbose: bool = True) -> str:
    """Compile dataio.cpp -> libmtio.so; returns the .so path."""
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           SRC, "-o", OUT, "-ljpeg", "-lpng", "-lz"]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    build()
