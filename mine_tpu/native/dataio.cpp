// Native data-IO for mine_tpu: JPEG/PNG decode + PIL-compatible bicubic
// resize + a threaded batch loader.
//
// This is the framework's counterpart of the native decode path the
// reference gets from torch's DataLoader workers (train.py:88-99 —
// num_workers subprocesses each running PIL-on-libjpeg): dataset classes
// call mine_tpu.native.load_image_rgb()/load_batch_rgb(), which land here
// via ctypes, decode with libjpeg/libpng directly, resample with the same
// separable filtered-bicubic PIL uses, and fan a batch across C++ threads
// (no GIL, no worker processes, no pickling).
//
// Output contract: float32 RGB, HWC, [0,1] — exactly what the loaders cache
// (data/llff.py). Resampling matches PIL's ImagingResample BICUBIC (Keys
// a=-0.5, support 2, filter scaled on downsample = antialias) to within
// uint8 rounding; tests/test_native_io.py gates this against PIL itself.
//
// Build: python -m mine_tpu.native.build  (g++ -O3 -shared, links
// libjpeg/libpng which the image ships; the Python wrapper falls back to
// PIL when the .so is absent so no build step is ever required).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

// ------------------------------------------------------------ decoding

// Refuse absurd header-claimed sizes before allocating (a corrupt file
// must produce a decode error, not a bad_alloc crossing the C ABI).
constexpr size_t kMaxPixels = size_t(64) * 1024 * 1024;

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
  bool warned;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  auto* mgr = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  std::longjmp(mgr->jump, 1);
}

void jpeg_emit_message(j_common_ptr cinfo, int msg_level) {
  // msg_level -1 is a corruption warning (e.g. premature EOF, bad marker):
  // libjpeg would "recover" by fabricating gray scanlines. PIL raises for
  // such files; we flag them so the decode reports failure (-> PIL path,
  // which then raises the same error the pure-PIL pipeline did).
  if (msg_level < 0)
    reinterpret_cast<JpegErrorMgr*>(cinfo->err)->warned = true;
}

// Decode a JPEG file to RGB8. Returns false on any decode error or
// corruption warning. NOTE: no object with a nontrivial destructor may be
// live between setjmp and the longjmp-ing calls (UB otherwise) — `out` is
// caller-owned and scanlines are read one at a time into it directly.
bool decode_jpeg(FILE* f, std::vector<uint8_t>* out, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = jpeg_error_exit;
  err.pub.emit_message = jpeg_emit_message;
  err.warned = false;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // converts grayscale/YCbCr to RGB
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  if (size_t(*w) * *h > kMaxPixels) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  out->resize(size_t(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() + size_t(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return !err.warned;
}

// Decode a PNG file to RGB8 via libpng's simplified API. Alpha handling
// must match PIL's convert("RGB"), which DROPS the alpha channel (keeps
// the raw RGB values) — so read RGBA and strip, never let libpng
// composite over a background.
bool decode_png(const char* path, std::vector<uint8_t>* out, int* w, int* h) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_file(&image, path)) return false;
  image.format = PNG_FORMAT_RGBA;
  // PIL ignores gAMA/iCCP at decode; suppress libpng's to-sRGB conversion
  // so files with gamma chunks decode to the same raw samples PIL returns
  image.flags |= PNG_IMAGE_FLAG_COLORSPACE_NOT_sRGB;
  *w = image.width;
  *h = image.height;
  if (size_t(*w) * *h > kMaxPixels) {
    png_image_free(&image);
    return false;
  }
  std::vector<uint8_t> rgba(PNG_IMAGE_SIZE(image));
  if (!png_image_finish_read(&image, nullptr, rgba.data(), 0, nullptr)) {
    png_image_free(&image);
    return false;
  }
  out->resize(size_t(*w) * *h * 3);
  const uint8_t* src = rgba.data();
  uint8_t* dst = out->data();
  for (size_t i = 0, n = size_t(*w) * *h; i < n; ++i) {
    dst[0] = src[0];
    dst[1] = src[1];
    dst[2] = src[2];
    dst += 3;
    src += 4;
  }
  return true;
}

// File-type sniff + decode. RGB8 HWC output.
bool decode_file(const char* path, std::vector<uint8_t>* out, int* w, int* h) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  unsigned char magic[8] = {0};
  size_t got = std::fread(magic, 1, 8, f);
  if (got < 3) {
    std::fclose(f);
    return false;
  }
  bool ok = false;
  if (magic[0] == 0xFF && magic[1] == 0xD8) {
    std::rewind(f);
    ok = decode_jpeg(f, out, w, h);
    std::fclose(f);
  } else if (magic[0] == 0x89 && magic[1] == 'P' && magic[2] == 'N') {
    std::fclose(f);  // simplified libpng API reopens by path
    ok = decode_png(path, out, w, h);
  } else {
    std::fclose(f);
  }
  return ok;
}

// ------------------------------------------------------ PIL-style resize

// Keys bicubic, a = -0.5 (PIL _imaging.c bicubic_filter), support 2.
double bicubic_kernel(double x) {
  constexpr double a = -0.5;
  x = std::fabs(x);
  if (x < 1.0) return ((a + 2.0) * x - (a + 3.0)) * x * x + 1.0;
  if (x < 2.0) return (((x - 5.0) * x + 8.0) * x - 4.0) * a;
  return 0.0;
}

// Per-output-pixel filter weights, PIL ImagingResampleHorizontal's scheme:
// center = (i+0.5)*scale; on downsample the filter is stretched by the
// scale (antialias); weights are normalized to sum 1.
struct FilterTable {
  int support;                 // max taps per output pixel
  std::vector<int> bounds;     // [out, 2]: (xmin, count)
  std::vector<double> weights; // [out, support]
};

FilterTable build_filter(int in_size, int out_size) {
  FilterTable t;
  double scale = double(in_size) / out_size;
  double filterscale = std::max(scale, 1.0);
  double support = 2.0 * filterscale;
  t.support = int(std::ceil(support)) * 2 + 1;
  t.bounds.resize(size_t(out_size) * 2);
  t.weights.assign(size_t(out_size) * t.support, 0.0);
  for (int i = 0; i < out_size; ++i) {
    double center = (i + 0.5) * scale;
    int xmin = std::max(0, int(center - support + 0.5));
    int xmax = std::min(in_size, int(center + support + 0.5));
    int count = xmax - xmin;
    double sum = 0.0;
    for (int j = 0; j < count; ++j) {
      double w = bicubic_kernel((j + xmin - center + 0.5) / filterscale);
      t.weights[size_t(i) * t.support + j] = w;
      sum += w;
    }
    if (sum != 0.0)
      for (int j = 0; j < count; ++j)
        t.weights[size_t(i) * t.support + j] /= sum;
    t.bounds[size_t(i) * 2] = xmin;
    t.bounds[size_t(i) * 2 + 1] = count;
  }
  return t;
}

inline uint8_t clamp_round_u8(double v) {
  // PIL stores each pass to uint8: round-half-up, clip to [0,255]. The
  // bicubic kernel overshoots, so replicating this INTERMEDIATE quantization
  // is required for PIL parity (float intermediates diverge by up to ~0.05
  // on noisy images — measured, not hypothetical).
  v = std::floor(v + 0.5);
  return uint8_t(std::min(std::max(v, 0.0), 255.0));
}

// u8 RGB (h,w) -> f32 RGB (out_h,out_w) in [0,1]; separable two-pass with
// PIL's per-pass uint8 quantization.
void resize_u8_to_f32(const uint8_t* in, int w, int h,
                      int out_w, int out_h, float* out) {
  FilterTable fx = build_filter(w, out_w);
  FilterTable fy = build_filter(h, out_h);

  // horizontal pass: (h, w, 3) u8 -> (h, out_w, 3) u8
  std::vector<uint8_t> tmp(size_t(h) * out_w * 3);
  for (int y = 0; y < h; ++y) {
    const uint8_t* row = in + size_t(y) * w * 3;
    uint8_t* trow = tmp.data() + size_t(y) * out_w * 3;
    for (int x = 0; x < out_w; ++x) {
      int xmin = fx.bounds[size_t(x) * 2];
      int count = fx.bounds[size_t(x) * 2 + 1];
      const double* wp = &fx.weights[size_t(x) * fx.support];
      double acc[3] = {0, 0, 0};
      for (int j = 0; j < count; ++j) {
        const uint8_t* px = row + size_t(xmin + j) * 3;
        acc[0] += wp[j] * px[0];
        acc[1] += wp[j] * px[1];
        acc[2] += wp[j] * px[2];
      }
      trow[size_t(x) * 3 + 0] = clamp_round_u8(acc[0]);
      trow[size_t(x) * 3 + 1] = clamp_round_u8(acc[1]);
      trow[size_t(x) * 3 + 2] = clamp_round_u8(acc[2]);
    }
  }

  // vertical pass: (h, out_w, 3) u8 -> (out_h, out_w, 3) u8 -> f32 / 255
  for (int y = 0; y < out_h; ++y) {
    int ymin = fy.bounds[size_t(y) * 2];
    int count = fy.bounds[size_t(y) * 2 + 1];
    const double* wp = &fy.weights[size_t(y) * fy.support];
    float* orow = out + size_t(y) * out_w * 3;
    for (int x = 0; x < out_w * 3; ++x) {
      double acc = 0.0;
      for (int j = 0; j < count; ++j)
        acc += wp[j] * tmp[size_t(ymin + j) * out_w * 3 + x];
      orow[x] = float(clamp_round_u8(acc) / 255.0);
    }
  }
}

}  // namespace

extern "C" {

// Decode `path` (JPEG or PNG), bicubic-resize to (out_w, out_h), write
// float32 RGB HWC in [0,1] to `out` (len out_h*out_w*3). 0 on success.
// No C++ exception may cross the C ABI (ctypes caller -> std::terminate),
// so every failure — including allocation — becomes a nonzero rc and the
// Python wrapper's PIL fallback takes over.
// src_w/src_h (nullable) receive the pre-resize image dimensions, so
// callers that need them (intrinsics rescaling in the llff/dtu loaders)
// don't pay a second file open for a header probe.
int mtio_load_resize(const char* path, int out_w, int out_h, float* out,
                     int* src_w, int* src_h) {
  try {
    std::vector<uint8_t> rgb;
    int w = 0, h = 0;
    if (!decode_file(path, &rgb, &w, &h)) return 1;
    if (out_w <= 0 || out_h <= 0) return 1;
    if (src_w) *src_w = w;
    if (src_h) *src_h = h;
    resize_u8_to_f32(rgb.data(), w, h, out_w, out_h, out);
    return 0;
  } catch (...) {
    return 1;
  }
}

// Batch variant across `nthreads` C++ threads. out: [n, out_h, out_w, 3]
// f32; rcs[i]: 0 success / 1 decode error; src_dims (nullable): [n, 2]
// (w, h) pre-resize sizes.
void mtio_load_resize_batch(const char** paths, int n, int out_w, int out_h,
                            float* out, int nthreads, int* rcs,
                            int* src_dims) {
  std::atomic<int> next(0);
  size_t stride = size_t(out_h) * out_w * 3;
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1))
      rcs[i] = mtio_load_resize(
          paths[i], out_w, out_h, out + stride * i,
          src_dims ? src_dims + 2 * i : nullptr,
          src_dims ? src_dims + 2 * i + 1 : nullptr);
  };
  int k = std::max(1, std::min(nthreads, n));
  std::vector<std::thread> pool;
  pool.reserve(k - 1);
  for (int t = 1; t < k; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
}

// Resize a caller-provided u8 RGB buffer (e.g. a lenslet crop) to f32 [0,1].
int mtio_resize_u8(const uint8_t* in, int w, int h,
                   int out_w, int out_h, float* out) {
  try {
    if (w <= 0 || h <= 0 || out_w <= 0 || out_h <= 0) return 1;
    resize_u8_to_f32(in, w, h, out_w, out_h, out);
    return 0;
  } catch (...) {
    return 1;
  }
}

}  // extern "C"
