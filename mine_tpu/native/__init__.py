"""Native (C++) data-IO with a transparent PIL fallback.

The reference's image pipeline rides torch DataLoader worker processes
(train.py:88-99) — native decode via PIL's libjpeg, parallelism via
fork+pickle. Here the native path is in-process C++ (native/dataio.cpp):
libjpeg/libpng decode, PIL-compatible filtered-bicubic resize, and a
C++ thread pool for batches — no GIL, no worker processes. Loaders call
`load_image_rgb` / `load_batch_rgb` and never know which backend ran:

  * if `libmtio.so` exists (built with `python -m mine_tpu.native.build`),
    the C++ path runs;
  * otherwise PIL, bit-compatible to within uint8 rounding
    (tests/test_native_io.py gates both paths against each other).

Set MINE_TPU_NATIVE_IO=0 to force the PIL path (e.g. to triage a decode
difference).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libmtio.so")
_lib = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("MINE_TPU_NATIVE_IO") == "0":
        return None
    if not os.path.exists(_SO):
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.mtio_load_resize.restype = ctypes.c_int
    lib.mtio_load_resize.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.mtio_load_resize_batch.restype = None
    lib.mtio_load_resize_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.mtio_resize_u8.restype = ctypes.c_int
    lib.mtio_resize_u8.argtypes = [
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_float)]
    _lib = lib
    return _lib


def available() -> bool:
    """True when the C++ library is built and loadable."""
    return _load() is not None


def _pil_load(path: str, size: Tuple[int, int]) -> Tuple[np.ndarray,
                                                         Tuple[int, int]]:
    from PIL import Image as PILImage
    pil = PILImage.open(path).convert("RGB")
    src_size = pil.size
    pil = pil.resize(size, PILImage.BICUBIC)
    return np.asarray(pil, dtype=np.float32) / 255.0, src_size


def load_image_rgb(path: str, size: Tuple[int, int],
                   with_src_size: bool = False):
    """Decode + bicubic-resize to `size` (w, h): float32 HWC RGB in [0,1].

    The shared image path of every dataset loader (the decode half of
    nerf_dataset.py:79-81's cache fill). C++ when built, PIL otherwise.
    With `with_src_size` returns (img, (src_w, src_h)) — one file open
    serves loaders that rescale intrinsics by the original size.
    """
    w, h = size
    lib = _load()
    if lib is None:
        img, src = _pil_load(path, size)
        return (img, src) if with_src_size else img
    out = np.empty((h, w, 3), np.float32)
    sw, sh = ctypes.c_int(0), ctypes.c_int(0)
    rc = lib.mtio_load_resize(
        os.fsencode(path), w, h,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(sw), ctypes.byref(sh))
    if rc != 0:  # undecodable by the native path — let PIL raise/handle
        img, src = _pil_load(path, size)
        return (img, src) if with_src_size else img
    return (out, (sw.value, sh.value)) if with_src_size else out


def load_batch_rgb(paths: Sequence[str], size: Tuple[int, int],
                   num_threads: int = 0,
                   with_src_sizes: bool = False):
    """Decode + resize a batch: float32 [N, h, w, 3] in [0,1].

    C++ thread-pool when built (num_threads<=0: one per CPU); sequential
    PIL otherwise. With `with_src_sizes` also returns an int [N, 2] array
    of pre-resize (w, h) per image.
    """
    w, h = size
    n = len(paths)
    out = np.empty((n, h, w, 3), np.float32)
    dims = np.zeros((n, 2), np.int32)
    lib = _load()
    if lib is None or n == 0:
        for i, p in enumerate(paths):
            out[i], dims[i] = _pil_load(p, size)
        return (out, dims) if with_src_sizes else out
    if num_threads <= 0:
        num_threads = os.cpu_count() or 1
    rcs = np.zeros(n, np.int32)
    arr = (ctypes.c_char_p * n)(*[os.fsencode(p) for p in paths])
    lib.mtio_load_resize_batch(
        arr, n, w, h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        num_threads, rcs.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    for i in np.nonzero(rcs)[0]:
        out[i], dims[i] = _pil_load(paths[i], size)  # per-item fallback
    return (out, dims) if with_src_sizes else out


def resize_rgb_u8(img: np.ndarray, size: Tuple[int, int]) -> np.ndarray:
    """Bicubic-resize a uint8 HWC RGB array: float32 [h, w, 3] in [0,1].

    For loaders that crop before resizing (e.g. the flowers lenslet grid).
    """
    if img.dtype != np.uint8 or img.ndim != 3 or img.shape[2] != 3:
        raise ValueError(f"expected uint8 HWC RGB, got {img.dtype} "
                         f"{img.shape}")

    def pil_resize():
        from PIL import Image as PILImage
        pil = PILImage.fromarray(img).resize(size, PILImage.BICUBIC)
        return np.asarray(pil, dtype=np.float32) / 255.0

    w, h = size
    lib = _load()
    if lib is None:
        return pil_resize()
    img = np.ascontiguousarray(img)
    out = np.empty((h, w, 3), np.float32)
    rc = lib.mtio_resize_u8(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        img.shape[1], img.shape[0], w, h,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if rc != 0:  # native allocation/shape failure — same answer via PIL
        return pil_resize()
    return out
