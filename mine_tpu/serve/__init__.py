"""Encode-once MPI serving: quantized plane cache + render-only engine.

MINE predicts an MPI once per image; every novel view after that is warp +
composite only. This package is the serving-side realization of that
asymmetry (README "Serving"):

  cache.py    MPICache — LRU of quantized MPI planes under a byte budget
  engine.py   RenderEngine — shape-bucketed jitted render-only program
  batcher.py  MicroBatcher — coalesces requests across distinct MPIs

Configured by the serve.* keys (configs/params_default.yaml,
config.ServeConfig).
"""

from mine_tpu.serve.batcher import MicroBatcher
from mine_tpu.serve.cache import (MPICache, MPIEntry, PyramidCache,
                                  dequantize_planes, image_id_for,
                                  quantize_planes)
from mine_tpu.serve.engine import RenderEngine, pow2_bucket

__all__ = [
    "MPICache", "MPIEntry", "MicroBatcher", "PyramidCache", "RenderEngine",
    "dequantize_planes", "image_id_for", "pow2_bucket", "quantize_planes",
]
