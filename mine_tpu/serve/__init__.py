"""Encode-once MPI serving: quantized plane cache + render-only engine.

MINE predicts an MPI once per image; every novel view after that is warp +
composite only. This package is the serving-side realization of that
asymmetry (README "Serving" / "Sharded serving"):

  cache.py     MPICache — LRU of quantized MPI planes under a byte budget
  engine.py    RenderEngine — shape-bucketed jitted render-only program
  batcher.py   MicroBatcher / ContinuousBatcher — request coalescing
  admission.py AdmissionController — tiered load shedding / degradation
  shardmap.py  serving mesh ("batch","model") + MeshRenderEngine
  fleet.py     ShardedPlaneCache (key-range partition + failover) +
               ServeFleet

Configured by the serve.* keys (configs/params_default.yaml,
config.ServeConfig).
"""

from mine_tpu.serve.admission import (TIER_BEST_EFFORT, TIER_CRITICAL,
                                      TIER_STANDARD, AdmissionController,
                                      DeadlineExceeded, RequestShed)
from mine_tpu.serve.batcher import ContinuousBatcher, MicroBatcher
from mine_tpu.serve.cache import (MPICache, MPIEntry, PyramidCache,
                                  dequantize_planes, image_id_for,
                                  quantize_planes)
from mine_tpu.serve.engine import RenderEngine, pow2_bucket
from mine_tpu.serve.fleet import ServeFleet, ShardedPlaneCache, shard_for_key
from mine_tpu.serve.shardmap import (SERVE_BATCH_AXIS, SERVE_MODEL_AXIS,
                                     MeshRenderEngine, make_serve_mesh,
                                     render_shardings)

__all__ = [
    "AdmissionController", "ContinuousBatcher", "DeadlineExceeded",
    "MPICache", "MPIEntry", "MeshRenderEngine", "MicroBatcher",
    "PyramidCache", "RenderEngine", "RequestShed", "SERVE_BATCH_AXIS",
    "SERVE_MODEL_AXIS", "ServeFleet", "ShardedPlaneCache",
    "TIER_BEST_EFFORT", "TIER_CRITICAL", "TIER_STANDARD",
    "dequantize_planes", "image_id_for", "make_serve_mesh", "pow2_bucket",
    "quantize_planes", "render_shardings", "shard_for_key",
]
