"""Encode-once MPI serving: quantized plane cache + render-only engine.

MINE predicts an MPI once per image; every novel view after that is warp +
composite only. This package is the serving-side realization of that
asymmetry (README "Serving" / "Sharded serving"):

  cache.py     MPICache — LRU of quantized MPI planes under a byte budget
  engine.py    RenderEngine — shape-bucketed jitted render-only program
  aot.py       AOTStore — serialized compiled-executable store for
               zero-warmup replica boot
  encoder.py   int8 encoder-weight quantization for the sync-encode path
  batcher.py   MicroBatcher / ContinuousBatcher — request coalescing
  admission.py AdmissionController — tiered load shedding / degradation
  shardmap.py  serving mesh ("batch","model") + MeshRenderEngine
  fleet.py     ShardedPlaneCache (key-range partition + failover) +
               ServeFleet
  session.py   StreamSession — keyframe-cadenced streaming video over the
               plane cache (shard-sticky ids, drift re-keying)
  stream.py    SessionManager — concurrent sessions through the batcher
  ring.py      HostRing / RingFront / Autoscaler — the multi-HOST ring:
               content-hash key ranges owned by hosts, each running a
               ServeFleet as its local slice, with the pressure-driven
               autoscaler (serve.ring.* keys, default off)
  hostnet.py   HostServer / HostClient — stdlib HTTP/JSON host transport,
               SIGTERM drain, subprocess host entrypoint
  wire.py      mtpu-wire1 binary frame format + f32/bf16/int8 wire codecs
               and the shared JSON framing seam (serve.wire.* keys,
               default off)

Configured by the serve.* keys (configs/params_default.yaml,
config.ServeConfig).
"""

from mine_tpu.serve.admission import (TIER_BEST_EFFORT, TIER_CRITICAL,
                                      TIER_STANDARD, AdmissionController,
                                      DeadlineExceeded, RequestShed)
from mine_tpu.serve.aot import AOTStore, env_fingerprint
from mine_tpu.serve.encoder import (dequantize_weights, make_encode_fn,
                                    quantize_weights_int8)
from mine_tpu.serve.batcher import ContinuousBatcher, MicroBatcher
from mine_tpu.serve.cache import (MPICache, MPIEntry, PyramidCache,
                                  dequantize_planes, image_id_for,
                                  quantize_planes)
from mine_tpu.serve.engine import RenderEngine, pow2_bucket
from mine_tpu.serve.fleet import ServeFleet, ShardedPlaneCache, shard_for_key
from mine_tpu.serve.hostnet import (CircuitBreaker, HostClient, HostServer,
                                    NetPolicy)
from mine_tpu.serve.wire import WireError, WirePolicy
from mine_tpu.serve.ring import (Autoscaler, BreakerOpen, HostRing,
                                 HostUnavailable, LocalHost, RingFront,
                                 pressure_score)
from mine_tpu.serve.session import (StreamSession, keyframe_id, probe_drift,
                                    relative_pose, session_key_prefix)
from mine_tpu.serve.stream import SessionManager
from mine_tpu.serve.shardmap import (SERVE_BATCH_AXIS, SERVE_MODEL_AXIS,
                                     MeshRenderEngine, make_serve_mesh,
                                     render_shardings)

__all__ = [
    "AOTStore", "AdmissionController", "Autoscaler", "BreakerOpen",
    "CircuitBreaker", "ContinuousBatcher",
    "DeadlineExceeded", "HostClient", "HostRing", "HostServer",
    "HostUnavailable", "LocalHost", "MPICache", "MPIEntry", "NetPolicy",
    "MeshRenderEngine", "MicroBatcher", "PyramidCache", "RenderEngine",
    "RequestShed", "RingFront", "SERVE_BATCH_AXIS", "SERVE_MODEL_AXIS",
    "ServeFleet", "SessionManager", "ShardedPlaneCache", "StreamSession",
    "TIER_BEST_EFFORT", "TIER_CRITICAL", "TIER_STANDARD",
    "WireError", "WirePolicy",
    "dequantize_planes", "dequantize_weights", "env_fingerprint",
    "image_id_for", "keyframe_id", "make_encode_fn", "make_serve_mesh",
    "pow2_bucket", "pressure_score", "probe_drift", "quantize_planes",
    "quantize_weights_int8", "relative_pose", "render_shardings",
    "session_key_prefix", "shard_for_key",
]
