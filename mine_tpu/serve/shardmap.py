"""Serving mesh: named-axis device mesh + sharding specs for the fleet.

The training mesh (mine_tpu/parallel/mesh.py) spans ("data", "plane") for
the encoder's gradient work; serving has a different parallel structure —
one jitted render-only program whose batch axis is POSES, not images — so
the fleet gets its own mesh with serving-native axis names:

  * "batch": the pose/request axis. Every op in the render program is
    per-pose independent (engine.py docstring), so sharding P along
    "batch" is embarrassingly parallel: each device renders its pose rows
    with the identical per-row program, which is why the mesh render stays
    BITWISE-identical to the single-device engine (tests/test_serve_fleet).
  * "model": the S plane axis of the cached MPI stack, for plane counts too
    large for one device's HBM. Cross-plane compositing (cumprod over S)
    makes GSPMD insert collectives along this axis — the same structure the
    training mesh's "plane" axis has.

`MeshRenderEngine` is the PR-5 `RenderEngine` with its ONE jitted program
given `NamedSharding` in/out specs: inputs are committed under the specs
before dispatch (the `_place` hook), outputs land pose-sharded. The pow2
bucket discipline is preserved — pose buckets are floored at the "batch"
axis size so every bucket divides evenly across the mesh, and the compile
set stays bounded at log2(max_bucket) x log2(max_requests) per mesh shape.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mine_tpu.serve.engine import RenderEngine, pow2_bucket

SERVE_BATCH_AXIS = "batch"
SERVE_MODEL_AXIS = "model"


def _check_pow2(name: str, n: int) -> None:
    if n < 1 or (n & (n - 1)) != 0:
        raise ValueError(
            f"{name} must be a power of two >= 1, got {n} (pow2 mesh axes "
            f"compose with the engine's pow2 shape buckets: every bucket "
            f"divides evenly across the mesh)")


def make_serve_mesh(batch: int = 1, model: int = 1,
                    devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ("batch", "model") serving mesh over the first batch*model
    devices. Both axis sizes must be powers of two (see _check_pow2)."""
    _check_pow2("serve.mesh_batch", batch)
    _check_pow2("serve.mesh_model", model)
    if devices is None:
        devices = jax.devices()
    n = batch * model
    if n > len(devices):
        raise ValueError(
            f"serve mesh {batch}x{model} needs {n} devices, "
            f"have {len(devices)}")
    dev_array = np.asarray(devices[:n]).reshape(batch, model)
    return Mesh(dev_array, (SERVE_BATCH_AXIS, SERVE_MODEL_AXIS))


def render_shardings(mesh: Mesh) -> dict:
    """NamedShardings for the render program's operands/results, keyed by
    operand name (the _render_impl signature):

      planes [R,S,4,H,W], scales [R,S,4,1,1], disp [R,S]: S along "model"
      K / K_inv [R,3,3]: replicated (tiny)
      idx [P], G [P,4,4], rgb/depth out [P,...]: P along "batch"
    """
    model = P(None, SERVE_MODEL_AXIS) \
        if mesh.shape[SERVE_MODEL_AXIS] > 1 else P()
    return {
        "planes": NamedSharding(mesh, model),
        "scales": NamedSharding(mesh, model),
        "disp": NamedSharding(mesh, model),
        "K": NamedSharding(mesh, P()),
        "K_inv": NamedSharding(mesh, P()),
        "idx": NamedSharding(mesh, P(SERVE_BATCH_AXIS)),
        "G": NamedSharding(mesh, P(SERVE_BATCH_AXIS)),
        "out": NamedSharding(mesh, P(SERVE_BATCH_AXIS)),
    }


class MeshRenderEngine(RenderEngine):
    """RenderEngine whose one jitted program spans a serving mesh.

    Same cache facade, same bucketed dispatch, same render math — the only
    deltas are (1) pose buckets floor at the "batch" axis size so the pose
    dim always divides across the mesh, (2) operands are device_put under
    the `render_shardings` specs before the call (`_place`), and (3) the
    jit carries pose-sharded out_shardings. Parity with the single-device
    engine is bitwise on 1/2/4-device CPU meshes (tests/test_serve_fleet);
    8 devices inherits the known GSPMD CPU divergence (ROADMAP).
    """

    def __init__(self, mesh_batch: int = 1, mesh_model: int = 1,
                 devices: Optional[Sequence[jax.Device]] = None, **kw):
        super().__init__(**kw)
        self.mesh = make_serve_mesh(mesh_batch, mesh_model, devices)
        self.mesh_batch = mesh_batch
        self.mesh_model = mesh_model
        self._shardings = render_shardings(self.mesh)
        # pose counts pad to pow2 buckets >= the batch axis, so every
        # bucket splits evenly (pow2 / pow2) with no ragged shard
        self._min_pose_bucket = mesh_batch
        out = self._shardings["out"]
        self._render = jax.jit(self._render_impl,
                               static_argnames=("warp_impl",),
                               out_shardings=(out, out))

    def num_devices(self) -> int:
        return self.mesh.size

    def _render_mesh(self):
        """warp_impl="pallas_fused" runs the render megakernel under
        shard_map over this mesh (pose rows over "batch"); the pose-bucket
        floor at mesh_batch keeps every bucket divisible."""
        return self.mesh

    def _mesh_desc(self) -> str:
        """AOT program-key component (engine._program_key): executables are
        compiled against committed NamedSharding inputs, so a 2x1 artifact
        must never be handed to a 1x1 engine (or vice versa)."""
        return f"{self.mesh_batch}x{self.mesh_model}"

    def _render_span_fields(self) -> dict:
        """Request traces rendered here carry the mesh topology, so a
        waterfall read offline still knows which fleet shape it measured."""
        return {"mesh": f"{self.mesh_batch}x{self.mesh_model}",
                "devices": self.mesh.size}

    def _place(self, planes, scales, disp, K, K_inv, idx, poses):
        """Commit every operand under its NamedSharding; the committed
        inputs are what make the jitted program span the mesh."""
        if self.mesh_model > 1 and planes.shape[1] % self.mesh_model:
            raise ValueError(
                f"plane count S={planes.shape[1]} must divide the model "
                f"axis ({self.mesh_model})")
        s = self._shardings
        put = jax.device_put
        return (put(planes, s["planes"]),
                None if scales is None else put(scales, s["scales"]),
                put(disp, s["disp"]),
                put(K, s["K"]),
                put(K_inv, s["K_inv"]),
                put(idx, s["idx"]),
                put(poses, s["G"]))
