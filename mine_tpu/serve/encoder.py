"""int8 *weight* quantization for the serve-side encoder.

A cold replica's cache misses pay a synchronous encode (engine._entry), and
the encoder's weight tensors dominate both the checkpoint bytes a booting
replica pulls and the HBM reads of that encode. This module stores the
encoder params as symmetric per-output-channel int8 — the exact scheme
`serve/cache.py` applies to MPI planes (amax/127 scale, zero-point-free,
all-zero guard) lifted from [S,C,1,1] plane scales to per-channel weight
scales — with the widening dequant FUSED into the jitted encode, so int8
is what crosses HBM and f32 is what the matmuls see.

Only float weight tensors with ndim >= 2 (Dense/Conv kernels) quantize;
biases, scalars, and batch-norm vectors stay f32 — they are tiny and their
precision is load-bearing. Everything is a knob: `serve.encoder_quant`
defaults to "off", which leaves the params tree untouched byte-for-byte
(pinned by tests/test_serve_aot.py).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp

ENCODER_QUANT_MODES = ("off", "int8")

# a quantized leaf is a dict with exactly these keys, so tree traversal can
# tell it from an ordinary params subtree without any side table
_QKEYS = frozenset(("q", "scale"))


def _is_qleaf(node: Any) -> bool:
    return isinstance(node, Mapping) and frozenset(node.keys()) == _QKEYS


def _quantizable(x: Any) -> bool:
    return (hasattr(x, "ndim") and x.ndim >= 2
            and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating))


def _quantize_leaf(w) -> Mapping[str, jnp.ndarray]:
    """f32 [..., out] kernel -> {"q": int8, "scale": f32 per-out-channel}.
    Mirrors cache.quantize_planes: symmetric, amax/127, all-zero guard."""
    w = jnp.asarray(w, jnp.float32)
    axes = tuple(range(w.ndim - 1))  # all but the output-feature axis
    amax = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale}


def is_quantized(params: Any) -> bool:
    """True if the tree contains at least one quantized leaf."""
    found = []

    def walk(node):
        if _is_qleaf(node):
            found.append(True)
        elif isinstance(node, Mapping):
            for v in node.values():
                walk(v)

    walk(params)
    return bool(found)


def quantize_weights_int8(params: Any) -> Any:
    """Quantize every >=2-D float leaf of a params tree to int8 + scales;
    other leaves pass through unchanged. Idempotent (already-quantized
    leaves are kept as-is) so callers can pre-quantize once and reuse."""
    if _is_qleaf(params):
        return params
    if isinstance(params, Mapping):
        return {k: quantize_weights_int8(v) for k, v in params.items()}
    if _quantizable(params):
        return _quantize_leaf(params)
    return params


def dequantize_weights(params: Any) -> Any:
    """Inverse of quantize_weights_int8; jit-traceable (the tree structure
    is static, the dequant is a widening cast * scale — the same fused
    pattern as engine._render_impl's plane dequant)."""
    if _is_qleaf(params):
        return params["q"].astype(jnp.float32) * params["scale"]
    if isinstance(params, Mapping):
        return {k: dequantize_weights(v) for k, v in params.items()}
    return params


def make_encode_fn(model, params, batch_stats,
                   encoder_quant: str = "off"):
    """Jitted image+disparity -> MPI encode with optional int8 weights.

    `model.apply({"params": p, "batch_stats": bs}, img, disp, train=False)`
    is the contract (infer/video.py's encode line). Params and batch stats
    are passed as ARGUMENTS of the jitted function — not closed over — so
    they stay device buffers instead of getting baked into the program as
    constants. With `encoder_quant="int8"` the stored tree is quantized
    once here (idempotent for pre-quantized trees) and dequantized INSIDE
    the jit, so int8 is the form that crosses HBM.

    Returns `encode(img, disparity) -> mpi`; the stored (possibly
    quantized) tree is exposed as `encode.params` for introspection.
    """
    if encoder_quant not in ENCODER_QUANT_MODES:
        raise ValueError(
            f"serve.encoder_quant must be one of {ENCODER_QUANT_MODES}, "
            f"got {encoder_quant!r}")
    quantized = encoder_quant == "int8"
    stored = quantize_weights_int8(params) if quantized else params

    def _encode(p, bs, img, disparity):
        if quantized:
            p = dequantize_weights(p)
        return model.apply({"params": p, "batch_stats": bs},
                           img, disparity, train=False)[0]

    jitted = jax.jit(_encode)

    def encode(img, disparity):
        return jitted(stored, batch_stats, img, disparity)

    encode.params = stored
    encode.quantized = quantized
    return encode
