"""AOT-compiled executable store: zero-warmup boot for serving replicas.

Every fleet scale-up, failover revival, or restart pays jit warmup per
(entries bucket, pose bucket, warp_impl, quant dtype, mesh shape) render
program — and the compile set is BOUNDED (engine.py docstring), so it is
enumerable offline. This module persists the compiled executables
themselves:

    build (tools/aot_warmstore.py, or any engine's live write-back)
      -> ship (the artifact directory is plain files; rsync/bake it)
      -> boot (`RenderEngine.warmup` loads executables instead of tracing)
      -> GC   (`AOTStore.gc` drops artifacts whose environment fingerprint
               no longer matches; `tools/audit.py`'s aot_staleness pass
               gates on it)

Artifacts are content-addressed: sha256 of the canonical-JSON *program key*
(bucket shapes + engine statics + mesh shape + environment fingerprint)
names the file, so a key change — different jax version, backend, topology,
or render configuration — can never alias a stale executable. Each artifact
is a pickle of `jax.experimental.serialize_executable.serialize` output
plus the key, written atomically, with a JSON sidecar carrying the key
alone so `--check` / GC / reporting never unpickle executable payloads.

The store is purely an ACCELERATOR, never a correctness dependency: every
load failure (missing, corrupt, key mismatch, deserialization error)
returns None and the engine falls back to live jit — then writes the fresh
executable back so the next replica boots warm.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional, Tuple

from mine_tpu import telemetry

_log = logging.getLogger(__name__)

# artifact / sidecar extensions: <digest>.aotx holds the pickled payload,
# <digest>.json holds the key alone (never unpickled for checks or GC)
ARTIFACT_EXT = ".aotx"
SIDECAR_EXT = ".json"

# bumped when the artifact layout changes; part of every program key so a
# layout change invalidates (misses, not crashes) every old artifact
STORE_SCHEMA = "mtpu-aot1"


def env_fingerprint() -> Dict[str, Any]:
    """The environment a compiled executable is only valid in: jax/jaxlib
    versions, backend platform, and device topology. Part of every program
    key, so artifacts from another environment hash to different names and
    simply miss (and `gc` can sweep them by comparing this dict)."""
    import jax
    import jaxlib
    devices = jax.devices()
    return {
        "schema": STORE_SCHEMA,
        "jax": jax.__version__,
        "jaxlib": jaxlib.version.__version__,
        "backend": jax.default_backend(),
        "devices": f"{len(devices)}x{devices[0].device_kind}",
        "processes": jax.process_count(),
    }


def key_digest(key: Dict[str, Any]) -> str:
    """Content address: sha256 over the canonical (sorted, compact) JSON of
    the program key."""
    blob = json.dumps(key, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class AOTStore:
    """Content-addressed directory of serialized compiled executables.

    `load` returns a ready-to-call `Compiled` (invoked with the program's
    DYNAMIC arguments only — static argnames are baked in) or None on any
    miss or failure; `save` serializes and writes atomically. Counters
    (`hits`/`misses`/`load_errors`/`saves`/`save_errors`) mirror into the
    telemetry registry under `serve.aot.*`.
    """

    def __init__(self, root: str):
        if not root:
            raise ValueError("AOTStore needs a directory path")
        self.root = str(root)
        self.hits = 0
        self.misses = 0
        self.load_errors = 0
        self.saves = 0
        self.save_errors = 0
        self._warned = set()

    # ---------------- paths ----------------

    def _paths(self, digest: str) -> Tuple[str, str]:
        return (os.path.join(self.root, digest + ARTIFACT_EXT),
                os.path.join(self.root, digest + SIDECAR_EXT))

    def _warn_once(self, slot: str, msg: str) -> None:
        if slot not in self._warned:
            self._warned.add(slot)
            _log.warning("%s", msg)

    # ---------------- load / save ----------------

    def load(self, key: Dict[str, Any]):
        """Deserialize the executable for `key`, or None (miss or any
        failure — the caller's live-jit fallback is the contract)."""
        digest = key_digest(key)
        art, _ = self._paths(digest)
        if not os.path.exists(art):
            self.misses += 1
            telemetry.counter("serve.aot.misses").inc()
            return None
        try:
            from jax.experimental import serialize_executable as se
            with open(art, "rb") as f:
                blob = pickle.load(f)
            if blob.get("key") != key:
                # digest collision or a hand-edited artifact: treat as a
                # corrupt entry, never hand back a mismatched executable
                raise ValueError("artifact key does not match request key")
            exe = se.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"])
        except Exception as e:  # noqa: BLE001 - any failure means "miss"
            self.load_errors += 1
            telemetry.counter("serve.aot.load_errors").inc()
            self._warn_once(
                "load:" + digest,
                f"AOT store load failed for {digest[:12]}… ({e!r}); "
                f"falling back to live jit")
            return None
        self.hits += 1
        telemetry.counter("serve.aot.hits").inc()
        return exe

    def save(self, key: Dict[str, Any], compiled) -> bool:
        """Serialize `compiled` under `key` (artifact + sidecar, each via
        atomic tmp+rename). Returns False on any failure — a broken store
        must never break serving."""
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps({"key": key, "payload": payload,
                                 "in_tree": in_tree, "out_tree": out_tree})
            os.makedirs(self.root, exist_ok=True)
            digest = key_digest(key)
            art, side = self._paths(digest)
            self._atomic_write(art, blob)
            meta = json.dumps({"key": key, "nbytes": len(blob)},
                              sort_keys=True, indent=1)
            self._atomic_write(side, meta.encode("utf-8"))
        except Exception as e:  # noqa: BLE001
            self.save_errors += 1
            telemetry.counter("serve.aot.save_errors").inc()
            self._warn_once("save", f"AOT store save failed ({e!r}); "
                                    f"serving continues without write-back")
            return False
        self.saves += 1
        telemetry.counter("serve.aot.saves").inc()
        return True

    def _atomic_write(self, path: str, data: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def contains(self, key: Dict[str, Any]) -> bool:
        return os.path.exists(self._paths(key_digest(key))[0])

    # ---------------- inventory / GC ----------------

    def entries(self) -> List[Dict[str, Any]]:
        """[{digest, key, nbytes, corrupt}] from sidecars alone (artifacts
        without a readable sidecar are listed as corrupt — check/GC treat
        them as stale)."""
        out: List[Dict[str, Any]] = []
        if not os.path.isdir(self.root):
            return out
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(ARTIFACT_EXT):
                continue
            digest = name[:-len(ARTIFACT_EXT)]
            art, side = self._paths(digest)
            rec = {"digest": digest, "key": None, "corrupt": False,
                   "nbytes": os.path.getsize(art)}
            try:
                with open(side, "r", encoding="utf-8") as f:
                    meta = json.load(f)
                rec["key"] = meta["key"]
                if key_digest(meta["key"]) != digest:
                    rec["corrupt"] = True
            except Exception:  # noqa: BLE001
                rec["corrupt"] = True
            out.append(rec)
        return out

    def stale_entries(self,
                      fingerprint: Optional[Dict[str, Any]] = None
                      ) -> List[Dict[str, Any]]:
        """Entries whose environment fingerprint differs from the current
        one (plus corrupt entries): exactly the set `gc` removes and the
        audit pass fails on."""
        if fingerprint is None:
            fingerprint = env_fingerprint()
        stale = []
        for rec in self.entries():
            if rec["corrupt"] or \
                    (rec["key"] or {}).get("fingerprint") != fingerprint:
                stale.append(rec)
        return stale

    def gc(self, dry_run: bool = False) -> List[str]:
        """Remove stale/corrupt artifacts (and their sidecars); returns the
        removed digests."""
        removed = []
        for rec in self.stale_entries():
            art, side = self._paths(rec["digest"])
            if not dry_run:
                for p in (art, side):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            removed.append(rec["digest"])
        return removed

    def stats(self) -> Dict[str, Any]:
        ents = self.entries()
        return {
            "root": self.root,
            "artifacts": len(ents),
            "bytes": sum(e["nbytes"] for e in ents),
            "hits": self.hits, "misses": self.misses,
            "load_errors": self.load_errors,
            "saves": self.saves, "save_errors": self.save_errors,
        }


# ------------------------------------------------------------------ packing

# manifest filename inside a packed artifact; carries the builder's
# fingerprint so a deploy can see at a glance what environment it targets
PACK_MANIFEST = "MANIFEST.json"


def pack_store(root: str, out_path: str) -> Dict[str, Any]:
    """Pack a store directory into ONE deployable tar artifact.

    The archive is FLAT — artifact/sidecar basenames plus a MANIFEST.json
    carrying the store schema, the builder's environment fingerprint and
    the member list — written atomically (tmp + rename) in sorted member
    order so identical stores pack byte-identically. Returns the manifest.
    Only `ARTIFACT_EXT`/`SIDECAR_EXT` files are packed; anything else in
    the directory is someone else's.
    """
    import io
    import tarfile

    store = AOTStore(root)
    names = sorted(
        f for f in os.listdir(store.root)
        if f.endswith(ARTIFACT_EXT) or f.endswith(SIDECAR_EXT))
    manifest = {
        "schema": STORE_SCHEMA,
        "fingerprint": env_fingerprint(),
        "members": names,
        "artifacts": sum(1 for f in names if f.endswith(ARTIFACT_EXT)),
    }
    out_dir = os.path.dirname(os.path.abspath(out_path)) or "."
    fd, tmp = tempfile.mkstemp(dir=out_dir, suffix=".pack.tmp")
    os.close(fd)
    try:
        with tarfile.open(tmp, "w") as tf:
            blob = json.dumps(manifest, sort_keys=True,
                              indent=1).encode("utf-8")
            info = tarfile.TarInfo(PACK_MANIFEST)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
            for name in names:
                tf.add(os.path.join(store.root, name), arcname=name)
        os.replace(tmp, out_path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return manifest


def unpack_store(artifact_path: str, root: str) -> Dict[str, Any]:
    """Unpack a packed artifact into a store directory (created if
    missing); returns the manifest. Member names are validated hard —
    flat basenames with the store's extensions only, so a hostile or
    corrupted archive can never write outside `root` — and each file is
    written atomically so a half-unpacked store still just misses."""
    import tarfile

    os.makedirs(root, exist_ok=True)
    manifest: Dict[str, Any] = {}
    with tarfile.open(artifact_path, "r") as tf:
        for m in tf.getmembers():
            name = m.name
            if not m.isfile() or name != os.path.basename(name) \
                    or name.startswith("."):
                raise ValueError(
                    f"packed store member {name!r} is not a flat file")
            if name == PACK_MANIFEST:
                manifest = json.loads(tf.extractfile(m).read())
                continue
            if not (name.endswith(ARTIFACT_EXT)
                    or name.endswith(SIDECAR_EXT)):
                raise ValueError(
                    f"packed store member {name!r} has a foreign extension")
            fd, tmp = tempfile.mkstemp(dir=root, suffix=".unpack.tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(tf.extractfile(m).read())
                os.replace(tmp, os.path.join(root, name))
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
    return manifest
