"""Serving fleet: key-range-sharded plane cache + mesh engine + scheduler.

The ROADMAP's serving lever is views/sec/chip x chips; this module is the
"x chips" part assembled from the fleet's three pieces:

  * `ShardedPlaneCache` — the PR-5 content-hash LRU partitioned by KEY
    RANGE: the id space (the leading 32 bits of the sha1 image id) is cut
    into `num_shards` contiguous ranges and each shard owns one, with its
    own byte budget (`serve.cache_bytes / num_shards`). Lookups route to
    the owner (a front-end shard that doesn't own the key counts a
    `serve.shard.remote_route`), misses trigger an owner-side encode
    (`serve.shard.owner_encode` + a `serve.shard.place` event), and a
    shard-count change rebalances every entry whose range moved
    (`serve.shard.rebalance`). Ownership is a pure function of
    (image_id, num_shards) — deterministic across processes, so any
    front-end routes identically (tests/test_serve_fleet.py).
  * `MeshRenderEngine` (serve/shardmap.py) — the one jitted render program
    spanning a ("batch", "model") device mesh.
  * `ContinuousBatcher` (serve/batcher.py) — keeps the engine's pow2 pose
    buckets filled across in-flight requesters.

`ServeFleet` wires them per the serve.* config keys and is what serve_cli
builds when `serve.mesh_batch * serve.mesh_model > 1` or
`serve.cache_shards > 1`.
"""

from __future__ import annotations

import hashlib
import itertools
import time
from typing import Callable, List, Optional

from mine_tpu import telemetry
from mine_tpu.serve.batcher import ContinuousBatcher, MicroBatcher
from mine_tpu.serve.cache import MPICache, MPIEntry
from mine_tpu.serve.shardmap import MeshRenderEngine
from mine_tpu.telemetry import tracing
from mine_tpu.telemetry.export import OpsServer
from mine_tpu.telemetry.slo import SLOTracker

_METRIC_PREFIX = "serve.shard"
# ownership uses the leading 32 bits of the content hash: wide enough that
# pow2 AND non-pow2 shard counts cut near-equal ranges, cheap to recompute
# anywhere (no routing table to distribute)
_KEY_BITS = 32


def _key_pos(image_id: str) -> int:
    """Position of an id in the [0, 2^32) key space. Content-hash ids
    (sha1 hex, serve/cache.py image_id_for) use their leading 8 hex digits
    directly; arbitrary ids (tests, benches) fall back to hashing the id
    string so every key still lands deterministically in the range."""
    try:
        return int(image_id[:8], 16)
    except ValueError:
        return int(hashlib.sha1(image_id.encode()).hexdigest()[:8], 16)


def shard_for_key(image_id: str, num_shards: int) -> int:
    """Owner shard of `image_id` under a `num_shards`-way key-range
    partition: shard s owns [s*2^32/N, (s+1)*2^32/N). Deterministic in
    (image_id, num_shards) alone."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    return (_key_pos(image_id) * num_shards) >> _KEY_BITS


class ShardedPlaneCache:
    """Key-range partition of the MPI plane cache across fleet shards.

    Drop-in for `MPICache` where the engine is concerned (get / put /
    __contains__ / stats), with the byte budget split evenly across the
    per-shard LRUs so one hot shard cannot evict another shard's residency.
    Per-occurrence routing telemetry lands under `serve.shard.*`; the
    per-shard LRUs keep mirroring the process-wide `serve.cache.*`
    counters, which therefore aggregate over all shards.
    """

    def __init__(self, num_shards: int = 1, capacity_bytes: int = 0,
                 quant: str = "bf16"):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        self.capacity_bytes = int(capacity_bytes)
        self.quant = quant
        self.shards: List[MPICache] = [
            MPICache(capacity_bytes=self.capacity_bytes // num_shards
                     if self.capacity_bytes else 0, quant=quant)
            for _ in range(num_shards)]
        self.owner_hits = 0
        self.remote_routes = 0
        self.owner_encodes = 0
        self.rebalances = 0

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    def owner(self, image_id: str) -> int:
        return shard_for_key(image_id, self.num_shards)

    def route(self, caller_shard: int, image_id: str) -> int:
        """Front-end routing step: the shard a request lands on forwards
        the key to its owner; a cross-shard hop is a remote route."""
        o = self.owner(image_id)
        if caller_shard != o:
            self.remote_routes += 1
            telemetry.counter(_METRIC_PREFIX + ".remote_route").inc()
        return o

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    def __contains__(self, image_id: str) -> bool:
        return image_id in self.shards[self.owner(image_id)]

    def keys(self):
        return [k for s in self.shards for k in s.keys()]

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self.shards)

    def get(self, image_id: str) -> Optional[MPIEntry]:
        entry = self.shards[self.owner(image_id)].get(image_id)
        if entry is not None:
            self.owner_hits += 1
            telemetry.counter(_METRIC_PREFIX + ".owner_hit").inc()
        return entry

    def put(self, image_id: str, mpi_rgb_S3HW, mpi_sigma_S1HW,
            disparity_S, K_33) -> MPIEntry:
        """Owner-side placement: the encode result lands on the shard that
        owns the key's range, never on the shard the request arrived at."""
        o = self.owner(image_id)
        entry = self.shards[o].put(image_id, mpi_rgb_S3HW, mpi_sigma_S1HW,
                                   disparity_S, K_33)
        self.owner_encodes += 1
        telemetry.counter(_METRIC_PREFIX + ".owner_encode").inc()
        telemetry.emit("serve.shard.place", image_id=image_id[:12],
                       shard=o, shards=self.num_shards, nbytes=entry.nbytes)
        return entry

    def rebalance(self, num_shards: int) -> int:
        """Repartition to `num_shards` key ranges, moving every resident
        entry whose owner changed; returns the move count. The per-shard
        budget is re-derived from the fleet-level `capacity_bytes`."""
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        old = self.shards
        per = self.capacity_bytes // num_shards if self.capacity_bytes else 0
        self.shards = [MPICache(capacity_bytes=per, quant=self.quant)
                       for _ in range(num_shards)]
        moved = 0
        for old_idx, shard in enumerate(old):
            for image_id in shard.keys():  # LRU order: recency survives
                entry = shard._entries[image_id]
                new_idx = self.owner(image_id)
                self.shards[new_idx].adopt(image_id, entry)
                moved += int(new_idx != old_idx)
        self.rebalances += 1
        telemetry.counter(_METRIC_PREFIX + ".rebalance").inc(moved)
        telemetry.emit("serve.shard.rebalance", from_shards=len(old),
                       to_shards=num_shards, moved=moved,
                       entries=len(self))
        return moved

    def stats(self) -> dict:
        agg = {"entries": len(self), "nbytes": self.nbytes,
               "shards": self.num_shards, "quant": self.quant,
               "owner_hits": self.owner_hits,
               "remote_routes": self.remote_routes,
               "owner_encodes": self.owner_encodes,
               "rebalances": self.rebalances}
        for k in ("hits", "misses", "evictions"):
            agg[k] = sum(s.stats()[k] for s in self.shards)
        agg["per_shard"] = [
            {"entries": len(s), "nbytes": s.nbytes} for s in self.shards]
        return agg


class ServeFleet:
    """Front door of the sharded serving fleet: one mesh render engine over
    a key-range-sharded cache, fed by the continuous batcher.

    `submit` is the request path (front-end shard assigned round-robin,
    key routed to its owner, render coalesced by the scheduler); `render` /
    `render_many` pass through to the engine for trajectory-style callers
    (serve_cli's video path).
    """

    def __init__(self, *,
                 mesh_batch: int = 1,
                 mesh_model: int = 1,
                 cache_shards: int = 1,
                 cache_bytes: int = 0,
                 cache_quant: str = "bf16",
                 scheduler: str = "continuous",
                 max_requests: int = 8,
                 max_wait_ms: float = 2.0,
                 max_bucket: int = 8,
                 encode_fn: Optional[Callable] = None,
                 start: bool = True,
                 devices=None,
                 trace_sample: Optional[float] = None,
                 slo_objective_ms: float = 0.0,
                 slo_target: float = 0.99,
                 slo_window_s: float = 60.0,
                 ops_port: Optional[int] = None,
                 **engine_kw):
        self.cache = ShardedPlaneCache(
            num_shards=cache_shards, capacity_bytes=cache_bytes,
            quant=cache_quant)
        self.engine = MeshRenderEngine(
            mesh_batch=mesh_batch, mesh_model=mesh_model, devices=devices,
            max_bucket=max_bucket, cache=self.cache, encode_fn=encode_fn,
            **engine_kw)
        if scheduler not in ("continuous", "micro"):
            raise ValueError(
                f"serve.scheduler must be continuous|micro, got {scheduler!r}")
        # trace_sample None = defer to the process-wide tracing.configure
        # rate; a number pins this fleet's own head-sampling rate
        self.trace_sample = trace_sample
        # the SLO tracker sees EVERY request (recording is cheap; sampling
        # is for traces) — the batcher's flush path feeds it
        self.slo = SLOTracker(objective_ms=slo_objective_ms,
                              target=slo_target, window_s=slo_window_s)
        batcher_cls = ContinuousBatcher if scheduler == "continuous" \
            else MicroBatcher
        self.batcher = batcher_cls(self.engine, max_requests=max_requests,
                                   max_wait_ms=max_wait_ms, start=start,
                                   slo=self.slo, auto_trace=False)
        self._front = itertools.count()
        # opt-in live ops plane; port 0 binds ephemeral (tests), None = off
        self.ops: Optional[OpsServer] = None
        if ops_port is not None:
            self.ops = OpsServer(port=ops_port, slo=self.slo).start()

    @classmethod
    def from_config(cls, serve_cfg, encode_fn=None, start: bool = True,
                    devices=None, **engine_kw) -> "ServeFleet":
        """Build from a config.ServeConfig (the serve.* key block).
        serve.ops_port 0 means "no endpoint" at the config surface (the
        ephemeral-port niche is a test concern, not a YAML one)."""
        return cls(mesh_batch=serve_cfg.mesh_batch,
                   mesh_model=serve_cfg.mesh_model,
                   cache_shards=serve_cfg.cache_shards,
                   cache_bytes=serve_cfg.cache_bytes,
                   cache_quant=serve_cfg.cache_quant,
                   scheduler=serve_cfg.scheduler,
                   max_requests=serve_cfg.max_requests,
                   max_wait_ms=serve_cfg.max_wait_ms,
                   max_bucket=serve_cfg.max_bucket,
                   slo_objective_ms=serve_cfg.slo_objective_ms,
                   slo_target=serve_cfg.slo_target,
                   slo_window_s=serve_cfg.slo_window_s,
                   ops_port=serve_cfg.ops_port if serve_cfg.ops_port > 0
                   else None,
                   encode_fn=encode_fn, start=start, devices=devices,
                   **engine_kw)

    def num_devices(self) -> int:
        return self.engine.num_devices()

    def submit(self, image_id: str, pose_44):
        """One view request through the fleet: round-robin front-end shard,
        owner routing (telemetry), scheduler coalescing. Resolves to
        (rgb [3,H,W], depth [1,H,W]) f32 numpy.

        A sampled request's trace is born HERE — the route decision is its
        first child span (front shard, owner shard, remote hop or not) and
        the context then rides the batcher's queue into the flush thread."""
        caller = next(self._front) % self.cache.num_shards
        trace = tracing.start("serve.request", sample=self.trace_sample,
                              image_id=str(image_id)[:12])
        t0 = time.perf_counter()
        owner = self.cache.route(caller, image_id)
        if trace is not None:
            trace.add_span("route", (time.perf_counter() - t0) * 1e3, t0=t0,
                           front_shard=caller, owner_shard=owner,
                           remote=caller != owner)
        return self.batcher.submit(image_id, pose_44, trace=trace)

    def render(self, image_id: str, poses_P44, **kw):
        return self.engine.render(image_id, poses_P44, **kw)

    def render_many(self, requests, **kw):
        return self.engine.render_many(requests, **kw)

    def encode(self, img_hwc, image_id: Optional[str] = None) -> str:
        return self.engine.encode(img_hwc, image_id=image_id)

    def warmup(self, image_id: str, **kw) -> None:
        self.engine.warmup(image_id, **kw)

    def stats(self) -> dict:
        s = self.cache.stats()
        s.update(device_calls=self.engine.device_calls,
                 sync_encodes=self.engine.sync_encodes,
                 flushes=self.batcher.flushes,
                 slo_breaches=self.slo.breaches,
                 mesh=f"{self.engine.mesh_batch}x{self.engine.mesh_model}")
        return s

    def close(self) -> None:
        self.batcher.close()
        if self.ops is not None:
            self.ops.close()
            self.ops = None
