"""Serving fleet: key-range-sharded plane cache + mesh engine + scheduler.

The ROADMAP's serving lever is views/sec/chip x chips; this module is the
"x chips" part assembled from the fleet's three pieces:

  * `ShardedPlaneCache` — the PR-5 content-hash LRU partitioned by KEY
    RANGE: the id space (the leading 32 bits of the sha1 image id) is cut
    into `num_shards` contiguous ranges and each shard owns one, with its
    own byte budget (`serve.cache_bytes / num_shards`). Lookups route to
    the owner (a front-end shard that doesn't own the key counts a
    `serve.shard.remote_route`), misses trigger an owner-side encode
    (`serve.shard.owner_encode` + a `serve.shard.place` event), and a
    shard-count change rebalances every entry whose range moved
    (`serve.shard.rebalance`). Ownership is a pure function of
    (image_id, num_shards) — deterministic across processes, so any
    front-end routes identically (tests/test_serve_fleet.py).
  * `MeshRenderEngine` (serve/shardmap.py) — the one jitted render program
    spanning a ("batch", "model") device mesh.
  * `ContinuousBatcher` (serve/batcher.py) — keeps the engine's pow2 pose
    buckets filled across in-flight requesters.

`ServeFleet` wires them per the serve.* config keys and is what serve_cli
builds when `serve.mesh_batch * serve.mesh_model > 1` or
`serve.cache_shards > 1`.
"""

from __future__ import annotations

import hashlib
import itertools
import time
from typing import Callable, List, Optional

from mine_tpu import telemetry
from mine_tpu.analysis.locks import ordered_lock
from mine_tpu.serve.admission import AdmissionController
from mine_tpu.serve.aot import AOTStore
from mine_tpu.serve.batcher import ContinuousBatcher, MicroBatcher
from mine_tpu.serve.cache import MPICache, MPIEntry
from mine_tpu.serve.shardmap import MeshRenderEngine
from mine_tpu.telemetry import tracing
from mine_tpu.telemetry.export import OpsServer
from mine_tpu.telemetry.slo import SLOTracker
from mine_tpu.testing import faults

_METRIC_PREFIX = "serve.shard"
# ownership uses the leading 32 bits of the content hash: wide enough that
# pow2 AND non-pow2 shard counts cut near-equal ranges, cheap to recompute
# anywhere (no routing table to distribute)
_KEY_BITS = 32


def _key_pos(image_id: str) -> int:
    """Position of an id in the [0, 2^32) key space. Content-hash ids
    (sha1 hex, serve/cache.py image_id_for) use their leading 8 hex digits
    directly; arbitrary ids (tests, benches) fall back to hashing the id
    string so every key still lands deterministically in the range."""
    try:
        return int(image_id[:8], 16)
    except ValueError:
        return int(hashlib.sha1(image_id.encode()).hexdigest()[:8], 16)


def shard_for_key(image_id: str, num_shards: int) -> int:
    """Owner shard of `image_id` under a `num_shards`-way key-range
    partition: shard s owns [s*2^32/N, (s+1)*2^32/N). Deterministic in
    (image_id, num_shards) alone."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    return (_key_pos(image_id) * num_shards) >> _KEY_BITS


class ShardedPlaneCache:
    """Key-range partition of the MPI plane cache across fleet shards.

    Drop-in for `MPICache` where the engine is concerned (get / put /
    __contains__ / stats), with the byte budget split evenly across the
    per-shard LRUs so one hot shard cannot evict another shard's residency.
    Per-occurrence routing telemetry lands under `serve.shard.*`; the
    per-shard LRUs keep mirroring the process-wide `serve.cache.*`
    counters, which therefore aggregate over all shards.

    Failover (PR 11): `fail_threshold` CONSECUTIVE placement failures mark
    a shard dead — its resident entries are dropped (the failure mode being
    modeled is the shard's memory going with it), a `serve.shard_dead`
    event fires, and its key range re-routes ring-wise to the next alive
    shard (`alive_owner`). `mark_alive` re-adopts a recovered shard: the
    same entry-move loop `rebalance()` uses walks every resident entry back
    to its true owner (`serve.shard_revive`). All shard-list / dead-set
    state is guarded by one rank-ordered lock ("serve.fleet.cache",
    analysis/locks.py) so routing, placement, rebalance and failover can
    race from the submit and flush threads.
    """

    def __init__(self, num_shards: int = 1, capacity_bytes: int = 0,
                 quant: str = "bf16", fail_threshold: int = 3):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if fail_threshold < 1:
            raise ValueError(
                f"fail_threshold must be >= 1, got {fail_threshold}")
        self.capacity_bytes = int(capacity_bytes)
        self.quant = quant
        self.fail_threshold = int(fail_threshold)
        self.shards: List[MPICache] = [
            MPICache(capacity_bytes=self.capacity_bytes // num_shards
                     if self.capacity_bytes else 0, quant=quant)
            for _ in range(num_shards)]
        self.owner_hits = 0
        self.remote_routes = 0
        self.owner_encodes = 0
        self.rebalances = 0
        self.failovers = 0  # shards marked dead over this cache's lifetime
        self._lock = ordered_lock("serve.fleet.cache")
        self._dead: set = set()
        self._fail_counts: dict = {}

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    @property
    def dead_shards(self) -> List[int]:
        with self._lock:
            return sorted(self._dead)

    def owner(self, image_id: str) -> int:
        return shard_for_key(image_id, self.num_shards)

    def _alive_owner(self, image_id: str) -> int:
        """True owner, or — when it is marked dead — the next alive shard
        ring-wise (callers hold self._lock). Deterministic in (image_id,
        num_shards, dead set), so every front-end re-routes identically."""
        o = shard_for_key(image_id, len(self.shards))
        if o not in self._dead:
            return o
        for step in range(1, len(self.shards)):
            cand = (o + step) % len(self.shards)
            if cand not in self._dead:
                return cand
        raise RuntimeError("every cache shard is marked dead")

    def alive_owner(self, image_id: str) -> int:
        with self._lock:
            return self._alive_owner(image_id)

    def route(self, caller_shard: int, image_id: str) -> int:
        """Front-end routing step: the shard a request lands on forwards
        the key to its (alive) owner; a cross-shard hop is a remote
        route."""
        with self._lock:
            o = self._alive_owner(image_id)
        if caller_shard != o:
            self.remote_routes += 1
            telemetry.counter(_METRIC_PREFIX + ".remote_route").inc()
        return o

    def __len__(self) -> int:
        with self._lock:
            return sum(len(s) for s in self.shards)

    def __contains__(self, image_id: str) -> bool:
        with self._lock:
            return image_id in self.shards[self._alive_owner(image_id)]

    def keys(self):
        with self._lock:
            return [k for s in self.shards for k in s.keys()]

    @property
    def nbytes(self) -> int:
        with self._lock:
            return sum(s.nbytes for s in self.shards)

    def get(self, image_id: str) -> Optional[MPIEntry]:
        with self._lock:
            entry = self.shards[self._alive_owner(image_id)].get(image_id)
        if entry is not None:
            self.owner_hits += 1
            telemetry.counter(_METRIC_PREFIX + ".owner_hit").inc()
        return entry

    def pop(self, image_id: str) -> Optional[MPIEntry]:
        """Remove an entry from its (alive) owner shard without counting an
        eviction — the streaming-session plane retires superseded keyframe
        MPIs through this (serve/session.py) so a long stream's dead
        keyframes never crowd the LRU. None when not resident."""
        with self._lock:
            return self.shards[self._alive_owner(image_id)].pop(image_id)

    def put(self, image_id: str, mpi_rgb_S3HW, mpi_sigma_S1HW,
            disparity_S, K_33, quant: Optional[str] = None) -> MPIEntry:
        """Owner-side placement: the encode result lands on the shard that
        owns the key's range (ring-stepped past dead shards), never on the
        shard the request arrived at. A placement failure counts toward the
        owner's consecutive-failure tally (`fail_threshold` of them marks
        it dead) and re-raises — the engine's bounded encode retry is the
        recovery path, and its next attempt routes past the dead shard."""
        with self._lock:
            o = self._alive_owner(image_id)
            try:
                faults.on_shard_put(o)  # chaos seam (no-op unplanned)
                entry = self.shards[o].put(
                    image_id, mpi_rgb_S3HW, mpi_sigma_S1HW,
                    disparity_S, K_33, quant=quant)
            except Exception:
                self._note_failure(o)
                raise
            self._fail_counts.pop(o, None)  # threshold is CONSECUTIVE
            shards = len(self.shards)
        self.owner_encodes += 1
        telemetry.counter(_METRIC_PREFIX + ".owner_encode").inc()
        telemetry.emit("serve.shard.place", image_id=image_id[:12],
                       shard=o, shards=shards, nbytes=entry.nbytes)
        return entry

    def _note_failure(self, shard: int) -> None:
        """One placement failure on `shard` (caller holds self._lock);
        crossing `fail_threshold` consecutive failures marks it dead."""
        n = self._fail_counts.get(shard, 0) + 1
        self._fail_counts[shard] = n
        if (n >= self.fail_threshold and shard not in self._dead
                and len(self._dead) + 1 < len(self.shards)):
            self._mark_dead(shard, failures=n)

    def _mark_dead(self, shard: int, failures: int) -> None:
        """Caller holds self._lock. The dead shard's residency is DROPPED
        (its memory died with it) and its key range re-routes via
        `_alive_owner` from this point on."""
        dropped = len(self.shards[shard])
        per = (self.capacity_bytes // len(self.shards)
               if self.capacity_bytes else 0)
        self.shards[shard] = MPICache(capacity_bytes=per, quant=self.quant)
        self._dead.add(shard)
        self.failovers += 1
        telemetry.counter(_METRIC_PREFIX + ".dead_total").inc()
        telemetry.gauge(_METRIC_PREFIX + ".dead").set(len(self._dead))
        telemetry.emit("serve.shard_dead", shard=shard,
                       shards=len(self.shards), failures=failures,
                       dropped=dropped)

    def mark_dead(self, shard: int) -> None:
        """Operator/test override: force a shard dead now (the organic path
        is `fail_threshold` consecutive placement failures)."""
        with self._lock:
            if shard in self._dead:
                return
            if len(self._dead) + 1 >= len(self.shards):
                raise RuntimeError("refusing to kill the last alive shard")
            self._mark_dead(shard, failures=self._fail_counts.get(shard, 0))

    def _remap_locked(self) -> int:
        """Move every resident entry to its current alive owner (caller
        holds self._lock) — the same walk `rebalance` does, over the live
        shard list instead of a rebuilt one. Returns the move count."""
        moved = 0
        for idx, shard in enumerate(self.shards):
            for image_id in shard.keys():  # LRU order: recency survives
                new_idx = self._alive_owner(image_id)
                if new_idx != idx:
                    entry = shard.pop(image_id)
                    self.shards[new_idx].adopt(image_id, entry)
                    moved += 1
        return moved

    def mark_alive(self, shard: int) -> int:
        """Re-adopt a recovered shard: clear its dead mark, then remap —
        entries its range parked on fallback shards move back to it.
        Returns the move count (0 if the shard wasn't dead)."""
        with self._lock:
            if shard not in self._dead:
                return 0
            self._dead.discard(shard)
            self._fail_counts.pop(shard, None)
            moved = self._remap_locked()
            shards = len(self.shards)
            dead_now = len(self._dead)
        telemetry.counter(_METRIC_PREFIX + ".rebalance").inc(moved)
        telemetry.gauge(_METRIC_PREFIX + ".dead").set(dead_now)
        telemetry.emit("serve.shard_revive", shard=shard, shards=shards,
                       moved=moved)
        return moved

    def rebalance(self, num_shards: int) -> int:
        """Repartition to `num_shards` key ranges, moving every resident
        entry whose owner changed; returns the move count. The per-shard
        budget is re-derived from the fleet-level `capacity_bytes`. A
        rebalance REBUILDS every shard, so dead marks and failure tallies
        reset — the new topology starts clean."""
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        with self._lock:
            old = self.shards
            per = (self.capacity_bytes // num_shards
                   if self.capacity_bytes else 0)
            self.shards = [MPICache(capacity_bytes=per, quant=self.quant)
                           for _ in range(num_shards)]
            self._dead.clear()
            self._fail_counts.clear()
            moved = 0
            for old_idx, shard in enumerate(old):
                for image_id in shard.keys():  # LRU order: recency survives
                    entry = shard._entries[image_id]
                    new_idx = self.owner(image_id)
                    self.shards[new_idx].adopt(image_id, entry)
                    moved += int(new_idx != old_idx)
            self.rebalances += 1
            entries = sum(len(s) for s in self.shards)
        telemetry.gauge(_METRIC_PREFIX + ".dead").set(0)
        telemetry.counter(_METRIC_PREFIX + ".rebalance").inc(moved)
        telemetry.emit("serve.shard.rebalance", from_shards=len(old),
                       to_shards=num_shards, moved=moved,
                       entries=entries)
        return moved

    def stats(self) -> dict:
        with self._lock:
            per_shard = [{"entries": len(s), "nbytes": s.nbytes,
                          "dead": i in self._dead}
                         for i, s in enumerate(self.shards)]
            dead = sorted(self._dead)
            shard_stats = [s.stats() for s in self.shards]
        agg = {"entries": sum(p["entries"] for p in per_shard),
               "nbytes": sum(p["nbytes"] for p in per_shard),
               "shards": len(per_shard), "quant": self.quant,
               "owner_hits": self.owner_hits,
               "remote_routes": self.remote_routes,
               "owner_encodes": self.owner_encodes,
               "rebalances": self.rebalances,
               "failovers": self.failovers,
               "dead_shards": dead}
        for k in ("hits", "misses", "evictions"):
            agg[k] = sum(s[k] for s in shard_stats)
        agg["per_shard"] = per_shard
        return agg


class ServeFleet:
    """Front door of the sharded serving fleet: one mesh render engine over
    a key-range-sharded cache, fed by the continuous batcher.

    `submit` is the request path (front-end shard assigned round-robin,
    key routed to its owner, render coalesced by the scheduler); `render` /
    `render_many` pass through to the engine for trajectory-style callers
    (serve_cli's video path).

    Self-protection (PR 11, all default-off): an `AdmissionController`
    sheds/degrades low tiers under pressure (serve/admission.py), requests
    carry priority tiers and deadlines into the batcher, the engine retries
    transient encode failures with jittered backoff, and the sharded cache
    fails over dead shards. `/healthz` on the ops endpoint reports
    `degraded` when the error budget is burning > 1x or a shard is dead.
    """

    def __init__(self, *,
                 mesh_batch: int = 1,
                 mesh_model: int = 1,
                 cache_shards: int = 1,
                 cache_bytes: int = 0,
                 cache_quant: str = "bf16",
                 scheduler: str = "continuous",
                 max_requests: int = 8,
                 max_wait_ms: float = 2.0,
                 max_bucket: int = 8,
                 encode_fn: Optional[Callable] = None,
                 start: bool = True,
                 devices=None,
                 trace_sample: Optional[float] = None,
                 slo_objective_ms: float = 0.0,
                 slo_target: float = 0.99,
                 slo_window_s: float = 60.0,
                 ops_port: Optional[int] = None,
                 default_tier: int = 1,
                 request_deadline_ms: float = 0.0,
                 encode_retries: int = 0,
                 encode_backoff_ms: float = 10.0,
                 shard_fail_threshold: int = 3,
                 admission_enabled: bool = False,
                 admission_burn_max: float = 1.0,
                 admission_queue_high: int = 64,
                 admission_inflight_high: int = 256,
                 admission_shed_factor: float = 2.0,
                 admission_hysteresis: float = 0.7,
                 aot_store_dir: str = "",
                 recorder=None,
                 **engine_kw):
        self.cache = ShardedPlaneCache(
            num_shards=cache_shards, capacity_bytes=cache_bytes,
            quant=cache_quant, fail_threshold=shard_fail_threshold)
        # serve.aot_store_dir: compiled-executable store (serve/aot.py) —
        # fleet warmup and shard revival boot from artifacts instead of
        # paying jit per bucket; "" keeps the engine exactly as before
        self.aot_store = AOTStore(aot_store_dir) if aot_store_dir else None
        self.engine = MeshRenderEngine(
            mesh_batch=mesh_batch, mesh_model=mesh_model, devices=devices,
            max_bucket=max_bucket, cache=self.cache, encode_fn=encode_fn,
            encode_retries=encode_retries,
            encode_backoff_ms=encode_backoff_ms,
            aot_store=self.aot_store,
            **engine_kw)
        if scheduler not in ("continuous", "micro"):
            raise ValueError(
                f"serve.scheduler must be continuous|micro, got {scheduler!r}")
        # trace_sample None = defer to the process-wide tracing.configure
        # rate; a number pins this fleet's own head-sampling rate
        self.trace_sample = trace_sample
        # the SLO tracker sees EVERY request (recording is cheap; sampling
        # is for traces) — the batcher's flush path feeds it
        self.slo = SLOTracker(objective_ms=slo_objective_ms,
                              target=slo_target, window_s=slo_window_s)
        # the admission controller's burn signal is the SLO tracker's
        # cached ratio (lock-free read — slo.burn)
        self.admission: Optional[AdmissionController] = None
        if admission_enabled:
            self.admission = AdmissionController(
                enabled=True, burn_max=admission_burn_max,
                queue_high=admission_queue_high,
                inflight_high=admission_inflight_high,
                shed_factor=admission_shed_factor,
                hysteresis=admission_hysteresis,
                burn_fn=lambda: self.slo.burn)
        batcher_cls = ContinuousBatcher if scheduler == "continuous" \
            else MicroBatcher
        self.batcher = batcher_cls(self.engine, max_requests=max_requests,
                                   max_wait_ms=max_wait_ms, start=start,
                                   slo=self.slo, auto_trace=False,
                                   admission=self.admission,
                                   default_tier=default_tier,
                                   request_deadline_ms=request_deadline_ms)
        self._front = itertools.count()
        # opt-in flight recorder (telemetry/recorder.py): the fleet doesn't
        # own it (the configuring caller closes it) — it registers its
        # state/SLO context so triggered bundles capture admission level,
        # shard health and the SLO window at the moment of the incident,
        # and feeds the /incidents route below. The recorder's event tee
        # auto-triggers on this fleet's slo_breach/shard_dead/shed edges.
        self.recorder = recorder
        if recorder is not None:
            recorder.set_slo(self.slo)
            recorder.add_state_provider("fleet", self.stats)
            recorder.add_state_provider("health", self.health)
        # opt-in live ops plane; port 0 binds ephemeral (tests), None = off
        self.ops: Optional[OpsServer] = None
        if ops_port is not None:
            self.ops = OpsServer(
                port=ops_port, slo=self.slo, health=self.health,
                incidents=(recorder.list_incidents
                           if recorder is not None else None)).start()

    @classmethod
    def from_config(cls, serve_cfg, encode_fn=None, start: bool = True,
                    devices=None, recorder=None, **engine_kw) -> "ServeFleet":
        """Build from a config.ServeConfig (the serve.* key block).
        serve.ops_port 0 means "no endpoint" at the config surface (the
        ephemeral-port niche is a test concern, not a YAML one)."""
        return cls(mesh_batch=serve_cfg.mesh_batch,
                   mesh_model=serve_cfg.mesh_model,
                   cache_shards=serve_cfg.cache_shards,
                   cache_bytes=serve_cfg.cache_bytes,
                   cache_quant=serve_cfg.cache_quant,
                   scheduler=serve_cfg.scheduler,
                   max_requests=serve_cfg.max_requests,
                   max_wait_ms=serve_cfg.max_wait_ms,
                   max_bucket=serve_cfg.max_bucket,
                   slo_objective_ms=serve_cfg.slo_objective_ms,
                   slo_target=serve_cfg.slo_target,
                   slo_window_s=serve_cfg.slo_window_s,
                   ops_port=serve_cfg.ops_port if serve_cfg.ops_port > 0
                   else None,
                   default_tier=serve_cfg.default_tier,
                   request_deadline_ms=serve_cfg.request_deadline_ms,
                   encode_retries=serve_cfg.encode_retries,
                   encode_backoff_ms=serve_cfg.encode_backoff_ms,
                   shard_fail_threshold=serve_cfg.shard_fail_threshold,
                   admission_enabled=serve_cfg.admission_enabled,
                   admission_burn_max=serve_cfg.admission_burn_max,
                   admission_queue_high=serve_cfg.admission_queue_high,
                   admission_inflight_high=serve_cfg.admission_inflight_high,
                   admission_shed_factor=serve_cfg.admission_shed_factor,
                   admission_hysteresis=serve_cfg.admission_hysteresis,
                   aot_store_dir=serve_cfg.aot_store_dir,
                   encode_fn=encode_fn, start=start, devices=devices,
                   recorder=recorder, **engine_kw)

    def num_devices(self) -> int:
        return self.engine.num_devices()

    def submit(self, image_id: str, pose_44, tier: Optional[int] = None,
               deadline_ms: Optional[float] = None, image=None):
        """One view request through the fleet: round-robin front-end shard,
        owner routing (telemetry), scheduler coalescing. Resolves to
        (rgb [3,H,W], depth [1,H,W]) f32 numpy.

        `tier` is the request's priority class (serve/admission.py tier
        constants; None = the fleet's default_tier), `deadline_ms` its
        end-to-end budget (None = the fleet default; expired requests are
        purged un-rendered), `image` the pixels for a sync-encode on miss.
        A shed request's future resolves to `RequestShed`.

        A sampled request's trace is born HERE — the route decision is its
        first child span (front shard, owner shard, remote hop or not) and
        the context then rides the batcher's queue into the flush thread."""
        caller = next(self._front) % self.cache.num_shards
        trace = tracing.start("serve.request", sample=self.trace_sample,
                              image_id=str(image_id)[:12])
        t0 = time.perf_counter()
        owner = self.cache.route(caller, image_id)
        if trace is not None:
            trace.add_span("route", (time.perf_counter() - t0) * 1e3, t0=t0,
                           front_shard=caller, owner_shard=owner,
                           remote=caller != owner)
        return self.batcher.submit(image_id, pose_44, trace=trace,
                                   tier=tier, deadline_ms=deadline_ms,
                                   image=image)

    def render(self, image_id: str, poses_P44, **kw):
        return self.engine.render(image_id, poses_P44, **kw)

    def render_many(self, requests, **kw):
        return self.engine.render_many(requests, **kw)

    def encode(self, img_hwc, image_id: Optional[str] = None) -> str:
        return self.engine.encode(img_hwc, image_id=image_id)

    def warmup(self, image_id: str, **kw) -> None:
        self.engine.warmup(image_id, **kw)

    def revive_shard(self, shard: int,
                     warm_image_id: Optional[str] = None) -> int:
        """Bring a dead cache shard back: re-adopt its stragglers
        (ShardedPlaneCache.mark_alive) and — when `warm_image_id` names a
        cached entry — re-run the store-aware engine warmup so the revived
        shard's first requests dispatch pre-compiled executables, never a
        live jit. Returns the number of re-adopted entries."""
        moved = self.cache.mark_alive(shard)
        if warm_image_id is not None:
            self.engine.warmup(warm_image_id)
        return moved

    def health(self) -> dict:
        """Liveness with a degraded flag (what /healthz serves): the fleet
        is `degraded` — still up, still HTTP 200 — when the error budget is
        burning faster than 1x or any cache shard is marked dead."""
        dead = self.cache.dead_shards
        burn = self.slo.burn
        degraded = bool(dead) or burn > 1.0
        return {"status": "degraded" if degraded else "ok",
                "error_budget_burn": round(burn, 4),
                "dead_shards": dead,
                "admission": self.admission.state if self.admission
                else "off"}

    def stats(self) -> dict:
        s = self.cache.stats()
        s.update(device_calls=self.engine.device_calls,
                 sync_encodes=self.engine.sync_encodes,
                 flushes=self.batcher.flushes,
                 slo_breaches=self.slo.breaches,
                 expired=self.batcher.expired,
                 shed=self.admission.shed if self.admission else 0,
                 degraded=self.admission.degraded if self.admission else 0,
                 mesh=f"{self.engine.mesh_batch}x{self.engine.mesh_model}")
        return s

    def close(self) -> None:
        self.batcher.close()
        if self.ops is not None:
            self.ops.close()
            self.ops = None
