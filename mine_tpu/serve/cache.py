"""Encode-once MPI cache: quantized plane storage under a byte budget.

MINE's economic property is that one encoder-decoder pass yields an MPI from
which arbitrarily many views render by warp+composite alone. Serving many
views per image therefore wants the encode result RESIDENT — this module is
that residency layer: an LRU keyed by image id under a byte budget, planes
stored quantized so the cache holds 2-4x more scenes per GB of HBM/RAM.

Quantization modes (serve.cache_quant):
  float32  no compression (exact; the eval-parity default)
  bf16     planes cast to bfloat16 (default). Dequant (astype f32) is a
           WIDENING cast — every bf16 value is exactly representable in
           f32 — so dequantization is deterministic and bit-stable: the
           rendered view from a bf16 cache entry is bitwise-identical to
           rendering from the host-dequantized planes (tests/test_serve.py).
  int8     symmetric per-plane-per-channel int8 with f32 scales:
           scale[s,c] = max|x[s,c]| / 127, q = round(x/scale). The absolute
           dequant error is bounded by scale/2 = max|x|/254 per (plane,
           channel) — documented AND test-enforced (tests/test_serve.py).

Dequantization is fused into the serving engine's jitted render program
(serve/engine.py), so the cache-resident form is what crosses HBM.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from mine_tpu import telemetry

QUANT_MODES = ("float32", "bf16", "int8")


def image_id_for(img: np.ndarray) -> str:
    """Content-addressed cache key: sha1 of the raw image bytes (no dataset
    cooperation needed — two requests for the same pixels share an entry)."""
    arr = np.ascontiguousarray(np.asarray(img))
    return hashlib.sha1(arr.tobytes()).hexdigest()


def quantize_planes(planes_SCHW: jnp.ndarray,
                    quant: str) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """f32 [S,C,H,W] planes -> (stored array, scales|None).

    int8 scales are [S,C,1,1] f32 (symmetric, zero-point-free); the all-zero
    plane guard keeps scale finite so 0 round-trips to exactly 0.
    """
    if quant not in QUANT_MODES:
        raise ValueError(f"quant must be one of {QUANT_MODES}, got {quant!r}")
    planes = jnp.asarray(planes_SCHW, jnp.float32)
    if quant == "float32":
        return planes, None
    if quant == "bf16":
        return planes.astype(jnp.bfloat16), None
    amax = jnp.max(jnp.abs(planes), axis=(-1, -2), keepdims=True)  # [S,C,1,1]
    scales = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(planes / scales), -127, 127).astype(jnp.int8)
    return q, scales


def dequantize_planes(stored: jnp.ndarray,
                      scales: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Inverse of quantize_planes; always f32 out. Mirrors the in-jit dequant
    of serve/engine.py (kept in sync by the engine parity tests)."""
    x = stored.astype(jnp.float32)
    if stored.dtype == jnp.int8:
        if scales is None:
            raise ValueError("int8 planes need their scales")
        x = x * scales
    return x


class MPIEntry(NamedTuple):
    """One cached encode: quantized planes + the geometry to render them."""
    planes: jnp.ndarray            # [S,4,H,W] rgb+sigma, f32/bf16/int8
    scales: Optional[jnp.ndarray]  # [S,4,1,1] f32 (int8 only, else None)
    disparity: jnp.ndarray         # [S] f32 plane disparities
    K: jnp.ndarray                 # [3,3] f32 source intrinsics
    nbytes: int

    def dequantized(self) -> jnp.ndarray:
        return dequantize_planes(self.planes, self.scales)


def _entry_nbytes(entry_arrays) -> int:
    return int(sum(np.dtype(a.dtype).itemsize * int(np.prod(a.shape))
                   for a in entry_arrays if a is not None))


def _sync_cache_gauges(cache) -> None:
    """Mirror a cache's residency into the registry (both cache classes)."""
    telemetry.gauge(cache._METRIC_PREFIX + ".entries").set(len(cache._entries))
    telemetry.gauge(cache._METRIC_PREFIX + ".nbytes").set(cache.nbytes)


class MPICache:
    """LRU over MPIEntry under `capacity_bytes` (0 = unbounded).

    get() refreshes recency; put() evicts least-recently-used entries until
    the new total fits (a single entry larger than the budget still stores —
    it just evicts everything else first). hits/misses/evictions counters
    feed serve_cli's stats line and the amortization bench; the same
    counts mirror into the telemetry registry under `serve.cache.*`
    (instance attrs are per-cache, registry counters are process-wide).
    """

    _METRIC_PREFIX = "serve.cache"

    def __init__(self, capacity_bytes: int = 0, quant: str = "bf16"):
        if quant not in QUANT_MODES:
            raise ValueError(
                f"quant must be one of {QUANT_MODES}, got {quant!r}")
        self.capacity_bytes = int(capacity_bytes)
        self.quant = quant
        self._entries: "OrderedDict[str, MPIEntry]" = OrderedDict()
        self.nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, image_id: str) -> bool:
        return image_id in self._entries

    def keys(self):
        """Ids in eviction order (least-recently-used first)."""
        return list(self._entries.keys())

    def put(self, image_id: str,
            mpi_rgb_S3HW: jnp.ndarray,
            mpi_sigma_S1HW: jnp.ndarray,
            disparity_S: jnp.ndarray,
            K_33: jnp.ndarray,
            quant: Optional[str] = None) -> MPIEntry:
        # `quant` overrides the cache's storage mode for THIS entry only —
        # the degradation ladder (serve/admission.py) places a degraded
        # request's encode at the next-cheaper mode; None keeps the default
        planes = jnp.concatenate(
            [jnp.asarray(mpi_rgb_S3HW, jnp.float32),
             jnp.asarray(mpi_sigma_S1HW, jnp.float32)], axis=1)  # [S,4,H,W]
        stored, scales = quantize_planes(planes, quant or self.quant)
        disparity = jnp.asarray(disparity_S, jnp.float32)
        K = jnp.asarray(K_33, jnp.float32)
        entry = MPIEntry(
            planes=stored, scales=scales, disparity=disparity, K=K,
            nbytes=_entry_nbytes((stored, scales, disparity, K)))
        old = self._entries.pop(image_id, None)
        if old is not None:
            self.nbytes -= old.nbytes
        self._entries[image_id] = entry
        self.nbytes += entry.nbytes
        if self.capacity_bytes > 0:
            while self.nbytes > self.capacity_bytes and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self.nbytes -= evicted.nbytes
                self.evictions += 1
                telemetry.counter(self._METRIC_PREFIX + ".evictions").inc()
        _sync_cache_gauges(self)
        return entry

    def adopt(self, image_id: str, entry: MPIEntry) -> MPIEntry:
        """Insert an ALREADY-quantized entry (a rebalance move between the
        fleet's cache shards — serve/fleet.py): same replace/budget/eviction
        semantics as put(), without re-quantizing the planes."""
        old = self._entries.pop(image_id, None)
        if old is not None:
            self.nbytes -= old.nbytes
        self._entries[image_id] = entry
        self.nbytes += entry.nbytes
        if self.capacity_bytes > 0:
            while self.nbytes > self.capacity_bytes and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self.nbytes -= evicted.nbytes
                self.evictions += 1
                telemetry.counter(self._METRIC_PREFIX + ".evictions").inc()
        _sync_cache_gauges(self)
        return entry

    def get(self, image_id: str) -> Optional[MPIEntry]:
        entry = self._entries.get(image_id)
        if entry is None:
            self.misses += 1
            telemetry.counter(self._METRIC_PREFIX + ".misses").inc()
            return None
        self.hits += 1
        telemetry.counter(self._METRIC_PREFIX + ".hits").inc()
        self._entries.move_to_end(image_id)
        return entry

    def pop(self, image_id: str) -> Optional[MPIEntry]:
        """Remove an entry WITHOUT counting an eviction (the fleet's
        failover remap moves it to another shard — serve/fleet.py — so it
        stays resident somewhere; an eviction count would misread as
        budget pressure)."""
        entry = self._entries.pop(image_id, None)
        if entry is not None:
            self.nbytes -= entry.nbytes
            _sync_cache_gauges(self)
        return entry

    def stats(self) -> dict:
        return {"entries": len(self._entries), "nbytes": self.nbytes,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "quant": self.quant}


class PyramidCache:
    """Eval-loop sibling of MPICache: caches one encode's FULL multi-scale
    MPI pyramid (per-scale [S,4,h,w] plane volumes) plus the disparity row
    the encode was conditioned on.

    The eval loop (train/loop.py run_eval, serve.eval_encode_once) encodes
    each distinct source image once and replays the pyramid for every
    (src, tgt) pair; the loss consumes all scales, so the whole pyramid is
    the cache unit (one entry evicts atomically — no partial pyramids).
    Same LRU/byte-budget/quantization semantics as MPICache; registry
    metrics land under `serve.eval_cache.*`.
    """

    _METRIC_PREFIX = "serve.eval_cache"

    def __init__(self, capacity_bytes: int = 0, quant: str = "float32"):
        if quant not in QUANT_MODES:
            raise ValueError(
                f"quant must be one of {QUANT_MODES}, got {quant!r}")
        self.capacity_bytes = int(capacity_bytes)
        self.quant = quant
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self.nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, image_id: str) -> bool:
        return image_id in self._entries

    def put(self, image_id: str, mpi_list, disparity_S) -> None:
        stored = [quantize_planes(m, self.quant) for m in mpi_list]
        disparity = jnp.asarray(disparity_S, jnp.float32)
        nbytes = _entry_nbytes(
            [a for pair in stored for a in pair] + [disparity])
        old = self._entries.pop(image_id, None)
        if old is not None:
            self.nbytes -= old[2]
        self._entries[image_id] = (stored, disparity, nbytes)
        self.nbytes += nbytes
        if self.capacity_bytes > 0:
            while self.nbytes > self.capacity_bytes and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self.nbytes -= evicted[2]
                self.evictions += 1
                telemetry.counter(self._METRIC_PREFIX + ".evictions").inc()
        _sync_cache_gauges(self)

    def get(self, image_id: str):
        """-> (per-scale dequantized f32 volumes, disparity [S]) or None."""
        entry = self._entries.get(image_id)
        if entry is None:
            self.misses += 1
            telemetry.counter(self._METRIC_PREFIX + ".misses").inc()
            return None
        self.hits += 1
        telemetry.counter(self._METRIC_PREFIX + ".hits").inc()
        self._entries.move_to_end(image_id)
        stored, disparity, _ = entry
        return [dequantize_planes(q, s) for q, s in stored], disparity

    def stats(self) -> dict:
        return {"entries": len(self._entries), "nbytes": self.nbytes,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "quant": self.quant}
