"""Host transport for the serving ring: stdlib HTTP/JSON host server +
client, SIGTERM drain, and the subprocess host entrypoint.

`HostServer` puts ONE ring host on the network: today's ServeFleet as the
local slice behind a `ThreadingHTTPServer` (the exact telemetry/export.py
OpsServer idiom — daemon thread, loopback default, port 0 = ephemeral, no
new deps). The wire format is JSON with base64 float32 arrays, so a
render round-trips BITWISE (tests/test_serve_ring.py pins HTTP == local):

    POST /render   {"image_id", "pose": [16 row-major floats], "tier",
                    "deadline_ms", "image": {shape,dtype,b64} | null}
                -> {"ok": true, "rgb": {...}, "depth": {...}}
                   or an error envelope {"ok": false, "kind", "error"}
                   (429 shed, 504 deadline, 503 draining — the client
                   re-raises the matching exception class, so admission
                   semantics survive the wire)
    GET  /healthz  fleet health + {"host", "state", "inflight"}
    GET  /stats    fleet stats + AOT boot evidence (bucket_loads/compiles)
    GET  /metrics  Prometheus text of this process's registry
    POST /drain    begin draining (the programmatic SIGTERM)

Preemption is ported serve-side from the train loop (train/resilience.py
PreemptionHandler): SIGTERM/SIGINT only flips the sticky flag; a watcher
thread then runs the drain — stop admitting (503), wait out the in-flight
requests (bounded by drain_timeout_s), emit the authoritative
`serve.host_drain` with the host's lifetime owner-hit/remote-route split,
dump a flight-recorder incident bundle when a recorder is armed, and close
the fleet. The key range hands back to the ring the moment any front
observes the 503 (serve/ring.py re-resolves ring-wise).

The transport is WIRE-HARDENED behind `serve.net.*` (all default off;
net-off constructs none of the machinery and stays bitwise-identical,
test-pinned): `NetPolicy` gives the client split connect/read timeouts,
bounded jittered-exponential-backoff retries (safe: a render is a pure
function of key+pose, so at-least-once is idempotent), a per-host
`CircuitBreaker` (closed -> open -> half-open with single-probe
admission, pinned `serve.breaker` events), and deadline propagation —
the budget LEFT rides the `X-Mtpu-Deadline-Left-Ms` header so a host
SWEEPS work the front already expired into the existing DeadlineExceeded
envelope instead of rendering it. Connections are kept alive per thread
(HTTP/1.1 + reconnect-on-stale), and every network fault a test needs —
latency, refusal, mid-response reset, truncation, partition — is
injected through the testing/faults.py net_* seams, never by
monkeypatching this module.

Since PR 20 the JSON wire has a negotiated BINARY sibling (serve/wire.py,
`serve.wire.*` keys, default off): a wire-enabled server advertises
`X-Mtpu-Wire: mtpu-wire1` on every response and accepts
`application/x-mtpu-wire1` batch frames on /render; a wire-enabled client
checks the advertisement once (a /healthz round) and speaks binary —
length-prefixed frames, raw little-endian tensors, f32/bf16/int8 wire
codecs, N coalesced requests per exchange — only to a peer that
advertised, falling back to the byte-identical JSON path otherwise
(counted `serve.wire.fallbacks`). ALL framing, JSON and binary, is built
and parsed by serve/wire.py helpers, so negotiation lives in exactly one
seam; a corrupted/truncated binary frame is rejected by the mtpu-wire1
tripwires and RETRIED like mangled JSON, never crashed on.

`main()` is the deployable unit's entrypoint: boot a host from a PACKED
AOT artifact (tools/aot_warmstore.py --pack) with zero live compiles and
serve until drained. Run `python -m mine_tpu.serve.hostnet --help`.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import random
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from mine_tpu import telemetry
from mine_tpu.analysis.locks import ordered_condition, ordered_lock
from mine_tpu.serve import wire
from mine_tpu.serve.admission import DeadlineExceeded, RequestShed
from mine_tpu.serve.ring import (HOST_ALIVE, HOST_DRAINING, BreakerOpen,
                                 HostUnavailable)
# the JSON tensor wire now lives in serve/wire.py (one framing seam for
# both formats); re-exported here because tools/tests import them from
# hostnet, the historical home
from mine_tpu.serve.wire import pack_array, unpack_array  # noqa: F401
from mine_tpu.testing import faults

# synthetic-host geometry (--synthetic): matches tools/serve_chaos_soak.py
# so the soak's keys/images render identically through subprocess hosts
SYN_S, SYN_HW = 4, 8


def synthetic_encode_fn(img_hwc):
    """The soak's deterministic tiny encoder (image bytes -> fixed MPI),
    shared here so subprocess hosts and in-parent builders produce
    IDENTICAL programs and plane data — the cross-process bitwise and
    zero-compile-join assertions depend on it."""
    rng = np.random.RandomState(int(np.asarray(img_hwc).sum()) % 1000)
    p = rng.uniform(-1, 1, (SYN_S, 4, SYN_HW, SYN_HW)).astype(np.float32)
    return (p[:, 0:3], p[:, 3:4],
            np.linspace(1.0, 0.2, SYN_S, dtype=np.float32),
            np.eye(3, dtype=np.float32))


# wire error envelope <-> exception class: the admission layer's verdicts
# must survive the HTTP hop (a shed best-effort request on a remote host
# is STILL a RequestShed to the front's caller, not a transport error)
_KIND_STATUS = {"RequestShed": 429, "DeadlineExceeded": 504,
                "HostUnavailable": 503}
_KIND_RAISE = {"RequestShed": RequestShed,
               "DeadlineExceeded": DeadlineExceeded,
               "HostUnavailable": HostUnavailable}

# the front's remaining deadline budget, in milliseconds, as seen at send
# time — the server sweeps non-positive values into the 504 envelope
DEADLINE_HEADER = "X-Mtpu-Deadline-Left-Ms"


@dataclasses.dataclass(frozen=True)
class NetPolicy:
    """The serve.net.* knobs as one immutable value (config.py parses the
    keys; serve_cli builds this and hands it to every HostClient and the
    RingFront). `enabled=False` — the default — constructs NONE of the
    hardening: no breaker, no retries, no deadline header, no prober."""

    enabled: bool = False
    connect_timeout_s: float = 5.0   # TCP connect budget (fail fast)
    read_timeout_s: float = 60.0     # response budget (renders are slow)
    retries: int = 2                 # extra attempts after the first
    backoff_ms: float = 20.0         # base of the jittered exponential
    breaker_threshold: int = 5       # consecutive failures -> open
    breaker_reset_s: float = 10.0    # open -> half-open after this long
    probe_interval_s: float = 0.0    # front heartbeat period (0 = off)
    suspect_misses: int = 3          # consecutive probe misses -> suspect
    dead_misses: int = 10            # consecutive REFUSED -> mark_dead
    revive_probes: int = 2           # consecutive oks -> clear suspicion


class CircuitBreaker:
    """Per-host client-side circuit: closed -> open after `threshold`
    consecutive failures, open -> half-open after `reset_s`, half-open
    admits ONE probe at a time — success closes, failure re-opens. State
    transitions emit the pinned `serve.breaker` event and bump
    `serve.net.breaker_<state>`; emits happen AFTER the lock releases
    (the "serve.net.breaker" rank sits below telemetry, see
    analysis/locks.py). `now_fn` is injectable so tests drive the reset
    window with a fake clock."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, host: str, threshold: int, reset_s: float,
                 now_fn=time.monotonic):
        self.host = str(host)
        self.threshold = int(threshold)
        self.reset_s = float(reset_s)
        self._now = now_fn
        self._lock = ordered_lock("serve.net.breaker")
        self.state = self.CLOSED
        self.failures = 0
        self.opens = 0
        self._opened_at = 0.0
        self._probing = False

    def allow(self) -> bool:
        """May a request go to the wire right now?"""
        transition = None
        ok = False
        with self._lock:
            if self.state == self.CLOSED:
                ok = True
            elif self.state == self.OPEN:
                if self._now() - self._opened_at >= self.reset_s:
                    self.state = self.HALF_OPEN
                    self._probing = True
                    transition = self.HALF_OPEN
                    ok = True
            else:  # HALF_OPEN: one probe in flight at a time
                if not self._probing:
                    self._probing = True
                    ok = True
            failures = self.failures
        if transition:
            self._emit(transition, failures)
        return ok

    def record(self, ok: bool) -> None:
        """Feed one wire verdict (every attempt, probe or request)."""
        transition = None
        with self._lock:
            self._probing = False
            if ok:
                if self.state != self.CLOSED:
                    transition = self.CLOSED
                self.state = self.CLOSED
                self.failures = 0
            else:
                self.failures += 1
                if (self.state == self.HALF_OPEN
                        or (self.state == self.CLOSED
                            and self.failures >= self.threshold)):
                    self.opens += 1
                    transition = self.OPEN
                    self.state = self.OPEN
                    self._opened_at = self._now()
            failures = self.failures
        if transition:
            self._emit(transition, failures)

    def _emit(self, state: str, failures: int) -> None:
        telemetry.emit("serve.breaker", host=self.host, state=state,
                       failures=int(failures))
        telemetry.counter(f"serve.net.breaker_{state}").inc()

    def snapshot(self) -> Dict:
        with self._lock:
            return {"state": self.state, "failures": self.failures,
                    "opens": self.opens}


class HostServer:
    """One ring host: a ServeFleet behind the stdlib HTTP/JSON transport.

    Construct bound (port 0 = ephemeral; read `.port`), then `.start()`.
    `drain()` is idempotent and runs the full hand-back sequence; the
    `drained` event fires when it completes (main() exits on it).
    """

    def __init__(self, fleet, host_id: str, port: int = 0,
                 host: str = "127.0.0.1", drain_timeout_s: float = 30.0,
                 recorder=None, wire_policy=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.fleet = fleet
        self.host_id = str(host_id)
        self.drain_timeout_s = float(drain_timeout_s)
        self.recorder = recorder
        # serve.wire.*: with a binary WirePolicy the server ADVERTISES
        # mtpu-wire1 on every response and accepts binary batch frames on
        # /render. None (the default) is the exact PR-19 server: no
        # advertisement header, JSON only — byte-identical, test-pinned.
        self.wire = wire_policy if (wire_policy is not None
                                    and wire_policy.binary) else None
        self.draining = False
        self.inflight = 0
        self.requests = 0
        self.swept = 0  # requests the deadline header expired on arrival
        self.drained = threading.Event()
        self._cv = ordered_condition("serve.hostnet.state")
        srv = self

        class _Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 + the always-set Content-Length = keep-alive:
            # the client's per-thread connection survives across
            # renders instead of paying TCP setup on every request
            protocol_version = "HTTP/1.1"

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if srv.wire is not None:
                    # the capability advertisement the client's one-time
                    # negotiation check reads (serve/wire.py)
                    self.send_header(wire.WIRE_HEADER, wire.WIRE_PROTO)
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, obj: Dict) -> None:
                self._send(code, (json.dumps(obj) + "\n").encode())

            def do_GET(self):  # noqa: N802 (stdlib handler API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._send_json(200, srv.healthz())
                    elif path == "/stats":
                        self._send_json(200, srv.stats())
                    elif path == "/metrics":
                        from mine_tpu.telemetry.export import (
                            CONTENT_TYPE, render_prometheus)
                        self._send(200, render_prometheus().encode(),
                                   CONTENT_TYPE)
                    else:
                        self._send_json(404, {"error": "not found"})
                except BrokenPipeError:
                    pass

            def do_POST(self):  # noqa: N802 (stdlib handler API)
                path = self.path.split("?", 1)[0]
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    raw_body = self.rfile.read(n)
                    if path == "/render":
                        left = None
                        raw = self.headers.get(DEADLINE_HEADER)
                        if raw is not None:
                            try:
                                left = float(raw)
                            except ValueError:
                                left = None  # malformed = absent
                        ctype = (self.headers.get("Content-Type")
                                 or "").split(";")[0].strip()
                        if (srv.wire is not None
                                and ctype == wire.CTYPE_BINARY):
                            telemetry.counter(
                                "serve.wire.bytes_rx").inc(len(raw_body))
                            code, payload, rctype = \
                                srv._handle_render_wire(
                                    raw_body, deadline_left_ms=left)
                            telemetry.counter(
                                "serve.wire.bytes_tx").inc(len(payload))
                            self._send(code, payload, rctype)
                            return
                        body = json.loads(raw_body or b"{}")
                        code, obj = srv._handle_render(
                            body, deadline_left_ms=left)
                        self._send_json(code, obj)
                        return
                    body = json.loads(raw_body or b"{}")
                    if path == "/drain":
                        # hand back asynchronously: the response must go
                        # out before the fleet starts tearing down
                        threading.Thread(target=srv.drain,
                                         kwargs={"reason": "http"},
                                         daemon=True).start()
                        self._send_json(200, {"ok": True,
                                              "host": srv.host_id})
                    else:
                        self._send_json(404, {"error": "not found"})
                except BrokenPipeError:
                    pass

            def log_message(self, fmt, *args):  # silence request noise
                pass

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread: Optional[threading.Thread] = None

    # -- request path -----------------------------------------------------

    def _handle_render(self, body: Dict, deadline_left_ms=None):
        """The legacy JSON /render: one request, one envelope — behavior
        (and bytes) identical to PR 19; parsing/packing now rides the
        serve/wire.py seam shared with the binary path."""
        if deadline_left_ms is not None and deadline_left_ms <= 0:
            # the front's budget was spent in flight: sweep instead of
            # rendering work nobody is waiting on — same verdict (and
            # client-side exception) as the batcher's own expiry sweep
            with self._cv:
                self.swept += 1
            telemetry.counter("serve.net.deadline_swept").inc()
            return 504, {"ok": False, "kind": "DeadlineExceeded",
                         "error": "deadline spent before host dispatch"}
        with self._cv:
            if self.draining:
                return 503, {"ok": False, "kind": "HostUnavailable",
                             "error": "draining"}
            self.inflight += 1
            self.requests += 1
        deadline_ms = body.get("deadline_ms")
        if deadline_left_ms is not None:
            # the host-local batcher sweeps against whichever budget is
            # tighter: the request's own or what the front has left
            deadline_ms = (min(float(deadline_ms), deadline_left_ms)
                           if deadline_ms else deadline_left_ms)
        try:
            req = wire.json_render_request(body)
            rgb, depth = self.fleet.submit(
                req["image_id"], req["pose"], tier=req["tier"],
                deadline_ms=deadline_ms, image=req["image"]).result()
            return 200, wire.json_render_envelope(
                {"ok": True, "rgb": rgb, "depth": depth})
        except Exception as e:
            kind = type(e).__name__
            return (_KIND_STATUS.get(kind, 500),
                    {"ok": False, "kind": kind, "error": str(e)})
        finally:
            with self._cv:
                self.inflight -= 1
                self._cv.notify_all()

    def _render_core(self, reqs: List[Dict], deadline_left_ms=None):
        """Admission + fleet dispatch for a decoded BATCH, in request
        order. Every admissible request is submitted before any result is
        collected, so an N-request frame rides the fleet's existing
        coalescing (the batcher groups the in-flight set into device
        batches exactly as it does for concurrent single requests).
        Returns one envelope per request — numpy rgb/depth when ok, the
        admission verdict (kind/error) otherwise; a shed or expired item
        never fails its batchmates."""
        out: List[Optional[Dict]] = [None] * len(reqs)
        pending = []
        for i, req in enumerate(reqs):
            if deadline_left_ms is not None and deadline_left_ms <= 0:
                with self._cv:
                    self.swept += 1
                telemetry.counter("serve.net.deadline_swept").inc()
                out[i] = {"ok": False, "kind": "DeadlineExceeded",
                          "error": "deadline spent before host dispatch"}
                continue
            with self._cv:
                if self.draining:
                    out[i] = {"ok": False, "kind": "HostUnavailable",
                              "error": "draining"}
                    continue
                self.inflight += 1
                self.requests += 1
            deadline_ms = req.get("deadline_ms")
            if deadline_left_ms is not None:
                deadline_ms = (min(float(deadline_ms), deadline_left_ms)
                               if deadline_ms else deadline_left_ms)
            try:
                fut = self.fleet.submit(
                    req["image_id"], req["pose"], tier=req.get("tier"),
                    deadline_ms=deadline_ms, image=req.get("image"))
            except Exception as e:
                with self._cv:
                    self.inflight -= 1
                    self._cv.notify_all()
                out[i] = {"ok": False, "kind": type(e).__name__,
                          "error": str(e)}
                continue
            pending.append((i, fut))
        for i, fut in pending:
            try:
                rgb, depth = fut.result()
                out[i] = {"ok": True, "rgb": rgb, "depth": depth}
            except Exception as e:
                out[i] = {"ok": False, "kind": type(e).__name__,
                          "error": str(e)}
            finally:
                with self._cv:
                    self.inflight -= 1
                    self._cv.notify_all()
        return out

    def _handle_render_wire(self, raw: bytes, deadline_left_ms=None):
        """One binary /render exchange: decode the mtpu-wire1 batch frame
        (hostile frames -> a 400 JSON envelope the client treats as
        non-retryable), dispatch through _render_core, and mirror the
        request's codec on the multi-result response frame."""
        t0 = time.monotonic()
        try:
            reqs, codec = wire.decode_render_request(raw)
        except wire.WireError as e:
            telemetry.counter("serve.wire.rejects").inc()
            env = {"ok": False, "kind": "WireError", "error": str(e)}
            return 400, (json.dumps(env) + "\n").encode(), wire.CTYPE_JSON
        telemetry.histogram("serve.wire.decode_ms").record(
            (time.monotonic() - t0) * 1e3)
        envs = self._render_core(reqs, deadline_left_ms=deadline_left_ms)
        t0 = time.monotonic()
        payload = wire.encode_render_response(envs, codec=codec)
        telemetry.histogram("serve.wire.encode_ms").record(
            (time.monotonic() - t0) * 1e3)
        # per-item verdicts travel INSIDE the frame envelopes (the client
        # re-raises typed per item); the HTTP status stays 200 for any
        # well-formed frame
        return 200, payload, wire.CTYPE_BINARY

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "HostServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"mine-tpu-host-{self.host_id}")
        self._thread.start()
        return self

    def drain(self, reason: str = "signal") -> None:
        """The hand-back sequence; idempotent, safe from any thread."""
        with self._cv:
            if self.draining:
                return
            self.draining = True
            deadline = time.monotonic() + self.drain_timeout_s
            while self.inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(timeout=min(left, 0.5))
            leftover = self.inflight
        cache = getattr(self.fleet, "cache", None)
        telemetry.emit(
            "serve.host_drain", host=self.host_id, hosts=0,
            inflight=leftover, reason=reason,
            owner_hits=getattr(cache, "owner_hits", 0),
            remote_routes=getattr(cache, "remote_routes", 0))
        if self.recorder is not None:
            try:
                self.recorder.trigger("host_drain", force=True, sync=True,
                                      host=self.host_id, reason=reason,
                                      inflight=leftover)
            except Exception:
                pass  # the bundle is evidence, not a drain dependency
        self.close()
        self.fleet.close()
        self.drained.set()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- introspection ----------------------------------------------------

    def healthz(self) -> Dict:
        out = dict(self.fleet.health())
        with self._cv:
            out.update(host=self.host_id,
                       state=HOST_DRAINING if self.draining
                       else HOST_ALIVE,
                       inflight=self.inflight)
        return out

    def stats(self) -> Dict:
        out = dict(self.fleet.stats())
        engine = getattr(self.fleet, "engine", None)
        with self._cv:
            out.update(host=self.host_id, requests=self.requests,
                       inflight=self.inflight, draining=self.draining,
                       swept=self.swept,
                       bucket_loads=getattr(engine, "bucket_loads", 0),
                       bucket_compiles=getattr(engine, "bucket_compiles",
                                               0))
        return out

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def install_drain_signals(server: HostServer):
    """Port of the train loop's preemption machinery: SIGTERM/SIGINT flip
    the handler's sticky flag (no I/O in the handler — resilience.py
    discipline), and a watcher thread runs the drain outside signal
    context. Returns the PreemptionHandler (uninstall() to restore)."""
    from mine_tpu.train.resilience import PreemptionHandler

    handler = PreemptionHandler().install()

    def _watch():
        while not handler.requested and not server.drained.is_set():
            time.sleep(0.05)
        if handler.requested:
            server.drain(reason="preempt")

    threading.Thread(target=_watch, daemon=True,
                     name=f"mine-tpu-drain-watch-{server.host_id}").start()
    return handler


# a kept-alive connection the server closed under us looks like one of
# these on the NEXT request — reconnect once, transparently (a fresh
# connection failing the same way is a real failure, not staleness)
_STALE = (http.client.BadStatusLine, http.client.CannotSendRequest,
          ConnectionResetError, BrokenPipeError)
# what a bounded retry may absorb: transport errors, protocol garbage,
# truncated/mangled JSON, and a binary frame that fails the mtpu-wire1
# tripwires (same class of damage as mangled JSON) — never an application
# verdict (the error envelope arrives as a 200..5xx with valid JSON and
# is re-raised typed)
_RETRYABLE = (OSError, http.client.HTTPException, json.JSONDecodeError,
              wire.WireError)


class HostClient:
    """Stdlib HTTP client half of the transport; satisfies the RingFront
    handle protocol (render/healthz/stats/close). Connections are kept
    alive PER THREAD (`threading.local` — the RingFront pool shares one
    client across workers, and http.client connections are not
    thread-safe), with one transparent reconnect when the server closed
    a kept-alive socket under us.

    With a NetPolicy (serve.net.*) the client is hardened: split
    connect/read timeouts, `retries` extra attempts with jittered
    exponential backoff, a per-host CircuitBreaker consulted before and
    fed after every wire attempt, and the request's remaining deadline
    budget sent as `X-Mtpu-Deadline-Left-Ms` (expired budget raises
    DeadlineExceeded CLIENT-side, without a wire attempt). Policy-off
    keeps the legacy single-attempt, single-timeout behavior.

    With a WirePolicy whose format is "binary" (serve.wire.*) the client
    NEGOTIATES: the first render checks whether the peer ever advertised
    `X-Mtpu-Wire` (one /healthz round if no response has been seen yet)
    and speaks mtpu-wire1 batch frames only to a peer that did, falling
    back to this exact JSON path otherwise — counted
    `serve.wire.fallbacks`, decided once per client lifetime. Wire-off
    (the default) constructs none of it and the request path is
    byte-identical to PR 19 (test-pinned).

    `net_src`/`net_name` tag this client's edge in the faults.py
    partition matrix ("src>dst") so tests sever individual links."""

    def __init__(self, address: str, timeout_s: float = 60.0,
                 policy: Optional[NetPolicy] = None, net_src: str = "front",
                 net_name: str = "",
                 wire_policy: Optional["wire.WirePolicy"] = None):
        host, port = address.rsplit(":", 1)
        self.host = host
        self.port = int(port)
        self.address = address
        self.timeout_s = float(timeout_s)
        self.policy = policy if (policy is not None
                                 and policy.enabled) else None
        self.breaker: Optional[CircuitBreaker] = None
        if self.policy is not None:
            self.breaker = CircuitBreaker(address,
                                          self.policy.breaker_threshold,
                                          self.policy.breaker_reset_s)
        self.net_src = str(net_src)
        self.net_name = str(net_name) or address
        self._local = threading.local()
        self.reconnects = 0  # stale keep-alive sockets replaced
        self.retries = 0     # policy retry attempts actually taken
        # payload bytes over this client's link, BOTH formats — the bench
        # derives bytes/view from deltas, so the JSON arm is measurable
        self.bytes_tx = 0
        self.bytes_rx = 0
        self.wire_policy = wire_policy if (wire_policy is not None
                                           and wire_policy.binary) else None
        self._wire_ok: Optional[bool] = None  # None = not yet negotiated
        self._server_wire = False  # peer advertised X-Mtpu-Wire
        self._neg_lock = ordered_lock("serve.wire.negotiate") \
            if self.wire_policy is not None else None

    # -- connection management (per thread) -------------------------------

    def _conn(self) -> "http.client.HTTPConnection":
        conn = getattr(self._local, "conn", None)
        if conn is None:
            timeout = (self.policy.connect_timeout_s if self.policy
                       else self.timeout_s)
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=timeout)
            self._local.conn = conn
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        self._local.conn = None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _wire(self, method: str, path: str, payload, headers):
        """One HTTP round over this thread's kept-alive connection.
        Returns (status, content-type, raw bytes) — decoding is the
        _decode_body seam's job, so the truncation fault can hand a CUT
        binary frame up to the mtpu-wire1 tripwires (proving the
        rejection path) while the JSON path keeps raising IncompleteRead
        exactly as PR 19 pinned."""
        conn = self._conn()
        if conn.sock is None:
            conn.connect()  # under connect_timeout_s
            if self.policy is not None:
                conn.sock.settimeout(self.policy.read_timeout_s)
        conn.request(method, path, body=payload, headers=headers)
        self.bytes_tx += len(payload) if payload else 0
        resp = conn.getresponse()
        data = resp.read()
        self.bytes_rx += len(data)
        if resp.getheader(wire.WIRE_HEADER) == wire.WIRE_PROTO:
            self._server_wire = True  # capability capture (benign race)
        ctype = (resp.getheader("Content-Type") or "").split(";")[0].strip()
        if faults.net_truncate():
            self._drop_conn()
            if ctype == wire.CTYPE_BINARY:
                data = data[:len(data) // 2]  # decoder must reject it
            else:
                raise http.client.IncompleteRead(data[:len(data) // 2])
        return resp.status, ctype, data

    def _attempt(self, method: str, path: str, payload, headers):
        """One logical attempt: the fault seam, the wire, and at most one
        transparent reconnect when a REUSED connection turned out stale.
        A fresh connection's failure always propagates — retrying it is
        the retry loop's (counted) job, not this layer's."""
        faults.net_request(self.net_src, self.net_name)
        conn = getattr(self._local, "conn", None)
        reused = conn is not None and conn.sock is not None
        try:
            return self._wire(method, path, payload, headers)
        except _STALE:
            self._drop_conn()
            if not reused:
                raise
            self.reconnects += 1
            telemetry.counter("serve.net.reconnects").inc()
            try:
                return self._wire(method, path, payload, headers)
            except Exception:
                self._drop_conn()
                raise
        except Exception:
            self._drop_conn()
            raise

    # -- request path -----------------------------------------------------

    @staticmethod
    def _encode_body(body):
        """THE request-framing seam (satellite: negotiation in one
        place): dict bodies frame as the PR-19 JSON bytes; a pre-framed
        mtpu-wire1 payload (bytes) passes through with the binary
        Content-Type. Both render paths and every control endpoint
        funnel through here."""
        if body is None:
            return None, wire.CTYPE_JSON
        if isinstance(body, (bytes, bytearray)):
            return bytes(body), wire.CTYPE_BINARY
        return json.dumps(body).encode(), wire.CTYPE_JSON

    @staticmethod
    def _decode_body(ctype: str, data: bytes):
        """The response half of the seam: binary frames decode through
        the mtpu-wire1 tripwires (WireError -> retried), everything else
        parses as JSON (json.JSONDecodeError -> retried)."""
        if ctype == wire.CTYPE_BINARY:
            return wire.decode_render_response(data)
        return json.loads(data or b"{}")

    def _request(self, method: str, path: str,
                 body=None,
                 deadline_ms: Optional[float] = None,
                 retry: bool = True):
        payload, ctype = self._encode_body(body)
        headers = {"Content-Type": ctype}
        pol = self.policy
        attempts = 1 + (pol.retries if (pol is not None and retry) else 0)
        t0 = time.monotonic()
        for attempt in range(attempts):
            if (pol is not None and deadline_ms is not None
                    and deadline_ms > 0):
                left = float(deadline_ms) - (time.monotonic() - t0) * 1e3
                if left <= 0:
                    telemetry.counter("serve.net.deadline_expired").inc()
                    raise DeadlineExceeded(
                        f"{self.address}: {deadline_ms:.0f}ms budget "
                        f"spent client-side after {attempt} attempt(s)")
                headers[DEADLINE_HEADER] = f"{left:.1f}"
            if self.breaker is not None and not self.breaker.allow():
                raise BreakerOpen(f"{self.address}: circuit open")
            try:
                status, rctype, data = self._attempt(method, path,
                                                     payload, headers)
                obj = self._decode_body(rctype, data)
            except _RETRYABLE as e:
                if self.breaker is not None:
                    self.breaker.record(False)
                if isinstance(e, TimeoutError):
                    # socket.timeout IS TimeoutError on py3.10+
                    telemetry.counter("serve.net.timeouts").inc()
                elif isinstance(e, ConnectionRefusedError):
                    telemetry.counter("serve.net.refused").inc()
                if attempt + 1 >= attempts:
                    raise
                self.retries += 1
                telemetry.counter("serve.net.retries").inc()
                time.sleep(pol.backoff_ms / 1e3 * (2 ** attempt)
                           * (0.5 + random.random()))
                continue
            if self.breaker is not None:
                self.breaker.record(True)
            return status, obj
        raise RuntimeError("unreachable")  # loop always returns/raises

    def _negotiate(self) -> bool:
        """Once per client lifetime: does the peer speak mtpu-wire1? The
        advertisement header rides EVERY wire-enabled response, so any
        prior round already answered; otherwise spend one /healthz. A
        silent (JSON-only) peer or a dead probe pins the fallback —
        binary framing AND the front's coalescer stay off for this link,
        counted `serve.wire.fallbacks`."""
        with self._neg_lock:
            if self._wire_ok is not None:
                return self._wire_ok
        if not self._server_wire:
            try:
                self._request("GET", "/healthz", retry=False)
            except Exception:
                pass
        ok = self._server_wire
        with self._neg_lock:
            if self._wire_ok is None:
                self._wire_ok = ok
                if not ok:
                    telemetry.counter("serve.wire.fallbacks").inc()
        return self._wire_ok

    def wire_active(self) -> bool:
        """True when this link negotiated binary framing (the RingFront
        consults this before arming the owner-coalescer for a handle)."""
        return self.wire_policy is not None and self._negotiate()

    def render(self, image_id, pose, tier=None, deadline_ms=None,
               image=None):
        if self.wire_policy is not None and self._negotiate():
            env = self.render_batch(
                [{"image_id": image_id, "pose": pose, "tier": tier,
                  "deadline_ms": deadline_ms, "image": image}],
                deadline_ms=deadline_ms)[0]
            if env.get("ok"):
                return env["rgb"], env["depth"]
            exc = _KIND_RAISE.get(env.get("kind", ""), RuntimeError)
            raise exc(f"{self.address}: {env.get('error', '')}")
        return self._render_json(image_id, pose, tier, deadline_ms, image)

    def _render_json(self, image_id, pose, tier, deadline_ms, image):
        """The PR-19 wire, byte-identical (framed by wire.py's pinned
        JSON builders)."""
        body = wire.json_render_body(
            {"image_id": image_id, "pose": pose, "tier": tier,
             "deadline_ms": deadline_ms, "image": image})
        status, obj = self._request("POST", "/render", body,
                                    deadline_ms=deadline_ms)
        if status == 200 and obj.get("ok"):
            env = wire.json_render_result(obj)
            return env["rgb"], env["depth"]
        kind = obj.get("kind", "")
        exc = _KIND_RAISE.get(kind, RuntimeError)
        raise exc(f"{self.address}: {obj.get('error', f'HTTP {status}')}")

    def render_batch(self, reqs: List[Dict],
                     deadline_ms: Optional[float] = None) -> List[Dict]:
        """N render requests, ONE negotiated mtpu-wire1 exchange; returns
        one envelope per request IN REQUEST ORDER ({"ok": True, "rgb",
        "depth"} numpy, or {"ok": False, "kind", "error"}). Against a
        peer that never advertised, degrades to N sequential JSON rounds
        — same envelopes, PR-19 bytes."""
        if not (self.wire_policy is not None and self._negotiate()):
            out = []
            for r in reqs:
                try:
                    rgb, depth = self._render_json(
                        r["image_id"], r["pose"], r.get("tier"),
                        r.get("deadline_ms"), r.get("image"))
                    out.append({"ok": True, "rgb": rgb, "depth": depth})
                except Exception as e:
                    out.append({"ok": False, "kind": type(e).__name__,
                                "error": str(e)})
            return out
        t0 = time.monotonic()
        payload = wire.encode_render_request(
            reqs, codec=self.wire_policy.codec)
        telemetry.histogram("serve.wire.encode_ms").record(
            (time.monotonic() - t0) * 1e3)
        status, obj = self._request("POST", "/render", payload,
                                    deadline_ms=deadline_ms)
        if isinstance(obj, list):
            if len(obj) != len(reqs):
                # a valid frame with the wrong arity is a server bug,
                # not wire damage — surface it, don't retry it
                raise RuntimeError(
                    f"{self.address}: batch response carries {len(obj)} "
                    f"envelope(s) for {len(reqs)} request(s)")
            return obj
        # a JSON envelope to a binary frame is a BATCH-level verdict
        # (hostile-frame 400, draining 503, ...): re-raise typed
        kind = obj.get("kind", "")
        exc = _KIND_RAISE.get(kind, RuntimeError)
        raise exc(f"{self.address}: {obj.get('error', f'HTTP {status}')}")

    def probe(self) -> Dict:
        """One /healthz round-trip that BYPASSES allow(): the front's
        heartbeat prober IS the half-open admission — its verdict feeds
        the breaker either way, so an open circuit heals from probes
        without spending a caller's request on it."""
        headers = {"Content-Type": wire.CTYPE_JSON}
        try:
            _, rctype, data = self._attempt("GET", "/healthz", None,
                                            headers)
            obj = self._decode_body(rctype, data)
        except Exception:
            if self.breaker is not None:
                self.breaker.record(False)
            raise
        if self.breaker is not None:
            self.breaker.record(True)
        return obj

    def breaker_snapshot(self) -> Optional[Dict]:
        return self.breaker.snapshot() if self.breaker is not None \
            else None

    def healthz(self) -> Dict:
        return self._request("GET", "/healthz")[1]

    def stats(self) -> Dict:
        return self._request("GET", "/stats")[1]

    def drain(self) -> Dict:
        return self._request("POST", "/drain", {}, retry=False)[1]

    def close(self) -> None:
        # drops the CALLING thread's kept-alive socket; other threads'
        # are closed by GC when the client goes away (daemon pool)
        self._drop_conn()


def _entries_counts(limit: int):
    """Every pow2 entries bucket the batcher can form (<= max_requests):
    the warmup set a host must cover so a concurrent flood — which
    coalesces distinct cache entries into R>1 dispatch batches — never
    triggers a live compile after a zero-compile join."""
    out, b = [], 1
    while b <= limit:
        out.append(b)
        b *= 2
    return out


def _build_fleet(args, encode_fn, recorder=None):
    from mine_tpu.serve import ServeFleet

    return ServeFleet(
        cache_shards=args.cache_shards, max_requests=args.max_requests,
        max_wait_ms=2.0, max_bucket=args.max_bucket, encode_fn=encode_fn,
        slo_objective_ms=args.slo_objective_ms, ops_port=None,
        encode_retries=3, encode_backoff_ms=5.0,
        admission_enabled=args.admission,
        admission_burn_max=0.0, admission_queue_high=args.queue_high,
        admission_inflight_high=0, aot_store_dir=args.aot_store,
        recorder=recorder)


def main(argv=None) -> int:
    """Subprocess host entrypoint (see module docstring). Every line of
    stdout is "key=value ..."-parseable; the spawner reads the `ready=1`
    line for the bound port and the zero-compile-join evidence."""
    import argparse
    import os
    import tempfile

    ap = argparse.ArgumentParser(
        description="mine-tpu serving ring host (stdlib HTTP/JSON)")
    ap.add_argument("--host-id", type=str, required=True)
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral; the bound port is printed")
    ap.add_argument("--cache-shards", type=int, default=2)
    ap.add_argument("--max-bucket", type=int, default=2)
    ap.add_argument("--max-requests", type=int, default=8)
    ap.add_argument("--slo-objective-ms", type=float, default=0.0)
    ap.add_argument("--admission", action="store_true",
                    help="enable the local admission ladder")
    ap.add_argument("--queue-high", type=int, default=64)
    ap.add_argument("--aot-store", type=str, default="",
                    help="AOT executable store directory")
    ap.add_argument("--aot-artifact", type=str, default="",
                    help="packed artifact (aot_warmstore.py --pack); "
                         "unpacked to a fresh store dir before boot")
    ap.add_argument("--warm-key", type=str, default="",
                    help="image id to put+warmup at boot — the warmup is "
                         "what records the AOT loads/compiles evidence")
    ap.add_argument("--warm-seed", type=int, default=0,
                    help="synthetic image seed for --warm-key")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0)
    ap.add_argument("--wire", choices=list(wire.WIRE_FORMATS),
                    default="json",
                    help="binary advertises mtpu-wire1 + accepts batch "
                         "frames on /render (serve.wire.format)")
    ap.add_argument("--incidents-dir", type=str, default="",
                    help="arm a flight recorder; drains dump a bundle")
    ap.add_argument("--build-artifact", type=str, default="",
                    help="builder mode: boot the same fleet, warm every "
                         "bucket, pack the store to this path, exit — "
                         "the artifact hosts then boot from is guaranteed "
                         "program-key-compatible")
    args = ap.parse_args(argv)

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from mine_tpu.serve import aot as serve_aot

    if args.build_artifact:
        store_dir = args.aot_store or tempfile.mkdtemp(
            prefix=f"host_{args.host_id}_build_")
        args.aot_store = store_dir
        fleet = _build_fleet(args, synthetic_encode_fn)
        img = np.full((SYN_HW, SYN_HW, 3), float(args.warm_seed),
                      np.float32)
        key = args.warm_key or "builder"
        fleet.engine.put(key, *synthetic_encode_fn(img))
        fleet.warmup(key,
                     entries_counts=_entries_counts(args.max_requests))
        compiles = fleet.engine.bucket_compiles
        loads = fleet.engine.bucket_loads
        fleet.close()
        manifest = serve_aot.pack_store(store_dir, args.build_artifact)
        print(f"host={args.host_id} built=1 compiles={compiles} "
              f"loads={loads} packed={manifest['artifacts']} "
              f"artifact={args.build_artifact}", flush=True)
        return 0

    if args.aot_artifact:
        # the packed artifact is the deployable unit: unpack to a private
        # store dir so concurrent hosts never share write paths
        store_dir = tempfile.mkdtemp(prefix=f"host_{args.host_id}_aot_")
        serve_aot.unpack_store(args.aot_artifact, store_dir)
        args.aot_store = store_dir
        print(f"host={args.host_id} unpacked_store={store_dir}",
              flush=True)

    recorder = None
    if args.incidents_dir:
        from mine_tpu.telemetry import recorder as trecorder

        recorder = trecorder.configure(
            args.incidents_dir, debounce_s=1.0, keep=8,
            config={"host": args.host_id})

    fleet = _build_fleet(args, synthetic_encode_fn, recorder=recorder)
    loads = compiles = 0
    if args.warm_key:
        img = np.full((SYN_HW, SYN_HW, 3), float(args.warm_seed),
                      np.float32)
        fleet.engine.put(args.warm_key, *synthetic_encode_fn(img))
        fleet.warmup(args.warm_key,
                     entries_counts=_entries_counts(args.max_requests))
        loads = fleet.engine.bucket_loads
        compiles = fleet.engine.bucket_compiles

    wire_policy = (wire.WirePolicy(format="binary")
                   if args.wire == "binary" else None)
    server = HostServer(fleet, args.host_id, port=args.port,
                        drain_timeout_s=args.drain_timeout_s,
                        recorder=recorder, wire_policy=wire_policy).start()
    handler = install_drain_signals(server)
    telemetry.emit("serve.host_join", host=args.host_id, hosts=1,
                   aot_loads=loads, aot_compiles=compiles)
    print(f"host={args.host_id} port={server.port} ready=1 "
          f"aot_loads={loads} aot_compiles={compiles} pid={os.getpid()}",
          flush=True)

    server.drained.wait()
    handler.uninstall()
    if recorder is not None:
        from mine_tpu.telemetry import recorder as trecorder

        trecorder.release(recorder)
    print(f"host={args.host_id} drained=1", flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
