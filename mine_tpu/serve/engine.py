"""Render-only serving engine: cached quantized MPIs -> novel views.

Decouples MPI *prediction* (the expensive encoder-decoder pass) from view
*synthesis* (warp + composite, exactly the `render_tgt_rgb_depth` math). One
jitted program renders P poses from R cached MPIs in a single device call:

    planes [R,S,4,H,W] (quantized)   ──dequant──┐
    disparity [R,S], K/K_inv [R,3,3] ──xyz_src──┤ gather by idx [P]
    idx [P] int32, G_tgt_src [P,4,4] ───────────┴─> render_tgt_rgb_depth
                                                    -> rgb [P,3,H,W], depth

Pose and entry counts are padded to power-of-two buckets (identity poses /
repeated entries, results sliced back), so the compile set is BOUNDED by
log2(max_bucket) x log2(max_requests) per (shape, quant, warp_impl) instead
of one executable per request size; `warmup` pre-traces the buckets through
the persistent compile cache (utils.configure_compile_cache). Every op in
the program is per-batch-row independent (einsums over the batch dim,
gather, elementwise, cumprod over S), so padding does not perturb real rows
— the engine parity tests assert this bitwise on CPU.

Dequantization is fused into the jitted program: the cache-resident form
(bf16 / int8, serve/cache.py) is what crosses HBM, and the bf16 widening
cast keeps the render bitwise-identical to rendering host-dequantized
planes.
"""

from __future__ import annotations

import logging
import random
import time
import warnings
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from mine_tpu import geometry, telemetry
from mine_tpu.ops import rendering
from mine_tpu.serve.cache import MPICache, MPIEntry, image_id_for
from mine_tpu.testing import faults

_log = logging.getLogger(__name__)

_warned_sync_encode = set()

# the graceful-degradation ladder's quant step-down (serve/admission.py):
# a degraded request's sync encode lands at the next-cheaper storage mode
DEGRADE_QUANT = {"float32": "bf16", "bf16": "int8", "int8": "int8"}


def _warn_sync_encode(engine_key, image_id: str) -> None:
    """One-time notice that a serve request missed the cache and forced a
    synchronous encode — the slow path must be visible in logs (same
    pattern as ops/rendering._warn_backend_fallback). The `serve.sync_encode`
    counter records EVERY occurrence (the warning only fires once per
    engine, which made sustained slow-path traffic invisible)."""
    if engine_key not in _warned_sync_encode:
        _warned_sync_encode.add(engine_key)
        warnings.warn(
            f"serve cache miss for image {image_id[:12]}…: running a "
            f"SYNCHRONOUS encode on the request path (pre-encode via "
            f"RenderEngine.put/encode to keep serving render-only)")


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= n (>=1): the static-shape bucket a request
    count pads to, so the compile set grows with log2 of the largest batch
    ever seen instead of one executable per batch size."""
    if n < 1:
        raise ValueError(f"need at least one element, got {n}")
    b = 1
    while b < n:
        b *= 2
    return b


def _identity_poses(n: int) -> np.ndarray:
    return np.tile(np.eye(4, dtype=np.float32), (n, 1, 1))


class RenderEngine:
    """Shape-bucketed jitted render over an encode-once MPI cache.

    Single-MPI path (`render`): chunk P poses through `max_bucket`-sized
    device calls (the video generator's path). Multi-MPI path
    (`render_many`): coalesce requests against DISTINCT cached entries into
    one call (the micro-batcher's flush path, serve/batcher.py).
    """

    def __init__(self,
                 use_alpha: bool = False,
                 is_bg_depth_inf: bool = False,
                 backend: str = "xla",
                 warp_impl: str = "xla",
                 warp_band: int = 48,
                 warp_dtype: str = "float32",
                 warp_sep_tol: float = 0.5,
                 max_bucket: int = 8,
                 cache: Optional[MPICache] = None,
                 encode_fn: Optional[Callable] = None,
                 encode_retries: int = 0,
                 encode_backoff_ms: float = 10.0,
                 aot_store=None):
        if max_bucket < 1 or (max_bucket & (max_bucket - 1)) != 0:
            raise ValueError(
                f"serve.max_bucket must be a power of two >= 1, "
                f"got {max_bucket}")
        self.use_alpha = use_alpha
        self.is_bg_depth_inf = is_bg_depth_inf
        self.backend = backend
        self.warp_impl = warp_impl
        self.warp_band = warp_band
        self.warp_dtype = warp_dtype
        self.warp_sep_tol = warp_sep_tol
        self.max_bucket = max_bucket
        self.cache = cache if cache is not None else MPICache()
        # encode_fn(img_hwc) -> (mpi_rgb [S,3,H,W], mpi_sigma [S,1,H,W],
        # disparity [S], K [3,3]) — the synchronous fallback for cache
        # misses; None keeps the engine strictly render-only (miss raises)
        self.encode_fn = encode_fn
        # bounded retry for TRANSIENT sync-encode failures (a flaky encoder
        # or a shard placement racing failover): `encode_retries` extra
        # attempts with jittered exponential backoff from
        # `encode_backoff_ms`; 0 retries = fail on the first error
        self.encode_retries = int(encode_retries)
        self.encode_backoff_ms = float(encode_backoff_ms)
        # optional serve/aot.py AOTStore: first dispatch of a bucket tries
        # a store load before tracing, and live compiles write back. None
        # (the default) keeps the dispatch path byte-identical to before.
        self.aot_store = aot_store
        self.device_calls = 0
        self.sync_encodes = 0
        # cold-bucket accounting, split by how the executable arrived:
        # a live jit trace+compile vs a deserialized store artifact
        self.bucket_compiles = 0
        self.bucket_loads = 0
        # pose buckets never drop below this (the mesh subclass raises it
        # to its "batch" axis size so buckets split evenly across devices)
        self._min_pose_bucket = 1
        # (Rb, Pb, warp_impl, planes dtype) keys already dispatched: a
        # first-seen key means jit traces + compiles a new executable —
        # the compile-set growth the pow2 bucketing is meant to bound
        self._seen_buckets = set()
        # aval-key -> Compiled executable (store-loaded or live-lowered);
        # only populated when an AOTStore is attached — without one every
        # dispatch goes through the plain jit below, exactly as before
        self._aot_execs = {}
        self._render = jax.jit(self._render_impl,
                               static_argnames=("warp_impl",))

    # ---------------- cache facade ----------------

    def put(self, image_id: str, mpi_rgb_S3HW, mpi_sigma_S1HW,
            disparity_S, K_33) -> MPIEntry:
        return self.cache.put(image_id, mpi_rgb_S3HW, mpi_sigma_S1HW,
                              disparity_S, K_33)

    def encode(self, img_hwc: np.ndarray,
               image_id: Optional[str] = None) -> str:
        """Encode an image through `encode_fn` and cache the MPI; returns
        the cache key (content hash unless given)."""
        if self.encode_fn is None:
            raise ValueError("RenderEngine has no encode_fn")
        if image_id is None:
            image_id = image_id_for(img_hwc)
        if image_id not in self.cache:
            self.cache.put(image_id, *self.encode_fn(img_hwc))
        return image_id

    def _entry(self, image_id: str, image=None, traces=(),
               degraded: bool = False) -> MPIEntry:
        entry = self.cache.get(image_id)
        if entry is not None:
            return entry
        if self.encode_fn is None or image is None:
            raise KeyError(
                f"image {image_id[:12]}… not cached and no synchronous "
                f"encode path (pass image= and set encode_fn)")
        # exactly once per miss, whatever the retry loop does below — the
        # counter's contract is "every sync encode", not "every attempt"
        self.sync_encodes += 1
        telemetry.counter("serve.sync_encode").inc()
        quant = None
        if degraded:
            # degradation ladder: a degraded request's encode lands at the
            # next-cheaper storage mode (None = already at the floor)
            step = DEGRADE_QUANT.get(self.cache.quant)
            quant = step if step != self.cache.quant else None
        t0 = time.perf_counter()
        attempts = max(0, self.encode_retries) + 1
        # emit=False: the span event would duplicate the richer one below
        with telemetry.span("serve.sync_encode", emit=False):
            for attempt in range(attempts):
                try:
                    faults.on_encode(image_id)  # chaos seam (no-op unplanned)
                    result = self.encode_fn(image)
                    entry = (self.cache.put(image_id, *result, quant=quant)
                             if quant is not None
                             else self.cache.put(image_id, *result))
                    break
                except Exception:
                    if attempt + 1 >= attempts:
                        raise
                    telemetry.counter("serve.encode_retry").inc()
                    # jittered exponential backoff: transient faults heal,
                    # and concurrent retriers decorrelate
                    delay_s = (self.encode_backoff_ms / 1e3) * (2 ** attempt)
                    time.sleep(delay_s * (0.5 + 0.5 * random.random()))
        if attempt:
            # a retry recovered: the one-time warning would cry wolf about
            # a path that self-healed — log at debug, keep the warning slot
            # unconsumed for a genuine clean-miss slow path
            telemetry.counter("serve.encode_retry_recovered").inc()
            _log.debug("sync encode for %s recovered after %d retr%s",
                       image_id[:12], attempt,
                       "y" if attempt == 1 else "ies")
        else:
            _warn_sync_encode(id(self), image_id)
        encode_ms = (time.perf_counter() - t0) * 1e3
        # every traced request waiting on this entry pays the encode: the
        # span lands in each of their traces, not just the one that missed
        for trace in traces:
            if trace is not None:
                trace.add_span("encode", encode_ms, t0=t0,
                               image_id=image_id[:12], sync=True)
        telemetry.emit("serve.sync_encode", image_id=image_id[:12],
                       total=self.sync_encodes, retries=attempt,
                       degraded=degraded)
        return entry

    # ---------------- jitted render ----------------

    def _render_impl(self, planes, scales, disp, K, K_inv, idx, G,
                     warp_impl: str):
        """planes [R,S,4,H,W] (quantized) + request gather idx [P] +
        poses G [P,4,4] -> (rgb [P,3,H,W], depth [P,1,H,W])."""
        if warp_impl == "pallas_fused":
            # no pre-dequant: the render megakernel reads the quantized
            # cache entries directly (scales in SMEM, dequant in registers,
            # kernels/render_fused.py) — the float volume never hits HBM.
            # Only the cheap [P]-gather of the cache slice happens here.
            H, W = planes.shape[-2], planes.shape[-1]
            grid = geometry.cached_pixel_grid(H, W)
            xyz_src = geometry.plane_xyz_src(grid, disp, K_inv)
            xyz_tgt = geometry.plane_xyz_tgt(xyz_src[idx], G)
            pq = planes[idx]
            psc = scales[idx] if planes.dtype == jnp.int8 else None
            res = rendering.render_tgt_rgb_depth(
                pq[:, :, 0:3], pq[:, :, 3:4], disp[idx], xyz_tgt, G,
                K_inv[idx], K[idx],
                use_alpha=self.use_alpha,
                is_bg_depth_inf=self.is_bg_depth_inf,
                backend=self.backend,
                warp_impl=warp_impl,
                warp_band=self.warp_band,
                warp_dtype=self.warp_dtype,
                warp_sep_tol=self.warp_sep_tol,
                mesh=self._render_mesh(),
                planes_q=pq, planes_scales=psc)
            return res.rgb, res.depth
        x = planes.astype(jnp.float32)
        if planes.dtype == jnp.int8:
            x = x * scales  # fused dequant: int8 never leaves this program
        rgb = x[:, :, 0:3]
        sigma = x[:, :, 3:4]
        H, W = x.shape[-2], x.shape[-1]
        grid = geometry.cached_pixel_grid(H, W)
        xyz_src = geometry.plane_xyz_src(grid, disp, K_inv)  # [R,S,3,H,W]
        xyz_tgt = geometry.plane_xyz_tgt(xyz_src[idx], G)
        res = rendering.render_tgt_rgb_depth(
            rgb[idx], sigma[idx], disp[idx], xyz_tgt, G,
            K_inv[idx], K[idx],
            use_alpha=self.use_alpha,
            is_bg_depth_inf=self.is_bg_depth_inf,
            backend=self.backend,
            warp_impl=warp_impl,
            warp_band=self.warp_band,
            warp_dtype=self.warp_dtype,
            warp_sep_tol=self.warp_sep_tol)
        return res.rgb, res.depth

    def _place(self, planes, scales, disp, K, K_inv, idx, poses):
        """Device-placement hook before dispatch. The base engine lets jit
        commit operands to the default device; the mesh engine
        (serve/shardmap.py) overrides this to device_put each operand under
        its NamedSharding so the jitted program spans the serving mesh."""
        return planes, scales, disp, K, K_inv, idx, poses

    def _render_mesh(self):
        """Serving mesh for the fused render path (warp_impl=
        "pallas_fused"): None on the single-device engine; the mesh engine
        (serve/shardmap.py) returns its Mesh so the megakernel runs under
        shard_map, batch-split over the mesh's leading axis. The other
        warp backends partition via GSPMD and never consult this."""
        return None

    def _render_span_fields(self) -> dict:
        """Extra fields for a request trace's "render" span; the mesh
        subclass adds its mesh shape so a waterfall shows which fleet
        topology rendered the request."""
        return {}

    # ---------------- AOT executable store (serve/aot.py) ----------------

    def _mesh_desc(self) -> str:
        """Mesh-shape component of the AOT program key; the mesh subclass
        overrides so e.g. a 2x1 fleet never loads a 1x1 executable."""
        return "1x1"

    def _aval_key(self, Rb: int, Pb: int, warp_impl: str, dtype: str,
                  S: int, H: int, W: int, has_scales: bool) -> tuple:
        """The in-process executable-cache key: everything that changes the
        program's input avals. Derivable both from staged arrays (dispatch)
        and from entry metadata + bucket sizes (warmup-from-store)."""
        return (Rb, Pb, warp_impl, dtype, S, H, W, has_scales)

    def _program_key(self, Rb: int, Pb: int, warp_impl: str, dtype: str,
                     S: int, H: int, W: int, has_scales: bool) -> dict:
        """The store's content-address input: the aval key plus every
        engine static baked into the traced program, the mesh shape, and
        the environment fingerprint (serve/aot.py)."""
        from mine_tpu.serve import aot as _aot
        return {
            "program": "serve_render",
            "entries_bucket": Rb, "poses_bucket": Pb,
            "warp_impl": warp_impl, "dtype": dtype,
            "planes": [S, H, W], "scaled": has_scales,
            "mesh": self._mesh_desc(),
            "engine": {
                "use_alpha": self.use_alpha,
                "is_bg_depth_inf": self.is_bg_depth_inf,
                "backend": self.backend,
                "warp_band": self.warp_band,
                "warp_dtype": self.warp_dtype,
                "warp_sep_tol": self.warp_sep_tol,
            },
            "fingerprint": _aot.env_fingerprint(),
        }

    def _dispatch(self, args, warp_impl: str):
        """Run the render program on staged args. Without a store this IS
        `self._render` (plain jit). With one, resolve a Compiled executable
        per aval key — store load, else a live `lower().compile()` written
        back — and invoke it with the DYNAMIC args only (`warp_impl` is
        baked into the compiled program). Returns (rgb, depth, source)
        where source is "jit" | "load" | "compile"."""
        if self.aot_store is None:
            rgb, depth = self._render(*args, warp_impl)
            return rgb, depth, "jit"
        planes, scales, _, _, _, _, poses = args
        key = self._aval_key(planes.shape[0], poses.shape[0], warp_impl,
                             str(planes.dtype), planes.shape[1],
                             planes.shape[-2], planes.shape[-1],
                             scales is not None)
        exe = self._aot_execs.get(key)
        source = "warm"
        if exe is None:
            pkey = self._program_key(*key)
            exe = self.aot_store.load(pkey)
            source = "load"
            if exe is None:
                # miss or failed deserialize: live compile, write back so
                # the NEXT replica boots warm (the store is an accelerator,
                # never a correctness dependency)
                exe = self._render.lower(*args,
                                         warp_impl=warp_impl).compile()
                self.aot_store.save(pkey, exe)
                source = "compile"
            self._aot_execs[key] = exe
        rgb, depth = exe(*args)
        return rgb, depth, source

    def _register_store_hit(self, bucket, key) -> bool:
        """Warmup hook: try loading `bucket`'s executable from the store;
        on a hit register it (no trace, no render) and account the
        cold-bucket event as a LOAD. Returns hit."""
        pkey = self._program_key(*key)
        t0 = time.perf_counter()
        exe = self.aot_store.load(pkey)
        if exe is None:
            return False
        self._aot_execs[key] = exe
        self._seen_buckets.add(bucket)
        load_ms = (time.perf_counter() - t0) * 1e3
        self.bucket_loads += 1
        telemetry.counter("serve.bucket_loads").inc()
        telemetry.emit("serve.bucket_compile", entries_bucket=bucket[0],
                       poses_bucket=bucket[1], warp_impl=bucket[2],
                       dtype=bucket[3], compile_ms=round(load_ms, 3),
                       store_hit=True, backend=bucket[2])
        return True

    def _call(self, entries: Sequence[MPIEntry], idx: np.ndarray,
              poses: np.ndarray, warp_impl: Optional[str],
              traces: Optional[Sequence] = None):
        """Bucket R and P, pad, dispatch ONE device call, slice."""
        t0 = time.perf_counter()
        warp_impl = warp_impl or self.warp_impl
        P = poses.shape[0]
        Pb = max(pow2_bucket(P), self._min_pose_bucket)
        if P < Pb:
            poses = np.concatenate([poses, _identity_poses(Pb - P)], axis=0)
            idx = np.concatenate([idx, np.zeros(Pb - P, idx.dtype)])
        R = len(entries)
        Rb = pow2_bucket(R)
        if len({str(e.planes.dtype) for e in entries}) > 1:
            # degraded placements (serve/admission.py) can coalesce entries
            # of different storage dtypes into one batch; stacking would
            # silently promote. Widen host-side to f32 — the dequant the
            # program would fuse anyway, so values are identical, at the
            # cost of this one call's HBM compression
            planes = jnp.stack([e.dequantized() for e in entries])
            scales = None
        else:
            planes = jnp.stack([e.planes for e in entries])
            scales = None
            if entries[0].scales is not None:
                scales = jnp.stack([e.scales for e in entries])
        disp = jnp.stack([e.disparity for e in entries])
        K = jnp.stack([e.K for e in entries])
        if R < Rb:
            # pad by repeating entry 0: all-valid data, never gathered
            def pad_r(a):
                return jnp.concatenate(
                    [a, jnp.broadcast_to(a[:1], (Rb - R,) + a.shape[1:])])
            planes, disp, K = pad_r(planes), pad_r(disp), pad_r(K)
            if scales is not None:
                scales = pad_r(scales)
        K_inv = geometry.inverse_intrinsics(K)
        args = self._place(planes, scales, disp, K, K_inv,
                           jnp.asarray(idx, jnp.int32),
                           jnp.asarray(poses, jnp.float32))
        t_dispatch = time.perf_counter()
        faults.on_render()  # chaos seam: injected slow device (no-op unplanned)
        rgb, depth, source = self._dispatch(args, warp_impl)
        self.device_calls += 1
        with telemetry.host_readback("serve.render_fetch"):  # device sync
            out = np.asarray(rgb[:P]), np.asarray(depth[:P])
        t_end = time.perf_counter()
        elapsed_ms = (t_end - t0) * 1e3
        bucket = (Rb, Pb, warp_impl, str(planes.dtype))
        compiled = bucket not in self._seen_buckets
        if compiled:
            # first dispatch of this (shape-bucket, impl, dtype) key: the
            # executable arrived either via a live jit trace+compile or a
            # store load (serve/aot.py), so this call's time is cold-path
            # dominated — recorded as a cold-bucket event, NOT into the
            # warm-latency histogram it would wreck
            self._seen_buckets.add(bucket)
            store_hit = source == "load"
            if store_hit:
                self.bucket_loads += 1
                telemetry.counter("serve.bucket_loads").inc()
            else:
                self.bucket_compiles += 1
                telemetry.counter("serve.bucket_compiles").inc()
            telemetry.emit("serve.bucket_compile", entries_bucket=Rb,
                           poses_bucket=Pb, warp_impl=warp_impl,
                           dtype=str(planes.dtype),
                           compile_ms=round(elapsed_ms, 3),
                           store_hit=store_hit, backend=warp_impl)
        else:
            telemetry.histogram("serve.render_call_ms").record(elapsed_ms)
            # per-backend label (a separate registry name, not a schema
            # change): lets obs_report attribute warm render-time movement
            # to the kernel backend that produced it
            telemetry.histogram(
                f"serve.render_call_ms[{warp_impl}]").record(elapsed_ms)
        if traces:
            # two host-side spans per traced rider: the stack/pad/place
            # work before dispatch, then the device call itself (dispatch
            # to output sync — compile-dominated on a cold bucket, which
            # the compiled flag marks so waterfalls aren't misread)
            extra = self._render_span_fields()
            pad_ms = (t_dispatch - t0) * 1e3
            render_ms = (t_end - t_dispatch) * 1e3
            for trace in traces:
                if trace is None:
                    continue
                trace.add_span("pad", pad_ms, t0=t0, entries_bucket=Rb,
                               poses_bucket=Pb, padded_poses=Pb - P)
                trace.add_span("render", render_ms, t0=t_dispatch,
                               warp_impl=warp_impl, compiled=compiled,
                               **extra)
        return out

    # ---------------- public render paths ----------------

    def render(self, image_id: str, poses_P44: np.ndarray,
               warp_impl: Optional[str] = None,
               image=None, trace=None) -> Tuple[np.ndarray, np.ndarray]:
        """All P poses against ONE cached MPI -> (rgb [P,3,H,W],
        depth [P,1,H,W]) f32 numpy. Full max_bucket chunks, then one
        pow2-bucketed remainder call. `trace` attaches a request trace
        (telemetry/tracing.py): every chunk's pad/render spans — and a
        sync encode, if this call pays one — land in it."""
        chunk_traces = [trace] if trace is not None else None
        entry = self._entry(image_id, image=image,
                            traces=chunk_traces or ())
        poses = np.asarray(poses_P44, np.float32)
        P = poses.shape[0]
        rgbs, depths = [], []
        for i in range(0, P, self.max_bucket):
            chunk = poses[i:i + self.max_bucket]
            rgb, depth = self._call(
                [entry], np.zeros(chunk.shape[0], np.int32), chunk,
                warp_impl, traces=chunk_traces)
            rgbs.append(rgb)
            depths.append(depth)
        return np.concatenate(rgbs), np.concatenate(depths)

    def render_many(self, requests: Sequence[Tuple[str, np.ndarray]],
                    warp_impl: Optional[str] = None,
                    traces: Optional[Sequence] = None,
                    images: Optional[Sequence] = None,
                    degraded: Optional[Sequence[bool]] = None
                    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Coalesced path: [(image_id, pose [4,4])...] across DISTINCT
        cached MPIs -> one device call; per-request (rgb, depth) in order.
        `traces` aligns with `requests` (None entries fine): each traced
        request gets this dispatch's pad/render spans. `images` aligns too:
        a request carrying its source pixels lets a cache miss fall back to
        the synchronous encode exactly like `render(image=...)` — the
        batcher's flush path forwards them. `degraded` (also aligned): an
        entry whose EVERY requester is degraded encodes at the stepped-down
        cache quant on a miss (one full-fidelity rider keeps the shared
        entry full-fidelity)."""
        if not requests:
            return []
        if traces is None:
            traces = [None] * len(requests)
        if images is None:
            images = [None] * len(requests)
        if degraded is None:
            degraded = [False] * len(requests)
        order: List[str] = []
        for image_id, _ in requests:
            if image_id not in order:
                order.append(image_id)
        entries = [
            self._entry(i,
                        image=next((im for (rid, _), im
                                    in zip(requests, images)
                                    if im is not None and rid == i), None),
                        traces=[t for (rid, _), t
                                in zip(requests, traces)
                                if t is not None and rid == i],
                        degraded=all(d for (rid, _), d
                                     in zip(requests, degraded) if rid == i))
            for i in order]
        idx = np.asarray([order.index(i) for i, _ in requests], np.int32)
        poses = np.stack([np.asarray(p, np.float32) for _, p in requests])
        rgb, depth = self._call(entries, idx, poses, warp_impl,
                                traces=[t for t in traces if t is not None])
        return [(rgb[j], depth[j]) for j in range(len(requests))]

    def warmup(self, image_id: str,
               pose_counts: Optional[Sequence[int]] = None,
               warp_impl: Optional[str] = None,
               entries_counts: Sequence[int] = (1,)) -> None:
        """Make the bucketed programs hot against a cached entry. Without
        an AOT store this pre-traces through JAX's persistent compile cache
        (utils.configure_compile_cache), exactly as before. With one
        (serve/aot.py), each bucket first tries a store load — registering
        the executable with zero program compiles — and only a miss falls
        back to the live render (which compiles and writes back). A store
        warmup then sweeps one cheap render per pose count that pads into
        a warmed bucket: the render programs are loaded, but the
        post-dispatch output slice/fetch for a REMAINDER count still
        compiles lazily per count, and on a truly cold replica those tiny
        compiles would otherwise land on the first odd-sized requests
        (cold-p99 must ~= warm-p99, the ROADMAP metric). `entries_counts`
        extends coverage to multi-entry buckets (the coalesced
        render_many path); the default matches the historic single-entry
        warmup."""
        from mine_tpu.utils import configure_compile_cache
        configure_compile_cache()
        if pose_counts is None:
            pose_counts, b = [], 1
            while b <= self.max_bucket:
                pose_counts.append(b)
                b *= 2
        warp = warp_impl or self.warp_impl
        entry = (self._entry(image_id)
                 if self.aot_store is not None
                 or any(r > 1 for r in entries_counts) else None)
        for r in entries_counts:
            for n in pose_counts:
                if self.aot_store is not None:
                    Rb = pow2_bucket(r)
                    Pb = max(pow2_bucket(n), self._min_pose_bucket)
                    dtype = str(entry.planes.dtype)
                    bucket = (Rb, Pb, warp, dtype)
                    if bucket in self._seen_buckets:
                        continue
                    S, _, H, W = entry.planes.shape
                    key = self._aval_key(Rb, Pb, warp, dtype, S, H, W,
                                         entry.scales is not None)
                    if self._register_store_hit(bucket, key):
                        continue
                if r == 1:
                    self.render(image_id, _identity_poses(n),
                                warp_impl=warp_impl)
                else:
                    self._call([entry] * r, np.zeros(n, np.int32),
                               _identity_poses(n), warp_impl)
        if self.aot_store is not None and pose_counts:
            limit = min(self.max_bucket,
                        max(max(pow2_bucket(n), self._min_pose_bucket)
                            for n in pose_counts))
            for n in range(1, limit + 1):
                self.render(image_id, _identity_poses(n),
                            warp_impl=warp_impl)
