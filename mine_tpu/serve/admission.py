"""Admission control: shed or degrade low-tier requests under pressure.

PR 8 gave the serving plane every *signal* a production control loop needs
(error-budget burn, queue depth, in-flight count); this module is the first
*actuator*. Requests carry an integer priority tier:

    0  best-effort   degraded first, shed first
    1  standard      the default; degraded only at the SHED level
    2+ critical      never shed, never degraded

and the controller collapses the pressure signals into one score — the max
over `signal / threshold` for each configured signal (a threshold <= 0
disables that signal) — mapped to three levels:

    ok       score < 1.0               everything admits
    degrade  1.0 <= score < shed_factor  tier-0 requests degrade
    shed     score >= shed_factor        tier-0 sheds, tier-1 degrades

Degradation is the graceful ladder (serve/batcher.py, serve/engine.py): a
degraded request's sync encode lands at the next-cheaper cache quant and an
all-degraded batch caps at half the pose bucket, trading fidelity and batch
shape for survival before anything is dropped. Shedding resolves the
request's future immediately with `RequestShed` — the caller gets a fast
failure instead of a doomed wait.

Level transitions are HYSTERETIC and edge-triggered like the SLO breach
events: stepping down a level requires the score to fall below
`threshold * hysteresis`, and each state change emits ONE `serve.admission`
event (never one per request) plus the `serve.admission.state` gauge.

Thread model: `decide()` is called under the batcher's queue lock (the
queue depth it consumes is only coherent there), so the controller needs no
lock of its own; the telemetry it touches nests ascending per
analysis/locks.py. The burn signal reads `SLOTracker.burn` — a lock-free
cached float — so a decision never contends with the SLO window.
"""

from __future__ import annotations

from typing import Callable, Optional

from mine_tpu import telemetry

TIER_BEST_EFFORT = 0
TIER_STANDARD = 1
TIER_CRITICAL = 2

LEVELS = ("ok", "degrade", "shed")


class RequestShed(RuntimeError):
    """The admission controller refused this request under overload; retry
    later or at a higher tier. Delivered through the request's future."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed while it was still queued; it was
    purged at dispatch time, never rendered (serve/batcher.py)."""


class AdmissionController:
    """See module docstring. `enabled=False` (the default) makes `decide`
    a constant "admit" — the zero-cost off state the parity tests pin."""

    def __init__(self, enabled: bool = False,
                 burn_max: float = 1.0,
                 queue_high: int = 64,
                 inflight_high: int = 256,
                 shed_factor: float = 2.0,
                 hysteresis: float = 0.7,
                 burn_fn: Optional[Callable[[], float]] = None):
        if shed_factor <= 1.0:
            raise ValueError(
                f"admission shed_factor must be > 1, got {shed_factor}")
        if not 0.0 < hysteresis <= 1.0:
            raise ValueError(
                f"admission hysteresis must be in (0, 1], got {hysteresis}")
        self.enabled = bool(enabled)
        self.burn_max = float(burn_max)
        self.queue_high = int(queue_high)
        self.inflight_high = int(inflight_high)
        self.shed_factor = float(shed_factor)
        self.hysteresis = float(hysteresis)
        self.burn_fn = burn_fn
        self._level = 0
        self.transitions = 0
        self.shed = 0
        self.degraded = 0

    @property
    def state(self) -> str:
        return LEVELS[self._level]

    def score(self, queue_depth: int, inflight: int) -> float:
        """Pressure score: max over configured signals of value/threshold.
        >= 1.0 means at least one signal crossed its line."""
        s = 0.0
        if self.burn_max > 0 and self.burn_fn is not None:
            s = max(s, self.burn_fn() / self.burn_max)
        if self.queue_high > 0:
            s = max(s, queue_depth / self.queue_high)
        if self.inflight_high > 0:
            s = max(s, inflight / self.inflight_high)
        return s

    def _update_level(self, score: float, queue_depth: int,
                      inflight: int) -> None:
        target = (2 if score >= self.shed_factor
                  else 1 if score >= 1.0 else 0)
        level = self._level
        if target > level:
            level = target  # escalate immediately: pressure is now
        elif target < level:
            # de-escalate one level at a time, and only once the score has
            # fallen clearly below the threshold being left (hysteresis):
            # a score oscillating around a line must not flap the state
            leaving = self.shed_factor if level == 2 else 1.0
            if score < leaving * self.hysteresis:
                level -= 1
        if level != self._level:
            prev = LEVELS[self._level]
            self._level = level
            self.transitions += 1
            telemetry.gauge("serve.admission.state").set(level)
            telemetry.emit("serve.admission", state=LEVELS[level], prev=prev,
                           score=round(score, 4), queue_depth=queue_depth,
                           inflight=inflight)

    def decide(self, tier: int, queue_depth: int, inflight: int) -> str:
        """-> "admit" | "degrade" | "shed" for one request. Updates the
        pressure level first (edge-triggered event on change), then applies
        the tier policy. Callers serialize (the batcher's queue lock)."""
        if not self.enabled:
            return "admit"
        self._update_level(self.score(queue_depth, inflight),
                           queue_depth, inflight)
        if tier >= TIER_CRITICAL or self._level == 0:
            return "admit"
        if self._level == 1:
            decision = "degrade" if tier <= TIER_BEST_EFFORT else "admit"
        else:  # shed level
            decision = "shed" if tier <= TIER_BEST_EFFORT else "degrade"
        if decision == "shed":
            self.shed += 1
            telemetry.counter("serve.admission.shed").inc()
        elif decision == "degrade":
            self.degraded += 1
            telemetry.counter("serve.admission.degraded").inc()
        return decision
