"""Streaming video sessions: keyframe-cadenced temporal reuse of the cache.

The serving stack renders novel views of STATIC cached MPIs; source video is
temporally redundant, so re-encoding every frame wastes the encoder on
content the previous frame already paid for. A `StreamSession` carries a
compact cached state forward instead — the PAPERS.md O(1)
autoregressive-caching idea applied to MINE's encode-once engine:

  * every Kth frame (`serve.session.keyframe_every`) is a KEYFRAME: its
    pixels ride the submit as `image=`, the engine's sync-encode path
    predicts a fresh MPI (exactly one `serve.sync_encode` per keyframe),
    and the planes land in the plane cache under a session-sticky id;
  * the frames in between are INTERPOLATED: render-only requests against
    the cached keyframe MPI at the frame's pose RELATIVE to the keyframe
    — the same jitted, pow2-bucketed render program static serving uses
    (no new compile surface beyond `serve.max_bucket`), submitted with the
    keyframe's pixels attached so a lost cache entry (shard failover,
    eviction) transparently re-encodes instead of failing the frame;
  * an ADAPTIVE mode re-keys early when a cheap drift proxy exceeds
    `serve.session.drift_budget`: mean |rendered - observed| on a
    stride-downsampled probe (causal — frame n's drift gates frame n+1),
    or the pose-delta norm against the keyframe pose (gates frame n
    itself, no render needed).

SHARD STICKINESS: every keyframe id starts with the session's fixed 8-hex
key prefix (`session_key_prefix`), so `fleet.py`'s key-range routing sends
the whole stream to ONE owner shard — a session never hops shards
mid-stream, and its keyframe residency never fragments across the fleet.
Superseded keyframes are retired from the cache (`pop`, no eviction count)
once their last in-flight frame resolves.

Keyframe encodes are tiered ABOVE interpolated renders (default
`serve.session.keyframe_tier` = critical): under admission pressure the
fleet sheds interpolation, never the encode the next K frames depend on.

Telemetry: `serve.session.*` counters/gauges (per-session drift and
keyframe age), KIND_FIELDS-pinned `serve.session_start` / `_keyframe` /
`_frame` / `_end` events, and span events distinguishing
`serve.session.keyframe_encode` from `serve.session.interp_render`.
`SessionManager` (serve/stream.py) multiplexes concurrent sessions through
the fleet's `ContinuousBatcher`.
"""

from __future__ import annotations

import hashlib
import time
from typing import Callable, Dict, Optional, Set

import numpy as np

from mine_tpu import telemetry
from mine_tpu.analysis.locks import ordered_lock

DRIFT_MODES = ("probe", "pose")

# re-key reasons carried by the serve.session_keyframe event
REASON_FIRST = "first"
REASON_CADENCE = "cadence"
REASON_DRIFT = "drift"
REASON_MANUAL = "manual"


def session_key_prefix(session_id: str) -> str:
    """Fixed leading-8-hex key prefix of a session: every keyframe id
    starts with it, so `fleet.shard_for_key` (which reads exactly the
    leading 8 hex digits) maps the WHOLE stream to one owner shard."""
    return hashlib.sha1(str(session_id).encode()).hexdigest()[:8]


def keyframe_id(prefix: str, session_id: str, frame: int) -> str:
    """Cache id of a session's keyframe at `frame`: the sticky prefix plus
    a per-keyframe unique suffix — same 40-hex shape as the content-hash
    ids (serve/cache.py image_id_for), constant key position."""
    suffix = hashlib.sha1(
        f"{session_id}/keyframe/{frame}".encode()).hexdigest()[:32]
    return prefix + suffix


def relative_pose(pose_44: np.ndarray, key_pose_44: np.ndarray) -> np.ndarray:
    """G_tgt_src from the frame's camera-from-world extrinsics to the
    keyframe's: the pose the render program warps the cached keyframe MPI
    by. Identity when the frame IS the keyframe (callers special-case that
    to keep K=1 bitwise-identical to the per-frame-encode path)."""
    return np.asarray(pose_44, np.float32) @ np.linalg.inv(
        np.asarray(key_pose_44, np.float32))


def probe_drift(rendered_3hw: np.ndarray, observed_hwc: np.ndarray,
                stride: int = 4) -> Optional[float]:
    """Cheap host-side drift proxy: mean |rendered - observed| over a
    stride-downsampled probe. Both sides are already host numpy (the
    engine's output fetch is the declared readback), so this adds no
    device sync and no compile surface. None when the shapes disagree —
    a caller streaming frames at a different resolution than the render
    simply gets no probe signal (pose mode still works)."""
    r = np.asarray(rendered_3hw, np.float32)
    o = np.asarray(observed_hwc, np.float32)
    if (o.ndim == 3 and o.shape != r.shape
            and (o.shape[2],) + o.shape[:2] == r.shape):
        o = np.transpose(o, (2, 0, 1))  # HWC -> CHW
    if r.shape != o.shape:
        return None
    s = max(1, int(stride))
    return float(np.mean(np.abs(r[:, ::s, ::s] - o[:, ::s, ::s])))


class StreamSession:
    """One streaming video session over the serve plane.

    `backend_submit(image_id, pose_44, tier=, image=) -> Future` is the
    fleet's (or a bare batcher's) submit; `cache` (optional) lets the
    session retire superseded keyframes. `process_frame` is the per-frame
    entry point — call it from ONE producer thread in frame order (the
    session lock serializes the submit, so queue order matches frame
    order). All session state sits under the rank-ordered "serve.session"
    lock (analysis/locks.py), which is safely held across the fleet submit.
    """

    def __init__(self, session_id: str,
                 backend_submit: Callable,
                 cache=None, *,
                 keyframe_every: int = 1,
                 drift_budget: float = 0.0,
                 drift_mode: str = "probe",
                 probe_stride: int = 4,
                 keyframe_tier: int = 2,
                 interp_tier: Optional[int] = None,
                 key_prefix: Optional[str] = None,
                 on_close: Optional[Callable] = None):
        if keyframe_every < 1:
            raise ValueError(
                f"keyframe_every must be >= 1, got {keyframe_every}")
        if drift_budget < 0:
            raise ValueError(
                f"drift_budget must be >= 0, got {drift_budget}")
        if drift_mode not in DRIFT_MODES:
            raise ValueError(f"drift_mode must be one of "
                             f"{'|'.join(DRIFT_MODES)}, got {drift_mode!r}")
        if probe_stride < 1:
            raise ValueError(
                f"probe_stride must be >= 1, got {probe_stride}")
        self.session_id = str(session_id)
        self._submit = backend_submit
        self._cache = cache
        self.keyframe_every = int(keyframe_every)
        self.drift_budget = float(drift_budget)
        self.drift_mode = drift_mode
        self.probe_stride = int(probe_stride)
        self.keyframe_tier = int(keyframe_tier)
        self.interp_tier = interp_tier
        self.key_prefix = (key_prefix if key_prefix is not None
                           else session_key_prefix(self.session_id))
        self._on_close = on_close
        self._lock = ordered_lock("serve.session")
        self._closed = False
        self._frame_idx = 0
        self._keyframe_id: Optional[str] = None
        self._keyframe_seq = -1
        self._keyframe_pose: Optional[np.ndarray] = None
        self._keyframe_pixels = None
        self._last_drift = 0.0
        # in-flight frames per keyframe id + ids superseded but not yet
        # poppable (their last frame is still rendering)
        self._outstanding: Dict[str, int] = {}
        self._retired: Set[str] = set()
        self.frames = 0
        self.keyframes = 0
        self.rekeys = 0  # adaptive (drift-triggered) keyframes only
        self.failed_frames = 0
        telemetry.counter("serve.session.opened").inc()
        telemetry.emit("serve.session_start", session=self.session_id,
                       keyframe_every=self.keyframe_every,
                       drift_mode=self.drift_mode,
                       drift_budget=self.drift_budget,
                       key_prefix=self.key_prefix)

    # ---------------- per-frame policy ----------------

    def _keyframe_reason(self, n: int, pose: np.ndarray) -> Optional[str]:
        """Why frame n re-keys, or None to interpolate (caller holds the
        session lock). The probe proxy is causal/lagged — frame n-1's
        measured drift gates frame n; the pose proxy gates frame n itself
        (no render needed to evaluate it)."""
        if self._keyframe_id is None:
            return REASON_FIRST
        if n - self._keyframe_seq >= self.keyframe_every:
            return REASON_CADENCE
        if self.drift_budget > 0:
            if self.drift_mode == "pose":
                delta = float(np.linalg.norm(
                    np.asarray(pose, np.float32) - self._keyframe_pose))
                if delta > self.drift_budget:
                    return REASON_DRIFT
            elif self._last_drift > self.drift_budget:
                return REASON_DRIFT
        return None

    def process_frame(self, frame, pose_44=None, force_keyframe: bool = False):
        """Submit one source frame; returns the request Future resolving to
        (rgb [3,H,W], depth [1,H,W]) f32 numpy. `frame` is the observed
        pixels in whatever form the fleet's encode_fn accepts (HWC float at
        the render resolution enables the probe drift proxy); `pose_44` the
        frame's camera extrinsics (None = static camera)."""
        pose = (np.eye(4, dtype=np.float32) if pose_44 is None
                else np.asarray(pose_44, np.float32))
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    f"session {self.session_id} is closed")
            n = self._frame_idx
            self._frame_idx += 1
            reason = (REASON_MANUAL if force_keyframe
                      else self._keyframe_reason(n, pose))
            if reason is not None:
                kid = keyframe_id(self.key_prefix, self.session_id, n)
                old = self._keyframe_id
                self._keyframe_id = kid
                self._keyframe_seq = n
                self._keyframe_pose = pose
                self._keyframe_pixels = frame
                self.keyframes += 1
                if reason == REASON_DRIFT:
                    self.rekeys += 1
                    telemetry.counter("serve.session.rekeys").inc()
                telemetry.counter("serve.session.keyframes").inc()
                telemetry.emit("serve.session_keyframe",
                               session=self.session_id, frame=n,
                               image_id=kid[:12], reason=reason)
                if old is not None:
                    self._retired.add(old)
                    self._maybe_pop(old)
                # the keyframe renders at identity EXACTLY (never
                # pose @ inv(pose), which is only numerically identity):
                # K=1 streaming must stay bitwise-identical to the
                # per-frame-encode path
                rel = np.eye(4, dtype=np.float32)
                tier = self.keyframe_tier
                image = frame
                kind = "keyframe"
            else:
                kid = self._keyframe_id
                rel = relative_pose(pose, self._keyframe_pose)
                tier = self.interp_tier
                # the keyframe's pixels ride along: a lost cache entry
                # (shard death, eviction) re-encodes the KEYFRAME
                # transparently instead of failing the frame
                image = self._keyframe_pixels
                kind = "interp"
            age = n - self._keyframe_seq
            self.frames += 1
            self._outstanding[kid] = self._outstanding.get(kid, 0) + 1
            telemetry.counter("serve.session.frames").inc()
            # submit under the session lock: queue order = frame order
            # (lock ranks: session 5 < batcher.cv 10 < fleet.cache 15)
            fut = self._submit(kid, rel, tier=tier, image=image)
        t0 = time.perf_counter()
        probe = frame if (kind == "interp"
                          and self.drift_mode == "probe") else None
        fut.add_done_callback(
            lambda f: self._complete(f, kind, kid, n, age, probe, t0))
        return fut

    # ---------------- completion path ----------------

    def _complete(self, fut, kind, kid, n, age, probe, t0) -> None:
        """Done-callback: runs on the resolving (flush) thread, which holds
        no batcher locks at set_result time — safe to take the session lock
        and touch the cache. Records the keyframe-vs-interpolated span
        split, the drift proxy, and the per-frame event."""
        ms = (time.perf_counter() - t0) * 1e3
        sid = self.session_id
        if fut.exception() is not None:
            telemetry.counter("serve.session.failed_frames").inc()
            with self._lock:
                self.failed_frames += 1
                self._settle(kid)
            telemetry.emit("serve.session_frame", session=sid, frame=n,
                           age=age, drift=None, ok=False)
            return
        name = ("serve.session.keyframe_encode" if kind == "keyframe"
                else "serve.session.interp_render")
        telemetry.histogram(name + "_ms").record(ms)
        telemetry.emit("span", name=name, ms=round(ms, 3), ok=True,
                       session=sid)
        drift = 0.0
        if probe is not None:
            rgb, _ = fut.result()
            d = probe_drift(rgb, probe, stride=self.probe_stride)
            if d is not None:
                drift = d
        with self._lock:
            if kind == "interp" and probe is not None:
                self._last_drift = drift
            self._settle(kid)
        telemetry.gauge(f"serve.session.drift.{sid}").set(drift)
        telemetry.gauge(f"serve.session.age.{sid}").set(age)
        telemetry.emit("serve.session_frame", session=sid, frame=n,
                       age=age, drift=round(drift, 6))

    def _settle(self, kid: str) -> None:
        """One in-flight frame of `kid` resolved (caller holds the session
        lock); a retired keyframe with nothing left in flight pops."""
        left = self._outstanding.get(kid, 0) - 1
        if left > 0:
            self._outstanding[kid] = left
        else:
            self._outstanding.pop(kid, None)
            if kid in self._retired:
                self._retired.discard(kid)
                self._pop(kid)

    def _maybe_pop(self, kid: str) -> None:
        """Pop `kid` now if nothing is in flight against it (caller holds
        the session lock)."""
        if self._outstanding.get(kid, 0) <= 0:
            self._retired.discard(kid)
            self._pop(kid)

    def _pop(self, kid: str) -> None:
        """Best-effort cache retirement — the LRU would get there anyway;
        failures (no cache attached, entry already evicted, a shard mid-
        failover) are not a session's problem."""
        if self._cache is None:
            return
        try:
            if self._cache.pop(kid) is not None:
                telemetry.counter("serve.session.keyframes_retired").inc()
        except Exception:
            pass

    # ---------------- introspection / lifecycle ----------------

    @property
    def last_drift(self) -> float:
        with self._lock:
            return self._last_drift

    @property
    def keyframe_age(self) -> int:
        """Frames since the current keyframe (-1 before the first)."""
        with self._lock:
            if self._keyframe_seq < 0:
                return -1
            return self._frame_idx - 1 - self._keyframe_seq

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def stats(self) -> dict:
        with self._lock:
            return {"session": self.session_id,
                    "frames": self.frames,
                    "keyframes": self.keyframes,
                    "rekeys": self.rekeys,
                    "failed_frames": self.failed_frames,
                    "keyframe_every": self.keyframe_every,
                    "drift_mode": self.drift_mode,
                    "drift_budget": self.drift_budget,
                    "last_drift": self._last_drift,
                    "in_flight": sum(self._outstanding.values()),
                    "closed": self._closed}

    def close(self) -> None:
        """End the stream: emit `serve.session_end`, retire the current
        keyframe (popped once its last in-flight frame resolves), and
        detach from the manager. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._keyframe_id is not None:
                self._retired.add(self._keyframe_id)
                self._maybe_pop(self._keyframe_id)
            frames, keyframes = self.frames, self.keyframes
        telemetry.counter("serve.session.closed").inc()
        telemetry.emit("serve.session_end", session=self.session_id,
                       frames=frames, keyframes=keyframes,
                       rekeys=self.rekeys,
                       failed_frames=self.failed_frames)
        if self._on_close is not None:
            self._on_close(self.session_id)
