"""`mtpu-wire1`: the host ring's binary wire format + tensor codecs.

PRs 18-19 made the ring elastic and failure-hardened, but every byte still
crossed the wire as JSON with base64 float32 tensors (~4/3 inflation on a
~4.7 MB flagship source image) and every request paid its own HTTP round
trip. This module is the transport's answer: a length-prefixed binary frame
(no base64, raw little-endian tensor bytes) plus wire codecs that ship the
*cheapest sufficient representation* — the int8 per-channel scheme the
plane cache already trusts (serve/cache.py) applied to the hop itself.

Frame layout (all integers little-endian):

    +----------------+-------------+------------------+------------------+
    | magic (10 B)   | hlen (u32)  | header JSON      | tensor segments  |
    | b"mtpu-wire1"  |             | (hlen bytes)     | (concatenated)   |
    +----------------+-------------+------------------+------------------+

The header is compact JSON: {"v": 1, "body": <JSON-safe dict>,
"tensors": [<desc>, ...]} where each desc declares its codec and the raw
segments ({"dtype", "shape", "nbytes"}) that follow in order. The body
references tensors by index (the request/response helpers below use plain
ints), so the JSON stays tiny while the tensors travel as verbatim bytes.

Decoding is HOSTILE-FRAME SAFE — a frame is rejected (`WireError`, which
the hardened client treats as retryable transport garbage, never crashed
on) when any of the four tripwires fires:

    bad magic        the prefix is not b"mtpu-wire1"
    truncated        declared header/segment bytes exceed what arrived
    oversized        the frame or any declared size exceeds `max_bytes`
    segment mismatch trailing bytes after the declared segments, or a
                     tensor desc whose segment count disagrees

Wire codecs (applied to float32 payload tensors only; every other dtype —
and every tensor under codec "f32" — ships raw and round-trips BITWISE):

    f32    raw little-endian float32 bytes (bitwise; the default)
    bf16   round-to-nearest-even narrowing to bfloat16 on the wire,
           widen-cast back to float32 on receipt (2x smaller; every bf16
           is exactly representable in f32, so the widening is lossless)
    int8   per-channel symmetric quantization — scale = max|x|/127 over
           the trailing two axes, the EXACT serve/cache.py scheme — 4x
           smaller with |x - dequant(x)| <= scale/2 per group

Negotiation rides Content-Type: a wire-enabled server advertises
`X-Mtpu-Wire: mtpu-wire1` on every response; a wire-enabled client checks
once (a /healthz round) and speaks `application/x-mtpu-wire1` only to a
server that advertised — anything else falls back to the byte-identical
PR-19 JSON path (counted `serve.wire.fallbacks`). The JSON body/envelope
builders live here too, so framing knowledge — JSON and binary — sits in
exactly ONE seam shared by HostClient and HostServer (serve/hostnet.py).

Stdlib + numpy only; importing this module never touches jax.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import struct
from typing import Dict, List, Sequence, Tuple

import numpy as np

try:  # bf16 lives in ml_dtypes (a jax dependency); gate it anyway
    from ml_dtypes import bfloat16 as _BF16
except Exception:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None

MAGIC = b"mtpu-wire1"
VERSION = 1
# refuse to decode (or declare) frames beyond this many bytes — a hostile
# length prefix must never become an allocation
MAX_FRAME_BYTES = 1 << 28  # 256 MiB

CTYPE_JSON = "application/json"
CTYPE_BINARY = "application/x-mtpu-wire1"
# capability advertisement: a wire-enabled HostServer sets this header on
# EVERY response; its absence is how a binary client detects a JSON-only
# peer and falls back
WIRE_HEADER = "X-Mtpu-Wire"
WIRE_PROTO = "mtpu-wire1"

WIRE_FORMATS = ("json", "binary")
WIRE_CODECS = ("f32", "bf16", "int8")

_U32 = struct.Struct("<I")


class WireError(ValueError):
    """A frame failed the mtpu-wire1 contract (hostile/corrupt/truncated).

    Deliberately transport-shaped, not application-shaped: the hardened
    HostClient retries it exactly like mangled JSON — a truncated binary
    frame is re-requested, never crashed on."""


@dataclasses.dataclass(frozen=True)
class WirePolicy:
    """The serve.wire.* knobs as one immutable value (config.py parses the
    keys; serve_cli builds this and hands it to HostServer, HostClient and
    the RingFront). The default — format "json", coalesce_ms 0 — arms
    NOTHING: no negotiation, no frames, no coalescer; the transport stays
    bitwise-identical to PR 19 (test-pinned)."""

    format: str = "json"      # json | binary (binary arms negotiation)
    codec: str = "f32"        # f32 | bf16 | int8 tensor codec on the wire
    coalesce_ms: float = 0.0  # front linger window for same-owner batching
    coalesce_max: int = 8     # requests per coalesced batch frame (cap)

    @property
    def binary(self) -> bool:
        return self.format == "binary"

    @property
    def coalesce(self) -> bool:
        return self.coalesce_ms > 0


# ------------------------------------------------------------- JSON path
# The PR-19 wire, verbatim — kept as the negotiated fallback and the
# default. These builders are the SINGLE source of the JSON byte layout:
# both hostnet halves call them, so wire-off stays byte-identical by
# construction (tests/test_serve_wire.py pins the exact payload bytes).

def pack_array(a: np.ndarray) -> Dict:
    """numpy -> JSON-safe {shape, dtype, b64}; bytes survive verbatim."""
    a = np.ascontiguousarray(a)
    return {"shape": list(a.shape), "dtype": str(a.dtype),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def unpack_array(d: Dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"]).copy()


def json_render_body(req: Dict) -> Dict:
    """One render request (numpy pose/image) -> the exact PR-19 JSON body
    (key insertion order pinned: json.dumps of this dict must reproduce
    the legacy payload byte-for-byte)."""
    image = req.get("image")
    return {"image_id": str(req["image_id"]),
            "pose": np.asarray(req["pose"],
                               np.float32).reshape(-1).tolist(),
            "tier": req.get("tier"),
            "deadline_ms": req.get("deadline_ms"),
            "image": pack_array(np.asarray(image, np.float32))
            if image is not None else None}


def json_render_request(body: Dict) -> Dict:
    """The server half: a decoded JSON /render body -> one request dict
    with numpy pose/image (the shape HostServer hands the fleet)."""
    image = body.get("image")
    return {"image_id": str(body["image_id"]),
            "pose": np.asarray(body["pose"], np.float32).reshape(4, 4),
            "tier": body.get("tier"),
            "deadline_ms": body.get("deadline_ms"),
            "image": unpack_array(image) if image else None}


def json_render_envelope(env: Dict) -> Dict:
    """One result envelope (numpy rgb/depth when ok) -> the exact PR-19
    JSON response object."""
    if env.get("ok"):
        return {"ok": True, "rgb": pack_array(env["rgb"]),
                "depth": pack_array(env["depth"])}
    return {"ok": False, "kind": env.get("kind", ""),
            "error": env.get("error", "")}


def json_render_result(obj: Dict) -> Dict:
    """One PR-19 JSON response object -> result envelope with numpy
    rgb/depth (the client half of json_render_envelope)."""
    if obj.get("ok"):
        return {"ok": True, "rgb": unpack_array(obj["rgb"]),
                "depth": unpack_array(obj["depth"])}
    return {"ok": False, "kind": obj.get("kind", ""),
            "error": obj.get("error", "")}


# ---------------------------------------------------------- tensor codecs

def _c(a: np.ndarray) -> np.ndarray:
    """C-contiguous view/copy that PRESERVES shape (np.ascontiguousarray
    silently promotes 0-d to 1-d, which would break the bitwise
    round-trip contract for scalars)."""
    a = np.asarray(a)
    return a if a.flags.c_contiguous else np.ascontiguousarray(a)


def _dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        if name == "bfloat16" and _BF16 is not None:
            return np.dtype(_BF16)
        raise WireError(f"unknown wire dtype {name!r}")


def int8_quantize(a: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel symmetric int8, the serve/cache.py scheme in numpy:
    scale = max|x|/127 reduced over the TRAILING TWO axes (global for
    0/1-d), q = clip(round(x/scale), -127, 127). Returns (q, scales) with
    scales broadcastable against q; |x - q*scale| <= scale/2 per group."""
    a = _c(np.asarray(a, dtype=np.float32))
    axes = tuple(range(a.ndim - 2, a.ndim)) if a.ndim >= 2 \
        else tuple(range(a.ndim))
    if a.size == 0:
        shape = [1 if i in axes else d for i, d in enumerate(a.shape)]
        return a.astype(np.int8), np.ones(shape, np.float32)
    with np.errstate(invalid="ignore"):
        amax = np.max(np.abs(a), axis=axes or None, keepdims=bool(axes))
        # a non-finite group (rendered depth can carry inf/NaN at
        # zero-alpha pixels) must never poison its scale: clamp to a
        # finite scale so FINITE members still hold the scale/2 bound and
        # the wire ships no inf scales (0 * inf = NaN on dequant)
        amax = np.where(np.isfinite(amax), amax, np.float32(127.0))
        scales = (np.maximum(amax, 1e-30) / 127.0).astype(np.float32)
        q = np.clip(np.round(a / scales), -127, 127)
    q = np.where(np.isfinite(q), q, np.float32(0.0)).astype(np.int8)
    return q, np.asarray(scales, np.float32)


def int8_dequantize(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    with np.errstate(invalid="ignore"):
        return (q.astype(np.float32)
                * np.asarray(scales, np.float32)).astype(np.float32)


def encode_tensor(a: np.ndarray, codec: str) -> Tuple[Dict, List]:
    """One tensor -> (desc, raw segment arrays). float32 inputs are
    transformed per `codec`; every other dtype ships raw (bitwise) — the
    frame layer is a faithful container for ANY numpy dtype."""
    if codec not in WIRE_CODECS:
        raise WireError(f"unknown wire codec {codec!r}")
    a = _c(a)
    if a.dtype != np.float32 or codec == "f32":
        return {"codec": "raw"}, [a]
    if codec == "bf16":
        if _BF16 is None:  # pragma: no cover - ml_dtypes ships with jax
            raise WireError("bf16 wire codec needs ml_dtypes")
        return {"codec": "bf16"}, [a.astype(_BF16)]
    q, scales = int8_quantize(a)
    return {"codec": "int8"}, [q, scales]


def decode_tensor(desc: Dict, arrays: Sequence[np.ndarray]) -> np.ndarray:
    codec = desc.get("codec")
    if codec == "raw":
        _want_segs(desc, arrays, 1)
        return arrays[0]
    if codec == "bf16":
        _want_segs(desc, arrays, 1)
        return arrays[0].astype(np.float32)
    if codec == "int8":
        _want_segs(desc, arrays, 2)
        return int8_dequantize(arrays[0], arrays[1])
    raise WireError(f"unknown tensor codec {codec!r}")


def _want_segs(desc: Dict, arrays: Sequence, n: int) -> None:
    if len(arrays) != n:
        raise WireError(
            f"segment count mismatch: codec {desc.get('codec')!r} "
            f"declares {len(arrays)} segment(s), needs {n}")


# ------------------------------------------------------------ frame layer

def encode_frame(body: Dict, tensors: Sequence[np.ndarray] = (),
                 codec: str = "f32",
                 max_bytes: int = MAX_FRAME_BYTES) -> bytes:
    """JSON-safe `body` + tensors -> one mtpu-wire1 frame. The body refers
    to tensors by list index (caller's convention); each tensor is
    codec-encoded into raw little-endian segments."""
    descs, segs = [], []
    for a in tensors:
        desc, arrs = encode_tensor(a, codec)
        d_segs = []
        for arr in arrs:
            arr = _c(arr)
            if arr.dtype.byteorder == ">":  # wire bytes are little-endian
                arr = arr.astype(arr.dtype.newbyteorder("<"))
            raw = arr.tobytes()
            d_segs.append({"dtype": str(arr.dtype),
                           "shape": list(arr.shape), "nbytes": len(raw)})
            segs.append(raw)
        descs.append({**desc, "segs": d_segs})
    header = json.dumps({"v": VERSION, "body": body, "tensors": descs},
                        separators=(",", ":")).encode()
    frame = b"".join([MAGIC, _U32.pack(len(header)), header] + segs)
    if len(frame) > max_bytes:
        raise WireError(
            f"oversized frame: {len(frame)} bytes > max {max_bytes}")
    return frame


def decode_frame(data: bytes, max_bytes: int = MAX_FRAME_BYTES
                 ) -> Tuple[Dict, List[np.ndarray]]:
    """One frame -> (body, decoded tensors). Every hostile-frame tripwire
    (module docstring) raises WireError; a valid frame's tensors come back
    as float32 (codec'd) or their original dtype (raw, bitwise)."""
    if len(data) > max_bytes:
        raise WireError(
            f"oversized frame: {len(data)} bytes > max {max_bytes}")
    if len(data) < len(MAGIC) + _U32.size:
        raise WireError(
            f"truncated frame: {len(data)} bytes is shorter than the "
            f"magic + length prefix")
    if data[:len(MAGIC)] != MAGIC:
        raise WireError(f"bad magic {data[:len(MAGIC)]!r} "
                        f"(expected {MAGIC!r})")
    off = len(MAGIC)
    (hlen,) = _U32.unpack_from(data, off)
    off += _U32.size
    if hlen > max_bytes:
        raise WireError(f"oversized header: {hlen} bytes > max {max_bytes}")
    if off + hlen > len(data):
        raise WireError(
            f"truncated frame: header declares {hlen} bytes, "
            f"{len(data) - off} remain")
    try:
        header = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise WireError(f"bad frame header: {e}") from e
    off += hlen
    if not isinstance(header, dict) or header.get("v") != VERSION:
        raise WireError(
            f"bad frame header: unknown version "
            f"{header.get('v') if isinstance(header, dict) else header!r}")
    descs = header.get("tensors", [])
    if not isinstance(descs, list):
        raise WireError("bad frame header: tensors must be a list")
    tensors: List[np.ndarray] = []
    for desc in descs:
        arrs = []
        d_segs = desc.get("segs", [])
        if not isinstance(d_segs, list):
            raise WireError("bad frame header: segs must be a list")
        for seg in d_segs:
            dt = _dtype(seg.get("dtype", ""))
            shape = tuple(int(s) for s in seg.get("shape", []))
            nbytes = int(seg.get("nbytes", -1))
            want = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
            if nbytes != want or nbytes < 0:
                raise WireError(
                    f"segment count mismatch: segment declares {nbytes} "
                    f"bytes but shape {shape} x {dt} needs {want}")
            if nbytes > max_bytes:
                raise WireError(
                    f"oversized segment: {nbytes} bytes > max {max_bytes}")
            if off + nbytes > len(data):
                raise WireError(
                    f"truncated frame: segment needs {nbytes} bytes, "
                    f"{len(data) - off} remain")
            arrs.append(np.frombuffer(
                data, dtype=dt, count=int(np.prod(shape, dtype=np.int64)),
                offset=off).reshape(shape).copy())
            off += nbytes
        tensors.append(decode_tensor(desc, arrs))
    if off != len(data):
        raise WireError(
            f"segment count mismatch: {len(data) - off} trailing bytes "
            f"after the declared segments")
    body = header.get("body")
    if not isinstance(body, dict):
        raise WireError("bad frame header: body must be an object")
    return body, tensors


# ------------------------------------------- render request/response seam
# The binary /render exchange is ALWAYS batch-framed (a single render is a
# batch of one): N same-owner requests cost one HTTP round, and the
# response carries per-request envelopes IN REQUEST ORDER — the front's
# coalescer maps result i back to future i no matter how the host-side
# batcher reordered the work by tier.

def encode_render_request(reqs: Sequence[Dict], codec: str = "f32",
                          max_bytes: int = MAX_FRAME_BYTES) -> bytes:
    """Render requests (numpy pose/image) -> one binary batch frame. The
    pose always ships raw f32 (16 floats — bitwise matters, size doesn't);
    the image upload uses `codec`. The frame body carries the codec so the
    server mirrors it on the response."""
    tensors: List[np.ndarray] = []
    items = []
    for req in reqs:
        pose = _c(np.asarray(req["pose"], np.float32).reshape(4, 4))
        item = {"image_id": str(req["image_id"]),
                "tier": req.get("tier"),
                "deadline_ms": req.get("deadline_ms"),
                "pose": len(tensors), "image": None}
        tensors.append(pose)
        image = req.get("image")
        if image is not None:
            item["image"] = len(tensors)
            tensors.append(np.asarray(image, np.float32))
        items.append(item)
    body = {"kind": "render_batch", "codec": codec, "batch": items}
    # pose must survive bitwise under EVERY codec: encode_tensor only
    # transforms f32 tensors, so ship poses raw by encoding per-tensor
    out_codecs = ["f32"] * len(tensors)
    for item in items:
        if item["image"] is not None:
            out_codecs[item["image"]] = codec
    return _encode_mixed(body, tensors, out_codecs, max_bytes)


def _encode_mixed(body: Dict, tensors: Sequence[np.ndarray],
                  codecs: Sequence[str], max_bytes: int) -> bytes:
    """encode_frame with a PER-TENSOR codec choice (poses raw, images
    quantized)."""
    descs, segs = [], []
    for a, codec in zip(tensors, codecs):
        desc, arrs = encode_tensor(a, codec)
        d_segs = []
        for arr in arrs:
            arr = _c(arr)
            if arr.dtype.byteorder == ">":
                arr = arr.astype(arr.dtype.newbyteorder("<"))
            raw = arr.tobytes()
            d_segs.append({"dtype": str(arr.dtype),
                           "shape": list(arr.shape), "nbytes": len(raw)})
            segs.append(raw)
        descs.append({**desc, "segs": d_segs})
    header = json.dumps({"v": VERSION, "body": body, "tensors": descs},
                        separators=(",", ":")).encode()
    frame = b"".join([MAGIC, _U32.pack(len(header)), header] + segs)
    if len(frame) > max_bytes:
        raise WireError(
            f"oversized frame: {len(frame)} bytes > max {max_bytes}")
    return frame


def decode_render_request(data: bytes,
                          max_bytes: int = MAX_FRAME_BYTES
                          ) -> Tuple[List[Dict], str]:
    """One binary batch frame -> (request dicts with numpy pose/image,
    the codec the response should mirror)."""
    body, tensors = decode_frame(data, max_bytes=max_bytes)
    if body.get("kind") != "render_batch":
        raise WireError(f"unexpected frame kind {body.get('kind')!r}")
    codec = body.get("codec", "f32")
    if codec not in WIRE_CODECS:
        raise WireError(f"unknown wire codec {codec!r}")
    reqs = []
    for item in body.get("batch", []):
        reqs.append({
            "image_id": str(item["image_id"]),
            "pose": np.asarray(_take(tensors, item["pose"]),
                               np.float32).reshape(4, 4),
            "tier": item.get("tier"),
            "deadline_ms": item.get("deadline_ms"),
            "image": (_take(tensors, item["image"])
                      if item.get("image") is not None else None),
        })
    return reqs, codec


def encode_render_response(envs: Sequence[Dict], codec: str = "f32",
                           max_bytes: int = MAX_FRAME_BYTES) -> bytes:
    """Result envelopes ({"ok": True, "rgb", "depth"} with numpy arrays,
    or {"ok": False, "kind", "error"}) -> one binary batch frame, in
    REQUEST order. rgb/depth downloads use `codec`."""
    tensors: List[np.ndarray] = []
    items = []
    for env in envs:
        if env.get("ok"):
            item = {"ok": True, "rgb": len(tensors),
                    "depth": len(tensors) + 1}
            tensors.append(np.asarray(env["rgb"], np.float32))
            tensors.append(np.asarray(env["depth"], np.float32))
        else:
            item = {"ok": False, "kind": env.get("kind", ""),
                    "error": env.get("error", "")}
        items.append(item)
    body = {"kind": "render_batch", "codec": codec, "batch": items}
    return encode_frame(body, tensors, codec=codec, max_bytes=max_bytes)


def decode_render_response(data: bytes,
                           max_bytes: int = MAX_FRAME_BYTES
                           ) -> List[Dict]:
    """One binary batch frame -> result envelopes with numpy rgb/depth."""
    body, tensors = decode_frame(data, max_bytes=max_bytes)
    if body.get("kind") != "render_batch":
        raise WireError(f"unexpected frame kind {body.get('kind')!r}")
    envs = []
    for item in body.get("batch", []):
        if item.get("ok"):
            envs.append({"ok": True,
                         "rgb": _take(tensors, item["rgb"]),
                         "depth": _take(tensors, item["depth"])})
        else:
            envs.append({"ok": False, "kind": item.get("kind", ""),
                         "error": item.get("error", "")})
    return envs


def _take(tensors: List[np.ndarray], idx) -> np.ndarray:
    try:
        i = int(idx)
        if i < 0:
            raise IndexError(i)
        return tensors[i]
    except (IndexError, TypeError, ValueError):
        raise WireError(
            f"segment count mismatch: body references tensor {idx!r}, "
            f"frame carries {len(tensors)}")
