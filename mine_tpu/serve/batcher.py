"""Micro-batcher: coalesce pending view requests into one device call.

Serving traffic arrives as independent (image_id, pose) requests, usually
against DIFFERENT cached MPIs. Dispatching each alone wastes the batch axis;
this batcher holds a request up to `max_wait_ms`, coalesces everything
pending (across distinct entries — the engine's request-gather handles the
mapping) and flushes one `RenderEngine.render_many` call of at most
`max_requests`. Results come back through per-request futures.

Thread model: callers `submit` from any thread; a single daemon flush thread
owns the device dispatch, so the engine's jitted call never races. Tests
drive `flush()` directly with `start=False` (no timing dependence).

Observability: a request carrying a TraceContext (telemetry/tracing.py —
attached by `ServeFleet.submit`, or started here when sampling is on) rides
the pending tuple across the thread handoff; the flush path records its
"queue" span (enqueue -> dispatch, tagged with which trigger released the
batch: a full bucket or the deadline), hands the trace to the engine for
pad/render/encode spans, and seals the trace when the future resolves.
An attached `slo` tracker (telemetry/slo.py) sees EVERY request's
end-to-end latency — SLO accounting is never sampled.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np

from mine_tpu import telemetry
from mine_tpu.analysis.locks import ordered_condition
from mine_tpu.serve.engine import RenderEngine, pow2_bucket
from mine_tpu.telemetry import tracing
from mine_tpu.telemetry.slo import SLOTracker

_log = logging.getLogger(__name__)


class MicroBatcher:
    def __init__(self, engine: RenderEngine,
                 max_requests: int = 8,
                 max_wait_ms: float = 2.0,
                 start: bool = True,
                 slo: Optional[SLOTracker] = None,
                 auto_trace: bool = True):
        if max_requests < 1:
            raise ValueError(f"max_requests must be >= 1, got {max_requests}")
        self.engine = engine
        self.max_requests = int(max_requests)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.flushes = 0
        self.slo = slo
        # the fleet's submit makes the sampling decision (its trace carries
        # the route span) and passes the result down — auto_trace=False
        # there keeps this layer from re-rolling the dice on requests the
        # fleet already declined to sample
        self.auto_trace = auto_trace
        self._cv = ordered_condition("serve.batcher.cv")
        # (image_id, pose, future, enqueue perf_counter, trace-or-None) —
        # the timestamp feeds the serve.batcher.queue_wait_ms histogram at
        # flush; the trace rides here across the submit->flush thread hop
        self._pending: List[Tuple[str, np.ndarray, Future, float,
                                  Optional[tracing.TraceContext]]] = []
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="mine-tpu-serve-batcher")
            self._thread.start()

    def submit(self, image_id: str, pose_44: np.ndarray,
               trace: Optional[tracing.TraceContext] = None) -> Future:
        """Enqueue one view request; resolves to (rgb [3,H,W],
        depth [1,H,W]) f32 numpy. `trace` attaches an already-started
        request trace (the fleet's submit passes one that already carries
        the route span); without one, the batcher makes its own sampling
        decision (unless auto_trace is off) so a bare-batcher deployment
        still gets traces."""
        if trace is None and self.auto_trace:
            trace = tracing.start("serve.request", image_id=str(image_id)[:12])
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._pending.append(
                (image_id, np.asarray(pose_44, np.float32), fut,
                 time.perf_counter(), trace))
            self._cv.notify()
        return fut

    def flush(self) -> int:
        """Dispatch up to max_requests pending requests in ONE device call;
        returns how many were served (0 = nothing pending)."""
        with self._cv:
            batch = self._pending[:self.max_requests]
            del self._pending[:len(batch)]
        if not batch:
            return 0
        now = time.perf_counter()
        cause = "full" if len(batch) >= self.max_requests else "deadline"
        wait_hist = telemetry.histogram("serve.batcher.queue_wait_ms")
        for _, _, _, t_enq, trace in batch:
            wait_hist.record((now - t_enq) * 1e3)
            if trace is not None:
                trace.add_span("queue", (now - t_enq) * 1e3, t0=t_enq,
                               flush_cause=cause, batch_size=len(batch))
        telemetry.histogram(
            "serve.batcher.coalesce_size",
            edges=telemetry.pow2_buckets(1024)).record(len(batch))
        try:
            results = self.engine.render_many(
                [(i, p) for i, p, _, _, _ in batch],
                traces=[t for _, _, _, _, t in batch])
            self.flushes += 1
            done = time.perf_counter()
            bucket = pow2_bucket(len(batch))
            for (_, _, fut, t_enq, trace), res in zip(batch, results):
                fut.set_result(res)
                if self.slo is not None:
                    self.slo.record((done - t_enq) * 1e3, bucket=bucket)
                tracing.finish(trace)
        except Exception as e:  # pragma: no cover - device failures
            for _, _, fut, _, trace in batch:
                if not fut.done():
                    fut.set_exception(e)
                tracing.finish(trace, ok=False)
        return len(batch)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                # first request in: linger up to max_wait_s for co-riders
                # unless a full batch is already there (max_wait_ms=0
                # flushes immediately)
                if (self.max_wait_s > 0 and not self._closed
                        and len(self._pending) < self.max_requests):
                    self._cv.wait(timeout=self.max_wait_s)
            self.flush()

    def close(self, timeout: float = 10.0) -> bool:
        """Drain pending requests and stop + JOIN the flush thread; returns
        True once the thread is confirmed dead. The join is bounded: a
        thread wedged in a device call can't hang the caller's exit — but a
        failed join is LOUD (a warning), never silent, because a dangling
        daemon thread racing interpreter teardown is exactly the flaky-exit
        bug this method exists to prevent."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)
        # drain on the caller's thread whatever the flush thread left
        # behind (it exits as soon as it sees _closed with an empty queue)
        while self.flush():
            pass
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
        if thread is not None and thread.is_alive():
            _log.warning(
                "batcher flush thread failed to join within %.1fs; "
                "it remains daemon and will die with the process", timeout)
            return False
        self._thread = None
        return True


class ContinuousBatcher(MicroBatcher):
    """Continuous-batching scheduler: keep the engine's pow2 pose buckets
    filled across in-flight requesters.

    Where MicroBatcher lingers ONCE per wakeup and then flushes whatever
    is pending, this scheduler runs a deadline loop: a batch dispatches the
    moment it is FULL (`max_requests`, one complete pow2 bucket), or when
    the OLDEST pending request's deadline (enqueue + `serve.max_wait_ms`)
    expires — no request waits past its deadline for co-riders, and a
    burst never waits at all. Admission is continuous: `submit` only takes
    the queue lock, which the flush path drops before the device call, so
    new requests keep boarding while a render is in flight and the next
    bucket is typically full by the time the engine returns.

    Same queue-wait / coalesce-size histograms as MicroBatcher (the flush
    path is inherited); `serve.batcher.flush_full` / `flush_deadline`
    count which trigger fired — the same full-vs-deadline verdict each
    request's "queue" trace span carries as `flush_cause`. Tests drive
    `_ready` and `flush()` directly with start=False (no timing
    dependence); `close()` joins the deadline loop like the base class.
    """

    def flush(self) -> int:
        n = super().flush()
        if n:
            telemetry.counter(
                "serve.batcher.flush_full" if n >= self.max_requests
                else "serve.batcher.flush_deadline").inc()
        return n

    def _ready(self, now: float) -> bool:
        """Dispatch decision (callers hold self._cv): full bucket, expired
        oldest deadline, or an immediate-mode (max_wait_ms=0) queue."""
        if len(self._pending) >= self.max_requests:
            return True
        if not self._pending:
            return False
        return (self.max_wait_s <= 0
                or now >= self._pending[0][3] + self.max_wait_s)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                now = time.perf_counter()
                if not self._closed and not self._ready(now):
                    # sleep only to the oldest deadline; a submit that
                    # fills the bucket notifies earlier. Loop back to
                    # re-decide instead of flushing blindly on wake.
                    self._cv.wait(timeout=max(
                        0.0, self._pending[0][3] + self.max_wait_s - now))
                    continue
            self.flush()
