"""Micro-batcher: coalesce pending view requests into one device call.

Serving traffic arrives as independent (image_id, pose) requests, usually
against DIFFERENT cached MPIs. Dispatching each alone wastes the batch axis;
this batcher holds a request up to `max_wait_ms`, coalesces everything
pending (across distinct entries — the engine's request-gather handles the
mapping) and flushes one `RenderEngine.render_many` call of at most
`max_requests`. Results come back through per-request futures.

Thread model: callers `submit` from any thread; a single daemon flush thread
owns the device dispatch, so the engine's jitted call never races. Tests
drive `flush()` directly with `start=False` (no timing dependence).

Observability: a request carrying a TraceContext (telemetry/tracing.py —
attached by `ServeFleet.submit`, or started here when sampling is on) rides
the pending tuple across the thread handoff; the flush path records its
"queue" span (enqueue -> dispatch, tagged with which trigger released the
batch: a full bucket or the deadline), hands the trace to the engine for
pad/render/encode spans, and seals the trace when the future resolves.
An attached `slo` tracker (telemetry/slo.py) sees EVERY request's
end-to-end latency — SLO accounting is never sampled.

Self-protection (PR 11, serve/admission.py): requests carry a priority
`tier` and an optional deadline. An attached `AdmissionController` is
consulted at submit time under the queue lock — a shed verdict resolves the
future immediately with `RequestShed`; a degrade verdict tags the request
for the graceful ladder (stepped-down cache quant on a sync-encode miss,
and an all-degraded batch caps at half the pose bucket). The flush path
runs a DEADLINE SWEEP before selecting: already-expired requests are purged
(future gets `DeadlineExceeded`) and never rendered. Dispatch selection is
priority-ordered — highest tier first, FIFO within a tier — via a stable
sort, so with every request at the default tier the order (and therefore
the output) is bitwise-identical to the plain FIFO batcher.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import List, NamedTuple, Optional

import numpy as np

from mine_tpu import telemetry
from mine_tpu.analysis.locks import ordered_condition
from mine_tpu.serve.admission import (AdmissionController, DeadlineExceeded,
                                      RequestShed)
from mine_tpu.serve.engine import RenderEngine, pow2_bucket
from mine_tpu.telemetry import tracing
from mine_tpu.telemetry.slo import SLOTracker

_log = logging.getLogger(__name__)


class _Pending(NamedTuple):
    """One queued request. Field ORDER is part of the queue's informal API
    (tests probe `_pending[0][3]` for the enqueue timestamp): the first
    five fields are exactly the PR-5 tuple; the tail is the PR-11
    resilience state."""
    image_id: str
    pose: np.ndarray
    fut: Future
    t_enq: float
    trace: Optional[tracing.TraceContext]
    tier: int = 1
    deadline: Optional[float] = None  # perf_counter timestamp; None = none
    degraded: bool = False
    image: Optional[np.ndarray] = None  # sync-encode fallback pixels


class MicroBatcher:
    def __init__(self, engine: RenderEngine,
                 max_requests: int = 8,
                 max_wait_ms: float = 2.0,
                 start: bool = True,
                 slo: Optional[SLOTracker] = None,
                 auto_trace: bool = True,
                 admission: Optional[AdmissionController] = None,
                 default_tier: int = 1,
                 request_deadline_ms: float = 0.0):
        if max_requests < 1:
            raise ValueError(f"max_requests must be >= 1, got {max_requests}")
        self.engine = engine
        self.max_requests = int(max_requests)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.flushes = 0
        self.slo = slo
        # the fleet's submit makes the sampling decision (its trace carries
        # the route span) and passes the result down — auto_trace=False
        # there keeps this layer from re-rolling the dice on requests the
        # fleet already declined to sample
        self.auto_trace = auto_trace
        # self-protection (serve/admission.py): None = every request admits
        # unconditionally (the PR-10 behavior, bitwise)
        self.admission = admission
        self.default_tier = int(default_tier)
        self.request_deadline_ms = float(request_deadline_ms)
        self.expired = 0  # requests purged by the deadline sweep
        # injectable clock (instance attr): the deadline-sweep regression
        # test replaces it with a fake so expiry needs no real waiting
        self._now = time.perf_counter
        self._cv = ordered_condition("serve.batcher.cv")
        # queued-but-unresolved + dispatched-but-unresolved: the in-flight
        # pressure signal the admission controller consumes (guarded by cv)
        self._inflight = 0
        self._pending: List[_Pending] = []
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="mine-tpu-serve-batcher")
            self._thread.start()

    def submit(self, image_id: str, pose_44: np.ndarray,
               trace: Optional[tracing.TraceContext] = None,
               tier: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               image: Optional[np.ndarray] = None) -> Future:
        """Enqueue one view request; resolves to (rgb [3,H,W],
        depth [1,H,W]) f32 numpy. `trace` attaches an already-started
        request trace (the fleet's submit passes one that already carries
        the route span); without one, the batcher makes its own sampling
        decision (unless auto_trace is off) so a bare-batcher deployment
        still gets traces.

        `tier` is the request's priority class (default `default_tier`;
        serve/admission.py); under pressure an attached controller may
        resolve the future immediately with `RequestShed`, or tag the
        request degraded. `deadline_ms` bounds its total queue+render time
        (default `request_deadline_ms`; 0/None = no deadline): a request
        still queued past its deadline is purged at dispatch time with
        `DeadlineExceeded`. `image` optionally carries the source pixels so
        a cache miss can fall back to the synchronous encode."""
        if trace is None and self.auto_trace:
            trace = tracing.start("serve.request", image_id=str(image_id)[:12])
        tier = self.default_tier if tier is None else int(tier)
        if deadline_ms is None:
            deadline_ms = self.request_deadline_ms
        fut: Future = Future()
        decision = "admit"
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if self.admission is not None:
                decision = self.admission.decide(
                    tier, len(self._pending), self._inflight)
            if decision != "shed":
                now = self._now()
                self._pending.append(_Pending(
                    image_id, np.asarray(pose_44, np.float32), fut, now,
                    trace, tier,
                    now + deadline_ms / 1e3 if deadline_ms > 0 else None,
                    decision == "degrade", image))
                self._inflight += 1
                self._cv.notify()
        if decision == "shed":
            fut.set_exception(RequestShed(
                f"request for {str(image_id)[:12]} shed at tier {tier} "
                f"(admission state {self.admission.state})"))
            tracing.finish(trace, ok=False)
        return fut

    def _take_batch(self, now: float):
        """Select the next dispatch batch (callers hold self._cv); returns
        (batch, expired). The sweep purges already-expired requests FIRST —
        they are never rendered; selection is then highest-tier-first, FIFO
        within a tier (a STABLE sort: uniform tiers reproduce plain FIFO
        exactly); an all-degraded batch caps at half the pose bucket (the
        graceful ladder's smaller-bucket step)."""
        expired: List[_Pending] = []
        if any(r.deadline is not None and r.deadline <= now
               for r in self._pending):
            keep: List[_Pending] = []
            for r in self._pending:
                (expired if r.deadline is not None and r.deadline <= now
                 else keep).append(r)
            self._pending[:] = keep
        if len({r.tier for r in self._pending}) > 1:
            ranked = sorted(self._pending, key=lambda r: (-r.tier, r.t_enq))
            batch = ranked[:self.max_requests]
            taken = {id(r) for r in batch}
            self._pending[:] = [r for r in self._pending
                                if id(r) not in taken]
        else:
            batch = self._pending[:self.max_requests]
            del self._pending[:len(batch)]
        if batch and all(r.degraded for r in batch):
            cap = max(1, self.max_requests // 2)
            if len(batch) > cap:
                self._pending[:0] = batch[cap:]
                batch = batch[:cap]
        return batch, expired

    def flush(self) -> int:
        """Dispatch up to max_requests pending requests in ONE device call;
        returns how many were served (0 = nothing pending). Requests whose
        deadline already passed are purged here — resolved with
        `DeadlineExceeded`, never rendered — before the batch is cut."""
        with self._cv:
            batch, expired = self._take_batch(self._now())
            self._inflight -= len(expired)
        if expired:
            self.expired += len(expired)
            telemetry.counter("serve.batcher.expired").inc(len(expired))
            for r in expired:
                r.fut.set_exception(DeadlineExceeded(
                    f"request for {str(r.image_id)[:12]} expired after "
                    f"{(self._now() - r.t_enq) * 1e3:.1f} ms in queue"))
                tracing.finish(r.trace, ok=False)
        if not batch:
            return 0
        now = time.perf_counter()
        cause = "full" if len(batch) >= self.max_requests else "deadline"
        wait_hist = telemetry.histogram("serve.batcher.queue_wait_ms")
        for r in batch:
            wait_hist.record((now - r.t_enq) * 1e3)
            if r.trace is not None:
                r.trace.add_span("queue", (now - r.t_enq) * 1e3, t0=r.t_enq,
                                 flush_cause=cause, batch_size=len(batch))
        telemetry.histogram(
            "serve.batcher.coalesce_size",
            edges=telemetry.pow2_buckets(1024)).record(len(batch))
        try:
            results = self.engine.render_many(
                [(r.image_id, r.pose) for r in batch],
                traces=[r.trace for r in batch],
                images=[r.image for r in batch],
                degraded=[r.degraded for r in batch])
            self.flushes += 1
            done = time.perf_counter()
            bucket = pow2_bucket(len(batch))
            for r, res in zip(batch, results):
                r.fut.set_result(res)
                if self.slo is not None:
                    self.slo.record((done - r.t_enq) * 1e3, bucket=bucket,
                                    tier=r.tier)
                tracing.finish(r.trace)
        except Exception as e:
            for r in batch:
                if not r.fut.done():
                    r.fut.set_exception(e)
                tracing.finish(r.trace, ok=False)
        finally:
            with self._cv:
                self._inflight -= len(batch)
        return len(batch)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                # first request in: linger up to max_wait_s for co-riders
                # unless a full batch is already there (max_wait_ms=0
                # flushes immediately)
                if (self.max_wait_s > 0 and not self._closed
                        and len(self._pending) < self.max_requests):
                    self._cv.wait(timeout=self.max_wait_s)
            self.flush()

    def close(self, timeout: float = 10.0) -> bool:
        """Drain pending requests and stop + JOIN the flush thread; returns
        True once the thread is confirmed dead. The join is bounded: a
        thread wedged in a device call can't hang the caller's exit — but a
        failed join is LOUD (a warning), never silent, because a dangling
        daemon thread racing interpreter teardown is exactly the flaky-exit
        bug this method exists to prevent."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)
        # drain on the caller's thread whatever the flush thread left
        # behind (it exits as soon as it sees _closed with an empty queue)
        while self.flush():
            pass
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
        if thread is not None and thread.is_alive():
            _log.warning(
                "batcher flush thread failed to join within %.1fs; "
                "it remains daemon and will die with the process", timeout)
            return False
        self._thread = None
        return True


class ContinuousBatcher(MicroBatcher):
    """Continuous-batching scheduler: keep the engine's pow2 pose buckets
    filled across in-flight requesters.

    Where MicroBatcher lingers ONCE per wakeup and then flushes whatever
    is pending, this scheduler runs a deadline loop: a batch dispatches the
    moment it is FULL (`max_requests`, one complete pow2 bucket), or when
    the OLDEST pending request's deadline (enqueue + `serve.max_wait_ms`)
    expires — no request waits past its deadline for co-riders, and a
    burst never waits at all. Admission is continuous: `submit` only takes
    the queue lock, which the flush path drops before the device call, so
    new requests keep boarding while a render is in flight and the next
    bucket is typically full by the time the engine returns.

    Same queue-wait / coalesce-size histograms as MicroBatcher (the flush
    path is inherited); `serve.batcher.flush_full` / `flush_deadline`
    count which trigger fired — the same full-vs-deadline verdict each
    request's "queue" trace span carries as `flush_cause`. Tests drive
    `_ready` and `flush()` directly with start=False (no timing
    dependence); `close()` joins the deadline loop like the base class.
    """

    def flush(self) -> int:
        n = super().flush()
        if n:
            telemetry.counter(
                "serve.batcher.flush_full" if n >= self.max_requests
                else "serve.batcher.flush_deadline").inc()
        return n

    def _ready(self, now: float) -> bool:
        """Dispatch decision (callers hold self._cv): full bucket, expired
        oldest deadline, or an immediate-mode (max_wait_ms=0) queue."""
        if len(self._pending) >= self.max_requests:
            return True
        if not self._pending:
            return False
        return (self.max_wait_s <= 0
                or now >= self._pending[0][3] + self.max_wait_s)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                now = time.perf_counter()
                if not self._closed and not self._ready(now):
                    # sleep only to the oldest deadline; a submit that
                    # fills the bucket notifies earlier. Loop back to
                    # re-decide instead of flushing blindly on wake.
                    self._cv.wait(timeout=max(
                        0.0, self._pending[0][3] + self.max_wait_s - now))
                    continue
            self.flush()
