"""Micro-batcher: coalesce pending view requests into one device call.

Serving traffic arrives as independent (image_id, pose) requests, usually
against DIFFERENT cached MPIs. Dispatching each alone wastes the batch axis;
this batcher holds a request up to `max_wait_ms`, coalesces everything
pending (across distinct entries — the engine's request-gather handles the
mapping) and flushes one `RenderEngine.render_many` call of at most
`max_requests`. Results come back through per-request futures.

Thread model: callers `submit` from any thread; a single daemon flush thread
owns the device dispatch, so the engine's jitted call never races. Tests
drive `flush()` directly with `start=False` (no timing dependence).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np

from mine_tpu import telemetry
from mine_tpu.serve.engine import RenderEngine


class MicroBatcher:
    def __init__(self, engine: RenderEngine,
                 max_requests: int = 8,
                 max_wait_ms: float = 2.0,
                 start: bool = True):
        if max_requests < 1:
            raise ValueError(f"max_requests must be >= 1, got {max_requests}")
        self.engine = engine
        self.max_requests = int(max_requests)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.flushes = 0
        self._cv = threading.Condition()
        # (image_id, pose, future, enqueue perf_counter) — the timestamp
        # feeds the serve.batcher.queue_wait_ms histogram at flush
        self._pending: List[Tuple[str, np.ndarray, Future, float]] = []
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="mine-tpu-serve-batcher")
            self._thread.start()

    def submit(self, image_id: str, pose_44: np.ndarray) -> Future:
        """Enqueue one view request; resolves to (rgb [3,H,W],
        depth [1,H,W]) f32 numpy."""
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._pending.append(
                (image_id, np.asarray(pose_44, np.float32), fut,
                 time.perf_counter()))
            self._cv.notify()
        return fut

    def flush(self) -> int:
        """Dispatch up to max_requests pending requests in ONE device call;
        returns how many were served (0 = nothing pending)."""
        with self._cv:
            batch = self._pending[:self.max_requests]
            del self._pending[:len(batch)]
        if not batch:
            return 0
        now = time.perf_counter()
        wait_hist = telemetry.histogram("serve.batcher.queue_wait_ms")
        for _, _, _, t_enq in batch:
            wait_hist.record((now - t_enq) * 1e3)
        telemetry.histogram(
            "serve.batcher.coalesce_size",
            edges=telemetry.pow2_buckets(1024)).record(len(batch))
        try:
            results = self.engine.render_many(
                [(i, p) for i, p, _, _ in batch])
            self.flushes += 1
            for (_, _, fut, _), res in zip(batch, results):
                fut.set_result(res)
        except Exception as e:  # pragma: no cover - device failures
            for _, _, fut, _ in batch:
                if not fut.done():
                    fut.set_exception(e)
        return len(batch)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                # first request in: linger up to max_wait_s for co-riders
                # unless a full batch is already there (max_wait_ms=0
                # flushes immediately)
                if (self.max_wait_s > 0 and not self._closed
                        and len(self._pending) < self.max_requests):
                    self._cv.wait(timeout=self.max_wait_s)
            self.flush()

    def close(self) -> None:
        """Drain pending requests, then stop the flush thread."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
        while self.flush():
            pass


class ContinuousBatcher(MicroBatcher):
    """Continuous-batching scheduler: keep the engine's pow2 pose buckets
    filled across in-flight requesters.

    Where MicroBatcher lingers ONCE per wakeup and then flushes whatever
    is pending, this scheduler runs a deadline loop: a batch dispatches the
    moment it is FULL (`max_requests`, one complete pow2 bucket), or when
    the OLDEST pending request's deadline (enqueue + `serve.max_wait_ms`)
    expires — no request waits past its deadline for co-riders, and a
    burst never waits at all. Admission is continuous: `submit` only takes
    the queue lock, which the flush path drops before the device call, so
    new requests keep boarding while a render is in flight and the next
    bucket is typically full by the time the engine returns.

    Same queue-wait / coalesce-size histograms as MicroBatcher (the flush
    path is inherited); `serve.batcher.flush_full` / `flush_deadline`
    count which trigger fired. Tests drive `_ready` and `flush()` directly
    with start=False (no timing dependence).
    """

    def flush(self) -> int:
        n = super().flush()
        if n:
            telemetry.counter(
                "serve.batcher.flush_full" if n >= self.max_requests
                else "serve.batcher.flush_deadline").inc()
        return n

    def _ready(self, now: float) -> bool:
        """Dispatch decision (callers hold self._cv): full bucket, expired
        oldest deadline, or an immediate-mode (max_wait_ms=0) queue."""
        if len(self._pending) >= self.max_requests:
            return True
        if not self._pending:
            return False
        return (self.max_wait_s <= 0
                or now >= self._pending[0][3] + self.max_wait_s)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                now = time.perf_counter()
                if not self._closed and not self._ready(now):
                    # sleep only to the oldest deadline; a submit that
                    # fills the bucket notifies earlier. Loop back to
                    # re-decide instead of flushing blindly on wake.
                    self._cv.wait(timeout=max(
                        0.0, self._pending[0][3] + self.max_wait_s - now))
                    continue
            self.flush()
